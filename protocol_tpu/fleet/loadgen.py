"""Concurrent-trace load harness: H sessions x T tenants over real gRPC.

``python -m protocol_tpu.fleet.loadgen`` replays recorded (or
synthesized — trace/synth is the single workload home) traces
CONCURRENTLY against one servicer over a real localhost gRPC seam: each
session runs the full wire-v2 session protocol (streamed snapshot, then
per-tick ``AssignDelta`` with only churned rows), handles
RESOURCE_EXHAUSTED-style refusals exactly like the production client
(bounded retry, then re-open from its own authoritative columns), and
records client-observed per-tick walls.

The report joins three views:

  * client side — per-tenant p50/p99 warm-tick latency (true merged
    histograms), min assigned fraction, refusal/reopen counts;
  * server side — the obs plane's snapshot (per-tenant histograms,
    shard occupancy, admission counters, budget fairness gauge), the
    same data the /metrics endpoint scrapes;
  * fairness — Jain's index over per-session warm throughput
    (demand-normalized: every session wants the same tick rate, so a
    starved session drags the index below 1 regardless of which tenant
    it belongs to).

The scaling model extrapolates the measured aggregate warm throughput
from this host's core count to real machines: the solve is CPU-bound,
the engines are thread-count invariant, and session locks are sharded,
so steady-state throughput scales ~linearly with cores until the wire
or the delta codec saturates — the model states its assumption instead
of hiding it.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import tempfile
import threading
import time
from typing import Optional

import numpy as np

from protocol_tpu.fleet.admission import jain_index
from protocol_tpu.obs.metrics import LatencyHistogram, tenant_of


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class _SessionStats:
    __slots__ = (
        "sid", "tenant", "cold_ms", "warm", "assigned_frac_min",
        "ticks_done", "refused", "reopens", "wall_s", "error",
        "transport_retries", "stale", "replayed",
        "moved_redirects", "failovers", "handoff_waits",
        "plan_mismatches", "verify_stopped",
    )

    def __init__(self, sid: str):
        self.sid = sid
        self.tenant = tenant_of(sid)
        self.cold_ms: list[float] = []
        self.warm: list[float] = []
        self.assigned_frac_min = 1.0
        self.ticks_done = 0
        self.refused = 0
        self.reopens = 0
        self.wall_s = 0.0
        self.error: Optional[str] = None
        # resilience ladder counters (the restart drill reads these)
        self.transport_retries = 0
        self.stale = 0
        self.replayed = 0
        # dfleet ladder counters (the multi-process drills read these)
        self.moved_redirects = 0
        self.failovers = 0
        self.handoff_waits = 0
        # plan verification against the fault-free in-process replay
        # (the zombie-resume gate's "zero double-applied ticks" proof:
        # a double-apply diverges the plan stream)
        self.plan_mismatches = 0
        self.verify_stopped = False


def _request_v2(snap, p_cols, r_cols, kernel: str):
    from protocol_tpu.proto import scheduler_pb2 as pb
    from protocol_tpu.proto import wire
    from protocol_tpu.trace import format as tfmt

    return pb.AssignRequestV2(
        providers=wire.encode_providers_v2(tfmt._as_ns(p_cols)),
        requirements=wire.encode_requirements_v2(tfmt._as_ns(r_cols)),
        weights=pb.CostWeights(
            price=snap.weights[0], load=snap.weights[1],
            proximity=snap.weights[2], priority=snap.weights[3],
        ),
        kernel=kernel, top_k=snap.top_k, eps=snap.eps,
        max_iters=snap.max_iters,
    )


def _open(client, snap, p_cols, r_cols, sid: str, kernel: str):
    """OpenSession from the current cumulative columns; returns the
    server-acknowledged fingerprint (None = refused)."""
    from protocol_tpu.proto import wire
    from protocol_tpu.trace import format as tfmt

    w = tfmt._as_ns(dict(zip(
        ("price", "load", "proximity", "priority"), snap.weights
    )))
    fp = wire.epoch_fingerprint(
        p_cols, r_cols, w, kernel, max(int(snap.top_k) or 64, 1),
        snap.eps, snap.max_iters,
    )
    req = _request_v2(snap, p_cols, r_cols, kernel)
    chunks = list(wire.chunk_snapshot(sid, fp, req))
    resp = client.open_session(iter(chunks), timeout=600)
    if not resp.ok:
        return None, resp.error, None
    p4t = wire.unblob(resp.result.provider_for_task, np.int32)
    return fp, "", p4t


def _drive_session(
    address,
    trace,
    sid: str,
    kernel: str,
    stats: _SessionStats,
    max_retries: int = 20,
    rpc_timeout_s: float = 600.0,
    baseline=None,
) -> None:
    """One session's whole life against the servicer: snapshot open,
    then every recorded delta as a lockstep tick. Refusals follow the
    production ladder: bounded backoff-retry for RESOURCE_EXHAUSTED,
    re-open from the current cumulative columns for evicted/unknown,
    and — the restart drill's rung — transport failures (a servicer
    dying or draining mid-tick) reconnect and retry the SAME call, so
    a kill+restart shows up as retries and warm resumes, never as a
    failed session.

    ``address`` may be an ORDERED endpoint list (the dfleet failover
    ladder): transport failures past the first reconnect rotate to the
    next endpoint, a ``moved:<endpoint>`` refusal rebinds straight to
    the session's new home, and an "unknown session" right after a
    failover rides a bounded handoff-wait (the journal rename may still
    be in flight) before conceding to a reopen.

    ``rpc_timeout_s`` sizes the per-delta deadline: the pause (zombie)
    drill needs a SHORT one so a delta parked inside a SIGSTOPped
    process trips the transport ladder instead of hanging the session
    on a frozen socket. ``baseline`` (the fault-free replay's per-tick
    plans) arms bit-identity verification: every fresh warm tick's
    plan is compared; verification stops at the first reopen (a cold
    re-ground legitimately re-derives duals)."""
    import grpc

    from protocol_tpu.proto import scheduler_pb2 as pb
    from protocol_tpu.proto import wire
    from protocol_tpu.services.scheduler_grpc import SchedulerBackendClient
    from protocol_tpu.trace import format as tfmt
    from protocol_tpu.trace.replay import iter_input_ticks

    endpoints = (
        [str(a) for a in address]
        if isinstance(address, (list, tuple)) else [str(address)]
    )
    ep_i = 0
    client = SchedulerBackendClient(endpoints[ep_i])

    def rebind(endpoint: Optional[str] = None):
        nonlocal client, ep_i
        if endpoint:
            if endpoint not in endpoints:
                endpoints.append(endpoint)
            ep_i = endpoints.index(endpoint)
        try:
            client.close()
        except Exception:
            pass
        client = SchedulerBackendClient(endpoints[ep_i])

    def send(call, transport_attempts: int = 60):
        """Run ``call(client)`` with reconnect-and-retry on transport
        failure (the restart window): bounded, deterministic backoff.
        The first retry reconnects the SAME endpoint (transient blip);
        later retries fail over down the endpoint list."""
        nonlocal ep_i
        for attempt in range(transport_attempts):
            try:
                return call(client)
            except grpc.RpcError:
                if attempt + 1 >= transport_attempts:
                    raise
                stats.transport_retries += 1
                time.sleep(0.02 * min(attempt + 1, 10))
                if attempt >= 1 and len(endpoints) > 1:
                    ep_i = (ep_i + 1) % len(endpoints)
                    stats.failovers += 1
                rebind()

    t_run = time.perf_counter()
    try:
        snap = trace.snapshot
        fp = None
        server_tick = 0
        for tick, p_cols, r_cols, delta in iter_input_ticks(trace):
            t0 = time.perf_counter()
            if tick == 0:
                fp, err, p4t = send(lambda c: _open(
                    c, snap, p_cols, r_cols, sid, kernel
                ))
                if fp is None:
                    stats.error = f"OpenSession refused: {err}"
                    return
                server_tick = 0
                stats.cold_ms.append((time.perf_counter() - t0) * 1e3)
            else:
                req = pb.AssignDeltaRequest(
                    session_id=sid, epoch_fingerprint=fp,
                    tick=server_tick + 1,
                )
                if delta.provider_rows.size:
                    req.provider_rows.CopyFrom(
                        wire.blob(delta.provider_rows, np.int32)
                    )
                    req.providers.CopyFrom(
                        wire.encode_providers_v2(tfmt._as_ns(delta.p_cols))
                    )
                if delta.task_rows.size:
                    req.task_rows.CopyFrom(
                        wire.blob(delta.task_rows, np.int32)
                    )
                    req.requirements.CopyFrom(
                        wire.encode_requirements_v2(
                            tfmt._as_ns(delta.r_cols)
                        )
                    )
                p4t = None
                reopened = False
                evict_retried = False
                served_stale = False
                for retry in range(max_retries):
                    resp = send(
                        lambda c: c.assign_delta(
                            req, timeout=rpc_timeout_s
                        )
                    )
                    if resp.session_ok:
                        server_tick += 1
                        if resp.stale:
                            stats.stale += 1
                            served_stale = True
                        if resp.replayed:
                            stats.replayed += 1
                        p4t = wire.unblob(
                            resp.result.provider_for_task, np.int32
                        )
                        break
                    stats.refused += 1
                    if "RESOURCE_EXHAUSTED" in resp.error:
                        # admission/backpressure/blackout: back off and
                        # retry the SAME tick (deterministic per-retry
                        # delay; many sessions desync naturally on
                        # server service order)
                        time.sleep(0.01 * (retry + 1))
                        continue
                    if resp.error.startswith("moved:"):
                        # live migration redirect: the session is WARM
                        # at its new home — rebind and resend the SAME
                        # tick (a reopen here would throw the warm
                        # arena away, the opposite of the migration's
                        # point)
                        stats.moved_redirects += 1
                        rebind(resp.error[len("moved:"):].strip())
                        continue
                    if (
                        "session evicted" in resp.error
                        and not evict_retried
                    ):
                        # a migration racing this in-flight tick lands
                        # as "session evicted"; ONE resend turns it
                        # into the moved redirect (a genuine eviction
                        # answers "unknown session" and re-opens)
                        evict_retried = True
                        continue
                    if (
                        "unknown session" in resp.error
                        and len(endpoints) > 1
                        and retry + 1 < max_retries
                    ):
                        # failover handoff window: the dead process's
                        # journal rename may still be in flight — OR a
                        # double transport blip rotated us away from
                        # the session's LIVE home. Rotate while
                        # waiting: the owner (live session or
                        # re-routed journal) is always somewhere in
                        # the endpoint list, so the walk converges
                        # warm instead of parking on a non-owner until
                        # the budget forces a reopen
                        stats.handoff_waits += 1
                        time.sleep(0.02 * (retry + 1))
                        ep_i = (ep_i + 1) % len(endpoints)
                        rebind()
                        continue
                    # tick mismatch / exhausted rungs: re-open from
                    # our authoritative cumulative columns (ladder);
                    # a "draining" refusal is transient — the
                    # replacement server admits, so keep trying
                    stats.reopens += 1
                    reopened = True
                    for dr in range(max_retries):
                        fp, err, p4t = send(lambda c: _open(
                            c, snap, p_cols, r_cols, sid, kernel
                        ))
                        if fp is not None or "draining" not in (
                            err or ""
                        ):
                            break
                        time.sleep(0.05 * (dr + 1))
                    if fp is None:
                        stats.error = f"re-open refused: {err}"
                        return
                    server_tick = 0
                    break
                if p4t is None:
                    stats.error = (
                        f"tick {tick} still refused after "
                        f"{max_retries} retries: {resp.error}"
                    )
                    return
                # a tick served via re-open paid a full snapshot COLD
                # solve — mislabeling it warm would inflate the warm
                # p99 the CI fleet gate floors on
                (stats.cold_ms if reopened else stats.warm).append(
                    (time.perf_counter() - t0) * 1e3
                )
                if reopened:
                    stats.verify_stopped = True
                if (
                    baseline is not None
                    and not stats.verify_stopped
                    and not served_stale
                    and tick < len(baseline)
                    and not np.array_equal(p4t, baseline[tick])
                ):
                    stats.plan_mismatches += 1
            stats.ticks_done += 1
            n_live = int(np.asarray(r_cols["valid"], bool).sum())
            if n_live > 0:
                stats.assigned_frac_min = min(
                    stats.assigned_frac_min,
                    float((p4t >= 0).sum()) / n_live,
                )
    except Exception as e:  # surfaced in the report, never swallowed
        stats.error = f"{type(e).__name__}: {e}"
    finally:
        stats.wall_s = time.perf_counter() - t_run
        client.close()


def run_load(
    sessions: int = 8,
    tenants: int = 2,
    providers: int = 512,
    tasks: int = 512,
    ticks: int = 8,
    churn: float = 0.02,
    kernel: str = "native-mt:1",
    shards: int = 4,
    skew: bool = False,
    traces: Optional[list] = None,
    admit_rate: Optional[float] = None,
    max_bytes: Optional[int] = None,
    queue_depth: int = 8,
    max_workers: int = 16,
    max_sessions: Optional[int] = None,
    seed: int = 0,
    check_endpoint: bool = True,
    restart_at_tick: Optional[int] = None,
    restart_mode: str = "crash",
    ckpt_dir: Optional[str] = None,
    ckpt_every: int = 1,
    processes: int = 1,
    chaos: Optional[str] = None,
    detect: bool = False,
    detector_period_s: float = 0.25,
    rpc_timeout_s: float = 600.0,
    max_retries: int = 20,
    verify_plans: bool = False,
) -> dict:
    """Run the harness; returns the report dict (see module docstring).

    ``skew=True`` gives tenant 0 exactly ONE session and spreads the
    rest over the remaining tenants — the "a tenant hammering 50
    sessions can't starve a tenant with 1" drill. ``traces`` replays
    recorded trace files (cycled over tenants) instead of synthesizing.

    ``restart_at_tick`` arms the RESTART DRILL: once every session has
    completed that many ticks, the servicer is taken down —
    ``restart_mode="crash"`` hard-stops it (the kill path; recovery
    rests on the per-tick flush-before-ack checkpoints),
    ``restart_mode="drain"`` runs the SIGTERM drain (stop admitting,
    finish in-flight ticks, flush checkpoints + trace tails) — and a
    fresh servicer on the same port rehydrates from ``ckpt_dir``
    (a temp dir when None). Sessions ride the production ladder
    through the outage; with checkpoints on, they resume WARM (zero
    reopens, counted in the report).

    ``processes > 1`` runs the DISTRIBUTED fleet instead: N real
    servicer subprocesses behind the consistent-hash endpoint ring,
    sessions routed (with ordered failover lists) by
    :class:`~protocol_tpu.dfleet.topology.FleetTopology` over a shared
    journal root. ``restart_at_tick`` then arms the PROCESS drill —
    ``crash`` SIGKILLs one process (``ChaosConfig.kill_proc`` via the
    ``chaos`` spec; default process 1) and re-routes its orphaned
    journals along the ring; ``drain`` live-migrates its sessions off
    first (Migrate RPC + "moved:" redirects), then SIGTERMs it. The
    report adds per-process scrape summaries and migration counters.

    ``chaos`` with ``pause_proc_at_tick`` arms the ZOMBIE drill
    (processes > 1 only): the target is SIGSTOPped — frozen, not dead
    — and recovery is AUTONOMOUS: the armed failure detector
    (``detect=True``, forced on for this drill) must promote it
    suspect→dead, re-route its journals, and bump the ring with ZERO
    driver-owned kill events; the zombie is then resumed and must be
    fence-refused. ``verify_plans`` compares every fresh warm tick's
    plan against the fault-free in-process replay (the zero-double-
    applied-ticks proof); ``rpc_timeout_s``/``max_retries`` size the
    client ladder for the freeze window. The report grows a
    ``detector`` section: time-to-detect, suspect flaps, fence
    refusals, false-positive ejections."""
    from protocol_tpu.fleet.fabric import FleetConfig
    from protocol_tpu.services.scheduler_grpc import serve
    from protocol_tpu.trace import format as tfmt
    from protocol_tpu.trace.synth import synth_trace

    if restart_mode not in ("crash", "drain"):
        raise ValueError(
            f"restart_mode must be crash|drain, got {restart_mode!r}"
        )
    if int(processes) <= 1 and (detect or verify_plans):
        # refusing beats a vacuous pass: the single-process path arms
        # no detector and builds no baseline, so accepting these flags
        # would report "verified" work that never ran
        raise ValueError(
            "detect/verify_plans require the distributed fleet "
            "(processes > 1)"
        )
    if int(processes) > 1:
        return _run_load_processes(
            sessions=sessions, tenants=tenants, providers=providers,
            tasks=tasks, ticks=ticks, churn=churn, kernel=kernel,
            shards=shards, skew=skew, traces=traces,
            max_workers=max_workers, max_sessions=max_sessions,
            seed=seed, restart_at_tick=restart_at_tick,
            restart_mode=restart_mode, ckpt_dir=ckpt_dir,
            ckpt_every=ckpt_every, processes=int(processes),
            chaos=chaos, admit_rate=admit_rate, max_bytes=max_bytes,
            queue_depth=queue_depth, detect=detect,
            detector_period_s=detector_period_s,
            rpc_timeout_s=rpc_timeout_s, max_retries=max_retries,
            verify_plans=verify_plans,
        )
    sessions = int(sessions)
    tenants = max(1, min(int(tenants), sessions))
    tmpdir = None
    if traces is None:
        tmpdir = tempfile.TemporaryDirectory(prefix="fleet_loadgen_")
        traces = [
            synth_trace(
                os.path.join(tmpdir.name, f"tenant{t}.trace"),
                n_providers=providers, n_tasks=tasks, ticks=ticks,
                churn=churn, seed=seed + t, kernel=kernel,
            )
            for t in range(tenants)
        ]
    parsed = [tfmt.read_trace(p) for p in traces]

    # session -> tenant assignment
    sids: list[tuple[str, object]] = []
    for i in range(sessions):
        if skew and tenants > 1:
            t = 0 if i == 0 else 1 + (i - 1) % (tenants - 1)
        else:
            t = i % tenants
        trace = parsed[t % len(parsed)]
        sids.append((f"t{t}@s{i}", trace))

    ckpt_tmp = None
    if restart_at_tick is not None and ckpt_dir is None:
        ckpt_tmp = tempfile.TemporaryDirectory(prefix="loadgen_ckpt_")
        ckpt_dir = ckpt_tmp.name
    if restart_at_tick is not None:
        # the first server dies mid-run, taking its metrics endpoint
        # with it: the scrape check would report a false negative
        check_endpoint = False
    cfg = FleetConfig(
        shards=shards,
        admit_rate=admit_rate,
        max_bytes=max_bytes,
        delta_queue_depth=queue_depth,
        ckpt_dir=ckpt_dir,
        ckpt_every=ckpt_every,
    )
    port = _free_port()
    address = f"127.0.0.1:{port}"
    serve_kwargs = dict(
        max_workers=max_workers,
        # every concurrent session must be pinnable: the default
        # max_sessions=8 would LRU-thrash 64 concurrent sessions
        max_sessions=max_sessions or max(sessions, 8),
        fleet=cfg,
    )
    server_box = [serve(
        address,
        metrics_port=0 if check_endpoint else None,
        **serve_kwargs,
    )]
    all_stats = [_SessionStats(sid) for sid, _ in sids]
    restart_report: dict = {}

    def _restart_controller(driver_threads):
        """Take the servicer down once every session has ticked past
        ``restart_at_tick``, then bring a fresh one up on the same
        port (rehydrating from ckpt_dir). Driver threads ride their
        retry ladders through the outage."""
        from protocol_tpu.services.scheduler_grpc import drain

        while True:
            # snapshot the live set once per pass: a driver flipping
            # its error flag mid-check must not empty the min() below
            live = [st for st in all_stats if not st.error]
            if not live:
                return  # everybody already failed; nothing to drill
            if min(st.ticks_done for st in live) >= restart_at_tick:
                break
            if not any(th.is_alive() for th in driver_threads):
                # the run finished before any session reached the drill
                # tick (restart_at_tick beyond the trace): exit instead
                # of spinning forever — the smoke gate reports the
                # never-fired drill as the explicit failure it is
                return
            time.sleep(0.01)
        old = server_box[0]
        if restart_mode == "drain":
            restart_report["flushed"] = drain(old, grace_s=10.0)
        else:
            old.stop(grace=None)  # the kill path: no drain, no flush
        server_box[0] = serve(address, metrics_port=None, **serve_kwargs)
        restart_report["restarted"] = True
        restart_report["sessions_restored"] = int(
            server_box[0].servicer.seam.snapshot().get(
                "session_session_restored", 0
            )
        )

    t_wall = time.perf_counter()
    try:
        threads = [
            threading.Thread(
                target=_drive_session,
                args=(address, trace, st.sid, kernel, st),
                kwargs=dict(
                    max_retries=max_retries,
                    rpc_timeout_s=rpc_timeout_s,
                ),
                name=f"loadgen-{st.sid}",
            )
            for (_, trace), st in zip(sids, all_stats)
        ]
        if restart_at_tick is not None:
            threads.append(threading.Thread(
                target=_restart_controller, args=(list(threads),),
                name="loadgen-restart",
            ))
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        wall_s = time.perf_counter() - t_wall
        server = server_box[0]
        obs_snapshot = server.servicer.obs.snapshot()
        endpoint_json = None
        if check_endpoint and server.metrics is not None:
            import urllib.request

            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{server.metrics.port}"
                    "/metrics.json",
                    timeout=10,
                ) as r:
                    endpoint_json = json.loads(r.read().decode())
            except Exception:
                # metrics_endpoint_ok=False IS the report for a dead
                # endpoint — crashing here would hide it behind a
                # traceback instead of a named gate failure
                endpoint_json = None
    finally:
        server = server_box[0]
        if server.metrics is not None:
            server.metrics.stop()
        server.stop(grace=None)
        if tmpdir is not None:
            tmpdir.cleanup()
        if ckpt_tmp is not None:
            ckpt_tmp.cleanup()

    # ---------------- aggregation ----------------
    by_tenant: dict[str, dict] = {}
    session_rates = []
    errors = []
    total_warm_ticks = 0
    for st in all_stats:
        if st.error:
            errors.append({"session": st.sid, "error": st.error})
        agg = by_tenant.setdefault(
            st.tenant,
            {
                "sessions": 0,
                "warm_hist": LatencyHistogram(),
                "cold_hist": LatencyHistogram(),
                "min_assigned_frac": 1.0,
                "ticks_done": 0,
                "refused": 0,
                "reopens": 0,
                "transport_retries": 0,
                "stale": 0,
                "replayed": 0,
            },
        )
        agg["sessions"] += 1
        for w in st.warm:
            agg["warm_hist"].observe_ms(w)
        for c in st.cold_ms:
            agg["cold_hist"].observe_ms(c)
        agg["min_assigned_frac"] = min(
            agg["min_assigned_frac"], st.assigned_frac_min
        )
        agg["ticks_done"] += st.ticks_done
        agg["refused"] += st.refused
        agg["reopens"] += st.reopens
        agg["transport_retries"] += st.transport_retries
        agg["stale"] += st.stale
        agg["replayed"] += st.replayed
        total_warm_ticks += len(st.warm)
        if st.wall_s > 0:
            # zero-warm sessions contribute rate 0: a starved session
            # (every tick refused or reopen-served) must pull the Jain
            # index down, not silently vanish from it
            session_rates.append(len(st.warm) / st.wall_s)

    obs_tenants = obs_snapshot.get("tenants", {})
    slo_snap = obs_snapshot.get("slo", {})

    def _tenant_quality(t: str) -> dict:
        """WHO was unassigned and WHY, not just the assigned fraction:
        per-tenant max starvation age + the unassigned-cause counters
        from the server's quality plane (empty dict for traces recorded
        before the plane existed)."""
        q = (obs_tenants.get(t) or {}).get("quality")
        out: dict = {}
        if q:
            out["starve_max_age"] = q["starvation"]["max_age"]
            causes = dict(q.get("outcomes") or {})
            causes.pop("assigned", None)
            out["unassigned_causes"] = causes
            gap = q.get("gap_per_task")
            if gap:
                out["gap_per_task_max"] = gap["max"]
        fired = (slo_snap.get("fired_by_tenant") or {}).get(t)
        if fired:
            out["slo_alerts_fired"] = fired
        return out

    tenants_out = {
        t: {
            "sessions": a["sessions"],
            "warm_tick": a["warm_hist"].snapshot_ms(),
            "cold_tick": a["cold_hist"].snapshot_ms(),
            "min_assigned_frac": round(a["min_assigned_frac"], 4),
            "ticks_done": a["ticks_done"],
            "refused": a["refused"],
            "reopens": a["reopens"],
            "transport_retries": a["transport_retries"],
            "stale": a["stale"],
            "replayed": a["replayed"],
            **_tenant_quality(t),
        }
        for t, a in sorted(by_tenant.items())
    }

    cores = os.cpu_count() or 1
    agg_warm_per_s = (
        total_warm_ticks / wall_s if wall_s > 0 else 0.0
    )
    # linear-in-cores extrapolation: CPU-bound thread-invariant solves
    # behind sharded locks; holds until the wire/codec saturates
    scaling = {
        "model": "linear in cores (CPU-bound solve, sharded locks); "
                 "valid until the wire or delta codec saturates",
        "measured_cores": cores,
        "measured_warm_ticks_per_s": round(agg_warm_per_s, 2),
        "projected_warm_ticks_per_s": {
            str(c): round(agg_warm_per_s * c / cores, 1)
            for c in (2, 4, 8, 16, 32, 64, 128)
        },
        "projected_sessions_at_1hz": {
            str(c): int(agg_warm_per_s * c / cores)
            for c in (2, 4, 8, 16, 32, 64, 128)
        },
    }

    report = {
        "config": {
            "sessions": sessions,
            "tenants": tenants,
            "providers": providers,
            "tasks": tasks,
            "ticks": ticks,
            "churn": churn,
            "kernel": kernel,
            "shards": shards,
            "skew": skew,
            "admit_rate": admit_rate,
            "max_bytes": max_bytes,
            "queue_depth": queue_depth,
            "seed": seed,
            "restart_at_tick": restart_at_tick,
            "restart_mode": (
                restart_mode if restart_at_tick is not None else None
            ),
            "ckpt_every": (
                ckpt_every if ckpt_dir is not None else None
            ),
            "traces": [str(p) for p in traces] if tmpdir is None else
                      "synth (ephemeral)",
        },
        "wall_s": round(wall_s, 3),
        "total_warm_ticks": total_warm_ticks,
        "aggregate_warm_ticks_per_s": round(agg_warm_per_s, 2),
        "fairness_index_sessions": jain_index(session_rates),
        "tenants": tenants_out,
        "errors": errors,
        "server_obs": {
            "tenants": obs_snapshot.get("tenants", {}),
            "fleet": obs_snapshot.get("fleet", {}),
            "admission": obs_snapshot.get("admission", {}),
            "budget": obs_snapshot.get("budget", {}),
        },
        "metrics_endpoint_ok": endpoint_json is not None,
        "scaling": scaling,
    }
    if restart_at_tick is not None:
        report["restart"] = {
            "mode": restart_mode,
            "at_tick": restart_at_tick,
            **restart_report,
            "reopens_total": sum(st.reopens for st in all_stats),
            "transport_retries_total": sum(
                st.transport_retries for st in all_stats
            ),
            "replayed_total": sum(st.replayed for st in all_stats),
        }
    return report


def _probe_zombie(proc, sid: str) -> dict:
    """Deterministic fence proof against a RESUMED zombie: any delta it
    answers must be a ``moved:`` redirect (the fence check precedes the
    session lookup), and its seam must count the refusal. Returns the
    drill-report fragment; a zombie that cannot be reached within the
    budget reports ``zombie_fence_refused=False`` and the gate fails —
    an unreachable zombie proves nothing."""
    import grpc

    from protocol_tpu.proto import scheduler_pb2 as pb
    from protocol_tpu.services.scheduler_grpc import (
        SchedulerBackendClient,
    )

    out = {"zombie_fence_refused": False}
    client = SchedulerBackendClient(proc.address)
    try:
        for attempt in range(40):
            try:
                resp = client.assign_delta(
                    pb.AssignDeltaRequest(
                        session_id=sid, epoch_fingerprint="probe",
                        tick=1,
                    ),
                    timeout=5.0,
                )
            except grpc.RpcError:
                time.sleep(0.25)
                continue
            out["zombie_fence_refused"] = (
                not resp.session_ok
                and (
                    resp.error.startswith("moved:")
                    or "fence superseded" in resp.error
                )
            )
            out["zombie_answer"] = resp.error
            break
        try:
            health = client.health(timeout=5.0)
            seam = {m.name: m.value for m in health.seam_metrics}
            out["zombie_fence_refusals"] = int(
                seam.get("session_fence_refused", 0)
            )
            out["zombie_fence_epoch"] = int(
                seam.get("ckpt_fence_epoch", 0)
            )
        except Exception:
            pass
    finally:
        client.close()
    return out


def _run_load_processes(
    sessions: int,
    tenants: int,
    providers: int,
    tasks: int,
    ticks: int,
    churn: float,
    kernel: str,
    shards: int,
    skew: bool,
    traces,
    max_workers: int,
    max_sessions,
    seed: int,
    restart_at_tick,
    restart_mode: str,
    ckpt_dir,
    ckpt_every: int,
    processes: int,
    chaos,
    admit_rate=None,
    max_bytes=None,
    queue_depth: int = 8,
    detect: bool = False,
    detector_period_s: float = 0.25,
    rpc_timeout_s: float = 600.0,
    max_retries: int = 20,
    verify_plans: bool = False,
) -> dict:
    """The distributed-fleet harness behind ``run_load(processes=N)``:
    real subprocesses, ring routing, the process-level kill/migrate
    drills, per-process scrape in the report. Client-side driving is
    the SAME ``_drive_session`` as the single-process harness — the
    failover/moved/handoff rungs are the only additions, and they are
    inert at one endpoint."""
    from protocol_tpu.dfleet.manager import ProcessFleet
    from protocol_tpu.faults.plan import ChaosConfig
    from protocol_tpu.trace import format as tfmt
    from protocol_tpu.trace.synth import synth_trace

    chaos_cfg = (
        ChaosConfig.from_spec(chaos) if isinstance(chaos, str)
        else (chaos or ChaosConfig())
    )
    # drill selection: an explicit --restart-at-tick uses restart_mode;
    # otherwise the CHAOS KNOB that armed the tick picks the action —
    # kill_proc_at_tick is always the crash drill and migrate_at_tick
    # always the live-migrate+drain drill, regardless of the
    # restart_mode default
    if restart_at_tick is not None:
        drill_tick = restart_at_tick
        drill_mode = restart_mode
        drill_proc = (
            chaos_cfg.migrate_proc if drill_mode == "drain"
            else chaos_cfg.kill_proc
        )
    elif chaos_cfg.kill_proc_at_tick is not None:
        drill_tick = chaos_cfg.kill_proc_at_tick
        drill_mode = "crash"
        drill_proc = chaos_cfg.kill_proc
    elif chaos_cfg.migrate_at_tick is not None:
        drill_tick = chaos_cfg.migrate_at_tick
        drill_mode = "drain"
        drill_proc = chaos_cfg.migrate_proc
    elif chaos_cfg.pause_proc_at_tick is not None:
        # the zombie drill: SIGSTOP the target and let the DETECTOR do
        # the rest (zero driver-owned kill events is part of the bar)
        drill_tick = chaos_cfg.pause_proc_at_tick
        drill_mode = "pause"
        drill_proc = chaos_cfg.pause_proc
    else:
        drill_tick = None
        drill_mode = restart_mode
        drill_proc = chaos_cfg.kill_proc
    detect = detect or drill_mode == "pause"
    sessions = int(sessions)
    tenants = max(1, min(int(tenants), sessions))
    tmpdir = None
    if traces is None:
        tmpdir = tempfile.TemporaryDirectory(prefix="dfleet_loadgen_")
        traces = [
            synth_trace(
                os.path.join(tmpdir.name, f"tenant{t}.trace"),
                n_providers=providers, n_tasks=tasks, ticks=ticks,
                churn=churn, seed=seed + t, kernel=kernel,
            )
            for t in range(tenants)
        ]
    parsed = [tfmt.read_trace(p) for p in traces]

    sids: list[tuple[str, object]] = []
    trace_idx: list[int] = []
    for i in range(sessions):
        if skew and tenants > 1:
            t = 0 if i == 0 else 1 + (i - 1) % (tenants - 1)
        else:
            t = i % tenants
        sids.append((f"t{t}@s{i}", parsed[t % len(parsed)]))
        trace_idx.append(t % len(parsed))

    env_extra = {}
    if isinstance(chaos, str) and chaos:
        # rate faults (drop/delay/...) fire inside every process's own
        # seeded interceptor; the scripted process events stay DRIVER-
        # owned here (a process cannot kill -9 itself cleanly)
        env_extra["PROTOCOL_TPU_CHAOS"] = chaos
    # admission/budget knobs ride the FleetConfig env surface into each
    # process (proc.py builds from_env then overrides only identity
    # fields) — a CLI knob accepted next to --processes must configure
    # the fleet, not silently measure against defaults
    if admit_rate is not None:
        env_extra["PROTOCOL_TPU_FLEET_ADMIT_RATE"] = str(admit_rate)
    if max_bytes is not None:
        env_extra["PROTOCOL_TPU_FLEET_MAX_BYTES"] = str(int(max_bytes))
    if queue_depth != 8:
        env_extra["PROTOCOL_TPU_FLEET_QUEUE_DEPTH"] = str(
            int(queue_depth)
        )
    fleet = ProcessFleet(
        processes=processes,
        journal_root=ckpt_dir,
        shards=shards,
        max_sessions=max_sessions or max(sessions, 8),
        max_workers=max_workers,
        ckpt_every=ckpt_every,
        env_extra=env_extra,
        discovery=True,
    )
    all_stats = [_SessionStats(sid) for sid, _ in sids]
    drill_report: dict = {}

    def _drill_controller(driver_threads):
        while True:
            live = [st for st in all_stats if not st.error]
            if not live:
                return
            if min(st.ticks_done for st in live) >= drill_tick:
                break
            if not any(th.is_alive() for th in driver_threads):
                return  # drill tick unreachable: reported, not spun on
            time.sleep(0.01)
        # if the configured target serves ZERO sessions (ring luck with
        # few sessions and ephemeral ports), retarget to the busiest
        # process — a drill that kills/migrates an idle process proves
        # nothing about recovery
        target = drill_proc
        topo = fleet.topology
        by_ep: dict = {}
        for st in all_stats:
            ep = topo.endpoint_for(st.sid)
            by_ep[ep] = by_ep.get(ep, 0) + 1
        if by_ep and not by_ep.get(fleet.proc_at(target).address):
            busiest = max(by_ep, key=lambda e: by_ep[e])
            target = next(
                p.index for p in fleet.procs if p.address == busiest
            )
            drill_report["retargeted"] = True
        drill_report["proc"] = fleet.proc_at(target).proc_id
        if drill_mode == "pause":
            # SIGSTOP, then HANDS OFF: the detector must promote
            # suspect->dead and run the ejection (topology bump, fence
            # supersession, journal re-route) with zero driver-owned
            # kill events — that autonomy is the thing under test
            pid = fleet.proc_at(target).proc_id
            t_pause = time.perf_counter()
            fleet.pause(target)
            drill_report["paused"] = True
            eject = None
            deadline = t_pause + 120.0
            while time.perf_counter() < deadline:
                eject = next(
                    (e for e in list(fleet.ejections)
                     if e["proc"] == pid), None,
                )
                if eject is not None:
                    break
                time.sleep(0.02)
            if eject is not None:
                drill_report["ejected_by_detector"] = True
                drill_report["time_to_detect_s"] = round(
                    eject["at"] - t_pause, 3
                )
                drill_report["journals_rerouted"] = eject[
                    "journals_rerouted"
                ]
                drill_report["generation"] = eject["generation"]
            # resume the zombie AFTER the ejection: its parked deltas
            # and anything clients still send it must be fence-refused
            fleet.resume(target)
            drill_report["resumed"] = True
            if eject is not None:
                drill_report.update(_probe_zombie(
                    fleet.proc_at(target), sids[0][0]
                ))
            return
        if drill_mode == "drain":
            # LIVE migration first (the source keeps answering with
            # "moved:" redirects while sessions rehydrate at the
            # target), then the graceful SIGTERM
            drill_report["migrated"] = fleet.migrate_all(target)
            fleet.drain(target)
            drill_report["drained"] = True
        else:
            fleet.kill(target)
            drill_report["killed"] = True
            moved = fleet.handoff_dead(target)
            drill_report["journals_rerouted"] = len(moved)
        drill_report["proc"] = fleet.proc_at(target).proc_id
        drill_report["generation"] = fleet.topology.generation

    baselines = None
    if verify_plans:
        # fault-free ground truth per trace: the in-process replay's
        # per-tick plans (bit-identical to the wire path by the
        # replay-identity gate) — what "zero double-applied ticks"
        # is asserted against
        from protocol_tpu.trace.replay import replay

        baselines = [
            replay(str(p), engine=kernel, verify=False, keep_p4t=True)[
                "p4ts"
            ]
            for p in traces
        ]

    t_wall = time.perf_counter()
    try:
        fleet.start()
        if detect:
            fleet.start_detector(period_s=detector_period_s)
        topo = fleet.topology
        threads = [
            threading.Thread(
                target=_drive_session,
                args=(
                    topo.failover_order(st.sid), trace, st.sid, kernel,
                    st,
                ),
                kwargs=dict(
                    max_retries=max_retries,
                    rpc_timeout_s=rpc_timeout_s,
                    baseline=(
                        baselines[trace_idx[i]] if baselines else None
                    ),
                ),
                name=f"dfleet-loadgen-{st.sid}",
            )
            for i, ((_, trace), st) in enumerate(
                zip(sids, all_stats)
            )
        ]
        if drill_tick is not None:
            threads.append(threading.Thread(
                target=_drill_controller, args=(list(threads),),
                name="dfleet-loadgen-drill",
            ))
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        wall_s = time.perf_counter() - t_wall
        # stop the detector BEFORE draining survivors: a drain's
        # SIGTERM window reads exactly like a dying process, and an
        # ejection fired at a DELIBERATELY drained proc would pollute
        # the false-positive ledger
        fleet.stop_detector()
        detector_snap = (
            fleet.detector.snapshot() if fleet.detector else None
        )
        ejection_events = list(fleet.ejections)
        scrapes = fleet.scrape()
        topology_out = fleet.topology.to_dict()
        # drain (don't kill) the survivors: each dumps its lock-witness
        # verdict at SIGTERM — reading witness files before this would
        # make the "zero violations in surviving processes" bar vacuous
        # (a SIGKILLed process writes nothing)
        for p in list(fleet.live()):
            try:
                fleet.drain(p.index)
            except Exception:
                pass
        witness = fleet.witness_violations()
    finally:
        fleet.stop()
        if tmpdir is not None:
            tmpdir.cleanup()

    # ---------------- aggregation (client-side) ----------------
    by_tenant: dict[str, dict] = {}
    session_rates = []
    errors = []
    total_warm_ticks = 0
    fleet_warm = LatencyHistogram()
    for st in all_stats:
        if st.error:
            errors.append({"session": st.sid, "error": st.error})
        agg = by_tenant.setdefault(
            st.tenant,
            {
                "sessions": 0,
                "warm_hist": LatencyHistogram(),
                "cold_hist": LatencyHistogram(),
                "min_assigned_frac": 1.0,
                "ticks_done": 0, "refused": 0, "reopens": 0,
                "transport_retries": 0, "stale": 0, "replayed": 0,
                "moved_redirects": 0, "failovers": 0,
                "handoff_waits": 0, "plan_mismatches": 0,
            },
        )
        agg["sessions"] += 1
        for w in st.warm:
            agg["warm_hist"].observe_ms(w)
            fleet_warm.observe_ms(w)
        for c in st.cold_ms:
            agg["cold_hist"].observe_ms(c)
        agg["min_assigned_frac"] = min(
            agg["min_assigned_frac"], st.assigned_frac_min
        )
        for key in (
            "ticks_done", "refused", "reopens", "transport_retries",
            "stale", "replayed", "moved_redirects", "failovers",
            "handoff_waits", "plan_mismatches",
        ):
            agg[key] += getattr(st, key)
        total_warm_ticks += len(st.warm)
        if st.wall_s > 0:
            session_rates.append(len(st.warm) / st.wall_s)

    def _proc_summary(snap) -> Optional[dict]:
        """The per-process slice the fleet report needs: migration/
        restore counters plus that process's own warm-tick view."""
        if snap is None:
            return None
        seam = snap.get("seam") or {}
        obs = snap.get("obs") or {}
        out = {
            k.replace("session_", "", 1): int(v)
            for k, v in seam.items()
            if k.startswith("session_") and any(
                m in k for m in (
                    "open", "restored", "rehydrated", "migrated",
                    "moved", "reopen", "hit", "replayed", "stale",
                    "fence",
                )
            )
        }
        for entry in (obs.get("sessions") or {}).values():
            tick = entry.get("tick") or {}
            if tick.get("count"):
                # scrape gives quantiles, not raw observations: carry
                # p50/p99 per session and report the worst-case p99
                out.setdefault("session_p99s_ms", []).append(
                    tick.get("p99_ms", 0.0)
                )
        if "session_p99s_ms" in out:
            p99s = out.pop("session_p99s_ms")
            out["warm_tick_p99_ms_max"] = max(p99s)
            out["sessions_observed"] = len(p99s)
        return out

    tenants_out = {
        t: {
            "sessions": a["sessions"],
            "warm_tick": a["warm_hist"].snapshot_ms(),
            "cold_tick": a["cold_hist"].snapshot_ms(),
            "min_assigned_frac": round(a["min_assigned_frac"], 4),
            **{k: a[k] for k in (
                "ticks_done", "refused", "reopens",
                "transport_retries", "stale", "replayed",
                "moved_redirects", "failovers", "handoff_waits",
                "plan_mismatches",
            )},
        }
        for t, a in sorted(by_tenant.items())
    }

    report = {
        "config": {
            "sessions": sessions, "tenants": tenants,
            "providers": providers, "tasks": tasks, "ticks": ticks,
            "churn": churn, "kernel": kernel, "shards": shards,
            "skew": skew, "seed": seed, "processes": processes,
            "chaos": chaos if isinstance(chaos, str) else None,
            "restart_at_tick": restart_at_tick,
            "restart_mode": (
                drill_mode if drill_tick is not None else None
            ),
            "ckpt_every": ckpt_every,
        },
        "wall_s": round(wall_s, 3),
        "total_warm_ticks": total_warm_ticks,
        "aggregate_warm_ticks_per_s": round(
            total_warm_ticks / wall_s if wall_s > 0 else 0.0, 2
        ),
        "fleet_warm_tick": fleet_warm.snapshot_ms(),
        "fairness_index_sessions": jain_index(session_rates),
        "tenants": tenants_out,
        "errors": errors,
        "topology": topology_out,
        "processes": {
            pid: _proc_summary(snap) for pid, snap in scrapes.items()
        },
        "witness_violations": witness,
        "migration": {
            "moved_redirects": sum(
                st.moved_redirects for st in all_stats
            ),
            "failovers": sum(st.failovers for st in all_stats),
            "handoff_waits": sum(st.handoff_waits for st in all_stats),
            "reopens_total": sum(st.reopens for st in all_stats),
            "replayed_total": sum(st.replayed for st in all_stats),
            "stale_total": sum(st.stale for st in all_stats),
            "plan_mismatches_total": sum(
                st.plan_mismatches for st in all_stats
            ),
        },
    }
    if verify_plans:
        report["verify_plans"] = True
    if detector_snap is not None:
        # detector observability (ISSUE 14 satellite): time-to-detect
        # (fault injection -> ejection), suspect flaps, fence refusals
        # (zombie probe + survivor scrapes), and the false-positive
        # ledger — an ejection of a process that was never faulted is
        # a drill failure, not noise
        expected = (
            {drill_report.get("proc")} if drill_mode == "pause"
            else set()
        )
        fence_refusals = drill_report.get("zombie_fence_refusals", 0)
        for snap in scrapes.values():
            if snap:
                fence_refusals += int(
                    (snap.get("seam") or {}).get(
                        "session_fence_refused", 0
                    )
                )
        report["detector"] = {
            "snapshot": detector_snap,
            "ejections": ejection_events,
            "suspect_flaps": detector_snap["totals"]["flaps"],
            "suspects_entered": detector_snap["totals"][
                "suspects_entered"
            ],
            "time_to_detect_s": drill_report.get("time_to_detect_s"),
            "fence_refusals": fence_refusals,
            "false_positive_ejections": [
                e for e in ejection_events if e["proc"] not in expected
            ],
        }
    if drill_tick is not None:
        report["drill"] = {
            "mode": drill_mode, "at_tick": drill_tick,
            **drill_report,
        }
    return report


class _EventDrillCtl:
    """Shared state between the event drill controller and the event
    drivers: per-driver progress (the drill trigger), the client-side
    chaos'd delivery schedule, and the storm ledger — fleet-level
    events (detector ejections, mass blackouts) the controller posts
    and every driver fans into its own session's firehose as leave
    events at the sentinel seq tier (``dstream.fanout``)."""

    def __init__(self, schedule=None, topology=None):
        self.schedule = schedule      # FaultSchedule (client delivery)
        self.topology = topology      # initial ring (source homing)
        self._lock = threading.Lock()
        self._storms: list[dict] = []
        self.events_done: dict[str, int] = {}

    def post(self, storm: dict) -> None:
        with self._lock:
            self._storms.append(dict(storm))

    def storms_from(self, cursor: int) -> list:
        with self._lock:
            return list(self._storms[cursor:])

    def progress(self, sid: str, n: int) -> None:
        self.events_done[sid] = n

    def min_progress(self, sids) -> int:
        done = [self.events_done.get(s, 0) for s in sids]
        return min(done) if done else 0


def _drive_event_session(
    address,
    trace,
    sid: str,
    kernel: str,
    rate_hz: float,
    reconcile_every: int,
    out: dict,
    rpc_timeout_s: float = 600.0,
    ctl=None,
    max_retries: int = 20,
    capture_final: bool = False,
) -> None:
    """One OPEN-LOOP event stream over a real wire session: events are
    sent at their trace-scheduled ``at_us`` offsets (never gated on the
    previous answer's completion — lateness is measured, not absorbed),
    through the stream session protocol (stream_mode OpenSession +
    event-typed AssignDelta ticks).

    ``address`` may be an ORDERED endpoint list (the dfleet failover
    ladder): the full ``_drive_session`` refusal ladder applies per
    event — RESOURCE_EXHAUSTED backoff, ``moved:`` rebind (live stream
    migration), evicted resend, handoff-wait rotate, reopen as the
    last rung (the drill bar is ZERO reopens: the checkpointed stream
    state must make every failover warm).

    ``ctl`` arms the distributed drill plane: its chaos schedule
    yields a chaos'd client-side DELIVERY order (drops→retransmits,
    dups, reorders — every re-delivery is a fresh wire tick, so the
    server's event-seq dedup, not tick CRC, must absorb it), and its
    storm ledger injects fleet-level leave events (ejection storms,
    mass blackouts) at the head of the remaining queue. Injected
    storms and their seqs are recorded in ``out["injected"]`` in
    first-send order so the fault-free baseline replay can apply the
    identical event multiset.

    ``capture_final`` pads the tail to the next reconcile boundary
    (``dstream.pad_event`` no-ops) and records the final RECONCILED
    plan in ``out["final_p4t"]`` — the bit-identity witness."""
    import grpc as _grpc

    from protocol_tpu.dstream import fanout as _fan
    from protocol_tpu.proto import scheduler_pb2 as pb
    from protocol_tpu.proto import wire
    from protocol_tpu.services.scheduler_grpc import (
        SchedulerBackendClient,
    )
    from protocol_tpu.stream.events import event_from_delta
    from protocol_tpu.trace import format as tfmt

    endpoints = (
        [str(a) for a in address]
        if isinstance(address, (list, tuple)) else [str(address)]
    )
    ep_i = 0
    client = SchedulerBackendClient(endpoints[ep_i])

    def rebind(endpoint: Optional[str] = None):
        nonlocal client, ep_i
        if endpoint:
            if endpoint not in endpoints:
                endpoints.append(endpoint)
            ep_i = endpoints.index(endpoint)
        try:
            client.close()
        except Exception:
            pass
        client = SchedulerBackendClient(endpoints[ep_i])

    def send(call, transport_attempts: int = 60):
        nonlocal ep_i
        for attempt in range(transport_attempts):
            try:
                return call(client)
            except _grpc.RpcError:
                if attempt + 1 >= transport_attempts:
                    raise
                out["transport_retries"] = (
                    out.get("transport_retries", 0) + 1
                )
                time.sleep(0.02 * min(attempt + 1, 10))
                if attempt >= 1 and len(endpoints) > 1:
                    ep_i = (ep_i + 1) % len(endpoints)
                    out["failovers"] = out.get("failovers", 0) + 1
                rebind()

    snap = trace.snapshot
    events = [event_from_delta(d) for d in trace.deltas]
    if any(ev is None for ev in events):
        out["error"] = "trace is not a stream trace"
        client.close()
        return
    # cumulative column state (events are full-state for their rows):
    # the reopen rung's authority, and the payload source for storm
    # leave events (snapshot values, valid=False)
    p_cum = {k: np.array(v, copy=True) for k, v in snap.p_cols.items()}
    r_cum = {k: np.array(v, copy=True) for k, v in snap.r_cols.items()}
    w = tfmt._as_ns(dict(zip(
        ("price", "load", "proximity", "priority"), snap.weights
    )))

    def _open_stream(p_cols, r_cols):
        req = _request_v2(snap, p_cols, r_cols, kernel)
        req.stream_mode = True
        req.reconcile_every = int(reconcile_every)
        new_fp = wire.epoch_fingerprint(
            p_cols, r_cols, w, kernel,
            max(int(snap.top_k) or 64, 1), snap.eps, snap.max_iters,
        )
        chunks = list(wire.chunk_snapshot(sid, new_fp, req))
        resp = send(lambda c: c.open_session(
            iter(chunks), timeout=rpc_timeout_s
        ))
        if not resp.ok:
            return None, resp.error
        return new_fp, ""

    # client-side chaos'd delivery order: drops become retransmits,
    # dups second copies, reorders late arrivals — every index is
    # delivered at least once, and every delivery is a fresh tick
    if ctl is not None and ctl.schedule is not None:
        from protocol_tpu.faults.plan import event_delivery_order

        order = event_delivery_order(
            ctl.schedule, len(events), site=f"events/{sid}"
        )
    else:
        order = list(range(len(events)))

    try:
        fp, err = _open_stream(snap.p_cols, snap.r_cols)
        if fp is None:
            out["error"] = f"open refused: {err}"
            return
        t_start = time.perf_counter()
        server_tick = 0
        walls_us: list = []
        injected: list = []
        lag_us_max = 0.0
        gap_max = 0.0
        reconciles = deduped = late = 0
        window_max = 0
        window_last = 0
        storm_cursor = 0
        storm_events = 0
        pad_i = 0
        first_sent: set = set()
        last_recon_p4t = None

        def _mint(storm) -> list:
            if storm.get("kind") == "ejection":
                rows = _fan.affected_rows(
                    ctl.topology, sid, storm["dead_proc"],
                    len(next(iter(p_cum.values()))),
                )
                return _fan.ejection_leave_events(
                    storm["generation"], rows, snap.p_cols
                )
            rows = np.asarray(storm.get("rows", ()), np.int32)
            return _fan.mass_leave_events(
                int(storm.get("mass_index", 0)), rows, snap.p_cols
            )

        def _send_event(ev):
            """Full refusal ladder for ONE event delivery. Returns the
            response, or None after an irrecoverable refusal (error is
            set). Folds applied full-state rows into the cumulative
            columns (dedup-ACKed deliveries are NOT folded: a reordered
            stale event would regress the authority)."""
            nonlocal server_tick, fp
            nonlocal reconciles, deduped, gap_max
            nonlocal window_max, window_last, last_recon_p4t
            evict_retried = False
            for retry in range(max_retries):
                dreq = pb.AssignDeltaRequest(
                    session_id=sid, epoch_fingerprint=fp,
                    tick=server_tick + 1,
                    event_source=ev.source, event_seq=int(ev.seq),
                    event_kind=ev.kind,
                )
                if ev.provider_rows.size:
                    dreq.provider_rows.CopyFrom(
                        wire.blob(ev.provider_rows, np.int32)
                    )
                    dreq.providers.CopyFrom(
                        wire.encode_providers_v2(tfmt._as_ns(ev.p_cols))
                    )
                if ev.task_rows.size:
                    dreq.task_rows.CopyFrom(
                        wire.blob(ev.task_rows, np.int32)
                    )
                    dreq.requirements.CopyFrom(
                        wire.encode_requirements_v2(
                            tfmt._as_ns(ev.r_cols)
                        )
                    )
                r = send(lambda c: c.assign_delta(
                    dreq, timeout=rpc_timeout_s
                ))
                if r.session_ok:
                    server_tick += 1
                    if r.replayed:
                        out["replayed"] = out.get("replayed", 0) + 1
                    reconciles += int(r.reconciled)
                    deduped += int(r.event_deduped)
                    gap_max = max(gap_max, float(r.gap_per_task))
                    window_last = int(r.events_since_reconcile)
                    window_max = max(window_max, window_last)
                    if not r.event_deduped:
                        if ev.provider_rows.size:
                            for name, a in ev.p_cols.items():
                                p_cum[name][ev.provider_rows] = (
                                    np.asarray(a)
                                )
                        if ev.task_rows.size:
                            for name, a in ev.r_cols.items():
                                r_cum[name][ev.task_rows] = (
                                    np.asarray(a)
                                )
                    if r.reconciled:
                        last_recon_p4t = wire.unblob(
                            r.result.provider_for_task, np.int32
                        )
                    out["assigned_last"] = int(r.result.num_assigned)
                    return r
                out["refused"] = out.get("refused", 0) + 1
                if "RESOURCE_EXHAUSTED" in r.error:
                    time.sleep(0.01 * (retry + 1))
                    continue
                if r.error.startswith("moved:"):
                    # live stream migration: the engine is re-armed
                    # WARM at the new home (dedup cursors + cadence
                    # travel in the checkpoint) — rebind and resend
                    out["moved_redirects"] = (
                        out.get("moved_redirects", 0) + 1
                    )
                    rebind(r.error[len("moved:"):].strip())
                    continue
                if "session evicted" in r.error and not evict_retried:
                    evict_retried = True
                    continue
                if (
                    "unknown session" in r.error
                    and len(endpoints) > 1
                    and retry + 1 < max_retries
                ):
                    out["handoff_waits"] = (
                        out.get("handoff_waits", 0) + 1
                    )
                    time.sleep(0.02 * (retry + 1))
                    rebind_idx()
                    continue
                # last rung: reopen from the cumulative columns (the
                # drill bar is zero of these — stream state travels)
                out["reopens"] = out.get("reopens", 0) + 1
                out["verify_stopped"] = True
                fp2, err2 = None, ""
                for dr in range(max_retries):
                    fp2, err2 = _open_stream(p_cum, r_cum)
                    if fp2 is not None or "draining" not in (
                        err2 or ""
                    ):
                        break
                    time.sleep(0.05 * (dr + 1))
                if fp2 is None:
                    out["error"] = f"re-open refused: {err2}"
                    return None
                fp = fp2
                server_tick = 0
                # fall through: the next retry resends this event as
                # tick 1 of the re-grounded session
            out["error"] = (
                f"event still refused after {max_retries} "
                f"retries: {r.error}"
            )
            return None

        def rebind_idx():
            nonlocal ep_i
            ep_i = (ep_i + 1) % len(endpoints)
            rebind()

        from collections import deque as _deque

        pending: "_deque" = _deque()
        i = 0
        sent = 0
        while i < len(order) or pending:
            if ctl is not None:
                storms = ctl.storms_from(storm_cursor)
                if storms:
                    storm_cursor += len(storms)
                    for storm in storms:
                        leaves = _mint(storm)
                        pending.extend(leaves)
                        injected.extend(leaves)
            if pending:
                ev = pending.popleft()
                storm_events += 1
            else:
                idx = order[i]
                i += 1
                ev = events[idx]
                if idx not in first_sent:
                    first_sent.add(idx)
                    # open-loop: wait for the scheduled arrival —
                    # lateness is recorded, never absorbed. Chaos
                    # re-deliveries (dups/retransmits) go immediately.
                    target = t_start + ev.at_us / 1e6
                    now = time.perf_counter()
                    if now < target:
                        time.sleep(target - now)
                    else:
                        lag_us_max = max(
                            lag_us_max, (now - target) * 1e6
                        )
                        late += 1
            t0 = time.perf_counter()
            r = _send_event(ev)
            if r is None:
                return
            if not r.reconciled:
                walls_us.append((time.perf_counter() - t0) * 1e6)
            sent += 1
            if ctl is not None:
                ctl.progress(sid, sent)
        if capture_final:
            # pad to the next reconcile boundary: the final answer
            # must be a RECONCILED plan (full solve of the converged
            # columns) for the bit-identity comparison
            while window_last > 0 and pad_i <= reconcile_every + 2:
                r = _send_event(_fan.pad_event(pad_i))
                if r is None:
                    return
                pad_i += 1
                sent += 1
            out["final_p4t"] = last_recon_p4t
        out["wall_s"] = time.perf_counter() - t_start
        out["events"] = sent
        out["storm_events"] = storm_events
        out["pad_events"] = pad_i
        out["injected"] = injected
        out["walls_us"] = walls_us
        out["reconciles"] = reconciles
        out["deduped"] = deduped
        out["gap_max"] = gap_max
        out["window_max"] = window_max
        out["late_events"] = late
        out["lag_us_max"] = round(lag_us_max, 1)
    except Exception as e:  # surfaced in the report, never swallowed
        out["error"] = f"{type(e).__name__}: {e}"
    finally:
        client.close()


_EVENT_LADDER_KEYS = (
    "refused", "transport_retries", "failovers", "moved_redirects",
    "handoff_waits", "reopens", "replayed",
)


def _aggregate_event_outs(sids, outs):
    """Per-tenant join of the event drivers' ``out`` dicts (shared by
    the single-process and distributed harnesses)."""
    from protocol_tpu.obs.metrics import LatencyHistogram, tenant_of as _t

    by_tenant: dict[str, dict] = {}
    errors = []
    total_events = 0
    for sid, out in zip(sids, outs):
        if out.get("error"):
            errors.append({"session": sid, "error": out["error"]})
            continue
        t = _t(sid)
        agg = by_tenant.setdefault(t, {
            "hist": LatencyHistogram(lowest_ns=100.0),
            "events": 0, "reconciles": 0, "deduped": 0,
            "gap_max": 0.0, "window_max": 0, "late_events": 0,
            "storm_events": 0, "assigned_last_min": None,
            **{k: 0 for k in _EVENT_LADDER_KEYS},
        })
        for us in out.get("walls_us", ()):
            agg["hist"].observe_ns(us * 1e3)
        agg["events"] += out.get("events", 0)
        agg["reconciles"] += out.get("reconciles", 0)
        agg["deduped"] += out.get("deduped", 0)
        agg["gap_max"] = max(agg["gap_max"], out.get("gap_max", 0.0))
        agg["window_max"] = max(
            agg["window_max"], out.get("window_max", 0)
        )
        agg["late_events"] += out.get("late_events", 0)
        agg["storm_events"] += out.get("storm_events", 0)
        for k in _EVENT_LADDER_KEYS:
            agg[k] += out.get(k, 0)
        a = out.get("assigned_last")
        if a is not None:
            prev = agg["assigned_last_min"]
            agg["assigned_last_min"] = (
                a if prev is None else min(prev, a)
            )
        total_events += out.get("events", 0)
    tenants_out = {}
    for t, agg in sorted(by_tenant.items()):
        tenants_out[t] = {
            "events": agg["events"],
            "event_rpc": agg["hist"].snapshot_us(),
            "reconciles": agg["reconciles"],
            "deduped": agg["deduped"],
            "gap_max": round(agg["gap_max"], 6),
            "events_since_reconcile_max": agg["window_max"],
            "late_events": agg["late_events"],
            "storm_events": agg["storm_events"],
            "assigned_last_min": agg["assigned_last_min"],
            **{k: agg[k] for k in _EVENT_LADDER_KEYS},
        }
    return tenants_out, errors, total_events


def _event_baseline_p4t(
    trace_path, kernel: str, reconcile_every: int, extra_events
):
    """Fault-free ground truth for a chaos'd / storm-injected stream
    session: the in-process replay of the SAME trace with the SAME
    injected events appended in-order, final full-solve reconcile.
    Per-source latest-wins plus storms at the sentinel seq tier make
    the converged columns — and therefore the reconciled plan —
    independent of where chaos interleaved the deliveries."""
    from protocol_tpu.stream.replay import stream_replay

    eng, _, th = str(kernel).partition(":")
    rep = stream_replay(
        str(trace_path), engine=eng,
        threads=int(th) if th else None,
        reconcile_every=int(reconcile_every), verify=False,
        final_reconcile=True, keep_recon_p4ts=True,
        extra_events=list(extra_events or ()),
    )
    p4ts = rep.get("recon_p4ts") or []
    return p4ts[-1] if p4ts else None


def _event_bit_identity(paths, sids, outs, kernel, reconcile_every):
    """Compare every driver's final reconciled plan against the
    fault-free baseline. Baselines are cached by (trace, injected
    seqs): the injected payloads are pure functions of (trace
    snapshot, source, seq), so equal keys mean equal baselines."""
    checked = mismatches = skipped = 0
    mismatched = []
    cache: dict = {}
    for tp, sid, out in zip(paths, sids, outs):
        if (
            out.get("error") or out.get("verify_stopped")
            or out.get("final_p4t") is None
        ):
            skipped += 1
            continue
        key = (str(tp), tuple(
            (e.source, int(e.seq)) for e in out.get("injected") or ()
        ))
        if key not in cache:
            cache[key] = _event_baseline_p4t(
                tp, kernel, reconcile_every, out.get("injected")
            )
        base = cache[key]
        checked += 1
        if base is None or not np.array_equal(out["final_p4t"], base):
            mismatches += 1
            mismatched.append(sid)
    return {
        "checked": checked,
        "mismatches": mismatches,
        "skipped": skipped,
        "mismatched_sessions": mismatched,
    }


def _trace_sources(trace) -> int:
    """Distinct event sources in a stream trace (the denominator of
    the zero-dropped-sources acceptance bar)."""
    from protocol_tpu.stream.events import event_from_delta

    srcs = set()
    for d in trace.deltas:
        ev = event_from_delta(d)
        if ev is not None:
            srcs.add(ev.source)
    return len(srcs)


def _run_events_processes(
    sessions: int,
    tenants: int,
    providers: int,
    tasks: int,
    events: int,
    rate_hz: float,
    kernel: str,
    reconcile_every: int,
    shards: int,
    max_workers: int,
    seed: int,
    rpc_timeout_s: float,
    processes: int,
    chaos=None,
    detect: bool = False,
    detector_period_s: float = 0.25,
    ckpt_dir=None,
    ckpt_every: int = 1,
    max_retries: int = 20,
    trace_path=None,
    mass_at_event=None,
    mass_frac: float = 0.1,
) -> dict:
    """The DISTRIBUTED event firehose (``--events --processes N``):
    every session is a stream-mode wire session homed by the ring on
    one of N real servicer subprocesses; drivers run the full failover
    ladder per event. The chaos spec arms three planes at once —
    client-side chaos'd DELIVERY (drop/dup/reorder of event sends,
    absorbed by server-side event-seq dedup), the scripted process
    drill (``kill_proc_at_tick`` = SIGKILL after that many EVENTS per
    session, ``migrate_at_tick`` = live migration + drain), and each
    process's own seeded interceptor. A kill translates into an
    EJECTION STORM: one leave event per source homed on the corpse,
    injected into every surviving session's firehose at the sentinel
    seq tier and absorbed online (O(churned rows) per event). A
    ``mass_at_event`` trigger composes the ``faults/`` blackout shape
    into a fleet-wide mass leave event. The report carries fleet-wide
    events/sec, per-event p99 µs, the stream rollup joined from every
    process's scrape, and the bit-identity verdict of every session's
    final reconciled plan against its fault-free baseline replay."""
    from protocol_tpu.dfleet.manager import ProcessFleet
    from protocol_tpu.dstream import fanout as _fan
    from protocol_tpu.dstream.rollup import stream_rollup
    from protocol_tpu.faults.plan import ChaosConfig, FaultSchedule
    from protocol_tpu.trace import format as tfmt
    from protocol_tpu.trace.synth import synth_event_trace

    chaos_cfg = (
        ChaosConfig.from_spec(chaos) if isinstance(chaos, str)
        else (chaos or ChaosConfig())
    )
    if chaos_cfg.kill_proc_at_tick is not None:
        drill_event, drill_mode = chaos_cfg.kill_proc_at_tick, "crash"
        drill_proc = chaos_cfg.kill_proc
    elif chaos_cfg.migrate_at_tick is not None:
        drill_event, drill_mode = chaos_cfg.migrate_at_tick, "drain"
        drill_proc = chaos_cfg.migrate_proc
    else:
        drill_event, drill_mode = None, None
        drill_proc = chaos_cfg.kill_proc
    schedule = FaultSchedule(chaos_cfg) if chaos_cfg.active() else None

    sessions = int(sessions)
    tenants = max(1, min(int(tenants), sessions))
    tmpdir = tempfile.TemporaryDirectory(prefix="dstream_loadgen_")
    try:
        paths = []
        for i in range(sessions):
            if trace_path:
                # the gate's golden-trace mode: every session replays
                # the SAME committed trace (identical baselines)
                paths.append(str(trace_path))
            else:
                paths.append(synth_event_trace(
                    os.path.join(tmpdir.name, f"s{i}.trace"),
                    n_providers=providers, n_tasks=tasks,
                    events=events, seed=seed + i, kernel=kernel,
                    rate_hz=rate_hz, reconcile_every=reconcile_every,
                ))
        parsed_cache: dict = {}
        traces = []
        for p in paths:
            if p not in parsed_cache:
                parsed_cache[p] = tfmt.read_trace(p)
            traces.append(parsed_cache[p])
        sids = [f"t{i % tenants}@es{i}" for i in range(sessions)]
        outs = [dict() for _ in range(sessions)]
        sources_per_session = [
            _trace_sources(parsed_cache[p]) for p in paths
        ]

        env_extra = {}
        if isinstance(chaos, str) and chaos:
            env_extra["PROTOCOL_TPU_CHAOS"] = chaos
        fleet = ProcessFleet(
            processes=int(processes),
            journal_root=ckpt_dir,
            shards=shards,
            max_sessions=max(sessions, 8),
            max_workers=max_workers,
            ckpt_every=ckpt_every,
            env_extra=env_extra,
            discovery=True,
        )
        drill_report: dict = {}
        mass_report: dict = {}
        ctl = _EventDrillCtl(schedule=schedule)

        def _wait_for_event(at, driver_threads) -> bool:
            while True:
                live = [
                    s for s, o in zip(sids, outs) if not o.get("error")
                ]
                if not live:
                    return False
                if ctl.min_progress(live) >= at:
                    return True
                if not any(th.is_alive() for th in driver_threads):
                    return False
                time.sleep(0.01)

        def _drill_controller(driver_threads):
            triggers = []
            if mass_at_event is not None:
                triggers.append((int(mass_at_event), "mass"))
            if drill_event is not None:
                triggers.append((int(drill_event), drill_mode))
            for at, mode in sorted(triggers):
                if not _wait_for_event(at, driver_threads):
                    return
                if mode == "mass":
                    sched = _fan.blackout_storm_schedule(
                        seed, chaos_cfg.blackout_shard or 1,
                        providers, mass_frac,
                    )
                    ctl.post({
                        "kind": "mass",
                        "mass_index": sched["mass_index"],
                        "rows": sched["rows"],
                    })
                    mass_report.update(
                        at_event=at, rows=len(sched["rows"]),
                        shard=sched["shard"],
                    )
                    continue
                # retarget to the busiest process if ring luck left
                # the configured target idle (same rule as batch mode)
                target = drill_proc
                topo = fleet.topology
                by_ep: dict = {}
                for s in sids:
                    ep = topo.endpoint_for(s)
                    by_ep[ep] = by_ep.get(ep, 0) + 1
                if by_ep and not by_ep.get(
                    fleet.proc_at(target).address
                ):
                    busiest = max(by_ep, key=lambda e: by_ep[e])
                    target = next(
                        p.index for p in fleet.procs
                        if p.address == busiest
                    )
                    drill_report["retargeted"] = True
                pid = fleet.proc_at(target).proc_id
                drill_report["proc"] = pid
                if mode == "drain":
                    # LIVE stream migration: sessions re-arm warm at
                    # the ring successor (full stream state travels in
                    # the checkpoint) — no storm, the sources flow on
                    drill_report["migrated"] = fleet.migrate_all(
                        target
                    )
                    fleet.drain(target)
                    drill_report["drained"] = True
                    continue
                t_kill = time.perf_counter()
                gen = None
                if detect:
                    # SIGKILL withOUT telling the fleet: the DETECTOR
                    # must notice the silence and run the autonomous
                    # ejection (topology bump + fence supersession +
                    # journal re-route) — a scripted fleet.kill would
                    # be removed from its watch and prove nothing
                    fleet.kill_unannounced(target)
                    drill_report["killed"] = True
                    eject = None
                    deadline = t_kill + 60.0
                    while time.perf_counter() < deadline:
                        eject = next(
                            (e for e in list(fleet.ejections)
                             if e["proc"] == pid), None,
                        )
                        if eject is not None:
                            break
                        time.sleep(0.02)
                    if eject is not None:
                        drill_report["ejected_by_detector"] = True
                        drill_report["time_to_detect_s"] = round(
                            eject["at"] - t_kill, 3
                        )
                        drill_report["journals_rerouted"] = eject[
                            "journals_rerouted"
                        ]
                        gen = eject["generation"]
                if gen is None:
                    # no detector (or it never fired): driver-owned
                    # takedown + journal re-route, the batch-mode shape
                    fleet.kill(target)
                    drill_report["killed"] = True
                    moved = fleet.handoff_dead(target)
                    drill_report["journals_rerouted"] = len(moved)
                    gen = fleet.topology.generation
                drill_report["generation"] = gen
                # the ejection storm: every source homed on the corpse
                # leaves, fanned into every session's firehose at the
                # storm seq tier (generation-keyed, deterministic)
                ctl.post({
                    "kind": "ejection", "dead_proc": pid,
                    "generation": gen,
                })
                drill_report["storm_posted"] = True

        t_wall = time.perf_counter()
        try:
            fleet.start()
            if detect:
                fleet.start_detector(period_s=detector_period_s)
            ctl.topology = fleet.topology
            topo = fleet.topology
            threads = [
                threading.Thread(
                    target=_drive_event_session,
                    args=(
                        topo.failover_order(sid), trace, sid, kernel,
                        rate_hz, reconcile_every, out,
                    ),
                    kwargs=dict(
                        rpc_timeout_s=rpc_timeout_s, ctl=ctl,
                        max_retries=max_retries, capture_final=True,
                    ),
                    name=f"dstream-{sid}",
                )
                for trace, sid, out in zip(traces, sids, outs)
            ]
            if drill_event is not None or mass_at_event is not None:
                threads.append(threading.Thread(
                    target=_drill_controller, args=(list(threads),),
                    name="dstream-drill",
                ))
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            wall_s = time.perf_counter() - t_wall
            fleet.stop_detector()
            detector_snap = (
                fleet.detector.snapshot() if fleet.detector else None
            )
            ejection_events = list(fleet.ejections)
            scrapes = fleet.scrape()
            rollup = stream_rollup(scrapes)
            topology_out = fleet.topology.to_dict()
            for p in list(fleet.live()):
                try:
                    fleet.drain(p.index)
                except Exception:
                    pass
            witness = fleet.witness_violations()
        finally:
            fleet.stop()

        # fault-free baselines replay INSIDE the try: synth traces
        # live in the tmpdir
        bit = _event_bit_identity(
            paths, sids, outs, kernel, reconcile_every
        )
    finally:
        tmpdir.cleanup()

    tenants_out, errors, total_events = _aggregate_event_outs(
        sids, outs
    )
    dropped = sum(
        n for n, o in zip(sources_per_session, outs)
        if o.get("error")
    )
    report = {
        "mode": "events",
        "config": {
            "sessions": sessions, "tenants": tenants,
            "providers": providers, "tasks": tasks,
            "events_per_session": events, "rate_hz": rate_hz,
            "reconcile_every": reconcile_every, "kernel": kernel,
            "shards": shards, "seed": seed,
            "processes": int(processes),
            "chaos": chaos if isinstance(chaos, str) else None,
            "detect": bool(detect),
            "trace_path": str(trace_path) if trace_path else None,
            "mass_at_event": mass_at_event,
        },
        "sessions": sessions,
        "tenants": tenants_out,
        "wall_s": round(wall_s, 3),
        "events_total": total_events,
        "events_per_s": round(total_events / max(wall_s, 1e-9), 1),
        "storm_events_total": sum(
            o.get("storm_events", 0) for o in outs
        ),
        "pad_events_total": sum(o.get("pad_events", 0) for o in outs),
        "ladder": {
            k: sum(o.get(k, 0) for o in outs)
            for k in _EVENT_LADDER_KEYS
        },
        "sources": {
            "total": sum(sources_per_session),
            "dropped": dropped,
        },
        "bit_identity": bit,
        "errors": errors,
        "topology": topology_out,
        "stream_rollup": rollup,
        "fleet_events_per_s": round(
            rollup.get("events", 0) / max(wall_s, 1e-9), 1
        ),
        "witness_violations": witness,
    }
    if detector_snap is not None:
        expected = (
            {drill_report.get("proc")} if drill_report.get("killed")
            else set()
        )
        report["detector"] = {
            "snapshot": detector_snap,
            "ejections": ejection_events,
            "false_positive_ejections": [
                e for e in ejection_events if e["proc"] not in expected
            ],
        }
    if drill_event is not None or mass_at_event is not None:
        report["drill"] = {
            "mode": drill_mode, "at_event": drill_event,
            **drill_report,
        }
    if mass_report:
        report["mass"] = mass_report
    return report


def run_events(
    sessions: int = 4,
    tenants: int = 2,
    providers: int = 512,
    tasks: int = 512,
    events: int = 128,
    rate_hz: float = 200.0,
    kernel: str = "native-mt:1",
    reconcile_every: int = 64,
    shards: int = 4,
    max_workers: int = 16,
    seed: int = 0,
    rpc_timeout_s: float = 600.0,
    processes: int = 1,
    chaos=None,
    detect: bool = False,
    ckpt_dir=None,
    ckpt_every: int = 1,
    max_retries: int = 20,
    trace_path=None,
    mass_at_event=None,
    mass_frac: float = 0.1,
    blackout_shard: int = 1,
    blackout_refusals: int = 2,
) -> dict:
    """The open-loop EVENT arrival mode (``--events``): H concurrent
    stream sessions each replaying a seeded synthetic event trace
    against real servicer(s) at its deterministic arrival schedule.
    Reports events/sec, per-event p50/p99 µs (client-observed RPC wall,
    reconcile answers excluded — they are full solves and reported
    separately), and the divergence/reconcile counters per tenant.

    ``processes > 1`` switches to the DISTRIBUTED firehose harness
    (:func:`_run_events_processes`): ring-routed sessions over N real
    servicer subprocesses, chaos'd delivery, the kill/migrate drills,
    ejection storms, and per-session bit-identity verdicts.

    ``mass_at_event`` composes the ``faults/`` blackout with the
    stream plane in-process: once every session has sent that many
    events, the harness arms ``SessionFabric.blackout`` on
    ``blackout_shard`` WITH a seeded leave-storm schedule, drains it,
    and fans the mass leave events into every session's firehose —
    the blackout drill exercises the stream path, not just the
    RESOURCE_EXHAUSTED retry ladder."""
    if int(processes) > 1:
        return _run_events_processes(
            sessions, tenants, providers, tasks, events, rate_hz,
            kernel, reconcile_every, shards, max_workers, seed,
            rpc_timeout_s, int(processes), chaos=chaos, detect=detect,
            ckpt_dir=ckpt_dir, ckpt_every=ckpt_every,
            max_retries=max_retries, trace_path=trace_path,
            mass_at_event=mass_at_event, mass_frac=mass_frac,
        )
    from protocol_tpu.dstream import fanout as _fan
    from protocol_tpu.fleet.fabric import FleetConfig
    from protocol_tpu.services.scheduler_grpc import serve
    from protocol_tpu.trace import format as tfmt
    from protocol_tpu.trace.synth import synth_event_trace

    sessions = int(sessions)
    tenants = max(1, min(int(tenants), sessions))
    tmpdir = tempfile.TemporaryDirectory(prefix="fleet_events_")
    mass_armed = mass_at_event is not None
    ctl = _EventDrillCtl() if mass_armed else None
    mass_report: dict = {}
    try:
        paths, traces = [], []
        for i in range(sessions):
            p = synth_event_trace(
                os.path.join(tmpdir.name, f"s{i}.trace"),
                n_providers=providers, n_tasks=tasks, events=events,
                seed=seed + i, kernel=kernel, rate_hz=rate_hz,
                reconcile_every=reconcile_every,
            ) if not trace_path else str(trace_path)
            paths.append(p)
            traces.append(tfmt.read_trace(p))
        port = _free_port()
        address = f"127.0.0.1:{port}"
        server = serve(
            address,
            max_workers=max_workers,
            max_sessions=max(sessions, 8),
            fleet=FleetConfig(shards=shards),
        )
        outs = [dict() for _ in range(sessions)]
        sids = [f"t{i % tenants}@es{i}" for i in range(sessions)]

        def _mass_controller(driver_threads):
            while True:
                live = [
                    s for s, o in zip(sids, outs) if not o.get("error")
                ]
                if not live:
                    return
                if ctl.min_progress(live) >= int(mass_at_event):
                    break
                if not any(th.is_alive() for th in driver_threads):
                    return
                time.sleep(0.005)
            # arm the blackout WITH its leave-storm schedule, then
            # drain and fan out — the full satellite composition path
            sched = _fan.blackout_storm_schedule(
                seed, blackout_shard, providers, mass_frac
            )
            server.servicer.sessions.blackout(
                blackout_shard, blackout_refusals, storm=sched
            )
            for storm in server.servicer.sessions.drain_storms():
                ctl.post({
                    "kind": "mass",
                    "mass_index": storm["mass_index"],
                    "rows": storm["rows"],
                })
            mass_report.update(
                at_event=int(mass_at_event),
                rows=len(sched["rows"]), shard=sched["shard"],
                refusals_armed=blackout_refusals,
            )

        t_wall = time.perf_counter()
        try:
            threads = [
                threading.Thread(
                    target=_drive_event_session,
                    args=(
                        address, trace, sid, kernel, rate_hz,
                        reconcile_every, out,
                    ),
                    kwargs=dict(
                        rpc_timeout_s=rpc_timeout_s, ctl=ctl,
                        max_retries=max_retries,
                        capture_final=mass_armed,
                    ),
                    name=f"events-{sid}",
                )
                for trace, sid, out in zip(traces, sids, outs)
            ]
            if mass_armed:
                threads.append(threading.Thread(
                    target=_mass_controller, args=(list(threads),),
                    name="events-mass",
                ))
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            wall_s = time.perf_counter() - t_wall
            obs_snapshot = server.servicer.obs.snapshot()
            fabric_snapshot = server.servicer.sessions.snapshot()
        finally:
            server.stop(grace=None)
        bit = (
            _event_bit_identity(
                paths, sids, outs, kernel, reconcile_every
            ) if mass_armed else None
        )
    finally:
        tmpdir.cleanup()

    tenants_out, errors, total_events = _aggregate_event_outs(
        sids, outs
    )
    report = {
        "mode": "events",
        "sessions": sessions,
        "tenants": tenants_out,
        "providers": providers,
        "tasks": tasks,
        "events_per_session": events,
        "rate_hz": rate_hz,
        "reconcile_every": reconcile_every,
        "kernel": kernel,
        "wall_s": round(wall_s, 3),
        "events_total": total_events,
        "events_per_s": round(total_events / max(wall_s, 1e-9), 1),
        "errors": errors,
        "server_obs": {
            sid: v.get("stream")
            for sid, v in obs_snapshot.get("sessions", {}).items()
            if v.get("stream")
        },
        "fabric": fabric_snapshot,
    }
    if mass_armed:
        report["mass"] = mass_report
        report["bit_identity"] = bit
        report["storm_events_total"] = sum(
            o.get("storm_events", 0) for o in outs
        )
    return report


def _print_report(rep: dict) -> None:
    cfg = rep["config"]
    print(
        f"fleet loadgen: {cfg['sessions']} sessions / {cfg['tenants']} "
        f"tenants @ {cfg['providers']}x{cfg['tasks']}, "
        f"{cfg['ticks']} ticks, kernel {cfg['kernel']}, "
        f"{cfg['shards']} shards"
    )
    print(
        f"  wall {rep['wall_s']}s, {rep['total_warm_ticks']} warm ticks "
        f"({rep['aggregate_warm_ticks_per_s']}/s aggregate), "
        f"session fairness (Jain) {rep['fairness_index_sessions']}"
    )
    hdr = (
        f"  {'tenant':<8} {'sess':>4} {'p50ms':>8} {'p99ms':>8} "
        f"{'min-assigned':>12} {'refused':>8} {'reopens':>8}"
    )
    print(hdr)
    for t, a in rep["tenants"].items():
        warm = a["warm_tick"]
        quality = ""
        if "starve_max_age" in a:
            causes = a.get("unassigned_causes") or {}
            cause_s = " ".join(
                f"{k}={v}" for k, v in sorted(causes.items()) if v
            )
            quality = (
                f"  starve<={a['starve_max_age']}"
                + (f" [{cause_s}]" if cause_s else "")
            )
        if a.get("slo_alerts_fired"):
            quality += f"  SLO-fired={a['slo_alerts_fired']}"
        print(
            f"  {t:<8} {a['sessions']:>4} "
            f"{warm.get('p50_ms', 0):>8} {warm.get('p99_ms', 0):>8} "
            f"{a['min_assigned_frac']:>12} {a['refused']:>8} "
            f"{a['reopens']:>8}{quality}"
        )
    fl = rep.get("server_obs", {}).get("fleet", {})
    if fl:
        print(
            f"  shards {fl.get('shards')} | arena "
            f"{fl.get('total_bytes', 0) / 1e6:.1f} MB | pressure "
            f"evictions {fl.get('pressure_evictions', 0)}"
        )
    bud = rep.get("server_obs", {}).get("budget", {})
    if bud:
        print(
            f"  thread budget: grants {bud.get('grants')} "
            f"(degraded {bud.get('degraded_grants')}), fairness gauge "
            f"{bud.get('fairness_index')}"
        )
    mig = rep.get("migration")
    if mig:
        print(
            f"  dfleet: failovers {mig['failovers']} | moved redirects "
            f"{mig['moved_redirects']} | handoff waits "
            f"{mig['handoff_waits']} | replayed {mig['replayed_total']}"
            f" | stale {mig['stale_total']} | reopens "
            f"{mig['reopens_total']}"
            + (
                f" | plan mismatches {mig['plan_mismatches_total']}"
                if rep.get("verify_plans") else ""
            )
        )
        det = rep.get("detector")
        if det:
            ttd = det.get("time_to_detect_s")
            print(
                "  detector: "
                + (f"time-to-detect {ttd}s | " if ttd is not None
                   else "")
                + f"suspects {det['suspects_entered']} | flaps "
                f"{det['suspect_flaps']} | fence refusals "
                f"{det['fence_refusals']} | false-positive ejections "
                f"{len(det['false_positive_ejections'])}"
            )
        for pid, p in sorted((rep.get("processes") or {}).items()):
            if p is None:
                print(f"  {pid}: (down)")
                continue
            line = " ".join(
                f"{k}={v}" for k, v in sorted(p.items())
                if not isinstance(v, float)
            )
            p99 = p.get("warm_tick_p99_ms_max")
            if p99 is not None:
                line += f" warm_p99_max={p99}ms"
            print(f"  {pid}: {line}")
        drill = rep.get("drill")
        if drill:
            print(f"  drill: {drill}")
    rs = rep.get("restart")
    if rs:
        print(
            f"  restart drill: mode={rs['mode']} at tick "
            f"{rs['at_tick']} | restored "
            f"{rs.get('sessions_restored', 0)} session(s) | reopens "
            f"{rs['reopens_total']} | transport retries "
            f"{rs['transport_retries_total']} | replayed "
            f"{rs['replayed_total']}"
            + (f" | drain-flushed {rs['flushed']}" if "flushed" in rs
               else "")
        )
    sc = rep.get("scaling")
    if sc:
        print(
            f"  scaling ({sc['model']}): measured "
            f"{sc['measured_warm_ticks_per_s']}/s on "
            f"{sc['measured_cores']} cores -> "
            + ", ".join(
                f"{c}c: {v}/s"
                for c, v in sc["projected_warm_ticks_per_s"].items()
            )
        )
    if rep["errors"]:
        print(f"  ERRORS ({len(rep['errors'])}):")
        for e in rep["errors"][:8]:
            print(f"    {e['session']}: {e['error']}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m protocol_tpu.fleet.loadgen",
        description="Concurrent-trace load harness for the scheduler "
                    "fleet (see module docstring).",
    )
    ap.add_argument("--sessions", type=int, default=8)
    ap.add_argument("--tenants", type=int, default=2)
    ap.add_argument("--providers", type=int, default=512)
    ap.add_argument("--tasks", type=int, default=512)
    ap.add_argument("--ticks", type=int, default=8)
    ap.add_argument("--churn", type=float, default=0.02)
    ap.add_argument("--kernel", default="native-mt:1")
    ap.add_argument("--shards", type=int, default=4)
    ap.add_argument("--skew", action="store_true",
                    help="tenant 0 gets exactly one session")
    ap.add_argument("--trace", action="append", default=None,
                    help="recorded trace file(s); cycled over tenants")
    ap.add_argument("--admit-rate", type=float, default=None)
    ap.add_argument("--max-bytes", type=int, default=None)
    ap.add_argument("--queue-depth", type=int, default=8)
    ap.add_argument("--max-workers", type=int, default=16)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--restart-at-tick", type=int, default=None,
                    help="restart drill: take the servicer down once "
                         "every session passed this tick, bring a "
                         "fresh one up on the same port (warm "
                         "checkpoint rehydration)")
    ap.add_argument("--restart-mode", choices=("crash", "drain"),
                    default="crash")
    ap.add_argument("--ckpt-dir", default=None)
    ap.add_argument("--ckpt-every", type=int, default=1)
    ap.add_argument("--processes", type=int, default=1,
                    help="N > 1 runs the DISTRIBUTED fleet: N real "
                         "servicer subprocesses behind the endpoint "
                         "ring over a shared journal root; the restart "
                         "drill becomes the process kill/migrate drill")
    ap.add_argument("--chaos", default=None,
                    help="seeded chaos spec (faults.plan.ChaosConfig): "
                         "rate faults arm every process's interceptor; "
                         "kill_proc_at_tick/migrate_at_tick/"
                         "pause_proc_at_tick script the driver-owned "
                         "process drills (pause = the zombie drill: "
                         "detector ejection + fence refusal)")
    ap.add_argument("--detect", action="store_true",
                    help="arm the autonomous failure detector "
                         "(forced on by the pause drill)")
    ap.add_argument("--rpc-timeout", type=float, default=600.0,
                    help="per-delta RPC deadline seconds (size small "
                         "for the pause drill so frozen sockets fail "
                         "over instead of hanging)")
    ap.add_argument("--max-retries", type=int, default=20)
    ap.add_argument("--verify-plans", action="store_true",
                    help="compare every fresh warm tick's plan against "
                         "the fault-free in-process replay "
                         "(bit-identity = zero double-applied ticks)")
    ap.add_argument("--events", type=int, default=None,
                    help="EVENT MODE: open-loop per-event arrival "
                         "instead of batch ticks — each session "
                         "replays N single-churn events through a "
                         "stream-mode wire session at the seeded "
                         "deterministic schedule; reports events/sec, "
                         "per-event p50/p99 µs, and divergence/"
                         "reconcile counts per tenant")
    ap.add_argument("--rate", type=float, default=200.0,
                    help="event mode: target open-loop arrival rate "
                         "per session (Hz)")
    ap.add_argument("--reconcile-every", type=int, default=64,
                    help="event mode: full-solve reconciliation "
                         "cadence (events)")
    ap.add_argument("--mass-at-event", type=int, default=None,
                    help="event mode: once every session has sent "
                         "this many events, arm a shard blackout WITH "
                         "its seeded leave-storm schedule and fan the "
                         "mass leave events into every session's "
                         "firehose (faults x stream composition)")
    ap.add_argument("--mass-frac", type=float, default=0.1,
                    help="fraction of provider rows a mass event "
                         "takes down")
    ap.add_argument("--out", default=None, help="write the JSON report")
    ap.add_argument("--smoke", action="store_true",
                    help="exit non-zero unless every session completed "
                         "with assigned fraction >= 0.9 (with a "
                         "restart drill armed: also zero reopens — "
                         "recovery must be warm)")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if args.events is not None:
        rep = run_events(
            sessions=args.sessions, tenants=args.tenants,
            providers=args.providers, tasks=args.tasks,
            events=args.events, rate_hz=args.rate,
            kernel=args.kernel, reconcile_every=args.reconcile_every,
            shards=args.shards, max_workers=args.max_workers,
            seed=args.seed, rpc_timeout_s=args.rpc_timeout,
            processes=args.processes, chaos=args.chaos,
            detect=args.detect, ckpt_dir=args.ckpt_dir,
            ckpt_every=args.ckpt_every, max_retries=args.max_retries,
            trace_path=(args.trace[0] if args.trace else None),
            mass_at_event=args.mass_at_event,
            mass_frac=args.mass_frac,
        )
        print(json.dumps(rep, indent=1, sort_keys=True))
        if args.out:
            with open(args.out, "w") as fh:
                json.dump(rep, fh, indent=1, sort_keys=True)
            print(f"report written: {args.out}")
        if args.smoke:
            bad = list(rep["errors"])
            for t, a in rep["tenants"].items():
                if not a["events"]:
                    bad.append({"tenant": t, "error": "no events ran"})
                if a["assigned_last_min"] is not None and (
                    # small synth populations seat ~90% even COLD
                    # (infeasible tasks); the smoke bar is "the stream
                    # did not bleed assignments", not "the marketplace
                    # is saturated". When a bit-identity verdict
                    # exists the final plan IS the fault-free plan —
                    # that bar subsumes this one (storms legitimately
                    # unseat the stormed rows' tasks).
                    a["assigned_last_min"] < 0.85 * args.tasks
                    and rep.get("bit_identity") is None
                    and rep.get("storm_events_total", 0) == 0
                ):
                    bad.append(
                        {"tenant": t, "error": "assigned < 0.85"}
                    )
            ladder = rep.get("ladder") or {}
            reopens = ladder.get("reopens", sum(
                a.get("reopens", 0) for a in rep["tenants"].values()
            ))
            if reopens:
                bad.append({"error": (
                    f"{reopens} full-snapshot reopens — stream "
                    "failover was not warm"
                )})
            bit = rep.get("bit_identity")
            if bit and bit["mismatches"]:
                bad.append({"error": (
                    f"{bit['mismatches']} final plans diverged from "
                    "the fault-free baseline: "
                    f"{bit['mismatched_sessions']}"
                )})
            drill = rep.get("drill")
            if drill and drill.get("mode") and not (
                drill.get("killed") or drill.get("drained")
            ):
                bad.append({"error": "process drill never fired"})
            src = rep.get("sources")
            if src and src["dropped"]:
                bad.append({"error": (
                    f"{src['dropped']} event sources dropped"
                )})
            det = rep.get("detector") or {}
            if det.get("false_positive_ejections"):
                bad.append({"error": (
                    "detector ejected never-faulted process(es): "
                    f"{det['false_positive_ejections']}"
                )})
            for pid, viols in (
                rep.get("witness_violations") or {}
            ).items():
                if viols:
                    bad.append({"proc": pid, "error": (
                        f"{len(viols)} lock-order witness violation(s)"
                    )})
            if bad:
                print(f"SMOKE FAIL: {bad}")
                return 1
            print("events smoke OK")
        return 0
    rep = run_load(
        sessions=args.sessions, tenants=args.tenants,
        providers=args.providers, tasks=args.tasks, ticks=args.ticks,
        churn=args.churn, kernel=args.kernel, shards=args.shards,
        skew=args.skew, traces=args.trace, admit_rate=args.admit_rate,
        max_bytes=args.max_bytes, queue_depth=args.queue_depth,
        max_workers=args.max_workers, seed=args.seed,
        restart_at_tick=args.restart_at_tick,
        restart_mode=args.restart_mode,
        ckpt_dir=args.ckpt_dir, ckpt_every=args.ckpt_every,
        processes=args.processes, chaos=args.chaos,
        detect=args.detect, rpc_timeout_s=args.rpc_timeout,
        max_retries=args.max_retries, verify_plans=args.verify_plans,
    )
    _print_report(rep)
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(rep, fh, indent=1, sort_keys=True)
        print(f"report written: {args.out}")
    if args.smoke:
        bad = list(rep["errors"])
        for t, a in rep["tenants"].items():
            if a["min_assigned_frac"] < 0.9:
                bad.append(
                    {"tenant": t, "error": "assigned frac < 0.9"}
                )
        rs = rep.get("restart")
        if rs and rs["reopens_total"] > 0:
            bad.append({
                "restart": rs["mode"],
                "error": f"{rs['reopens_total']} full-snapshot "
                         "reopens after restart — recovery was not "
                         "warm",
            })
        if rs and not rs.get("restarted"):
            bad.append({
                "restart": rs["mode"],
                "error": "restart controller never fired",
            })
        drill = rep.get("drill")
        if drill:
            mig = rep["migration"]
            if mig["reopens_total"] > 0:
                bad.append({
                    "drill": drill["mode"],
                    "error": f"{mig['reopens_total']} full-snapshot "
                             "reopens after the process drill — "
                             "recovery was not warm",
                })
            if not (
                drill.get("killed") or drill.get("drained")
                or drill.get("paused")
            ):
                bad.append({
                    "drill": drill["mode"],
                    "error": "process drill never fired",
                })
            if drill.get("paused") and not drill.get(
                "ejected_by_detector"
            ):
                bad.append({
                    "drill": drill["mode"],
                    "error": "paused process was never ejected by the "
                             "detector",
                })
            if mig.get("plan_mismatches_total"):
                bad.append({
                    "drill": drill["mode"],
                    "error": f"{mig['plan_mismatches_total']} plans "
                             "diverged from the fault-free replay",
                })
            det = rep.get("detector") or {}
            if det.get("false_positive_ejections"):
                bad.append({
                    "drill": drill["mode"],
                    "error": "detector ejected never-faulted "
                             f"process(es): "
                             f"{det['false_positive_ejections']}",
                })
            for pid, viols in (
                rep.get("witness_violations") or {}
            ).items():
                if viols:
                    bad.append({
                        "proc": pid,
                        "error": f"{len(viols)} lock-order witness "
                                 "violation(s)",
                    })
        if bad:
            print(f"SMOKE FAIL: {bad}")
            return 1
        print("loadgen smoke OK")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
