"""Sharded session fabric: the multi-tenant registry behind the seam.

One :class:`~protocol_tpu.services.session_store.SessionStore` is one
lock domain — fine for a handful of sessions, a serialization point for
a fleet. :class:`SessionFabric` spreads sessions over N stores by
consistent hashing (sha1 ring with virtual nodes, so adding a shard
moves ~1/N of the keys) and presents the SAME api surface
(``put``/``get``/``drop``/``__len__``/``evictions``/``expirations``),
so the servicer, tests, and the obs plane's occupancy gauges are
shard-count agnostic.

On top of the shards sits the **arena memory budget**. Every session's
pinned bytes are estimated ONCE at open from rows x dtype widths
(:func:`estimate_arena_bytes` — the wire specs already fix every
column's width) and rolled up per tenant and fleet-wide under a single
leaf lock. Crossing ``max_bytes`` (or a tenant crossing
``tenant_max_bytes``, or the fleet crossing the global ``max_sessions``
count) triggers eviction PRESSURE: expired sessions are swept first,
then the globally least-recently-used victim (chosen across all
shards, per-shard LRU candidates compared by ``last_used``) is evicted
with the PR 3 evicted-flag semantics — an in-flight delta that already
looked the victim up refuses instead of solving against a disowned
arena, and the client re-opens from its authoritative state.

Lock ordering (deadlock freedom): shard locks never nest, and the
fabric's ``_budget_lock`` is a LEAF — stores invoke the accounting
callback under their own lock and the callback takes only the budget
lock; the fabric never calls into a shard while holding it.
"""

from __future__ import annotations

import bisect
import dataclasses
import hashlib
import os
from typing import Optional

import numpy as np

from protocol_tpu.obs.metrics import tenant_of
from protocol_tpu.services.session_store import SessionStore
from protocol_tpu.utils.lockwitness import make_lock


def _h(key: str) -> int:
    return int.from_bytes(hashlib.sha1(key.encode()).digest()[:8], "big")


# bound for per-tenant counter dicts whose keys derive from client-minted
# session ids (same rationale as TenantAdmission.max_tenants)
_MAX_TENANT_KEYS = 512


def estimate_arena_bytes(
    p_cols: dict, r_cols: dict, top_k: int,
    reverse_r: int = 8, slack: int = 16,
) -> int:
    """Byte estimate of one session's pinned server-side state, from
    rows x dtype widths: the padded columns (held twice — the session's
    copy plus the arena's canonical previous-tick copy for dirty
    detection), the [T, K] candidate structure (i32 provider + f32
    cost), and the solver duals/flags (price f32 + retired u8 over P,
    potentials f32 over P and T). An estimate, not an audit — the
    budget needs a deterministic, O(columns) number at open time, not a
    heap walk."""
    pb = sum(int(np.asarray(a).nbytes) for a in p_cols.values())
    rb = sum(int(np.asarray(a).nbytes) for a in r_cols.values())
    p_pad = int(np.asarray(p_cols["gpu_count"]).shape[0])
    t_pad = int(np.asarray(r_cols["cpu_cores"]).shape[0])
    k = min(max(int(top_k), 1), max(p_pad, 1))
    cand = t_pad * k * 8  # cand_p i32 + cand_c f32
    # the persistent repair state: reverse-edge keys u64 over
    # [P, reverse_r] and the slack shadow i32+f32 over [T, slack] —
    # defaults mirror NativeSolveArena's; callers running bigger knobs
    # must pass theirs or the admission budget undercounts
    cand += p_pad * reverse_r * 8 + t_pad * slack * 8
    duals = p_pad * (4 + 1 + 4) + t_pad * 4
    return 2 * (pb + rb) + cand + duals


@dataclasses.dataclass
class FleetConfig:
    """Fleet knobs, separate from the servicer's per-store arguments.
    The defaults keep standalone behavior identical: unlimited
    admission, no byte budget, and a fabric whose global
    ``max_sessions`` pressure reproduces the single-store LRU exactly.

    ``PROTOCOL_TPU_FLEET_*`` environment variables configure a served
    process without code changes (``from_env``)."""

    shards: int = 4
    vnodes: int = 64
    max_bytes: Optional[int] = None
    tenant_max_bytes: Optional[int] = None
    admit_rate: Optional[float] = None  # tokens/s per tenant; None = off
    admit_burst: float = 16.0
    tenant_weights: Optional[dict] = None
    delta_queue_depth: int = 8  # <= 0 disables backpressure
    # ---- resilience layer (chaos plane). ``ckpt_dir`` enables warm
    # session checkpoints (faults/checkpoint.py): flushed every
    # ``ckpt_every`` ticks BEFORE the tick is acknowledged, rehydrated
    # at servicer boot. ``tick_deadline_ms`` arms the per-tick solve
    # watchdog: a tick whose budget is already burned is served the
    # previous plan with an explicit stale flag, never more than
    # ``max_stale_ticks`` in a row (the bounded-staleness contract).
    ckpt_dir: Optional[str] = None
    ckpt_every: int = 1
    tick_deadline_ms: Optional[float] = None
    max_stale_ticks: int = 2
    # ---- distributed fleet (dfleet). ``proc_id`` namespaces this
    # process's checkpoint journals under the shared ``ckpt_dir`` root
    # (journals are keyed by (proc id, session id) so N processes can
    # share one journal volume); ``endpoint`` is the address this
    # process advertises in "moved:<endpoint>" migration redirects and
    # the discovery map.
    proc_id: str = "p0"
    endpoint: Optional[str] = None

    @classmethod
    def from_env(cls) -> "FleetConfig":
        env = os.environ.get

        def _opt(name, cast):
            raw = env(name)
            return cast(raw) if raw else None

        return cls(
            shards=int(env("PROTOCOL_TPU_FLEET_SHARDS", "4")),
            max_bytes=_opt("PROTOCOL_TPU_FLEET_MAX_BYTES", int),
            tenant_max_bytes=_opt(
                "PROTOCOL_TPU_FLEET_TENANT_MAX_BYTES", int
            ),
            admit_rate=_opt("PROTOCOL_TPU_FLEET_ADMIT_RATE", float),
            admit_burst=float(env("PROTOCOL_TPU_FLEET_ADMIT_BURST", "16")),
            delta_queue_depth=int(
                env("PROTOCOL_TPU_FLEET_QUEUE_DEPTH", "8")
            ),
            ckpt_dir=env("PROTOCOL_TPU_FLEET_CKPT_DIR") or None,
            ckpt_every=int(env("PROTOCOL_TPU_FLEET_CKPT_EVERY", "1")),
            tick_deadline_ms=_opt(
                "PROTOCOL_TPU_FLEET_TICK_DEADLINE_MS", float
            ),
            max_stale_ticks=int(
                env("PROTOCOL_TPU_FLEET_MAX_STALE", "2")
            ),
            proc_id=env("PROTOCOL_TPU_FLEET_PROC_ID", "p0"),
            endpoint=env("PROTOCOL_TPU_FLEET_ENDPOINT") or None,
        )


class SessionFabric:
    """Consistent-hash sharded SessionStore fleet with a global arena
    memory budget. See the module docstring for the design contract."""

    def __init__(
        self,
        shards: int = 4,
        max_sessions: int = 8,
        ttl_s: float = 900.0,
        max_bytes: Optional[int] = None,
        tenant_max_bytes: Optional[int] = None,
        vnodes: int = 64,
    ):
        self.n_shards = max(1, int(shards))
        # GLOBAL cap: each shard could hold the whole fleet; the fabric
        # enforces the fleet-wide count itself via global-LRU pressure,
        # which reproduces the single-store LRU semantics exactly (the
        # victim is the least-recently-used session anywhere)
        self.max_sessions = int(max_sessions)
        self.max_bytes = max_bytes
        self.tenant_max_bytes = tenant_max_bytes
        self.shards = [
            SessionStore(
                max_sessions=self.max_sessions,
                ttl_s=ttl_s,
                on_evict=self._on_store_evict,
            )
            for _ in range(self.n_shards)
        ]
        # consistent-hash ring: vnodes per shard, immutable after init
        ring = sorted(
            (_h(f"shard-{i}/vnode-{j}"), i)
            for i in range(self.n_shards)
            for j in range(max(1, int(vnodes)))
        )
        self._ring_keys = [k for k, _ in ring]
        self._ring_shards = [s for _, s in ring]
        # ---- arena budget accounting (LEAF lock: callbacks land here
        # from under shard locks; never call a shard while holding it)
        self._budget_lock = make_lock("budget")
        self._by_session: dict[str, tuple] = {}  # sid -> (session, tenant, bytes)
        self._tenant_bytes: dict[str, int] = {}
        self._total_bytes = 0
        self._pressure_evictions = 0
        self._evictions_by_tenant: dict[str, int] = {}
        # ---- shard blackout (chaos plane: store-level fault). A
        # blacked-out shard REFUSES the next N lookups with the
        # RESOURCE_EXHAUSTED retry shape — the session still exists, so
        # a client that backs off and retries resumes warm with zero
        # reopens; an eviction-shaped refusal here would amplify a
        # transient shard outage into a full-snapshot reopen herd.
        self._blackout_lock = make_lock("blackout")
        self._blackout: dict[int, int] = {}  # shard index -> refusals left
        self.blackout_refusals_served = 0
        # ---- blackout x stream composition (ISSUE 20 satellite). The
        # refusal counter above is the whole story for BATCH sessions,
        # but a regional blackout should also take providers off the
        # grid — and the refusal path emits no leave events, so stream
        # sessions would never hear about it. Arming a blackout can now
        # carry a seeded leave-storm schedule (dstream.fanout.
        # blackout_storm_schedule); the drill driver drains it and fans
        # mass leave events into every session's firehose, so blackout
        # drills exercise the stream path, not just the retry ladder.
        self._blackout_storms: list[dict] = []
        self.blackout_storms_armed = 0
        # optional let-go observer (the servicer's checkpoint GC): fires
        # for EVERY store let-go path with its reason, under the owning
        # shard's lock — leaf work only, same contract as on_evict
        self.on_let_go = None

    # ---------------- shard map ----------------

    def shard_index(self, session_id: str) -> int:
        i = bisect.bisect_right(self._ring_keys, _h(session_id))
        return self._ring_shards[i % len(self._ring_shards)]

    def shard_of(self, session_id: str) -> SessionStore:
        return self.shards[self.shard_index(session_id)]

    # ---------------- SessionStore-compatible surface ----------------

    def put(self, session) -> None:
        self.shard_of(session.session_id).put(session)
        self._account(session)
        self._apply_pressure(protect=session.session_id)

    def get(self, session_id: str, fingerprint: str):
        idx = self.shard_index(session_id)
        with self._blackout_lock:
            left = self._blackout.get(idx, 0)
            if left > 0:
                self._blackout[idx] = left - 1
                self.blackout_refusals_served += 1
                return None, (
                    "RESOURCE_EXHAUSTED: shard blackout (retry)"
                )
        return self.shards[idx].get(session_id, fingerprint)

    def blackout(self, shard: int, refusals: int, storm=None) -> None:
        """Black out one shard for the next ``refusals`` lookups (the
        chaos plane's store-level fault). Deterministic by construction:
        counted in lookups, not wall-clock.

        ``storm`` optionally attaches a seeded leave-storm schedule
        (``dstream.fanout.blackout_storm_schedule``): the blackout then
        also represents providers leaving the grid, and the drill
        driver drains the schedule (:meth:`drain_storms`) to fan mass
        leave events into every stream session's firehose."""
        with self._blackout_lock:
            self._blackout[int(shard) % self.n_shards] = int(refusals)
            if storm is not None:
                self._blackout_storms.append(dict(storm))
                self.blackout_storms_armed += 1

    def drain_storms(self) -> list:
        """Pop every armed leave-storm schedule (drill-driver seam:
        each schedule is fanned out exactly once)."""
        with self._blackout_lock:
            storms, self._blackout_storms = self._blackout_storms, []
            return storms

    def drop(self, session_id: str) -> None:
        self.shard_of(session_id).drop(session_id)

    def snapshot_sessions(self) -> list:
        """Point-in-time list of every live session across shards (the
        drain path's checkpoint-flush walk)."""
        out = []
        for shard in self.shards:
            out.extend(shard.snapshot_sessions())
        return out

    def __len__(self) -> int:
        return sum(len(s) for s in self.shards)

    @property
    def evictions(self) -> int:
        return sum(s.evictions for s in self.shards)

    @property
    def expirations(self) -> int:
        return sum(s.expirations for s in self.shards)

    # ---------------- fleet surface ----------------

    def sweep(self) -> int:
        """Deterministic TTL sweep over every shard (satellite of the
        fleet issue: idle expired sessions release their arena bytes
        without waiting for the next access-path touch). The eviction
        callbacks release the byte accounting as a side effect."""
        return sum(shard.sweep() for shard in self.shards)

    @property
    def total_bytes(self) -> int:
        with self._budget_lock:
            return self._total_bytes

    def tenant_bytes(self, tenant: str) -> int:
        with self._budget_lock:
            return self._tenant_bytes.get(tenant, 0)

    def snapshot(self) -> dict:
        """Occupancy + budget gauges for the obs plane (rendered on the
        existing /metrics endpoint via ObsRegistry.attach(fleet=...))."""
        with self._budget_lock:
            tenant_bytes = {
                t: b for t, b in self._tenant_bytes.items() if b
            }
            total = self._total_bytes
            pressure = self._pressure_evictions
            by_tenant = dict(self._evictions_by_tenant)
        return {
            "shards": [len(s) for s in self.shards],
            "sessions": len(self),
            "max_sessions": self.max_sessions,
            "total_bytes": total,
            "max_bytes": self.max_bytes,
            "tenant_bytes": tenant_bytes,
            "tenant_max_bytes": self.tenant_max_bytes,
            "pressure_evictions": pressure,
            "evictions": self.evictions,
            "expirations": self.expirations,
            "evictions_by_tenant": by_tenant,
            "blackout_refusals_served": self.blackout_refusals_served,
            "blackout_storms_armed": self.blackout_storms_armed,
        }

    # ---------------- budget accounting ----------------

    def _account(self, session) -> None:
        with self._budget_lock:
            if session.evicted:
                # lost the open-vs-pressure race before accounting: the
                # store already flagged it (flag is set BEFORE the
                # eviction callback fires), so adding bytes now would
                # leak them forever
                return
            tenant = tenant_of(session.session_id)
            est = int(session.arena_bytes)
            self._by_session[session.session_id] = (session, tenant, est)
            self._tenant_bytes[tenant] = (
                self._tenant_bytes.get(tenant, 0) + est
            )
            self._total_bytes += est

    def _on_store_evict(self, session, reason: str) -> None:
        # store callback: runs under the owning shard's lock; only the
        # leaf budget lock may be taken here
        if self.on_let_go is not None:
            try:
                self.on_let_go(session, reason)
            except Exception:
                pass  # an observer failure must never fail an eviction
        with self._budget_lock:
            entry = self._by_session.get(session.session_id)
            if entry is None or entry[0] is not session:
                # never accounted (lost the open race) or already
                # superseded by a same-id re-open — nothing to release
                return
            del self._by_session[session.session_id]
            _, tenant, est = entry
            remaining = self._tenant_bytes.get(tenant, 0) - est
            if remaining > 0:
                self._tenant_bytes[tenant] = remaining
            else:
                # prune zeroed tenants: tenant keys derive from
                # client-minted session ids (a bare uuid's tenant is
                # the whole uuid), so keeping dead entries would grow
                # this dict — and the _over_budget scan of it — by one
                # per client ever connected
                self._tenant_bytes.pop(tenant, None)
            self._total_bytes -= est
            if reason in ("lru", "pressure"):
                # only involuntary capacity evictions count here —
                # client-initiated drop/replace and TTL expiry have
                # their own store counters, and folding them in would
                # make the per-tenant pressure signal unusable
                self._evictions_by_tenant[tenant] = (
                    self._evictions_by_tenant.get(tenant, 0) + 1
                )
                while len(self._evictions_by_tenant) > _MAX_TENANT_KEYS:
                    self._evictions_by_tenant.pop(
                        next(iter(self._evictions_by_tenant))
                    )
            if reason == "pressure":
                self._pressure_evictions += 1

    # ---------------- eviction pressure ----------------

    def _over_budget(self) -> tuple[bool, Optional[str]]:
        with self._budget_lock:
            if self.max_bytes is not None and (
                self._total_bytes > self.max_bytes
            ):
                return True, None
            if self.tenant_max_bytes is not None:
                for t, b in self._tenant_bytes.items():
                    if b > self.tenant_max_bytes:
                        return True, t
        if len(self) > self.max_sessions:
            return True, None
        return False, None

    def _global_lru(
        self, exclude=(), tenant: Optional[str] = None
    ) -> Optional[tuple[int, str]]:
        """Globally least-recently-used session: each shard nominates
        its local LRU (under its own lock), the fabric picks the oldest
        ``last_used`` (ties broken by session id for determinism)."""
        best = None
        for i, shard in enumerate(self.shards):
            cand = shard.lru_candidate(exclude=exclude, tenant=tenant)
            if cand is None:
                continue
            sid, last_used = cand
            key = (last_used, sid)
            if best is None or key < best[0]:
                best = (key, i, sid)
        if best is None:
            return None
        return best[1], best[2]

    def _apply_pressure(self, protect: str) -> None:
        """Evict until the fleet is back under its count/byte budgets.
        ``protect`` (the session just opened) is never the victim — it
        is the most recently used by definition, but a same-timestamp
        tie must not evict the session whose open triggered the
        pressure. Expired sessions go first (their memory is free);
        then global LRU victims. Bounded: each round evicts exactly one
        session or stops."""
        swept = False
        for _ in range(self.max_sessions + len(self) + 8):
            over, tenant = self._over_budget()
            if not over:
                return
            if not swept:
                swept = True
                if self.sweep():
                    continue
            victim = self._global_lru(exclude=(protect,), tenant=tenant)
            if victim is None:
                # nothing evictable (the protected session alone is
                # over budget): admission/estimation should have
                # refused upstream; never evict the session mid-open
                return
            shard_i, sid = victim
            self.shards[shard_i].evict(sid, reason="pressure")
