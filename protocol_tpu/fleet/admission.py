"""Admission control and fair thread scheduling for the fleet layer.

Three pieces, all non-blocking (the seam's concurrency story forbids
parking an RPC worker on a fairness decision):

``TokenBucket``/``TenantAdmission`` rate-limit session opens and delta
ticks per tenant (tenant = the ``tenant_of`` prefix of the session id).
An over-rate call gets a ``RESOURCE_EXHAUSTED``-style refusal on the
existing protocol surface (``ok=false`` / ``session_ok=false``), which
the client's fallback ladder already handles — refusal is a protocol
answer, never an exception.

``FairThreadBudget`` extends :class:`EngineThreadBudget` with weighted
max-min fairness over tenants: when more than one tenant holds engine
threads, a tenant's grant is capped at its weighted share of the pool
minus what it already holds — so a tenant hammering 50 sessions cannot
starve a tenant with 1. The base contract is untouched: ``acquire``
NEVER blocks, a drained pool degrades to the 1-thread floor, and grants
are sound because the engines are bit-identical at every thread count
(a smaller grant changes wall-clock, never a matching). With a single
active tenant the cap vanishes and grants are bit-compatible with the
base class — single-session behavior is unchanged by construction.

Clocks are injectable (``clock=``) so tests drive refill deterministically;
the defaults read ``time.monotonic`` exactly like the session TTLs.
"""

from __future__ import annotations

import math
import time
from collections import OrderedDict
from typing import Callable, Optional

from protocol_tpu.services.session_store import EngineThreadBudget
from protocol_tpu.utils.lockwitness import make_lock


def jain_index(xs) -> float:
    """Jain's fairness index: 1.0 = perfectly even, 1/n = one winner.
    Zeros are KEPT — a fully-starved participant must drag the index
    down (that is the starvation signal the fleet gate floors on);
    dropping zeros would compute fairness over the healthy survivors
    only and report ~1.0 on exactly the regression this measures."""
    xs = [max(0.0, float(x)) for x in xs]
    if not xs or sum(xs) <= 0:
        return 1.0  # vacuous: nobody did (or wanted) any work
    s = sum(xs)
    return round((s * s) / (len(xs) * sum(x * x for x in xs)), 4)


class TokenBucket:
    """Classic token bucket: ``rate`` tokens/s refill up to ``burst``.
    ``try_take`` is non-blocking — admission refuses, it never queues."""

    def __init__(
        self,
        rate: float,
        burst: float,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.rate = float(rate)
        self.burst = float(burst)
        self._clock = clock
        self._lock = make_lock("bucket")
        self._tokens = float(burst)
        self._last = clock()

    def try_take(self, n: float = 1.0) -> bool:
        with self._lock:
            now = self._clock()
            self._tokens = min(
                self.burst, self._tokens + (now - self._last) * self.rate
            )
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    @property
    def tokens(self) -> float:
        with self._lock:
            return self._tokens


class TenantAdmission:
    """Per-tenant token-bucket admission for OpenSession/AssignDelta.

    ``rate=None`` admits everything (the single-tenant default — the
    fleet knobs must not change standalone behavior) but still counts,
    so the obs plane's per-tenant admitted/refused counters work in
    both modes. ``per_tenant`` overrides (rate, burst) for named
    tenants."""

    def __init__(
        self,
        rate: Optional[float] = None,
        burst: float = 16.0,
        per_tenant: Optional[dict] = None,
        clock: Callable[[], float] = time.monotonic,
        max_tenants: int = 512,
    ):
        self.rate = rate
        self.burst = float(burst)
        self.per_tenant = dict(per_tenant or {})
        self._clock = clock
        self._lock = make_lock("admission")
        # LRU-bounded: tenant keys are derived from client-minted
        # session ids (a bare uuid's "tenant" is the whole uuid — the
        # production RemoteBatchMatcher mints exactly those), so an
        # unbounded dict would grow one bucket + counter entry per
        # session ever seen and explode the per-tenant /metrics
        # cardinality. Same recency-eviction contract as ObsRegistry.
        self.max_tenants = int(max_tenants)
        # tenant -> {"bucket": TokenBucket|None, "admitted": n, "refused": n}
        self._tenants: OrderedDict[str, dict] = OrderedDict()

    def _entry_locked(self, tenant: str) -> dict:
        e = self._tenants.get(tenant)
        if e is not None:
            self._tenants.move_to_end(tenant)
        else:
            spec = self.per_tenant.get(tenant)
            if spec is not None:
                bucket = TokenBucket(spec[0], spec[1], clock=self._clock)
            elif self.rate is not None:
                bucket = TokenBucket(self.rate, self.burst, clock=self._clock)
            else:
                bucket = None
            e = self._tenants[tenant] = {
                "bucket": bucket, "admitted": 0, "refused": 0,
            }
            while len(self._tenants) > self.max_tenants:
                self._tenants.popitem(last=False)
        return e

    def admit(self, tenant: str) -> bool:
        """True = proceed; False = refuse this call (the caller answers
        with the protocol's refusal shape, not an exception)."""
        with self._lock:
            e = self._entry_locked(tenant)
            bucket = e["bucket"]
        # the bucket has its own lock; taking a token outside the
        # registry lock keeps tenants from serializing on each other
        ok = bucket is None or bucket.try_take()
        with self._lock:
            e["admitted" if ok else "refused"] += 1
        return ok

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "rate": self.rate,
                "burst": self.burst,
                "tenants": {
                    t: {"admitted": e["admitted"], "refused": e["refused"]}
                    for t, e in self._tenants.items()
                },
            }


class FairThreadBudget(EngineThreadBudget):
    """Weighted-fair :class:`EngineThreadBudget`.

    Grant ordering is max-min over the tenants currently holding
    threads: with >1 active tenant, tenant ``t`` (weight ``w_t``,
    default 1.0) is capped at ``ceil(total * w_t / sum(active
    weights)) - in_use_t``, floored at the never-blocking 1-thread
    grant. A sole tenant sees exactly the base-class behavior —
    ``min(want, available)`` with the same floor — so the fleet layer
    being "on" never perturbs single-session grants.

    ``fairness_index`` is Jain's index over cumulative granted threads
    per tenant: 1.0 = perfectly even service, 1/n = one tenant took
    everything. It is a *supply* gauge (what the budget handed out), so
    under deliberately skewed demand it reports that skew honestly —
    the loadgen computes the demand-normalized per-session index on
    top of it."""

    def __init__(
        self,
        total: Optional[int] = None,
        weights: Optional[dict] = None,
        max_tenants: int = 512,
    ):
        super().__init__(total)
        self.weights = dict(weights or {})
        # LRU-bounded like TenantAdmission._tenants: uuid-session
        # "tenants" would otherwise accumulate one books entry per
        # session ever served. Tenants still HOLDING threads are never
        # pruned (their in_use books must balance on release).
        self.max_tenants = int(max_tenants)
        self._in_use: dict[str, int] = {}
        self._granted: OrderedDict[str, int] = OrderedDict()

    def _weight(self, tenant: str) -> float:
        return max(float(self.weights.get(tenant, 1.0)), 1e-9)

    def acquire(self, want: int, tenant: str = "-") -> int:
        want = self.total if want <= 0 else min(int(want), self.total)
        with self._lock:
            active = {t for t, n in self._in_use.items() if n > 0}
            active.add(tenant)
            capped = want
            if len(active) > 1:
                wsum = sum(self._weight(t) for t in active)
                share = int(
                    math.ceil(self.total * self._weight(tenant) / wsum)
                )
                capped = min(
                    want, max(1, share - self._in_use.get(tenant, 0))
                )
            grant = max(1, min(capped, self._avail))
            self._avail -= grant
            self._in_use[tenant] = self._in_use.get(tenant, 0) + grant
            self._granted[tenant] = self._granted.get(tenant, 0) + grant
            self._granted.move_to_end(tenant)
            if len(self._granted) > self.max_tenants:
                # prune oldest idle tenants (never one holding threads)
                for t in list(self._granted):
                    if len(self._granted) <= self.max_tenants:
                        break
                    if self._in_use.get(t, 0) <= 0:
                        self._granted.pop(t)
                        self._in_use.pop(t, None)
            self.grants += 1
            if grant < want:
                self.degraded_grants += 1
            if self._avail < self.min_avail:
                self.min_avail = self._avail
        self._point(want, grant)
        return grant

    @staticmethod
    def _point(want: int, grant: int) -> None:
        from protocol_tpu.obs.spans import TRACER

        TRACER.point("budget.grant", want=want, grant=grant)

    def release(self, grant: int, tenant: str = "-") -> None:
        with self._lock:
            self._avail += int(grant)
            self._in_use[tenant] = self._in_use.get(tenant, 0) - int(grant)

    def fairness_index(self) -> float:
        """Jain's fairness index over cumulative granted threads."""
        with self._lock:
            xs = list(self._granted.values())
        return jain_index(xs)

    def tenant_snapshot(self) -> dict:
        with self._lock:
            return {
                t: {
                    "in_use": self._in_use.get(t, 0),
                    "granted_total": g,
                    "weight": self._weight(t),
                }
                for t, g in self._granted.items()
            }
