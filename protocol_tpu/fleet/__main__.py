"""``python -m protocol_tpu.fleet`` — alias for the load harness
(``python -m protocol_tpu.fleet.loadgen``)."""

import sys

from protocol_tpu.fleet.loadgen import main

sys.exit(main())
