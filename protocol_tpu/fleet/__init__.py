"""Multi-tenant scheduler fleet: sharded session fabric, admission
control, weighted-fair thread scheduling, and the concurrent-trace load
harness (``python -m protocol_tpu.fleet.loadgen``).

The scheduler seam serves one session well; this package makes it a
*fleet* service (ROADMAP item 2 — many pools x heavy churn, not one
giant matrix):

  * :class:`SessionFabric` — consistent-hash session->shard mapping
    over N ``SessionStore`` shards (each its own lock domain) with a
    fleet-wide arena byte budget and cross-shard LRU eviction pressure.
  * :class:`TenantAdmission` — per-tenant token-bucket admission on
    OpenSession/AssignDelta; refusals ride the protocol's existing
    ``ok=false`` shapes (RESOURCE_EXHAUSTED-style), which the client
    fallback ladder already handles.
  * :class:`FairThreadBudget` — weighted-fair grant ordering on the
    engine thread budget (never blocks, 1-thread floor preserved).
  * ``loadgen`` — replays H recorded/synthetic traces concurrently over
    real gRPC against one servicer and reports per-tenant p50/p99 tick
    latency, assigned fraction, fairness, and a core-count scaling
    model (imported lazily: it pulls in the servicer).

Tenancy is encoded in the session id: ``tenant@session`` (the
``tenant_of`` convention the obs plane already keys on).
"""

from protocol_tpu.fleet.admission import (  # noqa: F401
    FairThreadBudget,
    TenantAdmission,
    TokenBucket,
)
from protocol_tpu.fleet.fabric import (  # noqa: F401
    FleetConfig,
    SessionFabric,
    estimate_arena_bytes,
)

__all__ = [
    "FairThreadBudget",
    "TenantAdmission",
    "TokenBucket",
    "FleetConfig",
    "SessionFabric",
    "estimate_arena_bytes",
]
