"""Operator / provider CLI.

Covers the reference's dev-utils chain CLIs (crates/dev-utils/examples/:
create_domain, compute_pool, mint_ai_token, whitelist_provider,
get_node_info, eject_node, submit_work, invalidate_work, transfer_eth,
set_min_stake_amount) and the worker CLI subcommands
(crates/worker/src/cli/command.rs:49-186: Run / Check / GenerateWallets /
Balance / SignMessage) against a running devnet's HTTP APIs.

    python -m protocol_tpu.cli [--ledger URL] [--orchestrator URL]
                               [--api-key KEY] <command> ...
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

import aiohttp

from protocol_tpu.security import Wallet


def _print(data) -> None:
    print(json.dumps(data, indent=2, default=str))


def _session() -> aiohttp.ClientSession:
    """Admin client honoring PROTOCOL_TPU_TLS_CA like serve.py's services —
    otherwise a TLS-enabled deployment has no CLI that can reach it."""
    from protocol_tpu.utils.tls import env_client_session

    return env_client_session()


async def ledger_call(args, kind: str, op: str, params: dict):
    headers = {"Authorization": f"Bearer {args.api_key}"} if kind == "write" else {}
    async with _session() as session:
        async with session.post(
            f"{args.ledger}/ledger/{kind}/{op}", json=params, headers=headers
        ) as resp:
            data = await resp.json()
            _print(data)
            return 0 if data.get("success") else 1


async def orch_call(args, method: str, path: str, body=None):
    headers = {"Authorization": f"Bearer {args.api_key}"}
    async with _session() as session:
        async with session.request(
            method, f"{args.orchestrator}{path}", json=body, headers=headers
        ) as resp:
            data = await resp.json()
            _print(data)
            return 0 if resp.status < 400 else 1


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="protocol_tpu.cli")
    parser.add_argument("--ledger", default="http://127.0.0.1:8095")
    parser.add_argument("--orchestrator", default="http://127.0.0.1:8090")
    parser.add_argument("--api-key", default="admin")
    sub = parser.add_subparsers(dest="cmd", required=True)

    # ---- wallet ops (worker CLI: GenerateWallets / SignMessage / Balance)
    sub.add_parser("generate-wallet", help="print a fresh wallet keypair")

    p = sub.add_parser("sign-message")
    p.add_argument("--key", required=True)
    p.add_argument("--message", required=True)

    p = sub.add_parser("balance")
    p.add_argument("--address", required=True)

    p = sub.add_parser(
        "check",
        help="hardware/software readiness report "
        "(worker/src/cli/command.rs Check)",
    )
    p.add_argument("--storage-path", default="/")
    p.add_argument("--probe-accelerator", action="store_true")
    p.add_argument("--port", type=int, default=None, help="advertise port to bind-probe")
    p.add_argument("--require-docker", action="store_true")
    p.add_argument("--speed-url", default=None, help="interconnect probe URL")

    p = sub.add_parser(
        "deregister",
        help="remove a compute node and reclaim its stake "
        "(worker/src/cli/command.rs Deregister)",
    )
    p.add_argument("--provider", required=True)
    p.add_argument("--node", required=True)
    p.add_argument(
        "--reclaim", type=int, default=0,
        help="stake amount to reclaim after removal (0 = none)",
    )

    # ---- chain admin ops (dev-utils)
    p = sub.add_parser("mint")
    p.add_argument("--address", required=True)
    p.add_argument("--amount", type=int, required=True)

    p = sub.add_parser("transfer")
    p.add_argument("--sender", required=True)
    p.add_argument("--to", required=True)
    p.add_argument("--amount", type=int, required=True)

    p = sub.add_parser("create-domain")
    p.add_argument("--name", required=True)
    p.add_argument("--validation-logic", default="")

    p = sub.add_parser("create-pool")
    p.add_argument("--domain-id", type=int, required=True)
    p.add_argument("--creator", required=True)
    p.add_argument("--manager", required=True)
    p.add_argument("--requirements", default="")

    p = sub.add_parser("start-pool")
    p.add_argument("--pool-id", type=int, required=True)
    p.add_argument("--caller", required=True)

    p = sub.add_parser("whitelist-provider")
    p.add_argument("--provider", required=True)

    p = sub.add_parser("get-node-info")
    p.add_argument("--node", required=True)

    p = sub.add_parser("eject-node")
    p.add_argument("--pool-id", type=int, required=True)
    p.add_argument("--node", required=True)
    p.add_argument("--caller", required=True)

    p = sub.add_parser("submit-work")
    p.add_argument("--pool-id", type=int, required=True)
    p.add_argument("--node", required=True)
    p.add_argument("--work-key", required=True)
    p.add_argument("--work-units", type=int, required=True)

    p = sub.add_parser("invalidate-work")
    p.add_argument("--pool-id", type=int, required=True)
    p.add_argument("--work-key", required=True)
    p.add_argument("--penalty", type=int, default=0)
    p.add_argument("--soft", action="store_true")

    p = sub.add_parser("pool-info")
    p.add_argument("--pool-id", type=int, required=True)

    # ---- orchestrator admin ops
    p = sub.add_parser("create-task")
    p.add_argument("--name", required=True)
    p.add_argument("--image", required=True)
    p.add_argument("--cmd", dest="task_cmd", default="", help="comma-separated argv")
    p.add_argument("--env", default="", help="K=V,K2=V2")
    p.add_argument("--topologies", default="", help="comma-separated group configs")
    p.add_argument("--replicas", type=int, default=0)
    p.add_argument("--requirements", default="", help="tpu_scheduler requirements DSL")

    sub.add_parser("list-tasks")
    sub.add_parser("list-nodes")
    sub.add_parser("list-groups")

    p = sub.add_parser("delete-task")
    p.add_argument("--task-id", required=True)

    p = sub.add_parser("ban-node")
    p.add_argument("--address", required=True)

    args = parser.parse_args(argv)

    # local wallet commands need no server
    if args.cmd == "generate-wallet":
        w = Wallet()
        _print({"address": w.address, "private_key": w.private_key_hex()})
        return 0
    if args.cmd == "sign-message":
        w = Wallet.from_hex(args.key)
        _print({"address": w.address, "signature": w.sign_message(args.message)})
        return 0
    if args.cmd == "check":
        from protocol_tpu.services.checks import run_all_checks

        specs, report = run_all_checks(
            args.storage_path,
            port=args.port,
            require_docker=args.require_docker,
            probe_accelerator=args.probe_accelerator,
            speed_url=args.speed_url,
        )
        _print(
            {
                "compute_specs": specs.to_dict(),
                "issues": [
                    {"level": i.level, "message": i.message} for i in report.issues
                ],
                "ready": not report.critical,
            }
        )
        return 0 if not report.critical else 1

    async def dispatch() -> int:
        if args.cmd == "balance":
            return await ledger_call(args, "read", "balance_of", {"address": args.address})
        if args.cmd == "mint":
            return await ledger_call(
                args, "write", "mint", {"address": args.address, "amount": args.amount}
            )
        if args.cmd == "transfer":
            return await ledger_call(
                args, "write", "transfer",
                {"sender": args.sender, "to": args.to, "amount": args.amount},
            )
        if args.cmd == "create-domain":
            return await ledger_call(
                args, "write", "create_domain",
                {"name": args.name, "validation_logic": args.validation_logic},
            )
        if args.cmd == "create-pool":
            return await ledger_call(
                args, "write", "create_pool",
                {
                    "domain_id": args.domain_id,
                    "creator": args.creator,
                    "compute_manager_key": args.manager,
                    "pool_data_uri": args.requirements,
                },
            )
        if args.cmd == "start-pool":
            return await ledger_call(
                args, "write", "start_pool",
                {"pool_id": args.pool_id, "caller": args.caller},
            )
        if args.cmd == "whitelist-provider":
            return await ledger_call(
                args, "write", "whitelist_provider", {"provider": args.provider}
            )
        if args.cmd == "get-node-info":
            return await ledger_call(args, "read", "get_node", {"node": args.node})
        if args.cmd == "eject-node":
            return await ledger_call(
                args, "write", "eject_node",
                {"pool_id": args.pool_id, "node": args.node, "caller": args.caller},
            )
        if args.cmd == "deregister":
            rc = await ledger_call(
                args, "write", "remove_compute_node",
                {"provider": args.provider, "node": args.node},
            )
            if rc == 0 and args.reclaim > 0:
                rc = await ledger_call(
                    args, "write", "reclaim_stake",
                    {"provider": args.provider, "amount": args.reclaim},
                )
            return rc
        if args.cmd == "submit-work":
            return await ledger_call(
                args, "write", "submit_work",
                {
                    "pool_id": args.pool_id,
                    "node": args.node,
                    "work_key": args.work_key,
                    "work_units": args.work_units,
                },
            )
        if args.cmd == "invalidate-work":
            op = "soft_invalidate_work" if args.soft else "invalidate_work"
            params = {"pool_id": args.pool_id, "work_key": args.work_key}
            if not args.soft:
                params["penalty"] = args.penalty
            return await ledger_call(args, "write", op, params)
        if args.cmd == "pool-info":
            return await ledger_call(
                args, "read", "get_pool_info", {"pool_id": args.pool_id}
            )

        if args.cmd == "create-task":
            body: dict = {"name": args.name, "image": args.image}
            if args.task_cmd:
                body["cmd"] = [c for c in args.task_cmd.split(",") if c]
            if args.env:
                body["env_vars"] = dict(
                    kv.split("=", 1) for kv in args.env.split(",") if "=" in kv
                )
            plugins: dict = {}
            if args.topologies:
                plugins["node_groups"] = {
                    "allowed_topologies": args.topologies.split(",")
                }
            tpu_cfg: dict = {}
            if args.replicas:
                tpu_cfg["replicas"] = [str(args.replicas)]
            if args.requirements:
                tpu_cfg["compute_requirements"] = [args.requirements]
            if tpu_cfg:
                plugins["tpu_scheduler"] = tpu_cfg
            if plugins:
                body["scheduling_config"] = {"plugins": plugins}
            return await orch_call(args, "POST", "/tasks", body)
        if args.cmd == "list-tasks":
            return await orch_call(args, "GET", "/tasks")
        if args.cmd == "list-nodes":
            return await orch_call(args, "GET", "/nodes")
        if args.cmd == "list-groups":
            return await orch_call(args, "GET", "/groups")
        if args.cmd == "delete-task":
            return await orch_call(args, "DELETE", f"/tasks/{args.task_id}")
        if args.cmd == "ban-node":
            return await orch_call(args, "POST", f"/nodes/{args.address}/ban")
        parser.error(f"unhandled command {args.cmd}")
        return 2

    return asyncio.run(dispatch())


if __name__ == "__main__":
    sys.exit(main())
