"""Blocked (matrix-free) Sinkhorn: ladder config #3 at 100k+ scale.

The dense Sinkhorn kernel (ops/assign.py) materializes [P, T] — ~40 GB at
100k x 100k, beyond a single chip. This variant keeps only the potentials
u[P], v[T] and recomputes cost blocks from the feature encodings on the fly
(the same streaming trick as candidates_topk):

  v-update: per task tile, a full column logsumexp over P — direct.
  u-update: per provider row, logsumexp over ALL T — a running
            (max, sum-exp) accumulator carried across task tiles in one
            lax.scan (associative streaming logsumexp).

Rounding: the optimal-plan mass for task t is monotone in
(u_p - cost[p,t]/eps), so the plan's top-K entries per task are exactly a
top-K candidate generation under the provider offset -eps*u — which then
feeds the sparse auction / greedy machinery. Sinkhorn supplies global
prices; the candidate auction supplies feasibility.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from protocol_tpu.ops.assign import AssignResult
from protocol_tpu.ops.cost import INFEASIBLE, CostWeights, cost_matrix
from protocol_tpu.ops.encoding import EncodedProviders, EncodedRequirements
from protocol_tpu.ops.sparse import (
    _slice_requirements,
    assign_auction_sparse_scaled,
    candidates_topk,
)

_NEG = -1e18


def make_k_block(ep, er, weights, eps, tile: int):
    """Factory for the streamed Gibbs-kernel block K[:, t0:t0+tile] =
    -cost/eps with infeasible entries at _NEG. Shared by the single-device
    and mesh-sharded Sinkhorn kernels — bit-identical math here is what
    their parity guarantee rests on."""

    def k_block(t0):
        r_tile = _slice_requirements(er, t0, tile)
        cost, _ = cost_matrix(ep, r_tile, weights)
        return jnp.where(cost < INFEASIBLE * 0.5, -cost / eps, _NEG)

    return k_block


def feasibility_scan(k_block, num_providers: int, starts: jax.Array):
    """One streaming pass: (row_any [P], col_any_tiles [n_tiles, tile])."""

    def feas_step(row_any, t0):
        feas = k_block(t0) > _NEG * 0.5
        return row_any | jnp.any(feas, axis=1), jnp.any(feas, axis=0)

    return lax.scan(feas_step, jnp.zeros(num_providers, bool), starts)


def streaming_row_logsumexp(
    k_block, v: jax.Array, starts: jax.Array, num_providers: int, tile: int
) -> jax.Array:
    """Row-wise logsumexp of K + v over all task tiles via a running
    (max, sum-exp) accumulator."""

    def u_step(carry, t0):
        run_max, run_sum = carry
        k = k_block(t0) + lax.dynamic_slice_in_dim(v, t0, tile)[None, :]
        blk_max = jnp.max(k, axis=1)
        new_max = jnp.maximum(run_max, blk_max)
        run_sum = run_sum * jnp.exp(run_max - new_max) + jnp.sum(
            jnp.exp(k - new_max[:, None]), axis=1
        )
        return (new_max, run_sum), None

    (m_u, s_u), _ = lax.scan(
        u_step,
        (
            jnp.full(num_providers, _NEG, jnp.float32),
            jnp.zeros(num_providers, jnp.float32),
        ),
        starts,
    )
    return m_u + jnp.log(jnp.maximum(s_u, 1e-30))


@partial(jax.jit, static_argnames=("num_iters", "tile"))
def sinkhorn_potentials_blocked(
    ep: EncodedProviders,
    er: EncodedRequirements,
    weights: CostWeights | None = None,
    eps: float | jax.Array = 0.05,
    num_iters: int = 50,
    tile: int = 1024,
) -> tuple[jax.Array, jax.Array]:
    """Log-domain potentials (u[P], v[T]) without materializing [P, T].

    Peak memory O(P * tile); each iteration streams the cost tensor twice
    (v pass + u pass).
    """
    if weights is None:
        weights = CostWeights()
    Pn = ep.gpu_count.shape[0]
    T = er.cpu_cores.shape[0]
    if T % tile != 0:
        raise ValueError(f"T={T} not divisible by tile={tile}; pad requirements")
    n_tiles = T // tile
    starts = jnp.arange(n_tiles, dtype=jnp.int32) * tile

    k_block = make_k_block(ep, er, weights, eps, tile)

    # feasibility-count pass -> balanced marginals (ops/assign.py semantics)
    row_any, col_any_tiles = feasibility_scan(k_block, Pn, starts)
    col_any = col_any_tiles.reshape(T)
    np_valid = jnp.maximum(jnp.sum(row_any), 1)
    nt_valid = jnp.maximum(jnp.sum(col_any), 1)
    m = jnp.minimum(np_valid, nt_valid).astype(jnp.float32)
    log_a = jnp.where(row_any, jnp.log(m / np_valid.astype(jnp.float32)), _NEG)
    log_b = jnp.where(col_any, jnp.log(m / nt_valid.astype(jnp.float32)), _NEG)

    def iteration(_i, uv):
        u, v = uv

        # ---- u-update: streaming logsumexp over all task tiles
        lse_u = streaming_row_logsumexp(k_block, v, starts, Pn, tile)
        u = jnp.where(row_any, log_a - lse_u, _NEG)

        # ---- v-update: per-tile full column logsumexp
        def v_step(carry, t0):
            k = k_block(t0) + u[:, None]
            blk_max = jnp.max(k, axis=0)
            lse = blk_max + jnp.log(
                jnp.maximum(jnp.sum(jnp.exp(k - blk_max[None, :]), axis=0), 1e-30)
            )
            return carry, lse

        _, lse_v_tiles = lax.scan(v_step, None, starts)
        v = log_b - lse_v_tiles.reshape(T)
        v = jnp.where(col_any, v, _NEG)
        return u, v

    u0 = jnp.zeros(Pn, jnp.float32)
    v0 = jnp.zeros(T, jnp.float32)
    return lax.fori_loop(0, num_iters, iteration, (u0, v0))


def assign_sinkhorn_blocked(
    ep: EncodedProviders,
    er: EncodedRequirements,
    weights: CostWeights | None = None,
    eps: float = 0.05,
    num_iters: int = 50,
    tile: int = 1024,
    k: int = 32,
) -> AssignResult:
    """Full matrix-free Sinkhorn matching: blocked potentials -> plan-guided
    top-K candidates (provider offset -eps*u) -> sparse auction rounding."""
    if weights is None:
        weights = CostWeights()
    u, _v = sinkhorn_potentials_blocked(
        ep, er, weights, eps=eps, num_iters=num_iters, tile=tile
    )
    # plan mass per (p, t) is monotone in u_p - cost/eps: bias candidate
    # selection by the provider potential
    offset = -eps * jnp.where(u > _NEG * 0.5, u, 0.0)
    cand_p, cand_c = candidates_topk(
        ep, er, weights, k=k, tile=tile, provider_offset=offset
    )
    return assign_auction_sparse_scaled(
        cand_p, cand_c, num_providers=ep.gpu_count.shape[0],
        eps_start=1.0, eps_end=0.02,
    )
