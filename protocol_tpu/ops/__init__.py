"""TPU-side assignment kernels and the numeric feature encoding feeding them.

Modules:
  encoding  - host-side interning + fixed-width numeric encode of the L0
              capability algebra; device-side vectorized ``meets()`` mask.
  cost      - provider x task cost tensor construction (price, load,
              proximity, staleness terms; +inf on incompatibility).
  assign    - assignment kernels: greedy first-fit(-decreasing) scan,
              Sinkhorn entropic OT with feasible rounding, Bertsekas
              auction with deterministic tie-breaking.
"""

# the jit-cache witness must wrap jax.jit BEFORE any kernel module's
# decorators execute (scripts/analysis/staging.py is the static twin)
from protocol_tpu.utils import jitwitness as _jitwitness

_jitwitness.install()

from protocol_tpu.ops.encoding import (
    EncodedProviders,
    EncodedRequirements,
    FeatureEncoder,
    compat_mask,
)
from protocol_tpu.ops.cost import CostWeights, cost_matrix
from protocol_tpu.ops.assign import (
    assign_auction,
    assign_greedy,
    assign_sinkhorn,
)

__all__ = [
    "CostWeights",
    "EncodedProviders",
    "EncodedRequirements",
    "FeatureEncoder",
    "assign_auction",
    "assign_greedy",
    "assign_sinkhorn",
    "compat_mask",
    "cost_matrix",
]
