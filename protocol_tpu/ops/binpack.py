"""Multi-resource vector bin-pack with anti-affinity (ladder #5).

BASELINE.md config #5: providers expose a CAPACITY VECTOR (gpu count,
VRAM, bandwidth, cpu, ram — any fixed set of R resources) and tasks carry
a DEMAND VECTOR; several tasks may land on one provider while capacity
holds. This generalizes the one-task-per-provider matching kernels
(ops/assign.py, ops/sparse.py), whose capacity model is the unit vector.

Anti-affinity is modeled as exclusion GROUPS over placement DOMAINS:
``anti_group[t]`` (-1 = none) names a group whose members must land on
distinct domains, and ``loc_id[p]`` maps providers to domains. Same-
provider exclusion is the special case ``loc_id = arange(P)``; same-
location (city/region) exclusion passes the location class id. This is
the spread-replicas / separate-failure-domains constraint the reference
cannot express at all (its matcher hands every node the same newest task,
crates/orchestrator/src/scheduler/mod.rs:26-74).

Kernel: vectorized first-fit-decreasing as a lax.scan over tasks in
``task_order`` (default: L1-demand descending — classic FFD). Each step is
a fused [P]-wide feasibility mask (capacity + compatibility + group
exclusion) and an argmin pick; running capacity and the [L, G] group
occupancy matrix are scan carries. Deterministic ties (lowest provider
index) make the kernel bit-parity with the host oracle in
tests/test_binpack.py.

Complexity: O(T) sequential steps of O(P*R) work — the right shape up to
~10k tasks per solve (BASELINE ladder #5's test scale). Past that, run it
per delta-frontier batch on top of the incremental matcher (the same
amortization argument as SCALING.md's warm path) rather than cold at 1M.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from protocol_tpu.ops.cost import INFEASIBLE


@jax.tree_util.register_dataclass
@dataclass
class BinpackResult:
    provider_for_task: jax.Array  # i32 [T], -1 = unassigned
    remaining_capacity: jax.Array  # f32 [P, R]

    def num_assigned(self) -> jax.Array:
        return jnp.sum(self.provider_for_task >= 0)


def ffd_demand_order(demand: jax.Array) -> jax.Array:
    """Classic FFD visit order: largest total demand first (L1 norm over
    the resource axis). Stable sort => deterministic among equals."""
    return jnp.argsort(-jnp.sum(demand, axis=1), stable=True).astype(jnp.int32)


@partial(jax.jit, static_argnames=("num_locations", "num_groups"))
def assign_binpack_ffd(
    cost: jax.Array,  # f32 [P, T]; INFEASIBLE marks incompatibility
    demand: jax.Array,  # f32 [T, R]
    capacity: jax.Array,  # f32 [P, R]
    task_order: jax.Array | None = None,  # i32 [T]
    anti_group: jax.Array | None = None,  # i32 [T], -1 = unconstrained
    loc_id: jax.Array | None = None,  # i32 [P] -> [0, num_locations)
    num_locations: int = 0,  # static; 0 = default per-provider domains
    num_groups: int = 0,  # static; 0 = no anti-affinity tracking
) -> BinpackResult:
    """First-fit-decreasing vector bin-pack on the accelerator.

    Each task (in ``task_order``) takes the CHEAPEST provider that (a) is
    compatible (finite cost), (b) has remaining capacity >= demand in every
    resource, and (c) does not violate the task's anti-affinity group on
    the provider's placement domain. Ties break to the lowest provider
    index (argmin picks the first minimum), matching the host oracle.
    """
    P, T = cost.shape
    if task_order is None:
        task_order = ffd_demand_order(demand)
    if anti_group is None:
        anti_group = jnp.full(T, -1, jnp.int32)
    if loc_id is None:
        loc_id = jnp.arange(P, dtype=jnp.int32)
        L = num_locations or P
    else:
        L = num_locations or P
    G = max(num_groups, 1)

    cols = jnp.take(cost.T, task_order, axis=0)  # [T, P] in visit order
    dem = jnp.take(demand, task_order, axis=0)  # [T, R]
    grp = jnp.take(anti_group, task_order, axis=0)  # [T]

    def step(carry, inputs):
        cap, used = carry  # cap [P, R]; used [L, G] bool
        col, d, g = inputs
        fits = jnp.all(cap >= d[None, :], axis=1)  # [P]
        g_safe = jnp.maximum(g, 0)
        # provider p excluded iff its domain already hosts group g
        excluded = (g >= 0) & used[loc_id, g_safe]  # [P]
        masked = jnp.where(fits & ~excluded, col, INFEASIBLE)
        p = jnp.argmin(masked).astype(jnp.int32)
        feasible = masked[p] < INFEASIBLE * 0.5
        take = jnp.where(feasible, d, jnp.zeros_like(d))
        cap = cap.at[p].add(-take)
        mark = feasible & (g >= 0)
        used = used.at[loc_id[p], g_safe].set(
            jnp.where(mark, True, used[loc_id[p], g_safe])
        )
        return (cap, used), jnp.where(feasible, p, -1)

    carry0 = (
        capacity.astype(jnp.float32),
        jnp.zeros((L, G), bool),
    )
    (cap_final, _), picks = lax.scan(step, carry0, (cols, dem, grp))
    provider_for_task = (
        jnp.full(T, -1, jnp.int32).at[task_order].set(picks.astype(jnp.int32))
    )
    return BinpackResult(provider_for_task, cap_final)


def binpack_oracle(cost, demand, capacity, task_order=None, anti_group=None, loc_id=None):
    """Host-side reference implementation (numpy, same tie-breaking):
    the parity oracle for assign_binpack_ffd — mirrors SURVEY §4's
    kernel-vs-CPU-oracle test strategy."""
    import numpy as np

    cost = np.asarray(cost)
    demand = np.asarray(demand, np.float64)
    cap = np.asarray(capacity, np.float64).copy()
    P, T = cost.shape
    if task_order is None:
        task_order = np.argsort(-demand.sum(axis=1), kind="stable")
    if anti_group is None:
        anti_group = np.full(T, -1, np.int64)
    if loc_id is None:
        loc_id = np.arange(P)
    used: set[tuple[int, int]] = set()
    out = np.full(T, -1, np.int64)
    for t in task_order:
        d = demand[t]
        g = int(anti_group[t])
        best, best_cost = -1, INFEASIBLE
        for p in range(P):
            if cost[p, t] >= INFEASIBLE * 0.5:
                continue
            if not (cap[p] >= d - 1e-9).all():
                continue
            if g >= 0 and (int(loc_id[p]), g) in used:
                continue
            if cost[p, t] < best_cost:
                best, best_cost = p, cost[p, t]
        if best >= 0:
            out[t] = best
            cap[best] -= d
            if g >= 0:
                used.add((int(loc_id[best]), g))
    return out, cap
