"""Batched assignment kernels (dense variants).

The measurement ladder (BASELINE.md) replaces the reference's per-heartbeat
greedy matcher (crates/orchestrator/src/scheduler/mod.rs:26-74, O(tasks) per
node, O(nodes*tasks) system-wide per interval) with one batched solve:

  assign_greedy    - vectorized first-fit(-decreasing): lax.scan over tasks,
                     masked argmin over providers per step. Bit-parity oracle
                     for the CPU greedy path given the same task order.
  assign_sinkhorn  - entropic OT in log-space (lax.while_loop), balanced via
                     equalized marginals, then rounded to a feasible matching
                     by a greedy pass over the transport plan.
  assign_auction   - Bertsekas auction: tasks bid for providers, eps-scaling
                     phases, deterministic tie-breaking (argmax picks the
                     lowest index). Near-optimal linear assignment.

Conventions:
  cost  f32 [P, T], INFEASIBLE (1e9) marks incompatibility
  out   AssignResult: provider_for_task i32 [T] (-1 = unassigned),
        task_for_provider i32 [P] (-1 = idle)

All kernels are jit-compatible with static shapes and no data-dependent
Python control flow. Dense [P, T] tensors cap out around ~30k x 30k on a
16 GB chip; the blocked/matrix-free variants for the 100k-1M ladder live in
``protocol_tpu.ops.blocked`` and ``protocol_tpu.parallel``.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from protocol_tpu.ops.cost import INFEASIBLE

# -inf stand-in that survives arithmetic. A Python float on purpose:
# a jnp scalar at module level would initialize the JAX backend at
# import time (fatal for control-plane processes when the remote
# accelerator is unreachable).
_NEG = -1e18


@jax.tree_util.register_dataclass
@dataclass
class AssignResult:
    provider_for_task: jax.Array  # i32 [T], -1 = unassigned
    task_for_provider: jax.Array  # i32 [P], -1 = idle

    def num_assigned(self) -> jax.Array:
        return jnp.sum(self.provider_for_task >= 0)


def _invert(provider_for_task: jax.Array, num_providers: int) -> jax.Array:
    """task_for_provider from provider_for_task (both injective over >=0)."""
    t_idx = jnp.arange(provider_for_task.shape[0], dtype=jnp.int32)
    out = jnp.full(num_providers, -1, jnp.int32)
    safe = jnp.where(provider_for_task >= 0, provider_for_task, num_providers)
    return out.at[safe].set(jnp.where(provider_for_task >= 0, t_idx, -1), mode="drop")


# --------------------------------------------------------------------------
# Greedy / first-fit-decreasing
# --------------------------------------------------------------------------


@partial(jax.jit, static_argnames=())
def assign_greedy(cost: jax.Array, task_order: jax.Array | None = None) -> AssignResult:
    """Sequential-greedy matching as a lax.scan.

    Visits tasks in ``task_order`` (default: ascending index = the reference's
    "first task in list wins" behavior); each task takes the cheapest still-
    available compatible provider. Ties break to the lowest provider index
    (jnp.argmin returns the first minimum), making the kernel a deterministic
    oracle against the host-side greedy matcher.
    """
    P, T = cost.shape
    if task_order is None:
        task_order = jnp.arange(T, dtype=jnp.int32)

    cols = jnp.take(cost.T, task_order, axis=0)  # [T, P] in visit order

    def step(avail, col):
        masked = jnp.where(avail, col, INFEASIBLE)
        p = jnp.argmin(masked).astype(jnp.int32)
        feasible = masked[p] < INFEASIBLE * 0.5
        avail = avail.at[p].set(jnp.where(feasible, False, avail[p]))
        return avail, jnp.where(feasible, p, -1)

    _, picks = lax.scan(step, jnp.ones(P, dtype=bool), cols)
    provider_for_task = (
        jnp.full(T, -1, jnp.int32).at[task_order].set(picks.astype(jnp.int32))
    )
    return AssignResult(provider_for_task, _invert(provider_for_task, P))


def ffd_order(demand: jax.Array) -> jax.Array:
    """First-fit-DECREASING visit order: biggest resource demand first.
    Stable sort => deterministic among equal demands."""
    return jnp.argsort(-demand, stable=True).astype(jnp.int32)


# --------------------------------------------------------------------------
# Sinkhorn entropic OT
# --------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("num_iters",))
def sinkhorn_plan(
    cost: jax.Array,
    eps: float | jax.Array = 0.05,
    num_iters: int = 200,
) -> jax.Array:
    """Log-domain Sinkhorn: returns the soft transport plan [P, T].

    Marginals are equalized so both sides carry mass min(P_valid, T_valid):
    a balanced problem even when P != T. Infeasible pairs carry INFEASIBLE
    cost and end up with ~zero plan mass. f32 throughout; the logsumexp
    reductions are the HBM-bound hot ops and fuse with the cost broadcast.
    """
    P, T = cost.shape
    feas_row = jnp.any(cost < INFEASIBLE * 0.5, axis=1)  # provider has any task
    feas_col = jnp.any(cost < INFEASIBLE * 0.5, axis=0)
    np_valid = jnp.maximum(jnp.sum(feas_row), 1)
    nt_valid = jnp.maximum(jnp.sum(feas_col), 1)
    m = jnp.minimum(np_valid, nt_valid).astype(jnp.float32)

    log_a = jnp.where(feas_row, jnp.log(m / np_valid.astype(jnp.float32)), _NEG)
    log_b = jnp.where(feas_col, jnp.log(m / nt_valid.astype(jnp.float32)), _NEG)

    K = jnp.where(cost < INFEASIBLE * 0.5, -cost / eps, _NEG)  # [P, T]

    def body(i, uv):
        u, v = uv
        u = log_a - jax.nn.logsumexp(K + v[None, :], axis=1)
        u = jnp.where(feas_row, u, _NEG)
        v = log_b - jax.nn.logsumexp(K + u[:, None], axis=0)
        v = jnp.where(feas_col, v, _NEG)
        return u, v

    u0 = jnp.zeros(P, jnp.float32)
    v0 = jnp.zeros(T, jnp.float32)
    u, v = lax.fori_loop(0, num_iters, body, (u0, v0))
    return jnp.exp(K + u[:, None] + v[None, :])


@partial(jax.jit, static_argnames=("num_iters",))
def assign_sinkhorn(
    cost: jax.Array,
    eps: float | jax.Array = 0.05,
    num_iters: int = 200,
) -> AssignResult:
    """Sinkhorn plan + feasible rounding.

    Rounding = greedy matching on the negated plan (take the strongest
    plan entries first), visiting tasks by their best plan mass descending.
    Guarantees a feasible matching (each provider used once, compatibility
    respected) — the constraint-satisfaction step the soft OT lacks.
    """
    plan = sinkhorn_plan(cost, eps=eps, num_iters=num_iters)
    feasible = cost < INFEASIBLE * 0.5
    # greedy wants a cost; use -plan, infeasible back to INFEASIBLE
    rounding_cost = jnp.where(feasible, -plan, INFEASIBLE)
    order = jnp.argsort(-jnp.max(plan, axis=0), stable=True).astype(jnp.int32)
    return assign_greedy(rounding_cost, task_order=order)


# --------------------------------------------------------------------------
# Bertsekas auction
# --------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("max_iters",))
def assign_auction(
    cost: jax.Array,
    eps: float | jax.Array = 0.01,
    max_iters: int = 500,
) -> AssignResult:
    """Forward auction: unassigned tasks bid for their best-value provider.

    value[t, p] = -cost[p, t] - price[p]. Each round every unassigned task
    bids price[p1] + (v1 - v2) + eps on its best provider p1; each provider
    takes the highest bid (ties -> lowest task index), evicting the previous
    owner. eps fixed per call; wrap with eps-scaling externally if needed.
    Near-optimal: within n*eps of the optimal assignment value.

    O(P*T) per round, all rounds inside one lax.while_loop — no host
    round-trips.
    """
    P, T = cost.shape
    value_base = jnp.where(cost < INFEASIBLE * 0.5, -cost, _NEG).T  # [T, P]
    task_feasible = jnp.any(value_base > _NEG * 0.5, axis=1)  # [T]

    def cond(state):
        it, price, owner, p4t = state
        unassigned = (p4t < 0) & task_feasible
        return (it < max_iters) & jnp.any(unassigned)

    def body(state):
        it, price, owner, p4t = state
        unassigned = (p4t < 0) & task_feasible  # [T]

        value = value_base - price[None, :]  # [T, P]
        p1 = jnp.argmax(value, axis=1).astype(jnp.int32)  # first max: lowest p
        v1 = jnp.take_along_axis(value, p1[:, None], axis=1)[:, 0]
        masked = value.at[jnp.arange(T), p1].set(_NEG)
        v2 = jnp.max(masked, axis=1)
        v2 = jnp.maximum(v2, jnp.float32(-1e8))  # single-option floor: finite bid

        bid_amt = price[p1] + (v1 - v2) + eps  # [T]

        # provider-side winner: dense scatter of bids, argmax per provider.
        bids = jnp.full((T, P), _NEG)
        bids = bids.at[jnp.arange(T), p1].set(jnp.where(unassigned, bid_amt, _NEG))
        win_bid = jnp.max(bids, axis=0)  # [P]
        win_task = jnp.argmax(bids, axis=0).astype(jnp.int32)  # ties: lowest t
        got_bid = win_bid > _NEG * 0.5  # [P]

        # evict previous owners of contested providers
        prev_owner = owner  # [P]
        evict_t = jnp.where(got_bid & (prev_owner >= 0), prev_owner, T)
        p4t = p4t.at[evict_t].set(-1, mode="drop")

        # install winners
        p_idx = jnp.arange(P, dtype=jnp.int32)
        win_t_safe = jnp.where(got_bid, win_task, T)
        p4t = p4t.at[win_t_safe].set(jnp.where(got_bid, p_idx, -1), mode="drop")
        owner = jnp.where(got_bid, win_task, owner)
        price = jnp.where(got_bid, win_bid, price)
        return it + 1, price, owner, p4t

    state0 = (
        jnp.int32(0),
        jnp.zeros(P, jnp.float32),
        jnp.full(P, -1, jnp.int32),
        jnp.full(T, -1, jnp.int32),
    )
    _, _, owner, p4t = lax.while_loop(cond, body, state0)
    return AssignResult(p4t, _invert(p4t, P))


def assign_auction_scaled(
    cost: jax.Array,
    eps_start: float = 1.0,
    eps_end: float = 0.01,
    scale: float = 0.2,
    max_iters_per_phase: int = 300,
) -> AssignResult:
    """eps-scaling wrapper: run auction phases with geometrically shrinking
    eps, warm-starting each phase from scratch prices (simple variant; price
    warm-starting is a planned optimization). Host-side loop over a few
    phases, device-side while_loop within each."""
    from protocol_tpu.ops.cost import with_tie_jitter

    # degeneracy breaker (see ops/cost.py tie_jitter): exact ties make
    # every open bidder target the same provider — 1 assignment/round
    cost = with_tie_jitter(cost)
    eps = eps_start
    result = None
    while True:
        result = assign_auction(cost, eps=eps, max_iters=max_iters_per_phase)
        if eps <= eps_end:
            return result
        eps = max(eps * scale, eps_end)
