"""Sparse (top-K candidate) assignment: the 1M-scale architecture.

A dense [P, T] cost tensor at 1M x 1M is ~4 TB — unrepresentable. But the
matching only ever uses each task's few best compatible providers, so the
pipeline splits:

  candidates_topk   one streaming pass over the cost tensor in task tiles
                    (lax.scan; [P, tile] per step, never materializing
                    [P, T]) emitting each task's K cheapest compatible
                    providers -> cand_provider/cand_cost [T, K].
  assign_auction_sparse
                    Bertsekas auction restricted to the candidate graph:
                    per-round work is O(T*K) gathers + scatter-max winner
                    resolution over the price vector [P] — independent of
                    P*T. Deterministic ties (lowest provider / lowest task).

With K ~ 32-128 the restricted matching is near-always optimal for
marketplace-shaped costs (many similar providers), while per-iteration HBM
traffic drops from O(P*T) to O(T*K): the difference between 2 s and
milliseconds at 8k x 8k, and the only viable shape at 1M x 1M.

Replaces: the reference's O(tasks)-per-heartbeat greedy walk
(crates/orchestrator/src/scheduler/mod.rs:26-74), at the scale ladder of
BASELINE.md configs #3-#5.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from protocol_tpu.ops.assign import AssignResult, _invert
from protocol_tpu.ops.cost import INFEASIBLE, CostWeights, cost_matrix, tie_jitter
from protocol_tpu.ops.encoding import EncodedProviders, EncodedRequirements

_NEG = -1e18


def _slice_requirements(r: EncodedRequirements, start: int, size: int) -> EncodedRequirements:
    """Static-size tile of the requirements pytree along the task axis."""
    return jax.tree.map(
        lambda leaf: lax.dynamic_slice_in_dim(leaf, start, size, axis=0), r
    )


def frontier_bids(cand_safe, value_base, price, f_idx, f_ok, num_options: int):
    """The auction's per-frontier bid computation, shared verbatim by the
    single-device kernel and the task-sharded mesh kernel — bit-identical
    math here is what the Jacobi parity guarantee between them rests on.

    Returns (p1 best provider, v1 best value, v2 runner-up value [floored]).
    """
    f_safe = jnp.where(f_ok, f_idx, 0)
    cp = cand_safe[f_safe]  # [B, K]
    value = value_base[f_safe] - price[cp]  # the only dynamic gather at scale
    k1 = jnp.argmax(value, axis=1).astype(jnp.int32)
    v1 = jnp.take_along_axis(value, k1[:, None], axis=1)[:, 0]
    v2 = jnp.max(
        jnp.where(jnp.arange(num_options)[None, :] == k1[:, None], _NEG, value),
        axis=1,
    )
    v2 = jnp.maximum(v2, jnp.float32(-1e8))  # single-option floor
    p1 = jnp.take_along_axis(cp, k1[:, None], axis=1)[:, 0]
    return p1, v1, v2


@partial(jax.jit, static_argnames=("k", "tile", "approx_recall"))
def candidates_topk(
    ep: EncodedProviders,
    er: EncodedRequirements,
    weights: CostWeights | None = None,
    k: int = 64,
    tile: int = 1024,
    provider_offset: jax.Array | None = None,
    task_offset: int | jax.Array = 0,
    approx_recall: float | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Each task's top-k cheapest compatible providers.

    Streams the cost tensor in [P, tile] blocks inside a lax.scan — peak
    memory O(P * tile), suitable for P up to ~1M with tile sized to fit.
    Returns (cand_provider i32 [T, k] with -1 padding, cand_cost f32 [T, k]).
    T must be divisible by tile (pad the requirements first).

    ``provider_offset`` [P] biases the SELECTION (e.g. -eps*u from Sinkhorn
    potentials: pick candidates by plan mass) while the returned costs stay
    the true costs, so downstream matchers optimize the real objective.

    ``task_offset`` shifts the task index used by the tie-jitter hash:
    callers that generate candidates in separate delta batches (the
    incremental CandidateCache) pass a persistent cursor so tasks from
    different batches stay decorrelated — identical jitter patterns would
    recreate the everyone-picks-the-same-k collapse the jitter prevents.

    ``approx_recall`` switches selection from exact ``lax.top_k`` (a
    sort-shaped reduction that dominates wall-clock at large P on TPU —
    measured 1.41 Gcells/s at P=131k, SCALING.md) to ``lax.approx_max_k``
    (XLA's TPU-native PartialReduce; expected severalfold faster, on-chip
    measurement pending) with the given per-row recall target. A missed
    candidate only perturbs WHICH near-tied provider a task may match —
    the same degeneracy the tie jitter above already randomizes — so
    matching quality is insensitive to recall ~0.95 for marketplace
    shapes. Deterministic for fixed inputs either way.
    """
    if weights is None:
        weights = CostWeights()
    T = er.cpu_cores.shape[0]
    if T % tile != 0:
        raise ValueError(f"T={T} not divisible by tile={tile}; pad requirements")
    n_tiles = T // tile
    k = min(k, int(ep.gpu_count.shape[0]))  # lax.top_k requires k <= P

    P = ep.gpu_count.shape[0]

    def step(carry, t0):
        provider, cost_k, _cost = _forward_tile_select(
            ep, er, weights, t0, tile, k,
            provider_offset, task_offset, approx_recall,
        )
        return carry, (provider, cost_k)

    _, (cand_p, cand_c) = lax.scan(
        step, None, jnp.arange(n_tiles, dtype=jnp.int32) * tile
    )
    return cand_p.reshape(T, k), cand_c.reshape(T, k)


def _forward_tile_select(
    ep, er, weights, t0, tile: int, k: int,
    provider_offset, task_offset, approx_recall,
):
    """One [P, tile] step of forward candidate selection, shared verbatim
    by the plain and bidirectional scans (``candidates_topk`` /
    ``candidates_topk_reverse``) — a selection-bias or jitter change must
    reach both or the cold bench/gRPC path silently diverges from the
    bidir path. Returns (provider [tile, k], true cost_k [tile, k], and
    the jittered [P, tile] cost block for the caller's reverse fold)."""
    P = ep.gpu_count.shape[0]
    r_tile = _slice_requirements(er, t0, tile)
    cost, _mask = cost_matrix(ep, r_tile, weights)  # [P, tile]
    # Degeneracy breaker: marketplaces have many identically-priced
    # providers; without jitter every task's top-k is the SAME k
    # providers, capping the matching at k regardless of supply (see
    # ops/cost.py tie_jitter).
    jitter = tie_jitter(P, tile, task_offset=t0 + jnp.uint32(task_offset))
    cost = jnp.where(cost < INFEASIBLE * 0.5, cost + jitter, cost)
    if provider_offset is None:
        selection = cost
    else:
        selection = jnp.where(
            cost < INFEASIBLE * 0.5, cost + provider_offset[:, None], cost
        )
    if approx_recall is None:
        neg_sel, idx = lax.top_k(-selection.T, k)  # [tile, k] best first
    else:
        neg_sel, idx = lax.approx_max_k(
            -selection.T, k, recall_target=approx_recall
        )
    cost_k = jnp.take_along_axis(cost.T, idx, axis=1)  # true costs
    sel_k = -neg_sel
    provider = jnp.where(sel_k < INFEASIBLE * 0.5, idx.astype(jnp.int32), -1)
    return provider, cost_k, cost


@partial(
    jax.jit,
    static_argnames=("k", "tile", "reverse_r", "approx_recall", "with_pools"),
)
def candidates_topk_reverse(
    ep: EncodedProviders,
    er: EncodedRequirements,
    weights: CostWeights | None = None,
    k: int = 64,
    tile: int = 1024,
    reverse_r: int = 8,
    provider_offset: jax.Array | None = None,
    task_offset: int | jax.Array = 0,
    approx_recall: float | None = None,
    with_pools: bool = False,
):
    """Bidirectional candidate generation: per-task top-k providers PLUS
    per-provider top-``reverse_r`` tasks, in the same streaming pass.

    Why: with price-dominated costs every task's top-k window covers the
    same cheap providers — at 32k x 32k only ~91% of providers appear in
    ANY task's list (measured), capping the maximum matching at 91% before
    the auction even starts, and 'every node gets a task' (the reference
    matcher's outcome, crates/orchestrator/src/scheduler/mod.rs:26-74) is
    unachievable. Reverse edges guarantee every provider at least
    ``reverse_r`` edges into the graph; merge them with
    :func:`merge_reverse_candidates` and the auction recovers ~100%
    assignment (stage-B completeness, SURVEY §7 hard part 2).

    Returns (cand_p [T,k], cand_c [T,k], rev_t [P,r] i32 with -1 padding,
    rev_c [P,r]). Reverse costs carry the same tie jitter as forward ones.

    Reverse selection is TILE-POOLED, not exact global top-r: each tile
    contributes its per-provider top-``ceil(r / n_tiles)`` tasks and the
    final edges are the best r of that pool. Exactness nobody needs is
    traded for the dominant cost: an exact running top-r folds a
    [P, r+tile] lax.top_k per tile (sort-shaped — measured +48% on the
    whole generation pass at 65k), while the pooled fold is an argmin-
    class reduction plus a [P, r+rt] merge. The properties completeness
    rests on survive exactly: every provider still gets r feasible-if-
    any edges into DISTINCT good tasks, and the single best edge per
    provider is the true global best (every tile's minimum is in the
    pool).

    ``with_pools=True`` additionally returns the raw per-tile
    contributions (pool_t, pool_c) as [P, n_tiles*rt] in tile order —
    the pre-fold state of the pooled selection. The warm-path candidate
    repair persists these: a provider's tile contribution depends only
    on its own cost row over that tile, so a churn-masked recompute is
    per-(provider, tile) local, and the folded rev_t/rev_c are
    re-derived by replaying this exact fold (see
    parallel/sparse.py::repair_topk_bidir_sharded).
    """
    if weights is None:
        weights = CostWeights()
    T = er.cpu_cores.shape[0]
    if T % tile != 0:
        raise ValueError(f"T={T} not divisible by tile={tile}; pad requirements")
    n_tiles = T // tile
    P = ep.gpu_count.shape[0]
    k = min(k, int(P))
    r = min(reverse_r, T)
    rt = max(1, -(-r // n_tiles))  # per-tile contribution (ceil div)

    def step(carry, t0):
        rev_c0, rev_t0 = carry  # [P, r] running best (smallest) costs/tasks
        # forward: per-task top-k providers (the exact shared step —
        # jitter, offsets, approx_max_k — of candidates_topk)
        provider, cost_k, cost = _forward_tile_select(
            ep, er, weights, t0, tile, k,
            provider_offset, task_offset, approx_recall,
        )
        # reverse: this tile's per-provider top-rt, then a tiny merge
        tid = t0 + jnp.arange(tile, dtype=jnp.int32)
        if rt == 1:
            j = jnp.argmin(cost, axis=1)
            tile_c = jnp.take_along_axis(cost, j[:, None], axis=1)
            tile_t = tid[j][:, None]
        else:
            neg, j = lax.top_k(-cost, rt)
            tile_c = -neg
            tile_t = tid[j]
        merged_c = jnp.concatenate([rev_c0, tile_c], axis=1)  # [P, r+rt]
        merged_t = jnp.concatenate([rev_t0, tile_t], axis=1)
        neg_c, m = lax.top_k(-merged_c, r)
        rev_c1 = -neg_c
        rev_t1 = jnp.take_along_axis(merged_t, m, axis=1)
        ys = (provider, cost_k)
        if with_pools:
            ys = ys + (tile_t, tile_c)
        return (rev_c1, rev_t1), ys

    carry0 = (
        jnp.full((P, r), jnp.float32(INFEASIBLE)),
        jnp.full((P, r), -1, jnp.int32),
    )
    (rev_c, rev_t), ys = lax.scan(
        step, carry0, jnp.arange(n_tiles, dtype=jnp.int32) * tile
    )
    cand_p, cand_c = ys[0], ys[1]
    rev_t = jnp.where(rev_c < INFEASIBLE * 0.5, rev_t, -1)
    out = (cand_p.reshape(T, k), cand_c.reshape(T, k), rev_t, rev_c)
    if with_pools:
        # ys pools are [n_tiles, P, rt]: flatten to [P, n_tiles*rt] in
        # tile order — the layout the repair refold consumes
        pool_t = jnp.moveaxis(ys[2], 0, 1).reshape(P, n_tiles * rt)
        pool_c = jnp.moveaxis(ys[3], 0, 1).reshape(P, n_tiles * rt)
        out = out + (pool_t, pool_c)
    return out


@partial(jax.jit, static_argnames=("extra",))
def merge_reverse_candidates(
    cand_p: jax.Array,
    cand_c: jax.Array,
    rev_t: jax.Array,
    rev_c: jax.Array,
    extra: int = 16,
) -> tuple[jax.Array, jax.Array]:
    """Scatter reverse (provider -> task) edges into up to ``extra`` extra
    candidate columns per task: returns ([T, K+extra] provider ids, costs).

    Exact sort-based placement (no collision loss up to the per-task cap):
    edges sorted by (task, cost), ranked within task by a cummax trick, and
    ranks >= extra dropped — when a task is many providers' best hope, the
    cheapest ``extra`` of them are kept. Edges duplicating a forward
    candidate are dropped first: a duplicate column makes the winner's
    runner-up value equal its best (v1 == v2), collapsing every bid on that
    provider to the minimal +eps increment — measured as a slower, slightly
    WORSE matching than forward-only at 4k.
    """
    T = cand_p.shape[0]
    P, r = rev_t.shape
    t_flat = jnp.where(rev_t.reshape(-1) >= 0, rev_t.reshape(-1), T)
    p_flat = jnp.repeat(jnp.arange(P, dtype=jnp.int32), r)
    c_flat = rev_c.reshape(-1)
    dup = jnp.any(
        cand_p[jnp.minimum(t_flat, T - 1)] == p_flat[:, None], axis=1
    )
    t_flat = jnp.where(dup, T, t_flat)
    order = jnp.lexsort((c_flat, t_flat))
    t_s, p_s, c_s = t_flat[order], p_flat[order], c_flat[order]
    n = t_s.shape[0]
    pos = jnp.arange(n, dtype=jnp.int32)
    new_seg = jnp.concatenate(
        [jnp.ones(1, bool), t_s[1:] != t_s[:-1]]
    )
    run_start = lax.associative_scan(
        jnp.maximum, jnp.where(new_seg, pos, -1)
    )
    rank = pos - run_start
    keep = (t_s < T) & (rank < extra)
    ti = jnp.where(keep, t_s, T)
    ri = jnp.where(keep, rank, 0)
    extra_p = jnp.full((T + 1, extra), -1, jnp.int32).at[ti, ri].set(
        p_s, mode="drop"
    )[:T]
    extra_c = jnp.full((T + 1, extra), jnp.float32(INFEASIBLE)).at[ti, ri].set(
        c_s, mode="drop"
    )[:T]
    return (
        jnp.concatenate([cand_p, extra_p], axis=1),
        jnp.concatenate([cand_c, extra_c], axis=1),
    )


def pick_tile(n_tasks: int, cap: int = 1024) -> int:
    """Largest tile <= ``cap`` that divides ``n_tasks`` exactly — the
    task-tiling contract of :func:`candidates_topk_reverse` (the scan
    carries fixed-shape tiles, so T % tile must be 0). Callers pad task
    counts to pow2 buckets, where this returns min(cap, n_tasks); the
    divisor walk keeps odd counts (tests, unpadded replays) working
    instead of raising. One home for the loop that used to be duplicated
    per call site (trace replay, bench, the jax arena)."""
    if n_tasks <= 0:
        return 1
    tile = min(cap, n_tasks)
    while n_tasks % tile != 0:
        tile -= 1
    return tile


def candidates_topk_bidir(
    ep: EncodedProviders,
    er: EncodedRequirements,
    weights: CostWeights | None = None,
    k: int = 64,
    tile: int = 1024,
    reverse_r: int = 8,
    extra: int = 16,
    provider_offset: jax.Array | None = None,
    task_offset: int | jax.Array = 0,
    approx_recall: float | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Forward top-k + reverse top-r candidates, merged: the coverage-safe
    candidate generator for complete matchings (every provider guaranteed
    edges into the graph). Returns ([T, k+extra] provider ids, costs)."""
    cand_p, cand_c, rev_t, rev_c = candidates_topk_reverse(
        ep, er, weights, k=k, tile=tile, reverse_r=reverse_r,
        provider_offset=provider_offset, task_offset=task_offset,
        approx_recall=approx_recall,
    )
    return merge_reverse_candidates(cand_p, cand_c, rev_t, rev_c, extra=extra)


@partial(jax.jit, static_argnames=("num_providers", "max_iters", "frontier", "retire"))
def assign_auction_sparse(
    cand_provider: jax.Array,
    cand_cost: jax.Array,
    num_providers: int,
    eps: float | jax.Array = 0.01,
    max_iters: int = 10000,
    frontier: int = 4096,
    retire: bool = True,
) -> AssignResult:
    """Auction on the candidate graph, Gauss-Seidel style.

    The naive Jacobi round re-gathers prices for ALL tasks' candidates every
    iteration — a [T, K] dynamic gather that dominates wall-clock on TPU
    (~17 ms at 32k x 64; gathers can't be hoisted because prices change).
    Instead each round processes a fixed-size *frontier* of up to
    ``frontier`` unassigned tasks: total gather traffic scales with the
    number of bid events (~O(T) for marketplace costs), not rounds x T.
    Bertsekas auction is correct for any nonempty subset of unassigned
    bidders per round, so this changes which eps-optimal matching is found
    (tie outcomes), not feasibility or quality. Set ``frontier >= T`` to
    recover the dense-parity Jacobi schedule.

    ``retire=True`` stops tasks whose best achievable value has been bid
    below -(2*max_cost + 10): economically "not worth it", and the
    termination guard against infinite eviction cycles when demand exceeds
    the candidate graph's capacity.

    For contended problems prefer :func:`assign_auction_sparse_scaled`:
    with a single small eps, every over-demanded provider's price must climb
    to the give-up level in eps-sized steps (millions of bid events at 32k);
    eps-scaling covers the same price range geometrically.
    """
    state, _stall = _sparse_auction_phase(
        cand_provider, cand_cost, num_providers, None,
        eps=eps, max_iters=max_iters, frontier=frontier, retire=retire,
    )
    p4t = state[3]
    return AssignResult(p4t, _invert(p4t, num_providers))


@partial(
    jax.jit,
    static_argnames=("num_providers", "max_iters", "frontier", "retire", "stall_limit"),
)
def _sparse_auction_phase(
    cand_provider: jax.Array,
    cand_cost: jax.Array,
    num_providers: int,
    state: tuple | None,
    eps: float | jax.Array = 0.01,
    max_iters: int = 10000,
    frontier: int = 4096,
    retire: bool = True,
    stall_limit: int = 0,
):
    """One eps phase of the frontier auction; ``state`` carries
    (it, price, owner, p4t, retired) across phases for warm starts.

    ``stall_limit`` > 0 additionally ends the phase after that many
    consecutive rounds with NO NET assignment progress. Per-task
    retirement cannot stop an unfillable tail: the open "hole" wanders
    the graph through eviction chains, so no single neighborhood's prices
    ever reach give_up (measured: 4000/4000 rounds with one open task).
    A stalled phase is pure price circulation — the scaled ladder hands
    the leftovers to the next phase / greedy cleanup instead."""
    T, K = cand_cost.shape
    P = num_providers
    B = min(frontier, T)

    cand_valid = cand_provider >= 0
    value_base = jnp.where(cand_valid, -cand_cost, _NEG)  # [T, K]
    task_feasible = jnp.any(cand_valid, axis=1)
    cand_safe = jnp.where(cand_valid, cand_provider, 0)
    finite_max = jnp.max(jnp.where(cand_valid, cand_cost, 0.0))
    give_up = -(2.0 * finite_max + 10.0) if retire else _NEG

    def cond(loop):
        (it, price, owner, p4t, retired), best, stall = loop
        go = (it < max_iters) & jnp.any((p4t < 0) & task_feasible & ~retired)
        if stall_limit > 0:
            go &= stall < stall_limit
        return go

    def body(loop):
        state, best, stall = loop
        it, price, owner, p4t, retired = state
        open_mask = (p4t < 0) & task_feasible & ~retired  # [T]

        # ---- frontier selection: up to B open tasks (fill = T -> dropped)
        f_idx = jnp.flatnonzero(open_mask, size=B, fill_value=T).astype(jnp.int32)
        f_ok = f_idx < T
        p1, v1, v2 = frontier_bids(cand_safe, value_base, price, f_idx, f_ok, K)

        newly_retired = f_ok & (v1 < give_up)
        retired = retired.at[jnp.where(newly_retired, f_idx, T)].set(True, mode="drop")

        bidding = f_ok & ~newly_retired & (v1 > _NEG * 0.5)
        bid_amt = price[p1] + (v1 - v2) + eps  # [B]
        tgt = jnp.where(bidding, p1, P)

        win_bid = jnp.full(P, _NEG).at[tgt].max(
            jnp.where(bidding, bid_amt, _NEG), mode="drop"
        )
        # among max bidders per provider, lowest task index wins
        is_winner_bid = bidding & (bid_amt >= win_bid[p1])
        win_task = jnp.full(P, T, jnp.int32).at[tgt].min(
            jnp.where(is_winner_bid, f_idx, T), mode="drop"
        )
        got_bid = (win_bid > _NEG * 0.5) & (win_task < T)

        evict_t = jnp.where(got_bid & (owner >= 0), owner, T)
        p4t = p4t.at[evict_t].set(-1, mode="drop")
        p_idx = jnp.arange(P, dtype=jnp.int32)
        win_t_safe = jnp.where(got_bid, win_task, T)
        p4t = p4t.at[win_t_safe].set(jnp.where(got_bid, p_idx, -1), mode="drop")
        owner = jnp.where(got_bid, win_task, owner)
        price = jnp.where(got_bid, win_bid, price)
        n_now = jnp.sum(p4t >= 0)
        improved = n_now > best
        best = jnp.maximum(best, n_now)
        stall = jnp.where(improved, 0, stall + 1)
        return (it + 1, price, owner, p4t, retired), best, stall

    if state is None:
        state = (
            jnp.int32(0),
            jnp.zeros(P, jnp.float32),
            jnp.full(P, -1, jnp.int32),
            jnp.full(T, -1, jnp.int32),
            jnp.zeros(T, bool),
        )
    else:
        # reset the iteration counter for this phase
        state = (jnp.int32(0),) + tuple(state[1:])
    loop0 = (state, jnp.sum(state[3] >= 0), jnp.int32(0))
    out, _best, stall = lax.while_loop(cond, body, loop0)
    return out, stall


@jax.jit
def _unassign_unhappy(cand_provider, cand_cost, price, owner, p4t, eps_next):
    """eps-CS repair between phases: holders whose assignment violates the
    tighter eps re-enter the auction; happy holders stay seated (avoids both
    full-reset cost and the mass-retirement pathology of pumped prices).

    The comparison carries a float-dust tolerance: a winning bid lands a
    task EXACTLY at the eps-CS boundary (its new value is v2 - eps, and v2
    becomes the new v1), so after a converged phase roughly half the
    matching sits at deficit == eps up to float32 rounding — measured at
    65k: 33,264/65,524 pairs within 1e-4 of the boundary, none beyond
    eps + 1e-3. Without the tolerance a warm restart at the SAME eps
    evicts that entire boundary population (~32k seeds for 655 churned
    tasks) and re-solves from scratch."""
    cand_valid = cand_provider >= 0
    cand_safe = jnp.where(cand_valid, cand_provider, 0)
    value = jnp.where(cand_valid, -cand_cost - price[cand_safe], _NEG)  # [T,K]
    v1 = jnp.max(value, axis=1)
    held = p4t  # [T]
    vcur = jnp.max(
        jnp.where(cand_safe == jnp.maximum(held, 0)[:, None], value, _NEG), axis=1
    )
    finite_max = jnp.max(jnp.where(cand_valid, cand_cost, 0.0))
    tol = 1e-5 * (1.0 + finite_max + jnp.max(jnp.abs(price)))
    unhappy = (held >= 0) & (vcur < v1 - eps_next - tol)
    P = owner.shape[0]
    owner = owner.at[jnp.where(unhappy, held, P)].set(-1, mode="drop")
    p4t = jnp.where(unhappy, -1, p4t)
    return owner, p4t


@partial(jax.jit, static_argnames=("budget",))
def _greedy_cleanup_compacted(cand_provider, cand_cost, owner, p4t, budget: int):
    """Forward auctions never lower prices, so an unfillable tail can strand
    providers at pumped prices. Sweep the OPEN tasks greedily (cheapest free
    candidate each) — the reference matcher's semantics on the tail; no
    provider idles while a compatible task waits.

    The scan is inherently sequential, so it runs over a compacted index set
    of at most ``budget`` open tasks (static size), never all T — the caller
    skips it entirely when nothing is open."""
    T, K = cand_cost.shape
    free = owner < 0  # [P]
    cand_valid = cand_provider >= 0
    cand_safe = jnp.where(cand_valid, cand_provider, 0)

    open_idx = jnp.flatnonzero(p4t < 0, size=budget, fill_value=T).astype(jnp.int32)
    ok = open_idx < T
    safe_idx = jnp.where(ok, open_idx, 0)

    def step(free, inputs):
        t_ok, cp, cc, valid = inputs
        cost_row = jnp.where(valid & free[cp], cc, INFEASIBLE)
        j = jnp.argmin(cost_row)
        feasible = (cost_row[j] < INFEASIBLE * 0.5) & t_ok
        p = cp[j]
        free = free.at[p].set(jnp.where(feasible, False, free[p]))
        return free, jnp.where(feasible, p, -1)

    _, picks = lax.scan(
        step, free, (ok, cand_safe[safe_idx], cand_cost[safe_idx], cand_valid[safe_idx])
    )
    return p4t.at[jnp.where(ok & (picks >= 0), open_idx, T)].set(
        jnp.where(picks >= 0, picks, -1), mode="drop"
    )


def _greedy_cleanup(cand_provider, cand_cost, owner, p4t):
    """Host wrapper: one scalar readback decides whether cleanup is needed;
    the compaction budget is a pow-2 bucket of the open count."""
    n_open = int(jnp.sum(p4t < 0))
    if n_open == 0:
        return p4t
    budget = 1024
    while budget < n_open:
        budget *= 2
    budget = min(budget, int(cand_cost.shape[0]))
    return _greedy_cleanup_compacted(cand_provider, cand_cost, owner, p4t, budget)


def assign_auction_sparse_scaled(
    cand_provider: jax.Array,
    cand_cost: jax.Array,
    num_providers: int,
    # eps_start=0.5 is 2.1-2.5x faster at 16k-65k with equal aggregate
    # quality — but BREAKS small-instance price semantics: a lone
    # bidder's first bid pumps the winner's price by the full v1-v2 gap,
    # and without enough coarser rungs the eps-CS repair leaves the task
    # parked on the WRONG (pricier) provider
    # (tests/test_marketplace.py::TestPriceFlipsAssignment). The coarse
    # start buys repair rungs, not convergence speed. Callers solving
    # large statistical marketplaces MAY pass a finer start; the default
    # preserves the reference's cheapest-wins semantics.
    eps_start: float = 4.0,
    eps_end: float = 0.02,
    scale: float = 0.25,
    max_iters_per_phase: int = 4000,
    frontier: int = 4096,
    with_prices: bool = False,
    stall_limit: int = 64,
    stats_out: dict | None = None,
    frontier_ladder: bool = True,
    with_state: bool = False,
):
    """eps-scaling auction: geometric eps ladder with warm-started prices
    (Bertsekas' eps-scaling — total bid events O(n log(1/eps)) instead of
    O(price_range / eps)).

    Phase discipline (mirrors native/assign_engine.cpp):
      - retirement runs in EVERY phase as a circuit breaker, but non-final
        retirements are REVERSED between phases (un-retire + eps-CS
        repair), so only the final phase's retirement is binding. Without
        this, an unfillable tail cycles through eviction chains until
        max_iters in every coarse phase — measured 4000/4000 rounds with
        ONE open task (50 s/phase on CPU at T=8k) vs ~tens of rounds to
        retire it. A viable task retired early by coarse-eps overshoot is
        re-opened at the next (finer) phase and re-bid correctly.
      - a final greedy cleanup seats any stranded provider/task pairs.

    The BINDING phase's stall circuit breaker (``stall_limit * 8``
    no-net-progress rounds) truncates long eviction chains that reshuffle
    without changing the assigned count; quality on such tails then falls
    to the greedy cleanup. ``stall_limit=0`` opts out (run to
    ``max_iters_per_phase``); a stall-terminated solve is reported via
    ``stats_out["stall_exit"]`` and a log line so quality regressions at
    large T stay observable.

    ``with_prices=True`` additionally returns the final price vector [P] —
    the warm-start state for the NEXT solve (see
    :func:`assign_auction_sparse_warm`). ``with_state=True`` returns
    (result, prices, retired [T]) — the retirement mask is dual state too:
    forward auctions never lower prices, so a task priced out of its whole
    candidate list STAYS priced out until a cold re-ground, and a warm
    chain that does not carry the mask re-fights the unfillable tail's
    full stall budget on every solve (measured: 1792 vs 476 rounds at a
    tail-heavy 2048).
    """
    state = None
    eps = eps_start
    rounds_total = 0
    # frontier_ladder: adaptive per-phase frontier shrink (see
    # _phase_adaptive) — disable to pin the exact Jacobi schedule (the
    # sharded-parity tests compare against the fixed-frontier mesh kernel)
    phase_fn = _phase_adaptive if frontier_ladder else _sparse_auction_phase
    while True:
        final = eps <= eps_end
        state, stall = phase_fn(
            cand_provider, cand_cost, num_providers, state,
            eps=eps, max_iters=max_iters_per_phase, frontier=frontier,
            # the FINAL phase's retirement is binding and its eviction
            # chains (closing eps_end-sized price gaps) legitimately make
            # no net progress for long stretches — give it 8x the
            # circuit-breaker budget of the disposable coarse phases
            retire=True,
            stall_limit=stall_limit * (8 if final else 1),
        )
        if stats_out is not None:
            # per-phase round count; readback only when asked for — the
            # fixed-frontier path otherwise keeps async phase dispatch
            rounds_total += int(state[0])
        if final:
            _report_stall("scaled", stall, stall_limit * 8, stats_out)
            if stats_out is not None:
                # the platform-independent cost driver: wall = rounds x
                # per-round kernel cost. Exposed so frontier/eps tuning
                # has a measurable objective off-chip.
                stats_out["rounds_total"] = rounds_total
            break
        eps = max(eps * scale, eps_end)
        it, price, owner, p4t, retired = state
        owner, p4t = _unassign_unhappy(
            cand_provider, cand_cost, price, owner, p4t, eps
        )
        # un-retire: coarse-phase retirement was only the circuit breaker
        retired = jnp.zeros_like(retired)
        state = (it, price, owner, p4t, retired)

    _, price, owner, p4t, retired = state
    p4t = _greedy_cleanup(cand_provider, cand_cost, owner, p4t)
    res = AssignResult(p4t, _invert(p4t, num_providers))
    if with_state:
        # a retired task the greedy cleanup managed to seat is assigned,
        # not priced out — clear its flag in the carried state
        return res, price, retired & (p4t < 0)
    if with_prices:
        return res, price
    return res


def _phase_adaptive(
    cand_provider,
    cand_cost,
    num_providers: int,
    state,
    eps,
    max_iters: int,
    frontier: int,
    retire: bool,
    stall_limit: int,
):
    """One eps phase run in SEGMENTS with a shrinking frontier executable.

    Measured (16k, CPU): round count is nearly flat in the frontier size
    (4105 rounds at B=4096 vs 4731 at B=512) because most rounds are tail
    eviction chains with a SMALL open set — a large static frontier makes
    every round pay large gathers for parallelism that isn't there. wall
    7.9 s at B=512 vs 16.9 s at B=4096 on the same instance. Every
    segment boundary, B DIRECT-FITS to the live open set: the smallest
    pow2 (floor 512) covering it, monotone non-increasing; segments
    re-enter the SAME phase kernel with carried state, so auction
    semantics are unchanged — only the per-round batch shape adapts.

    The stall circuit breaker lives at segment granularity out here (a
    per-segment stall_limit static would re-trace the kernel every
    segment — measured to dwarf the frontier win): the kernel's trailing
    no-progress count accumulates across whole-segment stalls, so a trip
    can land up to one segment late — benign, the tail then falls to
    greedy cleanup exactly as a true stall would. Segments are a FIXED
    size for the same retrace reason; the phase budget is honored at
    segment granularity (up to seg_rounds-1 extra rounds past
    ``max_iters``, a budget-cap semantic, not a correctness one).
    """
    seg_rounds = 256
    T = cand_cost.shape[0]
    task_feasible = jnp.any(cand_provider >= 0, axis=1)
    iters_left = max_iters
    total_it = 0
    B = min(frontier, T)
    carried_stall = 0
    while iters_left > 0:
        state, stall = _sparse_auction_phase(
            cand_provider, cand_cost, num_providers, state,
            eps=eps, max_iters=seg_rounds, frontier=B, retire=retire,
            stall_limit=0,
        )
        it = int(state[0])
        total_it += it
        iters_left -= it
        s = int(stall)
        carried_stall = carried_stall + it if s >= it else s
        if it < seg_rounds:
            break  # converged or emptied
        if stall_limit > 0 and carried_stall >= stall_limit:
            break  # circuit breaker (segment-boundary granularity)
        # candidate-less tasks stay open forever: they must not pin the
        # frontier large (the kernel's own open_mask excludes them too)
        open_count = int(
            jnp.sum((state[3] < 0) & ~state[4] & task_feasible)
        )
        if open_count == 0:
            break
        fit = 512
        while fit < open_count and fit < B:
            fit *= 2
        B = min(B, fit)
    # report the PHASE's total rounds in the state's counter slot (each
    # segment resets it; the ladder's rounds_total sums these) and the
    # ACCUMULATED stall so _report_stall sees breaker trips (the last
    # segment alone can never reach a limit > seg_rounds)
    state = (jnp.int32(total_it),) + tuple(state[1:])
    return state, jnp.int32(carried_stall)


def _report_stall(kind: str, stall, limit: int, stats_out: dict | None) -> None:
    """Record (and log) a binding-phase stall termination. One scalar
    readback — negligible next to the solve it describes."""
    stalled = bool(limit > 0 and int(stall) >= limit)
    if stats_out is not None:
        stats_out["stall_exit"] = stalled
        stats_out["stall_rounds"] = int(stall)
    if stalled:
        import logging

        logging.getLogger(__name__).info(
            "sparse auction (%s) stall-terminated after %d no-progress "
            "rounds; tail quality falls to greedy cleanup (stall_limit=0 "
            "opts out)",
            kind,
            int(stall),
        )


def assign_auction_sparse_warm(
    cand_provider: jax.Array,
    cand_cost: jax.Array,
    num_providers: int,
    price0: jax.Array,
    p4t0: jax.Array,
    eps: float = 0.02,
    max_iters: int = 20000,
    frontier: int = 4096,
    stall_limit: int = 64,
    stats_out: dict | None = None,
    frontier_ladder: bool = True,
    retired0: jax.Array | None = None,
    with_state: bool = False,
) -> tuple[AssignResult, jax.Array]:
    """Incremental (delta-frontier) auction solve: SURVEY §7 hard part 4.

    The reference re-walks every task per heartbeat
    (crates/orchestrator/src/scheduler/mod.rs:26-74); a cold batch re-solve
    every population change would waste the batched win the same way. This
    warm start carries the auction's dual state across solves:

      ``price0`` [P]  final prices of the previous solve (new providers: 0).
      ``p4t0``  [T]   previous assignment re-expressed in the new index
                      space (-1 for new/changed tasks). Must be injective
                      over >= 0.

    Seeded pairs violating eps-complementary-slackness under ``price0`` —
    including any whose seeded provider is no longer a candidate — are
    evicted by the same repair used between eps-scaling phases, so only the
    *delta frontier* (new tasks, freed providers, changed costs) re-enters
    the bidding. Forward auction from arbitrary initial prices and a
    partial eps-CS assignment terminates eps-optimal (Bertsekas), so the
    warm path's solution quality matches the cold path's final phase.

    ``retired0`` [T] carries the previous solve's retirement mask (third
    element of a ``with_state=True`` return). Retirement is a statement
    about PRICES ("best value below give-up"), and forward auctions never
    lower prices, so it stays valid across warm solves: without the mask
    every warm solve re-bids the unfillable tail until the stall breaker
    trips (512 wasted rounds per solve in a chain). Rows whose costs or
    candidates changed must be cleared by the caller (the CandidateCache
    rebuild does this wholesale). Retired-but-now-seatable pairs are still
    caught by the greedy cleanup, which ignores the mask.

    Returns (AssignResult, final prices [P]), plus the final retirement
    mask [T] when ``with_state=True``.
    """
    # a seed for a task with NO candidates would sail through the eps-CS
    # repair (vcur == v1 == -inf is not "unhappy") and emerge as an
    # infeasible pair in the final matching — drop such seeds outright
    task_has_cand = jnp.any(cand_provider >= 0, axis=1)
    p4t0 = jnp.where(task_has_cand, p4t0, -1)
    # Forward auctions only raise prices, and carried prices compound
    # across warm solves. The retirement floor is give_up =
    # -(2*max_cost + 10); keep the worst seeded value -max_cost - price
    # ABOVE the floor by SHIFTING all prices down uniformly until
    # max(price) <= max_cost + 5. A constant shift changes no value
    # difference, so it preserves the entire price landscape (who
    # outbids whom, who is unhappy) — unlike a clamp, which flattens the
    # top of the distribution, i.e. exactly the contended providers:
    # measured at 65k, min-clamping capped 65,535/65,536 prices and the
    # eps-CS repair then evicted 59,997 seeds for 655 churned tasks,
    # making "warm" a from-scratch fine-eps solve (the r4 0.2x
    # regression). Negative prices are fine: the auction only ever
    # compares price DIFFERENCES (values -cost - price and bid
    # increments), never absolute levels.
    finite_max = jnp.max(jnp.where(cand_provider >= 0, cand_cost, 0.0))
    price0 = jnp.asarray(price0, jnp.float32)
    shift = jnp.maximum(jnp.max(price0) - (finite_max + 5.0), 0.0)
    price0 = price0 - shift
    owner0 = _invert(p4t0, num_providers)
    owner0, p4t0 = _unassign_unhappy(
        cand_provider, cand_cost, price0, owner0, p4t0, eps
    )
    if retired0 is None:
        retired_seed = jnp.zeros(cand_cost.shape[0], bool)
    else:
        # a seeded assignment outranks a stale retirement flag
        retired_seed = jnp.asarray(retired0, bool) & (p4t0 < 0)
    state = (
        jnp.int32(0),
        jnp.asarray(price0, jnp.float32),
        owner0,
        p4t0,
        retired_seed,
    )
    phase_fn = _phase_adaptive if frontier_ladder else _sparse_auction_phase
    state, stall = phase_fn(
        cand_provider, cand_cost, num_providers, state,
        eps=eps, max_iters=max_iters, frontier=frontier, retire=True,
        # the warm solve is a binding final phase: same 8x stall budget as
        # the scaled ladder's last phase (see assign_auction_sparse_scaled);
        # stall_limit=0 opts out (run to max_iters)
        stall_limit=stall_limit * 8,
    )
    _report_stall("warm", stall, stall_limit * 8, stats_out)
    if stats_out is not None:
        # same cost driver the cold ladder exposes: wall = rounds x
        # per-round kernel cost (see assign_auction_sparse_scaled)
        stats_out["rounds_total"] = int(state[0])
    _, price, owner, p4t, retired = state
    p4t = _greedy_cleanup(cand_provider, cand_cost, owner, p4t)
    res = AssignResult(p4t, _invert(p4t, num_providers))
    if with_state:
        return res, price, retired & (p4t < 0)
    return res, price


def sinkhorn_potentials_sparse_np(
    cand_provider,
    cand_cost,
    num_providers: int,
    eps: float = 0.05,
    max_iters: int = 100,
    tol: float = 1e-3,
    f0=None,
    g0=None,
):
    """Pure-NumPy reference for the native sparse Sinkhorn engine
    (``native.sinkhorn_sparse_mt``): log-domain entropic OT restricted to
    the top-K candidate edges, one eps phase.

    This is the parity oracle, not a production path — it mirrors the C++
    engine's numerics exactly: balanced uniform marginals over rows/columns
    with >= 1 feasible edge (the ops/blocked.py convention), f (provider)
    update then g (task) update per iteration, float64 accumulation with
    potentials rounded to float32 after each update, edge sums accumulated
    in ascending-edge order (np.bincount's input order == the engine's CSR
    fill order), and the same provider-marginal convergence gate. Any
    remaining difference is libm exp/log ulps, bounded well under the 1e-6
    parity the tests assert.

    Returns (f [P] f32, g [T] f32, iterations_run, final_marginal_err).
    """
    import numpy as np

    cand_p = np.asarray(cand_provider, np.int32)
    cand_c = np.asarray(cand_cost, np.float32)
    T, K = cand_p.shape
    P = int(num_providers)
    valid = (cand_p >= 0) & (cand_p < P) & (cand_c < INFEASIBLE * 0.5)
    vflat = valid.ravel()
    t_idx = np.repeat(np.arange(T, dtype=np.int64), K)[vflat]
    p_idx = cand_p.ravel().astype(np.int64)[vflat]
    c = cand_c.ravel().astype(np.float64)[vflat]
    col_any = valid.any(axis=1)
    row_any = np.zeros(P, bool)
    row_any[p_idx] = True
    f = (
        np.zeros(P, np.float32)
        if f0 is None
        else np.array(f0, np.float32, copy=True)
    )
    g = (
        np.zeros(T, np.float32)
        if g0 is None
        else np.array(g0, np.float32, copy=True)
    )
    np_valid = int(row_any.sum())
    nt_valid = int(col_any.sum())
    if np_valid == 0 or nt_valid == 0:
        return f, g, 0, 0.0
    import math

    m = float(min(np_valid, nt_valid))
    log_a = math.log(m / np_valid)
    log_b = math.log(m / nt_valid)
    a_mass = m / np_valid
    inv_eps = 1.0 / float(eps)
    deps = float(eps)

    it = 0
    err = 0.0
    prev_err = float("inf")
    stall = 0
    while it < max_iters:
        it += 1
        # ---- f (provider) update: segmented logsumexp over edges by p
        val = (g.astype(np.float64)[t_idx] - c) * inv_eps
        mx = np.full(P, -np.inf)
        np.maximum.at(mx, p_idx, val)
        s = np.bincount(
            p_idx, weights=np.exp(val - mx[p_idx]), minlength=P
        )
        with np.errstate(divide="ignore", invalid="ignore"):
            lse = mx + np.log(s)
        f = np.where(
            row_any, (deps * (log_a - lse)), f.astype(np.float64)
        ).astype(np.float32)
        # ---- g (task) update: segmented logsumexp over edges by t
        val = (f.astype(np.float64)[p_idx] - c) * inv_eps
        mt = np.full(T, -np.inf)
        np.maximum.at(mt, t_idx, val)
        st = np.bincount(
            t_idx, weights=np.exp(val - mt[t_idx]), minlength=T
        )
        with np.errstate(divide="ignore", invalid="ignore"):
            lse_t = mt + np.log(st)
        g = np.where(
            col_any, (deps * (log_b - lse_t)), g.astype(np.float64)
        ).astype(np.float32)
        # ---- provider-marginal drift (task marginals exact after g)
        mass = np.bincount(
            p_idx,
            weights=np.exp(
                (f.astype(np.float64)[p_idx] + g.astype(np.float64)[t_idx] - c)
                * inv_eps
            ),
            minlength=P,
        )
        err = float(
            np.max(np.abs(mass[row_any] - a_mass) / a_mass)
        )
        if err <= tol:
            break
        # stagnation exit, mirroring the engine: infeasible uniform
        # marginals on a sparse support plateau above tol while the
        # potentials drift — two consecutive <0.5%-improvement checks
        # (after an 8-iteration settling window) stop the burn
        if it >= 8 and err >= 0.995 * prev_err:
            stall += 1
            if stall >= 2:
                break
        else:
            stall = 0
        prev_err = err
    return f, g, it, err


def assign_topk(
    ep: EncodedProviders,
    er: EncodedRequirements,
    weights: CostWeights | None = None,
    k: int = 64,
    tile: int = 1024,
    eps: float = 0.01,
    max_iters: int = 1000,
) -> AssignResult:
    """Full sparse pipeline: streaming candidate generation + sparse auction."""
    cand_p, cand_c = candidates_topk(ep, er, weights, k=k, tile=tile)
    return assign_auction_sparse(
        cand_p, cand_c, num_providers=ep.num, eps=eps, max_iters=max_iters
    )
