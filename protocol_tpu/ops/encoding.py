"""Numeric encoding of the capability algebra for TPU kernels.

The reference evaluates ``ComputeSpecs::meets()`` (crates/shared/src/models/
node.rs:377-527) one node at a time on the CPU, with stringly-typed GPU-model
fuzzy matching in the inner loop. That shape cannot batch. Here the split is:

- **Host side** (this module's ``FeatureEncoder``): intern GPU model strings
  into a vocabulary of class ids once per distinct string; resolve each
  requirement's fuzzy model CSV against the vocabulary into a *bitmask over
  classes*. All string work happens exactly once per distinct string, not per
  (provider, task) pair.
- **Device side** (``compat_mask``): pure int32 comparisons over fixed-width
  arrays — `[P]` provider features vs `[T, K]` requirement options (K padded
  GPU OR-alternatives) — producing the `[P, T]` compatibility mask in one
  fused XLA computation. Absent fields use a ``-1`` sentinel; "no constraint"
  passes, "constraint on an absent spec" fails, matching the reference's
  Option semantics exactly (parity-tested against the Python ``meets()``).

Static shapes everywhere: K (max GPU alternatives) and W (model-bitmask words)
are fixed at encode time, so jit caches one executable per (P, T, K, W)
bucket.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from protocol_tpu.models.node import (
    ComputeRequirements,
    ComputeSpecs,
    NodeLocation,
    _models_fuzzy_match,
)

# Number of padded GPU OR-alternatives per requirement set. The reference DSL
# rarely exceeds 2-3 alternatives; overflowing options raise at encode time.
DEFAULT_MAX_GPU_OPTIONS = 4


@jax.tree_util.register_dataclass
@dataclass
class EncodedProviders:
    """Fixed-width provider features, shape [P] each. -1 = absent."""

    gpu_count: jax.Array  # i32
    gpu_mem_mb: jax.Array  # i32, per card
    gpu_model_id: jax.Array  # i32, index into the model vocabulary; -1 = none
    has_gpu: jax.Array  # bool
    has_cpu: jax.Array  # bool, node reports a CPU spec at all
    cpu_cores: jax.Array  # i32
    ram_mb: jax.Array  # i32
    storage_gb: jax.Array  # i32
    lat: jax.Array  # f32, radians
    lon: jax.Array  # f32, radians
    has_location: jax.Array  # bool (an explicit flag: (0,0) is a valid coord)
    price: jax.Array  # f32, arbitrary cost units
    load: jax.Array  # f32, 0..1 current utilization
    valid: jax.Array  # bool, padding rows are False

    @property
    def num(self) -> int:
        return int(self.gpu_count.shape[0])


@jax.tree_util.register_dataclass
@dataclass
class EncodedRequirements:
    """Fixed-width requirement features. Scalars are [T]; GPU OR-options are
    [T, K]; the model-class bitmask is [T, K, W] uint32."""

    cpu_required: jax.Array  # bool [T], requirement carries a CPU block at all
    cpu_cores: jax.Array  # i32 [T], -1 = unconstrained
    ram_mb: jax.Array  # i32 [T]
    storage_gb: jax.Array  # i32 [T]
    gpu_opt_valid: jax.Array  # bool [T, K]
    gpu_count: jax.Array  # i32 [T, K], -1 = unconstrained
    gpu_mem_min: jax.Array  # i32 [T, K]  (covers memory_mb and memory_mb_min)
    gpu_mem_max: jax.Array  # i32 [T, K]
    gpu_total_mem_min: jax.Array  # i32 [T, K]
    gpu_total_mem_max: jax.Array  # i32 [T, K]
    gpu_model_mask: jax.Array  # u32 [T, K, W]; all-ones = unconstrained
    gpu_model_constrained: jax.Array  # bool [T, K]
    lat: jax.Array  # f32 [T], radians (task origin; 0 if none)
    lon: jax.Array  # f32 [T]
    has_location: jax.Array  # bool [T]
    priority: jax.Array  # f32 [T] (newest-first ordering weight)
    valid: jax.Array  # bool [T], padding rows are False

    @property
    def num(self) -> int:
        return int(self.cpu_cores.shape[0])

    @property
    def max_gpu_options(self) -> int:
        return int(self.gpu_opt_valid.shape[1])


class FeatureEncoder:
    """Host-side interning + batch encoding.

    The encoder owns the GPU-model vocabulary. It is incremental: new model
    strings get fresh class ids, and requirement bitmasks are resolved against
    the vocabulary *at encode time* (so encode requirements after the
    providers they will be matched with, or re-encode on vocab growth —
    ``vocab_version`` tracks this).
    """

    def __init__(self, model_words: int = 8, max_gpu_options: int = DEFAULT_MAX_GPU_OPTIONS):
        # W words of 32 bits each => capacity model_words*32 distinct models
        self._vocab: dict[str, int] = {}
        self._vocab_list: list[str] = []
        self.model_words = model_words
        self.max_gpu_options = max_gpu_options
        self.vocab_version = 0

    # ---------------- vocabulary ----------------

    def intern_model(self, model: Optional[str]) -> int:
        if model is None:
            return -1
        key = model.strip()
        mid = self._vocab.get(key)
        if mid is None:
            mid = len(self._vocab_list)
            if mid >= self.model_words * 32:
                raise ValueError(
                    f"GPU model vocabulary overflow (> {self.model_words * 32}); "
                    "construct the FeatureEncoder with more model_words"
                )
            self._vocab[key] = mid
            self._vocab_list.append(key)
            self.vocab_version += 1
        return mid

    def _model_csv_to_mask(self, csv: Optional[str]) -> tuple[np.ndarray, bool]:
        """Resolve a requirement's model CSV into a bitmask over vocab classes
        using the reference's fuzzy-match rule. Returns (mask[W] u32,
        constrained)."""
        mask = np.zeros(self.model_words, dtype=np.uint32)
        if csv is None:
            return mask, False
        for mid, spec_model in enumerate(self._vocab_list):
            if _models_fuzzy_match(spec_model, csv):
                mask[mid >> 5] |= np.uint32(1) << np.uint32(mid & 31)
        return mask, True

    # ---------------- providers ----------------

    def encode_providers(
        self,
        specs: Sequence[Optional[ComputeSpecs]],
        locations: Optional[Sequence[Optional[NodeLocation]]] = None,
        prices: Optional[Sequence[float]] = None,
        loads: Optional[Sequence[float]] = None,
        pad_to: Optional[int] = None,
    ) -> EncodedProviders:
        n = len(specs)
        p = pad_to if pad_to is not None else n
        if p < n:
            raise ValueError("pad_to smaller than provider count")

        gpu_count = np.full(p, -1, np.int32)
        gpu_mem = np.full(p, -1, np.int32)
        gpu_model = np.full(p, -1, np.int32)
        has_gpu = np.zeros(p, bool)
        has_cpu = np.zeros(p, bool)
        cpu_cores = np.full(p, -1, np.int32)
        ram = np.full(p, -1, np.int32)
        storage = np.full(p, -1, np.int32)
        lat = np.zeros(p, np.float32)
        lon = np.zeros(p, np.float32)
        has_loc = np.zeros(p, bool)
        price = np.zeros(p, np.float32)
        load = np.zeros(p, np.float32)
        valid = np.zeros(p, bool)

        for i, s in enumerate(specs):
            valid[i] = True
            if s is None:
                continue
            if s.gpu is not None:
                has_gpu[i] = True
                if s.gpu.count is not None:
                    gpu_count[i] = s.gpu.count
                if s.gpu.memory_mb is not None:
                    gpu_mem[i] = s.gpu.memory_mb
                gpu_model[i] = self.intern_model(s.gpu.model)
            if s.cpu is not None:
                has_cpu[i] = True
                if s.cpu.cores is not None:
                    cpu_cores[i] = s.cpu.cores
            if s.ram_mb is not None:
                ram[i] = s.ram_mb
            if s.storage_gb is not None:
                storage[i] = s.storage_gb
        if locations is not None:
            for i, lc in enumerate(locations):
                if lc is not None:
                    lat[i] = np.radians(lc.latitude)
                    lon[i] = np.radians(lc.longitude)
                    has_loc[i] = True
        if prices is not None:
            price[: len(prices)] = np.asarray(prices, np.float32)
        if loads is not None:
            load[: len(loads)] = np.asarray(loads, np.float32)

        return EncodedProviders(
            gpu_count=jnp.asarray(gpu_count),
            gpu_mem_mb=jnp.asarray(gpu_mem),
            gpu_model_id=jnp.asarray(gpu_model),
            has_gpu=jnp.asarray(has_gpu),
            has_cpu=jnp.asarray(has_cpu),
            cpu_cores=jnp.asarray(cpu_cores),
            ram_mb=jnp.asarray(ram),
            storage_gb=jnp.asarray(storage),
            lat=jnp.asarray(lat),
            lon=jnp.asarray(lon),
            has_location=jnp.asarray(has_loc),
            price=jnp.asarray(price),
            load=jnp.asarray(load),
            valid=jnp.asarray(valid),
        )

    # ---------------- requirements ----------------

    def encode_requirements(
        self,
        reqs: Sequence[ComputeRequirements],
        locations: Optional[Sequence[Optional[NodeLocation]]] = None,
        priorities: Optional[Sequence[float]] = None,
        pad_to: Optional[int] = None,
    ) -> EncodedRequirements:
        n = len(reqs)
        t = pad_to if pad_to is not None else n
        if t < n:
            raise ValueError("pad_to smaller than requirement count")
        k, w = self.max_gpu_options, self.model_words

        cpu_required = np.zeros(t, bool)
        cpu_cores = np.full(t, -1, np.int32)
        ram = np.full(t, -1, np.int32)
        storage = np.full(t, -1, np.int32)
        opt_valid = np.zeros((t, k), bool)
        gcount = np.full((t, k), -1, np.int32)
        gmem_min = np.full((t, k), -1, np.int32)
        gmem_max = np.full((t, k), -1, np.int32)
        gtot_min = np.full((t, k), -1, np.int32)
        gtot_max = np.full((t, k), -1, np.int32)
        gmask = np.zeros((t, k, w), np.uint32)
        gconstrained = np.zeros((t, k), bool)
        lat = np.zeros(t, np.float32)
        lon = np.zeros(t, np.float32)
        has_loc = np.zeros(t, bool)
        prio = np.zeros(t, np.float32)
        valid = np.zeros(t, bool)

        for i, r in enumerate(reqs):
            valid[i] = True
            if r.cpu is not None:
                cpu_required[i] = True
                if r.cpu.cores is not None:
                    cpu_cores[i] = r.cpu.cores
            if r.ram_mb is not None:
                ram[i] = r.ram_mb
            if r.storage_gb is not None:
                storage[i] = r.storage_gb
            if len(r.gpu) > k:
                raise ValueError(
                    f"requirement has {len(r.gpu)} GPU alternatives > max {k}"
                )
            for j, g in enumerate(r.gpu):
                opt_valid[i, j] = True
                if g.count is not None:
                    gcount[i, j] = g.count
                # memory_mb is itself a lower bound (node.rs:480-500); when a
                # dict-deserialized requirement carries both (the DSL parser
                # rejects the combination but the wire path does not), the
                # effective bound is the stricter of the two.
                bounds = [b for b in (g.memory_mb, g.memory_mb_min) if b is not None]
                if bounds:
                    gmem_min[i, j] = max(bounds)
                if g.memory_mb_max is not None:
                    gmem_max[i, j] = g.memory_mb_max
                if g.total_memory_min is not None:
                    gtot_min[i, j] = g.total_memory_min
                if g.total_memory_max is not None:
                    gtot_max[i, j] = g.total_memory_max
                gmask[i, j], gconstrained[i, j] = self._model_csv_to_mask(g.model)
        if locations is not None:
            for i, lc in enumerate(locations):
                if lc is not None:
                    lat[i] = np.radians(lc.latitude)
                    lon[i] = np.radians(lc.longitude)
                    has_loc[i] = True
        if priorities is not None:
            prio[: len(priorities)] = np.asarray(priorities, np.float32)

        return EncodedRequirements(
            cpu_required=jnp.asarray(cpu_required),
            cpu_cores=jnp.asarray(cpu_cores),
            ram_mb=jnp.asarray(ram),
            storage_gb=jnp.asarray(storage),
            gpu_opt_valid=jnp.asarray(opt_valid),
            gpu_count=jnp.asarray(gcount),
            gpu_mem_min=jnp.asarray(gmem_min),
            gpu_mem_max=jnp.asarray(gmem_max),
            gpu_total_mem_min=jnp.asarray(gtot_min),
            gpu_total_mem_max=jnp.asarray(gtot_max),
            gpu_model_mask=jnp.asarray(gmask),
            gpu_model_constrained=jnp.asarray(gconstrained),
            lat=jnp.asarray(lat),
            lon=jnp.asarray(lon),
            has_location=jnp.asarray(has_loc),
            priority=jnp.asarray(prio),
            valid=jnp.asarray(valid),
        )


def _ge_min(spec: jax.Array, req: jax.Array) -> jax.Array:
    """'spec >= req' with Option semantics: no constraint passes; constraint
    on an absent spec fails (node.rs `is_none_or(|s| s < req)`)."""
    return (req < 0) | ((spec >= 0) & (spec >= req))


def _le_max(spec: jax.Array, req: jax.Array) -> jax.Array:
    return (req < 0) | ((spec >= 0) & (spec <= req))


def compat_mask(p: EncodedProviders, r: EncodedRequirements) -> jax.Array:
    """Vectorized ``ComputeSpecs.meets()``: bool [P, T].

    Pure elementwise int32 logic — XLA fuses this into a handful of VPU ops;
    no gathers except the [W]-word model-bitmask lookup, which is indexed by
    provider only.
    """
    P = p.gpu_count.shape[0]
    T = r.cpu_cores.shape[0]

    # ----- scalar AND constraints: [P, 1] vs [1, T] -> [P, T]
    # A requirement carrying any CPU block (even without a cores bound)
    # demands the node report a CPU spec (node.rs:379-390).
    ok = ~r.cpu_required[None, :] | (
        p.has_cpu[:, None] & _ge_min(p.cpu_cores[:, None], r.cpu_cores[None, :])
    )
    ok &= _ge_min(p.ram_mb[:, None], r.ram_mb[None, :])
    ok &= _ge_min(p.storage_gb[:, None], r.storage_gb[None, :])

    # ----- GPU OR alternatives: broadcast [P,1,1] vs [1,T,K] -> [P,T,K]
    pc = p.gpu_count[:, None, None]
    pm = p.gpu_mem_mb[:, None, None]
    rc = r.gpu_count[None, :, :]

    # exact count: None spec passes only req_count==0 (node.rs:445-459)
    count_ok = (rc < 0) | jnp.where(pc < 0, rc == 0, pc == rc)
    mem_ok = _ge_min(pm, r.gpu_mem_min[None, :, :]) & _le_max(pm, r.gpu_mem_max[None, :, :])

    # total memory binds only when the provider reports count AND memory
    total = pc * pm
    have_total = (pc >= 0) & (pm >= 0)
    tot_ok = (
        ((r.gpu_total_mem_min[None, :, :] < 0) | ~have_total | (total >= r.gpu_total_mem_min[None, :, :]))
        & ((r.gpu_total_mem_max[None, :, :] < 0) | ~have_total | (total <= r.gpu_total_mem_max[None, :, :]))
    )

    # model bitmask: provider class id -> (word, bit); gather the word column
    word = jnp.maximum(p.gpu_model_id, 0) >> 5  # [P]
    bit = jnp.maximum(p.gpu_model_id, 0) & 31  # [P]
    # r.gpu_model_mask: [T, K, W] -> select per-provider word -> [P, T, K]
    words = jnp.take(r.gpu_model_mask, word, axis=2)  # [T, K, P]
    words = jnp.moveaxis(words, 2, 0)  # [P, T, K]
    model_hit = ((words >> bit[:, None, None].astype(jnp.uint32)) & 1).astype(bool)
    has_model = (p.gpu_model_id >= 0)[:, None, None]
    model_ok = ~r.gpu_model_constrained[None, :, :] | (has_model & model_hit)

    opt_ok = count_ok & mem_ok & tot_ok & model_ok
    opt_ok &= r.gpu_opt_valid[None, :, :]

    any_opt = jnp.any(r.gpu_opt_valid, axis=1)  # [T] requirement has GPU options
    gpu_ok = jnp.where(
        any_opt[None, :],
        p.has_gpu[:, None] & jnp.any(opt_ok, axis=2),
        True,
    )
    ok &= gpu_ok
    ok &= p.valid[:, None] & r.valid[None, :]
    return ok
