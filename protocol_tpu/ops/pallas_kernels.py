"""Pallas TPU kernel: fused compat + cost + running-top-K candidates.

The XLA path (ops/sparse.candidates_topk) materializes each [P, tile] cost
block in HBM and then runs lax.top_k over it. This kernel fuses the whole
candidate pipeline in VMEM: a grid over provider blocks computes the cost
block (capability mask -> cost terms -> tie-breaking jitter) and folds it
into a running per-task top-K held in scratch — the [P, tile] tensor never
exists outside VMEM, cutting the HBM traffic of candidate generation from
O(P*T) writes+reads to O(P*T) reads of the packed features only.

Feature packing (host side, ops/encoding-compatible; the kernel's
feasibility mask depends on the `valid` slots — an alternative packer must
fill them):
  pi  i32 [P, 8]  gpu_count, gpu_mem_mb, gpu_model_id, has_gpu, has_cpu,
                  cpu_cores, ram_mb, storage_gb         (-1 = absent)
  pf  f32 [P, 8]  lat, lon, has_loc, price, load, VALID(0/1), 0, 0
  ri  i32 [T, 8]  cpu_required, cpu_cores, ram_mb, storage_gb,
                  gpu_required(any option), VALID(0/1), 0, 0
  ro  i32 [T, K*8] per GPU OR-option: valid, count, mem_min, mem_max,
                  tot_min, tot_max, model_constrained, 0
  rm  u32 [T, K*W] model-class bitmask words
  rf  f32 [T, 8]  lat, lon, has_loc, priority, 0, 0, 0, 0

The kernel reproduces ops/encoding.compat_mask + ops/cost.cost_matrix +
the hash jitter bit-for-bit (parity-tested in interpret mode against the
XLA path); integration stays behind `use_pallas=` flags until profiled on
real hardware.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from protocol_tpu.ops.cost import EARTH_RADIUS_KM, INFEASIBLE, CostWeights
from protocol_tpu.ops.encoding import EncodedProviders, EncodedRequirements

_NEG = -1e18


# ---------------------------------------------------------------- packing


def pack_features(
    ep: EncodedProviders, er: EncodedRequirements
) -> tuple[jax.Array, ...]:
    """Host-side packing of the encoding dataclasses into the kernel's
    fixed-width matrices."""
    P = ep.gpu_count.shape[0]
    T, K = er.gpu_opt_valid.shape
    W = er.gpu_model_mask.shape[-1]

    pi = jnp.stack(
        [
            jnp.asarray(ep.gpu_count, jnp.int32),
            jnp.asarray(ep.gpu_mem_mb, jnp.int32),
            jnp.asarray(ep.gpu_model_id, jnp.int32),
            jnp.asarray(ep.has_gpu, jnp.int32),
            jnp.asarray(ep.has_cpu, jnp.int32),
            jnp.asarray(ep.cpu_cores, jnp.int32),
            jnp.asarray(ep.ram_mb, jnp.int32),
            jnp.asarray(ep.storage_gb, jnp.int32),
        ],
        axis=1,
    )
    pf = jnp.stack(
        [
            jnp.asarray(ep.lat, jnp.float32),
            jnp.asarray(ep.lon, jnp.float32),
            jnp.asarray(ep.has_location, jnp.float32),
            jnp.asarray(ep.price, jnp.float32),
            jnp.asarray(ep.load, jnp.float32),
            jnp.asarray(ep.valid, jnp.float32),
            jnp.zeros(P, jnp.float32),
            jnp.zeros(P, jnp.float32),
        ],
        axis=1,
    )
    ri = jnp.stack(
        [
            jnp.asarray(er.cpu_required, jnp.int32),
            jnp.asarray(er.cpu_cores, jnp.int32),
            jnp.asarray(er.ram_mb, jnp.int32),
            jnp.asarray(er.storage_gb, jnp.int32),
            jnp.any(jnp.asarray(er.gpu_opt_valid), axis=1).astype(jnp.int32),
            jnp.asarray(er.valid, jnp.int32),
            jnp.zeros(T, jnp.int32),
            jnp.zeros(T, jnp.int32),
        ],
        axis=1,
    )
    ro = jnp.concatenate(
        [
            jnp.stack(
                [
                    jnp.asarray(er.gpu_opt_valid[:, k], jnp.int32),
                    jnp.asarray(er.gpu_count[:, k], jnp.int32),
                    jnp.asarray(er.gpu_mem_min[:, k], jnp.int32),
                    jnp.asarray(er.gpu_mem_max[:, k], jnp.int32),
                    jnp.asarray(er.gpu_total_mem_min[:, k], jnp.int32),
                    jnp.asarray(er.gpu_total_mem_max[:, k], jnp.int32),
                    jnp.asarray(er.gpu_model_constrained[:, k], jnp.int32),
                    jnp.zeros(T, jnp.int32),
                ],
                axis=1,
            )
            for k in range(K)
        ],
        axis=1,
    )
    rm = jnp.asarray(er.gpu_model_mask, jnp.uint32).reshape(T, K * W)
    rf = jnp.stack(
        [
            jnp.asarray(er.lat, jnp.float32),
            jnp.asarray(er.lon, jnp.float32),
            jnp.asarray(er.has_location, jnp.float32),
            jnp.asarray(er.priority, jnp.float32),
            jnp.zeros(T, jnp.float32),
            jnp.zeros(T, jnp.float32),
            jnp.zeros(T, jnp.float32),
            jnp.zeros(T, jnp.float32),
        ],
        axis=1,
    )
    return pi, pf, ri, ro, rm, rf


# ---------------------------------------------------------------- kernel


def _cost_block(pi, pf, ri, ro, rm, rf, weights, p0, K, W):
    """[PB, TB] cost block from packed features (pure jnp; runs inside the
    kernel body on VMEM-resident blocks)."""
    PB = pi.shape[0]
    TB = ri.shape[0]

    def col_i(mat, j):
        return mat[:, j]

    # provider columns
    p_count = col_i(pi, 0)[:, None]
    p_mem = col_i(pi, 1)[:, None]
    p_model = col_i(pi, 2)[:, None]
    p_hasgpu = col_i(pi, 3)[:, None]
    p_hascpu = col_i(pi, 4)[:, None]
    p_cores = col_i(pi, 5)[:, None]
    p_ram = col_i(pi, 6)[:, None]
    p_stor = col_i(pi, 7)[:, None]

    r_cpureq = col_i(ri, 0)[None, :]
    r_cores = col_i(ri, 1)[None, :]
    r_ram = col_i(ri, 2)[None, :]
    r_stor = col_i(ri, 3)[None, :]
    r_anygpu = col_i(ri, 4)[None, :]
    r_valid = col_i(ri, 5)[None, :]

    def ge_min(spec, req):
        return (req < 0) | ((spec >= 0) & (spec >= req))

    ok = (r_cpureq == 0) | ((p_hascpu > 0) & ge_min(p_cores, r_cores))
    ok &= ge_min(p_ram, r_ram)
    ok &= ge_min(p_stor, r_stor)

    any_opt_ok = jnp.zeros((PB, TB), bool)
    word = jnp.maximum(p_model, 0) >> 5
    bit = (jnp.maximum(p_model, 0) & 31).astype(jnp.uint32)
    for k in range(K):
        o = ro[:, k * 8 : (k + 1) * 8]
        o_valid = o[:, 0][None, :]
        o_count = o[:, 1][None, :]
        o_mmin = o[:, 2][None, :]
        o_mmax = o[:, 3][None, :]
        o_tmin = o[:, 4][None, :]
        o_tmax = o[:, 5][None, :]
        o_constr = o[:, 6][None, :]

        count_ok = (o_count < 0) | jnp.where(p_count < 0, o_count == 0, p_count == o_count)
        mem_ok = ge_min(p_mem, o_mmin) & ((o_mmax < 0) | ((p_mem >= 0) & (p_mem <= o_mmax)))
        total = p_count * p_mem
        have_total = (p_count >= 0) & (p_mem >= 0)
        tot_ok = ((o_tmin < 0) | ~have_total | (total >= o_tmin)) & (
            (o_tmax < 0) | ~have_total | (total <= o_tmax)
        )
        # model bitmask: select this option's word by provider class
        words = rm[:, k * W : (k + 1) * W]  # [TB, W]
        sel = jnp.zeros((PB, TB), jnp.uint32)
        for w in range(W):
            sel = jnp.where(word == w, words[:, w][None, :], sel)
        model_hit = ((sel >> bit) & 1) > 0
        model_ok = (o_constr == 0) | ((p_model >= 0) & model_hit)

        any_opt_ok |= (o_valid > 0) & count_ok & mem_ok & tot_ok & model_ok

    gpu_ok = jnp.where(r_anygpu > 0, (p_hasgpu > 0) & any_opt_ok, True)
    ok &= gpu_ok
    ok &= (pf[:, 5] > 0)[:, None] & (r_valid > 0)

    # cost terms (ops/cost.cost_matrix)
    lat1, lon1 = pf[:, 0][:, None], pf[:, 1][:, None]
    lat2, lon2 = rf[:, 0][None, :], rf[:, 1][None, :]
    dlat, dlon = lat2 - lat1, lon2 - lon1
    a = jnp.sin(dlat / 2) ** 2 + jnp.cos(lat1) * jnp.cos(lat2) * jnp.sin(dlon / 2) ** 2
    dist = 2.0 * EARTH_RADIUS_KM * jnp.arcsin(jnp.sqrt(jnp.clip(a, 0.0, 1.0)))
    has_loc = (pf[:, 2] > 0)[:, None] & (rf[:, 2] > 0)[None, :]

    cost = weights.price * pf[:, 3][:, None] + weights.load * pf[:, 4][:, None]
    cost = cost + jnp.where(has_loc, weights.proximity * dist, 0.0)
    cost = cost - weights.priority * rf[:, 3][None, :]
    cost = jnp.where(ok, cost, INFEASIBLE)

    # deterministic tie-breaking jitter (ops/sparse.candidates_topk)
    gp = (p0 + jax.lax.broadcasted_iota(jnp.uint32, (PB, TB), 0)).astype(jnp.uint32)
    gt = jax.lax.broadcasted_iota(jnp.uint32, (PB, TB), 1).astype(jnp.uint32)
    h = gp * jnp.uint32(2654435761) ^ gt * jnp.uint32(40503)
    jitter = (h & jnp.uint32(1023)).astype(jnp.float32) * jnp.float32(1e-7)
    return jnp.where(cost < INFEASIBLE * 0.5, cost + jitter, cost)


def _topk_kernel(pi, pf, ri, ro, rm, rf, out_val, out_idx, weights, K, W, PB, k):
    """Grid step: fold this provider block's cost into the running top-k.

    Scratchless variant: the running top-k lives in the OUTPUT refs (same
    block for every grid step along providers), initialized at step 0.
    Selection per slot: k rounds of masked row-min over the [PB+k] merge
    candidates — k is small (<=128), PB is the block size.
    """
    step = pl.program_id(0)
    p0 = (step * PB).astype(jnp.uint32)

    cost = _cost_block(pi[:], pf[:], ri[:], ro[:], rm[:], rf[:], weights, p0, K, W)
    TB = cost.shape[1]

    @pl.when(step == 0)
    def _init():
        out_val[:] = jnp.full((TB, k), INFEASIBLE, jnp.float32)
        out_idx[:] = jnp.full((TB, k), -1, jnp.int32)

    # merge: [TB, k + PB] values; select k smallest per row
    blk_val = cost.T  # [TB, PB]
    blk_idx = (step * PB + jax.lax.broadcasted_iota(jnp.int32, (TB, PB), 1))
    merged_val = jnp.concatenate([out_val[:], blk_val], axis=1)
    merged_idx = jnp.concatenate([out_idx[:], blk_idx], axis=1)

    # iterative selection: k rounds of row-argmin with masking
    def select(i, carry):
        mval, midx, oval, oidx = carry
        j = jnp.argmin(mval, axis=1)  # [TB]
        rows = jax.lax.broadcasted_iota(jnp.int32, (TB,), 0)
        best_v = mval[rows, j]
        best_i = midx[rows, j]
        oval = oval.at[:, i].set(best_v)
        oidx = oidx.at[:, i].set(best_i)
        mval = mval.at[rows, j].set(INFEASIBLE * 2.0)
        return mval, midx, oval, oidx

    _, _, new_val, new_idx = jax.lax.fori_loop(
        0,
        k,
        select,
        (
            merged_val,
            merged_idx,
            jnp.zeros((TB, k), jnp.float32),
            jnp.zeros((TB, k), jnp.int32),
        ),
    )
    out_val[:] = new_val
    out_idx[:] = jnp.where(new_val < INFEASIBLE * 0.5, new_idx, -1)


def candidates_topk_pallas(
    ep: EncodedProviders,
    er: EncodedRequirements,
    weights: CostWeights | None = None,
    k: int = 64,
    provider_block: int = 1024,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """Fused candidate generation; same contract as ops/sparse.candidates_topk
    (for T small enough to fit one task tile in VMEM — pair with an outer
    task loop at larger T). Returns (cand_provider [T, k], cand_cost [T, k]).

    Cost weights are baked into the kernel as compile-time constants (one
    executable per weight setting — weights change rarely), which keeps the
    kernel signature to the six packed feature blocks.
    """
    if weights is None:
        weights = CostWeights()
    wtuple = (
        float(weights.price),
        float(weights.load),
        float(weights.proximity),
        float(weights.priority),
    )
    return _candidates_topk_pallas_jit(
        ep, er, wtuple, k=k, provider_block=provider_block, interpret=interpret
    )


@functools.partial(
    jax.jit, static_argnames=("wtuple", "k", "provider_block", "interpret")
)
def _candidates_topk_pallas_jit(
    ep: EncodedProviders,
    er: EncodedRequirements,
    wtuple: tuple,
    k: int,
    provider_block: int,
    interpret: bool,
) -> tuple[jax.Array, jax.Array]:
    weights = CostWeights(*wtuple)
    pi, pf, ri, ro, rm, rf = pack_features(ep, er)
    P = pi.shape[0]
    T = ri.shape[0]
    K = er.gpu_opt_valid.shape[1]
    W = er.gpu_model_mask.shape[-1]
    if P % provider_block != 0:
        raise ValueError(f"P={P} not divisible by provider_block={provider_block}")
    k = min(k, P)

    kernel = functools.partial(
        _topk_kernel, weights=weights, K=K, W=W, PB=provider_block, k=k
    )
    grid = (P // provider_block,)
    val, idx = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((provider_block, 8), lambda i: (i, 0)),
            pl.BlockSpec((provider_block, 8), lambda i: (i, 0)),
            pl.BlockSpec((T, 8), lambda i: (0, 0)),
            pl.BlockSpec((T, K * 8), lambda i: (0, 0)),
            pl.BlockSpec((T, K * W), lambda i: (0, 0)),
            pl.BlockSpec((T, 8), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((T, k), lambda i: (0, 0)),
            pl.BlockSpec((T, k), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, k), jnp.float32),
            jax.ShapeDtypeStruct((T, k), jnp.int32),
        ],
        interpret=interpret,
    )(pi, pf, ri, ro, rm, rf)
    provider = jnp.where(val < INFEASIBLE * 0.5, idx, -1)
    return provider, val
