"""Provider x task cost tensor.

The reference scores nothing — its matcher takes the first compatible task
(crates/orchestrator/src/scheduler/mod.rs:26-74) and uses Haversine proximity
only for group seeding (crates/orchestrator/src/plugins/node_groups/
mod.rs:217-255). Here those signals become explicit cost terms so the
assignment kernels can optimize globally:

  cost[p, t] = w_price * price[p]
             + w_load * load[p]
             + w_proximity * haversine(provider, task origin)   (0 if either
                                                                 side has no
                                                                 location)
             - w_priority * priority[t]
             + INFEASIBLE where !compat_mask[p, t]

All terms are f32; the tensor is built in one fused XLA computation and is
the only O(P*T) object in the pipeline.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from protocol_tpu.ops.encoding import EncodedProviders, EncodedRequirements, compat_mask

# Large-but-finite infeasibility penalty: keeps arithmetic NaN-free under
# auction price updates while dominating every feasible cost. A plain Python
# float on purpose — a jnp scalar would silently turn host-side numpy math
# (baselines, oracles) into per-op JAX dispatches.
INFEASIBLE = 1e9

EARTH_RADIUS_KM = 6371.0


@jax.tree_util.register_dataclass
@dataclass
class CostWeights:
    # plain floats (valid pytree leaves); jnp scalars here would initialize
    # the JAX backend on construction, which control-plane code must avoid
    price: float = 1.0
    load: float = 1.0
    proximity: float = 0.001  # per km
    priority: float = 0.0


def haversine_km(
    lat1: jax.Array, lon1: jax.Array, lat2: jax.Array, lon2: jax.Array
) -> jax.Array:
    """Great-circle distance in km; inputs in radians, broadcastable shapes.

    Same formula as the reference's group-proximity seeding
    (node_groups/mod.rs:217-255), vectorized.
    """
    dlat = lat2 - lat1
    dlon = lon2 - lon1
    a = jnp.sin(dlat / 2) ** 2 + jnp.cos(lat1) * jnp.cos(lat2) * jnp.sin(dlon / 2) ** 2
    # clip for numerical safety at antipodes
    return 2.0 * EARTH_RADIUS_KM * jnp.arcsin(jnp.sqrt(jnp.clip(a, 0.0, 1.0)))


def tie_jitter(
    num_providers: int,
    num_tasks: int,
    provider_offset: int | jax.Array = 0,
    task_offset: int | jax.Array = 0,
) -> jax.Array:
    """Deterministic hash(p, t) epsilon grid [P, T] in [0, ~1e-4).

    Marketplaces have many identically-priced providers; with exact ties
    and deterministic argmax, every open bidder targets the SAME provider
    each auction round — one assignment per round (observed: a 400-slot
    dense solve assigning exactly max_iters providers). Adding this to
    feasible cells decorrelates targets while preserving any real cost
    gap > 1e-4. Shared by candidates_topk and the dense matcher solves so
    their tie behavior matches."""
    p_idx = (jnp.uint32(provider_offset) + jnp.arange(num_providers, dtype=jnp.uint32))[:, None]
    t_idx = (jnp.uint32(task_offset) + jnp.arange(num_tasks, dtype=jnp.uint32))[None, :]
    h = p_idx * jnp.uint32(2654435761) ^ t_idx * jnp.uint32(40503)
    return (h & jnp.uint32(1023)).astype(jnp.float32) * jnp.float32(1e-7)


def tie_jitter_ids(p_ids: jax.Array, t_ids: jax.Array) -> jax.Array:
    """:func:`tie_jitter` for GATHERED index sets: the same hash(p, t)
    grid, [len(p_ids), len(t_ids)], keyed on explicit GLOBAL ids instead
    of offset+arange ranges. The warm-path candidate repair kernels
    recompute arbitrary (provider, task) subsets and must land on the
    exact jitter the full generation pass applied at those global
    coordinates — same constant, same mask, same f32 scale, or repaired
    cells drift off the regen-exactness contract by up to 1e-4."""
    p_idx = jnp.asarray(p_ids, jnp.uint32)[:, None]
    t_idx = jnp.asarray(t_ids, jnp.uint32)[None, :]
    h = p_idx * jnp.uint32(2654435761) ^ t_idx * jnp.uint32(40503)
    return (h & jnp.uint32(1023)).astype(jnp.float32) * jnp.float32(1e-7)


def with_tie_jitter(cost: jax.Array) -> jax.Array:
    """Apply :func:`tie_jitter` to the feasible cells of a dense [P, T]
    cost matrix — the one-line form every dense auction call site uses.
    Not folded into assign_auction itself: the sparse kernels pre-jitter
    inside candidates_topk, and parity tests feed both sides the same
    matrix, so jitter must be applied exactly once at the builder."""
    return jnp.where(
        cost < INFEASIBLE * 0.5,
        cost + tie_jitter(cost.shape[0], cost.shape[1]),
        cost,
    )


def cost_matrix(
    p: EncodedProviders,
    r: EncodedRequirements,
    weights: CostWeights | None = None,
    mask: jax.Array | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Returns (cost [P, T] f32, compat [P, T] bool)."""
    if weights is None:
        weights = CostWeights()
    if mask is None:
        mask = compat_mask(p, r)

    base = weights.price * p.price + weights.load * p.load  # [P]
    cost = jnp.broadcast_to(base[:, None], mask.shape).astype(jnp.float32)

    dist = haversine_km(p.lat[:, None], p.lon[:, None], r.lat[None, :], r.lon[None, :])
    has_loc = p.has_location[:, None] & r.has_location[None, :]
    cost = cost + jnp.where(has_loc, weights.proximity * dist, 0.0)
    cost = cost - weights.priority * r.priority[None, :]
    cost = jnp.where(mask, cost, INFEASIBLE)
    return cost, mask


@jax.jit
def _cost_pairs_vmapped(p_rows, r, weights) -> jax.Array:
    def pair(pr, rr):
        c, _ = cost_matrix(
            jax.tree.map(lambda a: a[None], pr),
            jax.tree.map(lambda a: a[None], rr),
            weights,
        )
        return c[0, 0]

    return jax.vmap(pair)(p_rows, r)


def cost_pairs(
    p: EncodedProviders,
    r: EncodedRequirements,
    provider_for_task: jax.Array,
    weights: CostWeights | None = None,
) -> jax.Array:
    """Per-pair cost of an assignment: [T] f32, INFEASIBLE where the task
    is unassigned or the pair is incompatible.

    Gathers the chosen provider rows and vmaps :func:`cost_matrix` over
    the pairs — O(T) work, so assignment quality is measurable at shapes
    where the [P, T] tensor cannot exist (the 100k/1M ladder rungs).
    Reusing cost_matrix rather than a pairwise re-derivation means this
    can never drift from what the solvers optimized."""
    if weights is None:
        weights = CostWeights()
    p4t = jnp.asarray(provider_for_task, jnp.int32)
    ep_rows = jax.tree.map(lambda a: jnp.take(a, jnp.maximum(p4t, 0), axis=0), p)
    cost = _cost_pairs_vmapped(ep_rows, r, weights)
    return jnp.where(p4t >= 0, cost, INFEASIBLE)
