"""Standalone service entry points for production deployments.

The reference ships one binary per service (discovery/orchestrator/
validator/worker mains wired by clap CLIs); the devnet here runs them all
in one process. This module is the per-pod equivalent the Helm charts
exec: each subcommand boots ONE service against a shared ledger API
(chain/remote.RemoteLedger — the counterpart of the reference services'
JSON-RPC contract wrappers) and runs its loops.

    python -m protocol_tpu.serve discovery     --ledger-url ... --pool-id N
    python -m protocol_tpu.serve orchestrator  --ledger-url ... --pool-id N
    python -m protocol_tpu.serve validator     --ledger-url ... --pool-id N
    python -m protocol_tpu.serve scheduler     --address 0.0.0.0:50061
    python -m protocol_tpu.serve worker        --ledger-url ... --pool-id N

Secrets come from env (MANAGER_KEY / ADMIN_API_KEY / S3_CREDENTIALS /
PROVIDER_KEY / NODE_KEY), mirroring the reference charts' envFromSecret.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
from typing import Optional

VERSION = os.environ.get("PROTOCOL_TPU_VERSION", "dev")


def _wallet_from_env(var: str):
    """Pod identity from ``var``; PROTOCOL_TPU_WALLET_SCHEME selects the
    signature scheme (``ed25519`` default, ``evm`` = secp256k1/keccak
    with embedded-pubkey wire, ``evm-recovery`` = the reference's literal
    r||s||v EIP-191 wire) — all three verify through the same seam, so
    pods of different schemes interoperate."""
    from protocol_tpu.security import EvmRecoveryWallet, EvmWallet, Wallet

    key = os.environ.get(var, "")
    if not key:
        raise SystemExit(f"{var} env var required")
    scheme = os.environ.get("PROTOCOL_TPU_WALLET_SCHEME", "ed25519")
    cls = {
        "ed25519": Wallet,
        "evm": EvmWallet,
        "evm-recovery": EvmRecoveryWallet,
    }.get(scheme)
    if cls is None:
        raise SystemExit(f"unknown PROTOCOL_TPU_WALLET_SCHEME {scheme!r}")
    return cls.from_hex(key)


def _ledger(args):
    from protocol_tpu.chain.remote import RemoteLedger

    return RemoteLedger(
        args.ledger_url, admin_api_key=os.environ.get("LEDGER_API_KEY", "")
    )


def _storage():
    creds = os.environ.get("S3_CREDENTIALS", "")
    bucket = os.environ.get("BUCKET_NAME", "")
    if creds and bucket:
        from protocol_tpu.utils.cloud_storage import GcsStorageProvider
        from protocol_tpu.utils.tls import public_client_session

        # GCS/S3 are PUBLIC endpoints: their certs chain to system roots,
        # not the pinned deployment CA, so they get their own session.
        # STORAGE_ENDPOINT overrides the real GCS host (emulators, the
        # signature-verifying fake bucket in full-stack drives).
        endpoint = os.environ.get(
            "STORAGE_ENDPOINT", "https://storage.googleapis.com"
        )
        return GcsStorageProvider(
            bucket, creds, public_client_session(), endpoint=endpoint
        )
    root = os.environ.get("STORAGE_DIR", "")
    if root:
        from protocol_tpu.utils.storage import LocalDirStorageProvider

        return LocalDirStorageProvider(
            root, public_base_url=os.environ.get("STORAGE_PUBLIC_URL", "")
        )
    return None


def _client_session():
    """aiohttp session honoring PROTOCOL_TPU_TLS_CA for internal peers."""
    from protocol_tpu.utils.tls import env_client_session

    return env_client_session()


def _public_session():
    """System-trust session for public endpoints (signed-URL storage)."""
    from protocol_tpu.utils.tls import public_client_session

    return public_client_session()


async def _close_sessions(*sessions) -> None:
    """Close aiohttp ClientSessions on graceful exit. The lazily-created
    public-trust sessions (worker signed-URL PUTs, GCS, toploc,
    geolocation) would otherwise leak their connectors when a serve
    coroutine is cancelled."""
    for s in sessions:
        if s is None or isinstance(s, str) or getattr(s, "closed", False):
            continue
        close = getattr(s, "close", None)
        if close is None:
            continue
        try:
            r = close()
            if asyncio.iscoroutine(r):
                await r
        except Exception:
            pass


def _server_ssl(args):
    """TLS server context from --tls-cert/--tls-key (or TLS_CERT/TLS_KEY
    env, the charts' secret mounts). None = plaintext, the pre-TLS
    behavior."""
    cert = getattr(args, "tls_cert", "") or os.environ.get("TLS_CERT", "")
    key = getattr(args, "tls_key", "") or os.environ.get("TLS_KEY", "")
    if not cert and not key:
        return None
    if not (cert and key):
        raise SystemExit("--tls-cert and --tls-key must be given together")
    from protocol_tpu.utils.tls import server_ssl_context

    return server_ssl_context(cert, key)


async def _run_app(app, port: int, ssl_context=None) -> None:
    from aiohttp import web

    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "0.0.0.0", port, ssl_context=ssl_context)
    await site.start()
    scheme = "https" if ssl_context is not None else "http"
    print(f"listening on :{port} ({scheme}, version {VERSION})", flush=True)


async def serve_discovery(args) -> None:
    from protocol_tpu.services.discovery import DiscoveryService
    from protocol_tpu.utils.location import HttpLocationResolver

    resolver = None
    if args.location_url:
        # geolocation is an external endpoint (reference location-service
        # shape): system trust, not the pinned CA. Self-hosting it behind
        # the deployment CA? Add that CA to the container's system trust
        # store (standard CA-bundle mount).
        resolver = HttpLocationResolver(
            args.location_url, _public_session()
        )
    svc = DiscoveryService(
        _ledger(args),
        args.pool_id,
        max_nodes_per_ip=args.max_nodes_per_ip,
        admin_api_key=os.environ.get("ADMIN_API_KEY", "admin"),
        location_resolver=resolver,
        persist_path=(
            os.path.join(args.state_dir, "discovery.aof") if args.state_dir else None
        ),
    )
    await _run_app(svc.make_app(), args.port, ssl_context=_server_ssl(args))
    try:
        while True:
            try:
                await asyncio.to_thread(svc.chain_sync_once)
                await svc.enrich_locations_once()
            except Exception as e:
                print(f"discovery loop error: {e}", file=sys.stderr)
            await asyncio.sleep(args.sync_interval)
    finally:
        await _close_sessions(resolver.http if resolver else None)


async def serve_orchestrator(args) -> None:
    from protocol_tpu.models.node import DiscoveryNode
    from protocol_tpu.security import sign_request
    from protocol_tpu.sched import Scheduler
    from protocol_tpu.sched.node_groups import (
        NodeGroupConfiguration,
        NodeGroupsPlugin,
    )
    from protocol_tpu.sched.tpu_backend import TpuBatchMatcher
    from protocol_tpu.services.orchestrator import OrchestratorService
    from protocol_tpu.store import StoreContext
    from protocol_tpu.store.kv import KVStore

    wallet = _wallet_from_env("MANAGER_KEY")
    ledger = _ledger(args)
    session = _client_session()
    if args.kv_url:
        # shared store pod (the reference's external Redis): api/processor
        # replicas all see the same state
        from protocol_tpu.store.remote_kv import RemoteKVStore

        store = StoreContext(
            RemoteKVStore(
                args.kv_url, api_key=os.environ.get("KV_API_KEY", "admin")
            )
        )
    else:
        if args.mode != "full":
            raise SystemExit(
                f"--mode {args.mode} needs --kv-url: split replicas must "
                "share a kv-api store pod"
            )
        store = StoreContext(
            KVStore(
                persist_path=(
                    os.path.join(args.state_dir, "orchestrator.aof")
                    if args.state_dir
                    else None
                )
            )
        )

    backend = args.scheduler_backend
    if backend != "local" and not (
        backend == "remote" or backend.startswith("remote:")
    ):
        raise SystemExit(
            f"unknown --scheduler-backend {backend!r} "
            "(want local | remote | remote:HOST:PORT)"
        )

    grpc_server = None
    groups_plugin = None
    group_configs = os.environ.get("NODE_GROUP_CONFIGS", "")
    if group_configs:
        configs = [
            NodeGroupConfiguration.from_dict(d) for d in json.loads(group_configs)
        ]
        groups_plugin = NodeGroupsPlugin(store, configs)
        groups_plugin.attach_observers()
    if backend != "local":
        from protocol_tpu.services import scheduler_grpc

        addr = backend.partition(":")[2]
        if not addr:
            # bare "remote": boot an in-process backend (devnet semantics);
            # hold the reference or the grpc.Server is GC'd and stops
            addr = "127.0.0.1:50061"
            grpc_server = scheduler_grpc.serve(addr)
        matcher = scheduler_grpc.RemoteBatchMatcher(
            store,
            addr,
            # wire protocol revision: v2 (tensor frames + delta sessions)
            # falls back to v1 automatically against an old server
            wire=os.environ.get("PROTOCOL_TPU_WIRE", "v2"),
            # the engine knobs ride the wire as the kernel string
            # ("native-mt[:N]" / "sinkhorn-mt[:N]" / "jax[:D]") when the
            # control plane is in degraded mode
            native_fallback=os.environ.get(
                "PROTOCOL_TPU_NATIVE_FALLBACK", ""
            ).lower()
            in ("1", "true", "yes"),
            native_engine=os.environ.get(
                "PROTOCOL_TPU_NATIVE_ENGINE", "native"
            ),
            native_threads=int(
                os.environ.get("PROTOCOL_TPU_NATIVE_THREADS") or 0
            ),
        )
    else:
        matcher = TpuBatchMatcher(
            store,
            native_fallback=os.environ.get(
                "PROTOCOL_TPU_NATIVE_FALLBACK", ""
            ).lower()
            in ("1", "true", "yes"),
            # native | native-mt | sinkhorn-mt | jax[:D]: native-* are
            # the multi-threaded host engines + persistent warm arena
            # for degraded-mode deployments with cores to spare
            # (sinkhorn-mt = the O(nnz) entropic solver with
            # auction-referee rounding); jax[:D] is the first-class JAX
            # engine — sharded candidate gen over D devices + adaptive
            # eps-ladder solve with warm dual carry
            native_engine=os.environ.get(
                "PROTOCOL_TPU_NATIVE_ENGINE", "native"
            ),
            # 0 = all hardware threads
            native_threads=int(
                os.environ.get("PROTOCOL_TPU_NATIVE_THREADS") or 0
            ),
            # deploy-time override of the dense/sparse cutover (cells =
            # p_bucket * s_bucket). Small fleets land on the dense solver
            # by default; soaks and staging set this low to exercise the
            # production sparse + candidate-cache + warm path end to end.
            dense_cell_budget=int(
                os.environ.get("PROTOCOL_TPU_DENSE_CELL_BUDGET", 1 << 24)
            ),
            # multi-chip pods: solve phase 1 over the device mesh (the
            # task-sharded eps-ladder/warm kernels, parallel/sparse.py)
            use_mesh=os.environ.get("PROTOCOL_TPU_USE_MESH", "").lower()
            in ("1", "true", "yes"),
            # stage-A approx_max_k selection (e.g. 0.95); empty = exact
            approx_recall=(
                float(os.environ["PROTOCOL_TPU_APPROX_RECALL"])
                if os.environ.get("PROTOCOL_TPU_APPROX_RECALL")
                else None
            ),
        )
    matcher.attach_observers()
    if groups_plugin is not None:
        # composed gang scheduling: grouped nodes resolve through the
        # plugin (matcher-ranked selection), ungrouped through the batch
        # solve — no longer mutually exclusive deployments
        matcher.attach_groups(groups_plugin)
        scheduler = Scheduler(
            store, plugins=[groups_plugin], batch_matcher=matcher
        )
    else:
        scheduler = Scheduler(store, batch_matcher=matcher)

    webhook = None
    webhook_configs = os.environ.get("WEBHOOK_CONFIGS", "")
    if webhook_configs:
        from protocol_tpu.sched.webhook import WebhookConfig, WebhookPlugin

        webhook = WebhookPlugin(
            WebhookConfig.from_json_env(webhook_configs), http=session
        )

    discovery_urls = [
        u for u in os.environ.get("DISCOVERY_URLS", "").split(",") if u
    ]

    async def discovery_fetcher():
        for url in discovery_urls:
            headers, _ = sign_request(f"/api/pool/{args.pool_id}", wallet)
            try:
                async with session.get(
                    f"{url}/api/pool/{args.pool_id}", headers=headers
                ) as resp:
                    data = await resp.json()
                    return [
                        DiscoveryNode.from_dict(d) for d in data.get("data", [])
                    ]
            except Exception:
                continue
        return []

    async def invite_sender(node, payload):
        url = (node.p2p_addresses or [None])[0]
        if not url:
            return False
        headers, body = sign_request("/control/invite", wallet, payload)
        try:
            async with session.post(
                f"{url}/invite", json=body, headers=headers
            ) as resp:
                return resp.status == 200
        except Exception:
            return False

    svc = OrchestratorService(
        ledger,
        args.pool_id,
        wallet,
        store=store,
        scheduler=scheduler,
        groups_plugin=groups_plugin,
        storage=_storage(),
        discovery_fetcher=discovery_fetcher if discovery_urls else None,
        invite_sender=invite_sender,
        admin_api_key=os.environ.get("ADMIN_API_KEY", "admin"),
        # default scheme follows the listener: an https listener behind an
        # http:// invite URL is unreachable to every worker dial
        heartbeat_url=os.environ.get(
            "HEARTBEAT_URL",
            f"{'https' if _server_ssl(args) is not None else 'http'}"
            f"://localhost:{args.port}",
        ),
        uploads_per_hour=int(os.environ.get("UPLOADS_PER_HOUR", "3")),
        control_http=session,
        webhook=webhook,
    )
    svc.grpc_server = grpc_server  # keep the in-process backend alive
    if webhook is not None:
        webhook.start()
    # mode-dependent surface (the reference's api/processor/full split,
    # orchestrator/src/main.rs + api/server.rs:202-220): api replicas serve
    # HTTP only, the processor runs the loops, full does both
    if args.mode == "api":
        await _run_app(svc.make_app(), args.port, ssl_context=_server_ssl(args))
        print(f"orchestrator[api] on :{args.port} (version {VERSION})", flush=True)
    elif args.mode == "processor":
        from aiohttp import web as _web

        health_app = _web.Application()
        health_app.router.add_get("/health", svc.health)
        await _run_app(health_app, args.port, ssl_context=_server_ssl(args))
        # only the loops; the HTTP surface lives in the api replicas.
        # keep the task references — the event loop holds tasks weakly
        svc.loop_tasks = svc.start_loops()
        print(
            f"orchestrator[processor] health on :{args.port} (version {VERSION})",
            flush=True,
        )
    else:
        await svc.serve(host="0.0.0.0", port=args.port)
        print(f"orchestrator on :{args.port} (version {VERSION})", flush=True)
    try:
        while True:  # loops run as tasks; keep the process alive
            await asyncio.sleep(3600)
    finally:
        await _close_sessions(
            session, getattr(getattr(svc, "storage", None), "http", None)
        )


async def serve_validator(args) -> None:
    from protocol_tpu.models.node import DiscoveryNode
    from protocol_tpu.security import sign_request
    from protocol_tpu.services.validator import (
        SyntheticDataValidator,
        ToplocClient,
        ValidatorService,
    )

    wallet = _wallet_from_env("VALIDATOR_KEY")
    ledger = _ledger(args)
    session = _client_session()

    synthetic = None
    toploc_session = None
    toploc_configs = os.environ.get("TOPLOC_CONFIGS", "")
    # storage built lazily: _storage() opens its own public session for GCS,
    # which must not sit idle (and unclosed) when toploc is unconfigured
    storage = _storage() if toploc_configs else None
    if toploc_configs and storage is not None:
        # toploc is an EXTERNAL verification service (bearer-auth HTTPS like
        # the reference's toploc API): system trust, not the pinned CA.
        # Self-hosting it behind the deployment CA? Add that CA to the
        # container's system trust store (standard CA-bundle mount).
        toploc_session = _public_session()
        clients = [
            ToplocClient(
                c["url"],
                toploc_session,
                auth_token=c.get("auth_token"),
                file_prefix_filter=c.get("file_prefix_filter"),
            )
            for c in json.loads(toploc_configs)
        ]
        synthetic = SyntheticDataValidator(
            ledger,
            args.pool_id,
            storage,
            clients,
            persist_path=(
                os.path.join(args.state_dir, "validator.aof")
                if args.state_dir
                else None
            ),
        )

    discovery_urls = [
        u for u in os.environ.get("DISCOVERY_URLS", "").split(",") if u
    ]

    async def fetcher():
        for url in discovery_urls:
            headers, _ = sign_request("/api/validator", wallet)
            try:
                async with session.get(
                    f"{url}/api/validator", headers=headers
                ) as resp:
                    data = await resp.json()
                    return [
                        DiscoveryNode.from_dict(d) for d in data.get("data", [])
                    ]
            except Exception:
                continue
        return []

    svc = ValidatorService(
        wallet,
        ledger,
        args.pool_id,
        synthetic=synthetic,
        discovery_fetcher=fetcher if discovery_urls else None,
        http=session,
    )
    await _run_app(svc.make_app(), args.port, ssl_context=_server_ssl(args))
    try:
        while True:
            try:
                await svc.validation_loop_once()
            except Exception as e:
                print(f"validation loop error: {e}", file=sys.stderr)
            await asyncio.sleep(args.loop_interval)
    finally:
        await _close_sessions(
            session, toploc_session, getattr(storage, "http", None)
        )


async def serve_ledger_api(args) -> None:
    """Dev economic substrate as a standalone pod (the reference devnet's
    reth + contracts; production would point LEDGER_URL at a real chain
    gateway instead). With --state-dir the chain survives pod restarts
    via periodic JSON snapshots (reth's durability, approximated)."""
    from protocol_tpu.chain import Ledger
    from protocol_tpu.services.ledger_api import LedgerApiService

    import signal

    ledger_path = (
        os.path.join(args.state_dir, "ledger.json") if args.state_dir else None
    )
    ledger = Ledger.open(ledger_path)
    if ledger_path and os.path.exists(ledger_path):
        print(f"ledger restored from {ledger_path}", flush=True)
    svc = LedgerApiService(
        ledger, admin_api_key=os.environ.get("ADMIN_API_KEY", "admin")
    )
    await _run_app(svc.make_app(), args.port, ssl_context=_server_ssl(args))

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(sig, stop.set)
    try:
        while not stop.is_set():
            try:
                await asyncio.wait_for(
                    stop.wait(), timeout=10.0 if ledger_path else 3600.0
                )
            except asyncio.TimeoutError:
                pass
            if ledger_path:
                await asyncio.to_thread(ledger.try_snapshot, ledger_path)
    finally:
        if ledger_path:
            # final snapshot on SIGTERM (k8s rolling restart): acknowledged
            # writes must never lose the race with the 10 s tick
            ledger.try_snapshot(ledger_path)


async def serve_kv_api(args) -> None:
    """Shared state store pod (the reference's external Redis)."""
    from protocol_tpu.services.kv_api import KvApiService
    from protocol_tpu.store.kv import KVStore

    kv = KVStore(
        persist_path=(
            os.path.join(args.state_dir, "kv.aof") if args.state_dir else None
        )
    )
    svc = KvApiService(kv, api_key=os.environ.get("KV_API_KEY", "admin"))
    await _run_app(svc.make_app(), args.port, ssl_context=_server_ssl(args))
    while True:
        await asyncio.sleep(3600)


def serve_scheduler(args) -> None:
    """The gRPC kernel backend — the pod that actually holds the TPU."""
    import signal

    from protocol_tpu.services.scheduler_grpc import drain, serve

    fleet = None
    if args.proc_id or args.ckpt_dir or args.endpoint:
        # dfleet pod identity: flags override the PROTOCOL_TPU_FLEET_*
        # env (the charts' surface), same precedence as everywhere else
        import dataclasses

        from protocol_tpu.fleet.fabric import FleetConfig

        fleet = FleetConfig.from_env()
        overrides = {}
        if args.proc_id:
            overrides["proc_id"] = args.proc_id
        if args.ckpt_dir:
            overrides["ckpt_dir"] = args.ckpt_dir
        # precedence: flag > PROTOCOL_TPU_FLEET_ENDPOINT env > bind
        # address (the env value must survive an unrelated flag — a
        # moved:<bind-address> redirect would hand clients 0.0.0.0)
        overrides["endpoint"] = (
            args.endpoint or fleet.endpoint or args.address
        )
        fleet = dataclasses.replace(fleet, **overrides)
    server = serve(
        address=args.address, max_workers=args.max_workers,
        metrics_port=args.metrics_port, fleet=fleet,
    )
    print(f"scheduler backend on {args.address} (version {VERSION})", flush=True)
    if server.metrics is not None:
        print(
            f"obs /metrics on 127.0.0.1:{server.metrics.port}", flush=True
        )

    def _on_sigterm(signum, frame):
        # graceful drain: stop admitting OpenSession, finish in-flight
        # ticks, flush session checkpoints + trace tails, exit 0 — a
        # rolling restart rehydrates every session warm instead of
        # stampeding clients into cold snapshot reopens
        flushed = drain(server)
        print(
            f"drained: {flushed} session checkpoint(s) flushed",
            flush=True,
        )
        raise SystemExit(0)

    signal.signal(signal.SIGTERM, _on_sigterm)
    server.wait_for_termination()


def serve_dfleet(args) -> int:
    """N scheduler servicer processes + the discovery endpoint — the
    whole distributed fleet from one command (the compose/Helm
    equivalent execs one ``scheduler`` pod per process and a discovery
    pod instead; this is the single-host shape and the local drill)."""
    import signal

    from protocol_tpu.dfleet.discovery import DiscoveryEndpoint
    from protocol_tpu.dfleet.manager import ProcessFleet

    fleet = ProcessFleet(
        processes=args.processes,
        journal_root=args.journal_root,
        shards=args.shards,
        max_sessions=args.max_sessions,
        max_workers=args.max_workers,
    )
    fleet.start()
    disco = DiscoveryEndpoint(
        lambda: fleet.topology, port=args.discovery_port
    )
    print(
        f"dfleet: {args.processes} servicer process(es) "
        f"{[p.address for p in fleet.procs]} (version {VERSION})",
        flush=True,
    )
    print(f"discovery on {disco.url}/fleet.json", flush=True)

    stop = []

    def _on_signal(signum, frame):
        stop.append(signum)

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    try:
        import time as _time

        while not stop:
            _time.sleep(0.5)
            for p in fleet.live():
                if p.popen is not None and p.popen.poll() is not None:
                    # a process died underneath us: re-route its
                    # journals so the survivors serve its sessions warm
                    print(
                        f"dfleet: {p.proc_id} exited "
                        f"(rc={p.popen.returncode}); re-routing "
                        "journals", flush=True,
                    )
                    p.alive = False
                    fleet.drop_endpoint(p.address)
                    moved = fleet.handoff_dead(p.index)
                    print(
                        f"dfleet: {len(moved)} journal(s) re-routed",
                        flush=True,
                    )
    finally:
        # graceful fleet drain: SIGTERM every live process (each
        # flushes its journals and exits 0), then stop discovery
        for p in fleet.live():
            try:
                fleet.drain(p.index)
            except Exception:
                pass
        disco.stop()
        fleet.stop()
    print("dfleet: drained and stopped", flush=True)
    return 0


async def serve_worker(args) -> None:
    from protocol_tpu.services.worker import (
        SubprocessRuntime,
        TaskBridge,
        WorkerAgent,
    )

    provider = _wallet_from_env("PROVIDER_KEY")
    node = _wallet_from_env("NODE_KEY")
    ledger = _ledger(args)
    session = _client_session()
    if args.advertise_ip == "auto":
        # STUN public-IP detection (reference checks/stun.rs via
        # cli/command.rs:332-339); explicit --advertise-ip skips it
        from protocol_tpu.utils.stun import get_public_ip

        detected = await asyncio.to_thread(get_public_ip)
        if detected is None:
            # fail closed: advertising a guessed/loopback address would
            # register an unreachable worker that still looks healthy
            raise SystemExit(
                "STUN public-IP detection failed (no UDP egress?); pass "
                "--advertise-ip explicitly"
            )
        args.advertise_ip = detected
        print(f"advertise ip (stun): {args.advertise_ip}", flush=True)
    from protocol_tpu.services.checks import run_all_checks

    specs, report = run_all_checks(
        "/",
        port=args.port,
        docker_bin=os.environ.get("PROTOCOL_TPU_DOCKER_BIN", "docker"),
        require_docker=args.runtime == "docker",
        probe_accelerator=False,
    )
    for issue in report.issues:
        print(f"check [{issue.level}]: {issue.message}", flush=True)
    if report.critical:
        # checks/issue.rs gating via cli/command.rs:388-397: criticals
        # block startup rather than registering a broken worker
        raise SystemExit("critical readiness issues; aborting (see above)")
    if args.runtime == "docker":
        from protocol_tpu.services.docker_runtime import DockerRuntime

        # the SAME binary the boot gate just validated
        def runtime_factory(slot=None):
            return DockerRuntime(
                socket_path=args.socket_path,
                docker_bin=os.environ.get("PROTOCOL_TPU_DOCKER_BIN", "docker"),
                slot=slot,
            )
    else:
        def runtime_factory(slot=None):
            return SubprocessRuntime(socket_path=args.socket_path)

    runtime = runtime_factory()
    ipfs = None
    if os.environ.get("IPFS_API_URL"):
        from protocol_tpu.utils.ipfs import IpfsMirror

        ipfs = IpfsMirror(os.environ["IPFS_API_URL"], http=session)
    server_ssl = _server_ssl(args)
    agent = WorkerAgent(
        provider,
        node,
        ledger,
        args.pool_id,
        runtime=runtime,
        compute_specs=specs,
        ip_address=args.advertise_ip,
        port=args.port,
        http=session,
        ipfs=ipfs,
        price=args.price,
        # advertise the scheme the control app actually serves: an https
        # listener behind an http:// discovery record is unreachable to
        # every orchestrator/validator dial
        control_scheme="https" if server_ssl is not None else "http",
        public_http="lazy",
        # colocated assignments (ladder #5) run concurrently, one runtime
        # per extra task (docker identities are per task id, so containers
        # never collide)
        runtime_factory=runtime_factory,
    )
    agent.register_on_ledger()
    bridge = TaskBridge(args.socket_path, agent)
    await bridge.start()
    await _run_app(agent.make_control_app(), args.port, ssl_context=server_ssl)
    urls = [u for u in args.discovery_urls.split(",") if u]
    await agent.upload_to_discovery(urls)
    last_monitor = 0.0
    try:
        while True:
            try:
                await agent.heartbeat_once()
                await agent.upload_to_discovery(urls)
                import time as _time

                if _time.monotonic() - last_monitor >= 60.0:
                    # stake/whitelist/membership drift watch
                    # (provider.rs:47-147, compute_node.rs:32-115)
                    last_monitor = _time.monotonic()
                    for alarm in await asyncio.to_thread(
                        agent.stake_monitor_once
                    ):
                        print(f"chain alarm: {alarm}", file=sys.stderr)
                    if agent.deregistered:
                        # a deregistered node must STOP, not keep advertising
                        # itself to discovery forever
                        raise SystemExit(
                            "compute node deregistered on-chain; exiting"
                        )
            except SystemExit:
                raise
            except Exception as e:
                print(f"worker loop error: {e}", file=sys.stderr)
            await asyncio.sleep(10.0)
    finally:
        # the "lazy" sentinel only becomes a session after the first
        # external signed-URL upload; _close_sessions skips the sentinel
        await _close_sessions(session, agent.public_http)


def run_bootstrap(args) -> int:
    """Idempotent economic bootstrap for the compose stack (the reference
    devnet's make-compose chain setup): ensure domain 0 + pool ``pool_id``
    exist and are started, the PROVIDER_KEY wallet is funded/whitelisted,
    and the VALIDATOR_KEY wallet holds the validator role. Safe to re-run;
    waits for the ledger-api pod to come up first."""
    import time

    from protocol_tpu.chain.ledger import LedgerError

    creator = _wallet_from_env("POOL_CREATOR_KEY")
    manager = _wallet_from_env("MANAGER_KEY")
    ledger = _ledger(args)

    deadline = time.monotonic() + float(os.environ.get("BOOTSTRAP_WAIT", "60"))
    while True:
        try:
            ledger.balance_of(creator.address)
            break
        except LedgerError as e:
            if time.monotonic() > deadline:
                print(f"ledger-api unreachable: {e}", file=sys.stderr)
                return 1
            time.sleep(2.0)

    def _pool_probe():
        # "unknown pool" must not be conflated with a transport blip: a
        # create against a ledger that already has the pool would mint a
        # duplicate domain/pool and wire the stack to the wrong id
        while True:
            try:
                return ledger.get_pool_info(args.pool_id)
            except LedgerError as e:
                if not str(e).startswith("unreachable"):
                    return None
                if time.monotonic() > deadline:
                    raise
                time.sleep(2.0)  # ledger blip: pace retries like the wait loop

    pool = _pool_probe()
    if pool is None:
        did = ledger.create_domain("compose", validation_logic="any")
        pid = ledger.create_pool(
            did, creator.address, manager.address,
            os.environ.get("POOL_DATA_URI", ""),
        )
        if pid != args.pool_id:
            print(
                f"created pool {pid} but COMPUTE_POOL_ID={args.pool_id}: "
                "the stack would point at a nonexistent pool",
                file=sys.stderr,
            )
            return 1
        ledger.start_pool(pid, creator.address)
        print(f"created domain {did} pool {pid} (started)", flush=True)
    else:
        # re-run repair: a crash between create_pool and start_pool must
        # not leave the pool PENDING forever behind the exists fast path
        if getattr(pool.status, "name", str(pool.status)) != "ACTIVE":
            ledger.start_pool(args.pool_id, creator.address)
            print(f"pool {args.pool_id} existed but was not active: started", flush=True)
        else:
            print(f"pool {args.pool_id} active; bootstrap already ran", flush=True)

    provider_key = os.environ.get("PROVIDER_KEY", "")
    if provider_key:
        from protocol_tpu.security import Wallet

        provider = Wallet.from_hex(provider_key)
        if ledger.balance_of(provider.address) < 1000:
            ledger.mint(provider.address, 1_000_000)
        if not ledger.provider_exists(provider.address):
            # whitelisting needs a registered provider; register here so
            # the worker's own boot sees it and just adds its node
            ledger.register_provider(
                provider.address, ledger.calculate_stake(1)
            )
        ledger.whitelist_provider(provider.address)
        print(f"provider {provider.address} funded + whitelisted", flush=True)

    validator_key = os.environ.get("VALIDATOR_KEY", "")
    if validator_key:
        from protocol_tpu.security import Wallet

        validator = Wallet.from_hex(validator_key)
        ledger.grant_validator_role(validator.address)
        print(f"validator role granted to {validator.address}", flush=True)
    return 0


def main(argv: Optional[list[str]] = None) -> int:
    parser = argparse.ArgumentParser(prog="protocol_tpu.serve")
    parser.add_argument("--version", action="version", version=VERSION)
    sub = parser.add_subparsers(dest="service", required=True)

    def common(p):
        # flags win; env (the charts' configuration surface) is the default
        p.add_argument(
            "--ledger-url", default=os.environ.get("LEDGER_URL", "")
        )
        p.add_argument(
            "--pool-id",
            type=int,
            default=int(os.environ.get("COMPUTE_POOL_ID", "-1")),
        )
        p.add_argument("--state-dir", default=os.environ.get("STATE_DIR", ""))
        # transport confidentiality (the reference's Noise layer,
        # p2p/src/lib.rs:324-335): serve HTTPS when a cert pair is given;
        # clients verify via PROTOCOL_TPU_TLS_CA
        p.add_argument("--tls-cert", default=os.environ.get("TLS_CERT", ""))
        p.add_argument("--tls-key", default=os.environ.get("TLS_KEY", ""))

    p = sub.add_parser("discovery")
    common(p)
    p.add_argument("--port", type=int, default=8089)
    p.add_argument("--max-nodes-per-ip", type=int, default=5)
    p.add_argument("--location-url", default="")
    p.add_argument("--sync-interval", type=float, default=10.0)

    p = sub.add_parser("orchestrator")
    common(p)
    p.add_argument("--port", type=int, default=8090)
    p.add_argument("--scheduler-backend", default="local")
    p.add_argument(
        "--mode",
        choices=["full", "api", "processor"],
        default="full",
        help="api = HTTP replicas, processor = loops; both need --kv-url "
        "(the reference's mode split over shared Redis)",
    )
    p.add_argument(
        "--kv-url",
        default=os.environ.get("KV_URL", ""),
        help="shared kv-api store pod (required for api/processor modes)",
    )

    p = sub.add_parser("kv-api")
    p.add_argument("--port", type=int, default=8096)
    p.add_argument("--state-dir", default=os.environ.get("STATE_DIR", ""))
    p.add_argument("--tls-cert", default=os.environ.get("TLS_CERT", ""))
    p.add_argument("--tls-key", default=os.environ.get("TLS_KEY", ""))

    p = sub.add_parser("validator")
    common(p)
    p.add_argument("--port", type=int, default=9879)
    p.add_argument("--loop-interval", type=float, default=5.0)

    p = sub.add_parser("scheduler")
    p.add_argument("--address", default="0.0.0.0:50061")
    p.add_argument("--max-workers", type=int, default=4)
    p.add_argument(
        "--metrics-port", type=int, default=None,
        help="consolidated /metrics scrape endpoint (obs plane); also "
             "via PROTOCOL_TPU_METRICS_PORT",
    )
    p.add_argument(
        "--proc-id", default=None,
        help="dfleet process id: namespaces this pod's checkpoint "
             "journals under the shared --ckpt-dir root (also "
             "PROTOCOL_TPU_FLEET_PROC_ID)",
    )
    p.add_argument(
        "--ckpt-dir", default=None,
        help="shared checkpoint-journal root (warm restart + live "
             "migration handoff; also PROTOCOL_TPU_FLEET_CKPT_DIR)",
    )
    p.add_argument(
        "--endpoint", default=None,
        help="advertised endpoint for moved:<endpoint> migration "
             "redirects (default: --address; also "
             "PROTOCOL_TPU_FLEET_ENDPOINT)",
    )

    p = sub.add_parser(
        "dfleet",
        help="N scheduler servicer processes behind the consistent-"
        "hash endpoint ring with a discovery endpoint, over one shared "
        "journal root (the multi-process deployment shape)",
    )
    p.add_argument("--processes", type=int, default=3)
    p.add_argument("--journal-root", required=True)
    p.add_argument("--shards", type=int, default=2)
    p.add_argument("--max-sessions", type=int, default=64)
    p.add_argument("--max-workers", type=int, default=8)
    p.add_argument("--discovery-port", type=int, default=0,
                   help="discovery endpoint port (0 = ephemeral)")

    p = sub.add_parser("ledger-api")
    p.add_argument("--port", type=int, default=8095)
    p.add_argument("--state-dir", default=os.environ.get("STATE_DIR", ""))
    p.add_argument("--tls-cert", default=os.environ.get("TLS_CERT", ""))
    p.add_argument("--tls-key", default=os.environ.get("TLS_KEY", ""))

    p = sub.add_parser(
        "bootstrap",
        help="idempotent dev/e2e economic bootstrap against a ledger-api "
        "pod: domain + pool + start + provider mint/whitelist + validator "
        "role (the compose stack's init container)",
    )
    common(p)

    p = sub.add_parser("worker")
    common(p)
    p.add_argument("--port", type=int, default=8091)
    p.add_argument(
        "--advertise-ip",
        default="127.0.0.1",
        help='"auto" = STUN public-IP detection (checks/stun.rs)',
    )
    p.add_argument("--discovery-urls", default="")
    p.add_argument("--runtime", choices=["subprocess", "docker"], default="docker")
    p.add_argument("--socket-path", default="/var/run/protocol-tpu/bridge.sock")
    p.add_argument(
        "--price",
        type=float,
        default=None,
        help="advertised ask price (cost units/hour) fed to the matcher's "
        "price cost term via discovery",
    )

    args = parser.parse_args(argv)
    from protocol_tpu.utils.logging import setup_logging

    setup_logging(
        level=os.environ.get("LOG_LEVEL", "info"),
        loki_url=os.environ.get("LOKI_URL") or None,
        labels={
            "service": args.service,
            "pool": str(getattr(args, "pool_id", "")),
        },
    )
    # Operational platform pin (e.g. PROTOCOL_TPU_FORCE_PLATFORM=cpu for
    # control-plane pods with no accelerator): applied via jax.config, which
    # outranks JAX_PLATFORMS when a site hook has already forced a platform.
    forced = os.environ.get("PROTOCOL_TPU_FORCE_PLATFORM", "")
    if forced:
        import jax

        jax.config.update("jax_platforms", forced)
    if args.service not in ("scheduler", "dfleet", "ledger-api", "kv-api"):
        if not args.ledger_url:
            parser.error("--ledger-url (or LEDGER_URL env) required")
        if args.pool_id < 0:
            parser.error("--pool-id (or COMPUTE_POOL_ID env) required")
    if args.service == "scheduler":
        serve_scheduler(args)
        return 0
    if args.service == "dfleet":
        return serve_dfleet(args)
    if args.service == "bootstrap":
        return run_bootstrap(args)
    coro = {
        "discovery": serve_discovery,
        "orchestrator": serve_orchestrator,
        "validator": serve_validator,
        "worker": serve_worker,
        "ledger-api": serve_ledger_api,
        "kv-api": serve_kv_api,
    }[args.service](args)
    asyncio.run(coro)
    return 0


if __name__ == "__main__":
    sys.exit(main())
