"""Deterministic heartbeat failure detector for the process fleet.

PR 12 gave the fleet journal re-routing, live migration, and a client
failover ladder — but every recovery was DRIVER-scripted: nothing
noticed a dead or wedged process on its own. This module is the
autonomous half: a per-process health tracker with an explicit

    alive --(heartbeats stop)--> suspect --(sustained)--> dead

state machine, EWMA inter-arrival tracking (the detection threshold
adapts to the sampler's real cadence instead of hardcoding a period),
and flap suppression (a slow-but-alive process that oscillates
alive<->suspect inflates its own thresholds instead of being ejected —
the gray-failure degradation ladder: while merely SUSPECT, a process
keeps its sessions and serves them under the bounded-staleness
watchdog contract; only DEAD triggers ejection).

Determinism contract (the lint enforces it): this module never reads a
clock. Every method takes ``now`` explicitly — the caller owns time
(:meth:`ProcessFleet.start_detector` feeds ``time.perf_counter``; the
tests feed a virtual clock), so a recorded sample sequence replays to
the identical transition sequence, byte for byte. ``dead`` is terminal
by design: a zombie's late heartbeat is COUNTED, never believed —
resurrection is a membership change (a fresh spawn with a fresh fence
epoch), not a state transition.

The detector itself is pure bookkeeping; POLICY lives in the caller.
On ``dead`` the fleet manager runs the existing recovery machinery:
``handoff_dead`` re-routes the namespace's journals along the ring,
the ``FleetTopology`` generation bumps (the discovery tier serves the
new ring on its next poll), and the namespace's fencing epoch is
superseded so the ejected process — paused, partitioned, or merely
slow — can never ack or flush against journals it no longer owns.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from protocol_tpu.utils.lockwitness import make_lock

ALIVE = "alive"
SUSPECT = "suspect"
DEAD = "dead"


@dataclasses.dataclass(frozen=True)
class DetectorConfig:
    """Detection thresholds. All elapsed-time comparisons are against
    ``factor * max(ewma, min_interval_s) * flap penalty`` — the EWMA
    tracks the sampler's real heartbeat cadence, ``min_interval_s``
    floors it (a fast sampler must not hair-trigger), and the penalty
    implements flap suppression (see :meth:`FailureDetector.evaluate`).
    """

    alpha: float = 0.3            # EWMA smoothing of inter-arrivals
    suspect_factor: float = 3.0   # alive -> suspect past this many ewmas
    dead_factor: float = 6.0      # suspect -> dead past this many ewmas
    min_interval_s: float = 0.1   # EWMA floor
    dead_misses: int = 3          # consecutive failed probes ALSO required
    flap_penalty: float = 1.0     # threshold inflation per recent flap
    flap_memory: int = 4          # recent-flap count cap
    flap_decay_beats: int = 8     # clean beats that forgive one flap
    max_penalty: float = 4.0      # penalty ceiling


class _ProcHealth:
    __slots__ = (
        "state", "last_seen", "ewma_s", "misses", "first_miss_at",
        "flaps", "recent_flaps", "clean_streak", "suspect_since",
        "dead_at", "zombie_beats",
    )

    def __init__(self) -> None:
        self.state = ALIVE
        self.last_seen: Optional[float] = None
        self.ewma_s: Optional[float] = None
        self.misses = 0
        self.first_miss_at: Optional[float] = None
        self.flaps = 0          # lifetime suspect->alive recoveries
        self.recent_flaps = 0   # the suppression window (decays)
        self.clean_streak = 0
        self.suspect_since: Optional[float] = None
        self.dead_at: Optional[float] = None
        self.zombie_beats = 0   # heartbeats AFTER dead (counted, ignored)


class FailureDetector:
    """Track N processes' heartbeat health (see module docstring).

    Thread contract: all methods are safe to call concurrently (one
    leaf lock); :meth:`evaluate` returns the NEWLY dead proc ids and the
    caller reacts outside the lock — the detector never calls back into
    fleet machinery, so its lock nests under nothing.
    """

    def __init__(self, proc_ids, config: Optional[DetectorConfig] = None):
        self.config = config or DetectorConfig()
        self._lock = make_lock("detector")
        self._procs: dict[str, _ProcHealth] = {
            str(pid): _ProcHealth() for pid in proc_ids
        }
        # bounded transition log: (proc, from, to, at) — what the fleet
        # report and the gate read to prove "suspect before dead"
        self.transitions: list[tuple] = []
        self.suspects_entered = 0
        self.ejections = 0

    # ---------------- membership ----------------

    def add(self, proc_id: str) -> None:
        with self._lock:
            self._procs.setdefault(str(proc_id), _ProcHealth())

    def remove(self, proc_id: str) -> None:
        """Forget a process the DRIVER took down itself (kill/drain):
        a scripted death must never count as a detector ejection."""
        with self._lock:
            self._procs.pop(str(proc_id), None)

    # ---------------- samples ----------------

    def heartbeat(self, proc_id: str, now: float) -> None:
        c = self.config
        with self._lock:
            p = self._procs.get(str(proc_id))
            if p is None:
                return
            if p.state == DEAD:
                # terminal: a zombie's late beat is evidence FOR the
                # fence drill, not a resurrection
                p.zombie_beats += 1
                return
            if p.state == SUSPECT:
                p.state = ALIVE
                p.flaps += 1
                p.recent_flaps = min(p.recent_flaps + 1, c.flap_memory)
                p.clean_streak = 0
                p.suspect_since = None
                self._log(proc_id, SUSPECT, ALIVE, now)
            else:
                p.clean_streak += 1
                if (
                    p.recent_flaps > 0
                    and p.clean_streak >= c.flap_decay_beats
                ):
                    p.recent_flaps -= 1
                    p.clean_streak = 0
            if p.last_seen is not None:
                interval = max(now - p.last_seen, 0.0)
                p.ewma_s = (
                    interval if p.ewma_s is None
                    else c.alpha * interval + (1.0 - c.alpha) * p.ewma_s
                )
            p.last_seen = now
            p.misses = 0
            p.first_miss_at = None

    def probe_failed(self, proc_id: str, now: float) -> None:
        with self._lock:
            p = self._procs.get(str(proc_id))
            if p is None or p.state == DEAD:
                return
            p.misses += 1
            if p.first_miss_at is None:
                p.first_miss_at = now

    # ---------------- evaluation ----------------

    def _threshold_s(self, p: _ProcHealth, factor: float) -> float:
        c = self.config
        ewma = max(p.ewma_s or c.min_interval_s, c.min_interval_s)
        penalty = min(
            1.0 + c.flap_penalty * p.recent_flaps, c.max_penalty
        )
        return factor * ewma * penalty

    def evaluate(self, now: float) -> list:
        """Advance every process's state machine to ``now``; returns
        the proc ids that JUST transitioned to dead (each id is
        returned exactly once, ever). Iteration order is sorted — two
        detectors fed the same samples eject in the same order."""
        c = self.config
        newly_dead: list = []
        with self._lock:
            for pid in sorted(self._procs):
                p = self._procs[pid]
                if p.state == DEAD:
                    continue
                anchor = (
                    p.last_seen if p.last_seen is not None
                    else p.first_miss_at
                )
                if anchor is None:
                    continue  # no sample yet: nothing to judge
                elapsed = now - anchor
                if p.state == ALIVE and elapsed > self._threshold_s(
                    p, c.suspect_factor
                ):
                    p.state = SUSPECT
                    p.suspect_since = now
                    p.clean_streak = 0
                    self.suspects_entered += 1
                    self._log(pid, ALIVE, SUSPECT, now)
                if (
                    p.state == SUSPECT
                    and p.misses >= c.dead_misses
                    and elapsed > self._threshold_s(p, c.dead_factor)
                ):
                    p.state = DEAD
                    p.dead_at = now
                    self.ejections += 1
                    self._log(pid, SUSPECT, DEAD, now)
                    newly_dead.append(pid)
        return newly_dead

    def _log(self, pid, frm, to, now) -> None:
        # caller holds the lock
        self.transitions.append((str(pid), frm, to, float(now)))
        del self.transitions[:-256]

    # ---------------- introspection ----------------

    def state_of(self, proc_id: str) -> Optional[str]:
        with self._lock:
            p = self._procs.get(str(proc_id))
            return p.state if p is not None else None

    def snapshot(self) -> dict:
        with self._lock:
            procs = {
                pid: {
                    "state": p.state,
                    "ewma_s": round(p.ewma_s, 6) if p.ewma_s else None,
                    "misses": p.misses,
                    "flaps": p.flaps,
                    "recent_flaps": p.recent_flaps,
                    "zombie_beats": p.zombie_beats,
                }
                for pid, p in sorted(self._procs.items())
            }
            return {
                "procs": procs,
                "totals": {
                    "suspects_entered": self.suspects_entered,
                    "ejections": self.ejections,
                    "flaps": sum(
                        p.flaps for p in self._procs.values()
                    ),
                },
                "transitions": list(self.transitions),
            }
