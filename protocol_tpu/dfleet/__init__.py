"""Distributed fleet-of-fleets: N servicer processes, one session space.

The reference protocol is explicitly multi-process — a discovery
service routes workers to an orchestrator pool coordinator — while the
reproduction's fleet (PR 7) and chaos/checkpoint plane (PR 9) lived in
ONE process. This package combines those two halves into a horizontally
scaled service that can lose any single process and keep serving warm:

  * :class:`FleetTopology` — consistent-hash session->process routing
    (the same sha1 ring the in-process fabric shards by, lifted to
    endpoints), with an ordered failover walk per session and a
    generation counter that bumps on membership change.
  * :class:`DiscoveryEndpoint` — the thin discovery tier (the
    reference's discovery/orchestrator split): an HTTP endpoint serving
    the endpoint map (``/fleet.json``) and per-session routes
    (``/route?session=...``) so clients bootstrap their failover lists
    without hardcoding the fleet.
  * :class:`ProcessFleet` — spawns/kills/drains real servicer
    processes over a SHARED checkpoint-journal root (each process owns
    its ``(proc id, session id)`` namespace), re-routes a dead
    process's orphaned journals along the ring, and drives LIVE
    migration through the servicer's ``Migrate`` RPC.

Migration protocol (zero client reopens, bounded staleness): the source
records a ``moved:<endpoint>`` redirect, evicts the session (reason
``migrate`` — in-flight solves refuse, the journal file survives),
flushes the journal at its final tick, and atomically renames it into
the target's namespace. The client follows the redirect and resends the
SAME delta; the target rehydrates the journal warm on that miss, and
the tick-cursor/CRC retransmit dedup carries "no tick lost or
double-applied" across the process boundary.

Autonomous resilience tier (ISSUE 14): :class:`FailureDetector` (in
``detector.py``) watches per-process Health heartbeats through a
deterministic alive→suspect→dead state machine (EWMA inter-arrival
thresholds, flap suppression so a slow-but-alive node degrades instead
of being ejected); on DEAD the manager runs the ejection autonomously
— generation bump, journal re-route, and FENCE supersession
(``faults/checkpoint.py``): a monotonic epoch stamped into each
process's journal namespace at spawn and superseded at ejection, so a
SIGSTOPped zombie that resumes finds itself out-fenced and refuses
(``moved:``) instead of double-applying ticks. Split-brain impossible
by construction: the journal's location is the authority — at the
highest fence.
"""

from protocol_tpu.dfleet.detector import (  # noqa: F401
    DetectorConfig,
    FailureDetector,
)
from protocol_tpu.dfleet.topology import FleetTopology  # noqa: F401

__all__ = ["FleetTopology", "FailureDetector", "DetectorConfig"]
