"""One servicer process of the distributed fleet (the spawn target).

``python -m protocol_tpu.dfleet.proc --address H:P --proc-id pK
--journal-root DIR`` boots ONE scheduler servicer whose checkpoint
journals live under the SHARED root in this process's own namespace
(``DIR/pK/``) and whose advertised endpoint rides every
``moved:<endpoint>`` redirect it ever issues. The manager
(:class:`~protocol_tpu.dfleet.manager.ProcessFleet`) spawns N of these,
health-polls them ready, and later kills (drill) or drains (rolling
upgrade) them.

SIGTERM runs the PR 9 graceful drain (stop admitting, finish in-flight
ticks, flush every journal) and exits 0 — after which the manager hands
the journals off along the ring and the survivors rehydrate them warm.

Prints ``DFLEET-READY <address> proc=<id> metrics=<port>`` once
serving; with the lock witness armed (``PROTOCOL_TPU_LOCK_WITNESS``),
any recorded violations are written to
``<journal-root>/witness_<proc-id>.json`` at drain/exit so the dfleet
perf gate can assert on them from the parent process.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys


def _dump_witness(journal_root: str, proc_id: str) -> None:
    if not os.environ.get("PROTOCOL_TPU_LOCK_WITNESS"):
        return
    from protocol_tpu.utils import lockwitness

    try:
        path = os.path.join(journal_root, f"witness_{proc_id}.json")
        with open(path, "w") as fh:
            json.dump(list(lockwitness.violations()), fh)
    except Exception:
        pass  # witness reporting must never block an exit


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m protocol_tpu.dfleet.proc",
        description="One dfleet servicer process (see module docstring).",
    )
    ap.add_argument("--address", required=True)
    ap.add_argument("--proc-id", required=True)
    ap.add_argument("--journal-root", required=True)
    ap.add_argument("--endpoint", default=None,
                    help="advertised endpoint (default: --address)")
    ap.add_argument("--shards", type=int, default=2)
    ap.add_argument("--max-sessions", type=int, default=64)
    ap.add_argument("--max-workers", type=int, default=8)
    ap.add_argument("--session-ttl-s", type=float, default=900.0)
    ap.add_argument("--ckpt-every", type=int, default=1)
    ap.add_argument("--metrics-port", type=int, default=0)
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from protocol_tpu.fleet.fabric import FleetConfig
    from protocol_tpu.services.scheduler_grpc import drain, serve

    cfg = FleetConfig.from_env()
    import dataclasses

    cfg = dataclasses.replace(
        cfg,
        shards=args.shards,
        ckpt_dir=args.journal_root,
        ckpt_every=args.ckpt_every,
        proc_id=args.proc_id,
        endpoint=args.endpoint or args.address,
    )
    server = serve(
        address=args.address,
        max_workers=args.max_workers,
        max_sessions=args.max_sessions,
        session_ttl_s=args.session_ttl_s,
        metrics_port=args.metrics_port,
        fleet=cfg,
    )
    metrics_port = server.metrics.port if server.metrics else 0

    def _on_sigterm(signum, frame):
        flushed = drain(server)
        print(f"dfleet proc {args.proc_id} drained: {flushed} "
              "journal(s) flushed", flush=True)
        _dump_witness(args.journal_root, args.proc_id)
        raise SystemExit(0)

    signal.signal(signal.SIGTERM, _on_sigterm)
    print(
        f"DFLEET-READY {args.address} proc={args.proc_id} "
        f"metrics={metrics_port}",
        flush=True,
    )
    server.wait_for_termination()
    _dump_witness(args.journal_root, args.proc_id)
    return 0


if __name__ == "__main__":
    sys.exit(main())
