"""Process fleet manager: spawn, route, kill, drain, migrate.

:class:`ProcessFleet` runs N REAL servicer processes (the
``protocol_tpu.dfleet.proc`` entrypoint — separate interpreters,
separate GILs, separate crash domains) over one shared checkpoint-
journal root, holds the authoritative :class:`FleetTopology`, and
optionally serves it through a :class:`DiscoveryEndpoint`. It is the
DRIVER the chaos plane's scripted process-level faults belong to (a
process cannot cleanly ``kill -9`` itself, same argument as the PR 9
servicer kill):

  * :meth:`kill` — SIGKILL, the crash drill. The dead process's
    journals are orphaned in its namespace; :meth:`handoff_dead`
    re-routes each along the new ring (atomic renames) so the
    survivors rehydrate the sessions warm on their first failed-over
    delta.
  * :meth:`drain` — SIGTERM, the rolling-upgrade path: the process
    flushes every journal itself and exits 0; the handoff then moves
    complete, final-tick journals.
  * :meth:`migrate_all` — LIVE migration via the servicer's
    ``Migrate`` RPC: the source stays up answering
    ``moved:<endpoint>`` redirects while its sessions rehydrate on the
    target — zero transport failures, zero reopens, the shard-
    rebalancing primitive.

Everything observable rides the per-process obs planes: each process
serves its own ``/metrics(.json)``; :meth:`scrape` joins them into the
per-process view the fleet report and the ``--dfleet`` perf gate read.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request
from typing import Optional

from protocol_tpu.dfleet.topology import FleetTopology
from protocol_tpu.utils.lockwitness import make_lock


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class ManagedProc:
    """One spawned servicer process and what the manager knows about it."""

    def __init__(self, index: int, proc_id: str, address: str):
        self.index = index
        self.proc_id = proc_id
        self.address = address
        self.popen: Optional[subprocess.Popen] = None
        self.metrics_port = 0
        self.alive = False
        # tail of the child's merged stdout/stderr, kept by the drainer
        # thread (debugging aid; bounded)
        self.output_tail: list = []

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"ManagedProc({self.proc_id}@{self.address} "
            f"alive={self.alive})"
        )


class ProcessFleet:
    """Spawn and manage N servicer processes (see module docstring).
    Usable as a context manager; :meth:`stop` kills everything left."""

    def __init__(
        self,
        processes: int = 3,
        journal_root: Optional[str] = None,
        shards: int = 2,
        max_sessions: int = 64,
        max_workers: int = 8,
        ckpt_every: int = 1,
        vnodes: int = 64,
        env_extra: Optional[dict] = None,
        ready_timeout_s: float = 120.0,
        discovery: bool = False,
    ):
        if journal_root is None:
            import tempfile

            self._tmp = tempfile.TemporaryDirectory(prefix="dfleet_")
            journal_root = self._tmp.name
        else:
            self._tmp = None
        self.journal_root = journal_root
        self.shards = shards
        self.max_sessions = max_sessions
        self.max_workers = max_workers
        self.ckpt_every = ckpt_every
        self.ready_timeout_s = ready_timeout_s
        self.env_extra = dict(env_extra or {})
        self._lock = make_lock("router")
        self.procs = [
            ManagedProc(i, f"p{i}", f"127.0.0.1:{_free_port()}")
            for i in range(max(1, int(processes)))
        ]
        self.topology = FleetTopology(
            [p.address for p in self.procs],
            procs={p.address: p.proc_id for p in self.procs},
            vnodes=vnodes,
        )
        # autonomous failure detection (start_detector): the detector
        # and its monitor thread, plus the ejection event log the
        # loadgen report and the zombie-resume gate read (time-to-
        # detect, false-positive accounting)
        self.detector = None
        self._detector_stop: Optional[threading.Event] = None
        self._detector_thread: Optional[threading.Thread] = None
        self.ejections: list = []
        self._detector_ejected: set = set()
        self.last_handoff_stats: dict = {}
        self.discovery = None
        if discovery:
            from protocol_tpu.dfleet.discovery import DiscoveryEndpoint

            self.discovery = DiscoveryEndpoint(lambda: self.topology)

    # ---------------- lifecycle ----------------

    def start(self) -> "ProcessFleet":
        # clear witness verdicts from an earlier run over a REUSED
        # journal root: a stale violation file would fail a clean run
        import glob

        for stale in glob.glob(
            os.path.join(self.journal_root, "witness_*.json")
        ):
            try:
                os.remove(stale)
            except OSError:
                pass
        for p in self.procs:
            self._spawn(p)
        deadline = time.monotonic() + self.ready_timeout_s
        for p in self.procs:
            self._wait_ready(p, deadline)
        return self

    def _spawn(self, p: ManagedProc) -> None:
        # fence stamp at spawn: the child's SessionCheckpointer adopts
        # this epoch at boot; ejection stamps a HIGHER one into the same
        # namespace, so a paused-then-resumed incarnation can prove to
        # itself that it was superseded (faults/checkpoint.py fencing)
        from protocol_tpu.faults.checkpoint import stamp_fence

        stamp_fence(
            self.journal_root, p.proc_id,
            topology=self.topology.to_dict(),
        )
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env.update(self.env_extra)
        p.popen = subprocess.Popen(
            [
                sys.executable, "-m", "protocol_tpu.dfleet.proc",
                "--address", p.address,
                "--proc-id", p.proc_id,
                "--journal-root", self.journal_root,
                "--shards", str(self.shards),
                "--max-sessions", str(self.max_sessions),
                "--max-workers", str(self.max_workers),
                "--ckpt-every", str(self.ckpt_every),
                "--metrics-port", "0",
            ],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        p.alive = True

    def _wait_ready(self, p: ManagedProc, deadline: float) -> None:
        """Block until the process printed its READY line (which carries
        the bound metrics port) and its Health RPC answers."""
        import select

        assert p.popen is not None and p.popen.stdout is not None
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise RuntimeError(
                    f"dfleet proc {p.proc_id} not ready in time"
                )
            # select before readline: a child that hangs WITHOUT
            # printing (bind stall, import deadlock) must trip the
            # ready timeout, not wedge start() on a blocking read
            ready, _, _ = select.select(
                [p.popen.stdout], [], [], min(remaining, 1.0)
            )
            if not ready:
                continue
            line = p.popen.stdout.readline()
            if not line:
                raise RuntimeError(
                    f"dfleet proc {p.proc_id} exited before READY "
                    f"(rc={p.popen.poll()})"
                )
            if line.startswith("DFLEET-READY"):
                for part in line.split():
                    if part.startswith("metrics="):
                        p.metrics_port = int(part.split("=", 1)[1])
                break
        # keep draining the pipe forever (daemon): a chatty child —
        # logging warnings under chaos, grpc noise — would otherwise
        # fill the ~64KB pipe buffer and BLOCK mid-write, wedging its
        # ticks; the bounded tail doubles as a debugging aid
        import threading

        def _drain_output(proc=p):
            try:
                for out_line in proc.popen.stdout:
                    proc.output_tail.append(out_line.rstrip())
                    del proc.output_tail[:-50]
            except Exception:
                pass

        threading.Thread(
            target=_drain_output,
            name=f"dfleet-drain-{p.proc_id}",
            daemon=True,
        ).start()
        from protocol_tpu.services.scheduler_grpc import (
            SchedulerBackendClient,
        )

        client = SchedulerBackendClient(p.address)
        try:
            while True:
                try:
                    client.health(timeout=5.0)
                    return
                except Exception:
                    if time.monotonic() > deadline:
                        raise RuntimeError(
                            f"dfleet proc {p.proc_id} never answered "
                            "Health"
                        )
                    time.sleep(0.05)
        finally:
            client.close()

    def live(self) -> list:
        return [p for p in self.procs if p.alive]

    def proc_at(self, index: int) -> ManagedProc:
        return self.procs[index]

    # ---------------- scripted faults (driver-owned) ----------------

    def drop_endpoint(self, address: str) -> None:
        """Remove a dead process's endpoint from the topology (bumps
        the generation). The LAST endpoint stays: a topology cannot be
        empty, and a fully-dead fleet's routing is moot anyway."""
        with self._lock:
            if (
                address in self.topology.endpoints
                and len(self.topology.endpoints) > 1
            ):
                self.topology = self.topology.without(address)

    def adopt_topology(self, topology: FleetTopology) -> bool:
        """Adopt an externally-built topology — GENERATION-MONOTONIC:
        a candidate no newer than the current one is refused (False).
        The discovery tier serves whatever this manager holds, so this
        guard is what makes a stale map racing a detector ejection
        lose fleet-wide, not just per-client."""
        with self._lock:
            if topology.generation <= self.topology.generation:
                return False
            self.topology = topology
            return True

    def _driver_takedown(self, p: ManagedProc) -> None:
        """A DRIVER-owned death begins: claim the process (alive flip
        under the lock — :meth:`_eject` checks the same flag under the
        same lock, so the detector can never race a scripted kill into
        a false ejection) and remove it from an armed detector BEFORE
        the signal lands — the unresponsive window of a deliberate
        kill/drain must not read as a failure, and a drain's final
        flushes must never be fence-refused by a racing ejection."""
        with self._lock:
            p.alive = False
        if self.detector is not None:
            self.detector.remove(p.proc_id)

    def kill(self, index: int) -> ManagedProc:
        """SIGKILL — the crash drill. Call :meth:`handoff_dead` next to
        re-route the orphaned journals; until then failed-over deltas
        ride the client's bounded handoff-wait rung."""
        p = self.procs[index]
        self._driver_takedown(p)
        if p.popen is not None:
            p.popen.kill()
            p.popen.wait(timeout=30)
        self.drop_endpoint(p.address)
        return p

    def kill_unannounced(self, index: int) -> ManagedProc:
        """SIGKILL withOUT the driver-takedown bookkeeping: the process
        dies but the fleet still believes it is alive — the crash-shaped
        cousin of the zombie drill's SIGSTOP. Heartbeats stop, the
        detector promotes suspect -> dead, and the full autonomous
        ejection (:meth:`_eject`: topology bump, fence supersession,
        journal re-route) runs on EVIDENCE, not on a driver script. Use
        with the detector armed; a plain :meth:`kill` removes the corpse
        from the detector's watch and owns the handoff itself."""
        p = self.procs[index]
        if p.popen is not None:
            p.popen.kill()
            p.popen.wait(timeout=30)
        return p

    def drain(self, index: int, timeout_s: float = 60.0) -> ManagedProc:
        """SIGTERM — graceful drain (flush journals, exit 0)."""
        p = self.procs[index]
        self._driver_takedown(p)
        if p.popen is not None:
            p.popen.terminate()
            p.popen.wait(timeout=timeout_s)
        self.drop_endpoint(p.address)
        return p

    def pause(self, index: int) -> ManagedProc:
        """SIGSTOP — the zombie drill's gray failure: every thread in
        the target freezes mid-instruction (locks held, deltas parked),
        the TCP sockets stay open, and nothing exits. The detector must
        classify this DEAD and eject; :meth:`resume` later releases the
        zombie, whose fence is by then superseded."""
        p = self.procs[index]
        if p.popen is not None:
            p.popen.send_signal(signal.SIGSTOP)
        return p

    def resume(self, index: int) -> ManagedProc:
        """SIGCONT — release a paused process. An ejected zombie that
        resumes finds its journal-namespace fence superseded: parked
        deltas are answered ``moved:``, flushes refuse, no tick it acks
        can double-apply."""
        p = self.procs[index]
        if p.popen is not None:
            p.popen.send_signal(signal.SIGCONT)
        return p

    def handoff_dead(self, index: int) -> list:
        """Re-route a dead (or ejected-while-paused) process's orphaned
        journals along the CURRENT ring (call after :meth:`kill`/
        :meth:`drain`; :meth:`_eject` calls it autonomously). Atomic
        renames: each journal lands in exactly one survivor's
        namespace, chosen by the same hash walk the clients fail over
        by. The source namespace's fence is superseded FIRST (stamped
        with the post-ejection ring), so even a source that was merely
        WEDGED — not dead — can never flush or ack again; torn journals
        are skipped with a counted warning (``last_handoff_stats``)."""
        from protocol_tpu.faults.checkpoint import handoff_orphans

        p = self.procs[index]
        if p.alive:
            raise RuntimeError(
                f"refusing to hand off journals of LIVE proc "
                f"{p.proc_id} — it would flush right back"
            )
        topo = self.topology
        stats: dict = {}
        moved = handoff_orphans(
            self.journal_root, p.proc_id,
            lambda sid: topo.procs[topo.endpoint_for(sid)],
            topology=topo.to_dict(),
            stats=stats,
        )
        self.last_handoff_stats = stats
        return moved

    # ---------------- autonomous failure detection ----------------

    def start_detector(
        self,
        period_s: float = 0.25,
        probe_timeout_s: float = 1.0,
        config=None,
    ) -> None:
        """Arm the heartbeat failure detector: a daemon thread samples
        every live process's Health RPC each ``period_s``, feeds the
        deterministic :class:`~protocol_tpu.dfleet.detector.
        FailureDetector` (which owns no clock — this thread is the
        clock), and on DEAD runs the full autonomous ejection:
        :meth:`_eject` → topology generation bump (discovery serves the
        new ring), fence supersession, journal re-route. Driver-killed
        processes (``alive=False``) are REMOVED from the detector, so a
        scripted kill never counts as a detector ejection (the
        false-positive ledger stays honest)."""
        from protocol_tpu.dfleet.detector import FailureDetector
        from protocol_tpu.services.scheduler_grpc import (
            SchedulerBackendClient,
        )

        if self._detector_thread is not None:
            return
        self.detector = FailureDetector(
            [p.proc_id for p in self.procs if p.alive], config=config
        )
        stop = threading.Event()
        self._detector_stop = stop

        def _monitor():
            clients: dict = {}
            try:
                while not stop.is_set():
                    for p in list(self.procs):
                        if stop.is_set():
                            break
                        if not p.alive:
                            # driver-owned deaths were already
                            # detector.remove()d in _driver_takedown;
                            # a DETECTOR-ejected proc keeps its DEAD
                            # record (its flaps stay in the totals and
                            # a resumed zombie's late beats land as
                            # zombie_beats — counted, never believed)
                            if p.proc_id in self._detector_ejected:
                                c = clients.get(p.proc_id)
                                if c is None:
                                    c = SchedulerBackendClient(
                                        p.address
                                    )
                                    clients[p.proc_id] = c
                                try:
                                    c.health(timeout=probe_timeout_s)
                                    self.detector.heartbeat(
                                        p.proc_id, time.perf_counter()
                                    )
                                except Exception:
                                    pass
                                continue
                            self.detector.remove(p.proc_id)
                            stale = clients.pop(p.proc_id, None)
                            if stale is not None:
                                try:
                                    stale.close()
                                except Exception:
                                    pass
                            continue
                        c = clients.get(p.proc_id)
                        if c is None:
                            c = SchedulerBackendClient(p.address)
                            clients[p.proc_id] = c
                        try:
                            c.health(timeout=probe_timeout_s)
                            self.detector.heartbeat(
                                p.proc_id, time.perf_counter()
                            )
                        except Exception:
                            self.detector.probe_failed(
                                p.proc_id, time.perf_counter()
                            )
                            # fresh channel next round: a wedged HTTP/2
                            # connection must not mask a recovered proc
                            clients.pop(p.proc_id, None)
                            try:
                                c.close()
                            except Exception:
                                pass
                    for dead_pid in self.detector.evaluate(
                        time.perf_counter()
                    ):
                        self._eject(dead_pid)
                    stop.wait(period_s)
            finally:
                for c in clients.values():
                    try:
                        c.close()
                    except Exception:
                        pass

        self._detector_thread = threading.Thread(
            target=_monitor, name="dfleet-detector", daemon=True
        )
        self._detector_thread.start()

    def stop_detector(self) -> None:
        if self._detector_stop is not None:
            self._detector_stop.set()
        if self._detector_thread is not None:
            self._detector_thread.join(timeout=10)
            self._detector_thread = None
            self._detector_stop = None

    def _eject(self, proc_id: str) -> Optional[dict]:
        """The autonomous ejection path (detector-owned; a scripted
        kill/drain never lands here): mark the process dead to the
        fleet, bump the topology generation (the discovery tier serves
        the new ring on its next poll), supersede its journal fence,
        and re-route its journals along the surviving ring — the exact
        machinery the driver used to invoke by hand, now invoked by
        evidence."""
        p = next(
            (q for q in self.procs if q.proc_id == str(proc_id)), None
        )
        if p is None:
            return None
        with self._lock:
            if not p.alive:
                return None  # driver got there first (kill/drain race)
            p.alive = False
        self.drop_endpoint(p.address)
        moved = self.handoff_dead(p.index)
        event = {
            "proc": p.proc_id,
            "at": time.perf_counter(),
            "journals_rerouted": len(moved),
            "journals_skipped": self.last_handoff_stats.get(
                "journals_skipped", 0
            ),
            "generation": self.topology.generation,
        }
        with self._lock:
            self.ejections.append(event)
            self._detector_ejected.add(p.proc_id)
        return event

    def migrate_all(
        self, src_index: int, dst_index: Optional[int] = None
    ) -> int:
        """LIVE migration: every session on ``src`` moves to ``dst``
        (default: the ring successor of the source's address) via the
        Migrate RPC. The source stays up redirecting; returns the
        number of sessions moved."""
        from protocol_tpu.proto import scheduler_pb2 as pb
        from protocol_tpu.services.scheduler_grpc import (
            SchedulerBackendClient,
        )

        src = self.procs[src_index]
        if dst_index is None:
            order = self.topology.without(src.address)
            dst_addr = order.endpoints[0] if len(
                order.endpoints) == 1 else order.endpoint_for(src.address)
            dst = next(
                p for p in self.procs if p.address == dst_addr
            )
        else:
            dst = self.procs[dst_index]
        client = SchedulerBackendClient(src.address)
        try:
            resp = client.migrate(pb.MigrateRequest(
                target_endpoint=dst.address,
                target_proc_id=dst.proc_id,
            ))
        finally:
            client.close()
        if not resp.ok:
            raise RuntimeError(f"migrate refused: {resp.error}")
        return int(resp.moved)

    # ---------------- observability ----------------

    def scrape(self) -> dict:
        """Per-process ``/metrics.json`` join: {proc_id: snapshot}.
        Dead or unreachable processes report ``None`` (the gate treats
        an EXPECTED corpse as fine and a silent one as a failure)."""
        out = {}
        for p in self.procs:
            if not p.alive or not p.metrics_port:
                out[p.proc_id] = None
                continue
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{p.metrics_port}/metrics.json",
                    timeout=10,
                ) as r:
                    out[p.proc_id] = json.loads(r.read().decode())
            except Exception:
                out[p.proc_id] = None
        return out

    def stream_rollup(self, scrapes=None) -> dict:
        """Fleet-wide join of the per-session ``"stream"`` metrics
        sections the batch scrape join ignores (ISSUE 20 satellite):
        events / dedup / reconcile / divergence counters summed across
        processes, latency p99 fleet-max. Pass a saved ``scrape()``
        result to roll up a point-in-time snapshot (e.g. one taken
        BEFORE draining the survivors)."""
        from protocol_tpu.dstream.rollup import stream_rollup

        return stream_rollup(
            self.scrape() if scrapes is None else scrapes
        )

    def witness_violations(self) -> dict:
        """Per-process lock-witness verdicts dumped at drain/exit
        (``witness_<proc>.json``; a SIGKILLed process leaves none —
        the survivors cover the migration/rehydrate paths)."""
        out = {}
        for p in self.procs:
            path = os.path.join(
                self.journal_root, f"witness_{p.proc_id}.json"
            )
            try:
                with open(path) as fh:
                    out[p.proc_id] = json.load(fh)
            except (OSError, ValueError):
                # missing (SIGKILLed before dumping) or truncated
                # (killed mid-dump): no verdict, not a crash here
                continue
        return out

    def stop(self) -> None:
        self.stop_detector()
        for p in self.procs:
            # kill by PROCESS liveness, not the alive flag: an ejected
            # zombie (alive=False, still running — possibly still
            # SIGSTOPped) must not outlive the fleet. SIGKILL
            # terminates stopped processes too.
            if p.popen is not None and p.popen.poll() is None:
                p.popen.kill()
                try:
                    p.popen.wait(timeout=30)
                except Exception:
                    pass
            p.alive = False
        if self.discovery is not None:
            self.discovery.stop()
        if self._tmp is not None:
            self._tmp.cleanup()

    def __enter__(self) -> "ProcessFleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
