"""Consistent-hash session->process routing for the distributed fleet.

The same sha1 ring the in-process :class:`SessionFabric` shards by,
lifted one level: keys are session ids, values are PROCESS endpoints.
Decoupling workload placement (which process serves a session) from the
workload itself (the session's journal, portable by construction) is
the VirtualFlow argument applied to the scheduler seam — and because
both layers hash the same way, a session's in-process shard is stable
regardless of which process it lands on.

The ring is immutable; membership change builds a NEW topology with a
bumped ``generation`` (:meth:`without` / :meth:`with_endpoint`), so a
topology object can be shared across threads without locking and a
stale client can detect it is routing on an old view. ``failover_order``
is the client ladder's endpoint list: the ring walk from the session's
position, deduplicated — the first entry is the session's home, the
rest are where its journal will be re-routed if the home dies (the
manager's orphan handoff uses the same walk, so client failover and
journal re-routing agree by construction).
"""

from __future__ import annotations

import bisect
from typing import Optional

# THE ring hash — imported from the in-process fabric, not copied: the
# "both layers hash the same way" shard-stability claim is an import,
# not a convention a future edit can silently break
from protocol_tpu.fleet.fabric import _h


class FleetTopology:
    """Immutable endpoint ring. ``procs`` maps endpoint -> proc id (the
    checkpoint-journal namespace that process owns)."""

    def __init__(
        self,
        endpoints: list,
        procs: Optional[dict] = None,
        vnodes: int = 64,
        generation: int = 0,
    ):
        self.endpoints = [str(e) for e in endpoints]
        if not self.endpoints:
            raise ValueError("topology needs at least one endpoint")
        if len(set(self.endpoints)) != len(self.endpoints):
            raise ValueError("duplicate endpoints in topology")
        self.procs = dict(procs) if procs else {
            e: f"p{i}" for i, e in enumerate(self.endpoints)
        }
        for e in self.endpoints:
            if e not in self.procs:
                raise ValueError(f"endpoint {e!r} has no proc id")
        self.vnodes = max(1, int(vnodes))
        self.generation = int(generation)
        ring = sorted(
            (_h(f"{e}/vnode-{j}"), i)
            for i, e in enumerate(self.endpoints)
            for j in range(self.vnodes)
        )
        self._ring_keys = [k for k, _ in ring]
        self._ring_idx = [i for _, i in ring]

    # ---------------- routing ----------------

    def endpoint_for(self, session_id: str) -> str:
        i = bisect.bisect_right(self._ring_keys, _h(session_id))
        return self.endpoints[self._ring_idx[i % len(self._ring_idx)]]

    def proc_for(self, session_id: str) -> str:
        return self.procs[self.endpoint_for(session_id)]

    def failover_order(self, session_id: str) -> list:
        """Ordered endpoint list for one session: home first, then the
        ring successors (deduplicated) — the client's failover ladder
        AND the journal re-route order, one walk for both."""
        start = bisect.bisect_right(self._ring_keys, _h(session_id))
        seen: list = []
        n = len(self._ring_idx)
        for step in range(n):
            ep = self.endpoints[self._ring_idx[(start + step) % n]]
            if ep not in seen:
                seen.append(ep)
                if len(seen) == len(self.endpoints):
                    break
        return seen

    # ---------------- membership (copy-on-change) ----------------

    def without(self, endpoint: str) -> "FleetTopology":
        """New topology with ``endpoint`` removed and the generation
        bumped (a killed/drained process). ~1/N of the sessions re-home
        to their ring successor; everyone else keeps their placement —
        the consistent-hash property the journal handoff relies on to
        move only the dead process's sessions."""
        remaining = [e for e in self.endpoints if e != endpoint]
        return FleetTopology(
            remaining,
            procs={e: self.procs[e] for e in remaining},
            vnodes=self.vnodes,
            generation=self.generation + 1,
        )

    def with_endpoint(
        self, endpoint: str, proc_id: str
    ) -> "FleetTopology":
        """New topology with ``endpoint`` added (scale-out / a replaced
        process coming back)."""
        if endpoint in self.endpoints:
            raise ValueError(f"endpoint {endpoint!r} already present")
        procs = dict(self.procs)
        procs[endpoint] = str(proc_id)
        return FleetTopology(
            self.endpoints + [endpoint],
            procs=procs,
            vnodes=self.vnodes,
            generation=self.generation + 1,
        )

    # ---------------- wire form (the discovery payload) ----------------

    def to_dict(self) -> dict:
        return {
            "generation": self.generation,
            "endpoints": list(self.endpoints),
            "procs": dict(self.procs),
            "vnodes": self.vnodes,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "FleetTopology":
        return cls(
            d["endpoints"],
            procs=d.get("procs"),
            vnodes=d.get("vnodes", 64),
            generation=d.get("generation", 0),
        )
