"""Discovery tier: the endpoint map, served over HTTP.

Mirrors the reference's discovery-service/orchestrator split: a worker
asks discovery where its pool lives, then speaks the pool protocol
there. Here a scheduler client asks ``/route?session=<sid>`` for the
session's home endpoint plus its ordered failover list (or fetches the
whole map from ``/fleet.json`` and routes client-side via
:class:`~protocol_tpu.dfleet.topology.FleetTopology` — same ring, same
answer). The payload carries the topology ``generation`` so a client
can tell a stale cached map from a fresh one after a membership change.

Same daemon-threaded ``ThreadingHTTPServer`` idiom as the obs
``/metrics`` endpoint — no new dependencies, and a scrape/debug surface
for free. The topology is read through a zero-arg callable so the
manager can swap in a new (immutable) topology on membership change
without any locking here.

Routes::

    /fleet.json            the full topology (endpoints, procs, generation)
    /route?session=<sid>   {"endpoint", "failover", "generation"}
    /healthz               liveness probe
"""

from __future__ import annotations

import json
import threading
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable

from protocol_tpu.dfleet.topology import FleetTopology


class DiscoveryEndpoint:
    """Serve one fleet's topology. ``topology_fn`` returns the CURRENT
    immutable :class:`FleetTopology` (the manager rebinds it on
    membership change)."""

    def __init__(
        self,
        topology_fn: Callable[[], FleetTopology],
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        self.topology_fn = topology_fn
        endpoint = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet: routing is periodic
                pass

            def _send(self, code: int, payload: dict) -> None:
                body = json.dumps(payload, sort_keys=True).encode()
                self.send_response(code)
                self.send_header(
                    "Content-Type", "application/json; charset=utf-8"
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                parsed = urllib.parse.urlparse(self.path)
                topo = endpoint.topology_fn()
                if parsed.path == "/fleet.json":
                    self._send(200, topo.to_dict())
                    return
                if parsed.path == "/route":
                    q = urllib.parse.parse_qs(parsed.query)
                    sid = (q.get("session") or [""])[0]
                    if not sid:
                        self._send(
                            400, {"error": "session query param required"}
                        )
                        return
                    self._send(200, {
                        "session": sid,
                        "endpoint": topo.endpoint_for(sid),
                        "failover": topo.failover_order(sid),
                        "generation": topo.generation,
                    })
                    return
                if parsed.path == "/healthz":
                    self._send(200, {"status": "ok"})
                    return
                self._send(404, {"error": f"no route {parsed.path!r}"})

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="dfleet-discovery",
            daemon=True,
        )
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


def fetch_topology(
    url: str,
    timeout: float = 10.0,
    current: "FleetTopology | None" = None,
) -> FleetTopology:
    """Client bootstrap/poll: fetch the fleet map from a discovery
    endpoint (``url`` is the endpoint base, e.g.
    ``http://127.0.0.1:8123``). GENERATION-MONOTONIC when ``current``
    is given: a fetched map whose generation is not strictly newer
    than the one already held is DISCARDED and ``current`` returned
    unchanged — a stale poll (a lagging discovery replica, a response
    that raced a detector ejection) must lose to the membership change
    it is stale against."""
    with urllib.request.urlopen(
        f"{url.rstrip('/')}/fleet.json", timeout=timeout
    ) as r:
        fetched = FleetTopology.from_dict(json.loads(r.read().decode()))
    if current is not None and fetched.generation <= current.generation:
        return current
    return fetched
