"""Orchestrator node store.

Reference: crates/orchestrator/src/store/domains/node_store.rs (hash per node
``orchestrator:node:{addr}`` + index set) and crates/orchestrator/src/models/
node.rs (OrchestratorNode, 8-state NodeStatus enum :74-85).
"""

from __future__ import annotations

import enum
import json
import time
from dataclasses import dataclass, field
from typing import Optional

from protocol_tpu.models.node import ComputeSpecs, NodeLocation
from protocol_tpu.models.task import TaskState
from protocol_tpu.store.kv import KVStore

NODE_KEY = "orchestrator:node:{}"
NODE_INDEX = "orchestrator:nodes"


class NodeStatus(str, enum.Enum):
    """Health FSM states (reference orchestrator/src/models/node.rs:74-85)."""

    DISCOVERED = "Discovered"
    WAITING_FOR_HEARTBEAT = "WaitingForHeartbeat"
    HEALTHY = "Healthy"
    UNHEALTHY = "Unhealthy"
    DEAD = "Dead"
    EJECTED = "Ejected"
    BANNED = "Banned"
    LOW_BALANCE = "LowBalance"

    @classmethod
    def parse(cls, s: str) -> "NodeStatus":
        for m in cls:
            if m.value == s:
                return m
        return cls.DISCOVERED


@dataclass
class OrchestratorNode:
    address: str
    ip_address: str = ""
    port: int = 0
    status: NodeStatus = NodeStatus.DISCOVERED
    task_id: Optional[str] = None
    task_state: Optional[TaskState] = None
    version: Optional[str] = None
    p2p_id: Optional[str] = None
    p2p_addresses: Optional[list[str]] = None
    compute_specs: Optional[ComputeSpecs] = None
    location: Optional[NodeLocation] = None
    first_seen: float = field(default_factory=time.time)
    last_status_change: Optional[float] = None
    # marketplace inputs to the batch matcher's cost terms: the provider's
    # advertised ask price (from discovery) and its self-reported host
    # utilization 0..1 (from heartbeats — external to this pool's own
    # assignment, so the load term cannot feed back into the solve)
    price: Optional[float] = None
    load: float = 0.0

    def to_dict(self) -> dict:
        d: dict = {
            "address": self.address,
            "ip_address": self.ip_address,
            "port": self.port,
            "status": self.status.value,
            "first_seen": self.first_seen,
        }
        if self.task_id is not None:
            d["task_id"] = self.task_id
        if self.task_state is not None:
            d["task_state"] = self.task_state.value
        if self.version is not None:
            d["version"] = self.version
        if self.p2p_id is not None:
            d["p2p_id"] = self.p2p_id
        if self.p2p_addresses is not None:
            d["p2p_addresses"] = self.p2p_addresses
        if self.compute_specs is not None:
            d["compute_specs"] = self.compute_specs.to_dict()
        if self.location is not None:
            d["location"] = self.location.to_dict()
        if self.last_status_change is not None:
            d["last_status_change"] = self.last_status_change
        if self.price is not None:
            d["price"] = self.price
        if self.load:
            d["load"] = self.load
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "OrchestratorNode":
        return cls(
            address=d["address"],
            ip_address=d.get("ip_address", ""),
            port=int(d.get("port", 0)),
            status=NodeStatus.parse(d.get("status", "Discovered")),
            task_id=d.get("task_id"),
            task_state=TaskState.parse(d["task_state"]) if d.get("task_state") else None,
            version=d.get("version"),
            p2p_id=d.get("p2p_id"),
            p2p_addresses=d.get("p2p_addresses"),
            compute_specs=ComputeSpecs.from_dict(d["compute_specs"])
            if d.get("compute_specs")
            else None,
            location=NodeLocation.from_dict(d["location"]) if d.get("location") else None,
            first_seen=float(d.get("first_seen", 0.0)),
            last_status_change=d.get("last_status_change"),
            price=float(d["price"]) if d.get("price") is not None else None,
            load=float(d.get("load", 0.0)),
        )


class NodeStore:
    def __init__(self, kv: KVStore):
        self.kv = kv

    def add_node(self, node: OrchestratorNode) -> None:
        with self.kv.atomic():
            self.kv.set(NODE_KEY.format(node.address), json.dumps(node.to_dict()))
            self.kv.sadd(NODE_INDEX, node.address)

    def get_node(self, address: str) -> Optional[OrchestratorNode]:
        raw = self.kv.get(NODE_KEY.format(address))
        return OrchestratorNode.from_dict(json.loads(raw)) if raw else None

    def get_nodes(self) -> list[OrchestratorNode]:
        addrs = sorted(self.kv.smembers(NODE_INDEX))
        raws = self.kv.mget(NODE_KEY.format(a) for a in addrs)
        return [OrchestratorNode.from_dict(json.loads(r)) for r in raws if r]

    def remove_node(self, address: str) -> None:
        with self.kv.atomic():
            self.kv.delete(NODE_KEY.format(address))
            self.kv.srem(NODE_INDEX, address)

    def update_node(self, node: OrchestratorNode) -> None:
        self.add_node(node)

    def update_node_status(self, address: str, status: NodeStatus) -> None:
        """Status transition, stamping last_status_change (reference
        node_store.rs update path)."""
        with self.kv.atomic():
            node = self.get_node(address)
            if node is None:
                return
            if node.status != status:
                node.status = status
                node.last_status_change = time.time()
                self.add_node(node)

    def update_node_task(
        self,
        address: str,
        task_id: Optional[str],
        task_state: Optional[TaskState],
    ) -> None:
        with self.kv.atomic():
            node = self.get_node(address)
            if node is None:
                return
            node.task_id = task_id
            node.task_state = task_state
            self.add_node(node)

    def update_node_p2p(
        self, address: str, p2p_id: Optional[str], p2p_addresses: Optional[list[str]]
    ) -> None:
        with self.kv.atomic():
            node = self.get_node(address)
            if node is None:
                return
            node.p2p_id = p2p_id
            node.p2p_addresses = p2p_addresses
            self.add_node(node)

    def get_uninvited_nodes(self) -> list[OrchestratorNode]:
        """Nodes awaiting an invite (reference node/invite.rs: Discovered)."""
        return [n for n in self.get_nodes() if n.status == NodeStatus.DISCOVERED]
