"""Heartbeat store: TTL'd beats + unhealthy counters.

Reference: crates/orchestrator/src/store/domains/heartbeat_store.rs —
beat key with 90 s expiry (:31-35) and per-node unhealthy counters consumed
by the status-update FSM.
"""

from __future__ import annotations

import json
from typing import Optional

from protocol_tpu.models.heartbeat import HeartbeatRequest
from protocol_tpu.store.kv import KVStore

BEAT_KEY = "orchestrator:heartbeat:{}"
UNHEALTHY_KEY = "orchestrator:unhealthy_counter:{}"

DEFAULT_TTL_SECONDS = 90.0


class HeartbeatStore:
    def __init__(self, kv: KVStore, ttl_seconds: float = DEFAULT_TTL_SECONDS):
        self.kv = kv
        self.ttl = ttl_seconds

    def beat(self, hb: HeartbeatRequest) -> None:
        self.kv.set(BEAT_KEY.format(hb.address), json.dumps(hb.to_dict()), ex=self.ttl)

    def get_heartbeat(self, address: str) -> Optional[HeartbeatRequest]:
        raw = self.kv.get(BEAT_KEY.format(address))
        return HeartbeatRequest.from_dict(json.loads(raw)) if raw else None

    def clear_heartbeat(self, address: str) -> None:
        self.kv.delete(BEAT_KEY.format(address))

    # ----- unhealthy counters (status_update/mod.rs miss counting)

    def increment_unhealthy_counter(self, address: str) -> int:
        return self.kv.incr(UNHEALTHY_KEY.format(address))

    def get_unhealthy_counter(self, address: str) -> int:
        raw = self.kv.get(UNHEALTHY_KEY.format(address))
        return int(raw) if raw else 0

    def clear_unhealthy_counter(self, address: str) -> None:
        self.kv.delete(UNHEALTHY_KEY.format(address))
