"""Domain stores over the KV schema (reference: orchestrator/src/store/domains/)."""
