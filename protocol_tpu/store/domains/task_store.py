"""Task store with observer hooks.

Reference: crates/orchestrator/src/store/domains/task_store.rs — task blob
per id + id list + name-uniqueness set + observer hooks that the node-groups
plugin uses to enable/disable topologies on task create/delete (:11-55).
"""

from __future__ import annotations

from typing import Callable, Optional

from protocol_tpu.models.task import Task
from protocol_tpu.store.kv import KVStore

TASK_KEY = "orchestrator:task:{}"
TASK_LIST = "orchestrator:tasks"
TASK_NAMES = "orchestrator:task_names"

TaskObserver = Callable[[Task], None]


class TaskStore:
    def __init__(self, kv: KVStore):
        self.kv = kv
        self._on_created: list[TaskObserver] = []
        self._on_deleted: list[TaskObserver] = []

    # ----- observers (reference task_store.rs observer hooks)

    def subscribe_created(self, fn: TaskObserver) -> None:
        self._on_created.append(fn)

    def subscribe_deleted(self, fn: TaskObserver) -> None:
        self._on_deleted.append(fn)

    # ----- CRUD

    def add_task(self, task: Task) -> None:
        """Stores the task; name uniqueness is enforced at the API layer
        (orchestrator/src/api/routes/task.rs:46-58) via ``name_exists``."""
        with self.kv.atomic():
            self.kv.set(TASK_KEY.format(task.id), task.to_json())
            self.kv.rpush(TASK_LIST, task.id)
            self.kv.sadd(TASK_NAMES, task.name)
        for fn in self._on_created:
            fn(task)

    def name_exists(self, name: str) -> bool:
        return self.kv.sismember(TASK_NAMES, name)

    def get_task(self, task_id: str) -> Optional[Task]:
        raw = self.kv.get(TASK_KEY.format(task_id))
        return Task.from_json(raw) if raw else None

    def get_all_tasks(self) -> list[Task]:
        ids = self.kv.lrange(TASK_LIST)
        raws = self.kv.mget(TASK_KEY.format(i) for i in ids)
        return [Task.from_json(r) for r in raws if r]

    def update_task(self, task: Task) -> None:
        self.kv.set(TASK_KEY.format(task.id), task.to_json())

    def delete_task(self, task_id: str) -> Optional[Task]:
        with self.kv.atomic():
            task = self.get_task(task_id)
            if task is None:
                return None
            self.kv.delete(TASK_KEY.format(task_id))
            self.kv.lrem(TASK_LIST, 0, task_id)
            self.kv.srem(TASK_NAMES, task.name)
        for fn in self._on_deleted:
            fn(task)
        return task

    def delete_all(self) -> None:
        for t in self.get_all_tasks():
            self.delete_task(t.id)
