"""Metrics store: per (task, label) hashes of node -> value.

Reference: crates/orchestrator/src/store/domains/metrics_store.rs. Metrics
flow worker TaskBridge -> heartbeat -> here -> Prometheus sync.
"""

from __future__ import annotations

from protocol_tpu.models.metric import MetricEntry
from protocol_tpu.store.kv import KVStore

METRIC_KEY = "orchestrator:metrics:{}:{}"  # task_id, label
METRIC_INDEX = "orchestrator:metrics_keys"


class MetricsStore:
    def __init__(self, kv: KVStore):
        self.kv = kv

    def store_metrics(self, entries: list[MetricEntry], node_address: str) -> None:
        # one pipelined batch: N metric entries cost one round trip on a
        # remote store instead of a lock + 2N calls
        ops = []
        for e in entries:
            key = METRIC_KEY.format(e.key.task_id, e.key.label)
            ops.append(("hset", [key, node_address, repr(e.value)], {}))
            ops.append(
                ("sadd", [METRIC_INDEX, f"{e.key.task_id}\x00{e.key.label}"], {})
            )
        if ops:
            self.kv.pipeline_execute(ops)

    def get_metrics_for_task(self, task_id: str) -> dict[str, dict[str, float]]:
        """label -> {node -> value}"""
        out: dict[str, dict[str, float]] = {}
        for entry in self.kv.smembers(METRIC_INDEX):
            tid, label = entry.split("\x00", 1)
            if tid != task_id:
                continue
            vals = self.kv.hgetall(METRIC_KEY.format(tid, label))
            out[label] = {n: float(v) for n, v in vals.items()}
        return out

    def get_all_metrics(self) -> dict[str, dict[str, dict[str, float]]]:
        """task_id -> label -> {node -> value}"""
        out: dict[str, dict[str, dict[str, float]]] = {}
        for entry in self.kv.smembers(METRIC_INDEX):
            tid, label = entry.split("\x00", 1)
            vals = self.kv.hgetall(METRIC_KEY.format(tid, label))
            out.setdefault(tid, {})[label] = {n: float(v) for n, v in vals.items()}
        return out

    def delete_metrics_for_node(self, node_address: str) -> None:
        """Purge a dead/ejected/banned node's metrics
        (status_update/mod.rs:314-350)."""
        with self.kv.atomic():
            for entry in list(self.kv.smembers(METRIC_INDEX)):
                tid, label = entry.split("\x00", 1)
                key = METRIC_KEY.format(tid, label)
                self.kv.hdel(key, node_address)
                if not self.kv.hgetall(key):
                    self.kv.srem(METRIC_INDEX, entry)

    def delete_metrics_for_task(self, task_id: str) -> None:
        with self.kv.atomic():
            for entry in list(self.kv.smembers(METRIC_INDEX)):
                tid, label = entry.split("\x00", 1)
                if tid == task_id:
                    self.kv.delete(METRIC_KEY.format(tid, label))
                    self.kv.srem(METRIC_INDEX, entry)
