"""In-process KV store with Redis semantics.

The reference talks to a real Redis from every service and spawns an embedded
redis-server per test (orchestrator/src/store/core/redis.rs:38-72). This
framework's state fits one coordinating process per pool (as the reference's
one-orchestrator-per-pool deployment does), so the store is in-process:
a thread-safe dict engine implementing exactly the Redis subset the control
plane uses —

  strings   get / set (NX, EX) / mget / incr / delete / exists / expire
  hashes    hset / hget / hgetall / hdel / hincrby
  sets      sadd / srem / smembers / sismember / scard
  zsets     zadd / zscore / zrem / zrangebyscore / zremrangebyscore / zcard
  lists     rpush / lpush / lrange / lrem / llen
  pipeline  atomic multi-op batch under one lock (the reference's pipelines
            and SET-NX races map onto this)

Lazy TTL expiry against a monotonic clock; a ``time_fn`` hook makes expiry
deterministic in tests. Keys are strings, values are strings (callers do
their own JSON), mirroring the wire-level Redis model so a networked Redis
backend could be slotted in behind the same interface later.
"""

from __future__ import annotations

import fnmatch
import threading
import time
from typing import Callable, Iterable, Optional


class KVStore:
    def __init__(self, time_fn: Callable[[], float] = time.monotonic):
        self._lock = threading.RLock()
        self._data: dict[str, object] = {}
        self._expiry: dict[str, float] = {}
        self._time = time_fn

    # ------------- internals -------------

    def _expired(self, key: str) -> bool:
        exp = self._expiry.get(key)
        if exp is not None and self._time() >= exp:
            self._data.pop(key, None)
            self._expiry.pop(key, None)
            return True
        return False

    def _get_typed(self, key: str, typ: type, create: bool = False):
        if self._expired(key):
            val = None
        else:
            val = self._data.get(key)
        if val is None:
            if not create:
                return None
            val = typ()
            self._data[key] = val
            self._expiry.pop(key, None)
        if not isinstance(val, typ):
            raise TypeError(f"WRONGTYPE key {key!r} holds {type(val).__name__}")
        return val

    # ------------- strings -------------

    def set(
        self,
        key: str,
        value: str,
        nx: bool = False,
        ex: Optional[float] = None,
    ) -> bool:
        with self._lock:
            self._expired(key)
            if nx and key in self._data:
                return False
            self._data[key] = str(value)
            if ex is not None:
                self._expiry[key] = self._time() + ex
            else:
                self._expiry.pop(key, None)
            return True

    def get(self, key: str) -> Optional[str]:
        with self._lock:
            v = self._get_typed(key, str)
            return v

    def mget(self, keys: Iterable[str]) -> list[Optional[str]]:
        with self._lock:
            return [self._get_typed(k, str) for k in keys]

    def incr(self, key: str, amount: int = 1) -> int:
        with self._lock:
            cur = self._get_typed(key, str)
            val = int(cur) + amount if cur is not None else amount
            self._data[key] = str(val)
            return val

    def delete(self, *keys: str) -> int:
        with self._lock:
            n = 0
            for key in keys:
                self._expired(key)
                if key in self._data:
                    del self._data[key]
                    self._expiry.pop(key, None)
                    n += 1
            return n

    def exists(self, key: str) -> bool:
        with self._lock:
            self._expired(key)
            return key in self._data

    def expire(self, key: str, seconds: float) -> bool:
        with self._lock:
            self._expired(key)
            if key not in self._data:
                return False
            self._expiry[key] = self._time() + seconds
            return True

    def ttl(self, key: str) -> Optional[float]:
        """Remaining TTL; None if no key or no expiry."""
        with self._lock:
            self._expired(key)
            if key not in self._data:
                return None
            exp = self._expiry.get(key)
            return None if exp is None else max(0.0, exp - self._time())

    def keys(self, pattern: str = "*") -> list[str]:
        with self._lock:
            return [k for k in list(self._data) if not self._expired(k) and fnmatch.fnmatch(k, pattern)]

    def flushall(self) -> None:
        with self._lock:
            self._data.clear()
            self._expiry.clear()

    # ------------- hashes -------------

    def hset(self, key: str, field: str, value: str) -> int:
        with self._lock:
            h = self._get_typed(key, dict, create=True)
            is_new = field not in h
            h[field] = str(value)
            return int(is_new)

    def hset_mapping(self, key: str, mapping: dict[str, str]) -> int:
        with self._lock:
            h = self._get_typed(key, dict, create=True)
            n = sum(1 for f in mapping if f not in h)
            h.update({f: str(v) for f, v in mapping.items()})
            return n

    def hget(self, key: str, field: str) -> Optional[str]:
        with self._lock:
            h = self._get_typed(key, dict)
            return None if h is None else h.get(field)

    def hgetall(self, key: str) -> dict[str, str]:
        with self._lock:
            h = self._get_typed(key, dict)
            return dict(h) if h else {}

    def hdel(self, key: str, *fields: str) -> int:
        with self._lock:
            h = self._get_typed(key, dict)
            if not h:
                return 0
            n = 0
            for f in fields:
                if f in h:
                    del h[f]
                    n += 1
            if not h:
                self.delete(key)
            return n

    def hincrby(self, key: str, field: str, amount: int = 1) -> int:
        with self._lock:
            h = self._get_typed(key, dict, create=True)
            val = int(h.get(field, "0")) + amount
            h[field] = str(val)
            return val

    # ------------- sets -------------

    def sadd(self, key: str, *members: str) -> int:
        with self._lock:
            s = self._get_typed(key, set, create=True)
            n = len(members) - len(s.intersection(members))
            s.update(str(m) for m in members)
            return n

    def srem(self, key: str, *members: str) -> int:
        with self._lock:
            s = self._get_typed(key, set)
            if not s:
                return 0
            n = len(s.intersection(members))
            s.difference_update(members)
            if not s:
                self.delete(key)
            return n

    def smembers(self, key: str) -> set[str]:
        with self._lock:
            s = self._get_typed(key, set)
            return set(s) if s else set()

    def sismember(self, key: str, member: str) -> bool:
        with self._lock:
            s = self._get_typed(key, set)
            return bool(s) and member in s

    def scard(self, key: str) -> int:
        with self._lock:
            s = self._get_typed(key, set)
            return len(s) if s else 0

    # ------------- sorted sets -------------

    def zadd(self, key: str, mapping: dict[str, float]) -> int:
        with self._lock:
            z = self._get_typed(key, dict, create=True)
            n = sum(1 for m in mapping if m not in z)
            z.update({str(m): float(s) for m, s in mapping.items()})
            return n

    def zscore(self, key: str, member: str) -> Optional[float]:
        with self._lock:
            z = self._get_typed(key, dict)
            return None if z is None else z.get(member)

    def zrem(self, key: str, *members: str) -> int:
        with self._lock:
            z = self._get_typed(key, dict)
            if not z:
                return 0
            n = 0
            for m in members:
                if m in z:
                    del z[m]
                    n += 1
            if not z:
                self.delete(key)
            return n

    def zrangebyscore(
        self, key: str, min_score: float = float("-inf"), max_score: float = float("inf")
    ) -> list[tuple[str, float]]:
        with self._lock:
            z = self._get_typed(key, dict)
            if not z:
                return []
            out = [(m, s) for m, s in z.items() if min_score <= s <= max_score]
            out.sort(key=lambda ms: (ms[1], ms[0]))
            return out

    def zremrangebyscore(self, key: str, min_score: float, max_score: float) -> int:
        with self._lock:
            victims = [m for m, _ in self.zrangebyscore(key, min_score, max_score)]
            return self.zrem(key, *victims) if victims else 0

    def zcard(self, key: str) -> int:
        with self._lock:
            z = self._get_typed(key, dict)
            return len(z) if z else 0

    # ------------- lists -------------

    def rpush(self, key: str, *values: str) -> int:
        with self._lock:
            lst = self._get_typed(key, list, create=True)
            lst.extend(str(v) for v in values)
            return len(lst)

    def lpush(self, key: str, *values: str) -> int:
        with self._lock:
            lst = self._get_typed(key, list, create=True)
            for v in values:
                lst.insert(0, str(v))
            return len(lst)

    def lrange(self, key: str, start: int = 0, stop: int = -1) -> list[str]:
        with self._lock:
            lst = self._get_typed(key, list)
            if not lst:
                return []
            if stop == -1:
                return list(lst[start:])
            return list(lst[start : stop + 1])

    def lrem(self, key: str, count: int, value: str) -> int:
        """Redis LREM semantics for count >= 0 (remove first `count`
        occurrences; 0 = all)."""
        with self._lock:
            lst = self._get_typed(key, list)
            if not lst:
                return 0
            removed = 0
            out = []
            for v in lst:
                if v == value and (count == 0 or removed < count):
                    removed += 1
                    continue
                out.append(v)
            if out:
                self._data[key] = out
            else:
                self.delete(key)
            return removed

    def llen(self, key: str) -> int:
        with self._lock:
            lst = self._get_typed(key, list)
            return len(lst) if lst else 0

    # ------------- atomic batches -------------

    def atomic(self):
        """Context manager holding the store lock across a multi-op batch —
        the moral equivalent of the reference's Redis pipelines and Lua
        scripts for group create/dissolve/merge atomicity
        (node_groups/mod.rs:298-322)."""
        return self._lock
