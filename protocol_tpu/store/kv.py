"""In-process KV store with Redis semantics and optional durability.

The reference talks to a real Redis from every service and spawns an embedded
redis-server per test (orchestrator/src/store/core/redis.rs:38-72). This
framework's state fits one coordinating process per pool (as the reference's
one-orchestrator-per-pool deployment does), so the store is in-process:
a thread-safe dict engine implementing exactly the Redis subset the control
plane uses —

  strings   get / set (NX, EX) / mget / incr / delete / exists / expire
  hashes    hset / hget / hgetall / hdel / hincrby
  sets      sadd / srem / smembers / sismember / scard
  zsets     zadd / zscore / zrem / zrangebyscore / zremrangebyscore / zcard
  lists     rpush / lpush / lrange / lrem / llen
  pipeline  atomic multi-op batch under one lock (the reference's pipelines
            and SET-NX races map onto this)

Lazy TTL expiry against a monotonic clock; a ``time_fn`` hook makes expiry
deterministic in tests. Keys are strings, values are strings (callers do
their own JSON), mirroring the wire-level Redis model so a networked Redis
backend could be slotted in behind the same interface later.

Durability (``persist_path``): the reference's services resume statelessly
because Redis outlives the process (redis.rs:38-72). With a persist path,
every mutation is appended to a JSON-lines journal (Redis-AOF style,
line-buffered so a killed process loses at most the in-flight line) and
replayed at construction; the journal is compacted to a minimal op
sequence at load and when it grows past ``compact_threshold`` entries.
TTLs are journaled as absolute wall-clock deadlines so they keep their
meaning across restarts (a persistent store therefore defaults to
``time.time`` rather than the monotonic clock).
"""

from __future__ import annotations

import fnmatch
import functools
import json
import os
import time

from protocol_tpu.utils.lockwitness import make_rlock
from typing import Callable, Iterable, Optional


def _journaled(fn):
    """Decorator for mutating methods: append (method, args) to the journal
    after the outermost successful call. Nested journaled calls (e.g.
    ``hdel`` -> ``delete``) are not journaled — replaying the outer op
    reproduces them."""
    name = fn.__name__

    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        if self._journal is None:
            return fn(self, *args, **kwargs)
        with self._lock:
            self._jdepth += 1
            try:
                out = fn(self, *args, **kwargs)
            finally:
                self._jdepth -= 1
            # no-op writes are NOT journaled: a failed SET NX / EXPIRE on a
            # missing key mutated nothing, and replaying it (especially an
            # expired SET-with-TTL, which replay resolves by deleting the
            # key) would corrupt state that the original call never touched
            if self._jdepth == 0 and not (name in ("set", "expire") and not out):
                self._journal_append(name, args, kwargs)
            return out

    return wrapper


class KVStore:
    def __init__(
        self,
        time_fn: Optional[Callable[[], float]] = None,
        persist_path: Optional[str] = None,
        compact_threshold: int = 100_000,
    ):
        self._lock = make_rlock("kv")
        self._data: dict[str, object] = {}
        self._expiry: dict[str, float] = {}
        # persistence needs wall-clock TTLs; in-memory stays monotonic
        self._time = time_fn or (time.time if persist_path else time.monotonic)
        self._journal = None
        self._jdepth = 0
        self._journal_ops = 0
        self._compact_threshold = compact_threshold
        self._persist_path = persist_path
        if persist_path is not None:
            os.makedirs(os.path.dirname(persist_path) or ".", exist_ok=True)
            if os.path.exists(persist_path):
                self._replay(persist_path)
            self._compact()  # also (re)opens the journal for appending

    # ------------- persistence -------------

    def _journal_append(self, method: str, args: tuple, kwargs: dict) -> None:
        entry: dict = {"m": method, "a": list(args)}
        kw = dict(kwargs)
        # TTLs become absolute wall deadlines (restart-stable)
        if method == "set" and kw.get("ex") is not None:
            kw["abs_ex"] = self._time() + kw.pop("ex")
        if method == "expire":
            # expire(key, seconds) — seconds may be positional
            seconds = kw.pop("seconds", None)
            if seconds is None and len(entry["a"]) == 2:
                seconds = entry["a"].pop(1)
            entry["abs"] = self._time() + float(seconds)
        if kw:
            entry["kw"] = kw
        self._journal.write(json.dumps(entry) + "\n")
        self._journal_ops += 1
        if self._journal_ops >= self._compact_threshold:
            self._compact()

    def _replay(self, path: str) -> None:
        # self._journal is None here, so the @_journaled wrappers pass
        # straight through without re-journaling
        now = self._time()
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    entry = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn final line from a crash
                method = entry.get("m")
                args = entry.get("a", [])
                kw = dict(entry.get("kw", {}))
                if method == "expire":
                    remaining = entry.get("abs", now) - now
                    if remaining <= 0:
                        self._data.pop(args[0], None)
                        self._expiry.pop(args[0], None)
                    else:
                        self.expire(args[0], remaining)
                    continue
                abs_ex = kw.pop("abs_ex", None)
                fn = getattr(self, method, None)
                if fn is None:
                    continue
                if abs_ex is not None:
                    if abs_ex <= now:
                        fn(*args, **kw)
                        self._data.pop(args[0], None)
                        self._expiry.pop(args[0], None)
                        continue
                    kw["ex"] = abs_ex - now
                fn(*args, **kw)

    def _compact(self) -> None:
        """Rewrite the journal as the minimal op sequence reconstructing the
        current state, atomically (tmp + rename)."""
        if self._persist_path is None:
            return
        with self._lock:
            if self._journal is not None:
                self._journal.close()
                self._journal = None
            tmp = self._persist_path + ".tmp"
            now = self._time()
            with open(tmp, "w") as f:
                for key in list(self._data):
                    if self._expired(key):
                        continue
                    val = self._data[key]
                    if isinstance(val, str):
                        f.write(json.dumps({"m": "set", "a": [key, val]}) + "\n")
                    elif isinstance(val, set):
                        f.write(json.dumps({"m": "sadd", "a": [key, *sorted(val)]}) + "\n")
                    elif isinstance(val, list):
                        f.write(json.dumps({"m": "rpush", "a": [key, *val]}) + "\n")
                    elif isinstance(val, dict):
                        # hashes hold str values, zsets hold floats
                        if val and isinstance(next(iter(val.values())), float):
                            f.write(json.dumps({"m": "zadd", "a": [key, val]}) + "\n")
                        else:
                            f.write(
                                json.dumps({"m": "hset_mapping", "a": [key, val]})
                                + "\n"
                            )
                    exp = self._expiry.get(key)
                    if exp is not None:
                        f.write(
                            json.dumps({"m": "expire", "a": [key], "abs": exp}) + "\n"
                        )
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._persist_path)
            self._journal = open(self._persist_path, "a", buffering=1)
            self._journal_ops = 0

    def close(self) -> None:
        with self._lock:
            if self._journal is not None:
                self._compact()
                self._journal.close()
                self._journal = None
                self._persist_path = None

    # ------------- internals -------------

    def _expired(self, key: str) -> bool:
        exp = self._expiry.get(key)
        if exp is not None and self._time() >= exp:
            self._data.pop(key, None)
            self._expiry.pop(key, None)
            return True
        return False

    def _get_typed(self, key: str, typ: type, create: bool = False):
        if self._expired(key):
            val = None
        else:
            val = self._data.get(key)
        if val is None:
            if not create:
                return None
            val = typ()
            self._data[key] = val
            self._expiry.pop(key, None)
        if not isinstance(val, typ):
            raise TypeError(f"WRONGTYPE key {key!r} holds {type(val).__name__}")
        return val

    # ------------- strings -------------

    @_journaled
    def set(
        self,
        key: str,
        value: str,
        *,
        # keyword-only: _journal_append rewrites the ex TTL to an absolute
        # deadline by kwarg name — a positional TTL would journal raw and
        # replay relative to RESTART time, extending expirations
        nx: bool = False,
        ex: Optional[float] = None,
    ) -> bool:
        with self._lock:
            self._expired(key)
            if nx and key in self._data:
                return False
            self._data[key] = str(value)
            if ex is not None:
                self._expiry[key] = self._time() + ex
            else:
                self._expiry.pop(key, None)
            return True

    def get(self, key: str) -> Optional[str]:
        with self._lock:
            v = self._get_typed(key, str)
            return v

    def mget(self, keys: Iterable[str]) -> list[Optional[str]]:
        with self._lock:
            return [self._get_typed(k, str) for k in keys]

    @_journaled
    def incr(self, key: str, amount: int = 1) -> int:
        with self._lock:
            cur = self._get_typed(key, str)
            val = int(cur) + amount if cur is not None else amount
            self._data[key] = str(val)
            return val

    @_journaled
    def delete(self, *keys: str) -> int:
        with self._lock:
            n = 0
            for key in keys:
                self._expired(key)
                if key in self._data:
                    del self._data[key]
                    self._expiry.pop(key, None)
                    n += 1
            return n

    def exists(self, key: str) -> bool:
        with self._lock:
            self._expired(key)
            return key in self._data

    @_journaled
    def expire(self, key: str, seconds: float) -> bool:
        with self._lock:
            self._expired(key)
            if key not in self._data:
                return False
            self._expiry[key] = self._time() + seconds
            return True

    def ttl(self, key: str) -> Optional[float]:
        """Remaining TTL; None if no key or no expiry."""
        with self._lock:
            self._expired(key)
            if key not in self._data:
                return None
            exp = self._expiry.get(key)
            return None if exp is None else max(0.0, exp - self._time())

    def keys(self, pattern: str = "*") -> list[str]:
        with self._lock:
            return [k for k in list(self._data) if not self._expired(k) and fnmatch.fnmatch(k, pattern)]

    @_journaled
    def flushall(self) -> None:
        with self._lock:
            self._data.clear()
            self._expiry.clear()

    # ------------- hashes -------------

    @_journaled
    def hset(self, key: str, field: str, value: str) -> int:
        with self._lock:
            h = self._get_typed(key, dict, create=True)
            is_new = field not in h
            h[field] = str(value)
            return int(is_new)

    @_journaled
    def hset_mapping(self, key: str, mapping: dict[str, str]) -> int:
        with self._lock:
            h = self._get_typed(key, dict, create=True)
            n = sum(1 for f in mapping if f not in h)
            h.update({f: str(v) for f, v in mapping.items()})
            return n

    def hget(self, key: str, field: str) -> Optional[str]:
        with self._lock:
            h = self._get_typed(key, dict)
            return None if h is None else h.get(field)

    def hgetall(self, key: str) -> dict[str, str]:
        with self._lock:
            h = self._get_typed(key, dict)
            return dict(h) if h else {}

    @_journaled
    def hdel(self, key: str, *fields: str) -> int:
        with self._lock:
            h = self._get_typed(key, dict)
            if not h:
                return 0
            n = 0
            for f in fields:
                if f in h:
                    del h[f]
                    n += 1
            if not h:
                self.delete(key)
            return n

    @_journaled
    def hincrby(self, key: str, field: str, amount: int = 1) -> int:
        with self._lock:
            h = self._get_typed(key, dict, create=True)
            val = int(h.get(field, "0")) + amount
            h[field] = str(val)
            return val

    # ------------- sets -------------

    @_journaled
    def sadd(self, key: str, *members: str) -> int:
        with self._lock:
            s = self._get_typed(key, set, create=True)
            n = len(members) - len(s.intersection(members))
            s.update(str(m) for m in members)
            return n

    @_journaled
    def srem(self, key: str, *members: str) -> int:
        with self._lock:
            s = self._get_typed(key, set)
            if not s:
                return 0
            n = len(s.intersection(members))
            s.difference_update(members)
            if not s:
                self.delete(key)
            return n

    def smembers(self, key: str) -> set[str]:
        with self._lock:
            s = self._get_typed(key, set)
            return set(s) if s else set()

    def sismember(self, key: str, member: str) -> bool:
        with self._lock:
            s = self._get_typed(key, set)
            return bool(s) and member in s

    def scard(self, key: str) -> int:
        with self._lock:
            s = self._get_typed(key, set)
            return len(s) if s else 0

    # ------------- sorted sets -------------

    @_journaled
    def zadd(self, key: str, mapping: dict[str, float]) -> int:
        with self._lock:
            z = self._get_typed(key, dict, create=True)
            n = sum(1 for m in mapping if m not in z)
            z.update({str(m): float(s) for m, s in mapping.items()})
            return n

    def zscore(self, key: str, member: str) -> Optional[float]:
        with self._lock:
            z = self._get_typed(key, dict)
            return None if z is None else z.get(member)

    @_journaled
    def zrem(self, key: str, *members: str) -> int:
        with self._lock:
            z = self._get_typed(key, dict)
            if not z:
                return 0
            n = 0
            for m in members:
                if m in z:
                    del z[m]
                    n += 1
            if not z:
                self.delete(key)
            return n

    def zrangebyscore(
        self, key: str, min_score: float = float("-inf"), max_score: float = float("inf")
    ) -> list[tuple[str, float]]:
        with self._lock:
            z = self._get_typed(key, dict)
            if not z:
                return []
            out = [(m, s) for m, s in z.items() if min_score <= s <= max_score]
            out.sort(key=lambda ms: (ms[1], ms[0]))
            return out

    @_journaled
    def zremrangebyscore(self, key: str, min_score: float, max_score: float) -> int:
        with self._lock:
            victims = [m for m, _ in self.zrangebyscore(key, min_score, max_score)]
            return self.zrem(key, *victims) if victims else 0

    def zcard(self, key: str) -> int:
        with self._lock:
            z = self._get_typed(key, dict)
            return len(z) if z else 0

    # ------------- lists -------------

    @_journaled
    def rpush(self, key: str, *values: str) -> int:
        with self._lock:
            lst = self._get_typed(key, list, create=True)
            lst.extend(str(v) for v in values)
            return len(lst)

    @_journaled
    def lpush(self, key: str, *values: str) -> int:
        with self._lock:
            lst = self._get_typed(key, list, create=True)
            for v in values:
                lst.insert(0, str(v))
            return len(lst)

    def lrange(self, key: str, start: int = 0, stop: int = -1) -> list[str]:
        with self._lock:
            lst = self._get_typed(key, list)
            if not lst:
                return []
            if stop == -1:
                return list(lst[start:])
            return list(lst[start : stop + 1])

    @_journaled
    def lrem(self, key: str, count: int, value: str) -> int:
        """Redis LREM semantics for count >= 0 (remove first `count`
        occurrences; 0 = all)."""
        with self._lock:
            lst = self._get_typed(key, list)
            if not lst:
                return 0
            removed = 0
            out = []
            for v in lst:
                if v == value and (count == 0 or removed < count):
                    removed += 1
                    continue
                out.append(v)
            if out:
                self._data[key] = out
            else:
                self.delete(key)
            return removed

    def llen(self, key: str) -> int:
        with self._lock:
            lst = self._get_typed(key, list)
            return len(lst) if lst else 0

    # ------------- atomic batches -------------

    def atomic(self):
        """Context manager holding the store lock across a multi-op batch —
        the moral equivalent of the reference's Redis pipelines and Lua
        scripts for group create/dissolve/merge atomicity
        (node_groups/mod.rs:298-322)."""
        return self._lock

    def pipeline_execute(self, ops: list) -> list:
        """Execute [(op, args, kwargs), ...] under one lock, returning each
        op's result — the Redis pipeline shape (one round trip over the
        remote client). Like a Redis pipeline, this is ISOLATED but not
        transactional: ops apply in order and a failing op aborts the
        remainder with earlier ops committed — batch only ops whose
        validity is guaranteed by construction."""
        out = []
        with self._lock:
            for op, args, kwargs in ops:
                out.append(getattr(self, op)(*args, **(kwargs or {})))
        return out
