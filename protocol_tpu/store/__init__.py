"""State store layer.

The reference keeps every service's state in Redis (SURVEY.md §2.3-2.6):
node hashes + index sets, task lists, heartbeat keys with TTL, group keys,
metric hashes, nonce replay caches. This package provides:

  kv            - an in-process KV store implementing the Redis-semantics
                  subset the framework uses (strings with TTL + SET NX,
                  hashes, sets, sorted sets, lists, atomic pipelines).
                  Hermetic per-test instances replace the reference's
                  embedded redis-server fixture.
  domains       - domain stores over the KV schema: nodes, tasks (+observer
                  hooks), heartbeats (TTL + unhealthy counters), metrics,
                  node groups.
  context       - StoreContext bundling the domain stores per service.
"""

from protocol_tpu.store.kv import KVStore
from protocol_tpu.store.context import StoreContext
from protocol_tpu.store.domains.node_store import NodeStore, OrchestratorNode, NodeStatus
from protocol_tpu.store.domains.task_store import TaskStore
from protocol_tpu.store.domains.heartbeat_store import HeartbeatStore
from protocol_tpu.store.domains.metrics_store import MetricsStore

__all__ = [
    "HeartbeatStore",
    "KVStore",
    "MetricsStore",
    "NodeStatus",
    "NodeStore",
    "OrchestratorNode",
    "StoreContext",
    "TaskStore",
]
