"""KVStore-interface client over the kv-api HTTP service.

The counterpart of the reference services' Redis clients: orchestrator
replicas (api/processor modes) construct ``StoreContext(RemoteKVStore(url))``
and share one state store exactly as the reference replicas share one
Redis (orchestrator/src/main.rs modes; store/core/redis.rs).

Synchronous transport (per-thread keep-alive connections via
utils.http_client): callers on an event loop already route store-touching
sections through ``asyncio.to_thread``. ``atomic()`` maps to the server's
advisory lock — read-modify-write sequences keep their cross-client
serialization, the property the in-process store gets from its RLock.
"""

from __future__ import annotations

import threading
from typing import Iterable, Optional

from protocol_tpu.utils.http_client import KeepAliveJsonClient


class RemoteKVError(RuntimeError):
    pass


class LockLostError(RemoteKVError):
    """The server reports the advisory lock this client's atomic section
    held has expired (and may have been reacquired by another client):
    serialization is already broken, so the op did NOT execute. Callers
    must retry the whole atomic section, not the single op."""


class _RemoteLock:
    """Context manager backing atomic(): acquires the server's advisory
    lock (re-entrant per client, like the in-process RLock)."""

    def __init__(self, store: "RemoteKVStore"):
        self.store = store

    def __enter__(self):
        if self.store._lock_depth == 0:
            # acquire BEFORE counting: a failed acquire must leave depth 0
            # (no __exit__ runs when __enter__ raises)
            self.store._lock_token = self.store._lock("acquire")
        self.store._lock_depth += 1
        return self

    def __exit__(self, *exc):
        self.store._lock_depth -= 1
        if self.store._lock_depth == 0:
            try:
                self.store._lock("release")
            finally:
                self.store._lock_token = None
        return False


class RemoteKVStore:
    # ops safe to resend after a lost response (no state change)
    READ_OPS = frozenset({
        "get", "mget", "hget", "hgetall", "smembers", "sismember", "scard",
        "zscore", "zrangebyscore", "zcard", "lrange", "llen", "keys",
        "exists", "ttl",
    })

    def __init__(self, base_url: str, api_key: str = "admin", timeout: float = 10.0):
        self.base_url = base_url.rstrip("/")
        self.api_key = api_key
        self.timeout = timeout
        self._http = KeepAliveJsonClient(base_url, timeout, RemoteKVError)
        self._tlocal = threading.local()

    # re-entrancy bookkeeping is per-thread (services may call the store
    # from worker threads concurrently)
    @property
    def _lock_depth(self) -> int:
        return getattr(self._tlocal, "depth", 0)

    @_lock_depth.setter
    def _lock_depth(self, v: int) -> None:
        self._tlocal.depth = v

    @property
    def _lock_token(self) -> Optional[str]:
        return getattr(self._tlocal, "token", None)

    @_lock_token.setter
    def _lock_token(self, v: Optional[str]) -> None:
        self._tlocal.token = v

    def _post(self, path: str, payload: dict, retry_response: bool = False):
        out = self._http.post(
            path,
            payload,
            headers={"Authorization": f"Bearer {self.api_key}"},
            retry_response=retry_response,
        )
        if not out.get("success"):
            err = out.get("error", "kv op failed")
            if err == "lock-lost":
                raise LockLostError(err)
            raise RemoteKVError(err)
        return out.get("data")

    def _lock(self, action: str) -> Optional[str]:
        import time

        deadline = time.monotonic() + self.timeout
        while True:
            try:
                return self._post(
                    "/kv/_lock",
                    {"action": action, "token": self._lock_token or ""},
                    retry_response=(action == "release"),
                )
            except RemoteKVError as e:
                if action == "acquire" and "locked" in str(e):
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.01)
                    continue
                raise

    def _post_with_lock_retry(
        self, path: str, payload: dict, retry_response: bool = False
    ):
        """in-process RLock semantics: a write that meets a foreign atomic
        section BLOCKS until the lock frees (bounded by timeout), it does
        not 500 the caller on first contention."""
        import time

        deadline = time.monotonic() + self.timeout
        while True:
            try:
                return self._post(path, payload, retry_response=retry_response)
            except RemoteKVError as e:
                if "locked" in str(e) and time.monotonic() < deadline:
                    time.sleep(0.01)
                    continue
                raise

    def _call(self, op: str, *args, **kwargs):
        payload = {
            "args": list(args),
            "kwargs": kwargs,
            "lock_token": self._lock_token or "",
        }
        return self._post_with_lock_retry(
            f"/kv/{op}", payload, retry_response=op in self.READ_OPS
        )

    def atomic(self) -> _RemoteLock:
        return _RemoteLock(self)

    # ops whose wire shape differs from the KVStore return type
    _RESHAPE = {"smembers": set, "zrangebyscore": lambda v: [tuple(x) for x in v]}

    def pipeline_execute(self, ops: list) -> list:
        """Op batch in ONE round trip (KVStore.pipeline_execute over the
        wire; same isolated-not-transactional semantics). Writes may be
        present: never response-retried. Results are reshaped to match
        the in-process store's return types."""
        payload = {
            "ops": [[op, list(args), kwargs or {}] for op, args, kwargs in ops],
            "lock_token": self._lock_token or "",
        }
        results = self._post_with_lock_retry("/kv/_pipeline", payload)
        return [
            self._RESHAPE[op](res) if op in self._RESHAPE else res
            for (op, _a, _k), res in zip(ops, results)
        ]

    # ---- surface (matches KVStore) ----

    def set(self, key, value, *, nx=False, ex=None):
        return self._call("set", key, value, nx=nx, ex=ex)

    def get(self, key):
        return self._call("get", key)

    def mget(self, keys: Iterable[str]):
        return self._call("mget", list(keys))

    def incr(self, key, amount=1):
        return self._call("incr", key, amount)

    def delete(self, *keys):
        return self._call("delete", *keys)

    def exists(self, key):
        return self._call("exists", key)

    def expire(self, key, seconds):
        return self._call("expire", key, seconds)

    def ttl(self, key):
        return self._call("ttl", key)

    def keys(self, pattern="*"):
        return self._call("keys", pattern)

    def flushall(self):
        return self._call("flushall")

    def hset(self, key, field, value):
        return self._call("hset", key, field, value)

    def hset_mapping(self, key, mapping):
        return self._call("hset_mapping", key, mapping)

    def hget(self, key, field):
        return self._call("hget", key, field)

    def hgetall(self, key):
        return self._call("hgetall", key)

    def hdel(self, key, *fields):
        return self._call("hdel", key, *fields)

    def hincrby(self, key, field, amount=1):
        return self._call("hincrby", key, field, amount)

    def sadd(self, key, *members):
        return self._call("sadd", key, *members)

    def srem(self, key, *members):
        return self._call("srem", key, *members)

    def smembers(self, key):
        return set(self._call("smembers", key))

    def sismember(self, key, member):
        return self._call("sismember", key, member)

    def scard(self, key):
        return self._call("scard", key)

    def zadd(self, key, mapping):
        return self._call("zadd", key, mapping)

    def zscore(self, key, member):
        return self._call("zscore", key, member)

    def zrem(self, key, *members):
        return self._call("zrem", key, *members)

    def zrangebyscore(self, key, min_score=float("-inf"), max_score=float("inf")):
        # json has no infinities: clamp to sentinel bounds
        lo = -1e300 if min_score == float("-inf") else min_score
        hi = 1e300 if max_score == float("inf") else max_score
        return [tuple(x) for x in self._call("zrangebyscore", key, lo, hi)]

    def zremrangebyscore(self, key, min_score, max_score):
        return self._call("zremrangebyscore", key, min_score, max_score)

    def zcard(self, key):
        return self._call("zcard", key)

    def rpush(self, key, *values):
        return self._call("rpush", key, *values)

    def lpush(self, key, *values):
        return self._call("lpush", key, *values)

    def lrange(self, key, start=0, stop=-1):
        return self._call("lrange", key, start, stop)

    def lrem(self, key, count, value):
        return self._call("lrem", key, count, value)

    def llen(self, key):
        return self._call("llen", key)
