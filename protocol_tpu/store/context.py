"""StoreContext: the bundle of domain stores each service holds.

Reference: crates/orchestrator/src/store/core/context.rs. ``new_test()``
mirrors the reference's embedded-redis fixture — a fresh hermetic store per
test (orchestrator/src/store/core/redis.rs:38-72).
"""

from __future__ import annotations

from protocol_tpu.store.kv import KVStore
from protocol_tpu.store.domains.heartbeat_store import HeartbeatStore
from protocol_tpu.store.domains.metrics_store import MetricsStore
from protocol_tpu.store.domains.node_store import NodeStore
from protocol_tpu.store.domains.task_store import TaskStore


class StoreContext:
    def __init__(self, kv: KVStore | None = None, heartbeat_ttl: float = 90.0):
        self.kv = kv or KVStore()
        self.node_store = NodeStore(self.kv)
        self.task_store = TaskStore(self.kv)
        self.heartbeat_store = HeartbeatStore(self.kv, ttl_seconds=heartbeat_ttl)
        self.metrics_store = MetricsStore(self.kv)

    @classmethod
    def new_test(cls, heartbeat_ttl: float = 90.0) -> "StoreContext":
        return cls(KVStore(), heartbeat_ttl=heartbeat_ttl)
