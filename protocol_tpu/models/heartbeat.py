"""Heartbeat payloads (reference: crates/shared/src/models/heartbeat.rs)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from protocol_tpu.models.task import TaskState


@dataclass
class TaskDetails:
    """Container/runtime details reported alongside a heartbeat
    (heartbeat.rs:24-31)."""

    container_id: Optional[str] = None
    container_status: Optional[str] = None
    exit_code: Optional[int] = None
    error_message: Optional[str] = None

    def to_dict(self) -> dict:
        return {
            "container_id": self.container_id,
            "container_status": self.container_status,
            "exit_code": self.exit_code,
            "error_message": self.error_message,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "TaskDetails":
        return cls(
            container_id=d.get("container_id"),
            container_status=d.get("container_status"),
            exit_code=d.get("exit_code"),
            error_message=d.get("error_message"),
        )


@dataclass
class HeartbeatRequest:
    """Worker -> orchestrator heartbeat body (heartbeat.rs:33-46)."""

    address: str = ""
    task_id: Optional[str] = None
    task_state: Optional[str] = None
    metrics: Optional[list[dict]] = None
    version: Optional[str] = None
    timestamp: Optional[float] = None
    p2p_id: Optional[str] = None
    p2p_addresses: Optional[list[str]] = None
    task_details: Optional[TaskDetails] = None
    # worker-reported host utilization 0..1 (external to this pool's own
    # assignment so the matcher's load term cannot feed back into itself)
    load: Optional[float] = None
    # colocated extras (ladder #5): {task_id: state} for every assigned
    # task running CONCURRENTLY beyond the primary current_task
    extra_task_states: Optional[dict] = None

    def task_state_enum(self) -> Optional[TaskState]:
        return TaskState.parse(self.task_state) if self.task_state else None

    def to_dict(self) -> dict:
        d: dict = {"address": self.address}
        for k in ("task_id", "task_state", "metrics", "version", "timestamp", "p2p_id", "p2p_addresses", "load", "extra_task_states"):
            v = getattr(self, k)
            if v is not None:
                d[k] = v
        if self.task_details is not None:
            d["task_details"] = self.task_details.to_dict()
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "HeartbeatRequest":
        return cls(
            address=d.get("address", ""),
            task_id=d.get("task_id"),
            task_state=d.get("task_state"),
            metrics=d.get("metrics"),
            version=d.get("version"),
            timestamp=d.get("timestamp"),
            p2p_id=d.get("p2p_id"),
            p2p_addresses=d.get("p2p_addresses"),
            task_details=TaskDetails.from_dict(d["task_details"])
            if d.get("task_details")
            else None,
            load=float(d["load"]) if d.get("load") is not None else None,
            extra_task_states=d.get("extra_task_states"),
        )
