"""Node, compute-spec, and compute-requirement models.

Behavioral parity with the reference's node model
(reference: crates/shared/src/models/node.rs):

- ``ComputeRequirements`` string DSL (node.rs:180-374), e.g.
  ``"gpu:count=8;gpu:model=H100;gpu:memory_mb=80000;cpu:cores=32;ram_mb=65536"``.
  Multiple GPU alternatives (OR logic) are expressed by repeating ``gpu:count``.
- Capability matching ``ComputeSpecs.meets()`` (node.rs:377-441) with GPU
  OR-semantics, fuzzy model matching and per-card / total-memory ranges
  (node.rs:443-527).

These are plain Python dataclasses (host-side, stringly-typed world); the
TPU-side numeric encoding of the same algebra lives in
``protocol_tpu.ops.encoding``.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field, asdict
from typing import Any, Optional


class RequirementsParseError(ValueError):
    """Raised for malformed requirement DSL strings."""


def _normalize_model(name: str) -> str:
    return name.lower().replace(" ", "_")


def _models_fuzzy_match(spec_model: str, req_models_csv: str) -> bool:
    """Fuzzy GPU-model match (node.rs:443-478): the requirement is a
    comma-separated list of acceptable models; normalization lowercases and
    underscores spaces; containment is checked in both directions, with and
    without underscores."""
    normalized_spec = _normalize_model(spec_model)
    spec_no_us = normalized_spec.replace("_", "")
    for raw in req_models_csv.split(","):
        normalized_req = _normalize_model(raw.strip())
        req_no_us = normalized_req.replace("_", "")
        if (
            normalized_req in normalized_spec
            or normalized_spec in normalized_req
            or req_no_us in spec_no_us
            or spec_no_us in req_no_us
        ):
            return True
    return False


@dataclass
class CpuSpecs:
    cores: Optional[int] = None
    model: Optional[str] = None

    def meets(self, requirement: "CpuSpecs") -> bool:
        if requirement.cores is not None:
            if self.cores is None or self.cores < requirement.cores:
                return False
        return True

    def to_dict(self) -> dict:
        return _drop_none(asdict(self))

    @classmethod
    def from_dict(cls, d: dict) -> "CpuSpecs":
        return cls(cores=d.get("cores"), model=d.get("model"))


@dataclass
class GpuSpecs:
    count: Optional[int] = None
    model: Optional[str] = None
    memory_mb: Optional[int] = None
    indices: Optional[list[int]] = None

    def meets(self, requirement: "GpuRequirements") -> bool:
        """Single-alternative GPU match (node.rs:443-527)."""
        if requirement.count is not None:
            # exact count match; a node with no count passes only a 0-count req
            if self.count is None:
                if requirement.count > 0:
                    return False
            elif self.count != requirement.count:
                return False

        if requirement.model is not None:
            if self.model is None or not _models_fuzzy_match(
                self.model, requirement.model
            ):
                return False

        if requirement.memory_mb is not None:
            if self.memory_mb is None or self.memory_mb < requirement.memory_mb:
                return False
        if requirement.memory_mb_min is not None:
            if self.memory_mb is None or self.memory_mb < requirement.memory_mb_min:
                return False
        if requirement.memory_mb_max is not None:
            if self.memory_mb is None or self.memory_mb > requirement.memory_mb_max:
                return False

        # Total-memory bounds apply only when the node reports both count and
        # per-card memory (node.rs:503-524).
        if (
            requirement.total_memory_min is not None
            and self.count is not None
            and self.memory_mb is not None
        ):
            if self.count * self.memory_mb < requirement.total_memory_min:
                return False
        if (
            requirement.total_memory_max is not None
            and self.count is not None
            and self.memory_mb is not None
        ):
            if self.count * self.memory_mb > requirement.total_memory_max:
                return False
        return True

    def to_dict(self) -> dict:
        return _drop_none(asdict(self))

    @classmethod
    def from_dict(cls, d: dict) -> "GpuSpecs":
        return cls(
            count=d.get("count"),
            model=d.get("model"),
            memory_mb=d.get("memory_mb"),
            indices=d.get("indices"),
        )


@dataclass
class GpuRequirements:
    count: Optional[int] = None
    model: Optional[str] = None
    memory_mb: Optional[int] = None  # per card
    memory_mb_min: Optional[int] = None
    memory_mb_max: Optional[int] = None
    total_memory_min: Optional[int] = None  # count * memory_mb
    total_memory_max: Optional[int] = None
    indices: Optional[list[int]] = None

    def any_set(self) -> bool:
        return any(
            v is not None
            for v in (
                self.count,
                self.model,
                self.memory_mb,
                self.memory_mb_min,
                self.memory_mb_max,
                self.total_memory_min,
                self.total_memory_max,
            )
        )

    def to_dict(self) -> dict:
        return _drop_none(asdict(self))

    @classmethod
    def from_dict(cls, d: dict) -> "GpuRequirements":
        return cls(**{k: d.get(k) for k in (
            "count", "model", "memory_mb", "memory_mb_min", "memory_mb_max",
            "total_memory_min", "total_memory_max", "indices")})


@dataclass
class ComputeSpecs:
    gpu: Optional[GpuSpecs] = None
    cpu: Optional[CpuSpecs] = None
    ram_mb: Optional[int] = None
    storage_gb: Optional[int] = None
    storage_path: str = "/var/lib/prime-worker"

    def meets(self, requirements: "ComputeRequirements") -> bool:
        """Capability gate (node.rs:377-441). CPU/RAM/storage are AND
        constraints; the GPU alternatives list is OR."""
        if requirements.cpu is not None:
            if self.cpu is None or not self.cpu.meets(requirements.cpu):
                return False
        if requirements.ram_mb is not None:
            if self.ram_mb is None or self.ram_mb < requirements.ram_mb:
                return False
        if requirements.storage_gb is not None:
            if self.storage_gb is None or self.storage_gb < requirements.storage_gb:
                return False
        if requirements.gpu:
            if self.gpu is None:
                return False
            if not any(self.gpu.meets(req) for req in requirements.gpu):
                return False
        return True

    def to_dict(self) -> dict:
        d: dict[str, Any] = {}
        if self.gpu is not None:
            d["gpu"] = self.gpu.to_dict()
        if self.cpu is not None:
            d["cpu"] = self.cpu.to_dict()
        if self.ram_mb is not None:
            d["ram_mb"] = self.ram_mb
        if self.storage_gb is not None:
            d["storage_gb"] = self.storage_gb
        d["storage_path"] = self.storage_path
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "ComputeSpecs":
        return cls(
            gpu=GpuSpecs.from_dict(d["gpu"]) if d.get("gpu") else None,
            cpu=CpuSpecs.from_dict(d["cpu"]) if d.get("cpu") else None,
            ram_mb=d.get("ram_mb"),
            storage_gb=d.get("storage_gb"),
            storage_path=d.get("storage_path", "/var/lib/prime-worker"),
        )


@dataclass
class ComputeRequirements:
    gpu: list[GpuRequirements] = field(default_factory=list)
    cpu: Optional[CpuSpecs] = None
    ram_mb: Optional[int] = None
    storage_gb: Optional[int] = None

    @classmethod
    def parse(cls, s: str) -> "ComputeRequirements":
        """Parse the requirements DSL (node.rs:180-374).

        ``key=value`` pairs separated by ``;``. A fresh ``gpu:count`` key while
        the current GPU alternative already has a count starts a new OR
        alternative. Exact ``gpu:memory_mb`` conflicts with the min/max forms;
        min>max is rejected at parse time.
        """
        req = cls()
        current = GpuRequirements()
        gpu_started = False

        def _int(key: str, value: str) -> int:
            try:
                v = int(value)
            except ValueError as e:
                raise RequirementsParseError(
                    f"Invalid {key} value '{value}': {e}"
                ) from None
            if v < 0:
                raise RequirementsParseError(f"Invalid {key} value '{value}': negative")
            return v

        for part in s.split(";"):
            part = part.strip()
            if not part:
                continue
            kv = part.split("=", 1)
            if len(kv) != 2:
                raise RequirementsParseError(f"Invalid key-value pair format: '{part}'")
            key, value = kv[0].strip(), kv[1].strip()

            if key == "gpu:count":
                if gpu_started and current.count is not None:
                    req.gpu.append(current)
                    current = GpuRequirements()
                gpu_started = True
                current.count = _int(key, value)
            elif key == "gpu:model":
                gpu_started = True
                current.model = value
            elif key == "gpu:memory_mb":
                gpu_started = True
                if current.memory_mb_min is not None or current.memory_mb_max is not None:
                    raise RequirementsParseError(
                        "Cannot specify both exact memory and min/max memory"
                    )
                current.memory_mb = _int(key, value)
            elif key == "gpu:memory_mb_min":
                gpu_started = True
                if current.memory_mb is not None:
                    raise RequirementsParseError(
                        "Cannot specify both exact memory and min/max memory"
                    )
                v = _int(key, value)
                if current.memory_mb_max is not None and current.memory_mb_max < v:
                    raise RequirementsParseError(
                        f"Invalid gpu:memory_mb_min value '{value}': min value is greater than max value"
                    )
                current.memory_mb_min = v
            elif key == "gpu:memory_mb_max":
                gpu_started = True
                if current.memory_mb is not None:
                    raise RequirementsParseError(
                        "Cannot specify both exact memory and min/max memory"
                    )
                v = _int(key, value)
                if current.memory_mb_min is not None and current.memory_mb_min > v:
                    raise RequirementsParseError(
                        f"Invalid gpu:memory_mb_max value '{value}': max value is less than min value"
                    )
                current.memory_mb_max = v
            elif key == "gpu:total_memory_min":
                gpu_started = True
                v = _int(key, value)
                if current.total_memory_max is not None and current.total_memory_max < v:
                    raise RequirementsParseError(
                        f"Invalid gpu:total_memory_min value '{value}': min value is greater than max value"
                    )
                current.total_memory_min = v
            elif key == "gpu:total_memory_max":
                gpu_started = True
                v = _int(key, value)
                if current.total_memory_min is not None and current.total_memory_min > v:
                    raise RequirementsParseError(
                        f"Invalid gpu:total_memory_max value '{value}': max value is less than min value"
                    )
                current.total_memory_max = v
            elif key == "cpu:cores":
                cpu = req.cpu or CpuSpecs()
                cpu.cores = _int(key, value)
                req.cpu = cpu
            elif key == "ram_mb":
                req.ram_mb = _int(key, value)
            elif key == "storage_gb":
                req.storage_gb = _int(key, value)
            else:
                raise RequirementsParseError(f"Unknown requirement key: '{key}'")

        if gpu_started and current.any_set():
            req.gpu.append(current)
        return req

    def to_dict(self) -> dict:
        d: dict[str, Any] = {"gpu": [g.to_dict() for g in self.gpu]}
        if self.cpu is not None:
            d["cpu"] = self.cpu.to_dict()
        if self.ram_mb is not None:
            d["ram_mb"] = self.ram_mb
        if self.storage_gb is not None:
            d["storage_gb"] = self.storage_gb
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "ComputeRequirements":
        return cls(
            gpu=[GpuRequirements.from_dict(g) for g in d.get("gpu", [])],
            cpu=CpuSpecs.from_dict(d["cpu"]) if d.get("cpu") else None,
            ram_mb=d.get("ram_mb"),
            storage_gb=d.get("storage_gb"),
        )


@dataclass
class NodeLocation:
    latitude: float = 0.0
    longitude: float = 0.0
    city: Optional[str] = None
    region: Optional[str] = None
    country: Optional[str] = None

    def to_dict(self) -> dict:
        return _drop_none(asdict(self))

    @classmethod
    def from_dict(cls, d: dict) -> "NodeLocation":
        return cls(
            latitude=float(d.get("latitude", 0.0)),
            longitude=float(d.get("longitude", 0.0)),
            city=d.get("city"),
            region=d.get("region"),
            country=d.get("country"),
        )


@dataclass
class Node:
    """A registered worker node (node.rs:10-23). ``id`` is the node wallet
    address; ``provider_address`` the staking provider's address."""

    id: str = ""
    provider_address: str = ""
    ip_address: str = ""
    port: int = 0
    compute_pool_id: int = 0
    compute_specs: Optional[ComputeSpecs] = None
    worker_p2p_id: Optional[str] = None
    worker_p2p_addresses: Optional[list[str]] = None
    # provider-advertised ask price (cost units/hour); a live input to the
    # batch matcher's price cost term — the reference scores nothing, so
    # this field is the marketplace half of the redesign (ops/cost.py)
    price: Optional[float] = None

    def to_dict(self) -> dict:
        d: dict[str, Any] = {
            "id": self.id,
            "provider_address": self.provider_address,
            "ip_address": self.ip_address,
            "port": self.port,
            "compute_pool_id": self.compute_pool_id,
            "compute_specs": self.compute_specs.to_dict() if self.compute_specs else None,
        }
        if self.worker_p2p_id is not None:
            d["worker_p2p_id"] = self.worker_p2p_id
        if self.worker_p2p_addresses is not None:
            d["worker_p2p_addresses"] = self.worker_p2p_addresses
        if self.price is not None:
            d["price"] = self.price
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Node":
        return cls(
            id=d.get("id", ""),
            provider_address=d.get("provider_address", ""),
            ip_address=d.get("ip_address", ""),
            port=int(d.get("port", 0)),
            compute_pool_id=int(d.get("compute_pool_id", 0)),
            compute_specs=ComputeSpecs.from_dict(d["compute_specs"])
            if d.get("compute_specs")
            else None,
            worker_p2p_id=d.get("worker_p2p_id"),
            worker_p2p_addresses=d.get("worker_p2p_addresses"),
            price=float(d["price"]) if d.get("price") is not None else None,
        )

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True)

    @classmethod
    def from_json(cls, s: str) -> "Node":
        return cls.from_dict(json.loads(s))


@dataclass
class DiscoveryNode:
    """Discovery-service view of a node plus chain-derived flags
    (node.rs:552-570)."""

    node: Node = field(default_factory=Node)
    is_validated: bool = False
    is_active: bool = False
    is_provider_whitelisted: bool = False
    is_blacklisted: bool = False
    last_updated: Optional[float] = None
    created_at: Optional[float] = None
    location: Optional[NodeLocation] = None
    latest_balance: Optional[int] = None

    def to_dict(self) -> dict:
        d = self.node.to_dict()
        d.update(
            {
                "is_validated": self.is_validated,
                "is_active": self.is_active,
                "is_provider_whitelisted": self.is_provider_whitelisted,
                "is_blacklisted": self.is_blacklisted,
            }
        )
        if self.last_updated is not None:
            d["last_updated"] = self.last_updated
        if self.created_at is not None:
            d["created_at"] = self.created_at
        if self.location is not None:
            d["location"] = self.location.to_dict()
        if self.latest_balance is not None:
            d["latest_balance"] = self.latest_balance
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "DiscoveryNode":
        return cls(
            node=Node.from_dict(d),
            is_validated=bool(d.get("is_validated", False)),
            is_active=bool(d.get("is_active", False)),
            is_provider_whitelisted=bool(d.get("is_provider_whitelisted", False)),
            is_blacklisted=bool(d.get("is_blacklisted", False)),
            last_updated=d.get("last_updated"),
            created_at=d.get("created_at"),
            location=NodeLocation.from_dict(d["location"]) if d.get("location") else None,
            latest_balance=d.get("latest_balance"),
        )

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True)

    @classmethod
    def from_json(cls, s: str) -> "DiscoveryNode":
        return cls.from_dict(json.loads(s))


def _drop_none(d: dict) -> dict:
    return {k: v for k, v in d.items() if v is not None}
