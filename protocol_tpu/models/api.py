"""API envelope models (reference: crates/shared/src/models/api.rs, storage.rs)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Generic, Optional, TypeVar

T = TypeVar("T")


@dataclass
class ApiResponse(Generic[T]):
    success: bool
    data: T

    def to_dict(self) -> dict:
        data = self.data
        if hasattr(data, "to_dict"):
            data = data.to_dict()
        elif isinstance(data, list):
            data = [x.to_dict() if hasattr(x, "to_dict") else x for x in data]
        return {"success": self.success, "data": data}


@dataclass
class RequestUploadRequest:
    """Signed-URL upload request (storage.rs)."""

    file_name: str
    file_size: int
    file_type: str
    sha256: str
    task_id: Optional[str] = None

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {
            "file_name": self.file_name,
            "file_size": self.file_size,
            "file_type": self.file_type,
            "sha256": self.sha256,
        }
        if self.task_id is not None:
            d["task_id"] = self.task_id
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "RequestUploadRequest":
        return cls(
            file_name=d["file_name"],
            file_size=int(d["file_size"]),
            file_type=d["file_type"],
            sha256=d["sha256"],
            task_id=d.get("task_id"),
        )
