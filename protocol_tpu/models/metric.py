"""Metric entries (reference: crates/shared/src/models/metric.rs).

A metric is keyed by (task_id, label) and carries a finite f64 value;
non-finite values are rejected at construction (metric.rs:24-29).
"""

from __future__ import annotations

import math
from dataclasses import dataclass


@dataclass(frozen=True)
class MetricKey:
    task_id: str
    label: str


@dataclass
class MetricEntry:
    key: MetricKey
    value: float

    def __post_init__(self) -> None:
        if not math.isfinite(self.value):
            raise ValueError(f"Metric value must be finite, got {self.value}")

    def to_dict(self) -> dict:
        return {
            "key": {"task_id": self.key.task_id, "label": self.key.label},
            "value": self.value,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "MetricEntry":
        return cls(
            key=MetricKey(task_id=d["key"]["task_id"], label=d["key"]["label"]),
            value=float(d["value"]),
        )
