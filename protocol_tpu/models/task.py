"""Task model: states, scheduling config, volume mounts, variable expansion.

Parity with reference crates/shared/src/models/task.rs:
- ``TaskState`` 8-state enum (task.rs:11-22), string round-trip with unknown
  strings mapping to UNKNOWN.
- ``VolumeMount`` label expansion of ``${TASK_ID}/${GROUP_ID}/${TIMESTAMP}/
  ${NODE_ADDRESS}`` (task.rs:63-142) and validation of supported variables.
- ``StorageConfig.file_name_template`` variable validation (task.rs:244-273).
- ``Task.generate_config_hash()`` hashing image/cmd/entrypoint plus sorted
  env vars and volume mounts (task.rs:187-221) — used by the worker runtime
  to name containers/sandboxes so a config change forces a restart.
"""

from __future__ import annotations

import enum
import hashlib
import json
import re
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Optional

_VAR_RE = re.compile(r"\$\{[^}]+\}")


class TaskState(str, enum.Enum):
    PENDING = "PENDING"
    PULLING = "PULLING"
    RUNNING = "RUNNING"
    COMPLETED = "COMPLETED"
    FAILED = "FAILED"
    PAUSED = "PAUSED"
    RESTARTING = "RESTARTING"
    UNKNOWN = "UNKNOWN"

    @classmethod
    def parse(cls, s: str) -> "TaskState":
        try:
            return cls(s)
        except ValueError:
            return cls.UNKNOWN


@dataclass
class SchedulingConfig:
    """Free-form plugin config map (task.rs:58-61); the node-groups plugin
    reads ``plugins["node_groups"]["allowed_topologies"]``."""

    plugins: Optional[dict[str, dict[str, list[str]]]] = None

    def allowed_topologies(self) -> list[str]:
        if not self.plugins:
            return []
        return list(self.plugins.get("node_groups", {}).get("allowed_topologies", []))

    def to_dict(self) -> dict:
        return {"plugins": self.plugins}

    @classmethod
    def from_dict(cls, d: dict) -> "SchedulingConfig":
        return cls(plugins=d.get("plugins"))


@dataclass
class VolumeMount:
    host_path: str
    container_path: str

    SUPPORTED_VARS = ("${TASK_ID}", "${GROUP_ID}", "${TIMESTAMP}", "${NODE_ADDRESS}")

    def replace_labels(
        self, task_id: str, node_address: Optional[str] = None
    ) -> "VolumeMount":
        host_path = self.host_path.replace("${TASK_ID}", task_id)
        container_path = self.container_path.replace("${TASK_ID}", task_id)
        if node_address is not None:
            host_path = host_path.replace("${NODE_ADDRESS}", node_address)
            container_path = container_path.replace("${NODE_ADDRESS}", node_address)
        ts = str(int(time.time()))
        host_path = host_path.replace("${TIMESTAMP}", ts)
        container_path = container_path.replace("${TIMESTAMP}", ts)
        return VolumeMount(host_path=host_path, container_path=container_path)

    def validate(self) -> None:
        if not self.host_path:
            raise ValueError("Host path cannot be empty")
        if not self.container_path:
            raise ValueError("Container path cannot be empty")
        for path, label in ((self.host_path, "host_path"), (self.container_path, "container_path")):
            for m in _VAR_RE.finditer(path):
                if m.group(0) not in self.SUPPORTED_VARS:
                    raise ValueError(
                        f"Volume mount {label} contains unsupported variable: "
                        f"{m.group(0)}. Supported variables: {list(self.SUPPORTED_VARS)}"
                    )

    def to_dict(self) -> dict:
        return {"host_path": self.host_path, "container_path": self.container_path}

    @classmethod
    def from_dict(cls, d: dict) -> "VolumeMount":
        return cls(host_path=d["host_path"], container_path=d["container_path"])


@dataclass
class StorageConfig:
    file_name_template: Optional[str] = None

    VALID_VARS = (
        "${ORIGINAL_NAME}",
        "${NODE_GROUP_ID}",
        "${NODE_GROUP_SIZE}",
        "${NODE_GROUP_INDEX}",
        "${TOTAL_UPLOAD_COUNT_AFTER}",
        "${CURRENT_FILE_INDEX}",
    )

    def validate(self) -> None:
        if self.file_name_template:
            for m in _VAR_RE.finditer(self.file_name_template):
                if m.group(0) not in self.VALID_VARS:
                    raise ValueError(
                        f"Storage config template contains invalid variable: {m.group(0)}"
                    )

    def to_dict(self) -> dict:
        return {"file_name_template": self.file_name_template}

    @classmethod
    def from_dict(cls, d: dict) -> "StorageConfig":
        return cls(file_name_template=d.get("file_name_template"))


def _validate_tpu_scheduler_plugin(cfg: "SchedulingConfig") -> None:
    """Malformed tpu_scheduler plugin config must be rejected at task
    creation — the batch matcher consumes these strings on its hot path."""
    if not cfg.plugins:
        return
    plug = cfg.plugins.get("tpu_scheduler")
    if not plug:
        return
    reps = plug.get("replicas")
    if reps:
        if not isinstance(reps[0], (str, int)):
            raise ValueError(f"invalid tpu_scheduler replicas: {reps[0]!r}")
        try:
            r = int(reps[0])
        except ValueError:
            raise ValueError(f"invalid tpu_scheduler replicas: {reps[0]!r}") from None
        if r <= 0:
            raise ValueError(f"tpu_scheduler replicas must be positive, got {r}")
    reqs = plug.get("compute_requirements")
    if reqs:
        if not isinstance(reqs[0], str):
            raise ValueError(
                f"invalid tpu_scheduler compute_requirements: {reqs[0]!r}"
            )
        from protocol_tpu.models.node import ComputeRequirements

        ComputeRequirements.parse(reqs[0])


@dataclass
class TaskMetadata:
    labels: Optional[dict[str, str]] = None

    def to_dict(self) -> dict:
        return {"labels": self.labels}

    @classmethod
    def from_dict(cls, d: dict) -> "TaskMetadata":
        return cls(labels=d.get("labels"))


@dataclass
class TaskRequest:
    """API-facing task creation payload (task.rs:144-155)."""

    image: str = ""
    name: str = ""
    env_vars: Optional[dict[str, str]] = None
    cmd: Optional[list[str]] = None
    entrypoint: Optional[list[str]] = None
    scheduling_config: Optional[SchedulingConfig] = None
    storage_config: Optional[StorageConfig] = None
    metadata: Optional[TaskMetadata] = None
    volume_mounts: Optional[list[VolumeMount]] = None

    @classmethod
    def from_dict(cls, d: dict) -> "TaskRequest":
        return cls(
            image=d.get("image", ""),
            name=d.get("name", ""),
            env_vars=d.get("env_vars"),
            cmd=d.get("cmd"),
            entrypoint=d.get("entrypoint"),
            scheduling_config=SchedulingConfig.from_dict(d["scheduling_config"])
            if d.get("scheduling_config")
            else None,
            storage_config=StorageConfig.from_dict(d["storage_config"])
            if d.get("storage_config")
            else None,
            metadata=TaskMetadata.from_dict(d["metadata"]) if d.get("metadata") else None,
            volume_mounts=[VolumeMount.from_dict(v) for v in d["volume_mounts"]]
            if d.get("volume_mounts")
            else None,
        )


@dataclass
class Task:
    name: str = ""
    id: str = field(default_factory=lambda: str(uuid.uuid4()))
    image: str = ""
    env_vars: Optional[dict[str, str]] = None
    cmd: Optional[list[str]] = None
    entrypoint: Optional[list[str]] = None
    state: TaskState = TaskState.UNKNOWN
    created_at: int = 0  # ms since epoch
    updated_at: Optional[int] = None
    scheduling_config: Optional[SchedulingConfig] = None
    storage_config: Optional[StorageConfig] = None
    metadata: Optional[TaskMetadata] = None
    volume_mounts: Optional[list[VolumeMount]] = None

    @classmethod
    def from_request(cls, request: TaskRequest) -> "Task":
        """Validated TaskRequest -> Task (task.rs:276-309)."""
        if request.storage_config is not None:
            request.storage_config.validate()
        if request.volume_mounts:
            for vm in request.volume_mounts:
                vm.validate()
        if request.scheduling_config is not None:
            _validate_tpu_scheduler_plugin(request.scheduling_config)
        return cls(
            name=request.name,
            image=request.image,
            cmd=request.cmd,
            entrypoint=request.entrypoint,
            env_vars=dict(request.env_vars) if request.env_vars else None,
            state=TaskState.PENDING,
            created_at=int(time.time() * 1000),
            scheduling_config=request.scheduling_config,
            storage_config=request.storage_config,
            metadata=request.metadata,
            volume_mounts=list(request.volume_mounts) if request.volume_mounts else None,
        )

    def generate_config_hash(self) -> str:
        """Stable digest of the runtime-relevant config (task.rs:187-221)."""
        h = hashlib.sha256()
        h.update(self.image.encode())
        h.update(json.dumps(self.cmd).encode())
        h.update(json.dumps(self.entrypoint).encode())
        if self.env_vars:
            for k in sorted(self.env_vars):
                h.update(k.encode())
                h.update(self.env_vars[k].encode())
        if self.volume_mounts:
            for vm in sorted(
                self.volume_mounts, key=lambda v: (v.host_path, v.container_path)
            ):
                h.update(vm.host_path.encode())
                h.update(vm.container_path.encode())
        return h.hexdigest()[:16]

    def allowed_topologies(self) -> list[str]:
        if self.scheduling_config is None:
            return []
        return self.scheduling_config.allowed_topologies()

    def to_dict(self) -> dict:
        d: dict[str, Any] = {
            "name": self.name,
            "id": self.id,
            "image": self.image,
            "env_vars": self.env_vars,
            "cmd": self.cmd,
            "entrypoint": self.entrypoint,
            "state": self.state.value,
            "created_at": self.created_at,
            "updated_at": self.updated_at,
        }
        if self.scheduling_config is not None:
            d["scheduling_config"] = self.scheduling_config.to_dict()
        if self.storage_config is not None:
            d["storage_config"] = self.storage_config.to_dict()
        if self.metadata is not None:
            d["metadata"] = self.metadata.to_dict()
        if self.volume_mounts is not None:
            d["volume_mounts"] = [vm.to_dict() for vm in self.volume_mounts]
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Task":
        return cls(
            name=d.get("name", ""),
            id=str(d.get("id") or uuid.uuid4()),
            image=d.get("image", ""),
            env_vars=d.get("env_vars"),
            cmd=d.get("cmd"),
            entrypoint=d.get("entrypoint"),
            state=TaskState.parse(d.get("state", "UNKNOWN")),
            created_at=int(d.get("created_at", 0)),
            updated_at=d.get("updated_at"),
            scheduling_config=SchedulingConfig.from_dict(d["scheduling_config"])
            if d.get("scheduling_config")
            else None,
            storage_config=StorageConfig.from_dict(d["storage_config"])
            if d.get("storage_config")
            else None,
            metadata=TaskMetadata.from_dict(d["metadata"]) if d.get("metadata") else None,
            volume_mounts=[VolumeMount.from_dict(v) for v in d["volume_mounts"]]
            if d.get("volume_mounts")
            else None,
        )

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True)

    @classmethod
    def from_json(cls, s: str) -> "Task":
        return cls.from_dict(json.loads(s))
