"""L0 data models: nodes, capability algebra, tasks, heartbeats, metrics.

Semantics mirror the reference's shared models
(/root/reference/crates/shared/src/models/) so that every control-plane
behavior (capability gating, scheduling, grouping, validation) can be
parity-tested against the reference's documented edge cases.
"""

from protocol_tpu.models.node import (
    ComputeRequirements,
    ComputeSpecs,
    CpuSpecs,
    DiscoveryNode,
    GpuRequirements,
    GpuSpecs,
    Node,
    NodeLocation,
)
from protocol_tpu.models.task import (
    SchedulingConfig,
    StorageConfig,
    Task,
    TaskRequest,
    TaskState,
    VolumeMount,
)
from protocol_tpu.models.heartbeat import HeartbeatRequest, TaskDetails
from protocol_tpu.models.metric import MetricEntry, MetricKey
from protocol_tpu.models.api import ApiResponse

__all__ = [
    "ApiResponse",
    "ComputeRequirements",
    "ComputeSpecs",
    "CpuSpecs",
    "DiscoveryNode",
    "GpuRequirements",
    "GpuSpecs",
    "HeartbeatRequest",
    "MetricEntry",
    "MetricKey",
    "Node",
    "NodeLocation",
    "SchedulingConfig",
    "StorageConfig",
    "Task",
    "TaskDetails",
    "TaskRequest",
    "TaskState",
    "VolumeMount",
]
