"""protocol_tpu — a TPU-native decentralized compute-orchestration framework.

A ground-up rebuild of the capabilities of PrimeIntellect-ai/protocol
(reference mounted at /root/reference, a Rust workspace of 7 crates:
p2p / shared / discovery / orchestrator / validator / worker / dev-utils),
re-designed TPU-first:

- The orchestrator's job<->worker matching hot loop
  (reference: crates/orchestrator/src/scheduler/mod.rs:26-74, an O(tasks)
  greedy matcher run per worker heartbeat) is lifted into batched JAX
  assignment kernels (vectorized first-fit-decreasing, Sinkhorn optimal
  transport, Bertsekas auction) over a provider x task cost tensor,
  sharded provider-wise across a TPU mesh via shard_map.
- The control plane (discovery registry, pool orchestrator, worker agent,
  validator, signed-HTTP security, heartbeat health FSM, node groups /
  gang scheduling) preserves the reference's behavior and API surface in
  asyncio Python services.
- The economic substrate (the reference's Ethereum contracts, absent as an
  empty submodule there) is provided as an in-process ledger implementing
  the same operation surface as the reference's contract wrappers
  (crates/shared/src/web3/contracts/).

Subpackages:
  models    - Node/ComputeSpecs/ComputeRequirements/Task/... data model (L0)
  ops       - JAX assignment kernels + feature encoding (L3)
  parallel  - mesh construction and sharded kernel variants
  sched     - Scheduler interface, CPU parity backend, TPU backend, plugins
  store     - redis-semantics in-process state store + domain stores (L1)
  security  - wallet, request signing, signature-validation middleware
  services  - discovery / orchestrator / worker / validator services
  chain     - in-process ledger (contract-wrapper-surface equivalent)
  utils     - storage providers, misc helpers
"""

__version__ = "0.1.0"
