"""Deterministic trace replay: feed a recorded workload through any
engine and transport, verify recorded outcomes bit-for-bit, and localize
the first divergence.

The replayer is the flight recorder's other half. A trace fixes the
exact solve inputs per tick (epoch snapshot + churned-row deltas); the
engines are bit-identical for every thread count (the -mt determinism
contract) and the session/unary seams solve the same padded columns, so
replaying a trace through

  * ``native-mt`` / ``sinkhorn-mt`` in-process (the arena),
  * the v1 unary wire (full snapshot per tick, servicer warm arena), or
  * the v2 session wire (streamed snapshot + AssignDelta ticks)

must reproduce the recorded ``provider_for_task`` bit-for-bit. When it
does not, the report names the first divergent tick and the exact row
set — a solver regression localizes to "tick 12, rows [841, 2207]"
instead of "the bench got slower". ``engine="jax"`` replays through the
accelerator-path warm arena (parallel/jax_arena.py) on every transport:
bit-identical against a jax-recorded golden, honest divergence + the
``compare()`` tolerance table against a native recording.

``compare()`` replays the same trace under two configs side by side —
the A/B harness every perf PR can now cite instead of hand-rolled bench
deltas.
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from protocol_tpu.obs.spans import TRACER as _tracer, span_dicts_compact
from protocol_tpu.proto import scheduler_pb2 as pb
from protocol_tpu.proto import wire
from protocol_tpu.trace import format as tfmt

_ENGINES = ("native-mt", "sinkhorn-mt", "jax")
_TRANSPORTS = ("inproc", "wire-v1", "wire-v2")
_ARENA_ENGINE = {
    "native-mt": "auction",
    "sinkhorn-mt": "sinkhorn",
    "jax": "jax",
}


def parse_engine(kernel: str) -> tuple[str, int]:
    """``native-mt[:N]`` / ``sinkhorn-mt[:N]`` / ``jax[:D]`` ->
    (engine, threads — sharded-gen devices for the jax engine)."""
    base, _, suffix = kernel.partition(":")
    if base not in _ENGINES:
        raise ValueError(
            f"engine must be one of {_ENGINES}, got {kernel!r}"
        )
    return base, (int(suffix) if suffix else 0)


def _kernel_str(engine: str, threads: int) -> str:
    return f"{engine}:{threads}" if threads else engine


def iter_input_ticks(trace: tfmt.Trace):
    """Yield ``(tick, p_cols, r_cols, delta_or_None)`` with the columns
    updated through each recorded delta (tick 0 = the snapshot itself).
    Columns are fresh copies per churned column (copy-on-write), so
    callers may hold references across ticks."""
    snap = trace.snapshot
    if snap is None:
        raise ValueError(f"{trace.path}: no snapshot frame (empty trace?)")
    p_cols = dict(snap.p_cols)
    r_cols = dict(snap.r_cols)
    yield 0, p_cols, r_cols, None
    for i, d in enumerate(trace.deltas, start=1):
        # fresh dicts BEFORE mutating: the previously-yielded dicts must
        # never change under a caller holding them
        p_cols, r_cols = dict(p_cols), dict(r_cols)
        for rows, delta, cols in (
            (d.provider_rows, d.p_cols, p_cols),
            (d.task_rows, d.r_cols, r_cols),
        ):
            if not rows.size:
                continue
            for name, vals in delta.items():
                col = cols[name].copy()
                col[rows] = vals
                cols[name] = col
        yield i, p_cols, r_cols, d


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class _InprocArena:
    """Transport "inproc": the session path minus the wire — identical
    pow2 padding (session_store._pad_cols) and arena construction, so
    in-process and wire-v2 replays are bit-identical by construction.
    ``engine="jax"`` gets the warm accelerator-path arena through the
    same factory the servicer uses (threads = sharded-gen devices)."""

    def __init__(self, snap: tfmt.Snapshot, engine: str, threads: int):
        from protocol_tpu.services.session_store import make_solve_arena

        self.engine = engine
        self.threads = threads
        self.top_k = max(int(snap.top_k) or 64, 1)
        self.arena = make_solve_arena(
            _ARENA_ENGINE[engine], k=self.top_k, threads=threads
        )
        self.weights = None  # set per solve

    def solve(self, snap, p_cols, r_cols) -> tuple[np.ndarray, dict]:
        from protocol_tpu.services.session_store import _pad_cols

        from protocol_tpu.ops.cost import CostWeights

        n_p, n_t = snap.n_providers, snap.n_tasks
        pp = _pad_cols(p_cols, n_p)
        rp = _pad_cols(r_cols, n_t)
        w = CostWeights(*snap.weights)
        p4t = self.arena.solve(tfmt._as_ns(pp), tfmt._as_ns(rp), w)
        return np.asarray(p4t, np.int32)[:n_t], self.arena.last_stats

    def close(self) -> None:
        pass


class _WireTransport:
    """Loopback gRPC replay: "wire-v1" ships a full v1 snapshot per tick
    (the servicer's warm unary arena solves the churn); "wire-v2" runs
    the real session protocol (streamed snapshot + AssignDelta)."""

    def __init__(self, snap: tfmt.Snapshot, engine: str, threads: int,
                 wire_version: str):
        from protocol_tpu.services.scheduler_grpc import (
            SchedulerBackendClient,
            serve,
        )

        self.kernel = _kernel_str(engine, threads)
        self.top_k = max(int(snap.top_k) or 64, 1)
        self.wire_version = wire_version
        port = _free_port()
        self.server = serve(f"127.0.0.1:{port}")
        self.client = SchedulerBackendClient(f"127.0.0.1:{port}")
        self._fp: Optional[str] = None
        self._tick = 0
        self.bytes_out = 0
        self.bytes_in = 0

    def _request_v2(self, snap, p_cols, r_cols) -> pb.AssignRequestV2:
        return pb.AssignRequestV2(
            providers=wire.encode_providers_v2(tfmt._as_ns(p_cols)),
            requirements=wire.encode_requirements_v2(tfmt._as_ns(r_cols)),
            weights=pb.CostWeights(
                price=snap.weights[0], load=snap.weights[1],
                proximity=snap.weights[2], priority=snap.weights[3],
            ),
            kernel=self.kernel, top_k=self.top_k, eps=snap.eps,
            max_iters=snap.max_iters,
        )

    def solve(self, snap, p_cols, r_cols, delta=None):
        if self.wire_version == "v1":
            from protocol_tpu.services.scheduler_grpc import encoded_to_proto

            from protocol_tpu.ops.cost import CostWeights

            req = encoded_to_proto(
                tfmt._as_ns(p_cols), tfmt._as_ns(r_cols),
                CostWeights(*snap.weights),
                kernel=self.kernel, top_k=self.top_k, eps=snap.eps,
                max_iters=snap.max_iters,
            )
            resp = self.client.assign(req, timeout=600)
            self.bytes_out += req.ByteSize()
            self.bytes_in += resp.ByteSize()
            p4t = np.fromiter(
                resp.provider_for_task, np.int32,
                count=len(resp.provider_for_task),
            )
            return p4t, {"solve_ms": resp.solve_ms}

        # ---- v2 session protocol
        if self._fp is None:
            w = tfmt._as_ns(
                dict(zip(
                    ("price", "load", "proximity", "priority"), snap.weights
                ))
            )
            self._fp = wire.epoch_fingerprint(
                p_cols, r_cols, w, self.kernel, self.top_k, snap.eps,
                snap.max_iters,
            )
            req = self._request_v2(snap, p_cols, r_cols)
            chunks = list(
                wire.chunk_snapshot("replay", self._fp, req)
            )
            resp = self.client.open_session(iter(chunks), timeout=600)
            if not resp.ok:
                raise RuntimeError(f"OpenSession refused: {resp.error}")
            self.bytes_out += sum(len(c.payload) for c in chunks)
            self.bytes_in += resp.ByteSize()
            self._tick = 0
            p4t = wire.unblob(resp.result.provider_for_task, np.int32)
            return p4t, {"solve_ms": resp.result.solve_ms}

        self._tick += 1
        req = pb.AssignDeltaRequest(
            session_id="replay", epoch_fingerprint=self._fp, tick=self._tick
        )
        if delta is not None and delta.provider_rows.size:
            req.provider_rows.CopyFrom(
                wire.blob(delta.provider_rows, np.int32)
            )
            req.providers.CopyFrom(
                wire.encode_providers_v2(tfmt._as_ns(delta.p_cols))
            )
        if delta is not None and delta.task_rows.size:
            req.task_rows.CopyFrom(wire.blob(delta.task_rows, np.int32))
            req.requirements.CopyFrom(
                wire.encode_requirements_v2(tfmt._as_ns(delta.r_cols))
            )
        resp = self.client.assign_delta(req, timeout=600)
        if not resp.session_ok:
            raise RuntimeError(
                f"AssignDelta tick {self._tick} refused: {resp.error}"
            )
        self.bytes_out += req.ByteSize()
        self.bytes_in += resp.ByteSize()
        p4t = wire.unblob(resp.result.provider_for_task, np.int32)
        return p4t, {"solve_ms": resp.result.solve_ms}

    def close(self) -> None:
        self.client.close()
        self.server.stop(grace=None)


def replay(
    trace_path: str,
    engine: Optional[str] = None,
    threads: Optional[int] = None,
    transport: str = "inproc",
    verify: bool = True,
    record_path: Optional[str] = None,
    max_ticks: Optional[int] = None,
    keep_p4t: bool = False,
) -> dict:
    """Replay a trace. Returns the report dict; ``report["divergence"]``
    is None when every verified tick reproduced the recorded assignments
    bit-for-bit (the empty divergence report), else it names the first
    divergent tick and row set.

    ``engine``/``threads`` default to the trace's recorded kernel string;
    ``transport`` is inproc | wire-v1 | wire-v2. ``record_path`` writes a
    new trace with this replay's outcomes (how golden traces are made).
    """
    if transport not in _TRANSPORTS:
        raise ValueError(
            f"transport must be one of {_TRANSPORTS}, got {transport!r}"
        )
    trace = tfmt.read_trace(trace_path)
    snap = trace.snapshot
    if snap is None:
        raise ValueError(f"{trace_path}: no snapshot frame")
    if engine:
        eng, eng_threads = parse_engine(engine)
    else:
        try:
            eng, eng_threads = parse_engine(snap.kernel or "native-mt")
        except ValueError:
            # captured from a kernel with no replay engine (e.g. the jax
            # "auction"/"greedy" unary kernels): refuse with direction
            # instead of a bare parse error — replaying through a
            # different engine cannot verify bit-for-bit anyway
            raise ValueError(
                f"{trace_path} records kernel {snap.kernel!r}, which has "
                f"no replay engine; pass engine= (one of {_ENGINES}) to "
                "replay it through an explicit engine (outcome "
                "verification will then report honest divergence)"
            )
    n_threads = eng_threads if threads is None else int(threads)

    # Pin the float pipeline to the one that PRODUCED the trace:
    # bit-for-bit outcome verification is only meaningful under the same
    # per-ISA pipeline (the determinism contract is within-ISA). Pre-ISA
    # traces carry no tag and were recorded by the historical scalar
    # pipeline. A host that cannot run the recorded ISA clamps down and
    # verification reports honest divergence (never a crash). The jax
    # engine never touches the native pipeline — no pin.
    pinned_isa: Optional[str] = None
    prev_isa_env: Optional[str] = None
    prev_isa_eff: Optional[str] = None
    effective_isa: Optional[str] = None
    if eng != "jax":
        import os as _os

        from protocol_tpu import native as _native

        pinned_isa = str(trace.meta.get("recorded_isa", "scalar"))
        prev_isa_env = _os.environ.get("PROTOCOL_TPU_NATIVE_ISA")
        try:
            prev_isa_eff = _native.current_isa()
            effective_isa = _native.set_isa(pinned_isa)
        except _native.NativeBuildError:
            pinned_isa = None  # no toolchain: backends will fail honestly

    if transport == "inproc":
        backend = _InprocArena(snap, eng, n_threads)
    else:
        backend = _WireTransport(
            snap, eng, n_threads, transport.split("-")[1]
        )

    writer = None
    if record_path is not None:
        meta = dict(trace.meta)
        meta.pop("version", None)
        meta.update(
            recorded_engine=eng, recorded_threads=n_threads,
            recorded_transport=transport, source_trace=trace_path,
        )
        if effective_isa is not None:
            # provenance for the NEXT replay's pin (and the CI
            # replay-identity job's audit of committed goldens)
            meta["recorded_isa"] = effective_isa
        writer = tfmt.TraceWriter(record_path, meta=meta)
        # the recorded epoch carries the kernel that actually solved it
        rsnap = tfmt.Snapshot(
            trace_id=snap.trace_id, fingerprint="", p_cols=snap.p_cols,
            r_cols=snap.r_cols, weights=snap.weights,
            kernel=_kernel_str(eng, n_threads), top_k=snap.top_k,
            eps=snap.eps, max_iters=snap.max_iters,
        )
        fp = wire.epoch_fingerprint(
            snap.p_cols, snap.r_cols,
            tfmt._as_ns(dict(zip(
                ("price", "load", "proximity", "priority"), snap.weights
            ))),
            rsnap.kernel, max(int(snap.top_k) or 64, 1), snap.eps,
            snap.max_iters,
        )
        writer.write_snapshot(snap.trace_id, fp, rsnap.request_v2())

    report: dict = {
        "trace": trace_path,
        "engine": eng,
        "threads": n_threads,
        "transport": transport,
        "recorded_kernel": snap.kernel,
        "providers": snap.n_providers,
        "tasks": snap.n_tasks,
        "ticks": 0,
        "verified_ticks": 0,
        "divergence": None,
        "tick_wall_ms": [],
        "assigned": [],
    }
    p4ts: list = []
    tick_stats: list = []  # scalar per-tick stats (quality plane)
    try:
        for tick, p_cols, r_cols, delta in iter_input_ticks(trace):
            if max_ticks is not None and tick >= max_ticks:
                break
            t0 = time.perf_counter()
            # root span per tick: the arena/servicer/client spans this
            # solve produces stitch under it, and a recording replay
            # lands them in the OUTCOME frame for the obs report
            mark = _tracer.mark()
            with _tracer.span("replay.tick", tick=tick) as root:
                if isinstance(backend, _WireTransport):
                    p4t, stats = backend.solve(snap, p_cols, r_cols, delta)
                else:
                    p4t, stats = backend.solve(snap, p_cols, r_cols)
            wall_ms = (time.perf_counter() - t0) * 1e3
            report["ticks"] += 1
            report["tick_wall_ms"].append(round(wall_ms, 3))
            report["assigned"].append(int((p4t >= 0).sum()))
            tick_stats.append({
                k: v for k, v in (stats or {}).items()
                if isinstance(v, (int, float, bool))
            })
            if keep_p4t:
                p4ts.append(p4t)
            if writer is not None:
                if delta is not None:
                    writer.write_delta_cols(
                        tick, delta.provider_rows, delta.p_cols,
                        delta.task_rows, delta.r_cols, events=delta.events,
                    )
                metrics = {"wall_ms": round(wall_ms, 3)}
                metrics.update(
                    {k: v for k, v in (stats or {}).items()
                     if isinstance(v, (int, float, bool, str))}
                )
                if root is not None:
                    spans = _tracer.since(mark, trace=root["trace"])
                    if spans:
                        metrics["trace_id"] = root["trace"]
                        metrics["spans"] = span_dicts_compact(spans)
                writer.write_outcome(tick, p4t, metrics=metrics)
            if verify:
                rec = trace.outcome_for(tick)
                if rec is not None:
                    report["verified_ticks"] += 1
                    if not np.array_equal(p4t, rec.provider_for_task):
                        rows = np.flatnonzero(
                            p4t != rec.provider_for_task
                        )
                        report["divergence"] = {
                            "tick": tick,
                            "n_rows": int(rows.size),
                            "rows": rows[:64].tolist(),
                            "recorded_assigned": rec.num_assigned,
                            "replayed_assigned": int((p4t >= 0).sum()),
                        }
                        break  # localized: first divergent tick + rows
    finally:
        backend.close()
        if writer is not None:
            writer.close()
        if pinned_isa is not None:
            # restore the caller's ISA selection (the pin is scoped to
            # this replay, not the process): the env var goes back to
            # its prior state and the engine back to its prior
            # EFFECTIVE isa (which may be a baked variant default, not
            # scalar)
            import os as _os

            from protocol_tpu import native as _native

            if prev_isa_env is None:
                _os.environ.pop("PROTOCOL_TPU_NATIVE_ISA", None)
            else:
                _os.environ["PROTOCOL_TPU_NATIVE_ISA"] = prev_isa_env
            try:
                if prev_isa_eff is not None:
                    _native._apply_isa(_native.load(), prev_isa_eff)
            except _native.NativeBuildError:
                pass

    walls = report["tick_wall_ms"]
    if walls:
        report["cold_ms"] = walls[0]
        if len(walls) > 1:
            report["warm_mean_ms"] = round(float(np.mean(walls[1:])), 3)
            report["warm_median_ms"] = round(
                float(np.median(walls[1:])), 3
            )
            # true distribution numbers (obs plane): what the fleet/
            # streaming gates will hold, not just means
            from protocol_tpu.obs.metrics import percentiles_ms

            report["warm_percentiles"] = percentiles_ms(walls[1:])
    quality = _aggregate_quality(tick_stats)
    if quality is not None:
        report["quality"] = quality
    if isinstance(backend, _WireTransport):
        report["wire_bytes_out"] = backend.bytes_out
        report["wire_bytes_in"] = backend.bytes_in
    if keep_p4t:
        report["p4ts"] = p4ts
    return report


def _aggregate_quality(tick_stats: list) -> Optional[dict]:
    """Roll the per-tick quality scalars (arena last_stats through the
    inproc backends; wire replays report quality server-side) into the
    replay report — the shared canonical roll-up (certified gap, plan
    churn over warm ticks, starvation, outcome-cause totals with the
    zero-unexplained invariant the CI quality gate holds)."""
    from protocol_tpu.obs.quality import aggregate_quality

    return aggregate_quality(tick_stats)


def compare(
    trace_path: str,
    config_a: dict,
    config_b: dict,
    max_ticks: Optional[int] = None,
) -> dict:
    """Replay one trace under two configs side by side (the A/B perf
    harness). Each config is {engine, threads, transport}. Reports both
    replays' timing/assignment stats plus a tick-wise matching diff."""
    a = replay(
        trace_path, verify=False, keep_p4t=True, max_ticks=max_ticks,
        **config_a,
    )
    b = replay(
        trace_path, verify=False, keep_p4t=True, max_ticks=max_ticks,
        **config_b,
    )
    n = min(len(a["p4ts"]), len(b["p4ts"]))
    first_diff = None
    diff_rows = 0
    for t in range(n):
        d = int((a["p4ts"][t] != b["p4ts"][t]).sum())
        diff_rows += d
        if d and first_diff is None:
            first_diff = t
    out = {
        "trace": trace_path,
        "a": {k: v for k, v in a.items() if k != "p4ts"},
        "b": {k: v for k, v in b.items() if k != "p4ts"},
        "identical": first_diff is None,
        "first_divergent_tick": first_diff,
        "divergent_rows_total": diff_rows,
    }
    if a.get("warm_mean_ms") and b.get("warm_mean_ms"):
        out["warm_speedup_b_over_a"] = round(
            a["warm_mean_ms"] / b["warm_mean_ms"], 3
        )
    # quality deltas, not just bit-identity: the A/B answer for "the
    # plans differ — by how MUCH, and who pays" (the streaming roadmap
    # item gates its bounded-staleness contract on exactly this)
    qa, qb = a.get("quality"), b.get("quality")
    if qa and qb:
        delta = {
            "gap_per_task_delta": round(
                qb["gap_per_task_mean"] - qa["gap_per_task_mean"], 6
            ),
            "starve_max_delta": qb["starve_max"] - qa["starve_max"],
        }
        if qa.get("plan_cost_mean"):
            delta["plan_cost_ratio_b_over_a"] = round(
                qb["plan_cost_mean"] / qa["plan_cost_mean"], 6
            )
        if (
            qa.get("churn_ratio_mean") is not None
            and qb.get("churn_ratio_mean") is not None
        ):
            delta["churn_ratio_delta"] = round(
                qb["churn_ratio_mean"] - qa["churn_ratio_mean"], 6
            )
        out["quality_delta"] = delta
    if a.get("assigned") and b.get("assigned"):
        out["assigned_min_delta"] = min(b["assigned"]) - min(a["assigned"])
    return out
