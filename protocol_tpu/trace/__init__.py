"""Cluster flight recorder: deterministic trace capture & replay for the
scheduler seam.

  format.py    versioned, gzip-framed, append-only trace format (wire-v2
               TensorBlob column codecs; torn tails tolerated)
  recorder.py  capture hooks behind PROTOCOL_TPU_TRACE=<path> (matcher,
               gRPC servicer, session delta application)
  replay.py    deterministic replayer — any engine, any transport,
               bit-for-bit outcome verification + divergence localization
  synth.py     parameterized workload generators (the single source of
               synthetic populations) and the trace factory

CLI: ``python -m protocol_tpu.trace {synth,record,replay,info}``.
"""

from protocol_tpu.trace.format import (  # noqa: F401
    P_TRACE_DTYPES,
    R_TRACE_DTYPES,
    Trace,
    TraceWriter,
    read_trace,
)
from protocol_tpu.trace.recorder import TraceRecorder  # noqa: F401
from protocol_tpu.trace.replay import compare, replay  # noqa: F401

__all__ = [
    "P_TRACE_DTYPES",
    "R_TRACE_DTYPES",
    "Trace",
    "TraceWriter",
    "read_trace",
    "TraceRecorder",
    "compare",
    "replay",
]
