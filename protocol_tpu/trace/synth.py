"""Synthetic workload generators — the single source of synthetic
populations for every bench/script/test in this repo, and the trace
factory behind ``python -m protocol_tpu.trace synth``.

Before the flight recorder, three scripts (bench.py, bench_scaling.py,
scripts/warm_chain_1m.py) each carried their own inline copy of the
marketplace generator; numbers measured on "the 16k synthetic fleet"
were never provably the SAME fleet. Now the generators live here, and
:func:`synth_trace` freezes a parameterized workload — churn rate, pool
growth/shrink via validity headroom, hotspot bursts, mass-disconnect —
into a trace file any engine can replay bit-reproducibly.

Generators are numpy-only and seeded; the same (seed, shape, knobs)
always emits byte-identical traces (the frame codec is deterministic
DEFLATE — see trace/format.py).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

MODEL_CLASSES = 12
MODEL_WORDS = 8
MAX_GPU_OPTS = 2


def synth_providers(rng: np.random.Generator, n: int):
    """Vectorized synthetic provider encodings, numpy-backed (host-side);
    device_put the tree to place it on an accelerator."""
    from protocol_tpu.ops.encoding import EncodedProviders

    model = rng.integers(0, MODEL_CLASSES, n).astype(np.int32)
    count = rng.choice([1, 2, 4, 8], n).astype(np.int32)
    mem = rng.choice([16000, 24000, 40000, 80000], n).astype(np.int32)
    return EncodedProviders(
        gpu_count=count,
        gpu_mem_mb=mem,
        gpu_model_id=model,
        has_gpu=np.ones(n, bool),
        has_cpu=np.ones(n, bool),
        cpu_cores=rng.choice([8, 16, 32, 64], n).astype(np.int32),
        ram_mb=rng.choice([32768, 65536, 131072], n).astype(np.int32),
        storage_gb=rng.choice([500, 1000, 4000], n).astype(np.int32),
        lat=np.radians(rng.uniform(-60, 60, n)).astype(np.float32),
        lon=np.radians(rng.uniform(-180, 180, n)).astype(np.float32),
        has_location=np.ones(n, bool),
        price=rng.uniform(0.5, 4.0, n).astype(np.float32),
        load=rng.uniform(0, 1, n).astype(np.float32),
        valid=np.ones(n, bool),
    )


def synth_requirements(rng: np.random.Generator, n: int):
    from protocol_tpu.ops.encoding import EncodedRequirements

    k, w = MAX_GPU_OPTS, MODEL_WORDS
    # each task accepts a random subset of model classes (OR alternatives)
    mask = np.zeros((n, k, w), np.uint32)
    accept = rng.random((n, MODEL_CLASSES)) < 0.4
    accept[np.arange(n), rng.integers(0, MODEL_CLASSES, n)] = True  # >=1 class
    for c in range(MODEL_CLASSES):
        mask[:, 0, c >> 5] |= np.where(
            accept[:, c], np.uint32(1) << np.uint32(c & 31), 0
        ).astype(np.uint32)
    opt_valid = np.zeros((n, k), bool)
    opt_valid[:, 0] = True
    count = np.full((n, k), -1, np.int32)
    count[:, 0] = rng.choice(
        [-1, 1, 2, 4, 8], n, p=[0.4, 0.15, 0.15, 0.15, 0.15]
    )
    mem_min = np.full((n, k), -1, np.int32)
    mem_min[:, 0] = rng.choice([-1, 16000, 40000], n, p=[0.5, 0.3, 0.2])
    return EncodedRequirements(
        cpu_required=np.zeros(n, bool),
        cpu_cores=rng.choice([-1, 8, 16], n, p=[0.5, 0.3, 0.2]).astype(
            np.int32
        ),
        ram_mb=rng.choice([-1, 32768], n, p=[0.6, 0.4]).astype(np.int32),
        storage_gb=rng.choice([-1, 500], n, p=[0.7, 0.3]).astype(np.int32),
        gpu_opt_valid=opt_valid,
        gpu_count=count,
        gpu_mem_min=mem_min,
        gpu_mem_max=np.full((n, k), -1, np.int32),
        gpu_total_mem_min=np.full((n, k), -1, np.int32),
        gpu_total_mem_max=np.full((n, k), -1, np.int32),
        gpu_model_mask=mask,
        gpu_model_constrained=opt_valid.copy(),
        lat=np.radians(rng.uniform(-60, 60, n)).astype(np.float32),
        lon=np.radians(rng.uniform(-180, 180, n)).astype(np.float32),
        has_location=np.ones(n, bool),
        priority=np.zeros(n, np.float32),
        valid=np.ones(n, bool),
    )


def synth_uniform_candidates(
    rng: np.random.Generator, t: int, p: int, k: int = 80,
    cost_hi: float = 10.0,
) -> tuple[np.ndarray, np.ndarray]:
    """Execution-evidence-at-shape candidate lists (the 1M warm-chain /
    stage-B smoke population): uniform random [T, K] provider ids + costs,
    no feature structure. Quality evidence belongs to the real-feature
    generators above."""
    cand_p = rng.integers(0, p, size=(t, k), dtype=np.int32)
    cand_c = rng.uniform(0.0, cost_hi, size=(t, k)).astype(np.float32)
    return cand_p, cand_c


# ---------------- trace factory ----------------


class _W:
    """Weights namespace for wire.epoch_fingerprint (CostWeights without
    the ops/cost import)."""

    def __init__(self, w: tuple):
        self.price, self.load, self.proximity, self.priority = (
            float(x) for x in w
        )


# CostWeights defaults (ops/cost.py) restated — synth stays importable
# without pulling the jax-backed cost module
DEFAULT_WEIGHTS = (1.0, 1.0, 0.001, 0.0)


def synth_trace(
    path: str,
    n_providers: int = 1024,
    n_tasks: int = 1024,
    ticks: int = 16,
    churn: float = 0.01,
    task_churn: float = 0.0,
    seed: int = 0,
    kernel: str = "native-mt",
    top_k: int = 64,
    eps: float = 0.02,
    max_iters: int = 0,
    weights: tuple = DEFAULT_WEIGHTS,
    headroom: float = 0.0,
    growth: float = 0.0,
    hotspot_every: int = 0,
    hotspot_frac: float = 0.05,
    disconnect_at: int = 0,
    disconnect_frac: float = 0.25,
    reconnect_after: int = 0,
    compresslevel: int = 6,
) -> str:
    """Write an input-only trace (no outcomes — ``replay --record`` adds
    them) for a parameterized synthetic workload.

    Knobs:
      churn           fraction of LIVE provider rows whose price/load
                      drift each tick (the per-heartbeat common case)
      task_churn      fraction of task rows re-rolled each tick
                      (requirement churn — structural, re-candidates)
      headroom        fraction of provider rows that start valid=False
                      (the join pool growth draws from; row counts are
                      fixed per epoch, so lifecycle is a validity flip)
      growth          fraction of remaining headroom activated per tick
                      (node-join events); negative = steady shrink
      hotspot_every   every N ticks, burst-load a geographic cluster
                      (hotspot_frac of providers nearest a random center)
      disconnect_at   at tick N, mass-disconnect disconnect_frac of live
                      providers (valid=False) — the failure-domain drill;
                      reconnect_after ticks later they return churned

    Returns ``path``.
    """
    from protocol_tpu.proto import scheduler_pb2 as pb
    from protocol_tpu.proto import wire
    from protocol_tpu.trace import format as tfmt

    rng = np.random.default_rng(seed)
    ep = synth_providers(rng, n_providers)
    er = synth_requirements(rng, n_tasks)
    p_cols = wire.canon_columns(ep, tfmt.P_TRACE_DTYPES)
    r_cols = wire.canon_columns(er, tfmt.R_TRACE_DTYPES)
    if headroom > 0:
        n_off = int(n_providers * headroom)
        if n_off:
            valid = p_cols["valid"].copy()
            valid[rng.choice(n_providers, n_off, replace=False)] = False
            p_cols["valid"] = valid

    wns = _W(weights)
    fp = wire.epoch_fingerprint(
        p_cols, r_cols, wns, kernel, top_k, eps, max_iters
    )
    req = pb.AssignRequestV2(
        providers=wire.encode_providers_v2(tfmt._as_ns(p_cols)),
        requirements=wire.encode_requirements_v2(tfmt._as_ns(r_cols)),
        weights=pb.CostWeights(
            price=wns.price, load=wns.load,
            proximity=wns.proximity, priority=wns.priority,
        ),
        kernel=kernel, top_k=top_k, eps=eps, max_iters=max_iters,
    )
    meta = {
        "generator": "synth_trace",
        "seed": seed,
        "n_providers": n_providers,
        "n_tasks": n_tasks,
        "ticks": ticks,
        "churn": churn,
        "task_churn": task_churn,
        "headroom": headroom,
        "growth": growth,
        "hotspot_every": hotspot_every,
        "disconnect_at": disconnect_at,
    }
    disconnected: Optional[np.ndarray] = None
    with tfmt.TraceWriter(path, meta=meta,
                          compresslevel=compresslevel) as w:
        w.write_snapshot(f"synth-{seed}", fp, req)
        for tick in range(1, ticks + 1):
            prev_p = dict(p_cols)
            prev_r = dict(r_cols)
            events: list = []

            # price/load drift on a random slice of the LIVE fleet
            live = np.flatnonzero(p_cols["valid"])
            n_drift = int(live.size * churn)
            if n_drift:
                rows = rng.choice(live, n_drift, replace=False)
                price = p_cols["price"].copy()
                load = p_cols["load"].copy()
                price[rows] = rng.uniform(0.5, 4.0, rows.size).astype(
                    np.float32
                )
                load[rows] = rng.uniform(0, 1, rows.size).astype(np.float32)
                p_cols["price"], p_cols["load"] = price, load
                events.append({"kind": "heartbeat_drift", "rows": n_drift})

            # requirement churn: re-roll a slice of tasks entirely
            n_tchurn = int(n_tasks * task_churn)
            if n_tchurn:
                rows = rng.choice(n_tasks, n_tchurn, replace=False)
                fresh = wire.canon_columns(
                    synth_requirements(rng, n_tchurn), tfmt.R_TRACE_DTYPES
                )
                for name in r_cols:
                    col = r_cols[name].copy()
                    col[rows] = fresh[name]
                    r_cols[name] = col
                events.append({"kind": "task_churn", "rows": n_tchurn})

            # pool growth/shrink via the validity headroom
            if growth > 0:
                off = np.flatnonzero(~p_cols["valid"])
                n_join = int(off.size * growth)
                if n_join:
                    rows = rng.choice(off, n_join, replace=False)
                    valid = p_cols["valid"].copy()
                    valid[rows] = True
                    p_cols["valid"] = valid
                    events.append({"kind": "node_join", "rows": n_join})
            elif growth < 0:
                on = np.flatnonzero(p_cols["valid"])
                n_leave = int(on.size * -growth)
                if n_leave:
                    rows = rng.choice(on, n_leave, replace=False)
                    valid = p_cols["valid"].copy()
                    valid[rows] = False
                    p_cols["valid"] = valid
                    events.append({"kind": "node_leave", "rows": n_leave})

            # hotspot burst: max out load around a random geo center
            if hotspot_every and tick % hotspot_every == 0:
                lat0 = rng.uniform(-1.0, 1.0)
                lon0 = rng.uniform(-np.pi, np.pi)
                d2 = (p_cols["lat"] - lat0) ** 2 + (p_cols["lon"] - lon0) ** 2
                n_hot = max(int(n_providers * hotspot_frac), 1)
                rows = np.argsort(d2, kind="stable")[:n_hot]
                load = p_cols["load"].copy()
                load[rows] = np.float32(1.0)
                p_cols["load"] = load
                events.append({"kind": "hotspot_burst", "rows": n_hot})

            # mass disconnect / delayed reconnect
            if disconnect_at and tick == disconnect_at:
                on = np.flatnonzero(p_cols["valid"])
                n_down = int(on.size * disconnect_frac)
                if n_down:
                    disconnected = rng.choice(on, n_down, replace=False)
                    valid = p_cols["valid"].copy()
                    valid[disconnected] = False
                    p_cols["valid"] = valid
                    events.append(
                        {"kind": "mass_disconnect", "rows": n_down}
                    )
            if (
                disconnected is not None
                and reconnect_after
                and tick == disconnect_at + reconnect_after
            ):
                valid = p_cols["valid"].copy()
                valid[disconnected] = True
                p_cols["valid"] = valid
                price = p_cols["price"].copy()
                price[disconnected] = rng.uniform(
                    0.5, 4.0, disconnected.size
                ).astype(np.float32)
                p_cols["price"] = price
                events.append(
                    {"kind": "mass_reconnect", "rows": int(disconnected.size)}
                )
                disconnected = None

            prow = wire.dirty_rows(p_cols, prev_p)
            trow = wire.dirty_rows(r_cols, prev_r)
            w.write_delta_cols(
                tick,
                prow,
                {n: a[prow] for n, a in p_cols.items()} if prow.size else None,
                trow,
                {n: a[trow] for n, a in r_cols.items()} if trow.size else None,
                events=events,
            )
    return path


# ---------------- event-trace factory (streaming workloads) ----------


def synth_event_trace(
    path: str,
    n_providers: int = 1024,
    n_tasks: int = 1024,
    events: int = 256,
    seed: int = 0,
    kernel: str = "native-mt",
    top_k: int = 64,
    eps: float = 0.02,
    max_iters: int = 0,
    weights: tuple = DEFAULT_WEIGHTS,
    rate_hz: float = 1000.0,
    heartbeat_w: float = 0.7,
    join_w: float = 0.1,
    leave_w: float = 0.1,
    task_w: float = 0.1,
    headroom: float = 0.1,
    mass_every: int = 0,
    mass_frac: float = 0.1,
    reconcile_every: int = 64,
    compresslevel: int = 6,
) -> str:
    """Write a STREAM trace: one DELTA frame per churn event, each
    carrying the full current row state for its rows plus the stream
    meta ``{kind, source, seq, at_us}`` (protocol_tpu/stream/events.py
    documents the taxonomy and the full-state supersession contract).

    Event sources are the churn emitters themselves — provider node
    ``p<row>`` or task submitter ``t<row>`` — with a strictly monotonic
    per-source seq, so a chaos'd delivery (drop/dup/reorder) of this
    trace converges through the dedup ladder. The arrival schedule is
    OPEN-LOOP and deterministic: ``at_us`` offsets accumulate seeded
    inter-arrival draws around ``1/rate_hz`` (no Poisson process, no
    clock — the same (seed, knobs) always writes byte-identical files).

    ``mass_every`` > 0 additionally injects a multi-row disconnect
    burst every N events (source ``m<k>``) — a latency/pressure drill
    that sits OUTSIDE the per-source supersession contract, so chaos'd
    idempotence workloads keep it at 0 (the default).
    """
    from protocol_tpu.proto import scheduler_pb2 as pb
    from protocol_tpu.proto import wire
    from protocol_tpu.trace import format as tfmt

    rng = np.random.default_rng(seed)
    ep = synth_providers(rng, n_providers)
    er = synth_requirements(rng, n_tasks)
    p_cols = wire.canon_columns(ep, tfmt.P_TRACE_DTYPES)
    r_cols = wire.canon_columns(er, tfmt.R_TRACE_DTYPES)
    n_off = int(n_providers * headroom)
    if n_off:
        valid = p_cols["valid"].copy()
        valid[rng.choice(n_providers, n_off, replace=False)] = False
        p_cols["valid"] = valid

    wns = _W(weights)
    fp = wire.epoch_fingerprint(
        p_cols, r_cols, wns, kernel, top_k, eps, max_iters
    )
    req = pb.AssignRequestV2(
        providers=wire.encode_providers_v2(tfmt._as_ns(p_cols)),
        requirements=wire.encode_requirements_v2(tfmt._as_ns(r_cols)),
        weights=pb.CostWeights(
            price=wns.price, load=wns.load,
            proximity=wns.proximity, priority=wns.priority,
        ),
        kernel=kernel, top_k=top_k, eps=eps, max_iters=max_iters,
    )
    meta = {
        "generator": "synth_event_trace",
        "stream": True,
        "seed": seed,
        "n_providers": n_providers,
        "n_tasks": n_tasks,
        "events": events,
        "rate_hz": rate_hz,
        "headroom": headroom,
        "mass_every": mass_every,
        "reconcile_every": reconcile_every,
    }
    kinds = ("heartbeat", "join", "leave", "task")
    mix = np.asarray(
        [heartbeat_w, join_w, leave_w, task_w], np.float64
    )
    mix = mix / mix.sum()
    seqs: dict = {}

    def _seq(source: str) -> int:
        seqs[source] = seqs.get(source, -1) + 1
        return seqs[source]

    def _p_state(rows: np.ndarray) -> dict:
        return {n: a[rows] for n, a in p_cols.items()}

    def _r_state(rows: np.ndarray) -> dict:
        return {n: a[rows] for n, a in r_cols.items()}

    at_us = 0
    empty = np.zeros(0, np.int32)
    with tfmt.TraceWriter(path, meta=meta,
                          compresslevel=compresslevel) as w:
        w.write_snapshot(f"synth-ev-{seed}", fp, req)
        for i in range(1, events + 1):
            at_us += int(1e6 / rate_hz * (0.5 + rng.random()))
            if mass_every and i % mass_every == 0:
                live = np.flatnonzero(p_cols["valid"])
                n_down = max(int(live.size * mass_frac), 1)
                rows = np.sort(
                    rng.choice(live, min(n_down, live.size), replace=False)
                ).astype(np.int32)
                valid = p_cols["valid"].copy()
                valid[rows] = False
                p_cols["valid"] = valid
                src = f"m{i}"
                ev_meta = {
                    "kind": "mass", "source": src, "seq": _seq(src),
                    "at_us": at_us, "rows": int(rows.size),
                }
                w.write_delta_cols(
                    i, rows, _p_state(rows), empty, None,
                    events=[ev_meta],
                )
                continue
            kind = kinds[int(rng.choice(4, p=mix))]
            live = np.flatnonzero(p_cols["valid"])
            dark = np.flatnonzero(~p_cols["valid"])
            # degrade gracefully when a kind has no eligible rows
            if kind == "join" and dark.size == 0:
                kind = "heartbeat"
            if kind in ("heartbeat", "leave") and live.size == 0:
                kind = "join" if dark.size else "task"
            if kind == "task":
                row = int(rng.integers(0, n_tasks))
                fresh = wire.canon_columns(
                    synth_requirements(rng, 1), tfmt.R_TRACE_DTYPES
                )
                for name in r_cols:
                    col = r_cols[name].copy()
                    col[row] = fresh[name][0]
                    r_cols[name] = col
                rows = np.asarray([row], np.int32)
                src = f"t{row}"
                w.write_delta_cols(
                    i, empty, None, rows, _r_state(rows),
                    events=[{
                        "kind": kind, "source": src, "seq": _seq(src),
                        "at_us": at_us, "rows": 1,
                    }],
                )
                continue
            if kind == "heartbeat":
                row = int(rng.choice(live))
                price = p_cols["price"].copy()
                load = p_cols["load"].copy()
                price[row] = np.float32(rng.uniform(0.5, 4.0))
                load[row] = np.float32(rng.uniform(0, 1))
                p_cols["price"], p_cols["load"] = price, load
            elif kind == "join":
                row = int(rng.choice(dark))
                fresh = wire.canon_columns(
                    synth_providers(rng, 1), tfmt.P_TRACE_DTYPES
                )
                for name in p_cols:
                    col = p_cols[name].copy()
                    col[row] = fresh[name][0]
                    p_cols[name] = col
            else:  # leave
                row = int(rng.choice(live))
                valid = p_cols["valid"].copy()
                valid[row] = False
                p_cols["valid"] = valid
            rows = np.asarray([row], np.int32)
            src = f"p{row}"
            w.write_delta_cols(
                i, rows, _p_state(rows), empty, None,
                events=[{
                    "kind": kind, "source": src, "seq": _seq(src),
                    "at_us": at_us, "rows": 1,
                }],
            )
    return path
