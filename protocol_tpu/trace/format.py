"""Cluster flight-recorder trace format: versioned, gzip-framed,
append-only.

A trace is the complete, bit-reproducible record of one scheduler-seam
workload: one epoch SNAPSHOT frame (the full columnar marketplace plus
every solve parameter, exactly the wire-v2 ``AssignRequestV2`` the seam
itself ships), then per-tick DELTA frames (churned provider/task rows as
full row replacements — the wire-v2 ``AssignDeltaRequest`` shape — plus
optional heartbeat/node-lifecycle events) and OUTCOME frames (the solve's
assignments, carried duals, and per-phase timings/wire-byte counters from
``SeamMetrics``). Anything the solve consumes rides the trace; replaying
it through any engine reproduces the recorded matching bit-for-bit or
localizes the first divergent tick.

File layout (all integers little-endian)::

    magic   b"PTTRACE1"                                (8 bytes)
    frame*  u8 kind | u8 flags | u32 len | u32 crc32   (10-byte header)
            payload[len]                               (deflate if flags&1)

Frames are written fully and flushed one at a time, so a killed run
always leaves a valid prefix: the reader stops at a truncated header, a
short payload, or a CRC mismatch and reports ``truncated=True`` instead
of raising — the surviving ticks replay normally. Compression is
per-frame DEFLATE (zlib): deterministic bytes (no gzip mtime header), so
recording the same workload twice produces byte-identical files.

Frame payloads reuse the wire-v2 ``TensorBlob`` codecs verbatim
(``protocol_tpu/proto/wire.py``): columns are C-order little-endian raw
bytes with the dtype asserted once at decode. The canonical per-column
dtypes are restated here as ``P_TRACE_DTYPES``/``R_TRACE_DTYPES`` —
traces persist on disk across code revisions, so the trace codec carries
its OWN copy of the table, and the ``dtype-contract`` lint
(scripts/lints/dtype_contract.py) cross-checks all three sites (wire,
arena, trace) column-for-column.
"""

from __future__ import annotations

import dataclasses
import json
import struct
import zlib
from typing import Iterator, Optional

import numpy as np

from protocol_tpu.proto import scheduler_pb2 as pb
from protocol_tpu.proto import wire

MAGIC = b"PTTRACE1"
VERSION = 1

# frame kinds
KIND_META = 1      # JSON: trace provenance + generator knobs
KIND_SNAPSHOT = 2  # pb.SnapshotChunk: epoch header + AssignRequestV2 payload
KIND_DELTA = 3     # u32 n | pb.AssignDeltaRequest[n] | JSON events
KIND_OUTCOME = 4   # u32 n | pb.AssignResponseV2[n] | JSON {tick, metrics}
KIND_EVENT = 5     # JSON {tick, events}: out-of-band structured events
#                    (SLO burn-rate alerts) — NOT solve inputs, so the
#                    replayer ignores them; old readers skip the kind
KIND_ARENA = 6     # named-ndarray pack (pack_arrays): carried solver
#                    state — used by the session CHECKPOINT files
#                    (faults/checkpoint.py), never by workload traces;
#                    the replayer skips the kind by the unknown-kind
#                    contract

_FLAG_DEFLATE = 1
_HEADER = struct.Struct("<BBII")

# Canonical trace-frame column dtypes. These MUST match the wire tables
# (proto/wire.py) column-for-column: the dtype-contract lint enforces it
# statically and _check_tables() enforces it at import. The duplication
# is deliberate — a trace on disk is decoded by THIS table, so a wire
# revision that drifts a column fails loudly here instead of silently
# reinterpreting archived bytes.
P_TRACE_DTYPES: dict[str, np.dtype] = {
    "gpu_count": np.dtype(np.int32),
    "gpu_mem_mb": np.dtype(np.int32),
    "gpu_model_id": np.dtype(np.int32),
    "has_gpu": np.dtype(np.bool_),
    "has_cpu": np.dtype(np.bool_),
    "cpu_cores": np.dtype(np.int32),
    "ram_mb": np.dtype(np.int32),
    "storage_gb": np.dtype(np.int32),
    "lat": np.dtype(np.float32),
    "lon": np.dtype(np.float32),
    "has_location": np.dtype(np.bool_),
    "price": np.dtype(np.float32),
    "load": np.dtype(np.float32),
    "valid": np.dtype(np.bool_),
}
R_TRACE_DTYPES: dict[str, np.dtype] = {
    "cpu_required": np.dtype(np.bool_),
    "cpu_cores": np.dtype(np.int32),
    "ram_mb": np.dtype(np.int32),
    "storage_gb": np.dtype(np.int32),
    "gpu_opt_valid": np.dtype(np.bool_),
    "gpu_count": np.dtype(np.int32),
    "gpu_mem_min": np.dtype(np.int32),
    "gpu_mem_max": np.dtype(np.int32),
    "gpu_total_mem_min": np.dtype(np.int32),
    "gpu_total_mem_max": np.dtype(np.int32),
    "gpu_model_mask": np.dtype(np.uint32),
    "gpu_model_constrained": np.dtype(np.bool_),
    "lat": np.dtype(np.float32),
    "lon": np.dtype(np.float32),
    "has_location": np.dtype(np.bool_),
    "priority": np.dtype(np.float32),
    "valid": np.dtype(np.bool_),
}


def _check_tables() -> None:
    # runtime twin of the dtype-contract lint's cross-check
    for name, mine, theirs in (
        ("P", P_TRACE_DTYPES, wire.P_WIRE_DTYPES),
        ("R", R_TRACE_DTYPES, wire.R_WIRE_DTYPES),
    ):
        if list(mine.items()) != list(theirs.items()):
            raise AssertionError(
                f"{name}_TRACE_DTYPES drifted from the wire table — archived "
                "traces would decode at the wrong widths"
            )


# ---------------- named-ndarray pack (ARENA frames) ----------------


def pack_arrays(named: dict[str, Optional[np.ndarray]]) -> bytes:
    """Deterministic bytes for a dict of (optionally None) ndarrays:
    a sorted JSON manifest (name -> dtype/shape/offset) followed by the
    C-order little-endian raw buffers. The checkpoint codec — same
    byte-exactness contract as the TensorBlob columns, without protobuf
    in the way (carried solver state is not a wire message)."""
    manifest: dict = {}
    buffers: list[bytes] = []
    off = 0
    for name in sorted(named):
        a = named[name]
        if a is None:
            manifest[name] = None
            continue
        a = np.ascontiguousarray(a)
        raw = a.tobytes()
        manifest[name] = {
            "dtype": a.dtype.name,
            "shape": list(a.shape),
            "offset": off,
        }
        buffers.append(raw)
        off += len(raw)
    head = json.dumps(manifest, sort_keys=True).encode()
    return struct.pack("<I", len(head)) + head + b"".join(buffers)


def unpack_arrays(payload: bytes) -> dict[str, Optional[np.ndarray]]:
    """Inverse of :func:`pack_arrays`. Raises ValueError on a short or
    inconsistent payload (a torn checkpoint must fail loudly at load,
    never decode at the wrong widths)."""
    if len(payload) < 4:
        raise ValueError("array pack too short for its header")
    (n,) = struct.unpack_from("<I", payload)
    head = payload[4:4 + n]
    if len(head) < n:
        raise ValueError("array pack manifest truncated")
    manifest = json.loads(head)
    base = 4 + n
    out: dict[str, Optional[np.ndarray]] = {}
    for name, m in manifest.items():
        if m is None:
            out[name] = None
            continue
        dt = np.dtype(m["dtype"])
        shape = tuple(int(s) for s in m["shape"])
        count = int(np.prod(shape)) if shape else 1
        start = base + int(m["offset"])
        end = start + count * dt.itemsize
        if end > len(payload):
            raise ValueError(f"array pack buffer {name!r} truncated")
        out[name] = np.frombuffer(
            payload[start:end], dtype=dt
        ).reshape(shape)
    return out


# ---------------- frame records ----------------


@dataclasses.dataclass
class DeltaRecord:
    """One recorded tick's inputs: churned rows + lifecycle events."""

    tick: int
    provider_rows: np.ndarray  # i32 [n]
    p_cols: dict[str, np.ndarray]  # churned rows only, trace dtypes
    task_rows: np.ndarray
    r_cols: dict[str, np.ndarray]
    events: list


@dataclasses.dataclass
class OutcomeRecord:
    """One recorded tick's solve result + provenance metrics."""

    tick: int
    provider_for_task: np.ndarray  # i32 [T]
    price: Optional[np.ndarray]  # f32 [P] (carried duals), may be absent
    num_assigned: int
    metrics: dict  # per-phase ms, wire bytes, arena stats


@dataclasses.dataclass
class Snapshot:
    """The epoch: full columns + every solve parameter."""

    trace_id: str
    fingerprint: str
    p_cols: dict[str, np.ndarray]
    r_cols: dict[str, np.ndarray]
    weights: tuple  # (price, load, proximity, priority) f32
    kernel: str
    top_k: int
    eps: float
    max_iters: int

    @property
    def n_providers(self) -> int:
        return int(self.p_cols["gpu_count"].shape[0])

    @property
    def n_tasks(self) -> int:
        return int(self.r_cols["cpu_cores"].shape[0])

    def request_v2(self) -> pb.AssignRequestV2:
        """Re-pack as the wire message (what the snapshot frame holds)."""
        return pb.AssignRequestV2(
            providers=wire.encode_providers_v2(
                _as_ns(self.p_cols)
            ),
            requirements=wire.encode_requirements_v2(
                _as_ns(self.r_cols)
            ),
            weights=pb.CostWeights(
                price=self.weights[0], load=self.weights[1],
                proximity=self.weights[2], priority=self.weights[3],
            ),
            kernel=self.kernel, top_k=self.top_k, eps=self.eps,
            max_iters=self.max_iters,
        )


@dataclasses.dataclass
class Trace:
    """A parsed trace: meta + snapshot + per-tick delta/outcome records."""

    path: str
    meta: dict
    snapshot: Optional[Snapshot]
    deltas: list  # DeltaRecord, tick order
    outcomes: list  # OutcomeRecord, tick order (tick 0 = snapshot solve)
    truncated: bool
    n_frames: int
    # EVENT frames ({tick, events}, e.g. SLO alerts) — observational
    # side channel, never replay input
    events: list = dataclasses.field(default_factory=list)

    @property
    def ticks(self) -> int:
        """Input ticks: the snapshot plus every delta frame."""
        return (1 if self.snapshot is not None else 0) + len(self.deltas)

    def outcome_for(self, tick: int) -> Optional[OutcomeRecord]:
        # index built lazily: replay verifies one lookup per tick, and a
        # linear scan would make a 16k-tick verification O(ticks^2)
        by_tick = self.__dict__.get("_outcome_by_tick")
        if by_tick is None or len(by_tick) != len(self.outcomes):
            by_tick = {o.tick: o for o in self.outcomes}
            self.__dict__["_outcome_by_tick"] = by_tick
        return by_tick.get(tick)


def _as_ns(cols: dict[str, np.ndarray]):
    ns = type("_Cols", (), {})()
    for name, arr in cols.items():
        setattr(ns, name, arr)
    return ns


# ---------------- writer ----------------


class TraceWriter:
    """Append-only frame writer. Every ``write_*`` call lands one fully
    flushed frame, so a SIGKILL can never lose more than the frame being
    written (the reader tolerates that torn tail)."""

    def __init__(self, path: str, meta: Optional[dict] = None,
                 compresslevel: int = 6):
        _check_tables()
        self.path = path
        self.compresslevel = compresslevel
        self._fh = open(path, "wb")
        self._fh.write(MAGIC)
        m = {"version": VERSION}
        m.update(meta or {})
        self._frame(KIND_META, json.dumps(m, sort_keys=True).encode())

    def _frame(self, kind: int, payload: bytes) -> None:
        flags = 0
        z = zlib.compress(payload, self.compresslevel)
        if len(z) < len(payload):
            payload, flags = z, _FLAG_DEFLATE
        self._fh.write(
            _HEADER.pack(kind, flags, len(payload), zlib.crc32(payload))
        )
        self._fh.write(payload)
        self._fh.flush()

    def write_snapshot(
        self, trace_id: str, fingerprint: str, request: pb.AssignRequestV2
    ) -> None:
        payload = request.SerializeToString()
        chunk = pb.SnapshotChunk(
            session_id=trace_id, epoch_fingerprint=fingerprint,
            payload=payload, total_bytes=len(payload),
        )
        self._frame(KIND_SNAPSHOT, chunk.SerializeToString())

    def write_delta(
        self, delta: pb.AssignDeltaRequest, events: Optional[list] = None
    ) -> None:
        body = delta.SerializeToString()
        ev = json.dumps(events or [], sort_keys=True).encode()
        self._frame(KIND_DELTA, struct.pack("<I", len(body)) + body + ev)

    def write_delta_cols(
        self,
        tick: int,
        provider_rows: np.ndarray,
        p_cols: Optional[dict[str, np.ndarray]],
        task_rows: np.ndarray,
        r_cols: Optional[dict[str, np.ndarray]],
        events: Optional[list] = None,
    ) -> None:
        """Column-dict convenience front end over :meth:`write_delta`."""
        req = pb.AssignDeltaRequest(tick=tick)
        if provider_rows is not None and provider_rows.size:
            req.provider_rows.CopyFrom(wire.blob(provider_rows, np.int32))
            req.providers.CopyFrom(wire.encode_providers_v2(_as_ns(p_cols)))
        if task_rows is not None and task_rows.size:
            req.task_rows.CopyFrom(wire.blob(task_rows, np.int32))
            req.requirements.CopyFrom(
                wire.encode_requirements_v2(_as_ns(r_cols))
            )
        self.write_delta(req, events)

    def write_events(self, tick: int, events: list) -> None:
        """Out-of-band structured events (SLO burn-rate alerts) tied to
        a tick. Never a solve input: the replayer skips EVENT frames,
        and pre-EVENT readers skip the unknown kind by contract."""
        self._frame(
            KIND_EVENT,
            json.dumps(
                {"tick": int(tick), "events": list(events)}, sort_keys=True
            ).encode(),
        )

    def write_arena(self, named: dict[str, Optional[np.ndarray]]) -> None:
        """Carried solver state as one ARENA frame (checkpoint files;
        workload traces never carry one — the replayer skips the
        kind)."""
        self._frame(KIND_ARENA, pack_arrays(named))

    def write_outcome(
        self,
        tick: int,
        provider_for_task: np.ndarray,
        price: Optional[np.ndarray] = None,
        metrics: Optional[dict] = None,
    ) -> None:
        resp = pb.AssignResponseV2(
            provider_for_task=wire.blob(provider_for_task, np.int32),
            num_assigned=int((np.asarray(provider_for_task) >= 0).sum()),
        )
        if price is not None:
            resp.price.CopyFrom(wire.blob(price, np.float32))
        body = resp.SerializeToString()
        tail = json.dumps(
            {"tick": int(tick), "metrics": metrics or {}}, sort_keys=True
        ).encode()
        self._frame(KIND_OUTCOME, struct.pack("<I", len(body)) + body + tail)

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------- reader ----------------


def read_frames(path: str) -> Iterator[tuple[int, bytes]]:
    """Yield (kind, payload) per intact frame; a torn tail (truncated
    header/payload, CRC mismatch) ends iteration cleanly — the final
    yield is the sentinel ``(-1, b"")`` ONLY when the tail was torn."""
    with open(path, "rb") as fh:
        if fh.read(len(MAGIC)) != MAGIC:
            raise ValueError(f"{path}: not a PTTRACE1 trace file")
        while True:
            head = fh.read(_HEADER.size)
            if not head:
                return  # clean EOF
            if len(head) < _HEADER.size:
                yield -1, b""
                return
            kind, flags, length, crc = _HEADER.unpack(head)
            payload = fh.read(length)
            if len(payload) < length or zlib.crc32(payload) != crc:
                yield -1, b""
                return
            if flags & _FLAG_DEFLATE:
                payload = zlib.decompress(payload)
            yield kind, payload


def _parse_snapshot(payload: bytes) -> Snapshot:
    chunk = pb.SnapshotChunk()
    chunk.ParseFromString(payload)
    req = pb.AssignRequestV2()
    req.ParseFromString(chunk.payload)
    p_cols = wire._decode_columns(req.providers, P_TRACE_DTYPES)
    r_cols = wire._decode_columns(req.requirements, R_TRACE_DTYPES)
    return Snapshot(
        trace_id=chunk.session_id,
        fingerprint=chunk.epoch_fingerprint,
        p_cols=p_cols,
        r_cols=r_cols,
        weights=(
            req.weights.price, req.weights.load,
            req.weights.proximity, req.weights.priority,
        ),
        kernel=req.kernel,
        top_k=int(req.top_k),
        eps=float(req.eps),
        max_iters=int(req.max_iters),
    )


def _parse_delta(payload: bytes) -> DeltaRecord:
    (n,) = struct.unpack_from("<I", payload)
    req = pb.AssignDeltaRequest()
    req.ParseFromString(payload[4:4 + n])
    events = json.loads(payload[4 + n:] or b"[]")
    prow = (
        wire.unblob(req.provider_rows, np.int32)
        if req.HasField("provider_rows") else np.zeros(0, np.int32)
    )
    trow = (
        wire.unblob(req.task_rows, np.int32)
        if req.HasField("task_rows") else np.zeros(0, np.int32)
    )
    p_cols = (
        wire._decode_columns(req.providers, P_TRACE_DTYPES)
        if prow.size else {}
    )
    r_cols = (
        wire._decode_columns(req.requirements, R_TRACE_DTYPES)
        if trow.size else {}
    )
    return DeltaRecord(
        tick=int(req.tick), provider_rows=prow, p_cols=p_cols,
        task_rows=trow, r_cols=r_cols, events=events,
    )


def _parse_outcome(payload: bytes) -> OutcomeRecord:
    (n,) = struct.unpack_from("<I", payload)
    resp = pb.AssignResponseV2()
    resp.ParseFromString(payload[4:4 + n])
    tail = json.loads(payload[4 + n:] or b"{}")
    return OutcomeRecord(
        tick=int(tail.get("tick", -1)),
        provider_for_task=wire.unblob(resp.provider_for_task, np.int32),
        price=(
            wire.unblob(resp.price, np.float32)
            if resp.HasField("price") else None
        ),
        num_assigned=int(resp.num_assigned),
        metrics=tail.get("metrics", {}),
    )


def read_trace(path: str) -> Trace:
    """Parse a trace file. Tolerant of torn tails: whatever frames are
    intact come back, with ``truncated=True`` flagging the tear."""
    _check_tables()
    meta: dict = {}
    snapshot: Optional[Snapshot] = None
    deltas: list[DeltaRecord] = []
    outcomes: list[OutcomeRecord] = []
    events: list = []
    truncated = False
    n_frames = 0
    for kind, payload in read_frames(path):
        if kind == -1:
            truncated = True
            break
        n_frames += 1
        if kind == KIND_META:
            meta = json.loads(payload)
        elif kind == KIND_SNAPSHOT:
            snapshot = _parse_snapshot(payload)
        elif kind == KIND_DELTA:
            deltas.append(_parse_delta(payload))
        elif kind == KIND_OUTCOME:
            outcomes.append(_parse_outcome(payload))
        elif kind == KIND_EVENT:
            events.append(json.loads(payload))
        # unknown kinds are skipped: future writers may append new frame
        # kinds without breaking old readers (the version rides in META)
    return Trace(
        path=path, meta=meta, snapshot=snapshot, deltas=deltas,
        outcomes=outcomes, truncated=truncated, n_frames=n_frames,
        events=events,
    )


def info(path: str) -> dict:
    """Human-facing summary (the ``trace info`` CLI verb)."""
    t = read_trace(path)
    out = {
        "path": path,
        "version": t.meta.get("version"),
        "meta": {k: v for k, v in t.meta.items() if k != "version"},
        "frames": t.n_frames,
        "truncated": t.truncated,
        "ticks": t.ticks,
        "outcomes": len(t.outcomes),
        "events": len(t.events),
    }
    if t.snapshot is not None:
        s = t.snapshot
        delta_rows = sum(
            int(d.provider_rows.size + d.task_rows.size) for d in t.deltas
        )
        out.update(
            providers=s.n_providers, tasks=s.n_tasks, kernel=s.kernel,
            top_k=s.top_k, eps=round(s.eps, 6), fingerprint=s.fingerprint,
            delta_rows_total=delta_rows,
        )
    if t.outcomes:
        out["assigned_last"] = t.outcomes[-1].num_assigned
        solve_ms = [
            o.metrics.get("solve_ms") for o in t.outcomes
            if o.metrics.get("solve_ms") is not None
        ]
        if solve_ms:
            out["mean_solve_ms"] = round(float(np.mean(solve_ms)), 3)
    return out
