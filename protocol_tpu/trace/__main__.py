"""Flight-recorder CLI: ``python -m protocol_tpu.trace <verb>``.

  synth    generate a parameterized synthetic workload trace (input-only)
  record   replay an input trace through an engine and write a new trace
           with outcomes — how golden traces are made
  replay   replay a trace, verify recorded outcomes bit-for-bit, print
           the (empty or localized) divergence report; --compare runs an
           A/B of two configs over the same trace
  info     summarize a trace (shape, ticks, frames, truncation, timings)

Every verb prints ONE JSON document on stdout; replay exits non-zero on
divergence so CI can gate on it directly.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _cmd_synth(args) -> int:
    from protocol_tpu.trace.synth import synth_trace

    path = synth_trace(
        args.out,
        n_providers=args.providers,
        n_tasks=args.tasks,
        ticks=args.ticks,
        churn=args.churn,
        task_churn=args.task_churn,
        seed=args.seed,
        kernel=args.kernel,
        top_k=args.top_k,
        eps=args.eps,
        headroom=args.headroom,
        growth=args.growth,
        hotspot_every=args.hotspot_every,
        hotspot_frac=args.hotspot_frac,
        disconnect_at=args.disconnect_at,
        disconnect_frac=args.disconnect_frac,
        reconnect_after=args.reconnect_after,
    )
    from protocol_tpu.trace import format as tfmt

    print(json.dumps(tfmt.info(path), indent=1))
    return 0


def _cmd_record(args) -> int:
    from protocol_tpu.trace.replay import replay

    rep = replay(
        args.trace,
        engine=args.engine,
        threads=args.threads,
        transport=args.transport,
        verify=False,
        record_path=args.out,
        max_ticks=args.max_ticks,
    )
    print(json.dumps(rep, indent=1))
    return 0


def _cmd_replay(args) -> int:
    from protocol_tpu.trace.replay import compare, replay

    if args.compare:
        eng_b, _, thr_b = args.compare.partition(":")
        rep = compare(
            args.trace,
            {"engine": args.engine, "threads": args.threads,
             "transport": args.transport},
            {"engine": eng_b or None,
             "threads": int(thr_b) if thr_b else None,
             "transport": args.compare_transport or args.transport},
            max_ticks=args.max_ticks,
        )
        print(json.dumps(rep, indent=1))
        return 0
    rep = replay(
        args.trace,
        engine=args.engine,
        threads=args.threads,
        transport=args.transport,
        verify=not args.no_verify,
        record_path=args.out,
        max_ticks=args.max_ticks,
    )
    print(json.dumps(rep, indent=1))
    if rep["divergence"] is not None:
        print(
            f"DIVERGENCE at tick {rep['divergence']['tick']}: "
            f"{rep['divergence']['n_rows']} rows differ "
            f"(first {rep['divergence']['rows'][:8]})",
            file=sys.stderr,
        )
        return 1
    return 0


def _cmd_info(args) -> int:
    from protocol_tpu.trace import format as tfmt

    print(json.dumps(tfmt.info(args.trace), indent=1))
    return 0


def main(argv=None) -> int:
    # the CLI drives CPU solves; never let an ambient remote accelerator
    # plugin wedge a replay
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    ap = argparse.ArgumentParser(prog="python -m protocol_tpu.trace")
    sub = ap.add_subparsers(dest="verb", required=True)

    sp = sub.add_parser("synth", help="generate a synthetic workload trace")
    sp.add_argument("out")
    sp.add_argument("--providers", type=int, default=1024)
    sp.add_argument("--tasks", type=int, default=1024)
    sp.add_argument("--ticks", type=int, default=16)
    sp.add_argument("--churn", type=float, default=0.01)
    sp.add_argument("--task-churn", type=float, default=0.0)
    sp.add_argument("--seed", type=int, default=0)
    sp.add_argument("--kernel", default="native-mt")
    sp.add_argument("--top-k", type=int, default=64)
    sp.add_argument("--eps", type=float, default=0.02)
    sp.add_argument("--headroom", type=float, default=0.0)
    sp.add_argument("--growth", type=float, default=0.0)
    sp.add_argument("--hotspot-every", type=int, default=0)
    sp.add_argument("--hotspot-frac", type=float, default=0.05)
    sp.add_argument("--disconnect-at", type=int, default=0)
    sp.add_argument("--disconnect-frac", type=float, default=0.25)
    sp.add_argument("--reconnect-after", type=int, default=0)
    sp.set_defaults(fn=_cmd_synth)

    def _replay_args(p, with_out_required: bool):
        p.add_argument("trace")
        p.add_argument("--engine", default=None,
                       help="native-mt[:N] | sinkhorn-mt[:N] | jax "
                            "(default: the trace's recorded kernel)")
        p.add_argument("--threads", type=int, default=None)
        p.add_argument("--transport", default="inproc",
                       choices=["inproc", "wire-v1", "wire-v2"])
        p.add_argument("--max-ticks", type=int, default=None)
        if with_out_required:
            p.add_argument("--out", required=True,
                           help="write the replayed trace (with outcomes)")
        else:
            p.add_argument("--out", default=None,
                           help="also write a trace with this replay's "
                                "outcomes")

    rp = sub.add_parser("record", help="replay + write outcomes (golden)")
    _replay_args(rp, with_out_required=True)
    rp.set_defaults(fn=_cmd_record)

    pp = sub.add_parser("replay", help="replay + verify bit-for-bit")
    _replay_args(pp, with_out_required=False)
    pp.add_argument("--no-verify", action="store_true")
    pp.add_argument("--compare", default=None, metavar="ENGINE[:THREADS]",
                    help="A/B: replay again under this engine and diff")
    pp.add_argument("--compare-transport", default=None)
    pp.set_defaults(fn=_cmd_replay)

    ip = sub.add_parser("info", help="summarize a trace file")
    ip.add_argument("trace")
    ip.set_defaults(fn=_cmd_info)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
