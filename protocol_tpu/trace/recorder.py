"""Flight-recorder capture hooks: ``PROTOCOL_TPU_TRACE=<path>`` makes any
live or bench run record its exact solve inputs and outcomes.

Three seam sites record (all behind the same env knob, all producing the
same trace/format.py frames):

  * **TpuBatchMatcher** (in-process / degraded-mode solves): the native
    arena path records the encoded columns it solves, diffing against its
    own shadow copy to emit O(churn) delta frames — the recorder is the
    wire protocol's column differ pointed at disk instead of a socket.
  * **the gRPC servicer** (unary v1/v2): same column-mode capture of the
    decoded request.
  * **SessionStore delta application** (the v2 session protocol): the
    recorder rides the session — ``OpenSession`` lands the epoch snapshot
    frame verbatim and every applied ``AssignDelta`` lands its exact wire
    rows, so the trace IS the session's wire history.

One trace file holds ONE epoch (one population shape + solve-parameter
set). When the recorded workload re-epochs (shape or params change), the
recorder rolls to ``<path>.e1``, ``<path>.e2``, ... — each file replays
independently. When several capture sites are live in one process, the
first ``from_env`` claim gets the bare path and later claimants get
``<path>.<role>`` (a recorder never multiplexes writers onto one file).

Recording is best-effort by design: a raise inside a capture hook must
never fail a scheduler tick, so hook call sites wrap in try/except and
surface failures as one warning.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

import numpy as np

from protocol_tpu.proto import scheduler_pb2 as pb
from protocol_tpu.proto import wire
from protocol_tpu.trace import format as tfmt
from protocol_tpu.utils.lockwitness import LazyLock, make_lock

ENV_VAR = "PROTOCOL_TPU_TRACE"

# LazyLock: module-global — the witness decision must wait for first use
_claim_lock = LazyLock("trace-claim")
_claimed: set[str] = set()

log = logging.getLogger(__name__)


def _claim(path: str, role: str) -> str:
    with _claim_lock:
        if path not in _claimed:
            _claimed.add(path)
            return path
        alt = f"{path}.{role or 'alt'}"
        n = 1
        while alt in _claimed:
            alt = f"{path}.{role or 'alt'}{n}"
            n += 1
        _claimed.add(alt)
        return alt


class TraceRecorder:
    """One capture stream -> one (or, across epochs, a family of) trace
    file(s). Thread-safe; frames land fully flushed (kill-proof tails)."""

    def __init__(self, path: str, role: str = "", meta: Optional[dict] = None):
        self.path = path
        self.role = role
        self.meta = dict(meta or {})
        self._lock = make_lock("trace")
        self._writer: Optional[tfmt.TraceWriter] = None
        self._epoch = 0
        self._tick = 0
        # column-mode shadow state (matcher / unary servicer capture)
        self._params: Optional[tuple] = None
        self._shadow_p: Optional[dict] = None
        self._shadow_r: Optional[dict] = None
        # wire-mode session claim (one session per trace stream)
        self._session_id: Optional[str] = None

    @classmethod
    def from_env(cls, role: str = "",
                 meta: Optional[dict] = None) -> Optional["TraceRecorder"]:
        path = os.environ.get(ENV_VAR, "")
        if not path:
            return None
        m = {"role": role}
        m.update(meta or {})
        return cls(_claim(path, role), role=role, meta=m)

    # ---------------- internals ----------------

    def _epoch_path(self) -> str:
        return self.path if self._epoch == 0 else f"{self.path}.e{self._epoch}"

    def _open_writer(self) -> tfmt.TraceWriter:
        if self._writer is None:
            self._writer = tfmt.TraceWriter(self._epoch_path(), meta=self.meta)
        return self._writer

    def _roll_epoch(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None
            self._epoch += 1
        self._tick = 0

    # ---------------- column-mode capture (matcher / unary) ----------------

    def record_solve(
        self,
        ep,
        er,
        weights,
        kernel: str,
        top_k: int,
        eps: float,
        max_iters: int,
        p4t: np.ndarray,
        price: Optional[np.ndarray] = None,
        metrics: Optional[dict] = None,
        events: Optional[list] = None,
    ) -> None:
        """Capture one full solve: first call (or any epoch change) writes
        the snapshot frame; steady-state calls diff against the shadow
        columns and write O(churn) delta frames; every call writes the
        outcome frame. ``ep``/``er`` are Encoded* batches (numpy- or
        jax-backed)."""
        p_cols = wire.canon_columns(ep, tfmt.P_TRACE_DTYPES)
        r_cols = wire.canon_columns(er, tfmt.R_TRACE_DTYPES)
        params = (
            kernel, int(top_k), np.float32(eps).item(), int(max_iters),
            float(weights.price), float(weights.load),
            float(weights.proximity), float(weights.priority),
            p_cols["gpu_count"].shape[0], r_cols["cpu_cores"].shape[0],
        )
        with self._lock:
            if self._session_id is not None:
                return  # session mode owns this stream
            if self._params != params or self._shadow_p is None:
                if self._params is not None:
                    self._roll_epoch()
                self._params = params
                fp = wire.epoch_fingerprint(
                    p_cols, r_cols, weights, kernel, top_k, eps, max_iters
                )
                req = pb.AssignRequestV2(
                    providers=wire.encode_providers_v2(tfmt._as_ns(p_cols)),
                    requirements=wire.encode_requirements_v2(
                        tfmt._as_ns(r_cols)
                    ),
                    weights=pb.CostWeights(
                        price=float(weights.price), load=float(weights.load),
                        proximity=float(weights.proximity),
                        priority=float(weights.priority),
                    ),
                    kernel=kernel, top_k=top_k, eps=eps, max_iters=max_iters,
                )
                self._open_writer().write_snapshot(
                    f"{self.role or 'live'}-e{self._epoch}", fp, req
                )
            else:
                self._tick += 1
                prow = wire.dirty_rows(p_cols, self._shadow_p)
                trow = wire.dirty_rows(r_cols, self._shadow_r)
                self._open_writer().write_delta_cols(
                    self._tick,
                    prow,
                    {n: a[prow] for n, a in p_cols.items()}
                    if prow.size else None,
                    trow,
                    {n: a[trow] for n, a in r_cols.items()}
                    if trow.size else None,
                    events=events,
                )
            self._shadow_p, self._shadow_r = p_cols, r_cols
            self._writer.write_outcome(
                self._tick, np.asarray(p4t, np.int32),
                price=None if price is None else np.asarray(
                    price, np.float32
                ),
                metrics=metrics,
            )

    # ---------------- wire-mode capture (session protocol) ----------------

    def record_session_open(
        self, session_id: str, fingerprint: str, req: pb.AssignRequestV2
    ) -> bool:
        """Claim the session for this stream and land its snapshot frame
        verbatim. Returns False (and records nothing) when another
        session already owns the stream — one trace, one session."""
        with self._lock:
            if self._params is not None:
                return False  # column-mode capture owns this stream
            if self._session_id is not None and self._session_id != session_id:
                return False
            if self._session_id == session_id:
                # same id re-opened: a fresh epoch of the same stream
                self._roll_epoch()
            self._session_id = session_id
            self._open_writer().write_snapshot(session_id, fingerprint, req)
            return True

    def record_session_delta(
        self,
        session_id: str,
        tick: int,
        provider_rows: np.ndarray,
        p_delta: dict,
        task_rows: np.ndarray,
        r_delta: dict,
        events: Optional[list] = None,
    ) -> None:
        """Land one APPLIED AssignDelta's exact rows (called from
        SolveSession.apply_delta, under the session lock — refused deltas
        never reach it, so the trace holds only ticks that solved)."""
        with self._lock:
            if self._session_id != session_id:
                return
            self._tick = int(tick)
            self._open_writer().write_delta_cols(
                int(tick),
                provider_rows,
                p_delta if provider_rows.size else None,
                task_rows,
                r_delta if task_rows.size else None,
                events=events,
            )

    def record_outcome(
        self,
        tick: int,
        p4t: np.ndarray,
        price: Optional[np.ndarray] = None,
        metrics: Optional[dict] = None,
        session_id: Optional[str] = None,
    ) -> None:
        with self._lock:
            if session_id is not None and self._session_id != session_id:
                return
            self._open_writer().write_outcome(
                int(tick), np.asarray(p4t, np.int32),
                price=None if price is None else np.asarray(
                    price, np.float32
                ),
                metrics=metrics,
            )

    def record_events(
        self,
        events: list,
        session_id: Optional[str] = None,
        tick: Optional[int] = None,
    ) -> None:
        """Out-of-band structured events (SLO burn-rate alerts).
        Ownership mirrors the outcome contract exactly: a session-owned
        stream accepts only its own session's events, a column-mode
        stream (``session_id=None``, the unary path) accepts only
        unary events — an event must never land in a stream that is
        recording a DIFFERENT workload's ticks. ``tick`` anchors the
        EVENT frame explicitly (the caller's wire tick); None falls
        back to the stream's current tick, which is only safe when the
        caller IS the path advancing it (column mode)."""
        with self._lock:
            if self._session_id != session_id:
                return
            if self._writer is None:
                return  # nothing recorded yet: no tick to anchor to
            self._writer.write_events(
                self._tick if tick is None else int(tick), events
            )

    def close(self) -> None:
        with self._lock:
            if self._writer is not None:
                self._writer.close()
                self._writer = None


def safe(fn, *args, **kwargs) -> None:
    """Run one capture hook, never letting it fail the solve path."""
    try:
        fn(*args, **kwargs)
    except Exception:  # pragma: no cover - defensive seam
        log.warning("trace capture hook failed", exc_info=True)
