"""Persistent warm-solve arena for the JAX engine (engine="jax").

The accelerator-path peer of :class:`~protocol_tpu.native.arena.
NativeSolveArena` behind the exact same duck-typed surface (solve /
apply_rows / reconcile / export_state / restore_state / invalidate,
``.price`` / ``.retired`` / ``.last_stats``), so every consumer of the
native arena — sessions, the unary servicer, checkpoints, migration,
the stream engine, trace replay — runs unchanged with ``engine="jax"``.
Two-stage split, mirroring SCALING.md's ICI cost model:

  - **Sharded candidate generation.** The bucketed top-K + reverse-edge
    pass as the jit-compiled, task-sharded kernel
    (:func:`~protocol_tpu.parallel.sparse.candidates_topk_bidir_sharded`
    over a 1xD mesh: zero per-round collectives, one ``all_gather`` of
    per-shard top-K, deterministic reverse-edge merge). Device-count
    INVARIANT: D=1 and D=4 produce the bit-identical candidate
    structure (asserted in tests/test_parallel_sparse.py and
    ``perf_gate.py --jax``), which is why the warm carry below stays
    sound across device-count changes and why the provenance tag
    excludes D.
  - **Adaptive-ladder solve.** Cold solves run the eps-annealed auction
    ladder (:func:`~protocol_tpu.ops.sparse.assign_auction_sparse_scaled`
    — jitted ``lax.while_loop`` phases on a single chip); warm solves
    carry the dual state (prices + retirement + matching) into the
    delta-frontier kernel (:func:`assign_auction_sparse_warm`), clearing
    retirement for exactly the rows whose candidates or costs changed —
    the caller contract that kernel documents.

Like the native arena, the jax arena REPAIRS its candidate structure
incrementally on warm ticks: the generation PARTS — forward lists
[T, k] and the raw per-tile reverse contribution pools
[P, n_tiles*rt] — persist across ticks, and a dirty tick runs the
churn-masked repair kernels
(:func:`~protocol_tpu.parallel.sparse.repair_topk_bidir_sharded`)
that recompute exactly the flagged forward rows and (provider, tile)
contribution blocks, replay the generation fold over the pools, and
re-merge. The oracle contract is the same one
``repair_topk_candidates_mt`` honors in C++: the repaired structure is
bit-identical to a from-scratch ``candidates_topk_bidir_sharded`` pass
on the current features, at every device count (tie jitter is keyed on
global indices, so a recomputed subset lands on the exact cells the
full pass would produce — see the exactness notes on each repair
kernel). ``last_stats`` reports the path honestly: warm repair ticks
carry ``cand_cold_passes: 0`` plus scope counters (``repair_rows``,
``repair_providers``, ``visited_cells_frac``); only genuinely cold
ticks — first solve, shape/weights change, ``cold_every``,
``max_dirty_frac`` overflow, or ``approx_recall`` mode (approx
selection has no exactness contract, hence no repair twin) — pay a
full pass and say so. Dirty detection, the byte-identical
short-circuit, ``max_dirty_frac``/``cold_every``/
``dual_refresh_every`` cadences, the dirty-task re-seat, and the seat
feasibility guard all mirror the native arena row for row.

Missing accelerators DEGRADE INSIDE the engine, never across engines:
asking for more devices than the host exposes clamps D to what exists,
counts the event (``device_degraded_events``), and flags every
subsequent ``last_stats`` — a jax solve on one CPU device is still a
jax solve. Silent fallback to the native engine would invalidate every
cross-backend A/B the trace subsystem runs.
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from protocol_tpu import obs
from protocol_tpu.native.arena import _P_SPEC, _R_SPEC, _canon, _dirty_rows
from protocol_tpu.utils import jitwitness as _jitwitness
from protocol_tpu.obs import quality as _quality
from protocol_tpu.obs.spans import TRACER as _tracer
from protocol_tpu.ops.encoding import EncodedProviders, EncodedRequirements
from protocol_tpu.ops.sparse import (
    assign_auction_sparse_scaled,
    assign_auction_sparse_warm,
    candidates_topk_bidir,
    pick_tile,
)

# persisted candidate-structure dtypes (same durable on-disk contract as
# native.arena._CAND_STATE_DTYPES: checkpoint frames and migration
# handoffs coerce through this table on restore). Since the warm path
# became incremental repair, the generation PARTS persist alongside the
# merged lists: forward top-k (fwd_*) and the raw per-tile reverse
# contribution pools (pool_*, [P, n_tiles*rt] in global tile order) are
# what the repair kernels patch in place — the merged lists alone cannot
# be repaired (a merge is not invertible), and the FOLDED reverse edges
# are derivable (fold replay) but not invertible either, so the pre-fold
# pools are the canonical persisted form. Pool memory grows as
# P * n_tiles * ceil(r / n_tiles) — between r and 2r-1 entries per
# provider (~2x the folded form at worst), megabytes through ~131k rows.
_JAX_STATE_DTYPES = {
    "cand_p": np.int32,
    "cand_c": np.float32,
    "fwd_p": np.int32,
    "fwd_c": np.float32,
    "pool_t": np.int32,
    "pool_c": np.float32,
}


def jax_isa() -> str:
    """Float-pipeline provenance tag for the jax engine — the XLA
    backend the candidate costs were scored under (``jax:cpu`` /
    ``jax:tpu`` / ...). Plays the role ``native.current_isa()`` plays
    for the native arena: a restore under a different backend cold
    re-grounds instead of warm-continuing on costs another float
    pipeline produced. Device COUNT is deliberately excluded — sharded
    generation is D-invariant (bit-identical candidate structure for
    any D), so a warm carry across a device-count change is sound."""
    return f"jax:{jax.devices()[0].platform}"


class JaxSolveArena:
    def __init__(
        self,
        k: int = 64,
        reverse_r: int = 8,
        extra: int = 16,
        threads: int = 0,
        cold_every: int = 256,
        max_dirty_frac: float = 0.25,
        eps_start: float = 4.0,
        eps_end: float = 0.02,
        dual_refresh_every: int = 16,
        devices: int = 0,
        approx_recall: Optional[float] = None,
    ):
        self.k = k
        self.reverse_r = reverse_r
        self.extra = extra
        # accepted (and settable — EngineThreadBudget grants write it)
        # for surface parity with the native arena; the jax engine's
        # parallelism is the device mesh, so the grant never changes a
        # result or a schedule here
        self.threads = threads
        self.cold_every = cold_every
        self.max_dirty_frac = max_dirty_frac
        self.eps_start = eps_start
        self.eps_end = eps_end
        self.dual_refresh_every = dual_refresh_every
        # requested device count for sharded generation (the gRPC
        # kernel string's ``jax:D`` suffix): 0 = all visible devices
        # (the accelerator-native default — use the mesh you have, the
        # same shape as the native engines' "0 = all hardware
        # threads"), resolved lazily at the first solve so constructing
        # an arena never forces backend init. Requests beyond the host
        # clamp with a counted, non-fatal flag (see module docstring).
        self.devices = int(devices)
        self.approx_recall = approx_recall
        self.engine = "jax"
        self.device_degraded = False
        self.device_degraded_events = 0
        self._mesh = None
        self._devices_effective: Optional[int] = None
        self.last_stats: dict = {}
        self._jit_mark = _jitwitness.snapshot()
        self.invalidate()

    # ---------------- carried-state surface (native-arena parity) ----

    @property
    def price(self) -> Optional[np.ndarray]:
        """Carried auction prices [P] after the last solve (dual state)."""
        return self._price

    @property
    def retired(self) -> Optional[np.ndarray]:
        """Carried retirement mask [T] after the last solve."""
        return self._retired

    @property
    def potentials(self) -> tuple[Optional[np.ndarray], Optional[np.ndarray]]:
        """Sinkhorn potentials — always (None, None): the jax engine's
        ladder is the auction; the slot exists for surface parity."""
        return None, None

    def invalidate(self) -> None:
        """Drop all carried state: the next solve is cold."""
        self._p_fields: Optional[dict] = None
        self._r_fields: Optional[dict] = None
        self._weights_key: Optional[tuple] = None
        self._cand_p: Optional[np.ndarray] = None
        self._cand_c: Optional[np.ndarray] = None
        self._fwd_p: Optional[np.ndarray] = None
        self._fwd_c: Optional[np.ndarray] = None
        self._pool_t: Optional[np.ndarray] = None
        self._pool_c: Optional[np.ndarray] = None
        # pad-bucket high-water marks for the repair kernels (the
        # ratchet state behind repair_topk_bidir_sharded's pad_floors):
        # carried across warm ticks so repair gathers never shrink into
        # a fresh, never-compiled bucket and retrace mid-chain
        self._repair_pads: dict = {}
        self._price: Optional[np.ndarray] = None
        self._retired: Optional[np.ndarray] = None
        self._p4t: Optional[np.ndarray] = None
        self._warm_solves = 0
        self._dual_age = 0
        self._starve_age: Optional[np.ndarray] = None
        self._last_quality: dict = {}
        self.last_repair_mask: Optional[np.ndarray] = None
        self._owned_cols: set = set()

    # ---------------- export / restore (checkpoint + migration) ------

    def export_state(self) -> Optional[dict]:
        """The carried warm state as a flat dict of scalars and arrays —
        the same key classes as the native arena's export (cand_* +
        duals + matching + cadence cursors + the arena's OWN baseline
        columns), so ``faults/checkpoint.py`` journals and migration
        handoffs carry it unchanged. Returns None before any solve.
        Arrays are copies — a checkpoint must not alias live state."""
        if self._cand_p is None:
            return None

        def _c(a):
            return None if a is None else np.array(a, copy=True)

        out = {
            "cand_p": _c(self._cand_p),
            "cand_c": _c(self._cand_c),
            # generation parts: what the warm-path repair kernels patch.
            # None under approx_recall (no repair twin — see _gen).
            "fwd_p": _c(self._fwd_p),
            "fwd_c": _c(self._fwd_c),
            "pool_t": _c(self._pool_t),
            "pool_c": _c(self._pool_c),
            # the pool width n_tiles*ceil(r/n_tiles) does not encode r
            # (rt saturates at 1), so the config rides along explicitly
            "reverse_r": int(self.reverse_r),
            "price": _c(self._price),
            "retired": _c(self._retired),
            "p4t": _c(self._p4t),
            "starve_age": _c(self._starve_age),
            "warm_solves": int(self._warm_solves),
            "dual_age": int(self._dual_age),
            "weights_key": tuple(self._weights_key),
            # same meta key as the native export so the checkpoint
            # layer's scalar handling is engine-blind; the tag itself
            # names the XLA backend (see jax_isa)
            "native_isa": jax_isa(),
        }
        for name, _ in _P_SPEC:
            out[f"pf_{name}"] = _c(self._p_fields[name])
        for name, _ in _R_SPEC:
            out[f"rf_{name}"] = _c(self._r_fields[name])
        return out

    def restore_state(self, ep, er, state: dict) -> None:
        """Rehydrate the warm chain from :meth:`export_state` output.
        The next ``solve`` continues it bit-identically; a carry this
        arena cannot honor — exported under a different XLA backend
        (the costs came from another float pipeline), by the native
        engine, or at a different candidate width — degrades to an
        honest cold re-ground on the first solve, never a hard error
        mid-tick."""
        self.invalidate()
        if "pf_gpu_count" in state:
            self._p_fields = {
                name: np.array(state[f"pf_{name}"], copy=True)
                for name, _ in _P_SPEC
            }
            self._r_fields = {
                name: np.array(state[f"rf_{name}"], copy=True)
                for name, _ in _R_SPEC
            }
        else:
            self._p_fields = _canon(ep, _P_SPEC)
            self._r_fields = _canon(er, _R_SPEC)
        cand_p = np.asarray(state["cand_p"])
        n_p = self._p_fields["gpu_count"].shape[0]
        n_t = self._r_fields["cpu_cores"].shape[0]
        k_eff = min(self.k, n_p)
        r_eff = min(self.reverse_r, n_t)
        if (
            state.get("native_isa") != jax_isa()
            or cand_p.ndim != 2
            or cand_p.shape != (n_t, k_eff + self.extra)
        ):
            self.invalidate()
            return
        # repair parts: a pre-repair carry (exported before the parts
        # existed) or part-shape skew (k/r config changed) degrades to a
        # cold re-ground exactly like a foreign ISA tag — the merged
        # lists alone cannot seed the repair path, and warm-continuing
        # on them while regenerating parts could pair parts and merge
        # from different feature snapshots. approx_recall arenas carry
        # no parts by design and stay on the regen path (see _gen).
        fwd_p = state.get("fwd_p")
        if self.approx_recall is None:
            # pool width follows the D-free tile policy (a function of
            # T only — the same _gen_plan law generation uses), so a
            # carry from any device count rehydrates here; skew against
            # the policy (k/r/tile config changed) degrades to cold
            tile = pick_tile(n_t, cap=min(1024, max(1, n_t // 8)))
            n_tiles = n_t // tile
            rt_eff = max(1, -(-r_eff // n_tiles))
            if (
                fwd_p is None
                or np.asarray(fwd_p).shape != (n_t, k_eff)
                or state.get("pool_t") is None
                or np.asarray(state["pool_t"]).shape
                != (n_p, n_tiles * rt_eff)
                or state.get("reverse_r") != self.reverse_r
            ):
                self.invalidate()
                return
            for name in ("fwd_p", "fwd_c", "pool_t", "pool_c"):
                setattr(
                    self, f"_{name}",
                    np.array(state[name], _JAX_STATE_DTYPES[name], copy=True),
                )
        self._cand_p = np.array(
            cand_p, _JAX_STATE_DTYPES["cand_p"], copy=True
        )
        self._cand_c = np.array(
            state["cand_c"], _JAX_STATE_DTYPES["cand_c"], copy=True
        )
        for name in ("price", "retired", "p4t", "starve_age"):
            v = state.get(name)
            setattr(
                self, f"_{name}",
                None if v is None else np.array(v, copy=True),
            )
        self._warm_solves = int(state["warm_solves"])
        self._dual_age = int(state["dual_age"])
        self._weights_key = tuple(state["weights_key"])

    # ---------------- internals ----------------

    @staticmethod
    def _wkey(weights) -> tuple:
        return (
            float(weights.price), float(weights.load),
            float(weights.proximity), float(weights.priority),
        )

    def _shapes_compatible(self, pf: dict, rf: dict) -> bool:
        old_p, old_r = self._p_fields, self._r_fields
        if old_p is None or old_r is None:
            return False
        return all(
            pf[n].shape == old_p[n].shape for n, _ in _P_SPEC
        ) and all(rf[n].shape == old_r[n].shape for n, _ in _R_SPEC)

    def _ensure_devices(self) -> int:
        """Resolve the requested device count against the host, once.
        Over-asking clamps to what exists — counted and flagged, never
        fatal, never a cross-engine fallback."""
        if self._devices_effective is None:
            avail = jax.local_device_count()
            want = avail if self.devices <= 0 else self.devices
            if want > avail:
                self.device_degraded = True
                self.device_degraded_events += 1
                want = max(avail, 1)
            self._devices_effective = want
            if want > 1:
                from protocol_tpu.parallel.mesh import make_mesh

                self._mesh = make_mesh(want)
        return self._devices_effective

    def _gen(self, pf: dict, rf: dict, weights):
        """One candidate-generation pass: sharded over the device mesh
        when D > 1 and the shard/tile shapes divide, single-device
        otherwise (flagged via ``gen_sharded``). Deterministic for
        fixed inputs — the warm path diffs its output row-wise against
        the carried structure to get the exact changed set.

        The tile is a function of T ONLY — never of D. Reverse-edge
        selection is tile-POOLED (per-tile top-ceil(r/n_tiles), best r
        of the pool: see candidates_topk_reverse), so the candidate
        structure is a function of the global tiling; a D-derived tile
        would silently break the bit-exact D-invariance contract this
        arena's warm carry (and the provenance tag's D-exclusion)
        rests on. The cap keeps the tile no larger than T/8 so a mesh
        of up to 8 devices shards evenly on round task counts; a shape
        where the per-shard count doesn't divide the tile degrades to
        single-device generation with the SAME tile — same bits,
        flagged, never a different structure.

        Side effect: stores the generation PARTS (forward lists + raw
        per-tile reverse contribution pools) on the arena — the
        persistent structure the warm-path repair patches.
        ``approx_recall`` mode stores None:
        ``lax.approx_max_k`` carries no exactness guarantee, so there
        is no repaired==regen contract to honor and those arenas stay
        on the (honest, counted) full-regen path."""
        ep = EncodedProviders(**pf)
        er = EncodedRequirements(**rf)
        T = rf["cpu_cores"].shape[0]
        tile, use_mesh = self._gen_plan(T)
        with_parts = self.approx_recall is None
        fwd = None
        if use_mesh:
            from protocol_tpu.parallel.sparse import (
                candidates_topk_bidir_sharded,
            )

            out = candidates_topk_bidir_sharded(
                ep, er, weights, mesh=self._mesh, k=self.k,
                tile=tile, reverse_r=self.reverse_r,
                extra=self.extra, approx_recall=self.approx_recall,
                with_parts=with_parts,
            )
            if with_parts:
                cand_p, cand_c, *fwd = out
            else:
                cand_p, cand_c = out
            sharded = True
        else:
            if with_parts:
                from protocol_tpu.ops.sparse import (
                    candidates_topk_reverse,
                    merge_reverse_candidates,
                )

                fwd_p, fwd_c, rev_t, rev_c, pool_t, pool_c = (
                    candidates_topk_reverse(
                        ep, er, weights, k=self.k, tile=tile,
                        reverse_r=self.reverse_r, with_pools=True,
                    )
                )
                cand_p, cand_c = merge_reverse_candidates(
                    fwd_p, fwd_c, rev_t, rev_c, extra=self.extra
                )
                fwd = [fwd_p, fwd_c, pool_t, pool_c]
            else:
                cand_p, cand_c = candidates_topk_bidir(
                    ep, er, weights, k=self.k, tile=tile,
                    reverse_r=self.reverse_r, extra=self.extra,
                    approx_recall=self.approx_recall,
                )
            sharded = False
        if fwd is not None:
            self._fwd_p = np.asarray(fwd[0], np.int32)
            self._fwd_c = np.asarray(fwd[1], np.float32)
            self._pool_t = np.asarray(fwd[2], np.int32)
            self._pool_c = np.asarray(fwd[3], np.float32)
        else:
            self._fwd_p = self._fwd_c = None
            self._pool_t = self._pool_c = None
        return (
            np.asarray(cand_p, np.int32),
            np.asarray(cand_c, np.float32),
            sharded,
        )

    def _gen_plan(self, T: int) -> tuple[int, bool]:
        """(tile, use_mesh) for shape T — ONE decision shared by the
        cold generation pass and the warm repair kernels, so a repair
        can never run under a different tiling or mesh choice than the
        pass that produced the structure it is patching."""
        tile = pick_tile(T, cap=min(1024, max(1, T // 8)))
        D = self._ensure_devices()
        use_mesh = (
            self._mesh is not None and T % D == 0 and (T // D) % tile == 0
        )
        return tile, use_mesh

    def _repair(self, pf: dict, rf: dict, weights, dirty_p, dirty_t):
        """Churn-masked structure repair: patch the persistent parts for
        the given dirty global rows and rebuild the merged lists —
        bit-identical to what :meth:`_gen` would produce on the current
        columns (the repaired==regen oracle contract), at O(churn
        scope) instead of O(P*T). Updates the stored structure in place
        and returns (changed-row mask vs the PREVIOUS merged lists,
        repair-scope stats). Caller guarantees parts exist
        (``approx_recall is None`` and the arena is primed)."""
        from protocol_tpu.parallel.sparse import repair_topk_bidir_sharded

        ep = EncodedProviders(**pf)
        er = EncodedRequirements(**rf)
        T = rf["cpu_cores"].shape[0]
        tile, use_mesh = self._gen_plan(T)
        cand_p, cand_c, fwd_p, fwd_c, pool_t, pool_c, stats = (
            repair_topk_bidir_sharded(
                ep, er, weights,
                fwd_p=self._fwd_p, fwd_c=self._fwd_c,
                pool_t=self._pool_t, pool_c=self._pool_c,
                dirty_p=dirty_p, dirty_t=dirty_t,
                reverse_r=self.reverse_r,
                mesh=self._mesh if use_mesh else None,
                tile=tile, extra=self.extra,
                pad_floors=self._repair_pads,
            )
        )
        self._repair_pads = dict(stats.get("pad_hw") or {})
        changed = (
            (cand_p != self._cand_p).any(axis=1)
            | (cand_c != self._cand_c).any(axis=1)
        )
        self._cand_p, self._cand_c = cand_p, cand_c
        self._fwd_p, self._fwd_c = fwd_p, fwd_c
        self._pool_t, self._pool_c = pool_t, pool_c
        return changed, stats

    def _ladder(self, P: int, eng: Optional[dict]):
        """Cold/refresh solve stage: the eps-annealed auction ladder
        from scratch duals over the CURRENT candidate structure."""
        res, price, retired = assign_auction_sparse_scaled(
            jnp.asarray(self._cand_p), jnp.asarray(self._cand_c),
            num_providers=P, eps_start=self.eps_start,
            eps_end=self.eps_end, stats_out=eng, with_state=True,
        )
        # np.array (not asarray): asarray over a device buffer hands back
        # a READ-ONLY view, and the arena mutates p4t in place on the
        # seat-guard and dirty-row paths. Owned copies, always.
        return (
            np.array(res.provider_for_task, np.int32),
            np.array(price, np.float32),
            np.array(retired, bool),
        )

    def _warm(
        self, P: int, p4t0: np.ndarray, changed: np.ndarray,
        eng: Optional[dict],
    ):
        """Warm solve stage: delta-frontier auction from the carried
        duals. Retirement is cleared for exactly the ``changed`` rows
        (candidates or costs moved, or the seat was re-opened) — the
        warm kernel's documented caller contract; the kernel itself
        applies the uniform price downshift that keeps carried prices
        sound."""
        res, price, retired = assign_auction_sparse_warm(
            jnp.asarray(self._cand_p), jnp.asarray(self._cand_c),
            num_providers=P,
            price0=jnp.asarray(self._price),
            p4t0=jnp.asarray(p4t0),
            eps=self.eps_end,
            retired0=jnp.asarray(self._retired & ~changed),
            stats_out=eng, with_state=True,
        )
        # Owned copies for the same reason as _ladder: the carried
        # structure must stay writable across warm ticks.
        return (
            np.array(res.provider_for_task, np.int32),
            np.array(price, np.float32),
            np.array(retired, bool),
        )

    def _quality_pass(
        self, rf: dict, p4t, price, prev_p4t, eng: Optional[dict] = None
    ) -> dict:
        t0 = time.perf_counter()
        stats, self._starve_age = _quality.tick_quality(
            self._cand_p, self._cand_c, p4t, price,
            valid=rf["valid"].astype(bool),
            prev_p4t=prev_p4t,
            starve_age=self._starve_age,
            outcomes=None,
            eng=eng,
        )
        stats["quality_ms"] = round((time.perf_counter() - t0) * 1e3, 3)
        self._last_quality = stats
        return stats

    def _base_stats(self, T: int, gen_sharded: bool) -> dict:
        base = {
            "native_isa": jax_isa(),
            "engine": "jax",
            "jax_devices": int(self._devices_effective or 1),
            "gen_sharded": gen_sharded,
            "device_degraded": self.device_degraded,
            "rows": T,
        }
        if _jitwitness.enabled():
            # compiles observed DURING this solve, per jit entry — the
            # warm-path contract is an empty dict here (perf_gate --jax
            # asserts it); plus the process-lifetime total for obs
            base["jit_compiles"] = _jitwitness.total()
            base["jit_compiles_delta"] = _jitwitness.delta(
                self._jit_mark
            )
            self._jit_mark = _jitwitness.snapshot()
        return base

    def _cold(self, weights, pf, rf, P, T) -> np.ndarray:
        eng: Optional[dict] = {} if obs.enabled() else None
        t0 = time.perf_counter()
        with _tracer.span("arena.candidates", cold=True, tasks=T):
            self._cand_p, self._cand_c, sharded = self._gen(pf, rf, weights)
        t_gen = time.perf_counter()
        with _tracer.span("arena.engine", engine="jax", cold=True):
            p4t, price, retired = self._ladder(P, eng)
        t_solve = time.perf_counter()
        self._p_fields, self._r_fields = pf, rf
        self._owned_cols = set()
        self._weights_key = self._wkey(weights)
        self._price, self._retired, self._p4t = price, retired, p4t
        self._warm_solves = 0
        self._dual_age = 0
        self._starve_age = None
        qual = (
            self._quality_pass(rf, p4t, price, None, eng)
            if obs.enabled() else {}
        )
        self.last_stats = {
            **self._base_stats(T, sharded),
            **qual,
            "cold": True,
            "cand_cold_passes": 1,
            "dirty_providers": P,
            "dirty_tasks": T,
            "changed_rows": T,
            "warm_solves_since_cold": 0,
            "assigned": int((p4t >= 0).sum()),
            "gen_ms": round((t_gen - t0) * 1e3, 3),
            "solve_ms": round((t_solve - t_gen) * 1e3, 3),
            **({f"eng_{k}": v for k, v in eng.items()} if eng else {}),
        }
        return p4t

    # ---------------- streaming entry points ----------------

    def apply_rows(
        self,
        provider_rows: Optional[np.ndarray],
        p_rows: Optional[dict],
        task_rows: Optional[np.ndarray],
        r_rows: Optional[dict],
        weights,
        event_eps_start: Optional[float] = None,
    ) -> np.ndarray:
        """Single-event entry (the stream engine's hot path), same
        contract as the native arena: explicit churned rows, values
        equal to the current columns dropped, the arena's baseline
        updated in place for truly-dirty rows, RuntimeError/ValueError
        on an unprimed arena or a weights mismatch.

        A dirty event pays O(churned rows): the churn-masked repair
        kernels patch exactly the flagged forward rows and reverse
        pools of the persistent structure (``cand_cold_passes: 0``,
        repair-scope counters in ``last_stats``) — same oracle contract
        as the batch warm path. Only ``approx_recall`` arenas (no
        repair twin) still pay a full regen, reported honestly as
        ``cand_cold_passes: 1``. ``event_eps_start`` is accepted for
        signature parity; the jax warm kernel runs one fine-eps phase
        (its own eps-CS repair handles re-seating)."""
        if self._cand_p is None:
            raise RuntimeError(
                "arena not primed for apply_rows: run solve() first "
                "(the persistent candidate structure must exist)"
            )
        if self._weights_key != self._wkey(weights):
            raise ValueError(
                "apply_rows under different weights: the carried "
                "structure was scored under the old weights (re-prime "
                "with a batch solve)"
            )
        t_start = time.perf_counter()
        P = self._p_fields["gpu_count"].shape[0]
        T = self._r_fields["cpu_cores"].shape[0]

        def _narrow(rows, vals, fields, spec, n, side):
            if rows is None or vals is None:
                return np.zeros(0, np.int32)
            rows = np.asarray(rows, np.int64).ravel()
            if rows.size == 0:
                return np.zeros(0, np.int32)
            if rows.min() < 0 or rows.max() >= n:
                raise ValueError(f"event row index out of range [0, {n})")
            dirty = np.zeros(rows.size, bool)
            canon = {}
            for name, dtype in spec:
                v = np.ascontiguousarray(np.asarray(vals[name]), dtype)
                if v.shape[0] != rows.size:
                    raise ValueError(
                        f"event column {name!r} has {v.shape[0]} rows "
                        f"for {rows.size} row indices"
                    )
                canon[name] = v
                diff = fields[name][rows] != v
                dirty |= diff.reshape(rows.size, -1).any(axis=1)
            keep = np.flatnonzero(dirty)
            if keep.size:
                idx = rows[keep]
                for name, _ in spec:
                    key = (side, name)
                    if key not in self._owned_cols:
                        fields[name] = fields[name].copy()
                        self._owned_cols.add(key)
                    fields[name][idx] = canon[name][keep]
            return rows[keep].astype(np.int32)

        dirty_p = _narrow(
            provider_rows, p_rows, self._p_fields, _P_SPEC, P, "p"
        )
        dirty_t = _narrow(
            task_rows, r_rows, self._r_fields, _R_SPEC, T, "r"
        )
        n_dp, n_dt = int(dirty_p.size), int(dirty_t.size)
        if n_dp == 0 and n_dt == 0:
            self.last_repair_mask = None
            self.last_stats = {
                **self._base_stats(T, False),
                "cold": False, "event": True,
                "cand_cold_passes": 0, "dirty_providers": 0,
                "dirty_tasks": 0, "changed_rows": 0,
                "assigned": int((self._p4t >= 0).sum()),
            }
            return self._p4t.copy()

        eng: Optional[dict] = {} if obs.enabled() else None
        if self._fwd_p is not None:
            changed, rep = self._repair(
                self._p_fields, self._r_fields, weights, dirty_p, dirty_t
            )
            sharded = self._gen_plan(T)[1]
            cold_passes = 0
        else:
            cand_p, cand_c, sharded = self._gen(
                self._p_fields, self._r_fields, weights
            )
            changed = (
                (cand_p != self._cand_p).any(axis=1)
                | (cand_c != self._cand_c).any(axis=1)
            )
            self._cand_p, self._cand_c = cand_p, cand_c
            rep = {}
            cold_passes = 1
        if n_dt:
            self._p4t[dirty_t] = -1
            changed[dirty_t] = True
        seat_check = np.flatnonzero(changed & (self._p4t >= 0))
        if seat_check.size:
            in_list = (
                self._cand_p[seat_check] == self._p4t[seat_check, None]
            ).any(axis=1)
            lost = seat_check[~in_list]
            if lost.size:
                self._p4t[lost] = -1
        t_gen = time.perf_counter()
        p4t, price, retired = self._warm(P, self._p4t, changed, eng)
        t_solve = time.perf_counter()
        self._price, self._retired, self._p4t = price, retired, p4t
        self.last_repair_mask = changed
        self.last_stats = {
            **self._base_stats(T, sharded),
            "cold": False,
            "event": True,
            "cand_cold_passes": cold_passes,
            # scope counters first: the stream-facing "repair_rows"
            # (rows whose merged lists actually changed — what the
            # certificate and EventResult count) overrides the repair
            # kernels' forward-scope counter of the same name
            **rep,
            "dirty_providers": n_dp,
            "dirty_tasks": n_dt,
            "changed_rows": int(changed.sum()),
            "repair_rows": int(changed.sum()),
            "assigned": int((p4t >= 0).sum()),
            "gen_ms": round((t_gen - t_start) * 1e3, 3),
            "solve_ms": round((t_solve - t_gen) * 1e3, 3),
            **({f"eng_{k}": v for k, v in eng.items()} if eng else {}),
        }
        return p4t

    def reconcile(self) -> np.ndarray:
        """Full batch re-solve over the CURRENT candidate structure from
        scratch duals — the stream engine's periodic reconciliation.
        The repaired==regen oracle contract makes the current structure
        equal to a from-scratch rebuild on the current columns, so this
        is bit-identical to a cold solve without re-paying a gen pass."""
        if self._cand_p is None:
            raise RuntimeError(
                "arena not primed for reconcile: run solve() first"
            )
        t0 = time.perf_counter()
        P = self._p_fields["gpu_count"].shape[0]
        T = self._r_fields["cpu_cores"].shape[0]
        eng: Optional[dict] = {} if obs.enabled() else None
        prev_p4t = self._p4t.copy() if obs.enabled() else None
        with _tracer.span("arena.engine", engine="jax", reconcile=True):
            p4t, price, retired = self._ladder(P, eng)
        t_solve = time.perf_counter()
        self._price, self._retired, self._p4t = price, retired, p4t
        self._warm_solves = 0
        self._dual_age = 0
        self._starve_age = None
        qual = (
            self._quality_pass(self._r_fields, p4t, price, prev_p4t, eng)
            if obs.enabled() else {}
        )
        self.last_stats = {
            **self._base_stats(T, False),
            **qual,
            "cold": False,
            "reconcile": True,
            "cand_cold_passes": 0,
            "dirty_providers": 0,
            "dirty_tasks": 0,
            "changed_rows": 0,
            "assigned": int((p4t >= 0).sum()),
            "solve_ms": round((t_solve - t0) * 1e3, 3),
            **({f"eng_{k}": v for k, v in eng.items()} if eng else {}),
        }
        return p4t

    # ---------------- the solve ----------------

    def solve(self, ep, er, weights) -> np.ndarray:
        """One marketplace solve. ``ep``/``er`` are EncodedProviders /
        EncodedRequirements (numpy- or jax-backed, or any object with
        the same field names); returns provider_for_task [T] i32."""
        with _tracer.span("arena.solve", engine="jax"):
            return self._solve_impl(ep, er, weights)

    def _solve_impl(self, ep, er, weights) -> np.ndarray:
        pf = _canon(ep, _P_SPEC)
        rf = _canon(er, _R_SPEC)
        P = pf["gpu_count"].shape[0]
        T = rf["cpu_cores"].shape[0]
        if P == 0 or T == 0:
            self.last_stats = {
                "native_isa": jax_isa(), "engine": "jax",
                "cold": True, "assigned": 0,
            }
            return np.full(T, -1, np.int32)

        if (
            not self._shapes_compatible(pf, rf)
            or self._weights_key != self._wkey(weights)
            or self._warm_solves >= self.cold_every
        ):
            return self._cold(weights, pf, rf, P, T)

        dirty_p = _dirty_rows(pf, self._p_fields, _P_SPEC)
        dirty_t = _dirty_rows(rf, self._r_fields, _R_SPEC)
        n_dp, n_dt = int(dirty_p.sum()), int(dirty_t.sum())
        if (n_dp + n_dt) / (P + T) > self.max_dirty_frac:
            return self._cold(weights, pf, rf, P, T)
        if n_dp == 0 and n_dt == 0:
            # byte-identical marketplace: the carried matching IS the
            # solve — same short-circuit as the native arena, with the
            # carried quality certificate reused verbatim
            self._warm_solves += 1
            qual: dict = {}
            if obs.enabled():
                t_q = time.perf_counter()
                self._starve_age = _quality.starvation_update(
                    self._starve_age, self._p4t,
                    rf["valid"].astype(bool),
                )
                qual = dict(self._last_quality)
                qual["churn_rows"] = 0
                qual["churn_ratio"] = 0.0
                qual["starve_max"] = (
                    int(self._starve_age.max())
                    if self._starve_age.size else 0
                )
                qual["starving"] = int((self._starve_age > 0).sum())
                qual["starve_hist"] = _quality.starvation_hist(
                    self._starve_age
                )
                qual["quality_ms"] = round(
                    (time.perf_counter() - t_q) * 1e3, 3
                )
                self._last_quality = qual
            self.last_stats = {
                **self._base_stats(T, False),
                **qual,
                "cold": False,
                "cand_cold_passes": 0,
                "dirty_providers": 0,
                "dirty_tasks": 0,
                "changed_rows": 0,
                "warm_solves_since_cold": self._warm_solves,
                "assigned": int((self._p4t >= 0).sum()),
            }
            return self._p4t.copy()

        eng: Optional[dict] = {} if obs.enabled() else None
        prev_p4t = self._p4t.copy() if obs.enabled() else None
        t_start = time.perf_counter()
        self._p_fields, self._r_fields = pf, rf
        self._owned_cols = set()

        # ---- churn-masked structure repair: recompute exactly the
        # flagged forward rows and reverse pools and re-merge —
        # bit-identical to a full regen on the current columns (the
        # repaired==regen oracle contract), without the O(P*T) pass.
        # The changed-row diff against the previous merged lists is
        # still exact (membership moved or any cost moved — a superset
        # of "materially cheaper", so clearing retirement on it is
        # sound, just occasionally generous). approx_recall arenas have
        # no parts (no exactness contract under approx_max_k) and keep
        # the honest full-regen path.
        if self._fwd_p is not None:
            changed, rep = self._repair(
                pf, rf, weights,
                np.flatnonzero(dirty_p), np.flatnonzero(dirty_t),
            )
            sharded = self._gen_plan(T)[1]
            cold_passes = 0
        else:
            cand_p, cand_c, sharded = self._gen(pf, rf, weights)
            changed = (
                (cand_p != self._cand_p).any(axis=1)
                | (cand_c != self._cand_c).any(axis=1)
            )
            self._cand_p, self._cand_c = cand_p, cand_c
            rep = {}
            cold_passes = 1
        if n_dt:
            # a dirty task's seat predates its new requirement: re-seat
            # from scratch
            di = np.flatnonzero(dirty_t)
            self._p4t[di] = -1
            changed[di] = True

        # ---- feasibility guard: a seat whose provider left the row's
        # candidate list must be unseated here (only changed rows can
        # have lost one — unchanged rows kept identical lists)
        seat_check = np.flatnonzero(changed & (self._p4t >= 0))
        if seat_check.size:
            in_list = (
                self._cand_p[seat_check] == self._p4t[seat_check, None]
            ).any(axis=1)
            lost = seat_check[~in_list]
            if lost.size:
                self._p4t[lost] = -1

        t_gen = time.perf_counter()
        _tracer.record_span(
            "arena.candidates", int(t_start * 1e9),
            int((t_gen - t_start) * 1e9), cold=False,
            dirty_providers=n_dp, dirty_tasks=n_dt,
        )
        dual_refresh = (
            self.dual_refresh_every > 0
            and self._dual_age >= self.dual_refresh_every
        )
        if dual_refresh:
            p4t, price, retired = self._ladder(P, eng)
            self._dual_age = 0
        else:
            p4t, price, retired = self._warm(P, self._p4t, changed, eng)
            self._dual_age += 1
        t_solve = time.perf_counter()
        _tracer.record_span(
            "arena.engine", int(t_gen * 1e9),
            int((t_solve - t_gen) * 1e9), engine="jax", cold=False,
        )
        self._price, self._retired, self._p4t = price, retired, p4t
        self._warm_solves += 1
        qual = (
            self._quality_pass(rf, p4t, price, prev_p4t, eng)
            if obs.enabled() else {}
        )
        self.last_stats = {
            **self._base_stats(T, sharded),
            **qual,
            "cold": False,
            "cand_cold_passes": cold_passes,
            **rep,
            "dual_refresh": dual_refresh,
            "dirty_providers": n_dp,
            "dirty_tasks": n_dt,
            "changed_rows": int(changed.sum()),
            "warm_solves_since_cold": self._warm_solves,
            "assigned": int((p4t >= 0).sum()),
            "gen_ms": round((t_gen - t_start) * 1e3, 3),
            "solve_ms": round((t_solve - t_gen) * 1e3, 3),
            **({f"eng_{k}": v for k, v in eng.items()} if eng else {}),
        }
        return p4t
