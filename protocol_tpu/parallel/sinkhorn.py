"""Provider-sharded blocked Sinkhorn over a device mesh.

Completes the 100k-ladder's multi-chip story (BASELINE.md config #3 on a
mesh): providers (and their potential u) are sharded over the 1-D mesh
axis; tasks (and v) are replicated. Per iteration:

  u-update:  entirely shard-local — each device streams ITS provider rows'
             logsumexp over task tiles (the blocked streaming accumulator
             of ops/blocked.py), no communication.
  v-update:  each device computes per-column partial (max, sum·exp) over
             its provider shard; the global logsumexp combines with one
             pmax + one psum per tile — the classic two-collective
             logsumexp-combine, riding ICI with O(T) traffic per
             iteration, independent of P.

Parity-tested against the single-device blocked kernel on the virtual
8-device CPU mesh.
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax import lax
from protocol_tpu.parallel._compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from protocol_tpu.ops.blocked import (
    _NEG,
    feasibility_scan,
    make_k_block,
    streaming_row_logsumexp,
)
from protocol_tpu.ops.cost import CostWeights
from protocol_tpu.ops.encoding import EncodedProviders, EncodedRequirements


@lru_cache(maxsize=64)
def _build_sharded_sinkhorn(
    mesh: Mesh,
    axis: str,
    weights_key: tuple,
    eps: float,
    num_iters: int,
    tile: int,
    T: int,
):
    # Cached per static config: a closure rebuilt per call would re-trace
    # and re-compile the fori_loop on every solve (see parallel/sparse.py).
    # ``er`` is a replicated ARGUMENT (not a capture) so data churn does
    # not invalidate the cache.
    weights = CostWeights(*weights_key)
    n_tiles = T // tile
    starts = jnp.arange(n_tiles, dtype=jnp.int32) * tile

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis), P()),
        out_specs=(P(axis), P()),
        check_vma=False,
    )
    def run(ep_local: EncodedProviders, er: EncodedRequirements):
        Pl = ep_local.gpu_count.shape[0]

        # shared streamed-kernel helpers (ops/blocked.py): bit-identical
        # math on each shard's provider rows is what parity rests on
        k_block = make_k_block(ep_local, er, weights, eps, tile)

        # feasibility pass: local row-any; column-any via psum of local anys
        row_any_l, col_any_tiles = feasibility_scan(k_block, Pl, starts)
        col_any = (
            lax.psum(col_any_tiles.reshape(T).astype(jnp.int32), axis) > 0
        )
        np_valid = jnp.maximum(
            lax.psum(jnp.sum(row_any_l.astype(jnp.int32)), axis), 1
        )
        nt_valid = jnp.maximum(jnp.sum(col_any), 1)
        m = jnp.minimum(np_valid, nt_valid).astype(jnp.float32)
        log_a = jnp.where(
            row_any_l, jnp.log(m / np_valid.astype(jnp.float32)), _NEG
        )
        log_b = jnp.where(
            col_any, jnp.log(m / nt_valid.astype(jnp.float32)), _NEG
        )

        def iteration(_i, uv):
            u_l, v = uv

            # ---- u-update: shard-local streaming logsumexp over tiles
            lse_u = streaming_row_logsumexp(k_block, v, starts, Pl, tile)
            u_l = jnp.where(row_any_l, log_a - lse_u, _NEG)

            # ---- v-update: per-tile column logsumexp with a two-collective
            # combine: global max (pmax), then psum of rescaled sum-exps
            def v_step(carry, t0):
                k = k_block(t0) + u_l[:, None]
                local_max = jnp.max(k, axis=0)  # [tile]
                gmax = lax.pmax(local_max, axis)
                local_sum = jnp.sum(jnp.exp(k - gmax[None, :]), axis=0)
                gsum = lax.psum(local_sum, axis)
                return carry, gmax + jnp.log(jnp.maximum(gsum, 1e-30))

            _, lse_tiles = lax.scan(v_step, None, starts)
            v = log_b - lse_tiles.reshape(T)
            v = jnp.where(col_any, v, _NEG)
            return u_l, v

        u0 = jnp.zeros(Pl, jnp.float32)
        v0 = jnp.zeros(T, jnp.float32)
        return lax.fori_loop(0, num_iters, iteration, (u0, v0))

    return run


def sinkhorn_potentials_sharded(
    ep: EncodedProviders,
    er: EncodedRequirements,
    mesh: Mesh,
    weights: CostWeights | None = None,
    eps: float = 0.05,
    num_iters: int = 50,
    tile: int = 1024,
    axis: str = "p",
) -> tuple[jax.Array, jax.Array]:
    """Returns (u [P] provider-sharded-then-gathered, v [T] replicated)."""
    if weights is None:
        weights = CostWeights()
    Pn = ep.gpu_count.shape[0]
    T = er.cpu_cores.shape[0]
    D = mesh.shape[axis]
    if Pn % D != 0:
        raise ValueError(f"P={Pn} not divisible by mesh size {D}; pad first")
    if T % tile != 0:
        raise ValueError(f"T={T} not divisible by tile={tile}; pad requirements")

    shard_p = NamedSharding(mesh, P(axis))
    ep = jax.tree.map(lambda x: jax.device_put(x, shard_p), ep)

    # astuple carries EVERY field in declaration order: a future CostWeights
    # field automatically reaches both the cache key and the rebuilt weights
    weights_key = tuple(
        float(v) for v in dataclasses.astuple(weights)
    )
    run = _build_sharded_sinkhorn(
        mesh, axis, weights_key, float(eps), int(num_iters), int(tile), T
    )
    return run(ep, er)
