"""Device-mesh construction and sharded assignment kernels.

The reference scales its control plane with tokio fan-out concurrency
(SURVEY.md §2.9); the O(providers x tasks) matching itself never scales.
Here the matching is SPMD over a 1-D provider mesh: each device owns a
contiguous shard of providers (cost rows), and the auction's combine step
rides ICI collectives (all_gather of per-shard top-2 candidates, max-combine
of replicated state).
"""

# the jit-cache witness must wrap jax.jit BEFORE any kernel module's
# decorators execute (scripts/analysis/staging.py is the static twin)
from protocol_tpu.utils import jitwitness as _jitwitness

_jitwitness.install()

from protocol_tpu.parallel.mesh import make_mesh, pad_to_multiple
from protocol_tpu.parallel.auction import assign_auction_sharded
from protocol_tpu.parallel.jax_arena import JaxSolveArena
from protocol_tpu.parallel.sinkhorn import sinkhorn_potentials_sharded
from protocol_tpu.parallel.sparse import (
    assign_auction_sparse_scaled_sharded,
    assign_auction_sparse_sharded,
    assign_auction_sparse_warm_sharded,
    candidates_topk_bidir_sharded,
)

__all__ = [
    "JaxSolveArena",
    "assign_auction_sharded",
    "assign_auction_sparse_scaled_sharded",
    "assign_auction_sparse_sharded",
    "assign_auction_sparse_warm_sharded",
    "candidates_topk_bidir_sharded",
    "make_mesh",
    "pad_to_multiple",
    "sinkhorn_potentials_sharded",
]
