"""Mesh helpers for the provider-sharded scheduler kernels."""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh


PROVIDER_AXIS = "p"


def make_mesh(num_devices: Optional[int] = None, axis: str = PROVIDER_AXIS) -> Mesh:
    """1-D mesh over the first ``num_devices`` devices (default: all).

    The provider axis is the only sharded axis in the scheduler: providers
    outnumber everything else and the per-provider state (prices, owners,
    feature rows) is embarrassingly shardable, while per-task state is small
    and replicated.
    """
    devices = jax.devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices, only {len(devices)} available"
            )
        devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (axis,))


def pad_to_multiple(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple
