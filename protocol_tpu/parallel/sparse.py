"""Task-sharded sparse auction over a device mesh.

The 1M x 1M configuration (BASELINE.md ladder #4/#5): candidate lists
[T, K] are sharded task-wise across the mesh (tasks outnumber everything
and their state is per-task), while the per-provider price/owner vectors
[P] are replicated and combined with max/min collectives each round —
P floats of ICI traffic per array, independent of T*K.

Round structure per device (mirrors ops/sparse.py's frontier auction):
  1. local frontier of open local tasks -> local bids
  2. local provider-side winner resolution (scatter-max / scatter-min)
  3. global combine: win_bid = pmax, win_task = pmin among max-bidders
     (task ids are globally formed as shard_offset + local index, so ties
     break identically to the single-device kernel)
  4. replicated price/owner update; each shard applies evictions/wins to
     the task rows it owns

With frontier >= T/D and retire=False this is the Jacobi schedule and is
exactly parity with the single-device sparse kernel — tested on the
virtual 8-device CPU mesh.
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax import lax
from protocol_tpu.parallel._compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from protocol_tpu.ops.assign import AssignResult, _invert
from protocol_tpu.ops.sparse import frontier_bids

_NEG = -1e18


def assign_auction_sparse_sharded(
    cand_provider: jax.Array,
    cand_cost: jax.Array,
    num_providers: int,
    mesh: Mesh,
    eps: float = 0.01,
    max_iters: int = 10000,
    frontier: int = 4096,
    retire: bool = True,
    axis: str = "p",
) -> AssignResult:
    """Sparse auction with tasks sharded over ``mesh`` axis ``axis``.

    cand_provider/cand_cost are [T, K] with T divisible by the mesh size.
    Returns a replicated AssignResult. A thin wrapper over the state-
    passing phase kernel with zero-initialized dual state — ONE shard_map
    body serves this, the eps ladder, and the warm solve, so the
    winner-resolution math the Jacobi parity guarantee rests on exists in
    exactly one sharded copy.
    """
    T, K = cand_cost.shape
    D = mesh.shape[axis]
    if T % D != 0:
        raise ValueError(f"T={T} not divisible by mesh size {D}; pad first")
    Pn = num_providers
    B = min(frontier, T // D)

    sharding = NamedSharding(mesh, P(axis, None))
    cand_provider = jax.device_put(cand_provider, sharding)
    cand_cost = jax.device_put(cand_cost, sharding)

    run = _build_sharded_phase(mesh, axis, Pn, B, int(max_iters), bool(retire))
    _price, _owner, p4t, _retired, _stall = run(
        cand_provider, cand_cost, jnp.float32(eps), jnp.int32(0),
        jnp.zeros(Pn, jnp.float32), jnp.full(Pn, -1, jnp.int32),
        jnp.full(T, -1, jnp.int32), jnp.zeros(T, bool),
    )
    return AssignResult(p4t, _invert(p4t, Pn))


@lru_cache(maxsize=64)
def _build_sharded_phase(
    mesh: Mesh,
    axis: str,
    Pn: int,
    B: int,
    max_iters: int,
    retire: bool,
):
    """The ONE sharded auction body: an eps PHASE that accepts carried
    dual state (prices, owner, assignment) and returns it, so the plain
    solve (zero state), the eps-scaling ladder, and the warm/incremental
    solve all compose over the mesh exactly like their single-device
    twins (ops/sparse._sparse_auction_phase). eps AND the stall limit
    ride in as traced scalars — one cached executable serves every rung
    of the ladder (limit <= 0 disables stall termination). Built once per
    static config and cached: a fresh closure per call would re-trace and
    re-compile the whole while_loop each solve (~9.5 s/call measured on
    the 8-dev CPU mesh)."""
    D = mesh.shape[axis]

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis, None), P(axis, None), P(), P(), P(), P(), P(), P()),
        out_specs=(P(), P(), P(), P(), P()),
        check_vma=False,
    )
    def run(cand_p_local, cand_c_local, eps, stall_limit, price0, owner0, p4t0,
            retired0):
        Tl, K = cand_p_local.shape
        T = Tl * D
        shard = lax.axis_index(axis)
        offset = (shard * Tl).astype(jnp.int32)
        p4t_local = lax.dynamic_slice_in_dim(p4t0, offset, Tl)
        retired_local = lax.dynamic_slice_in_dim(retired0, offset, Tl)

        cand_valid = cand_p_local >= 0
        value_base = jnp.where(cand_valid, -cand_c_local, _NEG)  # [Tl, K]
        task_feasible = jnp.any(cand_valid, axis=1)
        cand_safe = jnp.where(cand_valid, cand_p_local, 0)
        finite_max = lax.pmax(
            jnp.max(jnp.where(cand_valid, cand_c_local, 0.0)), axis
        )
        give_up = -(2.0 * finite_max + 10.0) if retire else jnp.float32(_NEG)

        def n_assigned(p4t_l):
            return lax.psum(jnp.sum(p4t_l >= 0), axis)

        def cond(loop):
            (it, price, owner, p4t_local, retired), best, stall = loop
            n_open = lax.psum(
                jnp.sum((p4t_local < 0) & task_feasible & ~retired), axis
            )
            go = (it < max_iters) & (n_open > 0)
            go &= (stall_limit <= 0) | (stall < stall_limit)
            return go

        def body(loop):
            state, best, stall = loop
            it, price, owner, p4t_local, retired = state
            open_mask = (p4t_local < 0) & task_feasible & ~retired

            f_idx = jnp.flatnonzero(open_mask, size=B, fill_value=Tl).astype(
                jnp.int32
            )
            f_ok = f_idx < Tl
            # shared bid math: bit-identical to the single-device kernel
            p1, v1, v2 = frontier_bids(
                cand_safe, value_base, price, f_idx, f_ok, K
            )

            newly_retired = f_ok & (v1 < give_up)
            retired = retired.at[jnp.where(newly_retired, f_idx, Tl)].set(
                True, mode="drop"
            )

            bidding = f_ok & ~newly_retired & (v1 > _NEG * 0.5)
            bid_amt = price[p1] + (v1 - v2) + eps
            tgt = jnp.where(bidding, p1, Pn)
            gtask = offset + f_idx  # global task ids of the frontier

            win_bid_l = jnp.full(Pn, _NEG).at[tgt].max(
                jnp.where(bidding, bid_amt, _NEG), mode="drop"
            )
            win_bid = lax.pmax(win_bid_l, axis)
            is_winner = bidding & (bid_amt >= win_bid[p1])
            win_task_l = jnp.full(Pn, T, jnp.int32).at[tgt].min(
                jnp.where(is_winner, gtask, T), mode="drop"
            )
            win_task = lax.pmin(win_task_l, axis)
            got_bid = (win_bid > _NEG * 0.5) & (win_task < T)

            evict_g = jnp.where(got_bid & (owner >= 0), owner, T)
            e_in = (evict_g >= offset) & (evict_g < offset + Tl)
            p4t_local = p4t_local.at[jnp.where(e_in, evict_g - offset, Tl)].set(
                -1, mode="drop"
            )
            p_idx = jnp.arange(Pn, dtype=jnp.int32)
            w_in = got_bid & (win_task >= offset) & (win_task < offset + Tl)
            p4t_local = p4t_local.at[jnp.where(w_in, win_task - offset, Tl)].set(
                jnp.where(w_in, p_idx, -1), mode="drop"
            )

            owner = jnp.where(got_bid, win_task, owner)
            price = jnp.where(got_bid, win_bid, price)
            n_now = n_assigned(p4t_local)
            improved = n_now > best
            best = jnp.maximum(best, n_now)
            stall = jnp.where(improved, 0, stall + 1)
            return (it + 1, price, owner, p4t_local, retired), best, stall

        state0 = (
            jnp.int32(0),
            jnp.asarray(price0, jnp.float32),
            jnp.asarray(owner0, jnp.int32),  # GLOBAL task ids
            p4t_local,
            retired_local,
        )
        loop0 = (state0, n_assigned(p4t_local), jnp.int32(0))
        (_, price, owner, p4t_local, retired_l), _best, stall = lax.while_loop(
            cond, body, loop0
        )
        return (
            price,
            owner,
            lax.all_gather(p4t_local, axis).reshape(T),
            lax.all_gather(retired_l, axis).reshape(T),
            stall,
        )

    return run


def _run_phase_sharded(
    mesh, axis, Pn, B0, max_iters, cand_p_dev, cand_c_dev,
    task_feasible, eps, stall_limit, price, owner, p4t,
    frontier_ladder, retired=None,
):
    """One sharded eps phase, optionally in fixed-size segments with the
    per-shard frontier executable direct-fit to the live open set — the
    mesh twin of ops.sparse._phase_adaptive (same measured rationale:
    most rounds are tail eviction chains with a small open set). The
    per-B executables come from the lru_cache'd builder, so the ladder
    costs at most a handful of compiles per config. The retirement mask
    threads through segments (and back to the caller) exactly like the
    single-device state tuple — resetting it per segment would re-open
    retired tasks mid-phase, a semantics drift from _phase_adaptive."""
    D = mesh.shape[axis]
    if retired is None:
        retired = jnp.zeros(p4t.shape[0], bool)
    if not frontier_ladder:
        run = _build_sharded_phase(mesh, axis, Pn, B0, int(max_iters), True)
        return run(
            cand_p_dev, cand_c_dev, jnp.float32(eps),
            jnp.int32(stall_limit), price, owner, p4t, retired,
        )
    seg_rounds = 256
    iters_left = int(max_iters)
    B = B0
    carried = 0
    floor = max(64, 512 // D)
    while iters_left > 0:
        run = _build_sharded_phase(mesh, axis, Pn, B, seg_rounds, True)
        price, owner, p4t, retired, stall = run(
            cand_p_dev, cand_c_dev, jnp.float32(eps), jnp.int32(0),
            price, owner, p4t, retired,
        )
        # the segment kernel reports only its own trailing stall; rounds
        # are bounded by seg_rounds so a whole-segment stall accumulates
        s = int(stall)
        carried = carried + seg_rounds if s >= seg_rounds else s
        iters_left -= seg_rounds
        open_count = int(jnp.sum((p4t < 0) & task_feasible & ~retired))
        if open_count == 0:
            break
        if stall_limit > 0 and carried >= int(stall_limit):
            break
        fit = floor
        while fit * D < open_count and fit < B:
            fit *= 2
        B = min(B, fit)
    return price, owner, p4t, retired, jnp.int32(carried)


def assign_auction_sparse_scaled_sharded(
    cand_provider: jax.Array,
    cand_cost: jax.Array,
    num_providers: int,
    mesh: Mesh,
    eps_start: float = 4.0,
    eps_end: float = 0.02,
    scale: float = 0.25,
    max_iters_per_phase: int = 4000,
    frontier: int = 4096,
    with_prices: bool = False,
    stall_limit: int = 64,
    axis: str = "p",
    stats_out: dict | None = None,
    frontier_ladder: bool = False,
    with_state: bool = False,
):
    """The eps-scaling ladder over the task-sharded phase kernel — the
    multi-chip twin of ops.sparse.assign_auction_sparse_scaled with the
    SAME phase discipline (disposable coarse phases whose retirements are
    reversed, eps-CS repair between rungs, binding final phase with an 8x
    stall budget, final greedy cleanup). Stage-B completeness at the 1M
    ladder shape = bidirectional candidates + this ladder over v5e-8
    (SCALING.md stage B2). The inter-phase repair and cleanup run on
    replicated arrays (O(T*K) elementwise — negligible next to the
    sharded while_loop they bracket)."""
    from protocol_tpu.ops.sparse import (
        _greedy_cleanup,
        _report_stall,
        _unassign_unhappy,
    )

    T, K = cand_cost.shape
    D = mesh.shape[axis]
    if T % D != 0:
        raise ValueError(f"T={T} not divisible by mesh size {D}; pad first")
    B = min(frontier, T // D)
    sharding = NamedSharding(mesh, P(axis, None))
    cand_p_dev = jax.device_put(cand_provider, sharding)
    cand_c_dev = jax.device_put(cand_cost, sharding)

    price = jnp.zeros(num_providers, jnp.float32)
    owner = jnp.full(num_providers, -1, jnp.int32)
    p4t = jnp.full(T, -1, jnp.int32)
    task_feasible = jnp.any(cand_provider >= 0, axis=1)
    eps = eps_start
    while True:
        final = eps <= eps_end
        # binding final phase gets 8x the disposable phases' stall budget
        # (same discipline as the single-device ladder)
        price, owner, p4t, retired, stall = _run_phase_sharded(
            mesh, axis, num_providers, B, max_iters_per_phase,
            cand_p_dev, cand_c_dev, task_feasible, eps,
            stall_limit * (8 if final else 1), price, owner, p4t,
            frontier_ladder,
        )
        if final:
            _report_stall("scaled-sharded", stall, stall_limit * 8, stats_out)
            break
        eps = max(eps * scale, eps_end)
        owner, p4t = _unassign_unhappy(
            cand_provider, cand_cost, price, owner, p4t, eps
        )
        # coarse-phase retirement was only a circuit breaker; each
        # _run_phase_sharded call starts from a fresh retired=0 mask, so
        # un-retire needs no explicit step here — only the binding
        # phase's retirement survives into the returned state
    p4t = _greedy_cleanup(cand_provider, cand_cost, owner, p4t)
    res = AssignResult(p4t, _invert(p4t, num_providers))
    if with_state:
        return res, price, retired & (p4t < 0)
    if with_prices:
        return res, price
    return res


def assign_auction_sparse_warm_sharded(
    cand_provider: jax.Array,
    cand_cost: jax.Array,
    num_providers: int,
    mesh: Mesh,
    price0: jax.Array,
    p4t0: jax.Array,
    eps: float = 0.02,
    max_iters: int = 20000,
    frontier: int = 4096,
    stall_limit: int = 64,
    axis: str = "p",
    stats_out: dict | None = None,
    frontier_ladder: bool = False,
    retired0: jax.Array | None = None,
    with_state: bool = False,
) -> tuple[AssignResult, jax.Array]:
    """Incremental (delta-frontier) solve over the mesh: the multi-chip
    twin of ops.sparse.assign_auction_sparse_warm — same seed hygiene
    (candidate-less seeds dropped, carried prices downshifted below the
    retirement floor), same eps-CS repair admission, one binding sharded
    phase, greedy cleanup, same optional retirement carry (``retired0`` /
    ``with_state`` — see the single-device docstring for why retirement
    is dual state). Returns (AssignResult, final prices [P]), plus the
    final retirement mask when ``with_state=True``."""
    from protocol_tpu.ops.sparse import (
        _greedy_cleanup,
        _report_stall,
        _unassign_unhappy,
    )

    T, K = cand_cost.shape
    D = mesh.shape[axis]
    if T % D != 0:
        raise ValueError(f"T={T} not divisible by mesh size {D}; pad first")

    task_has_cand = jnp.any(cand_provider >= 0, axis=1)
    p4t0 = jnp.where(task_has_cand, jnp.asarray(p4t0, jnp.int32), -1)
    # uniform downshift, NOT a clamp — must stay bit-identical to the
    # single-device seed hygiene (see ops.sparse.assign_auction_sparse_warm
    # for the measured clamp pathology)
    finite_max = jnp.max(jnp.where(cand_provider >= 0, cand_cost, 0.0))
    price0 = jnp.asarray(price0, jnp.float32)
    price0 = price0 - jnp.maximum(jnp.max(price0) - (finite_max + 5.0), 0.0)
    owner0 = _invert(p4t0, num_providers)
    owner0, p4t0 = _unassign_unhappy(
        cand_provider, cand_cost, price0, owner0, p4t0, eps
    )

    if retired0 is None:
        retired_seed = jnp.zeros(T, bool)
    else:
        retired_seed = jnp.asarray(retired0, bool) & (p4t0 < 0)
    sharding = NamedSharding(mesh, P(axis, None))
    cand_p_dev = jax.device_put(cand_provider, sharding)
    cand_c_dev = jax.device_put(cand_cost, sharding)
    price, owner, p4t, retired, stall = _run_phase_sharded(
        mesh, axis, num_providers, min(frontier, T // D), max_iters,
        cand_p_dev, cand_c_dev, jnp.any(cand_provider >= 0, axis=1), eps,
        stall_limit * 8, price0, owner0, p4t0, frontier_ladder,
        retired=retired_seed,
    )
    _report_stall("warm-sharded", stall, stall_limit * 8, stats_out)
    p4t = _greedy_cleanup(cand_provider, cand_cost, owner, p4t)
    res = AssignResult(p4t, _invert(p4t, num_providers))
    if with_state:
        return res, price, retired & (p4t < 0)
    return res, price


def _merge_rev_pools(
    rev_c_all: jax.Array, rev_t_all: jax.Array, r: int
) -> tuple[jax.Array, jax.Array]:
    """Final cross-shard pool merge: best r of the D per-shard [P, r]
    pools (associativity up to jitter-decorrelated ties; same multiset
    as the sequential fold). ONE home on purpose — the from-scratch
    sharded generation and the warm-path reverse repair must run the
    exact same merge ops or the repaired==regen oracle contract quietly
    decays into "usually identical". Returns (rev_t [P, r], rev_c)."""
    from protocol_tpu.ops.cost import INFEASIBLE

    D, Pn, _ = rev_c_all.shape
    rev_c_cat = jnp.moveaxis(rev_c_all, 0, 1).reshape(Pn, D * r)
    rev_t_cat = jnp.moveaxis(rev_t_all, 0, 1).reshape(Pn, D * r)
    neg_c, m = lax.top_k(-rev_c_cat, r)
    rev_c = -neg_c
    rev_t = jnp.take_along_axis(rev_t_cat, m, axis=1)
    rev_t = jnp.where(rev_c < INFEASIBLE * 0.5, rev_t, -1)
    return rev_t, rev_c


def candidates_topk_bidir_sharded(
    ep,
    er,
    weights=None,
    *,
    mesh: Mesh,
    k: int = 64,
    tile: int = 1024,
    reverse_r: int = 8,
    extra: int = 16,
    axis: str = "p",
    approx_recall: float | None = None,
    with_parts: bool = False,
):
    """Task-sharded bidirectional candidate generation — the mesh twin of
    ops.sparse.candidates_topk_bidir, and the stage where multi-chip
    actually PAYS: generation is the measured wall-clock dominator of a
    cold solve (793 s gen vs 32 s solve at 65k CPU, SCALING.md) and it is
    embarrassingly parallel over task tiles. Each device streams its own
    [P, tile] cost blocks (providers replicated: P x ~14 f32 columns,
    megabytes at 1M) with ZERO per-round collectives; the only
    communication in the whole pass is one all_gather of the [T, k]
    forward lists and the [D, P, r] reverse pools at the end — so v5e-8
    speedup on this stage is ~linear in D, unlike the solve kernel whose
    every round all-reduces the [P] price/owner vectors (see the ICI cost
    model in SCALING.md).

    Parity: the forward tile step is ops.sparse._forward_tile_select
    (shared verbatim — jitter offsets arranged so each shard computes the
    exact global tile it would own single-device), and the reverse pools
    keep the tile-pooled contract (per-tile top-ceil(r/n_tiles_GLOBAL),
    best r of the pool). Pool merging is associative up to float ties,
    which the tie jitter already decorrelates — asserted bit-exact in
    tests/test_parallel_sparse.py.

    ``with_parts=True`` additionally returns the un-merged structure
    parts — (merged_p, merged_c, fwd_p [T, k], fwd_c [T, k],
    pool_t [P, n_tiles*rt], pool_c [P, n_tiles*rt]) — the persistent
    state the warm-path repair (:func:`repair_topk_bidir_sharded`)
    maintains across ticks. The pools are the RAW per-tile reverse
    contributions in global tile order (pre-fold, no -1 masking, fully
    D-invariant: a contribution depends only on the provider's own cost
    row over that tile and the global jitter grid); the folded
    rev_t/rev_c are re-derived from them by replaying the per-shard
    fold, which is what makes reverse repair O(churned provider-tile
    blocks) instead of O(|scope| * T).
    """
    from protocol_tpu.ops.cost import INFEASIBLE, CostWeights
    from protocol_tpu.ops.sparse import (
        _forward_tile_select,
        merge_reverse_candidates,
    )

    if weights is None:
        weights = CostWeights()
    T = er.cpu_cores.shape[0]
    D = mesh.shape[axis]
    if T % D != 0:
        raise ValueError(f"T={T} not divisible by mesh size {D}; pad first")
    Tl = T // D
    if Tl % tile != 0:
        raise ValueError(
            f"local task count {Tl} not divisible by tile={tile}"
        )
    n_tiles_global = T // tile
    Pn = int(ep.gpu_count.shape[0])
    k = min(k, Pn)
    r = min(reverse_r, T)
    rt = max(1, -(-r // n_tiles_global))  # per-tile pool contribution

    er_sharded = jax.tree.map(
        lambda a: jax.device_put(a, NamedSharding(mesh, P(axis))), er
    )
    gen = _build_sharded_gen(
        mesh, axis, dataclasses.astuple(weights), Pn, Tl, k, tile, r, rt,
        approx_recall, jax.tree.structure(er), with_parts,
    )
    if with_parts:
        cand_p, cand_c, rev_c_all, rev_t_all, tile_t_all, tile_c_all = gen(
            ep, er_sharded
        )
    else:
        cand_p, cand_c, rev_c_all, rev_t_all = gen(ep, er_sharded)
    rev_t, rev_c = _merge_rev_pools(rev_c_all, rev_t_all, r)
    merged_p, merged_c = merge_reverse_candidates(
        cand_p, cand_c, rev_t, rev_c, extra=extra
    )
    if with_parts:
        # [n_tiles, P, rt] in global tile order -> [P, n_tiles*rt]
        pool_t = jnp.moveaxis(tile_t_all, 0, 1).reshape(
            Pn, n_tiles_global * rt
        )
        pool_c = jnp.moveaxis(tile_c_all, 0, 1).reshape(
            Pn, n_tiles_global * rt
        )
        return merged_p, merged_c, cand_p, cand_c, pool_t, pool_c
    return merged_p, merged_c


@lru_cache(maxsize=32)
def _build_sharded_gen(
    mesh: Mesh,
    axis: str,
    weights_tuple: tuple,
    Pn: int,
    Tl: int,
    k: int,
    tile: int,
    r: int,
    rt: int,
    approx_recall,
    er_treedef,
    with_pools: bool = False,
):
    """Cached builder for the sharded generation executable (same
    re-trace rationale as _build_sharded_phase: a fresh jit+shard_map
    closure per call would recompile the whole scan each rebuild).
    ``with_pools`` additionally streams out each tile's raw reverse
    contribution [n_tiles, P, rt] (shard-major concatenation == global
    tile order) — the persistent pre-fold state the warm repair keeps."""
    from protocol_tpu.ops.cost import INFEASIBLE, CostWeights
    from protocol_tpu.ops.sparse import _forward_tile_select

    weights = CostWeights(*weights_tuple)
    D = mesh.shape[axis]
    er_specs = jax.tree.unflatten(
        er_treedef, [P(axis)] * er_treedef.num_leaves
    )
    out_specs = (P(axis, None), P(axis, None), P(axis, None, None),
                 P(axis, None, None))
    if with_pools:
        out_specs = out_specs + (P(axis, None, None), P(axis, None, None))

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), er_specs),
        out_specs=out_specs,
        check_vma=False,
    )
    def gen(ep_rep, er_local):
        shard = lax.axis_index(axis)
        offset = (shard * Tl).astype(jnp.uint32)

        def step(carry, t0):
            rev_c0, rev_t0 = carry
            # shared forward step: jitter keyed on the GLOBAL task index
            # via task_offset, so each shard produces exactly the columns
            # the single-device scan would at its global tile
            provider, cost_k, cost = _forward_tile_select(
                ep_rep, er_local, weights, t0, tile, k,
                None, offset, approx_recall,
            )
            tid = offset.astype(jnp.int32) + t0 + jnp.arange(tile, dtype=jnp.int32)
            if rt == 1:
                j = jnp.argmin(cost, axis=1)
                tile_c = jnp.take_along_axis(cost, j[:, None], axis=1)
                tile_t = tid[j][:, None]
            else:
                neg, j = lax.top_k(-cost, rt)
                tile_c = -neg
                tile_t = tid[j]
            merged_c = jnp.concatenate([rev_c0, tile_c], axis=1)
            merged_t = jnp.concatenate([rev_t0, tile_t], axis=1)
            neg_c, m = lax.top_k(-merged_c, r)
            ys = (provider, cost_k)
            if with_pools:
                ys = ys + (tile_t, tile_c)
            return (-neg_c, jnp.take_along_axis(merged_t, m, axis=1)), ys

        carry0 = (
            jnp.full((Pn, r), jnp.float32(INFEASIBLE)),
            jnp.full((Pn, r), -1, jnp.int32),
        )
        (rev_c_l, rev_t_l), ys = lax.scan(
            step, carry0, jnp.arange(Tl // tile, dtype=jnp.int32) * tile
        )
        cand_p, cand_c = ys[0], ys[1]
        out = (
            cand_p.reshape(Tl, k),
            cand_c.reshape(Tl, k),
            rev_c_l[None],  # [1, P, r] -> stacked [D, P, r] across shards
            rev_t_l[None],
        )
        if with_pools:
            # [ntl, P, rt] local tiles; shard-axis concat of the leading
            # dim reassembles the global tile order
            out = out + (ys[2], ys[3])
        return out

    return gen


# --------------------------------------------------------------------
# warm-path candidate repair (ISSUE 18): churn-masked recompute of the
# persistent bidirectional structure, bit-identical to a from-scratch
# candidates_topk_bidir_sharded pass on the current features
# --------------------------------------------------------------------

# above-INFEASIBLE sentinel: padded rows/columns in the gathered repair
# batches must never win a selection or flag an enter-mask cell
_PAD_COST = 1e18


def _pow2_pad(n: int, lo: int = 8) -> int:
    """Next power of two >= max(n, lo): bounds the set of distinct
    compiled shapes the repair kernels can request (each pad size is one
    lru_cache'd executable, like the phase builders' B ladder)."""
    p = lo
    while p < n:
        p *= 2
    return p


def _gather_rows(tree, idx: "object", pad: int):
    """Host-side gather of pytree rows with clamp-padding: rows beyond
    ``idx`` repeat row 0 and are discarded by the caller's scatter."""
    import numpy as np

    full = np.zeros(pad, np.int64)
    full[: len(idx)] = idx
    return jax.tree.map(lambda a: jnp.asarray(np.asarray(a)[full]), tree)


@lru_cache(maxsize=32)
def _build_repair_enter(
    weights_tuple: tuple, tile: int, n_tiles: int, dp_pad: int,
    ep_treedef, er_treedef,
):
    """Forward enter-scan kernel: do any of the DIRTY providers' fresh
    (jittered) costs beat a stored row's k-th selection value? Rows they
    do — plus rows that LIST a dirty provider, handled host-side — are
    exactly the rows whose forward top-k can differ from a from-scratch
    pass; everything else keeps bit-identical stored entries. Streams
    [dp_pad, tile] cost blocks over the full task axis (the same memory
    envelope as generation), jitter keyed on explicit GLOBAL ids so a
    gathered provider subset lands on the exact grid the full pass
    applied. ``<=`` on the threshold over-flags exact float ties — the
    flagged row is then recomputed exactly, so ties cost a row of work,
    never a bit of drift."""
    from protocol_tpu.ops.cost import INFEASIBLE, CostWeights, cost_matrix
    from protocol_tpu.ops.cost import tie_jitter_ids
    from protocol_tpu.ops.sparse import _slice_requirements

    weights = CostWeights(*weights_tuple)

    def enter_scan(ep_dirty, p_ids, p_valid, er, thresh):
        def step(_, t0):
            r_tile = _slice_requirements(er, t0, tile)
            cost, _m = cost_matrix(ep_dirty, r_tile, weights)
            jit_grid = tie_jitter_ids(
                p_ids, t0.astype(jnp.uint32) + jnp.arange(tile, dtype=jnp.uint32)
            )
            cost = jnp.where(cost < INFEASIBLE * 0.5, cost + jit_grid, cost)
            cost = jnp.where(p_valid[:, None], cost, jnp.float32(_PAD_COST))
            th = lax.dynamic_slice_in_dim(thresh, t0, tile)
            hit = (cost <= th[None, :]) & (cost < INFEASIBLE * 0.5)
            return None, jnp.any(hit, axis=0)

        _, enter = lax.scan(
            step, None, jnp.arange(n_tiles, dtype=jnp.int32) * tile
        )
        return enter.reshape(n_tiles * tile)

    return jax.jit(enter_scan)


@lru_cache(maxsize=32)
def _build_repair_forward(
    weights_tuple: tuple, Pn: int, kk: int, c_pad: int,
    ep_treedef, er_rows_treedef,
):
    """Forward row recompute: the exact per-row selection of generation
    (_forward_tile_select with provider_offset=None) on a GATHERED task
    subset — full [Pn, c_pad] jittered cost block, stable lax.top_k, the
    same -1 erasure of infeasible slots. A row's forward list depends on
    nothing but its own cost column, so recomputed rows are bit-identical
    to the columns a from-scratch pass would produce regardless of tile
    or shard placement. Also returns the fresh cost block masked to the
    DIRTY task columns (_PAD_COST elsewhere) — the orchestrator folds it
    into the per-(provider, tile) minima that drive the reverse
    enter-mask."""
    from protocol_tpu.ops.cost import INFEASIBLE, CostWeights, cost_matrix
    from protocol_tpu.ops.cost import tie_jitter_ids

    weights = CostWeights(*weights_tuple)

    def forward_rows(ep, er_rows, t_ids, col_dirty):
        cost, _m = cost_matrix(ep, er_rows, weights)  # [Pn, c_pad]
        jit_grid = tie_jitter_ids(jnp.arange(Pn, dtype=jnp.uint32), t_ids)
        cost = jnp.where(cost < INFEASIBLE * 0.5, cost + jit_grid, cost)
        neg_sel, idx = lax.top_k(-cost.T, kk)  # [c_pad, kk] best first
        sel_k = -neg_sel
        provider = jnp.where(
            sel_k < INFEASIBLE * 0.5, idx.astype(jnp.int32), -1
        )
        cost_k = jnp.take_along_axis(cost.T, idx, axis=1)
        dirty_cost = jnp.where(
            col_dirty[None, :], cost, jnp.float32(_PAD_COST)
        )
        return provider, cost_k, dirty_cost

    return jax.jit(forward_rows)


@lru_cache(maxsize=32)
def _build_repair_enter_sharded(
    mesh: Mesh, axis: str, weights_tuple: tuple, Tl: int, tile: int,
    dp_pad: int, ep_treedef, er_treedef,
):
    """Mesh twin of _build_repair_enter: the enter-scan is the one
    repair stage whose work is O(dirty_providers * T) rather than
    O(churn), so at scale it shards over task tiles exactly like
    generation — each shard streams its local [dp_pad, tile] blocks
    (jitter keyed on GLOBAL task ids via the shard offset) and emits its
    [Tl] slice of the enter mask with zero per-round collectives."""
    from protocol_tpu.ops.cost import INFEASIBLE, CostWeights, cost_matrix
    from protocol_tpu.ops.cost import tie_jitter_ids
    from protocol_tpu.ops.sparse import _slice_requirements

    weights = CostWeights(*weights_tuple)
    er_specs = jax.tree.unflatten(
        er_treedef, [P(axis)] * er_treedef.num_leaves
    )

    def enter_scan_sharded(ep_dirty, p_ids, p_valid, er_local, thresh_local):
        shard = lax.axis_index(axis)
        offset = (shard * Tl).astype(jnp.uint32)

        def step(_, t0):
            r_tile = _slice_requirements(er_local, t0, tile)
            cost, _m = cost_matrix(ep_dirty, r_tile, weights)
            jit_grid = tie_jitter_ids(
                p_ids,
                offset + t0.astype(jnp.uint32)
                + jnp.arange(tile, dtype=jnp.uint32),
            )
            cost = jnp.where(cost < INFEASIBLE * 0.5, cost + jit_grid, cost)
            cost = jnp.where(p_valid[:, None], cost, jnp.float32(_PAD_COST))
            th = lax.dynamic_slice_in_dim(thresh_local, t0, tile)
            hit = (cost <= th[None, :]) & (cost < INFEASIBLE * 0.5)
            return None, jnp.any(hit, axis=0)

        _, enter = lax.scan(
            step, None, jnp.arange(Tl // tile, dtype=jnp.int32) * tile
        )
        return enter.reshape(Tl)

    return jax.jit(
        shard_map(
            enter_scan_sharded,
            mesh=mesh,
            in_specs=(P(), P(), P(), er_specs, P(axis)),
            out_specs=P(axis),
            check_vma=False,
        )
    )


@lru_cache(maxsize=32)
def _build_repair_tile(
    weights_tuple: tuple, tile: int, rt: int, s_pad: int,
    ep_rows_treedef, er_tile_treedef,
):
    """Per-tile reverse CONTRIBUTION recompute: one tile's raw
    top-``rt`` per gathered provider — the exact per-tile half of the
    generation fold (same cost ops, same global-id jitter, same
    argmin/top_k branch), nothing folded. A contribution (p, j) depends
    on nothing but provider p's own cost row over tile j, so recomputed
    blocks are bit-identical to the blocks a from-scratch pass emits
    regardless of batch membership or device count; the fold itself is
    replayed over the persisted pools by _build_repair_refold. No -1
    masking here: pools persist raw (infeasible entries keep their
    INFEASIBLE+jitter cost), matching the gen-side emission."""
    from protocol_tpu.ops.cost import INFEASIBLE, CostWeights, cost_matrix
    from protocol_tpu.ops.cost import tie_jitter_ids

    weights = CostWeights(*weights_tuple)

    def tile_contrib(ep_rows, p_ids, er_tile, t0):
        cost, _m = cost_matrix(ep_rows, er_tile, weights)  # [s_pad, tile]
        jit_grid = tie_jitter_ids(
            p_ids,
            t0.astype(jnp.uint32) + jnp.arange(tile, dtype=jnp.uint32),
        )
        cost = jnp.where(cost < INFEASIBLE * 0.5, cost + jit_grid, cost)
        tid = t0.astype(jnp.int32) + jnp.arange(tile, dtype=jnp.int32)
        if rt == 1:
            j = jnp.argmin(cost, axis=1)
            tile_c = jnp.take_along_axis(cost, j[:, None], axis=1)
            tile_t = tid[j][:, None]
        else:
            neg, j = lax.top_k(-cost, rt)
            tile_c = -neg
            tile_t = tid[j]
        return tile_t, tile_c

    return jax.jit(tile_contrib)


@lru_cache(maxsize=32)
def _build_repair_refold(
    Pn: int, n_tiles: int, rt: int, r: int, d_fold: int,
):
    """Fold replay: derive the per-provider best-r reverse edges from
    the persisted [P, n_tiles*rt] contribution pools by running the
    EXACT fold the from-scratch pass runs at ``d_fold`` devices — each
    fold lane owns n_tiles/d_fold consecutive tiles, folds them
    sequentially (concat carry-first, stable top_k, INFEASIBLE/-1
    init), and the lanes meet in _merge_rev_pools, the same final merge
    generation uses. Pure structure ops on ~P*(r + n_tiles*rt) floats —
    milliseconds at any churn, which is what buys reverse repair its
    O(churned blocks) cost. top_k here is selection, not arithmetic, so
    jit fusion cannot perturb a bit."""
    from protocol_tpu.ops.cost import INFEASIBLE

    ntl = n_tiles // d_fold

    def refold(pool_t, pool_c):
        # [P, n_tiles*rt] tile order -> [ntl, D, P, rt] scan layout
        pt = jnp.moveaxis(
            pool_t.reshape(Pn, d_fold, ntl, rt), (1, 2), (1, 0)
        )
        pc = jnp.moveaxis(
            pool_c.reshape(Pn, d_fold, ntl, rt), (1, 2), (1, 0)
        )

        def step(carry, x):
            rev_c0, rev_t0 = carry  # [D, P, r]
            tile_t, tile_c = x      # [D, P, rt]
            merged_c = jnp.concatenate([rev_c0, tile_c], axis=-1)
            merged_t = jnp.concatenate([rev_t0, tile_t], axis=-1)
            neg_c, m = lax.top_k(-merged_c, r)
            return (-neg_c, jnp.take_along_axis(merged_t, m, axis=-1)), None

        carry0 = (
            jnp.full((d_fold, Pn, r), jnp.float32(INFEASIBLE)),
            jnp.full((d_fold, Pn, r), -1, jnp.int32),
        )
        (rev_c_all, rev_t_all), _ = lax.scan(step, carry0, (pt, pc))
        return _merge_rev_pools(rev_c_all, rev_t_all, r)

    return jax.jit(refold)


def repair_topk_bidir_sharded(
    ep,
    er,
    weights=None,
    *,
    fwd_p,
    fwd_c,
    pool_t,
    pool_c,
    dirty_p,
    dirty_t,
    reverse_r: int = 8,
    mesh: Mesh | None = None,
    tile: int = 1024,
    extra: int = 16,
    axis: str = "p",
    pad_floors: dict | None = None,
):
    """Churn-masked repair of the persistent bidirectional candidate
    structure — the JAX twin of the native engine's
    ``repair_topk_candidates_mt``, honoring the same oracle contract:
    the repaired (fwd, pools, merged) structure is bit-identical to a
    from-scratch :func:`candidates_topk_bidir_sharded` pass on the
    CURRENT features, at every device count (exactness argued per
    kernel above; cross-D identity is the tile-pooled D-invariance the
    generation path already certifies).

    Scope derivation (host-side numpy over the stored structure — no
    full cost pass anywhere):

      forward rows R        = dirty tasks
                            ∪ rows listing a dirty provider in their top-k
                            ∪ rows a dirty provider's fresh cost can enter
                              (enter-scan kernel vs the stored k-th value)
      reverse blocks (p, j) = all tiles of dirty providers
                            ∪ blocks whose contribution lists a dirty task
                            ∪ blocks a dirty task's fresh cost can enter
                              (per-tile min fresh dirty cost vs the
                              block's worst kept contribution)

    Rows in R and flagged (provider, tile) blocks are recomputed
    EXACTLY (full selection on their own cost columns/blocks);
    everything else keeps stored bits, and the folded reverse edges are
    re-derived by REPLAYING the generation fold over the pools
    (_build_repair_refold) — so reverse repair costs O(flagged blocks *
    tile), not O(|provider scope| * T). The block enter-test carries no
    feasibility guard on purpose: a cell flipping feasible->infeasible
    still lands INFEASIBLE+jitter in the cost grid and can displace an
    infeasible-tail entry of a half-empty block in a fresh pass, and
    bit-identity owes those tail bits too. Leave-promotion inside a
    tile cannot change an unflagged block: a tilemate promoted by a
    dirty task's exit requires the dirty task to have been IN the
    block's top-rt — which flags containment.

    ``ep``/``er`` carry the CURRENT features; stored arrays are NOT
    mutated (fresh arrays returned). ``dirty_p``/``dirty_t`` are global
    row indices. Unsupported generation modes (``provider_offset``,
    ``approx_recall``) have no repair twin — callers on those modes
    keep the regen path. Returns ``(cand_p, cand_c, fwd_p, fwd_c,
    pool_t, pool_c, stats)`` with honest scope counters
    (``repair_rows``, ``repair_providers``, ``repair_blocks``,
    ``visited_cells_frac`` — the fraction of the P*T cost grid
    re-evaluated; the refold and final merge are structure ops both
    paths pay and are excluded).

    ``pad_floors`` is the pad-bucket ratchet: a mapping of kernel
    family ("enter" / "forward" / "tile") to the largest pow-2 pad that
    family has already compiled for. Each gather pads to at least that
    floor, so the jit compile-key set is MONOTONE across a warm chain —
    a later tick can never fall into a smaller, never-traced bucket and
    stall on the tracer mid-tick. Exactness is unaffected: every repair
    kernel is per-row (no cross-row reduction), pad rows are clamp
    copies, and write-back slices ``[:n]``, so a row's bits do not
    depend on the batch pad. The new high-water marks come back in
    ``stats["pad_hw"]`` for the caller to persist alongside the parts;
    the wasted pad work is bounded by one pow-2 bucket and the floor
    only rises log-many times over a process lifetime."""
    import numpy as np

    from protocol_tpu.ops.cost import CostWeights
    from protocol_tpu.ops.sparse import merge_reverse_candidates

    if weights is None:
        weights = CostWeights()
    wtuple = dataclasses.astuple(weights)
    Pn = int(ep.gpu_count.shape[0])
    T = int(er.cpu_cores.shape[0])
    if T % tile != 0:
        raise ValueError(f"T={T} not divisible by tile={tile}")
    n_tiles = T // tile
    fwd_p = np.asarray(fwd_p)
    fwd_c = np.asarray(fwd_c)
    pool_t_np = np.array(pool_t, copy=True)
    pool_c_np = np.array(pool_c, copy=True)
    kk = fwd_p.shape[1]
    r = min(reverse_r, T)
    rt = max(1, -(-r // n_tiles))
    if pool_t_np.shape[1] != n_tiles * rt:
        raise ValueError(
            f"pool width {pool_t_np.shape[1]} != n_tiles*rt "
            f"({n_tiles}*{rt}) for reverse_r={reverse_r}"
        )
    dirty_p = np.asarray(dirty_p, np.int64).ravel()
    dirty_t = np.asarray(dirty_t, np.int64).ravel()
    ep_treedef = jax.tree.structure(ep)
    er_treedef = jax.tree.structure(er)

    pad_hw = dict(pad_floors) if pad_floors else {}

    def _padq(kind: str, n: int) -> int:
        p = max(_pow2_pad(n), pad_hw.get(kind, 0))
        pad_hw[kind] = p
        return p

    use_mesh = (
        mesh is not None and T % mesh.shape[axis] == 0
        and (T // mesh.shape[axis]) % tile == 0
    )

    # ---- forward scope
    rows = np.zeros(T, bool)
    rows[dirty_t] = True
    enter_count = 0
    if dirty_p.size:
        rows |= np.isin(fwd_p, dirty_p).any(axis=1)
        dp_pad = _padq("enter", dirty_p.size)
        ep_dirty = _gather_rows(ep, dirty_p, dp_pad)
        p_ids = np.zeros(dp_pad, np.uint32)
        p_ids[: dirty_p.size] = dirty_p
        p_valid = np.zeros(dp_pad, bool)
        p_valid[: dirty_p.size] = True
        if use_mesh:
            D = mesh.shape[axis]
            run = _build_repair_enter_sharded(
                mesh, axis, wtuple, T // D, tile, dp_pad,
                ep_treedef, er_treedef,
            )
            er_dev = jax.tree.map(
                lambda a: jax.device_put(
                    a, NamedSharding(mesh, P(axis))
                ), er,
            )
            thresh = jax.device_put(
                jnp.asarray(fwd_c[:, -1]), NamedSharding(mesh, P(axis))
            )
        else:
            run = _build_repair_enter(
                wtuple, tile, n_tiles, dp_pad, ep_treedef, er_treedef,
            )
            er_dev = jax.tree.map(jnp.asarray, er)
            thresh = jnp.asarray(fwd_c[:, -1])
        enter = np.asarray(
            run(
                ep_dirty, jnp.asarray(p_ids), jnp.asarray(p_valid),
                er_dev, thresh,
            )
        )
        enter_count = int(enter.sum())
        rows |= enter
    R = np.flatnonzero(rows)

    # ---- forward recompute (chunked at the generation tile's memory
    # envelope) + per-(provider, tile) dirty-cost minima for the
    # reverse block enter-mask
    fwd_p_new, fwd_c_new = fwd_p, fwd_c
    min_dirty_tile = np.full((Pn, n_tiles), _PAD_COST, np.float32)
    is_dirty_t = np.zeros(T, bool)
    is_dirty_t[dirty_t] = True
    if R.size:
        fwd_p_new = fwd_p.copy()
        fwd_c_new = fwd_c.copy()
        ep_full = jax.tree.map(jnp.asarray, ep)
        chunk_cap = min(1024, tile)
        for lo in range(0, R.size, chunk_cap):
            chunk = R[lo: lo + chunk_cap]
            c_pad = _padq("forward", chunk.size)
            er_rows = _gather_rows(er, chunk, c_pad)
            t_ids = np.zeros(c_pad, np.uint32)
            t_ids[: chunk.size] = chunk
            col_dirty = np.zeros(c_pad, bool)
            col_dirty[: chunk.size] = is_dirty_t[chunk]
            run = _build_repair_forward(
                wtuple, Pn, kk, c_pad, ep_treedef,
                jax.tree.structure(er_rows),
            )
            prov, cost_k, dc = run(
                ep_full, er_rows, jnp.asarray(t_ids),
                jnp.asarray(col_dirty),
            )
            fwd_p_new[chunk] = np.asarray(prov)[: chunk.size]
            fwd_c_new[chunk] = np.asarray(cost_k)[: chunk.size]
            if col_dirty.any():
                dc = np.asarray(dc)[:, : chunk.size]
                tiles_of = chunk // tile
                for j in np.unique(tiles_of[is_dirty_t[chunk]]):
                    sel = tiles_of == j
                    np.minimum(
                        min_dirty_tile[:, j], dc[:, sel].min(axis=1),
                        out=min_dirty_tile[:, j],
                    )

    # ---- reverse scope: flag (provider, tile) contribution blocks
    flag = np.zeros((Pn, n_tiles), bool)
    flag[dirty_p, :] = True
    if dirty_t.size:
        pt3 = pool_t_np.reshape(Pn, n_tiles, rt)
        pc3 = pool_c_np.reshape(Pn, n_tiles, rt)
        flag |= np.isin(pt3, dirty_t).any(axis=2)
        flag |= min_dirty_tile <= pc3[:, :, -1]
    blocks = int(flag.sum())
    if blocks:
        s_cap = 4096
        for j in np.flatnonzero(flag.any(axis=0)):
            er_tile = jax.tree.map(
                lambda a: jnp.asarray(
                    np.asarray(a)[j * tile: (j + 1) * tile]
                ), er,
            )
            t0 = jnp.uint32(j * tile)
            sj = np.flatnonzero(flag[:, j])
            for lo in range(0, sj.size, s_cap):
                sc = sj[lo: lo + s_cap]
                s_pad = _padq("tile", sc.size)
                ep_rows = _gather_rows(ep, sc, s_pad)
                p_ids = np.zeros(s_pad, np.uint32)
                p_ids[: sc.size] = sc
                run = _build_repair_tile(
                    wtuple, tile, rt, s_pad,
                    jax.tree.structure(ep_rows),
                    jax.tree.structure(er_tile),
                )
                tt, tc = run(ep_rows, jnp.asarray(p_ids), er_tile, t0)
                pool_t_np[sc, j * rt: (j + 1) * rt] = (
                    np.asarray(tt)[: sc.size]
                )
                pool_c_np[sc, j * rt: (j + 1) * rt] = (
                    np.asarray(tc)[: sc.size]
                )

    # ---- fold replay + auction-visible merge (exact, deterministic:
    # bit-identical parts in => bit-identical merged lists out)
    d_fold = mesh.shape[axis] if use_mesh else 1
    refold = _build_repair_refold(Pn, n_tiles, rt, r, d_fold)
    rev_t, rev_c = refold(
        jnp.asarray(pool_t_np), jnp.asarray(pool_c_np)
    )
    cand_p, cand_c = merge_reverse_candidates(
        jnp.asarray(fwd_p_new), jnp.asarray(fwd_c_new),
        rev_t, rev_c, extra=extra,
    )
    visited = R.size * Pn + blocks * tile + dirty_p.size * T
    stats = {
        "repair_rows": int(R.size),
        "repair_providers": int(flag.any(axis=1).sum()),
        "repair_blocks": blocks,
        "repair_enter_rows": enter_count,
        "visited_cells_frac": round(visited / max(Pn * T, 1), 6),
        "pad_hw": pad_hw,
    }
    return (
        np.asarray(cand_p, np.int32),
        np.asarray(cand_c, np.float32),
        fwd_p_new,
        fwd_c_new,
        pool_t_np,
        pool_c_np,
        stats,
    )
