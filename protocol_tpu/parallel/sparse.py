"""Task-sharded sparse auction over a device mesh.

The 1M x 1M configuration (BASELINE.md ladder #4/#5): candidate lists
[T, K] are sharded task-wise across the mesh (tasks outnumber everything
and their state is per-task), while the per-provider price/owner vectors
[P] are replicated and combined with max/min collectives each round —
P floats of ICI traffic per array, independent of T*K.

Round structure per device (mirrors ops/sparse.py's frontier auction):
  1. local frontier of open local tasks -> local bids
  2. local provider-side winner resolution (scatter-max / scatter-min)
  3. global combine: win_bid = pmax, win_task = pmin among max-bidders
     (task ids are globally formed as shard_offset + local index, so ties
     break identically to the single-device kernel)
  4. replicated price/owner update; each shard applies evictions/wins to
     the task rows it owns

With frontier >= T/D and retire=False this is the Jacobi schedule and is
exactly parity with the single-device sparse kernel — tested on the
virtual 8-device CPU mesh.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax import lax
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from protocol_tpu.ops.assign import AssignResult, _invert
from protocol_tpu.ops.sparse import frontier_bids

_NEG = -1e18


@lru_cache(maxsize=64)
def _build_sharded_auction(
    mesh: Mesh,
    axis: str,
    Pn: int,
    B: int,
    eps: float,
    max_iters: int,
    retire: bool,
):
    # Built once per static config and cached: defining the shard_map'd
    # closure inside the public entry point made every call a fresh Python
    # callable, so jit/shard_map re-traced AND re-compiled the whole
    # while_loop each solve (~9.5 s/call on the 8-dev CPU mesh vs ~ms
    # steady-state once cached).
    D = mesh.shape[axis]

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis, None), P(axis, None)),
        out_specs=P(),
        check_vma=False,
    )
    def run(cand_p_local: jax.Array, cand_c_local: jax.Array) -> jax.Array:
        Tl, K = cand_p_local.shape
        T = Tl * D
        shard = lax.axis_index(axis)
        offset = (shard * Tl).astype(jnp.int32)

        cand_valid = cand_p_local >= 0
        value_base = jnp.where(cand_valid, -cand_c_local, _NEG)  # [Tl, K]
        task_feasible = jnp.any(cand_valid, axis=1)
        cand_safe = jnp.where(cand_valid, cand_p_local, 0)
        finite_max = lax.pmax(
            jnp.max(jnp.where(cand_valid, cand_c_local, 0.0)), axis
        )
        give_up = -(2.0 * finite_max + 10.0) if retire else jnp.float32(_NEG)

        def cond(state):
            it, price, owner, p4t_local, retired = state
            n_open = lax.psum(
                jnp.sum((p4t_local < 0) & task_feasible & ~retired), axis
            )
            return (it < max_iters) & (n_open > 0)

        def body(state):
            it, price, owner, p4t_local, retired = state
            open_mask = (p4t_local < 0) & task_feasible & ~retired

            f_idx = jnp.flatnonzero(open_mask, size=B, fill_value=Tl).astype(
                jnp.int32
            )
            f_ok = f_idx < Tl
            # shared bid math: bit-identical to the single-device kernel
            p1, v1, v2 = frontier_bids(
                cand_safe, value_base, price, f_idx, f_ok, K
            )

            newly_retired = f_ok & (v1 < give_up)
            retired = retired.at[jnp.where(newly_retired, f_idx, Tl)].set(
                True, mode="drop"
            )

            bidding = f_ok & ~newly_retired & (v1 > _NEG * 0.5)
            bid_amt = price[p1] + (v1 - v2) + eps
            tgt = jnp.where(bidding, p1, Pn)
            gtask = offset + f_idx  # global task ids of the frontier

            # local winner resolution
            win_bid_l = jnp.full(Pn, _NEG).at[tgt].max(
                jnp.where(bidding, bid_amt, _NEG), mode="drop"
            )
            # global max bid per provider
            win_bid = lax.pmax(win_bid_l, axis)
            # global winner task: min global-task-id among global-max bidders
            is_winner = bidding & (bid_amt >= win_bid[p1])
            win_task_l = jnp.full(Pn, T, jnp.int32).at[tgt].min(
                jnp.where(is_winner, gtask, T), mode="drop"
            )
            win_task = lax.pmin(win_task_l, axis)
            got_bid = (win_bid > _NEG * 0.5) & (win_task < T)

            # evictions + installs on the task rows this shard owns
            # (explicit range masks: negative scatter indices are not
            # reliably dropped, so map out-of-shard ids to Tl)
            evict_g = jnp.where(got_bid & (owner >= 0), owner, T)  # global ids
            e_in = (evict_g >= offset) & (evict_g < offset + Tl)
            p4t_local = p4t_local.at[jnp.where(e_in, evict_g - offset, Tl)].set(
                -1, mode="drop"
            )
            p_idx = jnp.arange(Pn, dtype=jnp.int32)
            w_in = got_bid & (win_task >= offset) & (win_task < offset + Tl)
            p4t_local = p4t_local.at[jnp.where(w_in, win_task - offset, Tl)].set(
                jnp.where(w_in, p_idx, -1), mode="drop"
            )

            # replicated provider state
            owner = jnp.where(got_bid, win_task, owner)
            price = jnp.where(got_bid, win_bid, price)
            return it + 1, price, owner, p4t_local, retired

        state0 = (
            jnp.int32(0),
            jnp.zeros(Pn, jnp.float32),
            jnp.full(Pn, -1, jnp.int32),  # owner holds GLOBAL task ids
            jnp.full(Tl, -1, jnp.int32),
            jnp.zeros(Tl, bool),
        )
        _, _, _, p4t_local, _ = lax.while_loop(cond, body, state0)
        return lax.all_gather(p4t_local, axis).reshape(T)

    return run


def assign_auction_sparse_sharded(
    cand_provider: jax.Array,
    cand_cost: jax.Array,
    num_providers: int,
    mesh: Mesh,
    eps: float = 0.01,
    max_iters: int = 10000,
    frontier: int = 4096,
    retire: bool = True,
    axis: str = "p",
) -> AssignResult:
    """Sparse auction with tasks sharded over ``mesh`` axis ``axis``.

    cand_provider/cand_cost are [T, K] with T divisible by the mesh size.
    Returns a replicated AssignResult.
    """
    T, K = cand_cost.shape
    D = mesh.shape[axis]
    if T % D != 0:
        raise ValueError(f"T={T} not divisible by mesh size {D}; pad first")
    Pn = num_providers
    B = min(frontier, T // D)

    sharding = NamedSharding(mesh, P(axis, None))
    cand_provider = jax.device_put(cand_provider, sharding)
    cand_cost = jax.device_put(cand_cost, sharding)

    run = _build_sharded_auction(
        mesh, axis, Pn, B, float(eps), int(max_iters), bool(retire)
    )
    p4t = run(cand_provider, cand_cost)
    return AssignResult(p4t, _invert(p4t, Pn))
