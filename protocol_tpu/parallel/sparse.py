"""Task-sharded sparse auction over a device mesh.

The 1M x 1M configuration (BASELINE.md ladder #4/#5): candidate lists
[T, K] are sharded task-wise across the mesh (tasks outnumber everything
and their state is per-task), while the per-provider price/owner vectors
[P] are replicated and combined with max/min collectives each round —
P floats of ICI traffic per array, independent of T*K.

Round structure per device (mirrors ops/sparse.py's frontier auction):
  1. local frontier of open local tasks -> local bids
  2. local provider-side winner resolution (scatter-max / scatter-min)
  3. global combine: win_bid = pmax, win_task = pmin among max-bidders
     (task ids are globally formed as shard_offset + local index, so ties
     break identically to the single-device kernel)
  4. replicated price/owner update; each shard applies evictions/wins to
     the task rows it owns

With frontier >= T/D and retire=False this is the Jacobi schedule and is
exactly parity with the single-device sparse kernel — tested on the
virtual 8-device CPU mesh.
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax import lax
from protocol_tpu.parallel._compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from protocol_tpu.ops.assign import AssignResult, _invert
from protocol_tpu.ops.sparse import frontier_bids

_NEG = -1e18


def assign_auction_sparse_sharded(
    cand_provider: jax.Array,
    cand_cost: jax.Array,
    num_providers: int,
    mesh: Mesh,
    eps: float = 0.01,
    max_iters: int = 10000,
    frontier: int = 4096,
    retire: bool = True,
    axis: str = "p",
) -> AssignResult:
    """Sparse auction with tasks sharded over ``mesh`` axis ``axis``.

    cand_provider/cand_cost are [T, K] with T divisible by the mesh size.
    Returns a replicated AssignResult. A thin wrapper over the state-
    passing phase kernel with zero-initialized dual state — ONE shard_map
    body serves this, the eps ladder, and the warm solve, so the
    winner-resolution math the Jacobi parity guarantee rests on exists in
    exactly one sharded copy.
    """
    T, K = cand_cost.shape
    D = mesh.shape[axis]
    if T % D != 0:
        raise ValueError(f"T={T} not divisible by mesh size {D}; pad first")
    Pn = num_providers
    B = min(frontier, T // D)

    sharding = NamedSharding(mesh, P(axis, None))
    cand_provider = jax.device_put(cand_provider, sharding)
    cand_cost = jax.device_put(cand_cost, sharding)

    run = _build_sharded_phase(mesh, axis, Pn, B, int(max_iters), bool(retire))
    _price, _owner, p4t, _retired, _stall = run(
        cand_provider, cand_cost, jnp.float32(eps), jnp.int32(0),
        jnp.zeros(Pn, jnp.float32), jnp.full(Pn, -1, jnp.int32),
        jnp.full(T, -1, jnp.int32), jnp.zeros(T, bool),
    )
    return AssignResult(p4t, _invert(p4t, Pn))


@lru_cache(maxsize=64)
def _build_sharded_phase(
    mesh: Mesh,
    axis: str,
    Pn: int,
    B: int,
    max_iters: int,
    retire: bool,
):
    """The ONE sharded auction body: an eps PHASE that accepts carried
    dual state (prices, owner, assignment) and returns it, so the plain
    solve (zero state), the eps-scaling ladder, and the warm/incremental
    solve all compose over the mesh exactly like their single-device
    twins (ops/sparse._sparse_auction_phase). eps AND the stall limit
    ride in as traced scalars — one cached executable serves every rung
    of the ladder (limit <= 0 disables stall termination). Built once per
    static config and cached: a fresh closure per call would re-trace and
    re-compile the whole while_loop each solve (~9.5 s/call measured on
    the 8-dev CPU mesh)."""
    D = mesh.shape[axis]

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis, None), P(axis, None), P(), P(), P(), P(), P(), P()),
        out_specs=(P(), P(), P(), P(), P()),
        check_vma=False,
    )
    def run(cand_p_local, cand_c_local, eps, stall_limit, price0, owner0, p4t0,
            retired0):
        Tl, K = cand_p_local.shape
        T = Tl * D
        shard = lax.axis_index(axis)
        offset = (shard * Tl).astype(jnp.int32)
        p4t_local = lax.dynamic_slice_in_dim(p4t0, offset, Tl)
        retired_local = lax.dynamic_slice_in_dim(retired0, offset, Tl)

        cand_valid = cand_p_local >= 0
        value_base = jnp.where(cand_valid, -cand_c_local, _NEG)  # [Tl, K]
        task_feasible = jnp.any(cand_valid, axis=1)
        cand_safe = jnp.where(cand_valid, cand_p_local, 0)
        finite_max = lax.pmax(
            jnp.max(jnp.where(cand_valid, cand_c_local, 0.0)), axis
        )
        give_up = -(2.0 * finite_max + 10.0) if retire else jnp.float32(_NEG)

        def n_assigned(p4t_l):
            return lax.psum(jnp.sum(p4t_l >= 0), axis)

        def cond(loop):
            (it, price, owner, p4t_local, retired), best, stall = loop
            n_open = lax.psum(
                jnp.sum((p4t_local < 0) & task_feasible & ~retired), axis
            )
            go = (it < max_iters) & (n_open > 0)
            go &= (stall_limit <= 0) | (stall < stall_limit)
            return go

        def body(loop):
            state, best, stall = loop
            it, price, owner, p4t_local, retired = state
            open_mask = (p4t_local < 0) & task_feasible & ~retired

            f_idx = jnp.flatnonzero(open_mask, size=B, fill_value=Tl).astype(
                jnp.int32
            )
            f_ok = f_idx < Tl
            # shared bid math: bit-identical to the single-device kernel
            p1, v1, v2 = frontier_bids(
                cand_safe, value_base, price, f_idx, f_ok, K
            )

            newly_retired = f_ok & (v1 < give_up)
            retired = retired.at[jnp.where(newly_retired, f_idx, Tl)].set(
                True, mode="drop"
            )

            bidding = f_ok & ~newly_retired & (v1 > _NEG * 0.5)
            bid_amt = price[p1] + (v1 - v2) + eps
            tgt = jnp.where(bidding, p1, Pn)
            gtask = offset + f_idx  # global task ids of the frontier

            win_bid_l = jnp.full(Pn, _NEG).at[tgt].max(
                jnp.where(bidding, bid_amt, _NEG), mode="drop"
            )
            win_bid = lax.pmax(win_bid_l, axis)
            is_winner = bidding & (bid_amt >= win_bid[p1])
            win_task_l = jnp.full(Pn, T, jnp.int32).at[tgt].min(
                jnp.where(is_winner, gtask, T), mode="drop"
            )
            win_task = lax.pmin(win_task_l, axis)
            got_bid = (win_bid > _NEG * 0.5) & (win_task < T)

            evict_g = jnp.where(got_bid & (owner >= 0), owner, T)
            e_in = (evict_g >= offset) & (evict_g < offset + Tl)
            p4t_local = p4t_local.at[jnp.where(e_in, evict_g - offset, Tl)].set(
                -1, mode="drop"
            )
            p_idx = jnp.arange(Pn, dtype=jnp.int32)
            w_in = got_bid & (win_task >= offset) & (win_task < offset + Tl)
            p4t_local = p4t_local.at[jnp.where(w_in, win_task - offset, Tl)].set(
                jnp.where(w_in, p_idx, -1), mode="drop"
            )

            owner = jnp.where(got_bid, win_task, owner)
            price = jnp.where(got_bid, win_bid, price)
            n_now = n_assigned(p4t_local)
            improved = n_now > best
            best = jnp.maximum(best, n_now)
            stall = jnp.where(improved, 0, stall + 1)
            return (it + 1, price, owner, p4t_local, retired), best, stall

        state0 = (
            jnp.int32(0),
            jnp.asarray(price0, jnp.float32),
            jnp.asarray(owner0, jnp.int32),  # GLOBAL task ids
            p4t_local,
            retired_local,
        )
        loop0 = (state0, n_assigned(p4t_local), jnp.int32(0))
        (_, price, owner, p4t_local, retired_l), _best, stall = lax.while_loop(
            cond, body, loop0
        )
        return (
            price,
            owner,
            lax.all_gather(p4t_local, axis).reshape(T),
            lax.all_gather(retired_l, axis).reshape(T),
            stall,
        )

    return run


def _run_phase_sharded(
    mesh, axis, Pn, B0, max_iters, cand_p_dev, cand_c_dev,
    task_feasible, eps, stall_limit, price, owner, p4t,
    frontier_ladder, retired=None,
):
    """One sharded eps phase, optionally in fixed-size segments with the
    per-shard frontier executable direct-fit to the live open set — the
    mesh twin of ops.sparse._phase_adaptive (same measured rationale:
    most rounds are tail eviction chains with a small open set). The
    per-B executables come from the lru_cache'd builder, so the ladder
    costs at most a handful of compiles per config. The retirement mask
    threads through segments (and back to the caller) exactly like the
    single-device state tuple — resetting it per segment would re-open
    retired tasks mid-phase, a semantics drift from _phase_adaptive."""
    D = mesh.shape[axis]
    if retired is None:
        retired = jnp.zeros(p4t.shape[0], bool)
    if not frontier_ladder:
        run = _build_sharded_phase(mesh, axis, Pn, B0, int(max_iters), True)
        return run(
            cand_p_dev, cand_c_dev, jnp.float32(eps),
            jnp.int32(stall_limit), price, owner, p4t, retired,
        )
    seg_rounds = 256
    iters_left = int(max_iters)
    B = B0
    carried = 0
    floor = max(64, 512 // D)
    while iters_left > 0:
        run = _build_sharded_phase(mesh, axis, Pn, B, seg_rounds, True)
        price, owner, p4t, retired, stall = run(
            cand_p_dev, cand_c_dev, jnp.float32(eps), jnp.int32(0),
            price, owner, p4t, retired,
        )
        # the segment kernel reports only its own trailing stall; rounds
        # are bounded by seg_rounds so a whole-segment stall accumulates
        s = int(stall)
        carried = carried + seg_rounds if s >= seg_rounds else s
        iters_left -= seg_rounds
        open_count = int(jnp.sum((p4t < 0) & task_feasible & ~retired))
        if open_count == 0:
            break
        if stall_limit > 0 and carried >= int(stall_limit):
            break
        fit = floor
        while fit * D < open_count and fit < B:
            fit *= 2
        B = min(B, fit)
    return price, owner, p4t, retired, jnp.int32(carried)


def assign_auction_sparse_scaled_sharded(
    cand_provider: jax.Array,
    cand_cost: jax.Array,
    num_providers: int,
    mesh: Mesh,
    eps_start: float = 4.0,
    eps_end: float = 0.02,
    scale: float = 0.25,
    max_iters_per_phase: int = 4000,
    frontier: int = 4096,
    with_prices: bool = False,
    stall_limit: int = 64,
    axis: str = "p",
    stats_out: dict | None = None,
    frontier_ladder: bool = False,
    with_state: bool = False,
):
    """The eps-scaling ladder over the task-sharded phase kernel — the
    multi-chip twin of ops.sparse.assign_auction_sparse_scaled with the
    SAME phase discipline (disposable coarse phases whose retirements are
    reversed, eps-CS repair between rungs, binding final phase with an 8x
    stall budget, final greedy cleanup). Stage-B completeness at the 1M
    ladder shape = bidirectional candidates + this ladder over v5e-8
    (SCALING.md stage B2). The inter-phase repair and cleanup run on
    replicated arrays (O(T*K) elementwise — negligible next to the
    sharded while_loop they bracket)."""
    from protocol_tpu.ops.sparse import (
        _greedy_cleanup,
        _report_stall,
        _unassign_unhappy,
    )

    T, K = cand_cost.shape
    D = mesh.shape[axis]
    if T % D != 0:
        raise ValueError(f"T={T} not divisible by mesh size {D}; pad first")
    B = min(frontier, T // D)
    sharding = NamedSharding(mesh, P(axis, None))
    cand_p_dev = jax.device_put(cand_provider, sharding)
    cand_c_dev = jax.device_put(cand_cost, sharding)

    price = jnp.zeros(num_providers, jnp.float32)
    owner = jnp.full(num_providers, -1, jnp.int32)
    p4t = jnp.full(T, -1, jnp.int32)
    task_feasible = jnp.any(cand_provider >= 0, axis=1)
    eps = eps_start
    while True:
        final = eps <= eps_end
        # binding final phase gets 8x the disposable phases' stall budget
        # (same discipline as the single-device ladder)
        price, owner, p4t, retired, stall = _run_phase_sharded(
            mesh, axis, num_providers, B, max_iters_per_phase,
            cand_p_dev, cand_c_dev, task_feasible, eps,
            stall_limit * (8 if final else 1), price, owner, p4t,
            frontier_ladder,
        )
        if final:
            _report_stall("scaled-sharded", stall, stall_limit * 8, stats_out)
            break
        eps = max(eps * scale, eps_end)
        owner, p4t = _unassign_unhappy(
            cand_provider, cand_cost, price, owner, p4t, eps
        )
        # coarse-phase retirement was only a circuit breaker; each
        # _run_phase_sharded call starts from a fresh retired=0 mask, so
        # un-retire needs no explicit step here — only the binding
        # phase's retirement survives into the returned state
    p4t = _greedy_cleanup(cand_provider, cand_cost, owner, p4t)
    res = AssignResult(p4t, _invert(p4t, num_providers))
    if with_state:
        return res, price, retired & (p4t < 0)
    if with_prices:
        return res, price
    return res


def assign_auction_sparse_warm_sharded(
    cand_provider: jax.Array,
    cand_cost: jax.Array,
    num_providers: int,
    mesh: Mesh,
    price0: jax.Array,
    p4t0: jax.Array,
    eps: float = 0.02,
    max_iters: int = 20000,
    frontier: int = 4096,
    stall_limit: int = 64,
    axis: str = "p",
    stats_out: dict | None = None,
    frontier_ladder: bool = False,
    retired0: jax.Array | None = None,
    with_state: bool = False,
) -> tuple[AssignResult, jax.Array]:
    """Incremental (delta-frontier) solve over the mesh: the multi-chip
    twin of ops.sparse.assign_auction_sparse_warm — same seed hygiene
    (candidate-less seeds dropped, carried prices downshifted below the
    retirement floor), same eps-CS repair admission, one binding sharded
    phase, greedy cleanup, same optional retirement carry (``retired0`` /
    ``with_state`` — see the single-device docstring for why retirement
    is dual state). Returns (AssignResult, final prices [P]), plus the
    final retirement mask when ``with_state=True``."""
    from protocol_tpu.ops.sparse import (
        _greedy_cleanup,
        _report_stall,
        _unassign_unhappy,
    )

    T, K = cand_cost.shape
    D = mesh.shape[axis]
    if T % D != 0:
        raise ValueError(f"T={T} not divisible by mesh size {D}; pad first")

    task_has_cand = jnp.any(cand_provider >= 0, axis=1)
    p4t0 = jnp.where(task_has_cand, jnp.asarray(p4t0, jnp.int32), -1)
    # uniform downshift, NOT a clamp — must stay bit-identical to the
    # single-device seed hygiene (see ops.sparse.assign_auction_sparse_warm
    # for the measured clamp pathology)
    finite_max = jnp.max(jnp.where(cand_provider >= 0, cand_cost, 0.0))
    price0 = jnp.asarray(price0, jnp.float32)
    price0 = price0 - jnp.maximum(jnp.max(price0) - (finite_max + 5.0), 0.0)
    owner0 = _invert(p4t0, num_providers)
    owner0, p4t0 = _unassign_unhappy(
        cand_provider, cand_cost, price0, owner0, p4t0, eps
    )

    if retired0 is None:
        retired_seed = jnp.zeros(T, bool)
    else:
        retired_seed = jnp.asarray(retired0, bool) & (p4t0 < 0)
    sharding = NamedSharding(mesh, P(axis, None))
    cand_p_dev = jax.device_put(cand_provider, sharding)
    cand_c_dev = jax.device_put(cand_cost, sharding)
    price, owner, p4t, retired, stall = _run_phase_sharded(
        mesh, axis, num_providers, min(frontier, T // D), max_iters,
        cand_p_dev, cand_c_dev, jnp.any(cand_provider >= 0, axis=1), eps,
        stall_limit * 8, price0, owner0, p4t0, frontier_ladder,
        retired=retired_seed,
    )
    _report_stall("warm-sharded", stall, stall_limit * 8, stats_out)
    p4t = _greedy_cleanup(cand_provider, cand_cost, owner, p4t)
    res = AssignResult(p4t, _invert(p4t, num_providers))
    if with_state:
        return res, price, retired & (p4t < 0)
    return res, price


def candidates_topk_bidir_sharded(
    ep,
    er,
    weights=None,
    *,
    mesh: Mesh,
    k: int = 64,
    tile: int = 1024,
    reverse_r: int = 8,
    extra: int = 16,
    axis: str = "p",
    approx_recall: float | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Task-sharded bidirectional candidate generation — the mesh twin of
    ops.sparse.candidates_topk_bidir, and the stage where multi-chip
    actually PAYS: generation is the measured wall-clock dominator of a
    cold solve (793 s gen vs 32 s solve at 65k CPU, SCALING.md) and it is
    embarrassingly parallel over task tiles. Each device streams its own
    [P, tile] cost blocks (providers replicated: P x ~14 f32 columns,
    megabytes at 1M) with ZERO per-round collectives; the only
    communication in the whole pass is one all_gather of the [T, k]
    forward lists and the [D, P, r] reverse pools at the end — so v5e-8
    speedup on this stage is ~linear in D, unlike the solve kernel whose
    every round all-reduces the [P] price/owner vectors (see the ICI cost
    model in SCALING.md).

    Parity: the forward tile step is ops.sparse._forward_tile_select
    (shared verbatim — jitter offsets arranged so each shard computes the
    exact global tile it would own single-device), and the reverse pools
    keep the tile-pooled contract (per-tile top-ceil(r/n_tiles_GLOBAL),
    best r of the pool). Pool merging is associative up to float ties,
    which the tie jitter already decorrelates — asserted bit-exact in
    tests/test_parallel_sparse.py.
    """
    from protocol_tpu.ops.cost import INFEASIBLE, CostWeights
    from protocol_tpu.ops.sparse import (
        _forward_tile_select,
        merge_reverse_candidates,
    )

    if weights is None:
        weights = CostWeights()
    T = er.cpu_cores.shape[0]
    D = mesh.shape[axis]
    if T % D != 0:
        raise ValueError(f"T={T} not divisible by mesh size {D}; pad first")
    Tl = T // D
    if Tl % tile != 0:
        raise ValueError(
            f"local task count {Tl} not divisible by tile={tile}"
        )
    n_tiles_global = T // tile
    Pn = int(ep.gpu_count.shape[0])
    k = min(k, Pn)
    r = min(reverse_r, T)
    rt = max(1, -(-r // n_tiles_global))  # per-tile pool contribution

    er_sharded = jax.tree.map(
        lambda a: jax.device_put(a, NamedSharding(mesh, P(axis))), er
    )
    gen = _build_sharded_gen(
        mesh, axis, dataclasses.astuple(weights), Pn, Tl, k, tile, r, rt,
        approx_recall, jax.tree.structure(er),
    )
    cand_p, cand_c, rev_c_all, rev_t_all = gen(ep, er_sharded)
    # final pool merge: best r of the D per-shard pools (associativity up
    # to jitter-decorrelated ties; same multiset as the sequential fold)
    rev_c_cat = jnp.moveaxis(rev_c_all, 0, 1).reshape(Pn, D * r)
    rev_t_cat = jnp.moveaxis(rev_t_all, 0, 1).reshape(Pn, D * r)
    neg_c, m = lax.top_k(-rev_c_cat, r)
    rev_c = -neg_c
    rev_t = jnp.take_along_axis(rev_t_cat, m, axis=1)
    rev_t = jnp.where(rev_c < INFEASIBLE * 0.5, rev_t, -1)
    return merge_reverse_candidates(cand_p, cand_c, rev_t, rev_c, extra=extra)


@lru_cache(maxsize=32)
def _build_sharded_gen(
    mesh: Mesh,
    axis: str,
    weights_tuple: tuple,
    Pn: int,
    Tl: int,
    k: int,
    tile: int,
    r: int,
    rt: int,
    approx_recall,
    er_treedef,
):
    """Cached builder for the sharded generation executable (same
    re-trace rationale as _build_sharded_phase: a fresh jit+shard_map
    closure per call would recompile the whole scan each rebuild)."""
    from protocol_tpu.ops.cost import INFEASIBLE, CostWeights
    from protocol_tpu.ops.sparse import _forward_tile_select

    weights = CostWeights(*weights_tuple)
    D = mesh.shape[axis]
    er_specs = jax.tree.unflatten(
        er_treedef, [P(axis)] * er_treedef.num_leaves
    )

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), er_specs),
        out_specs=(P(axis, None), P(axis, None), P(axis, None, None),
                   P(axis, None, None)),
        check_vma=False,
    )
    def gen(ep_rep, er_local):
        shard = lax.axis_index(axis)
        offset = (shard * Tl).astype(jnp.uint32)

        def step(carry, t0):
            rev_c0, rev_t0 = carry
            # shared forward step: jitter keyed on the GLOBAL task index
            # via task_offset, so each shard produces exactly the columns
            # the single-device scan would at its global tile
            provider, cost_k, cost = _forward_tile_select(
                ep_rep, er_local, weights, t0, tile, k,
                None, offset, approx_recall,
            )
            tid = offset.astype(jnp.int32) + t0 + jnp.arange(tile, dtype=jnp.int32)
            if rt == 1:
                j = jnp.argmin(cost, axis=1)
                tile_c = jnp.take_along_axis(cost, j[:, None], axis=1)
                tile_t = tid[j][:, None]
            else:
                neg, j = lax.top_k(-cost, rt)
                tile_c = -neg
                tile_t = tid[j]
            merged_c = jnp.concatenate([rev_c0, tile_c], axis=1)
            merged_t = jnp.concatenate([rev_t0, tile_t], axis=1)
            neg_c, m = lax.top_k(-merged_c, r)
            return (-neg_c, jnp.take_along_axis(merged_t, m, axis=1)), (
                provider, cost_k,
            )

        carry0 = (
            jnp.full((Pn, r), jnp.float32(INFEASIBLE)),
            jnp.full((Pn, r), -1, jnp.int32),
        )
        (rev_c_l, rev_t_l), (cand_p, cand_c) = lax.scan(
            step, carry0, jnp.arange(Tl // tile, dtype=jnp.int32) * tile
        )
        return (
            cand_p.reshape(Tl, k),
            cand_c.reshape(Tl, k),
            rev_c_l[None],  # [1, P, r] -> stacked [D, P, r] across shards
            rev_t_l[None],
        )

    return gen
