"""jax version compatibility for the mesh kernels.

``shard_map`` was promoted out of ``jax.experimental`` (and its
replication-check kwarg renamed ``check_rep`` -> ``check_vma``) around
jax 0.6; this repo targets the promoted API. One shim, imported by every
``parallel/`` module, keeps older runtimes working.
"""

import jax

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # jax < 0.6 keeps shard_map at its pre-promotion home
    from jax.experimental.shard_map import shard_map as _shard_map_compat

    def shard_map(*args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map_compat(*args, **kwargs)
