"""Provider-sharded Bertsekas auction via shard_map.

Layout (BASELINE.json ladder config #4):
  - cost rows (providers) sharded over the 1-D ``p`` mesh axis; each device
    owns [P/D, T] of the value tensor — the only O(P*T) object.
  - per-provider state (price, owner) lives shard-local [P/D].
  - per-task state (assignment) is replicated [T] and updated identically on
    every device from all_gather'd per-shard candidates, so no scatter of
    task state ever crosses shards.

Per iteration the ICI traffic is 4 arrays of [D, T] (per-shard best value,
runner-up value, best provider id, best provider's price) + one [T] i32
max-combine for assignment deltas — independent of P.

Deterministic tie-breaking everywhere: argmax returns the first maximum, and
global provider ids are formed as shard_offset + local index, so lower
provider ids win ties exactly as in the dense kernel
(protocol_tpu.ops.assign.assign_auction), which is its parity oracle.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from protocol_tpu.parallel._compat import shard_map

from protocol_tpu.ops.assign import AssignResult, _invert
from protocol_tpu.ops.cost import INFEASIBLE

_NEG = -1e18


@lru_cache(maxsize=64)
def _build_sharded_dense_auction(
    mesh: Mesh, axis: str, eps: float, max_iters: int
):
    # Cached per static config: a closure rebuilt per call would re-trace
    # and re-compile the while_loop on every solve (see parallel/sparse.py).
    D = mesh.shape[axis]

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis, None),),
        out_specs=P(),
        check_vma=False,
    )
    def run(cost_local: jax.Array) -> jax.Array:
        Pl, T = cost_local.shape
        shard = lax.axis_index(axis)
        offset = (shard * Pl).astype(jnp.int32)

        value_base = jnp.where(cost_local < INFEASIBLE * 0.5, -cost_local, _NEG).T  # [T, Pl]
        feas_local = jnp.any(value_base > _NEG * 0.5, axis=1)
        task_feasible = lax.psum(feas_local.astype(jnp.int32), axis) > 0  # [T]

        def cond(state):
            it, price, owner, p4t = state
            return (it < max_iters) & jnp.any((p4t < 0) & task_feasible)

        def body(state):
            it, price, owner, p4t = state
            unassigned = (p4t < 0) & task_feasible  # [T] replicated

            # ---- local top-2 per task over this shard's providers
            value = value_base - price[None, :]  # [T, Pl]
            p1l = jnp.argmax(value, axis=1).astype(jnp.int32)
            v1l = jnp.take_along_axis(value, p1l[:, None], axis=1)[:, 0]
            v2l = jnp.max(value.at[jnp.arange(T), p1l].set(_NEG), axis=1)
            price1l = price[p1l]
            p1g = jnp.where(v1l > _NEG * 0.5, offset + p1l, jnp.int32(-1))

            # ---- global top-2 combine (all_gather over the mesh axis)
            av1 = lax.all_gather(v1l, axis)  # [D, T]
            av2 = lax.all_gather(v2l, axis)
            ap1 = lax.all_gather(p1g, axis)
            apr = lax.all_gather(price1l, axis)

            # best shard: max value, ties -> lowest global provider id.
            # av1 ties across shards mean equal value; prefer lower shard
            # (== lower provider id range): argmax picks first max.
            best_shard = jnp.argmax(av1, axis=0).astype(jnp.int32)  # [T]
            gv1 = jnp.take_along_axis(av1, best_shard[None, :], axis=0)[0]
            gp1 = jnp.take_along_axis(ap1, best_shard[None, :], axis=0)[0]
            gprice1 = jnp.take_along_axis(apr, best_shard[None, :], axis=0)[0]
            # runner-up: max of (other shards' v1, best shard's v2)
            av1_masked = jnp.where(
                jnp.arange(D)[:, None] == best_shard[None, :], _NEG, av1
            )
            gv2 = jnp.maximum(jnp.max(av1_masked, axis=0), jnp.max(av2, axis=0))
            gv2 = jnp.maximum(gv2, jnp.float32(-1e8))  # single-option floor

            bid_amt = gprice1 + (gv1 - gv2) + eps  # [T]
            bidding = unassigned & (gv1 > _NEG * 0.5)

            # ---- provider-side winner resolution, local providers only
            local_target = bidding & (gp1 >= offset) & (gp1 < offset + Pl)
            tgt = jnp.where(local_target, gp1 - offset, Pl)  # [T], Pl = drop
            bids = jnp.full((T, Pl), _NEG)
            bids = bids.at[jnp.arange(T), tgt].set(
                jnp.where(local_target, bid_amt, _NEG), mode="drop"
            )
            win_bid = jnp.max(bids, axis=0)  # [Pl]
            win_task = jnp.argmax(bids, axis=0).astype(jnp.int32)  # ties: low t
            got_bid = win_bid > _NEG * 0.5

            # ---- local state updates
            evict_t = jnp.where(got_bid & (owner >= 0), owner, T)
            new_owner = jnp.where(got_bid, win_task, owner)
            new_price = jnp.where(got_bid, win_bid, price)

            # ---- replicated assignment update via max-combine:
            # encode "no change" as -2; eviction (-1) and win (p>=0) beat it.
            delta = jnp.full(T, -2, jnp.int32)
            delta = delta.at[evict_t].set(-1, mode="drop")
            pidx = offset + jnp.arange(Pl, dtype=jnp.int32)
            win_t_safe = jnp.where(got_bid, win_task, T)
            delta = delta.at[win_t_safe].set(
                jnp.where(got_bid, pidx, -2), mode="drop"
            )
            gdelta = lax.pmax(delta, axis)
            p4t = jnp.where(gdelta > -2, gdelta, p4t)
            return it + 1, new_price, new_owner, p4t

        state0 = (
            jnp.int32(0),
            jnp.zeros(Pl, jnp.float32),
            jnp.full(Pl, -1, jnp.int32),
            jnp.full(T, -1, jnp.int32),
        )
        _, _, _, p4t = lax.while_loop(cond, body, state0)
        return p4t

    return run


def assign_auction_sharded(
    cost: jax.Array,
    mesh: Mesh,
    eps: float = 0.01,
    max_iters: int = 500,
    axis: str = "p",
) -> AssignResult:
    """Auction with cost rows sharded over ``mesh`` axis ``axis``.

    ``cost`` is [P, T] with P divisible by the mesh size. Returns a fully
    replicated AssignResult identical (same ties) to the dense kernel.
    """
    Ptot, T = cost.shape
    D = mesh.shape[axis]
    if Ptot % D != 0:
        raise ValueError(f"P={Ptot} not divisible by mesh size {D}; pad first")

    cost = jax.device_put(cost, NamedSharding(mesh, P(axis, None)))
    run = _build_sharded_dense_auction(mesh, axis, float(eps), int(max_iters))
    p4t = run(cost)
    return AssignResult(p4t, _invert(p4t, Ptot))
