"""Wire protocol v2 helpers: zero-copy tensor frames for the scheduler seam.

v1 ships every column as a repeated-scalar proto field — O(P) Python
object churn per column on BOTH sides of the seam (list round-trip in,
per-element type checks out). v2 ships each column as a ``TensorBlob``:
``ndarray.tobytes()`` on the producer, ``np.frombuffer`` on the consumer —
the per-column cost is two memcpys regardless of row count, and the
(de)serialization cost of a 1M-row marketplace drops from seconds of
Python-loop work to milliseconds of buffer copies.

Layout contract: blobs are C-order **little-endian** (x86/ARM native; the
dtype string is the numpy name, never a byte-order-prefixed spec), and
each Encoded* column rides under its dataclass field name with a fixed
canonical dtype (`P_WIRE_DTYPES` / `R_WIRE_DTYPES`). Dtypes are asserted
once at decode — the seam is the trust boundary, kernels never re-check.

Session epochs: a snapshot's identity is ``epoch_fingerprint`` — sha1
over every column's bytes plus the solve parameters. The server pins
per-``(session_id, fingerprint)`` warm state; a delta tick against an
unknown or mismatched session is refused (``session_ok=false``) and the
client falls back down the ladder (fresh snapshot -> stateless v1).
"""

from __future__ import annotations

import dataclasses
import gzip as _gzip
import hashlib
from typing import Iterable, Iterator, Optional

import numpy as np

from protocol_tpu.proto import scheduler_pb2 as pb

# canonical wire dtype per encoded column (mirrors the Encoded* dataclass
# dtypes in ops/encoding.py; bool stays 1-byte numpy bool_)
P_WIRE_DTYPES: dict[str, np.dtype] = {
    "gpu_count": np.dtype(np.int32),
    "gpu_mem_mb": np.dtype(np.int32),
    "gpu_model_id": np.dtype(np.int32),
    "has_gpu": np.dtype(np.bool_),
    "has_cpu": np.dtype(np.bool_),
    "cpu_cores": np.dtype(np.int32),
    "ram_mb": np.dtype(np.int32),
    "storage_gb": np.dtype(np.int32),
    "lat": np.dtype(np.float32),
    "lon": np.dtype(np.float32),
    "has_location": np.dtype(np.bool_),
    "price": np.dtype(np.float32),
    "load": np.dtype(np.float32),
    "valid": np.dtype(np.bool_),
}
R_WIRE_DTYPES: dict[str, np.dtype] = {
    "cpu_required": np.dtype(np.bool_),
    "cpu_cores": np.dtype(np.int32),
    "ram_mb": np.dtype(np.int32),
    "storage_gb": np.dtype(np.int32),
    "gpu_opt_valid": np.dtype(np.bool_),
    "gpu_count": np.dtype(np.int32),
    "gpu_mem_min": np.dtype(np.int32),
    "gpu_mem_max": np.dtype(np.int32),
    "gpu_total_mem_min": np.dtype(np.int32),
    "gpu_total_mem_max": np.dtype(np.int32),
    "gpu_model_mask": np.dtype(np.uint32),
    "gpu_model_constrained": np.dtype(np.bool_),
    "lat": np.dtype(np.float32),
    "lon": np.dtype(np.float32),
    "has_location": np.dtype(np.bool_),
    "priority": np.dtype(np.float32),
    "valid": np.dtype(np.bool_),
}


def blob(arr: np.ndarray, dtype: Optional[np.dtype] = None) -> pb.TensorBlob:
    """Pack an ndarray into a TensorBlob (one cast if needed, one memcpy)."""
    a = np.asarray(arr)
    if dtype is not None:
        a = np.ascontiguousarray(a, dtype)
    else:
        a = np.ascontiguousarray(a)
    return pb.TensorBlob(
        data=a.tobytes(), dtype=a.dtype.name, shape=list(a.shape)
    )


def unblob(msg: pb.TensorBlob, expect: Optional[np.dtype] = None) -> np.ndarray:
    """Zero-copy view over the blob bytes. The seam's single dtype check:
    a blob whose dtype disagrees with the declared column dtype is a
    protocol violation, not something to coerce quietly."""
    try:
        dt = np.dtype(msg.dtype)
    except TypeError:
        # np.dtype raises TypeError for garbage strings — normalize to
        # the seam's protocol-violation exception so the servicer's
        # except ValueError handlers answer INVALID_ARGUMENT, not UNKNOWN
        raise ValueError(f"tensor frame has invalid dtype {msg.dtype!r}")
    if expect is not None and dt != np.dtype(expect):
        raise ValueError(
            f"tensor frame dtype mismatch: got {dt.name}, want "
            f"{np.dtype(expect).name}"
        )
    shape = tuple(msg.shape)
    n = int(np.prod(shape)) if shape else 0
    if len(msg.data) != n * dt.itemsize:
        raise ValueError(
            f"tensor frame size mismatch: {len(msg.data)} bytes for shape "
            f"{shape} dtype {dt.name}"
        )
    return np.frombuffer(msg.data, dtype=dt).reshape(shape)


def _encode_columns(enc, spec: dict[str, np.dtype], out) -> None:
    for name, dt in spec.items():
        nt = out.columns.add()
        nt.name = name
        nt.tensor.CopyFrom(blob(getattr(enc, name), dt))


def encode_providers_v2(ep) -> pb.ProviderBatchV2:
    m = pb.ProviderBatchV2()
    _encode_columns(ep, P_WIRE_DTYPES, m)
    return m


def encode_requirements_v2(er) -> pb.RequirementBatchV2:
    m = pb.RequirementBatchV2()
    _encode_columns(er, R_WIRE_DTYPES, m)
    return m


def _decode_columns(msg, spec: dict[str, np.dtype]) -> dict[str, np.ndarray]:
    cols = {nt.name: nt.tensor for nt in msg.columns}
    missing = set(spec) - set(cols)
    if missing:
        raise ValueError(f"tensor batch missing columns: {sorted(missing)}")
    out = {name: unblob(cols[name], dt) for name, dt in spec.items()}
    # ---- input hardening at the wire (chaos-plane satellite): a frame
    # that decodes at the right dtypes can still be poison — ragged
    # row counts index out of sibling columns, and a NaN/Inf cost
    # propagates through the cost tensor into carried session state
    # where no later tick can flush it. Reject HERE, before anything
    # lands in an arena; the servicer answers INVALID_ARGUMENT (every
    # decode call site already wraps ValueError).
    n_rows = None
    for name, a in out.items():
        if a.ndim == 0:
            raise ValueError(f"column {name!r} is not row-shaped")
        if n_rows is None:
            n_rows = a.shape[0]
        elif a.shape[0] != n_rows:
            raise ValueError(
                f"column row-count mismatch: {name!r} has {a.shape[0]} "
                f"rows, expected {n_rows}"
            )
        if a.dtype.kind == "f" and a.size and not np.isfinite(a).all():
            raise ValueError(
                f"non-finite values in column {name!r} (NaN/Inf costs "
                "are refused before they can poison a session arena)"
            )
    return out


def decode_providers_v2(msg: pb.ProviderBatchV2):
    from protocol_tpu.ops.encoding import EncodedProviders

    return EncodedProviders(**_decode_columns(msg, P_WIRE_DTYPES))


def decode_requirements_v2(msg: pb.RequirementBatchV2):
    from protocol_tpu.ops.encoding import EncodedRequirements

    return EncodedRequirements(**_decode_columns(msg, R_WIRE_DTYPES))


# ---------------- session epochs ----------------


def canon_columns(enc, spec: dict[str, np.dtype]) -> dict[str, np.ndarray]:
    """Canonical contiguous numpy columns for diffing / fingerprinting."""
    return {
        name: np.ascontiguousarray(np.asarray(getattr(enc, name)), dt)
        for name, dt in spec.items()
    }


def epoch_fingerprint(
    p_cols: dict[str, np.ndarray],
    r_cols: dict[str, np.ndarray],
    weights,
    kernel: str,
    top_k: int,
    eps: float,
    max_iters: int,
) -> str:
    """Identity of a session epoch: the full snapshot content + every solve
    parameter. Anything that would change the solve changes the hex.

    ``top_k`` is normalized exactly as the server's kernel dispatch
    normalizes it (0/absent means "server default 64"), so a client
    sending top_k=0 and the server hashing the effective value agree."""
    top_k = max(int(top_k) or 64, 1)
    h = hashlib.sha1()
    for spec, cols in ((P_WIRE_DTYPES, p_cols), (R_WIRE_DTYPES, r_cols)):
        for name in spec:
            a = cols[name]
            h.update(name.encode())
            h.update(str(a.shape).encode())
            h.update(a.tobytes())
    # solve parameters are hashed at WIRE precision (f32 proto fields):
    # the server recomputes the fingerprint from the decoded request, so a
    # client hashing float64 0.02 against a round-tripped f32 0.0199999...
    # would never match its own epoch
    params = np.array(
        [weights.price, weights.load, weights.proximity, weights.priority,
         eps],
        np.float32,
    )
    h.update(params.tobytes())
    h.update(f"{kernel}:{int(top_k)}:{int(max_iters)}".encode())
    return h.hexdigest()


def dirty_rows(
    new: dict[str, np.ndarray], old: dict[str, np.ndarray]
) -> np.ndarray:
    """Row indices whose value changed in ANY column (trailing axes
    collapsed) — the client-side churn detector for AssignDelta ticks."""
    names = list(new)
    n = new[names[0]].shape[0]
    dirty = np.zeros(n, bool)
    for name in names:
        diff = new[name] != old[name]
        dirty |= diff.reshape(n, -1).any(axis=1)
    return np.flatnonzero(dirty).astype(np.int32)


def take_rows(cols: dict[str, np.ndarray], rows: np.ndarray) -> object:
    """Duck-typed Encoded* view holding only the given rows (for packing a
    delta batch through encode_*_v2)."""
    ns = type("_Rows", (), {})()
    for name, arr in cols.items():
        setattr(ns, name, arr[rows])
    return ns


# ---------------- streaming snapshots ----------------

DEFAULT_CHUNK_BYTES = 1 << 20


def chunk_snapshot(
    session_id: str,
    fingerprint: str,
    request: pb.AssignRequestV2,
    chunk_bytes: int = DEFAULT_CHUNK_BYTES,
    use_gzip: bool = True,
) -> Iterator[pb.SnapshotChunk]:
    """Serialize a full-snapshot request into bounded SnapshotChunk frames
    (first frame carries the header) — 1M-row marketplaces stream through
    a default-sized gRPC window instead of needing one giant unary
    message. gzip pays on the snapshot (cold path, highly compressible
    columnar ints) and is skipped per-tick where latency rules."""
    payload = request.SerializeToString()
    codec = ""
    if use_gzip:
        gz = _gzip.compress(payload, compresslevel=1)
        if len(gz) < len(payload):
            payload, codec = gz, "gzip"
    total = len(payload)
    first = True
    for off in range(0, max(total, 1), chunk_bytes):
        part = payload[off:off + chunk_bytes]
        if first:
            yield pb.SnapshotChunk(
                session_id=session_id,
                epoch_fingerprint=fingerprint,
                payload=part,
                codec=codec,
                total_bytes=total,
            )
            first = False
        else:
            yield pb.SnapshotChunk(payload=part)


# streamed snapshots must not become an uncapped ingress: the unary paths
# are bounded by the channel's 1 GiB message cap, so the reassembled (and
# decompressed) snapshot gets the same bound
MAX_SNAPSHOT_BYTES = 1 << 30


def assemble_snapshot(
    chunks: Iterable[pb.SnapshotChunk],
    max_bytes: int = MAX_SNAPSHOT_BYTES,
) -> tuple[str, str, pb.AssignRequestV2, int]:
    """Server-side inverse of chunk_snapshot. Returns
    (session_id, claimed fingerprint, parsed request, wire bytes
    received). Enforces ``max_bytes`` on BOTH the accumulated stream and
    the decompressed payload (a small gzip bomb must not OOM the
    backend)."""
    session_id = fingerprint = codec = None
    total = 0
    received = 0
    parts: list[bytes] = []
    for ch in chunks:
        if session_id is None:
            session_id = ch.session_id
            fingerprint = ch.epoch_fingerprint
            codec = ch.codec
            total = int(ch.total_bytes)
            if total > max_bytes:
                raise ValueError(
                    f"snapshot stream declares {total} bytes "
                    f"(cap {max_bytes})"
                )
        received += len(ch.payload)
        if received > max_bytes:
            raise ValueError(
                f"snapshot stream exceeds {max_bytes} bytes"
            )
        parts.append(ch.payload)
    if session_id is None:
        raise ValueError("empty snapshot stream")
    payload = b"".join(parts)
    if total and len(payload) != total:
        raise ValueError(
            f"snapshot stream truncated: {len(payload)}/{total} bytes"
        )
    if codec == "gzip":
        import zlib

        d = zlib.decompressobj(16 + zlib.MAX_WBITS)
        out = d.decompress(payload, max_bytes + 1)
        if len(out) > max_bytes:
            raise ValueError(
                f"decompressed snapshot exceeds {max_bytes} bytes"
            )
        payload = out + d.flush()
    elif codec:
        raise ValueError(f"unknown snapshot codec {codec!r}")
    req = pb.AssignRequestV2()
    req.ParseFromString(payload)
    return session_id, fingerprint, req, received


def strip_padding(enc):
    """Drop pow2-padding rows (valid=False tail) before the wire: padded
    rows would be real entities to the backend and dead weight on the
    wire. Shared by the v1 and v2 client paths."""
    n = int(np.asarray(enc.valid).sum())
    return dataclasses.replace(
        enc,
        **{
            f.name: np.asarray(getattr(enc, f.name))[:n]
            for f in dataclasses.fields(enc)
        },
    )
