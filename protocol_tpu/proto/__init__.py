"""Generated protobuf messages for the scheduler gRPC shim.

Regenerate with:  protoc --python_out=. protocol_tpu/proto/scheduler.proto
(run from the repo root). The gRPC service wiring is hand-rolled in
protocol_tpu.services.scheduler_grpc via generic method handlers, so no
grpc protoc plugin is required.
"""
