"""Deterministic fixed-point float wire format for challenge payloads.

Reference counterpart: crates/p2p/src/message/hardware_challenge.rs:8-54 —
``FixedF64``, an i64 wrapper ensuring both sides of the challenge wire
hold BIT-IDENTICAL inputs regardless of the peer's float formatter/parser
(a JSON round-trip through a different language's repr can perturb the
last ulp, and a challenge that hashes or compares inputs must not depend
on that).

**Wire format: a deliberate DEVIATION from the reference.** The
reference serializes each FixedF64 as a 12-decimal string (``"{:.12}"``)
inside a ``data_a``/``rows_a``/``cols_a`` schema; this codec ships
Q31.32 integers (``encode(x) = round(x * 2^32)`` as a Python int —
arbitrary precision, no i64 overflow concerns on this side; ``decode``
the exact inverse onto float64) under ``matrix_*_fixed`` keys. The
determinism PROPERTY is equivalent — both wires quantize to a fixed
grid so decode is formatter-independent — but a reference-format peer
would not parse this wire (and vice versa); cross-implementation
challenge interop would need a transcoder. See PARITY.md.

Challenge matrices travel encoded; each side decodes to the same
float64s, so the only remaining divergence between validator and worker
is the device matmul itself — which is compared under an explicit
tolerance because the two sides legitimately run on DIFFERENT hardware
(TPU accumulation order vs host BLAS; the reference compares exactly
only because both of its sides run the same nalgebra CPU kernel).
"""

from __future__ import annotations

import numpy as np

SCALE_BITS = 32
_SCALE = float(1 << SCALE_BITS)


def encode_array(x) -> list:
    """float array (any nesting) -> same-shape nested lists of ints.

    Raises ValueError on non-finite values: inf/nan have no fixed-point
    representation, and int(inf)/int(nan) would otherwise surface as an
    unrelated OverflowError deep in a wire handler."""
    arr = np.asarray(x, np.float64)
    if not np.isfinite(arr).all():
        raise ValueError("non-finite value cannot be FixedF64-encoded")
    q = np.rint(arr * _SCALE)
    # arbitrary-precision ints via Python objects: values beyond i64 are
    # legal on this wire (challenge entries are ~N(0,1), so in practice
    # they are tiny, but the codec must not silently wrap)
    return np.vectorize(int, otypes=[object])(q).tolist()


def decode_array(x) -> np.ndarray:
    """nested lists of ints -> float64 ndarray (exact inverse of encode
    up to the quantization done at encode time).

    Wire input is untrusted: ragged shapes, strings, or ints beyond
    float64 range all raise ValueError (never OverflowError/TypeError),
    so handlers need exactly one except clause."""
    try:
        return np.asarray(x, np.float64) / _SCALE
    except (OverflowError, TypeError, ValueError) as e:
        raise ValueError(f"malformed FixedF64 payload: {e}") from e


def roundtrip(x) -> np.ndarray:
    """The values a peer will see after one wire crossing."""
    return decode_array(encode_array(x))
