"""Shared utilities: storage providers, prometheus text rendering."""

from protocol_tpu.utils.storage import (
    LocalDirStorageProvider,
    MockStorageProvider,
    StorageProvider,
)

__all__ = ["LocalDirStorageProvider", "MockStorageProvider", "StorageProvider"]
