"""TLS for the wire: confidentiality to match the reference's transport.

The reference's control RPCs ride libp2p TCP+Noise+Yamux
(crates/p2p/src/lib.rs:324-335) — encrypted AND mutually authenticated.
This framework's redesign keeps mutual authentication through wallet
signatures on every request (security/signer.py), but round 2 left every
plane plaintext HTTP: integrity without confidentiality. This module adds
the missing half — standard TLS on every aiohttp server and keep-alive
client, driven by cert/key paths in serve.py args and chart values.

  server_ssl_context(cert, key)   for aiohttp TCPSite / kv-api
  client_ssl_context(ca)          verify servers against a deployment CA
                                  (PROTOCOL_TPU_TLS_CA env, or system trust)
  generate_self_signed(dir)       dev/test PKI: a CA plus a localhost server
                                  cert signed by it (the devnet's Noise-less
                                  equivalent of libp2p's generated keypair)
"""

from __future__ import annotations

import datetime
import ipaddress
import os
import ssl
from typing import Optional


def server_ssl_context(cert_path: str, key_path: str) -> ssl.SSLContext:
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert_path, key_path)
    return ctx


def client_ssl_context(ca_path: Optional[str] = None) -> ssl.SSLContext:
    """Verifying client context. ``ca_path`` PINS a deployment CA — it
    REPLACES system trust, so only certs chaining to the operator's CA are
    accepted on the control plane (any public CA being able to mint an
    accepted cert would defeat pinning). None uses system trust. Public
    endpoints (GCS/S3 signed URLs, geolocation) must use a SEPARATE
    system-trust session — see public_client_session()."""
    if ca_path:
        return ssl.create_default_context(cafile=ca_path)
    return ssl.create_default_context()


def env_client_ssl_context() -> Optional[ssl.SSLContext]:
    """The ambient client context: PROTOCOL_TPU_TLS_CA names the CA file.
    Returns None when unset (plaintext deployments stay plaintext)."""
    ca = os.environ.get("PROTOCOL_TPU_TLS_CA", "")
    return client_ssl_context(ca) if ca else None


def env_client_session():
    """aiohttp session for INTERNAL peers (discovery/orchestrator/worker/
    validator/ledger/kv): verifies against the pinned deployment CA when
    PROTOCOL_TPU_TLS_CA is set. The single construction point for the
    control plane's client transport (serve.py services and the operator
    CLI both use it)."""
    import aiohttp

    ctx = env_client_ssl_context()
    if ctx is None:
        return aiohttp.ClientSession()
    return aiohttp.ClientSession(connector=aiohttp.TCPConnector(ssl=ctx))


def public_client_session():
    """aiohttp session for PUBLIC endpoints (GCS/S3 signed URLs,
    geolocation): always system trust, never the pinned deployment CA —
    pinning would break public hosts, and mixing the two trust roots in
    one context would let any public CA reach the control plane."""
    import aiohttp

    return aiohttp.ClientSession()


def generate_self_signed(
    out_dir: str,
    hostnames: Optional[list[str]] = None,
) -> dict:
    """Dev/test PKI: writes ca.pem, server.pem, server.key under out_dir
    and returns their paths. The server cert covers localhost + any extra
    hostnames/IPs."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    os.makedirs(out_dir, exist_ok=True)
    now = datetime.datetime.now(datetime.timezone.utc)

    def _name(cn: str) -> x509.Name:
        return x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, cn)])

    ca_key = ec.generate_private_key(ec.SECP256R1())
    ca_cert = (
        x509.CertificateBuilder()
        .subject_name(_name("protocol-tpu dev CA"))
        .issuer_name(_name("protocol-tpu dev CA"))
        .public_key(ca_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=365))
        .add_extension(x509.BasicConstraints(ca=True, path_length=0), critical=True)
        .sign(ca_key, hashes.SHA256())
    )

    srv_key = ec.generate_private_key(ec.SECP256R1())
    sans: list[x509.GeneralName] = [
        x509.DNSName("localhost"),
        x509.IPAddress(ipaddress.ip_address("127.0.0.1")),
    ]
    for h in hostnames or []:
        try:
            sans.append(x509.IPAddress(ipaddress.ip_address(h)))
        except ValueError:
            sans.append(x509.DNSName(h))
    srv_cert = (
        x509.CertificateBuilder()
        .subject_name(_name("localhost"))
        .issuer_name(ca_cert.subject)
        .public_key(srv_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=365))
        .add_extension(x509.SubjectAlternativeName(sans), critical=False)
        .sign(ca_key, hashes.SHA256())
    )

    paths = {
        "ca": os.path.join(out_dir, "ca.pem"),
        "cert": os.path.join(out_dir, "server.pem"),
        "key": os.path.join(out_dir, "server.key"),
    }
    with open(paths["ca"], "wb") as f:
        f.write(ca_cert.public_bytes(serialization.Encoding.PEM))
    with open(paths["cert"], "wb") as f:
        f.write(srv_cert.public_bytes(serialization.Encoding.PEM))
    fd = os.open(paths["key"], os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "wb") as f:
        f.write(
            srv_key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.PKCS8,
                serialization.NoEncryption(),
            )
        )
    return paths
