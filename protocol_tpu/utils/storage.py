"""Storage providers: artifact uploads via signed URLs.

Reference: crates/shared/src/utils/mod.rs — ``StorageProvider`` trait
{file_exists, generate_mapping_file, resolve_mapping_for_sha,
generate_upload_signed_url} (:9-28) with ``MockStorageProvider`` (:30-110)
and a GCS implementation (google_cloud.rs). Here: the same trait shape, the
in-memory mock for tests, and a local-directory provider for dev clusters
(upload "signed URLs" are file:// paths plus an HMAC token — the seam where
a real GCS/S3 backend would plug in).
"""

from __future__ import annotations

import hashlib
import hmac
import os
import time
from abc import ABC, abstractmethod
from typing import Optional


class StorageProvider(ABC):
    @abstractmethod
    async def file_exists(self, object_name: str) -> bool: ...

    @abstractmethod
    async def generate_upload_signed_url(
        self,
        object_name: str,
        content_type: Optional[str] = None,
        expires_in: float = 3600.0,
        max_bytes: Optional[int] = None,
    ) -> str: ...

    @abstractmethod
    async def generate_mapping_file(self, sha256: str, file_name: str) -> None:
        """Write ``mapping/{sha256}`` -> file name (used by the validator to
        resolve work keys to artifacts)."""

    @abstractmethod
    async def resolve_mapping_for_sha(self, sha256: str) -> Optional[str]: ...


class MockStorageProvider(StorageProvider):
    """In-memory provider (shared/src/utils/mod.rs:30-110)."""

    def __init__(self):
        self.files: dict[str, bytes] = {}
        self.mappings: dict[str, str] = {}
        self.issued_urls: list[str] = []

    async def file_exists(self, object_name: str) -> bool:
        return object_name in self.files

    async def generate_upload_signed_url(
        self, object_name, content_type=None, expires_in=3600.0, max_bytes=None
    ) -> str:
        url = f"mock://upload/{object_name}?expires={int(time.time() + expires_in)}"
        self.issued_urls.append(url)
        return url

    async def generate_mapping_file(self, sha256: str, file_name: str) -> None:
        self.mappings[sha256] = file_name
        self.files[f"mapping/{sha256}"] = file_name.encode()

    async def resolve_mapping_for_sha(self, sha256: str) -> Optional[str]:
        return self.mappings.get(sha256)

    # test helper: simulate the worker completing an upload
    async def put(self, object_name: str, data: bytes) -> None:
        self.files[object_name] = data


class LocalDirStorageProvider(StorageProvider):
    """Filesystem-backed provider for dev deployments; URLs carry an HMAC
    token so the upload endpoint can reject unsigned paths."""

    def __init__(
        self,
        root: str,
        secret: bytes = b"dev-secret",
        public_base_url: str = "",
    ):
        self.root = root
        self.secret = secret
        # when set, signed URLs are HTTP PUT endpoints (served by the
        # orchestrator's /storage/upload route) instead of file:// paths
        self.public_base_url = public_base_url.rstrip("/")
        os.makedirs(root, exist_ok=True)

    def _path(self, object_name: str) -> str:
        # object names are worker-controlled: normalize, strip any absolute
        # prefix, and refuse paths that escape the storage root
        safe = os.path.normpath(object_name).lstrip(os.sep)
        if safe.startswith(".."):
            raise ValueError(f"object name escapes storage root: {object_name!r}")
        full = os.path.join(self.root, safe)
        if os.path.commonpath([os.path.abspath(full), os.path.abspath(self.root)]) != os.path.abspath(self.root):
            raise ValueError(f"object name escapes storage root: {object_name!r}")
        return full

    def _token(self, object_name: str, expires: int, max_bytes: int) -> str:
        # max_bytes is part of the signed payload: the approved size is
        # enforceable at upload time (GCS content-length-range semantics)
        return hmac.new(
            self.secret,
            f"{object_name}|{expires}|{max_bytes}".encode(),
            hashlib.sha256,
        ).hexdigest()[:32]

    async def file_exists(self, object_name: str) -> bool:
        return os.path.exists(self._path(object_name))

    async def generate_upload_signed_url(
        self, object_name, content_type=None, expires_in=3600.0, max_bytes=None
    ) -> str:
        from urllib.parse import quote

        # reject escaping names at ISSUE time (the token would otherwise
        # validate while the write later fails)
        self._path(object_name)
        expires = int(time.time() + expires_in)
        size_cap = int(max_bytes) if max_bytes else 0
        token = self._token(object_name, expires, size_cap)
        if self.public_base_url:
            return (
                f"{self.public_base_url}/storage/upload/{quote(object_name, safe='/')}"
                f"?expires={expires}&max_bytes={size_cap}&token={token}"
            )
        return (
            f"file://{self._path(object_name)}"
            f"?expires={expires}&max_bytes={size_cap}&token={token}"
        )

    async def put(self, object_name: str, data: bytes) -> None:
        path = self._path(object_name)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "wb") as f:
            f.write(data)

    async def put_stream(self, object_name: str, chunk_iter, cap: int) -> int:
        """Stream chunks to disk; deletes the partial file and raises
        ValueError if the running total exceeds ``cap``. Returns bytes
        written."""
        path = self._path(object_name)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        total = 0
        tmp = path + ".part"
        try:
            with open(tmp, "wb") as f:
                async for chunk in chunk_iter:
                    total += len(chunk)
                    if total > cap:
                        raise ValueError("upload exceeds approved size")
                    f.write(chunk)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return total

    def verify_upload_url(
        self, object_name: str, expires: int, token: str, max_bytes: int = 0
    ) -> bool:
        if time.time() > expires:
            return False
        return hmac.compare_digest(
            self._token(object_name, expires, max_bytes), token
        )

    async def generate_mapping_file(self, sha256: str, file_name: str) -> None:
        path = self._path(f"mapping/{sha256}")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(file_name)

    async def resolve_mapping_for_sha(self, sha256: str) -> Optional[str]:
        path = self._path(f"mapping/{sha256}")
        if not os.path.exists(path):
            return None
        with open(path) as f:
            return f.read()
