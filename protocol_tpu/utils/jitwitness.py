"""Runtime jit-cache witness: the dynamic half of the staging analyzer
(``scripts/analysis/staging.py``).

The static retrace pass proves the *code* cannot recompile per tick
(non-array Python args are static, compile keys are padded/bucketed);
this module counts what XLA actually compiles, per entry, live. The
mechanism: ``jax.jit`` is wrapped so the function being staged gets one
extra Python frame that increments a per-entry counter — and that frame
only ever runs while JAX is TRACING. A cache-hit call dispatches the
compiled executable without touching Python, so the steady-state cost
of the witness is exactly zero; the armed/disarmed distinction
(``PROTOCOL_TPU_JIT_WITNESS=1``, like the lock witness) governs who
*reads* the counters (arena ``last_stats``, the perf gate's
zero-recompile assertion), not whether they exist.

The patch must land before any ``@jax.jit`` decorator executes, which
is why the jit-owning packages (``ops``, ``parallel``, the jax path in
``sched/tpu_backend.py``) import this module first thing. Call-form
jits (the lru_cached sharded builders) resolve ``jax.jit`` at call
time and are covered regardless of import order.

What a "compile" means here: one execution of the staged function's
Python body — i.e. one trace, which is one cache miss, which is one
XLA compilation (or AOT lowering). Counts aggregate by qualified name,
so a B-ladder of builder instances shows up as one entry whose count
is the ladder depth — and a warm tick at steady state shows up as a
zero delta, which is precisely the gate contract.
"""

from __future__ import annotations

import functools
import os
import threading

_counts: dict = {}
_counts_lock = threading.Lock()  # meta-lock, never witnessed
_installed = False


def enabled() -> bool:
    v = os.environ.get("PROTOCOL_TPU_JIT_WITNESS", "")
    return v not in ("", "0", "off", "false")


def _entry_name(fun) -> str:
    mod = getattr(fun, "__module__", None) or "?"
    qual = getattr(fun, "__qualname__", None) or repr(fun)
    return f"{mod}:{qual}"


def _bump(entry: str) -> None:
    with _counts_lock:
        _counts[entry] = _counts.get(entry, 0) + 1


def counts() -> dict:
    """Per-entry compile counts since process start (or ``reset()``)."""
    with _counts_lock:
        return dict(_counts)


def total() -> int:
    with _counts_lock:
        return sum(_counts.values())


def reset() -> None:
    with _counts_lock:
        _counts.clear()


def snapshot() -> dict:
    """Alias of :func:`counts` named for its role in delta bracketing:
    ``snap = snapshot(); ...work...; delta(snap)``."""
    return counts()


def delta(since: dict) -> dict:
    """Entries whose compile count grew past ``since`` (a
    :func:`snapshot`), mapped to how many NEW compilations each paid."""
    now = counts()
    return {
        k: v - since.get(k, 0)
        for k, v in now.items()
        if v > since.get(k, 0)
    }


def install() -> None:
    """Idempotently wrap ``jax.jit`` with the trace counter. Safe to
    call from every jit-owning module; the first caller wins."""
    global _installed
    if _installed:
        return
    with _counts_lock:
        if _installed:
            return
        _installed = True
    import jax

    orig_jit = jax.jit

    @functools.wraps(orig_jit)
    def counting_jit(fun=None, **kwargs):
        if fun is None:
            # factory form: jax.jit(static_argnames=...) -> decorator
            return lambda f: counting_jit(f, **kwargs)
        entry = _entry_name(fun)

        @functools.wraps(fun)
        def staged(*args, **kw):
            # this frame exists only during tracing — compiled-cache
            # hits never re-enter the Python body
            _bump(entry)
            return fun(*args, **kw)

        return orig_jit(staged, **kwargs)

    counting_jit._pt_jitwitness = True  # marker for tests / reentry
    jax.jit = counting_jit
