"""JAX platform forcing for hermetic CPU runs.

The ambient environment registers a remote-TPU PJRT plugin ("axon") via
sitecustomize and forces ``jax_platforms="axon,cpu"`` through
``jax.config.update`` at import, which takes precedence over the
``JAX_PLATFORMS`` env var. Any code that must run on the virtual host-CPU
mesh (tests, the driver's multi-chip dryrun) has to override the config
value *after* importing jax AND ensure the host device count is set before
the CPU backend first initializes. This module is the single home for that
dance.
"""

from __future__ import annotations

import os
import re

_FLAG = "--xla_force_host_platform_device_count"


def force_host_cpu(n_devices: int = 8) -> None:
    """Pin JAX to the host-CPU platform with >= ``n_devices`` devices.

    Must run before the CPU backend is first initialized (before any jax
    op runs on CPU in this process). Raises with a diagnosis if the
    requested device count cannot be satisfied.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(re.escape(_FLAG) + r"=(\d+)", flags)
    if m is None:
        os.environ["XLA_FLAGS"] = (flags + f" {_FLAG}={n_devices}").strip()
    elif int(m.group(1)) < n_devices:
        os.environ["XLA_FLAGS"] = flags.replace(m.group(0), f"{_FLAG}={n_devices}")

    import jax

    jax.config.update("jax_platforms", "cpu")
    got = len(jax.devices("cpu"))
    if got < n_devices:
        raise RuntimeError(
            f"need {n_devices} host devices, got {got}: the CPU backend was "
            "already initialized before force_host_cpu() — call it before "
            "any jax op in this process"
        )
