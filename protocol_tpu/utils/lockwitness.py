"""Runtime lock-order witness: the dynamic half of the lock-order
analyzer (``scripts/analysis/lockorder.py``).

The static analyzer proves the *code* acquires locks in spec order
(``scripts/analysis/lock_order.toml``); this module asserts the same
order *live*. Every lock-owning module creates its locks through
:func:`make_lock` with the lock's spec domain name. With
``PROTOCOL_TPU_LOCK_WITNESS`` unset (the default) that is a plain
``threading.Lock`` — zero overhead, nothing changes. With
``PROTOCOL_TPU_LOCK_WITNESS=1`` each lock is wrapped in a
:class:`WitnessedLock` that checks, at every acquisition, that the
acquiring thread holds no lock of equal or higher rank — the same
strict-ascending-rank rule the static pass enforces, now checked under
the real interleavings of the fleet race suite and the chaos drills.

Violations are RECORDED, not raised (``violations()`` returns them, the
race/chaos tests assert the list is empty): raising inside a lock
acquisition would turn an ordering bug into an unrelated crash halfway
through a drill, losing the evidence. ``PROTOCOL_TPU_LOCK_WITNESS=strict``
raises immediately instead — the bisection mode.

Rank rule: a thread may acquire a lock only while every lock it already
holds has a strictly LOWER rank. Equal rank is a violation too — that is
what "shard locks never nest" means mechanically. Reentrant domains
(``reentrant = true`` in the spec) may re-acquire a lock they already
hold (RLock semantics); acquiring a *different* instance of the same
domain still violates.

The domain/rank table is loaded from the committed spec so the static
and dynamic checks can never drift apart.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

_SPEC_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    ))),
    "scripts", "analysis", "lock_order.toml",
)

# loaded lazily on first witnessed-lock creation; None = not yet loaded
_RANKS: Optional[dict] = None
_REENTRANT: frozenset = frozenset()

_tls = threading.local()
_violations: list = []
_violations_lock = threading.Lock()  # meta-lock, never witnessed


class LockOrderViolation(RuntimeError):
    pass


def _load_ranks() -> dict:
    global _RANKS, _REENTRANT
    if _RANKS is not None:
        return _RANKS
    try:
        # load the spec module BY PATH: perf_gate/serve processes may
        # not have the repo root on sys.path, and the witness must not
        # depend on the ``scripts`` package being importable
        import importlib.util
        import sys

        loader_path = os.path.join(
            os.path.dirname(_SPEC_PATH), "spec.py"
        )
        mod_spec = importlib.util.spec_from_file_location(
            "_pt_lock_spec", loader_path
        )
        mod = importlib.util.module_from_spec(mod_spec)
        # dataclasses resolves string annotations through
        # sys.modules[cls.__module__]; a path-loaded module must be
        # registered or @dataclass itself raises on 3.10
        sys.modules[mod_spec.name] = mod
        mod_spec.loader.exec_module(mod)
        spec = mod.load_spec(_SPEC_PATH)
        _RANKS = dict(spec.ranks)
        _REENTRANT = frozenset(spec.reentrant)
    except Exception:
        # the witness must degrade to INERT, never crash the server: a
        # missing/unparsable spec means no ordering is asserted (the
        # static analyzer fails CI on the spec instead). An empty rank
        # table disables checking entirely — all-zero ranks would
        # otherwise read every nested acquisition as a violation.
        _RANKS = {}
        _REENTRANT = frozenset()
    return _RANKS


def enabled() -> bool:
    v = os.environ.get("PROTOCOL_TPU_LOCK_WITNESS", "")
    return v not in ("", "0", "off", "false")


def strict() -> bool:
    return os.environ.get("PROTOCOL_TPU_LOCK_WITNESS", "") == "strict"


def _held() -> list:
    st = getattr(_tls, "held", None)
    if st is None:
        st = _tls.held = []
    return st


def violations() -> list:
    with _violations_lock:
        return list(_violations)


def reset() -> None:
    with _violations_lock:
        _violations.clear()


def _record(entry: dict) -> None:
    with _violations_lock:
        if len(_violations) < 1024:  # bounded: a hot loop can't OOM us
            _violations.append(entry)
    if strict():
        raise LockOrderViolation(str(entry))


class WitnessedLock:
    """A ``threading.Lock`` twin that checks the rank order on acquire.

    Supports the full surface the codebase uses: ``with``, bare
    ``acquire()/release()`` (tests hold session locks across calls), and
    ``locked()``. The held-stack is thread-local; blocking on a
    contended lock is unchanged — the witness only looks at what THIS
    thread already holds at the acquisition attempt."""

    __slots__ = ("domain", "rank", "reentrant", "_lock")

    def __init__(self, domain: str, reentrant: Optional[bool] = None):
        ranks = _load_ranks()
        self.domain = domain
        self.rank = int(ranks.get(domain, 0))
        self.reentrant = (
            domain in _REENTRANT if reentrant is None else bool(reentrant)
        )
        self._lock = (
            threading.RLock() if self.reentrant else threading.Lock()
        )

    def _check(self) -> None:
        if not _RANKS:
            return  # inert: no spec, no ordering asserted
        held = _held()
        if not held:
            return
        if self.reentrant and any(e[2] is self for e in held):
            return  # RLock re-acquisition of the same instance
        top_rank = max(e[1] for e in held)
        if self.rank <= top_rank:
            _record({
                "acquiring": self.domain,
                "rank": self.rank,
                "held": [(e[0], e[1]) for e in held],
                "thread": threading.current_thread().name,
            })

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._check()
        got = self._lock.acquire(blocking, timeout)
        if got:
            _held().append((self.domain, self.rank, self))
        return got

    def release(self) -> None:
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][2] is self:
                del held[i]
                break
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()


def make_lock(domain: str):
    """Create the lock for ``domain`` (a ``[domains]`` key in
    ``lock_order.toml``). Plain ``threading.Lock`` unless the witness is
    armed — call sites pay one env read at *creation*, nothing per
    acquisition."""
    if enabled():
        return WitnessedLock(domain)
    return threading.Lock()


def make_rlock(domain: str):
    """Reentrant variant (``ledger``/``kv`` keep RLock semantics)."""
    if enabled():
        return WitnessedLock(domain, reentrant=True)
    return threading.RLock()


class LazyLock:
    """Module-level lock whose witness decision happens at FIRST USE,
    not import: module globals (``_claim_lock``, ``_PROFILE_LOCK``) are
    created when the module first imports — in a test session that is
    during collection, before any fixture arms the witness, so an
    import-time ``make_lock`` would silently pin them as plain Locks
    for the whole process. Costs one attribute check per acquisition on
    these two low-frequency locks."""

    __slots__ = ("domain", "_lock")

    def __init__(self, domain: str):
        self.domain = domain
        self._lock = None

    def _resolve(self):
        lock = self._lock
        if lock is None:
            # double-checked under the meta-lock: two racing creators
            # handing out DIFFERENT lock objects would break mutual
            # exclusion, the one property a lock must never lose
            with _violations_lock:
                if self._lock is None:
                    self._lock = make_lock(self.domain)
                lock = self._lock
        return lock

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        return self._resolve().acquire(blocking, timeout)

    def release(self) -> None:
        self._resolve().release()

    def locked(self) -> bool:
        return self._resolve().locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()
