"""Structured logging with optional Loki push.

Reference: crates/worker/src/utils/logging.rs:39-60 — env_logger plus an
optional Loki sink configured by --loki-url, labeled with the node's
address/pool/port so a Grafana stack can slice worker logs per pool.

``LokiHandler`` batches records on a daemon thread and POSTs the Loki
push-API shape ({"streams": [{"stream": labels, "values": [[ns, line]]}]})
with plain urllib — no extra dependencies, and a failed push never
raises into application code (batch is dropped after retries, counted in
``dropped``).
"""

from __future__ import annotations

import json
import logging
import queue
import threading
import time
import urllib.request
from typing import Optional


class LokiHandler(logging.Handler):
    def __init__(
        self,
        url: str,
        labels: Optional[dict[str, str]] = None,
        flush_interval: float = 2.0,
        max_batch: int = 500,
        timeout: float = 5.0,
    ):
        super().__init__()
        self.url = url.rstrip("/") + "/loki/api/v1/push"
        self.labels = {"job": "protocol_tpu", **(labels or {})}
        self.flush_interval = flush_interval
        self.max_batch = max_batch
        self.timeout = timeout
        self.queue: "queue.Queue[tuple[int, str]]" = queue.Queue(maxsize=10_000)
        self.dropped = 0
        self.pushed = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def emit(self, record: logging.LogRecord) -> None:
        try:
            line = self.format(record)
        except Exception:
            return
        try:
            self.queue.put_nowait((time.time_ns(), line))
        except queue.Full:
            self.dropped += 1

    def _drain(self) -> list[tuple[int, str]]:
        out: list[tuple[int, str]] = []
        while len(out) < self.max_batch:
            try:
                out.append(self.queue.get_nowait())
            except queue.Empty:
                break
        return out

    def _push(self, values: list[tuple[int, str]]) -> None:
        payload = json.dumps(
            {
                "streams": [
                    {
                        "stream": self.labels,
                        "values": [[str(ts), line] for ts, line in values],
                    }
                ]
            }
        ).encode()
        req = urllib.request.Request(
            self.url,
            data=payload,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout):
                self.pushed += len(values)
        except Exception:
            self.dropped += len(values)

    def _run(self) -> None:
        while not self._stop.is_set():
            self._stop.wait(self.flush_interval)
            batch = self._drain()
            if batch:
                self._push(batch)

    def flush(self) -> None:
        batch = self._drain()
        if batch:
            self._push(batch)

    def close(self) -> None:
        self._stop.set()
        self.flush()
        super().close()


def setup_logging(
    level: str = "info",
    loki_url: Optional[str] = None,
    labels: Optional[dict[str, str]] = None,
) -> Optional[LokiHandler]:
    """env_logger-equivalent root config + optional Loki sink
    (logging.rs:39-60). Returns the handler so callers can flush/close."""
    logging.basicConfig(
        level=getattr(logging, level.upper(), logging.INFO),
        format="%(asctime)s %(levelname)s %(name)s %(message)s",
    )
    if not loki_url:
        return None
    handler = LokiHandler(loki_url, labels=labels)
    handler.setFormatter(
        logging.Formatter("%(levelname)s %(name)s %(message)s")
    )
    logging.getLogger().addHandler(handler)
    return handler
