"""Optional IPFS artifact mirroring.

Reference: the worker can embed a rust-ipfs node and, on every artifact
upload, additionally put the bytes as a raw block and provide the CID
(worker/src/cli/command.rs:443-483 boots the node;
docker/taskbridge/file_handler.rs:109-118, 342-352 mirrors uploads).

The TPU-native deployment shape runs a kubo daemon as a sidecar instead
of embedding a node in-process; this client speaks kubo's HTTP API
(``POST /api/v0/add``) so the worker's upload path can mirror artifacts
with zero new dependencies. Mirroring is strictly best-effort, exactly
like the reference's: a down IPFS daemon never fails the primary
signed-URL upload or the work submission.
"""

from __future__ import annotations

import json
from typing import Optional


class IpfsMirror:
    def __init__(
        self,
        api_url: str = "http://127.0.0.1:5001",
        http=None,
        timeout: float = 10.0,
    ):
        self.api_url = api_url.rstrip("/")
        self.http = http  # aiohttp-compatible session
        self.timeout = timeout
        self.mirrored: int = 0
        self.failed: int = 0

    async def add(self, data: bytes, file_name: str = "artifact") -> Optional[str]:
        """Add bytes; returns the CID or None (best-effort). Uses kubo's
        multipart ``/api/v0/add`` with raw leaves (the reference stores a
        raw block, file_handler.rs:342-347). A hung daemon is bounded by
        ``timeout`` — mirroring must never stall work submission."""
        import aiohttp

        form = aiohttp.FormData()
        # FormData handles filename escaping (quotes/CRLF in a
        # workload-supplied name must not inject MIME headers)
        form.add_field(
            "file",
            data,
            filename=file_name,
            content_type="application/octet-stream",
        )
        try:
            async with self.http.post(
                f"{self.api_url}/api/v0/add",
                params={"raw-leaves": "true", "pin": "true"},
                data=form,
                timeout=aiohttp.ClientTimeout(total=self.timeout),
            ) as resp:
                if resp.status != 200:
                    self.failed += 1
                    return None
                payload = json.loads(await resp.text())
                cid = payload.get("Hash")
                if cid:
                    self.mirrored += 1
                else:
                    self.failed += 1  # 200 without a CID is still a miss
                return cid
        except Exception:
            self.failed += 1
            return None
