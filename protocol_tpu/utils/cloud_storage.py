"""Cloud object-storage providers: GCS (V4 RSA signed URLs) and S3 (SigV4).

Reference: crates/shared/src/utils/google_cloud.rs:16-233 —
``GcsStorageProvider``: base64-encoded service-account credentials,
``bucket[/subpath]`` splitting, ``mapping/{sha256}`` objects, and signed
PUT URLs whose max size is enforced by signing a ``content-length`` header.

Design difference from the reference: the reference drives object
reads/writes through an OAuth'd JSON-API client and only mints signed URLs
for workers. Here EVERY operation uses a V4 signed URL the provider mints
for itself (HEAD for file_exists, PUT for generate_mapping_file, GET for
resolve_mapping_for_sha) — one signing path, no token-refresh machinery,
and the whole provider is exercisable against a local fake bucket that
verifies real signatures.

Both schemes share the V4 canonical-request shape; they differ only in the
algorithm label (GOOG4-RSA-SHA256 vs AWS4-HMAC-SHA256), scope service
name, query-param prefix, and how the string-to-sign is signed (RSA with
the service-account key vs the SigV4 HMAC key ladder).
"""

from __future__ import annotations

import base64
import datetime
import hashlib
import hmac
import json
import urllib.parse
from typing import Optional

from .storage import StorageProvider

_UNSIGNED = "UNSIGNED-PAYLOAD"


def _quote(s: str) -> str:
    return urllib.parse.quote(s, safe="-_.~")


def _canonical_request(
    method: str,
    encoded_path: str,
    query: dict[str, str],
    headers: dict[str, str],
    payload_hash: str = _UNSIGNED,
) -> tuple[str, str]:
    """Returns (canonical_request, signed_headers). Shared V4 shape.
    ``encoded_path`` must already be percent-encoded — the SAME encoding
    goes into the signed canonical request and the returned URL, or the
    two diverge for names with spaces/'%'/'?' and every request 403s."""
    items = sorted((_quote(k), _quote(v)) for k, v in query.items())
    canonical_query = "&".join(f"{k}={v}" for k, v in items)
    lower = {k.lower().strip(): v.strip() for k, v in headers.items()}
    signed_headers = ";".join(sorted(lower))
    canonical_headers = "".join(f"{k}:{lower[k]}\n" for k in sorted(lower))
    req = "\n".join(
        [
            method,
            encoded_path,
            canonical_query,
            canonical_headers,
            signed_headers,
            payload_hash,
        ]
    )
    return req, signed_headers


def _split_bucket(bucket: str) -> tuple[str, str]:
    """``bucket[/subpath]`` -> (bucket, subpath) (google_cloud.rs:45-56)."""
    name, _, subpath = bucket.partition("/")
    return name, subpath.strip("/")


class _SignedUrlProvider(StorageProvider):
    """StorageProvider over V4 signed URLs; subclasses provide the signing
    scheme. ``http`` is an aiohttp-compatible session; ``endpoint`` defaults
    to the real service and is overridden in tests to point at a fake."""

    algorithm: str
    scope_service: str
    param_prefix: str  # "X-Goog-" or "X-Amz-"

    region = "auto"

    def __init__(self, bucket: str, http, endpoint: str):
        self.bucket, self.subpath = _split_bucket(bucket)
        self.http = http
        self.endpoint = endpoint.rstrip("/")

    # ---- scheme hooks

    def _credential_name(self) -> str:
        raise NotImplementedError

    def _sign(self, string_to_sign: bytes) -> str:
        raise NotImplementedError

    # ---- signing

    def _object_path(self, object_name: str) -> str:
        object_name = object_name.lstrip("/")
        if self.subpath:
            object_name = f"{self.subpath}/{object_name}"
        return f"/{self.bucket}/{object_name}"

    def sign_url(
        self,
        method: str,
        object_name: str,
        expires_in: float = 3600.0,
        extra_headers: Optional[dict[str, str]] = None,
    ) -> str:
        now = datetime.datetime.now(datetime.timezone.utc)
        stamp = now.strftime("%Y%m%dT%H%M%SZ")
        datestamp = now.strftime("%Y%m%d")
        scope = (
            f"{datestamp}/{self.region}/{self.scope_service}/{self._request_kind()}"
        )
        path = urllib.parse.quote(self._object_path(object_name), safe="/-_.~")
        host = urllib.parse.urlparse(self.endpoint).netloc
        headers = {"host": host}
        headers.update(extra_headers or {})
        p = self.param_prefix
        query = {
            f"{p}Algorithm": self.algorithm,
            f"{p}Credential": f"{self._credential_name()}/{scope}",
            f"{p}Date": stamp,
            f"{p}Expires": str(int(expires_in)),
            f"{p}SignedHeaders": ";".join(sorted(h.lower() for h in headers)),
        }
        canonical, _signed = _canonical_request(method, path, query, headers)
        string_to_sign = "\n".join(
            [
                self.algorithm,
                stamp,
                scope,
                hashlib.sha256(canonical.encode()).hexdigest(),
            ]
        ).encode()
        signature = self._sign(string_to_sign)
        qs = "&".join(
            f"{_quote(k)}={_quote(v)}" for k, v in sorted(query.items())
        )
        return f"{self.endpoint}{path}?{qs}&{p}Signature={signature}"

    def _request_kind(self) -> str:
        raise NotImplementedError

    # ---- StorageProvider over self-minted signed URLs

    async def file_exists(self, object_name: str) -> bool:
        url = self.sign_url("HEAD", object_name, expires_in=300)
        async with self.http.head(url) as resp:
            return resp.status == 200

    async def generate_mapping_file(self, sha256: str, file_name: str) -> None:
        """Write mapping/{sha256} -> file name (google_cloud.rs:84-113)."""
        body = file_name.lstrip("/").encode()
        url = self.sign_url(
            "PUT",
            f"mapping/{sha256}",
            expires_in=300,
            extra_headers={"content-length": str(len(body))},
        )
        async with self.http.put(
            url, data=body, headers={"Content-Length": str(len(body))}
        ) as resp:
            if resp.status not in (200, 201):
                raise RuntimeError(
                    f"mapping upload failed: {resp.status} {await resp.text()}"
                )

    async def resolve_mapping_for_sha(self, sha256: str) -> Optional[str]:
        url = self.sign_url("GET", f"mapping/{sha256}", expires_in=300)
        async with self.http.get(url) as resp:
            if resp.status != 200:
                return None
            return (await resp.text()).strip()

    async def generate_upload_signed_url(
        self,
        object_name: str,
        content_type: Optional[str] = None,
        expires_in: float = 3600.0,
        max_bytes: Optional[int] = None,
    ) -> str:
        # max size enforced by SIGNING the content-length header: the
        # uploader must send exactly the approved length or the signature
        # does not verify (google_cloud.rs:165-168)
        headers: dict[str, str] = {}
        if content_type:
            headers["content-type"] = content_type
        if max_bytes is not None:
            headers["content-length"] = str(int(max_bytes))
        return self.sign_url("PUT", object_name, expires_in, headers or None)


class GcsStorageProvider(_SignedUrlProvider):
    """GCS over V4 signed URLs, RSA-signed with the service-account key.

    ``credentials_base64`` is the reference's base64-encoded
    service-account JSON (google_cloud.rs:22-43): needs ``client_email``
    and ``private_key``.
    """

    algorithm = "GOOG4-RSA-SHA256"
    scope_service = "storage"
    param_prefix = "X-Goog-"

    def __init__(
        self,
        bucket: str,
        credentials_base64: str,
        http,
        endpoint: str = "https://storage.googleapis.com",
    ):
        super().__init__(bucket, http, endpoint)
        info = json.loads(base64.b64decode(credentials_base64))
        self.client_email = info["client_email"]
        from cryptography.hazmat.primitives import serialization

        self._key = serialization.load_pem_private_key(
            info["private_key"].encode(), password=None
        )

    def _credential_name(self) -> str:
        return self.client_email

    def _request_kind(self) -> str:
        return "goog4_request"

    def _sign(self, string_to_sign: bytes) -> str:
        from cryptography.hazmat.primitives import hashes
        from cryptography.hazmat.primitives.asymmetric import padding

        sig = self._key.sign(string_to_sign, padding.PKCS1v15(), hashes.SHA256())
        return sig.hex()


class S3StorageProvider(_SignedUrlProvider):
    """S3 (or any S3-compatible endpoint, incl. GCS interop) over SigV4
    presigned URLs with HMAC access keys."""

    algorithm = "AWS4-HMAC-SHA256"
    scope_service = "s3"
    param_prefix = "X-Amz-"

    def __init__(
        self,
        bucket: str,
        access_key: str,
        secret_key: str,
        http,
        endpoint: str = "https://s3.amazonaws.com",
        region: str = "us-east-1",  # real AWS rejects scope region "auto"
    ):
        super().__init__(bucket, http, endpoint)
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region

    def _credential_name(self) -> str:
        return self.access_key

    def _request_kind(self) -> str:
        return "aws4_request"

    def _sign(self, string_to_sign: bytes) -> str:
        # the SigV4 key ladder (date -> region -> service -> request)
        def h(key: bytes, msg: str) -> bytes:
            return hmac.new(key, msg.encode(), hashlib.sha256).digest()

        datestamp = string_to_sign.decode().split("\n")[1][:8]
        k = h(f"AWS4{self.secret_key}".encode(), datestamp)
        k = h(k, self.region)
        k = h(k, self.scope_service)
        k = h(k, "aws4_request")
        return hmac.new(k, string_to_sign, hashlib.sha256).hexdigest()
