"""Prometheus metrics registries for the orchestrator and validator.

Mirrors the reference's metric families:

  - orchestrator/src/metrics/mod.rs:6-126 — compute_task_gauges,
    task_info, file-upload + heartbeat counters, node/task/group gauges,
    nodes_per_task, task_state, status-update duration histogram
  - orchestrator/src/metrics/sync_service.rs:37-180 — the 10 s
    store -> registry rebuild (here run on scrape)
  - validator/src/metrics.rs:8-70 — loop/api histograms, invalidation
    and group-validation counters

Plus one addition the reference has no analog for: the batch matcher's
solve-duration histogram (the hot path this framework moves on-device).
"""

from __future__ import annotations

try:
    from prometheus_client import (
        CollectorRegistry,
        Counter,
        Gauge,
        Histogram,
        generate_latest,
    )
except ImportError:  # pragma: no cover - minimal envs (CI perf gate)
    # SeamMetrics degrades to its plain-dict mirror; the orchestrator /
    # validator registries (which only run in full deployments) raise at
    # construction time instead of at import time.
    CollectorRegistry = Counter = Gauge = Histogram = None

    def generate_latest(registry):
        raise ImportError("prometheus_client is not installed")

_STATUS_BUCKETS = [
    0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.0, 5.0, 10.0, 15.0, 30.0, 45.0,
    60.0, 90.0, 120.0,
]
_LOOP_BUCKETS = [
    0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.0, 5.0, 10.0, 15.0, 30.0, 60.0,
    120.0, 300.0,
]
_API_BUCKETS = [0.1, 0.5, 1.0, 2.0, 5.0, 10.0, 30.0, 60.0]
_SOLVE_BUCKETS = [
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
]


class OrchestratorMetrics:
    """metrics/mod.rs:6-126 families on a private registry."""

    def __init__(self, pool_id: int):
        self.pool_id = str(pool_id)
        self.registry = CollectorRegistry()
        r = self.registry
        self.compute_task_gauges = Gauge(
            "compute_gauges",
            "Compute task gauge metrics",
            ["node_address", "task_id", "task_name", "label", "pool_id",
             "group_id", "group_config_name"],
            registry=r,
        )
        self.task_info = Gauge(
            "task_info",
            "Task information with metadata",
            ["task_id", "task_name", "pool_id", "metadata"],
            registry=r,
        )
        self.file_upload_requests_total = Counter(
            "orchestrator_file_upload_requests",
            "Total number of file upload requests",
            ["task_id", "task_name", "node_address", "pool_id"],
            registry=r,
        )
        self.nodes_total = Gauge(
            "orchestrator_nodes_total",
            "Total number of nodes by status",
            ["status", "pool_id"],
            registry=r,
        )
        self.tasks_total = Gauge(
            "orchestrator_tasks_total",
            "Total number of tasks",
            ["pool_id"],
            registry=r,
        )
        self.groups_total = Gauge(
            "orchestrator_groups_total",
            "Total number of node groups by configuration",
            ["configuration_name", "pool_id"],
            registry=r,
        )
        self.heartbeat_requests_total = Counter(
            "orchestrator_heartbeat_requests",
            "Total number of heartbeat requests per node",
            ["node_address", "pool_id"],
            registry=r,
        )
        self.nodes_per_task = Gauge(
            "orchestrator_nodes_per_task",
            "Number of nodes actively working on each task",
            ["task_id", "task_name", "pool_id"],
            registry=r,
        )
        self.task_state = Gauge(
            "orchestrator_task_state",
            "Task state reported from nodes (1 active, 0 inactive)",
            ["node_address", "task_id", "task_state", "pool_id"],
            registry=r,
        )
        self.status_update_execution_time = Histogram(
            "orchestrator_status_update_execution_time_seconds",
            "Duration of status update execution",
            ["pool_id"],
            buckets=_STATUS_BUCKETS,
            registry=r,
        )
        # framework addition: the on-device matcher's solve cost
        self.solve_duration = Histogram(
            "orchestrator_scheduler_solve_duration_seconds",
            "Duration of batch matcher solves",
            ["backend", "pool_id"],
            buckets=_SOLVE_BUCKETS,
            registry=r,
        )

    def record_heartbeat(self, node_address: str) -> None:
        self.heartbeat_requests_total.labels(
            node_address=node_address, pool_id=self.pool_id
        ).inc()

    def record_upload_request(
        self, node_address: str, task_id: str, task_name: str
    ) -> None:
        self.file_upload_requests_total.labels(
            task_id=task_id or "",
            task_name=task_name or "",
            node_address=node_address,
            pool_id=self.pool_id,
        ).inc()

    def sync(self, store, groups_plugin=None) -> None:
        """Store -> registry rebuild (sync_service.rs:37-180), run at
        scrape time instead of on a 10 s loop."""
        pid = self.pool_id
        self.nodes_total.clear()
        by_status: dict[str, int] = {}
        nodes = store.node_store.get_nodes()
        for n in nodes:
            by_status[n.status.value] = by_status.get(n.status.value, 0) + 1
        for status, count in by_status.items():
            self.nodes_total.labels(status=status, pool_id=pid).set(count)

        tasks = store.task_store.get_all_tasks()
        self.tasks_total.clear()
        self.tasks_total.labels(pool_id=pid).set(len(tasks))
        names = {t.id: t.name for t in tasks}
        self.task_info.clear()
        for t in tasks:
            self.task_info.labels(
                task_id=t.id, task_name=t.name, pool_id=pid, metadata=""
            ).set(1)

        self.groups_total.clear()
        if groups_plugin is not None:
            by_config: dict[str, int] = {}
            for g in groups_plugin.get_groups():
                by_config[g.configuration_name] = (
                    by_config.get(g.configuration_name, 0) + 1
                )
            for config_name, count in by_config.items():
                self.groups_total.labels(
                    configuration_name=config_name, pool_id=pid
                ).set(count)

        # per-node task state + nodes-per-task from live heartbeats
        self.task_state.clear()
        self.nodes_per_task.clear()
        per_task: dict[str, int] = {}
        for n in nodes:
            hb = store.heartbeat_store.get_heartbeat(n.address)
            if hb is None or not hb.task_id:
                continue
            per_task[hb.task_id] = per_task.get(hb.task_id, 0) + 1
            self.task_state.labels(
                node_address=n.address,
                task_id=hb.task_id,
                task_state=hb.task_state or "UNKNOWN",
                pool_id=pid,
            ).set(1)
        for task_id, count in per_task.items():
            self.nodes_per_task.labels(
                task_id=task_id, task_name=names.get(task_id, ""), pool_id=pid
            ).set(count)

        # workload metrics (container -> bridge -> heartbeat -> store)
        self.compute_task_gauges.clear()
        group_of = (
            {a: g for g in (groups_plugin.get_groups() if groups_plugin else [])
             for a in g.nodes}
        )
        for task_id, labels in store.metrics_store.get_all_metrics().items():
            for label, per_node in labels.items():
                for node_addr, value in per_node.items():
                    g = group_of.get(node_addr)
                    self.compute_task_gauges.labels(
                        node_address=node_addr,
                        task_id=task_id,
                        task_name=names.get(task_id, ""),
                        label=label,
                        pool_id=pid,
                        group_id=g.id if g else "",
                        group_config_name=g.configuration_name if g else "",
                    ).set(value)

    def render(self) -> bytes:
        return generate_latest(self.registry)


_WIRE_MS_BUCKETS = [
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
    500.0, 1000.0, 2500.0,
]


class SeamMetrics:
    """Per-phase instrumentation for the scheduler gRPC seam (wire v2).

    Phases: ``serialize`` (client-side pack), ``decode`` (server-side
    unpack), ``solve`` (kernel), ``rpc`` (client-observed round trip) —
    histograms in milliseconds. Byte counters per direction and event
    counters for the session ladder (hit / miss / evict / expired /
    mismatch / reopen / retry / fallback_v1).

    A plain-dict mirror is authoritative for :meth:`snapshot` (what rides
    in ``HealthResponse.seam_metrics`` and what the bench scrapes), with
    an optional prometheus registry for scrape endpoints — the seam must
    stay measurable in environments without prometheus_client."""

    def __init__(self, role: str = "server"):
        from protocol_tpu.utils.lockwitness import make_lock

        self.role = role
        self._lock = make_lock("seam")
        self._ms_sum: dict[str, float] = {}
        self._ms_count: dict[str, int] = {}
        self._bytes: dict[str, int] = {}
        self._events: dict[str, int] = {}
        try:
            self.registry = CollectorRegistry()
            self._h_phase = Histogram(
                "scheduler_seam_phase_ms",
                "Wire-seam per-phase latency (ms)",
                ["role", "phase"],
                buckets=_WIRE_MS_BUCKETS,
                registry=self.registry,
            )
            self._c_bytes = Counter(
                "scheduler_seam_wire_bytes",
                "Wire bytes through the scheduler seam",
                ["role", "direction"],
                registry=self.registry,
            )
            self._c_events = Counter(
                "scheduler_seam_session_events",
                "Session-protocol events at the scheduler seam",
                ["role", "event"],
                registry=self.registry,
            )
        except Exception:  # pragma: no cover - prometheus_client absent
            self.registry = None

    def observe_ms(self, phase: str, ms: float) -> None:
        with self._lock:
            self._ms_sum[phase] = self._ms_sum.get(phase, 0.0) + float(ms)
            self._ms_count[phase] = self._ms_count.get(phase, 0) + 1
        if self.registry is not None:
            self._h_phase.labels(role=self.role, phase=phase).observe(ms)

    def add_bytes(self, direction: str, n: int) -> None:
        with self._lock:
            self._bytes[direction] = self._bytes.get(direction, 0) + int(n)
        if self.registry is not None:
            self._c_bytes.labels(role=self.role, direction=direction).inc(n)

    def count(self, event: str, n: int = 1) -> None:
        with self._lock:
            self._events[event] = self._events.get(event, 0) + int(n)
        if self.registry is not None:
            self._c_events.labels(role=self.role, event=event).inc(n)

    def snapshot(self) -> dict[str, float]:
        """Flat name->value view: ``<phase>_ms_sum`` / ``<phase>_count``,
        ``bytes_<direction>``, ``session_<event>``."""
        with self._lock:
            out: dict[str, float] = {}
            for phase, s in self._ms_sum.items():
                out[f"{phase}_ms_sum"] = round(s, 3)
                out[f"{phase}_count"] = float(self._ms_count[phase])
            for direction, n in self._bytes.items():
                out[f"bytes_{direction}"] = float(n)
            for event, n in self._events.items():
                out[f"session_{event}"] = float(n)
            return out

    def render(self) -> bytes:
        if self.registry is None:  # pragma: no cover
            return b""
        return generate_latest(self.registry)


class ValidatorMetrics:
    """validator/src/metrics.rs:8-70 families on a private registry."""

    def __init__(self, validator_id: str, pool_id: int):
        self.validator_id = validator_id
        self.pool_id = str(pool_id)
        self.registry = CollectorRegistry()
        r = self.registry
        base = ["validator_id", "pool_id"]
        self.validation_loop_duration = Histogram(
            "validator_validation_loop_duration_seconds",
            "Duration of the validation loop",
            base,
            buckets=_LOOP_BUCKETS,
            registry=r,
        )
        self.work_keys_invalidated = Counter(
            "validator_work_keys_invalidated",
            "Total work keys invalidated",
            base,
            registry=r,
        )
        self.work_keys_soft_invalidated = Counter(
            "validator_work_keys_soft_invalidated",
            "Total work keys soft invalidated",
            base + ["group_key"],
            registry=r,
        )
        self.work_keys_to_process = Gauge(
            "validator_work_keys_to_process",
            "Work keys to process in the current validation loop",
            base,
            registry=r,
        )
        self.errors = Counter(
            "validator_errors",
            "Total errors",
            base + ["error"],
            registry=r,
        )
        self.api_duration = Histogram(
            "validator_api_duration_seconds",
            "Verification-API request duration",
            base + ["endpoint"],
            buckets=_API_BUCKETS,
            registry=r,
        )
        self.api_requests = Counter(
            "validator_api_requests",
            "Total verification-API requests",
            base + ["endpoint", "status"],
            registry=r,
        )
        self.group_validations = Counter(
            "validator_group_validations",
            "Total group validations by result",
            base + ["group_id", "result"],
            registry=r,
        )
        self.group_work_units_check_total = Counter(
            "validator_group_work_units_check",
            "Whether the work units match the group total",
            base + ["group_id", "result"],
            registry=r,
        )

    def _base(self) -> dict:
        return {"validator_id": self.validator_id, "pool_id": self.pool_id}

    def render(self) -> bytes:
        return generate_latest(self.registry)
