"""Shared keep-alive JSON POST transport for the remote KV/ledger clients.

One per-thread persistent connection (fresh TCP handshakes per op
dominated measured client latency), honoring any path prefix in the base
URL (ingress-routed deployments). No proxy support by design: these
clients speak pod-to-pod inside a cluster; HTTP(S)_PROXY env vars are
deliberately not consulted.

Retry policy — the part that must not be casual: a request that failed
while SENDING never reached the server and is always safe to resend
(including the stale kept-alive socket the server closed while idle). A
failure while READING the response is ambiguous — the server may have
applied the request — so it is retried only when the caller marks the
operation response-retryable. That flag is safe for reads always; for
WRITES it is safe only when the caller makes the resend idempotent
end-to-end (e.g. RemoteLedger attaches a per-call tx_id the ledger API
deduplicates — a resent applied-but-response-lost write replays the
recorded outcome). A write without such a peer-side guarantee must NOT
set it, or a lost response can double-apply.
"""

from __future__ import annotations

import http.client
import json
import threading
import urllib.parse
from typing import Optional, Type

from protocol_tpu.utils.tls import env_client_ssl_context


class KeepAliveJsonClient:
    def __init__(
        self,
        base_url: str,
        timeout: float,
        error_cls: Type[Exception],
    ):
        parsed = urllib.parse.urlparse(base_url.rstrip("/"))
        self._https = parsed.scheme == "https"
        self._netloc = parsed.netloc
        self._prefix = parsed.path.rstrip("/")
        self.timeout = timeout
        self.error_cls = error_cls
        # https peers are verified against the deployment CA
        # (PROTOCOL_TPU_TLS_CA) or system trust — never unverified
        self._ssl_context = env_client_ssl_context() if self._https else None
        self._tlocal = threading.local()

    def _connection(self):
        conn = getattr(self._tlocal, "conn", None)
        if conn is None:
            if self._https:
                import ssl as _ssl

                conn = http.client.HTTPSConnection(
                    self._netloc,
                    timeout=self.timeout,
                    context=self._ssl_context or _ssl.create_default_context(),
                )
            else:
                conn = http.client.HTTPConnection(
                    self._netloc, timeout=self.timeout
                )
            self._tlocal.conn = conn
        return conn

    def drop_connection(self) -> None:
        conn = getattr(self._tlocal, "conn", None)
        if conn is not None:
            try:
                conn.close()
            except Exception:
                pass
            self._tlocal.conn = None

    def post(
        self,
        path: str,
        payload: dict,
        headers: Optional[dict] = None,
        retry_response: bool = False,
    ) -> dict:
        """POST json, return the parsed body (also for error statuses —
        callers inspect {"success": ...}). ``retry_response=True`` marks
        the op safe to resend after a failure while reading the response
        (reads, or writes the peer deduplicates — see module docstring)."""
        body = json.dumps(payload)
        hdrs = {"Content-Type": "application/json", **(headers or {})}
        full_path = f"{self._prefix}{path}"
        for attempt in (0, 1):
            conn = self._connection()
            try:
                conn.request("POST", full_path, body=body, headers=hdrs)
            except (http.client.HTTPException, OSError) as e:
                # send phase: the request never completed transmission —
                # always safe to retry once on a fresh connection
                self.drop_connection()
                if attempt == 0:
                    continue
                raise self.error_cls(f"unreachable: {e}") from e
            try:
                resp = conn.getresponse()
                raw = resp.read()
            except (http.client.HTTPException, OSError) as e:
                self.drop_connection()
                if attempt == 0 and retry_response:
                    continue
                raise self.error_cls(
                    f"no response ({'retryable read' if retry_response else 'write; not retried'}): {e}"
                ) from e
            try:
                return json.loads(raw)
            except json.JSONDecodeError as e:
                self.drop_connection()
                raise self.error_cls(
                    f"bad response (HTTP {resp.status})"
                ) from e
        raise self.error_cls("unreachable")
