"""Kill-proof JSONL artifact appends for bench/measurement scripts.

The r4/r5 scaling artifacts died to timeouts with everything buffered in
memory (header-only logs on disk — VERDICT r5 "what's weak" #4). Every
measurement row goes through one contract: open/append/close per row, so
a SIGKILL can never erase a finished stage's evidence.
"""

from __future__ import annotations

import json
import os


def append_jsonl(path: str, row: dict) -> None:
    """Append one JSON row to ``path`` immediately (no-op when ``path``
    is empty/falsy — the scripts' artifact-disable convention)."""
    if not path:
        return
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "a") as fh:
        fh.write(json.dumps(row) + "\n")
