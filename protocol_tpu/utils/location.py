"""IP -> geo location resolvers.

Reference: crates/discovery/src/location_service.rs — an ipapi.co-style
GET per IP feeding NodeLocation, consumed by the 30 s enrichment loop.
Here the resolver is the pluggable seam `DiscoveryService.location_resolver`
expects; two implementations:

  HttpLocationResolver    ip-api-style JSON endpoint with an in-memory
                          cache (one lookup per distinct IP).
  StaticLocationResolver  table/prefix-based (dev clusters, tests, and
                          air-gapped deployments).
"""

from __future__ import annotations

from typing import Optional

from protocol_tpu.models.node import NodeLocation


class StaticLocationResolver:
    def __init__(self, table: Optional[dict[str, NodeLocation]] = None,
                 default: Optional[NodeLocation] = None):
        self.table = table or {}
        self.default = default

    async def __call__(self, ip: str) -> Optional[NodeLocation]:
        if ip in self.table:
            return self.table[ip]
        # longest-prefix match on dotted quads ("10.1." -> region)
        best, best_len = self.default, -1
        for prefix, loc in self.table.items():
            if prefix.endswith(".") and ip.startswith(prefix) and len(prefix) > best_len:
                best, best_len = loc, len(prefix)
        return best


class HttpLocationResolver:
    """GET {base_url}/{ip} expecting {"latitude": .., "longitude": ..,
    "city"/"region"/"country": ..} (the reference's location-service shape),
    with per-IP caching and an optional API key header."""

    def __init__(self, base_url: str, http, api_key: Optional[str] = None):
        self.base_url = base_url.rstrip("/")
        self.http = http
        self.api_key = api_key
        self._cache: dict[str, Optional[NodeLocation]] = {}

    async def __call__(self, ip: str) -> Optional[NodeLocation]:
        if ip in self._cache:
            return self._cache[ip]
        headers = {"Authorization": f"Bearer {self.api_key}"} if self.api_key else {}
        loc: Optional[NodeLocation] = None
        try:
            async with self.http.get(f"{self.base_url}/{ip}", headers=headers) as resp:
                if resp.status == 200:
                    d = await resp.json()
                    if "latitude" in d and "longitude" in d:
                        loc = NodeLocation.from_dict(d)
        except Exception:
            loc = None
        # negative results are NOT cached: the enrichment loop retries them
        if loc is not None:
            self._cache[ip] = loc
        return loc
