"""Self-contained interactive API docs page.

The reference serves Swagger UI for its orchestrator API
(crates/orchestrator/src/api/server.rs:46-97, utoipa-swagger-ui). That
ships a bundled third-party JS app; this framework's deployments are
zero-egress and dependency-light, so /docs is a single static page —
no CDN, no vendored bundle — that fetches the service's own
/openapi.json and renders an explorer with a try-it console
(method + path + bearer key + JSON body -> live response).
"""

from __future__ import annotations

DOCS_HTML = """<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>protocol_tpu API</title>
<style>
  :root { color-scheme: light dark; }
  body { font: 14px/1.5 system-ui, sans-serif; margin: 0 auto; max-width: 960px;
         padding: 1.5rem; }
  h1 { font-size: 1.3rem; }
  .op { border: 1px solid color-mix(in srgb, currentColor 25%, transparent);
        border-radius: 6px; margin: .4rem 0; }
  .op > summary { padding: .45rem .7rem; cursor: pointer; display: flex;
                  gap: .7rem; align-items: baseline; }
  .op[open] > summary { border-bottom: 1px solid
                        color-mix(in srgb, currentColor 15%, transparent); }
  .m { font-weight: 700; width: 4.2em; text-align: center; border-radius: 4px;
       padding: .05rem .3rem; font-size: .8rem; color: #fff; }
  .get { background: #2f7d4f; } .post { background: #2b6cb0; }
  .put { background: #b7791f; } .delete { background: #c53030; }
  .path { font-family: ui-monospace, monospace; }
  .sum { opacity: .75; flex: 1; text-align: right; font-size: .85rem; }
  .body { padding: .7rem; }
  textarea, input { font: 12px ui-monospace, monospace; width: 100%;
                    box-sizing: border-box; margin: .15rem 0; }
  textarea { min-height: 4.5rem; }
  pre { background: color-mix(in srgb, currentColor 8%, transparent);
        padding: .6rem; border-radius: 6px; overflow: auto; max-height: 22rem; }
  button { cursor: pointer; padding: .25rem .9rem; }
  #key { max-width: 22rem; }
  .muted { opacity: .65; }
</style>
</head>
<body>
<h1 id="title">protocol_tpu API</h1>
<p class="muted" id="desc"></p>
<p><label>Authorization bearer key (admin routes):
   <input id="key" placeholder="admin" autocomplete="off"></label></p>
<div id="ops">loading /openapi.json…</div>
<script>
(async () => {
  const spec = await (await fetch('openapi.json')).json();
  document.getElementById('title').textContent =
    spec.info.title + ' — v' + spec.info.version;
  document.getElementById('desc').textContent = spec.info.description || '';
  const ops = document.getElementById('ops');
  ops.textContent = '';
  // escape spec-derived strings: a route docstring (or parameter name)
  // containing HTML must render as text, not inject into the page
  const esc = (s) => String(s).replace(/[&<>"']/g, (c) => ({
    '&': '&amp;', '<': '&lt;', '>': '&gt;', '"': '&quot;', "'": '&#39;',
  }[c]));
  for (const [path, methods] of Object.entries(spec.paths)) {
    for (const [method, op] of Object.entries(methods)) {
      const d = document.createElement('details');
      d.className = 'op';
      const params = (op.parameters || []).map(p => p.name);
      const mcls = /^[a-z]+$/.test(method) ? method : 'get';
      d.innerHTML = `
        <summary>
          <span class="m ${mcls}">${esc(method.toUpperCase())}</span>
          <span class="path">${esc(path)}</span>
          <span class="sum">${esc(op.summary || '')}</span>
        </summary>
        <div class="body">
          ${params.map(p =>
            `<label>${esc(p)}: <input data-param="${esc(p)}"></label>`).join('')}
          ${['post', 'put', 'patch'].includes(method)
            ? '<textarea data-body placeholder="JSON body"></textarea>' : ''}
          <button data-send>Send</button>
          <pre data-out class="muted">—</pre>
        </div>`;
      d.querySelector('[data-send]').onclick = async () => {
        let url = path;
        for (const inp of d.querySelectorAll('[data-param]'))
          url = url.replace('{' + inp.dataset.param + '}',
                            encodeURIComponent(inp.value));
        const headers = {};
        const key = document.getElementById('key').value;
        if (key) headers['Authorization'] = 'Bearer ' + key;
        const bodyEl = d.querySelector('[data-body]');
        const init = { method: method.toUpperCase(), headers };
        if (bodyEl && bodyEl.value) {
          headers['Content-Type'] = 'application/json';
          init.body = bodyEl.value;
        }
        const out = d.querySelector('[data-out]');
        out.textContent = '…';
        try {
          const r = await fetch(url, init);
          const text = await r.text();
          let shown = text;
          try { shown = JSON.stringify(JSON.parse(text), null, 2); }
          catch (e) {}
          out.textContent = r.status + ' ' + r.statusText + '\\n' + shown;
        } catch (e) { out.textContent = 'request failed: ' + e; }
      };
      ops.appendChild(d);
    }
  }
})();
</script>
</body>
</html>
"""


def docs_handler():
    """aiohttp handler serving the docs page (mount next to /openapi.json)."""
    from aiohttp import web

    async def handler(request: web.Request) -> web.Response:
        return web.Response(text=DOCS_HTML, content_type="text/html")

    return handler
