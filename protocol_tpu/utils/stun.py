"""STUN public-IP detection (RFC 5389 binding request, stdlib only).

Reference: the worker discovers its public IP via a STUN check at boot
(worker/src/checks/stun.rs, used at cli/command.rs:332-339) so the
address it advertises to discovery is reachable from outside NAT. Same
capability here: one UDP binding request, parse the
(XOR-)MAPPED-ADDRESS attribute. Best-effort — deployments that know
their address pass it explicitly (--advertise-ip), and this fills the
gap when they don't.
"""

from __future__ import annotations

import os
import socket
import struct
from typing import Optional

_BINDING_REQUEST = 0x0001
_BINDING_RESPONSE = 0x0101
_MAGIC_COOKIE = 0x2112A442
_ATTR_MAPPED_ADDRESS = 0x0001
_ATTR_XOR_MAPPED_ADDRESS = 0x0020

DEFAULT_SERVERS = [
    ("stun.l.google.com", 19302),
    ("stun.cloudflare.com", 3478),
]


def _parse_response(data: bytes, txn_id: bytes) -> Optional[str]:
    if len(data) < 20:
        return None
    msg_type, msg_len, cookie = struct.unpack("!HHI", data[:8])
    if msg_type != _BINDING_RESPONSE or cookie != _MAGIC_COOKIE:
        return None
    if data[8:20] != txn_id:
        return None
    off = 20
    end = min(len(data), 20 + msg_len)
    plain: Optional[str] = None
    while off + 4 <= end:
        attr_type, attr_len = struct.unpack("!HH", data[off : off + 4])
        value = data[off + 4 : off + 4 + attr_len]
        if attr_type == _ATTR_XOR_MAPPED_ADDRESS and len(value) >= 8:
            family = value[1]
            if family == 0x01:  # IPv4
                # XOR form wins regardless of attribute order: NAT ALGs
                # rewrite the plain MAPPED-ADDRESS in flight (why RFC 5389
                # introduced the XOR encoding)
                raw = struct.unpack("!I", value[4:8])[0] ^ _MAGIC_COOKIE
                return socket.inet_ntoa(struct.pack("!I", raw))
        if attr_type == _ATTR_MAPPED_ADDRESS and len(value) >= 8:
            if value[1] == 0x01 and plain is None:
                plain = socket.inet_ntoa(value[4:8])
        # attributes are 32-bit aligned
        off += 4 + attr_len + ((4 - attr_len % 4) % 4)
    return plain


def get_public_ip(
    servers: Optional[list[tuple[str, int]]] = None,
    timeout: float = 2.0,
) -> Optional[str]:
    """One binding round-trip per server until one answers; None if none
    do (offline / egress-less environments)."""
    txn_id = os.urandom(12)
    request = struct.pack("!HHI", _BINDING_REQUEST, 0, _MAGIC_COOKIE) + txn_id
    for host, port in servers or DEFAULT_SERVERS:
        try:
            with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as sock:
                sock.settimeout(timeout)
                sock.sendto(request, (host, port))
                data, _addr = sock.recvfrom(2048)
            ip = _parse_response(data, txn_id)
            if ip:
                return ip
        except OSError:
            continue
    return None
