"""Observability CLI: ``python -m protocol_tpu.obs <verb>``.

  report   text flame/phase breakdown + per-tick percentile table from a
           flight-recorder trace (--json for the structured form)
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _cmd_report(args) -> int:
    from protocol_tpu.obs.report import render, report_dict

    if args.json:
        print(json.dumps(report_dict(args.trace), indent=1))
    else:
        print(render(args.trace))
    return 0


def main(argv=None) -> int:
    # report reads frames only, but the trace codec imports the wire
    # module; keep any ambient accelerator plugin out of the way
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    ap = argparse.ArgumentParser(prog="python -m protocol_tpu.obs")
    sub = ap.add_subparsers(dest="verb", required=True)

    rp = sub.add_parser(
        "report", help="flame/phase report from a trace file"
    )
    rp.add_argument("trace")
    rp.add_argument("--json", action="store_true")
    rp.set_defaults(fn=_cmd_report)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
