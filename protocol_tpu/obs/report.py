"""Trace-native flame/phase report: ``python -m protocol_tpu.obs report``.

Renders, offline, from any recorded or replayed flight-recorder trace:

  * a **per-tick phase table** — wall / decode / candidate-gen / engine
    walls plus the native engine's INTERNAL phases (bidding rounds, bids,
    evictions, Sinkhorn sweeps, repair passes) that ride OUTCOME-frame
    metrics as ``eng_*`` scalars,
  * a **flame breakdown** — span trees aggregated across ticks by call
    path (each OUTCOME frame's ``spans`` list), with total/self time and
    percent-of-total bars,
  * a **percentile table** — true p50/p90/p99/p999 tick latency from the
    obs histograms, split cold vs warm.

This is how "where did the 220 s go" gets answered for any recorded
engine x transport combination without re-running anything.
"""

from __future__ import annotations

from typing import Optional

from protocol_tpu.obs.metrics import LatencyHistogram
from protocol_tpu.obs.quality import aggregate_quality

# per-tick table columns pulled from OUTCOME metrics when present:
# (key, header, is_ms)
_PHASE_COLS = (
    ("wall_ms", "wall", True),
    ("decode_ms", "decode", True),
    ("gen_ms", "gen", True),
    ("solve_ms", "solve", True),
    ("eng_bid_ms", "bid", True),
    ("eng_repair_ms", "repair", True),
    ("eng_merge_ms", "merge", True),
    ("eng_sink_f_ms", "sink_f", True),
    ("eng_sink_g_ms", "sink_g", True),
    ("eng_rounds", "rounds", False),
    ("eng_bids", "bids", False),
    ("eng_evicted", "evict", False),
    ("eng_sink_iters", "sweeps", False),
    # incremental candidate maintenance (the repair kernel's phase wall
    # and row accounting; cold ticks report the cold-pass counter)
    ("eng_cand_repair_merge_ms", "cand_rep", True),
    ("eng_cand_repair_rows", "rep_rows", False),
    ("eng_cand_repair_rescans", "rescans", False),
    ("cand_cold_passes", "cold_gen", False),
    ("changed_rows", "dirty", False),
    ("delta_rows", "delta", False),
)


def _fmt(v, is_ms: bool) -> str:
    if v is None:
        return "-"
    if is_ms:
        return f"{float(v):.1f}"
    return str(int(v))


def _tick_wall(m: dict) -> Optional[float]:
    """Best-available end-to-end wall for a tick's outcome metrics."""
    for key in ("wall_ms",):
        if m.get(key) is not None:
            return float(m[key])
    if m.get("decode_ms") is not None or m.get("solve_ms") is not None:
        return float(m.get("decode_ms") or 0.0) + float(
            m.get("solve_ms") or 0.0
        )
    return None


def tick_table(outcomes) -> list[str]:
    """The per-tick phase breakdown (native internal phases included)."""
    cols = [
        c for c in _PHASE_COLS
        if any(o.metrics.get(c[0]) is not None for o in outcomes)
    ]
    lines = []
    header = "tick  " + "  ".join(f"{h:>8}" for _, h, _ in cols) + "  assigned"
    lines.append(header)
    lines.append("-" * len(header))
    for o in outcomes:
        m = o.metrics
        row = f"{o.tick:>4}  " + "  ".join(
            f"{_fmt(m.get(k), is_ms):>8}" for k, _, is_ms in cols
        )
        lines.append(f"{row}  {o.num_assigned:>8}")
    return lines


def _span_paths(spans: list[dict]) -> dict[tuple, tuple[float, int]]:
    """Aggregate one tick's spans into {path: (total_us, count)} where
    path is the name chain from the root."""
    by_id = {s["span"]: s for s in spans}

    def path_of(s) -> tuple:
        chain = [s["name"]]
        seen = {s["span"]}
        cur = s
        while cur.get("parent") is not None:
            parent = by_id.get(cur["parent"])
            if parent is None or parent["span"] in seen:
                break
            chain.append(parent["name"])
            seen.add(parent["span"])
            cur = parent
        return tuple(reversed(chain))

    out: dict[tuple, list] = {}
    for s in spans:
        p = path_of(s)
        cur = out.setdefault(p, [0.0, 0])
        cur[0] += float(s.get("us", 0.0))
        cur[1] += 1
    return {k: (v[0], v[1]) for k, v in out.items()}


def flame(outcomes, width: int = 32) -> list[str]:
    """Aggregate span trees across every tick into one text flame."""
    totals: dict[tuple, list] = {}
    for o in outcomes:
        for path, (us, n) in _span_paths(o.metrics.get("spans") or []).items():
            cur = totals.setdefault(path, [0.0, 0])
            cur[0] += us
            cur[1] += n
    if not totals:
        return ["(no spans recorded in this trace)"]
    roots_us = sum(us for p, (us, n) in totals.items() if len(p) == 1)
    roots_us = roots_us or max(us for us, _ in totals.values())
    lines = [
        f"{'span path':<44} {'total ms':>10} {'calls':>6}  % of root"
    ]
    lines.append("-" * len(lines[0]))
    for path in sorted(totals, key=lambda p: (p[:1], -totals[p][0])):
        us, n = totals[path]
        frac = us / roots_us if roots_us else 0.0
        bar = "#" * max(1, int(frac * width)) if us else ""
        label = "  " * (len(path) - 1) + path[-1]
        lines.append(
            f"{label:<44} {us / 1e3:>10.1f} {n:>6}  {frac:>5.1%} {bar}"
        )
    return lines


# quality-plane columns pulled from OUTCOME metrics: (key, header, fmt)
_QUALITY_COLS = (
    ("gap_per_task", "gap/task", "f6"),
    ("churn_ratio", "churn", "f4"),
    ("starve_max", "starve", "i"),
    ("outcome_no_candidates", "no_cand", "i"),
    ("outcome_outbid", "outbid", "i"),
    ("outcome_retired", "retired", "i"),
    ("outcome_unexplained", "unexpl", "i"),
)


def _fmt_q(v, fmt: str) -> str:
    if v is None:
        return "-"
    if fmt == "f6":
        return f"{float(v):.6f}"
    if fmt == "f4":
        return f"{float(v):.4f}"
    return str(int(v))


def quality_summary(outcomes, events=None) -> Optional[dict]:
    """Aggregate the quality scalars riding OUTCOME frames via the
    shared canonical roll-up (None when the trace predates the quality
    plane), plus the trace's SLO alert-event count."""
    out = aggregate_quality([o.metrics for o in outcomes])
    if out is None:
        return None
    alerts = [
        e for frame in (events or []) for e in frame.get("events", [])
        if e.get("kind") == "slo"
    ]
    if alerts:
        out["slo_alerts"] = len(alerts)
    return out


def quality_table(outcomes, events=None) -> list[str]:
    """The decision-quality section: per-tick certified gap / churn /
    starvation / unassigned-cause table plus the roll-up line (and any
    SLO alert events the trace carries)."""
    summary = quality_summary(outcomes, events)
    if summary is None:
        return ["(no quality scalars in this trace — re-record with the "
                "obs plane on)"]
    cols = [
        c for c in _QUALITY_COLS
        if any(o.metrics.get(c[0]) is not None for o in outcomes)
    ]
    lines = []
    header = "tick  " + "  ".join(f"{h:>9}" for _, h, _ in cols)
    lines.append(header)
    lines.append("-" * len(header))
    for o in outcomes:
        m = o.metrics
        if m.get("gap_per_task") is None:
            continue
        lines.append(
            f"{o.tick:>4}  " + "  ".join(
                f"{_fmt_q(m.get(k), fmt):>9}" for k, _, fmt in cols
            )
        )
    lines.append("")
    causes = summary["causes"]
    lines.append(
        f"certified gap/task mean {summary['gap_per_task_mean']:.6f} "
        f"max {summary['gap_per_task_max']:.6f}"
        + (
            f" | churn mean {summary['churn_ratio_mean']:.4f} "
            f"max {summary['churn_ratio_max']:.4f}"
            if "churn_ratio_mean" in summary else ""
        )
        + f" | starvation max {summary['starve_max']} ticks"
    )
    lines.append(
        "unassigned causes: "
        f"no_candidates={causes['no_candidates']} "
        f"outbid={causes['outbid']} retired={causes['retired']} "
        f"unexplained={summary['unexplained_unassigned']}"
        f" (assigned task-ticks: {causes['assigned']})"
    )
    if summary.get("slo_alerts"):
        lines.append(f"SLO alert events in trace: {summary['slo_alerts']}")
        for frame in events or []:
            for e in frame.get("events", []):
                if e.get("kind") != "slo":
                    continue
                lines.append(
                    f"  tick {e.get('tick'):>4} {e.get('state'):>5} "
                    f"{e.get('slo')} session={e.get('session')} "
                    f"value={e.get('value')} threshold={e.get('threshold')} "
                    f"burn={e.get('burn_short')}/{e.get('burn_long')}"
                )
    return lines


def percentile_table(outcomes) -> list[str]:
    """Cold vs warm tick-latency distribution (obs histograms)."""
    cold = LatencyHistogram()
    warm = LatencyHistogram()
    for o in outcomes:
        w = _tick_wall(o.metrics)
        if w is None:
            continue
        (cold if o.metrics.get("cold") or o.tick == 0 else warm).observe_ms(w)
    lines = [
        f"{'ticks':<6} {'count':>6} {'mean':>9} {'p50':>9} {'p90':>9} "
        f"{'p99':>9} {'p999':>9} {'max':>9}   (ms)"
    ]
    lines.append("-" * len(lines[0]))
    for name, h in (("cold", cold), ("warm", warm)):
        s = h.snapshot_ms()
        if not s.get("count"):
            lines.append(f"{name:<6} {0:>6}")
            continue
        lines.append(
            f"{name:<6} {s['count']:>6} {s['mean_ms']:>9.2f} "
            f"{s['p50_ms']:>9.2f} {s['p90_ms']:>9.2f} {s['p99_ms']:>9.2f} "
            f"{s['p999_ms']:>9.2f} {s['max_ms']:>9.2f}"
        )
    return lines


def report_dict(trace_path: str) -> dict:
    """Structured form of the report (the --json output)."""
    from protocol_tpu.trace import format as tfmt

    t = tfmt.read_trace(trace_path)
    ticks = []
    cold = LatencyHistogram()
    warm = LatencyHistogram()
    for o in t.outcomes:
        m = {
            k: v for k, v in o.metrics.items() if k != "spans"
        }
        ticks.append({
            "tick": o.tick, "num_assigned": o.num_assigned, **m,
        })
        w = _tick_wall(o.metrics)
        if w is not None:
            (cold if o.metrics.get("cold") or o.tick == 0 else warm
             ).observe_ms(w)
    out = {
        "trace": trace_path,
        "truncated": t.truncated,
        "ticks": ticks,
        "cold": cold.snapshot_ms(),
        "warm": warm.snapshot_ms(),
    }
    quality = quality_summary(t.outcomes, t.events)
    if quality is not None:
        out["quality"] = quality
    if t.snapshot is not None:
        out.update(
            providers=t.snapshot.n_providers, tasks=t.snapshot.n_tasks,
            kernel=t.snapshot.kernel,
        )
    return out


def render(trace_path: str) -> str:
    """The human-facing text report."""
    from protocol_tpu.trace import format as tfmt

    t = tfmt.read_trace(trace_path)
    lines: list[str] = []
    head = f"obs report: {trace_path}"
    if t.snapshot is not None:
        head += (
            f"  [{t.snapshot.n_providers}x{t.snapshot.n_tasks} "
            f"kernel={t.snapshot.kernel} ticks={t.ticks}]"
        )
    if t.truncated:
        head += "  (TRUNCATED TAIL)"
    lines.append(head)
    lines.append("=" * len(head))
    if not t.outcomes:
        lines.append("no OUTCOME frames — an input-only trace; replay it "
                     "(python -m protocol_tpu.trace record) to profile")
        return "\n".join(lines)
    lines.append("")
    lines.append("per-tick phase breakdown")
    lines.extend(tick_table(t.outcomes))
    lines.append("")
    lines.append("tick latency distribution")
    lines.extend(percentile_table(t.outcomes))
    lines.append("")
    lines.append("quality (decision plane)")
    lines.extend(quality_table(t.outcomes, t.events))
    lines.append("")
    lines.append("flame (span totals across ticks)")
    lines.extend(flame(t.outcomes))
    return "\n".join(lines)
