"""Seam-wide observability plane (spans + per-session metrics + report).

Everything the fleet/streaming roadmap items will be measured against
lives here, spanning all four layers of the scheduler seam:

  * ``obs.spans`` — a deterministic-safe structured span tracer
    (monotonic-clock ring buffer, explicit counter-allocated IDs, no
    wall-clock or randomness), propagated across the gRPC seam via
    request metadata so a client tick stitches into one causal trace.
  * ``obs.metrics`` — HDR-style latency histograms (true p50/p99/p999,
    not sums/means) plus the per-session/per-tenant registry: tick
    latency, assigned fraction, arena reuse ratio, EngineThreadBudget
    saturation. The plain-dict snapshot is AUTHORITATIVE; prometheus is
    an optional export, same degradation contract as ``SeamMetrics``.
  * ``obs.endpoint`` — one consolidated ``/metrics`` scrape endpoint on
    the servicer merging SeamMetrics, SessionStore occupancy, and the
    new arena/budget gauges (503s cleanly when prometheus_client is
    absent; ``/metrics.json`` serves the authoritative snapshot always).
  * ``obs.report`` — ``python -m protocol_tpu.obs report <trace>``: a
    text flame/phase breakdown + per-tick percentile table from any
    recorded or replayed flight-recorder trace, including the native
    engine's INTERNAL phases (bidding rounds, eps sweeps, dirty-row
    repair) that ride OUTCOME frames.

Determinism contract: instrumentation reads monotonic clocks and
appends to ring buffers — it never feeds solver state, so the
replay-identity gate passes bit-for-bit with tracing enabled (CI proves
it, the obs-overhead gate bounds its cost). ``PROTOCOL_TPU_OBS=0``
turns the whole plane off.
"""

from __future__ import annotations

import os

from protocol_tpu.obs import spans
from protocol_tpu.obs.metrics import LatencyHistogram, ObsRegistry
from protocol_tpu.obs.spans import SpanTracer, tracer

__all__ = [
    "LatencyHistogram", "ObsRegistry", "SpanTracer", "enabled",
    "set_enabled", "spans", "tracer",
]

# the ONE owner of the PROTOCOL_TPU_OBS flag: the tracer's enabled bit
# is derived from this parse (set_enabled keeps them in lockstep)
_ENABLED = os.environ.get("PROTOCOL_TPU_OBS", "1").strip().lower() not in (
    "0", "off", "false", "no",
)
spans.TRACER.enabled = _ENABLED


def enabled() -> bool:
    """Whether the observability plane is on (default yes; the
    obs-overhead CI gate bounds its cost to a few percent)."""
    return _ENABLED


def set_enabled(flag: bool) -> None:
    """Toggle the plane at runtime (the overhead gate's A/B switch)."""
    global _ENABLED
    _ENABLED = bool(flag)
    spans.TRACER.enabled = bool(flag)
