"""Decision-quality plane: certified optimality gaps, plan churn, and
per-task starvation over the scheduler seam.

PR 6 made the seam's *latency* observable; this module makes its
*decisions* observable. Everything here is computed from state the
engines already carry — the candidate structure, the carried dual
prices, the previous plan — so the quality signals are nearly free and
NEVER feed solver state (the replay-identity gate runs with the plane
on; matchings are bit-for-bit either way).

  * :func:`duality_gap` — a **certified** upper bound on how far the
    plan's cost sits above the optimal assignment on the same candidate
    support, from LP duality: with prices ``pi`` (the auction's carried
    duals, or the Sinkhorn referee's derived prices), the dual point
    ``y_p = pi_p`` (over providers reachable from assigned tasks),
    ``g_t = min_q (c(t,q) + pi_q)`` is feasible for the LP that covers
    exactly the plan's assigned task set, so

        gap = plan_cost - dual_bound
            = sum_t eps-CS slack(t) + sum_{reachable idle p} pi_p

    is a certificate, not an estimate: the true optimum lies within
    ``gap`` of the plan, whatever the engine did to get there. The
    certificate's dual point caps prices at the give-up magnitude
    (2*max_cost + 10) — any nonnegative dual certifies, and the cap
    strips the single-option bid floor's price spikes without
    loosening converged marketplaces. At auction convergence every
    slack is <= the engine eps and (on saturated marketplaces) no
    reachable provider idles, so ``gap_per_task <= eps`` — the CI gate
    holds ``<= 2x eps``.
  * :func:`plan_churn` — fraction of (valid) tasks whose provider
    changed tick-over-tick: the stability price of each warm solve,
    and the number the streaming-assignment roadmap item will gate its
    bounded-staleness contract on.
  * :func:`starvation_update` / :func:`starvation_hist` — per-task
    consecutive-ticks-unassigned ages (max + a log2-bucket histogram):
    which tasks are quietly never seated, not just how many.
  * :func:`tick_quality` — the one arena entry point folding all of the
    above plus the native outcome taxonomy
    (:data:`protocol_tpu.native.OUTCOME_NAMES`) into flat scalars that
    ride ``last_stats`` -> ObsRegistry -> OUTCOME frames -> the obs
    report.

Determinism contract: pure functions of (candidates, plan, duals) —
no clocks, no randomness (the determinism lint covers this module).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

# mirrors ops/cost.py INFEASIBLE without importing the jax-backed module
# (the quality pass runs in control-plane processes with no backend)
_INFEASIBLE = 1e9

# outcome code -> last_stats scalar key (order matters: it is the
# report's cause-table column order)
OUTCOME_STAT_KEYS = (
    (0, "outcome_assigned"),
    (1, "outcome_no_candidates"),
    (2, "outcome_outbid"),
    (3, "outcome_retired"),
)

# starvation-age histogram bucket upper bounds (ticks); the last bucket
# is open-ended. Log2-spaced: ages are a heavy-tailed signal and the
# interesting question is "how LONG has the tail been starving".
STARVE_BUCKETS = (1, 2, 4, 8, 16, 32, 64)


def duality_gap(
    cand_p: np.ndarray,
    cand_c: np.ndarray,
    p4t: np.ndarray,
    price: np.ndarray,
) -> dict:
    """Certified duality gap of a plan on its candidate support.

    ``cand_p``/``cand_c``: [T, K] candidate lists (provider id / cost,
    -1 = empty slot); ``p4t``: [T] plan (provider per task, -1 =
    unassigned); ``price``: [P] dual prices the engine carried out of
    the solve. Returns plan_cost, dual_bound, gap_total, gap_per_task
    (gap normalized by assigned count), plus the certificate's two
    addends (cs_slack, idle_price) for diagnosis.
    """
    cand_p = np.asarray(cand_p)
    cand_c = np.asarray(cand_c)
    p4t = np.asarray(p4t)
    price = np.asarray(price, np.float64)
    feas = (cand_p >= 0) & (cand_c < _INFEASIBLE * 0.5)
    # the certificate may use ANY nonnegative dual point; capping
    # prices at the engine's give-up magnitude (2*max_cost + 10, the
    # most any bidder would ever pay) strips the single-option bid
    # floor's ~1e8 price spikes without loosening converged
    # marketplaces, where every price already sits below the cap —
    # same dual point the engine's in-solve certificate pass uses
    cmax = float(cand_c[feas].max()) if feas.any() else 0.0
    price = np.minimum(price, 2.0 * cmax + 10.0)
    safe_p = np.maximum(cand_p, 0)
    adj = np.where(feas, cand_c.astype(np.float64) + price[safe_p], np.inf)
    best = adj.min(axis=1)

    rows = np.flatnonzero(p4t >= 0)
    if rows.size == 0:
        return {
            "plan_cost": 0.0, "dual_bound": 0.0, "gap_total": 0.0,
            "gap_per_task": 0.0, "cs_slack": 0.0, "idle_price": 0.0,
        }
    seat = p4t[rows]
    seat_m = (cand_p[rows] == seat[:, None]) & feas[rows]
    has_seat = seat_m.any(axis=1)
    rows = rows[has_seat]
    seat = seat[has_seat]
    j = seat_m[has_seat].argmax(axis=1)
    seat_c = cand_c[rows, j].astype(np.float64)
    seat_adj = seat_c + price[seat]
    slack = np.maximum(seat_adj - best[rows], 0.0)

    # reachable providers: any feasible candidate edge out of an
    # assigned task's row; the idle ones are the certificate's second
    # addend (a pumped price on a reachable-but-unused provider is a
    # real optimality question, not noise)
    reach = np.zeros(price.shape[0], bool)
    fr = feas[rows]
    reach[cand_p[rows][fr]] = True
    used = np.zeros(price.shape[0], bool)
    used[seat] = True
    idle_price = float(price[reach & ~used].sum())

    plan_cost = float(seat_c.sum())
    cs_slack = float(slack.sum())
    gap_total = cs_slack + idle_price
    n = int(rows.size)
    return {
        "plan_cost": round(plan_cost, 4),
        "dual_bound": round(plan_cost - gap_total, 4),
        "gap_total": round(gap_total, 6),
        "gap_per_task": round(gap_total / max(n, 1), 6),
        "cs_slack": round(cs_slack, 6),
        "idle_price": round(idle_price, 6),
    }


def plan_churn(
    prev_p4t: np.ndarray, p4t: np.ndarray, valid: Optional[np.ndarray]
) -> tuple[int, float]:
    """(rows changed, churn ratio) between two consecutive plans over
    the valid task rows — any seat change counts, including a task
    gaining or losing its seat."""
    prev_p4t = np.asarray(prev_p4t)
    p4t = np.asarray(p4t)
    changed = prev_p4t != p4t
    if valid is not None:
        v = np.asarray(valid, bool)
        changed = changed & v
        n = int(v.sum())
    else:
        n = int(p4t.shape[0])
    rows = int(changed.sum())
    return rows, round(rows / max(n, 1), 6)


def starvation_update(
    age: Optional[np.ndarray], p4t: np.ndarray, valid: Optional[np.ndarray]
) -> np.ndarray:
    """Advance the per-task consecutive-ticks-unassigned ages by one
    tick: assigned (or invalid) rows reset to 0, starving rows
    increment. ``age=None`` starts from zeros (cold solve)."""
    p4t = np.asarray(p4t)
    if age is None or np.asarray(age).shape[0] != p4t.shape[0]:
        age = np.zeros(p4t.shape[0], np.int32)
    starving = p4t < 0
    if valid is not None:
        starving = starving & np.asarray(valid, bool)
    return np.where(starving, np.asarray(age, np.int32) + 1, 0).astype(
        np.int32
    )


def starvation_hist(age: np.ndarray) -> list[int]:
    """Counts of starving tasks per :data:`STARVE_BUCKETS` age bucket
    (last bucket open-ended); zeros-only rows (not starving) excluded."""
    age = np.asarray(age)
    ages = age[age > 0]
    out: list[int] = []
    lo = 0
    for hi in STARVE_BUCKETS:
        out.append(int(((ages > lo) & (ages <= hi)).sum()))
        lo = hi
    out.append(int((ages > lo).sum()))
    return out


def aggregate_quality(tick_stats: list) -> Optional[dict]:
    """Canonical roll-up of per-tick quality scalar dicts (the
    ``tick_quality`` vocabulary, as carried by ``last_stats`` / OUTCOME
    frame metrics) — THE one implementation every surface shares
    (replay report, ``obs report``, bench): certified gap mean/max,
    plan churn mean/max over the ticks that carried it, starvation max,
    the zero-unexplained invariant the CI gate holds, and the
    outcome-cause totals (always all four taxonomy columns). ``None``
    when no tick carried quality scalars (a trace/run predating the
    plane, or obs off)."""
    qs = [s for s in tick_stats if s and s.get("gap_per_task") is not None]
    if not qs:
        return None
    gaps = [float(s["gap_per_task"]) for s in qs]
    churns = [
        float(s["churn_ratio"]) for s in qs
        if s.get("churn_ratio") is not None
    ]
    out: dict = {
        "ticks": len(qs),
        "gap_per_task_mean": round(float(np.mean(gaps)), 6),
        "gap_per_task_max": round(float(np.max(gaps)), 6),
        "plan_cost_mean": round(float(np.mean(
            [float(s.get("plan_cost", 0.0)) for s in qs]
        )), 4),
        "starve_max": int(max(int(s.get("starve_max", 0)) for s in qs)),
        "unexplained_unassigned": int(sum(
            int(s.get("outcome_unexplained", 0)) for s in qs
        )),
        "causes": {
            key.removeprefix("outcome_"): int(
                sum(int(s.get(key, 0)) for s in qs)
            )
            for _, key in OUTCOME_STAT_KEYS
        },
    }
    if churns:
        out["churn_ratio_mean"] = round(float(np.mean(churns)), 6)
        out["churn_ratio_max"] = round(float(np.max(churns)), 6)
    return out


def gap_from_certificate(
    p4t: np.ndarray,
    plan_cost: float,
    cs_slack: float,
    idle_price: float,
) -> dict:
    """Assemble the certified duality gap from the scalars the ENGINE's
    margin pass accumulated (plan cost, eps-CS slack, reachable-idle
    price — capped-price dual point) — O(1) here instead of re-scanning
    the [T, K] candidate structure. Numerically equal to
    :func:`duality_gap` up to f32 rounding (the tests cross-check the
    two)."""
    p4t = np.asarray(p4t)
    cs_slack = float(cs_slack)
    gap_total = cs_slack + float(idle_price)
    n = int((p4t >= 0).sum())
    return {
        "plan_cost": round(float(plan_cost), 4),
        "dual_bound": round(float(plan_cost) - gap_total, 4),
        "gap_total": round(gap_total, 6),
        "gap_per_task": round(gap_total / max(n, 1), 6),
        "cs_slack": round(cs_slack, 6),
        "idle_price": round(float(idle_price), 6),
    }


def tick_quality(
    cand_p: np.ndarray,
    cand_c: np.ndarray,
    p4t: np.ndarray,
    price: Optional[np.ndarray],
    valid: Optional[np.ndarray] = None,
    prev_p4t: Optional[np.ndarray] = None,
    starve_age: Optional[np.ndarray] = None,
    outcomes: Optional[dict] = None,
    eng: Optional[dict] = None,
) -> tuple[dict, np.ndarray]:
    """One tick's full quality record: (flat stats dict, new starvation
    ages). The arena calls this once per solve with the obs plane on;
    everything lands as scalars (plus the small ``starve_hist`` list)
    next to the tick's phase stats in ``last_stats``.

    When the engine's certificate scalars (``plan_cost`` /
    ``cs_slack`` / ``idle_price`` in ``eng``) are in hand the gap is
    assembled in O(1) from them; otherwise the O(T*K) reference
    :func:`duality_gap` scan runs (the jax replay path, tests).
    """
    stats: dict = {}
    have_cert = (
        eng is not None
        and "plan_cost" in eng
        and "idle_price" in eng
        and "cs_slack" in eng
    )
    if have_cert:
        stats.update(gap_from_certificate(
            p4t, eng["plan_cost"], eng["cs_slack"], eng["idle_price"],
        ))
    elif price is not None:
        stats.update(duality_gap(cand_p, cand_c, p4t, price))
    if prev_p4t is not None and np.asarray(prev_p4t).shape == np.asarray(
        p4t
    ).shape:
        rows, ratio = plan_churn(prev_p4t, p4t, valid)
        stats["churn_rows"] = rows
        stats["churn_ratio"] = ratio
    new_age = starvation_update(starve_age, p4t, valid)
    stats["starve_max"] = int(new_age.max()) if new_age.size else 0
    stats["starving"] = int((new_age > 0).sum())
    stats["starve_hist"] = starvation_hist(new_age)

    if outcomes is not None and "codes" in outcomes:
        codes = np.asarray(outcomes["codes"])
        v = (
            np.asarray(valid, bool)
            if valid is not None
            else np.ones(codes.shape[0], bool)
        )
        for code, key in OUTCOME_STAT_KEYS:
            stats[key] = int(((codes == code) & v).sum())
        # the completeness invariant the CI gate holds: every valid
        # unassigned task carries a cause code (assigned tasks are code
        # 0 by construction, so unexplained == valid unassigned rows
        # whose code claims "assigned")
        unassigned = (np.asarray(p4t) < 0) & v
        stats["outcome_unexplained"] = int(
            (unassigned & (codes == 0)).sum()
        )
        margin = outcomes.get("margin")
        if margin is not None:
            m = np.asarray(margin)[v & (np.asarray(p4t) >= 0)]
            if m.size:
                stats["win_margin_mean"] = round(float(m.mean()), 6)
                stats["win_margin_min"] = round(float(m.min()), 6)
    return stats, new_age
