"""Declarative per-tenant SLOs with multi-window burn-rate alerting.

A small, deterministic SLO engine over the seam's per-tick quality and
latency signals. Each tick the :class:`~protocol_tpu.obs.metrics.ObsRegistry`
feeds one observation per session into :meth:`SLOEngine.observe`; the
engine classifies it good/bad per objective, pushes the bit into
TICK-INDEXED windows, and fires a structured alert event when BOTH a
short and a long window burn the error budget faster than the window
pair's threshold (the classic multi-window burn-rate rule: the short
window gives fast detection, the long window keeps one-tick blips from
paging).

Objectives (any subset may be set; unset = not evaluated):

  ==================  ===============================================
  p99_warm_tick_ms    warm tick wall above this is a bad tick
  min_assigned_frac   assigned fraction below this is a bad tick
  max_starvation_age  any task starving longer than this: bad tick
  max_gap_per_task    certified duality gap per task above this: bad
  max_churn_ratio     plan churn ratio above this: bad tick
  ==================  ===============================================

Burn rate = (bad fraction over the window) / ``budget_frac``. A pair
only evaluates once BOTH its windows have filled (a half-filled window
must not page), so detection latency is floored at the pair's LONG
window: with the default 5% budget and window pairs, a sustained
20%-bad signal fires the fast pair the moment its 32-tick long window
fills; a slow 10% bleed fires the slow pair once 128 ticks are in.
Outages shorter than the fast pair's long window never page — by
design, ticks are cheap and sub-window blips are the noise the long
window exists to absorb.

DETERMINISM: windows are counted in TICKS, never wall-clock — the
engine reads no clock and holds no timestamps, so replaying a recorded
workload reproduces the exact same alert sequence (the determinism lint
enforces the no-wall-clock rule on this module). Alert events carry the
tick index; wall-clock correlation belongs to the scrape layer.
"""

from __future__ import annotations

import os
from collections import OrderedDict, deque
from itertools import islice
from dataclasses import dataclass, field
from typing import Optional

# (short window ticks, long window ticks, burn-rate threshold): both
# windows must burn >= threshold to fire; the pairs are ordered
# fast-to-slow and evaluated independently.
DEFAULT_WINDOWS = ((8, 32, 4.0), (32, 128, 2.0))

# objective catalog: (objective name, config attr, metric key, sense)
# sense "gt": metric > threshold is bad; "lt": metric < threshold is bad
_OBJECTIVES = (
    ("warm_tick_p99_ms", "p99_warm_tick_ms", "wall_ms", "gt"),
    ("assigned_frac", "min_assigned_frac", "assigned_frac", "lt"),
    ("starvation_age", "max_starvation_age", "starve_max", "gt"),
    ("gap_per_task", "max_gap_per_task", "gap_per_task", "gt"),
    ("churn_ratio", "max_churn_ratio", "churn_ratio", "gt"),
    # bounded-staleness contract (resilience plane): a tick is bad when
    # the deadline watchdog's consecutive stale-answer streak exceeds
    # the objective — sustained degradation pages, one absorbed
    # overrun does not
    ("stale_streak", "max_stale_streak", "stale_streak", "gt"),
)


@dataclass(frozen=True)
class SLOConfig:
    """Declarative objective set. All-None (the default) is inert: the
    engine records nothing and fires nothing."""

    p99_warm_tick_ms: Optional[float] = None
    min_assigned_frac: Optional[float] = None
    max_starvation_age: Optional[float] = None
    max_gap_per_task: Optional[float] = None
    max_churn_ratio: Optional[float] = None
    max_stale_streak: Optional[float] = None
    budget_frac: float = 0.05
    windows: tuple = DEFAULT_WINDOWS

    def active(self) -> bool:
        return any(
            getattr(self, attr) is not None for _, attr, _, _ in _OBJECTIVES
        )

    @classmethod
    def from_env(cls, env: Optional[dict] = None) -> "SLOConfig":
        """PROTOCOL_TPU_SLO_{P99_MS,MIN_ASSIGNED,MAX_STARVE,MAX_GAP,
        MAX_CHURN,MAX_STALE,BUDGET} — unset vars leave the objective
        off."""
        e = os.environ if env is None else env

        def _f(name: str) -> Optional[float]:
            v = e.get(f"PROTOCOL_TPU_SLO_{name}", "").strip()
            return float(v) if v else None

        return cls(
            p99_warm_tick_ms=_f("P99_MS"),
            min_assigned_frac=_f("MIN_ASSIGNED"),
            max_starvation_age=_f("MAX_STARVE"),
            max_gap_per_task=_f("MAX_GAP"),
            max_churn_ratio=_f("MAX_CHURN"),
            max_stale_streak=_f("MAX_STALE"),
            budget_frac=_f("BUDGET") or 0.05,
        )

    def snapshot(self) -> dict:
        out = {
            attr: getattr(self, attr)
            for _, attr, _, _ in _OBJECTIVES
            if getattr(self, attr) is not None
        }
        out["budget_frac"] = self.budget_frac
        out["windows"] = [list(w) for w in self.windows]
        return out


@dataclass
class _ObjectiveState:
    """Per (session, objective) burn-rate state: the tick-indexed bad
    bits plus which window pairs are currently firing."""

    bits: deque = field(default_factory=deque)
    active: list = field(default_factory=list)  # bool per window pair


class SLOEngine:
    """Evaluates one :class:`SLOConfig` across sessions. Not
    thread-safe by itself — the ObsRegistry calls it under its own
    lock, the same serialization every other per-session stat gets."""

    def __init__(self, config: SLOConfig, max_sessions: int = 512):
        self.config = config
        self.max_sessions = int(max_sessions)
        self._long_max = max(
            (w[1] for w in config.windows), default=0
        )
        # session -> objective name -> _ObjectiveState (LRU-bounded:
        # session ids are client-minted, same story as the registry)
        self._state: OrderedDict[str, dict] = OrderedDict()
        self.fired_total = 0
        self._fired_by_tenant: dict[str, int] = {}

    # ---------------- internals ----------------

    def _session_state(self, session_id: str) -> dict:
        s = self._state.get(session_id)
        if s is None:
            s = self._state[session_id] = {}
            while len(self._state) > self.max_sessions:
                self._state.popitem(last=False)
        else:
            self._state.move_to_end(session_id)
        return s

    @staticmethod
    def _burn(bits: deque, window: int, budget: float) -> Optional[float]:
        """Burn rate over the trailing ``window`` bits; None until the
        window has filled (a half-filled window must not page)."""
        n = len(bits)
        if n < window:
            return None
        # bits is bounded at the longest window, so the tail walk is a
        # few hundred ints at most — no ring bookkeeping needed
        bad = sum(islice(bits, n - window, n))
        return (bad / window) / max(budget, 1e-9)

    # ---------------- the observe step ----------------

    def observe(
        self,
        session_id: str,
        tenant: str,
        tick: int,
        metrics: dict,
        cold: bool = False,
    ) -> list[dict]:
        """Feed one session tick; returns the alert events that FIRED
        or CLEARED on this tick (usually empty). ``metrics`` keys match
        the objective catalog (wall_ms, assigned_frac, starve_max,
        gap_per_task, churn_ratio); absent keys skip their objective
        for this tick."""
        cfg = self.config
        if not cfg.active():
            return []
        state = self._session_state(session_id)
        events: list[dict] = []
        for name, attr, key, sense in _OBJECTIVES:
            threshold = getattr(cfg, attr)
            if threshold is None:
                continue
            if name == "warm_tick_p99_ms" and cold:
                continue  # latency objective is a warm-tick contract
            value = metrics.get(key)
            if value is None:
                continue
            bad = (
                value > threshold if sense == "gt" else value < threshold
            )
            st = state.get(name)
            if st is None:
                st = state[name] = _ObjectiveState(
                    bits=deque(maxlen=self._long_max),
                    active=[False] * len(cfg.windows),
                )
            st.bits.append(1 if bad else 0)
            # one burn per DISTINCT window length (the default pairs
            # share their 32-tick window), computed under the registry
            # lock the solve path also serializes on — keep it cheap
            burns = {
                w: self._burn(st.bits, w, cfg.budget_frac)
                for w in sorted({
                    w for pair in cfg.windows for w in pair[:2]
                })
            }
            for i, (short, long_w, burn_thresh) in enumerate(cfg.windows):
                burn_s = burns[short]
                burn_l = burns[long_w]
                if burn_s is None or burn_l is None:
                    continue
                firing = burn_s >= burn_thresh and burn_l >= burn_thresh
                if firing == st.active[i]:
                    continue
                st.active[i] = firing
                event = {
                    "kind": "slo",
                    "state": "fire" if firing else "clear",
                    "slo": name,
                    "session": session_id,
                    "tenant": tenant,
                    "tick": int(tick),
                    "value": value,
                    "threshold": threshold,
                    "burn_short": round(burn_s, 3),
                    "burn_long": round(burn_l, 3),
                    "window": [short, long_w],
                }
                events.append(event)
                if firing:
                    self.fired_total += 1
                    self._fired_by_tenant[tenant] = (
                        self._fired_by_tenant.get(tenant, 0) + 1
                    )
        return events

    def active_alerts(self) -> list[dict]:
        """Currently-firing (session, objective, window) triples."""
        out = []
        for sid, objectives in self._state.items():
            for name, st in objectives.items():
                for i, firing in enumerate(st.active):
                    if firing:
                        out.append({
                            "session": sid, "slo": name,
                            "window": list(self.config.windows[i][:2]),
                        })
        return out

    def snapshot(self) -> dict:
        return {
            "config": self.config.snapshot(),
            "fired_total": self.fired_total,
            "fired_by_tenant": dict(self._fired_by_tenant),
            "active": self.active_alerts(),
        }
