"""Consolidated ``/metrics`` scrape endpoint for the scheduler servicer.

One HTTP listener merges every prometheus-renderable source on the
servicer — the existing ``SeamMetrics`` registry, the per-session
:class:`~protocol_tpu.obs.metrics.ObsRegistry` (which folds in
SessionStore occupancy and EngineThreadBudget gauges at scrape time) —
into a single text exposition, so one Prometheus scrape job covers the
whole seam.

Degradation contract (same as SeamMetrics): without prometheus_client
the sources still MEASURE (their dict snapshots stay authoritative and
ride ``/metrics.json`` + the Health RPC); only the prometheus text
endpoint degrades, answering **503** with a plain-text pointer instead
of crashing or half-rendering.

Routes::

    /metrics       prometheus text (200) | 503 when prometheus is absent
    /metrics.json  the authoritative dict snapshots (always 200)
    /healthz       liveness probe
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from protocol_tpu.obs.metrics import prometheus_available


class MetricsEndpoint:
    """Daemon-threaded scrape server over a set of metric sources.

    ``prom_sources``: objects with ``render() -> bytes`` (prometheus
    text; may raise ImportError when prometheus_client is absent).
    ``json_sources``: name -> object with ``snapshot() -> dict``.
    """

    def __init__(
        self,
        prom_sources: Optional[list] = None,
        json_sources: Optional[dict] = None,
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        self.prom_sources = list(prom_sources or [])
        self.json_sources = dict(json_sources or {})
        endpoint = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet: scrapes are periodic
                pass

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    if not prometheus_available():
                        self._send(
                            503,
                            b"prometheus_client is not installed; the "
                            b"authoritative snapshot is at /metrics.json\n",
                            "text/plain; charset=utf-8",
                        )
                        return
                    chunks = []
                    for src in endpoint.prom_sources:
                        try:
                            chunks.append(src.render())
                        except ImportError:  # pragma: no cover
                            continue
                    self._send(
                        200, b"".join(chunks),
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                elif path == "/metrics.json":
                    body = json.dumps(
                        {
                            name: src.snapshot()
                            for name, src in endpoint.json_sources.items()
                        },
                        sort_keys=True,
                    ).encode()
                    self._send(200, body, "application/json")
                elif path == "/healthz":
                    self._send(200, b"ok\n", "text/plain; charset=utf-8")
                else:
                    self._send(404, b"not found\n", "text/plain")

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="obs-metrics",
            daemon=True,
        )
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


def start_for_servicer(
    servicer, host: str = "127.0.0.1", port: int = 0
) -> MetricsEndpoint:
    """Wire a servicer's seam + obs registries into one endpoint."""
    prom = []
    if getattr(servicer.seam, "registry", None) is not None:
        prom.append(servicer.seam)
    prom.append(servicer.obs)
    return MetricsEndpoint(
        prom_sources=prom,
        json_sources={"seam": servicer.seam, "obs": servicer.obs},
        host=host,
        port=port,
    )
