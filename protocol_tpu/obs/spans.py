"""Deterministic-safe structured span tracer.

A span is one timed region of the seam — candidate generation, an engine
solve, a wire encode/decode, a session-store lookup, a thread-budget
grant — recorded as a plain dict into a bounded ring buffer:

    {"name", "trace", "span", "parent", "t0_ns", "dur_ns", "attrs"}

Design constraints (the determinism lint's world view):

  * **Monotonic clock only** (``time.perf_counter_ns``): span timings
    ride NEXT TO results, never into them, and no wall-clock read ever
    happens on a solver path.
  * **Explicit IDs**: span ids come from a process-local counter and the
    trace id is ``<pid hex>.<root span id>`` — no randomness, no UUIDs,
    so two captures of the same workload produce structurally identical
    traces (timings differ, ids and nesting do not).
  * **Bounded memory**: the ring keeps the last ``capacity`` completed
    spans; producers never block and never allocate per-span beyond one
    small dict.

Nesting is thread-local (each thread has its own open-span stack), and
causality crosses the gRPC seam via one metadata header
(``x-pt-span: <trace>/<span id>``): the client injects its current
context, the servicer adopts it as the remote parent of its RPC root
span, and a client tick stitches into one causal trace across
processes. Cross-thread handoff inside a process works the same way —
pass ``header()`` and open the child with ``remote_parent=``.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Iterable, Optional

# gRPC metadata key (must be lowercase per the gRPC metadata contract)
METADATA_KEY = "x-pt-span"


class SpanTracer:
    """Ring-buffered span recorder. Thread-safe; cheap when disabled
    (one attribute check, no lock)."""

    def __init__(self, capacity: int = 4096, enabled: bool = True):
        from protocol_tpu.utils.lockwitness import make_lock

        self.enabled = enabled
        self.capacity = int(capacity)
        self._lock = make_lock("tracer")
        self._ring: deque = deque(maxlen=self.capacity)
        self._next_id = 1
        self._seq = 0  # completed spans ever (ring-overflow-proof cursor)
        self._tls = threading.local()

    # ---------------- internals ----------------

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _alloc_id(self) -> int:
        with self._lock:
            sid = self._next_id
            self._next_id += 1
            return sid

    def _record(self, rec: dict) -> None:
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            self._ring.append(rec)

    # ---------------- the span API ----------------

    @contextmanager
    def span(self, name: str, remote_parent: Optional[str] = None, **attrs):
        """Open a nested span. ``remote_parent`` is a ``header()`` string
        from another thread/process (wins over the thread-local stack —
        it's how the servicer adopts the client's context). Yields the
        open frame dict (callers may add attrs before exit)."""
        if not self.enabled:
            yield None
            return
        t0 = time.perf_counter_ns()
        stack = self._stack()
        trace = parent = None
        if remote_parent:
            trace, _, pspan = remote_parent.partition("/")
            try:
                parent = int(pspan)
            except ValueError:
                trace = parent = None
        if trace is None and stack:
            trace = stack[-1]["trace"]
            parent = stack[-1]["span"]
        sid = self._alloc_id()
        if trace is None:
            trace = f"{os.getpid():x}.{sid}"
        frame = {
            "name": name, "trace": trace, "span": sid,
            "parent": parent, "t0_ns": t0, "attrs": dict(attrs),
        }
        stack.append(frame)
        try:
            yield frame
        finally:
            t1 = time.perf_counter_ns()
            # pop by identity: a mismatched exit (generator abandoned
            # mid-span) must not corrupt an unrelated frame
            if stack and stack[-1] is frame:
                stack.pop()
            elif frame in stack:  # pragma: no cover - defensive
                stack.remove(frame)
            frame["dur_ns"] = t1 - t0
            self._record(frame)

    def record_span(
        self, name: str, t0_ns: int, dur_ns: int, **attrs
    ) -> None:
        """Record an ALREADY-TIMED region as a completed span, parented
        to the current thread's innermost open span. For callers whose
        region boundaries don't nest cleanly inside a ``with`` block
        (the arena's warm candidate-maintenance sweep)."""
        if not self.enabled:
            return
        stack = self._stack()
        trace = stack[-1]["trace"] if stack else None
        parent = stack[-1]["span"] if stack else None
        sid = self._alloc_id()
        self._record({
            "name": name, "trace": trace or f"{os.getpid():x}.{sid}",
            "span": sid, "parent": parent, "t0_ns": int(t0_ns),
            "dur_ns": int(dur_ns), "attrs": dict(attrs),
        })

    def point(self, name: str, **attrs) -> None:
        """Zero-duration event span (evictions, refusals, grants)."""
        if not self.enabled:
            return
        stack = self._stack()
        trace = stack[-1]["trace"] if stack else None
        parent = stack[-1]["span"] if stack else None
        sid = self._alloc_id()
        self._record({
            "name": name, "trace": trace or f"{os.getpid():x}.{sid}",
            "span": sid, "parent": parent,
            "t0_ns": time.perf_counter_ns(), "dur_ns": 0,
            "attrs": dict(attrs),
        })

    # ---------------- propagation ----------------

    def header(self) -> str:
        """``<trace>/<span>`` of the current thread's innermost open
        span, or "" when none is open (callers skip injection then)."""
        stack = self._stack()
        if not stack:
            return ""
        top = stack[-1]
        return f"{top['trace']}/{top['span']}"

    def inject(self, metadata=None) -> Optional[list]:
        """Append the propagation header to a gRPC metadata list.
        Returns the (possibly new) list, or the input unchanged when no
        span is open / tracing is off."""
        if not self.enabled:
            return metadata
        h = self.header()
        if not h:
            return metadata
        md = list(metadata or [])
        md.append((METADATA_KEY, h))
        return md

    @staticmethod
    def extract(metadata: Optional[Iterable]) -> Optional[str]:
        """Pull the propagation header out of gRPC invocation metadata
        (an iterable of (key, value) pairs); None when absent."""
        if metadata is None:
            return None
        for k, v in metadata:
            if k == METADATA_KEY:
                return v
        return None

    # ---------------- consumption ----------------

    def mark(self) -> int:
        """Cursor for :meth:`since` (count of spans completed so far)."""
        with self._lock:
            return self._seq

    def since(self, mark: int, trace: Optional[str] = None) -> list[dict]:
        """Completed spans with seq > ``mark`` (oldest first), optionally
        filtered to one trace id. Spans evicted by ring overflow between
        mark and now are gone — callers get what survived."""
        with self._lock:
            out = [r for r in self._ring if r["seq"] > mark]
        if trace is not None:
            out = [r for r in out if r["trace"] == trace]
        return out

    def drain(self) -> list[dict]:
        """Return and clear every buffered completed span."""
        with self._lock:
            out = list(self._ring)
            self._ring.clear()
        return out

    def snapshot(self) -> list[dict]:
        with self._lock:
            return list(self._ring)


# One process-global tracer: every seam layer (matcher, arena, servicer,
# client, replay) records into the same ring, and loopback tests see
# client + server spans side by side. Cross-process stitching happens
# through the metadata header + trace ids persisted in OUTCOME frames.
# The PROTOCOL_TPU_OBS flag has ONE owner — protocol_tpu.obs.__init__
# parses it and sets TRACER.enabled (the package __init__ always runs
# before this module is reachable).
TRACER = SpanTracer(enabled=True)


def tracer() -> SpanTracer:
    return TRACER


def span_dicts_compact(spans: list[dict]) -> list[dict]:
    """Wire/trace-frame form of a span list: drop the ring-cursor seq and
    round timings to µs so OUTCOME frames stay small."""
    out = []
    for s in spans:
        d = {
            "name": s["name"], "trace": s["trace"], "span": s["span"],
            "parent": s["parent"], "us": round(s["dur_ns"] / 1e3, 1),
        }
        if s.get("attrs"):
            d["attrs"] = s["attrs"]
        out.append(d)
    return out
