"""Per-session/per-tenant seam metrics: HDR-style latency histograms and
the registry behind the consolidated ``/metrics`` scrape.

``SeamMetrics`` (utils/metrics.py) keeps per-phase SUMS and COUNTS —
enough for a mean, useless for the latency distributions every ROADMAP
frontier is gated on (p50/p99 tick latency at hundreds of concurrent
sessions, per-event p99 µs). :class:`LatencyHistogram` fixes that with
an HdrHistogram-style log2 bucket layout (16 linear sub-buckets per
power of two => <= ~6% relative quantile error across nine decades,
O(1) record, a few KB per histogram) — true p50/p99/p999 without
storing samples.

:class:`ObsRegistry` keys histograms + gauges per session (tenant =
the session-id prefix before ``@``), tracking per tick: latency,
assigned fraction, arena reuse ratio (fraction of candidate rows NOT
recomputed — the warm-path health number), delta rows, and
EngineThreadBudget saturation. The plain-dict :meth:`snapshot` is
AUTHORITATIVE — prometheus is an optional render-time export with the
same degradation contract as SeamMetrics (no prometheus_client => the
registry still measures, only the scrape endpoint 503s).
"""

from __future__ import annotations

import math
from collections import OrderedDict, deque
from typing import Optional

try:
    from prometheus_client import CollectorRegistry, Gauge, generate_latest
except ImportError:  # pragma: no cover - minimal envs
    CollectorRegistry = Gauge = None

    def generate_latest(registry):
        raise ImportError("prometheus_client is not installed")


def prometheus_available() -> bool:
    return CollectorRegistry is not None


_SUB = 16  # linear sub-buckets per power of two


class LatencyHistogram:
    """HDR-style histogram over nanoseconds.

    Bucket index = (exponent, linear sub-bucket of the mantissa): values
    are first scaled by ``lowest_ns`` (everything below lands in bucket
    0), then ``frexp`` splits off the power of two and the mantissa's
    top bits pick one of 16 linear sub-buckets — so relative error is
    bounded by 1/16 at every magnitude, unlike fixed linear buckets.
    Quantiles come back as the sub-bucket midpoint."""

    __slots__ = ("lowest_ns", "_counts", "count", "sum_ns", "max_ns")

    def __init__(self, lowest_ns: float = 1000.0, decades: int = 9):
        # default resolution floor 1 µs, range ~1 µs .. ~18 min
        self.lowest_ns = float(lowest_ns)
        n_buckets = int(decades * math.log2(10)) * _SUB + _SUB
        self._counts = [0] * n_buckets
        self.count = 0
        self.sum_ns = 0.0
        self.max_ns = 0.0

    def _index(self, ns: float) -> int:
        v = ns / self.lowest_ns
        if v < 1.0:
            return 0
        m, e = math.frexp(v)  # v = m * 2**e, 0.5 <= m < 1
        idx = (e - 1) * _SUB + int((m - 0.5) * 2 * _SUB)
        return min(idx, len(self._counts) - 1)

    def _value(self, idx: int) -> float:
        # inverse of _index: bucket (e, sub) covers
        # [2^e * (1 + sub/16), 2^e * (1 + (sub+1)/16)) * lowest_ns;
        # report the midpoint
        e, sub = divmod(idx, _SUB)
        return self.lowest_ns * (2.0 ** e) * (1.0 + (sub + 0.5) / _SUB)

    def observe_ns(self, ns: float) -> None:
        ns = float(ns)
        self._counts[self._index(ns)] += 1
        self.count += 1
        self.sum_ns += ns
        if ns > self.max_ns:
            self.max_ns = ns

    def observe_ms(self, ms: float) -> None:
        self.observe_ns(ms * 1e6)

    def quantile_ns(self, q: float) -> float:
        """Value at quantile ``q`` (0..1); 0.0 on an empty histogram."""
        if self.count == 0:
            return 0.0
        # rank per the HdrHistogram convention: ceil(q * count), clamped
        rank = max(1, min(self.count, math.ceil(q * self.count)))
        seen = 0
        for idx, c in enumerate(self._counts):
            seen += c
            if seen >= rank:
                # bucket midpoints can overshoot the true sample; cap at
                # the recorded max so no quantile ever exceeds max_ms
                # (the HdrHistogram convention)
                return min(self._value(idx), self.max_ns)
        return self.max_ns  # pragma: no cover - unreachable

    def merge(self, other: "LatencyHistogram") -> None:
        if other.lowest_ns != self.lowest_ns or (
            len(other._counts) != len(self._counts)
        ):
            raise ValueError("histogram layouts differ")
        for i, c in enumerate(other._counts):
            self._counts[i] += c
        self.count += other.count
        self.sum_ns += other.sum_ns
        self.max_ns = max(self.max_ns, other.max_ns)

    def snapshot_ms(self) -> dict:
        """{count, mean, p50, p90, p99, p999, max} in milliseconds."""
        if self.count == 0:
            return {"count": 0}
        return {
            "count": self.count,
            "mean_ms": round(self.sum_ns / self.count / 1e6, 3),
            "p50_ms": round(self.quantile_ns(0.50) / 1e6, 3),
            "p90_ms": round(self.quantile_ns(0.90) / 1e6, 3),
            "p99_ms": round(self.quantile_ns(0.99) / 1e6, 3),
            "p999_ms": round(self.quantile_ns(0.999) / 1e6, 3),
            "max_ms": round(self.max_ns / 1e6, 3),
        }

    def snapshot_us(self) -> dict:
        """Microsecond-keyed snapshot — the stream plane's per-event
        scale, where ms rounding would flatten the whole distribution
        into its bottom bucket."""
        if self.count == 0:
            return {"count": 0}
        return {
            "count": self.count,
            "mean_us": round(self.sum_ns / self.count / 1e3, 1),
            "p50_us": round(self.quantile_ns(0.50) / 1e3, 1),
            "p90_us": round(self.quantile_ns(0.90) / 1e3, 1),
            "p99_us": round(self.quantile_ns(0.99) / 1e3, 1),
            "p999_us": round(self.quantile_ns(0.999) / 1e3, 1),
            "max_us": round(self.max_ns / 1e3, 1),
        }


def percentiles_ms(walls_ms) -> dict:
    """One-shot helper for bench emitters: feed a list of wall-clock ms
    through a histogram and return its snapshot (p50/p99/... keys)."""
    h = LatencyHistogram()
    for w in walls_ms:
        h.observe_ms(float(w))
    return h.snapshot_ms()


def tenant_of(session_id: str) -> str:
    """Tenant key of a session id: the prefix before ``@`` (sessions are
    free-form ids today; the fleet roadmap will mint ``tenant@pool``
    ids, and the registry is already keyed for it)."""
    head, sep, _ = (session_id or "").partition("@")
    return head if sep else (session_id or "unknown")


class _SessionObs:
    __slots__ = (
        "ticks", "cold_ticks", "assigned_frac", "min_assigned_frac",
        "rows_total", "rows_changed", "delta_rows",
        # quality plane (decision observability): certified duality gap,
        # plan churn, starvation ages, and the outcome-cause counters
        "gap_last", "gap_max", "churn_last", "churn_max",
        "starve_max", "starve_hist", "outcome_counts", "unexplained",
        # resilience plane: degraded (stale-plan) answers, flagged by
        # the servicer's tick-deadline watchdog, and the worst streak
        "stale_ticks", "stale_streak_max",
        # incremental candidate maintenance: full-matrix passes vs
        # repaired/rescanned rows (the candidate-generation wall's
        # headline counters — a warm fleet should hold cold_passes at
        # its cold-solve count and grow repairs, never the reverse)
        "cand_cold_passes", "cand_repaired_rows", "cand_rescan_rows",
        # stream plane: per-event apply latency (µs-scale HDR) and the
        # dedup / reconcile / divergence / repair-scope counters
        "events", "events_deduped", "events_reconciled",
        "event_divergence_max", "event_repair_rows",
        # float-pipeline provenance: which native ISA scored this
        # session's plans (last write wins — the tag is a setting)
        "native_isa",
    )

    def __init__(self):
        self.ticks = LatencyHistogram()
        self.cold_ticks = LatencyHistogram()
        self.assigned_frac = 0.0
        self.min_assigned_frac = 1.0
        self.rows_total = 0
        self.rows_changed = 0
        self.delta_rows = 0
        self.stale_ticks = 0
        self.stale_streak_max = 0
        self.gap_last: Optional[float] = None
        self.gap_max = 0.0
        self.churn_last: Optional[float] = None
        self.churn_max = 0.0
        self.starve_max = 0
        self.starve_hist: Optional[list] = None
        # cause name -> cumulative task-tick count (assigned included,
        # so fractions are computable from the counters alone)
        self.outcome_counts: Optional[dict] = None
        self.unexplained = 0
        self.cand_cold_passes = 0
        self.cand_repaired_rows = 0
        self.cand_rescan_rows = 0
        self.events = LatencyHistogram(lowest_ns=100.0)
        self.events_deduped = 0
        self.events_reconciled = 0
        self.event_divergence_max = 0
        self.event_repair_rows = 0
        self.native_isa: Optional[str] = None

    def reuse_ratio(self) -> float:
        """Fraction of candidate rows the warm path did NOT recompute."""
        if self.rows_total == 0:
            return 0.0
        return min(1.0, max(0.0, 1.0 - self.rows_changed / self.rows_total))

    def observe_quality(self, stats: dict) -> None:
        """Fold one tick's quality scalars (the arena's last_stats keys
        from obs.quality.tick_quality) into the roll-up."""
        if stats.get("stale"):
            # degraded answer: the deadline watchdog served the
            # previous plan — counted per session AND per tenant so the
            # staleness contract is auditable, not just flagged
            self.stale_ticks += 1
            self.stale_streak_max = max(
                self.stale_streak_max, int(stats.get("stale_streak") or 1)
            )
        gap = stats.get("gap_per_task")
        if gap is not None:
            self.gap_last = float(gap)
            self.gap_max = max(self.gap_max, float(gap))
        churn = stats.get("churn_ratio")
        if churn is not None:
            self.churn_last = float(churn)
            self.churn_max = max(self.churn_max, float(churn))
        if stats.get("starve_max") is not None:
            self.starve_max = max(self.starve_max, int(stats["starve_max"]))
        hist = stats.get("starve_hist")
        if hist:
            if self.starve_hist is None or len(self.starve_hist) != len(hist):
                self.starve_hist = [0] * len(hist)
            for i, c in enumerate(hist):
                self.starve_hist[i] += int(c)
        # the ONE taxonomy home is quality.OUTCOME_STAT_KEYS — a new
        # outcome code must not silently miss the per-tenant counters
        from protocol_tpu.obs.quality import OUTCOME_STAT_KEYS

        cause_keys = tuple(
            (key, key.removeprefix("outcome_"))
            for _, key in OUTCOME_STAT_KEYS
        )
        if any(stats.get(k) is not None for k, _ in cause_keys):
            if self.outcome_counts is None:
                self.outcome_counts = {name: 0 for _, name in cause_keys}
            for key, name in cause_keys:
                self.outcome_counts[name] += int(stats.get(key) or 0)
        self.unexplained += int(stats.get("outcome_unexplained") or 0)

    def quality_snapshot(self) -> Optional[dict]:
        if (
            self.gap_last is None
            and self.churn_last is None
            and self.outcome_counts is None
            and self.starve_hist is None
        ):
            return None
        out: dict = {
            "starvation": {
                "max_age": self.starve_max,
                "hist": list(self.starve_hist or []),
            },
        }
        if self.gap_last is not None:
            out["gap_per_task"] = {
                "last": round(self.gap_last, 6),
                "max": round(self.gap_max, 6),
            }
        if self.churn_last is not None:
            out["churn_ratio"] = {
                "last": round(self.churn_last, 6),
                "max": round(self.churn_max, 6),
            }
        if self.outcome_counts is not None:
            out["outcomes"] = dict(self.outcome_counts)
            out["outcomes"]["unexplained"] = self.unexplained
        return out


class ObsRegistry:
    """Per-session/per-tenant metrics + scrape-time gauges.

    The dict :meth:`snapshot` is authoritative and always available;
    :meth:`render` produces prometheus text when prometheus_client is
    installed (gauges rebuilt from the snapshot at scrape time, the
    sync_service.rs store->registry pattern) and raises ImportError
    otherwise — the endpoint turns that into a clean 503."""

    def __init__(self, role: str = "server", max_sessions: int = 512):
        self.role = role
        # LRU-bounded: session ids are client-minted (often per-process
        # uuids) and the SessionStore evicts without telling the
        # registry, so an unbounded dict would grow one _SessionObs
        # (two histograms) per uuid ever seen AND explode prometheus
        # label cardinality on every scrape. Recency-evicted at
        # ``max_sessions`` instead.
        self.max_sessions = int(max_sessions)
        from protocol_tpu.utils.lockwitness import make_lock

        self._lock = make_lock("registry")
        self._sessions: OrderedDict[str, _SessionObs] = OrderedDict()
        # per-tenant roll-up, recorded in the SAME observe_tick pass:
        # tenant histograms are true merged distributions (p50/p99 over
        # every tick the tenant's sessions ran), not an after-the-fact
        # merge of per-session quantiles — the fleet gates read these
        self._tenants: OrderedDict[str, _SessionObs] = OrderedDict()
        # scrape-time sources attached by the servicer
        self._budget = None  # EngineThreadBudget
        self._store = None  # SessionStore
        self._fleet = None  # fleet.fabric.SessionFabric
        self._admission = None  # fleet.admission.TenantAdmission
        self._registry = None
        # SLO engine (obs.slo.SLOEngine): evaluated inside observe_tick
        # under the registry lock; fired/cleared alert events land in a
        # bounded ring for the snapshot and are returned to the caller
        # (the servicer appends them to the trace as event frames)
        self._slo = None
        self._alerts = deque(maxlen=256)
        # dfleet process identity: stamps every snapshot/scrape so a
        # multi-process join (loadgen --processes, the fleet manager's
        # scrape) can tell which process it is reading without relying
        # on port bookkeeping
        self._proc_id = None

    def attach(
        self, budget=None, store=None, fleet=None, admission=None,
        slo=None, proc_id=None,
    ) -> None:
        if budget is not None:
            self._budget = budget
        if store is not None:
            self._store = store
        if fleet is not None:
            self._fleet = fleet
        if admission is not None:
            self._admission = admission
        if slo is not None:
            self._slo = slo
        if proc_id is not None:
            self._proc_id = str(proc_id)

    # ---------------- recording ----------------

    def _entry(self, store: OrderedDict, key: str) -> _SessionObs:
        """Get-or-create with LRU bounding — one policy for both the
        per-session and per-tenant registries (keys are client-minted,
        so both need the recency cap)."""
        s = store.get(key)
        if s is None:
            s = store[key] = _SessionObs()
            while len(store) > self.max_sessions:
                store.popitem(last=False)
        else:
            store.move_to_end(key)
        return s

    def observe_tick(
        self,
        session_id: str,
        wall_ms: float,
        n_tasks: int,
        num_assigned: int,
        arena_stats: Optional[dict] = None,
        delta_rows: int = 0,
        cold: Optional[bool] = None,
    ) -> list:
        """One solve tick for one session: latency, assigned fraction,
        the reuse ratio inputs, and (when present in ``arena_stats``)
        the quality-plane scalars — certified gap, churn, starvation,
        outcome causes. Returns the SLO alert events this tick fired or
        cleared (empty without an attached SLO engine / breach), so the
        caller can append them to the trace as event frames.

        No ``arena_stats`` means a STATELESS kernel (auction/topk/...):
        every such tick is a full solve — classified cold, and excluded
        from the reuse ratio (a path with no warm carry must not read
        as perfectly warm)."""
        stats = arena_stats or {}
        if cold is None:
            cold = bool(stats.get("cold", True)) if stats else True
        frac = min(1.0, num_assigned / n_tasks) if n_tasks > 0 else None
        with self._lock:
            session_entry = self._entry(self._sessions, session_id)
            # tick index = ticks this session observed BEFORE this one
            # (0-based, matching trace/report tick numbering): cold +
            # warm, deterministic, replay-stable — never wall-clock
            tick = (
                session_entry.ticks.count + session_entry.cold_ticks.count
            )
            for s in (
                session_entry,
                self._entry(self._tenants, tenant_of(session_id)),
            ):
                (s.cold_ticks if cold else s.ticks).observe_ms(wall_ms)
                if frac is not None:
                    # clamp: the one-to-many "best" kernel counts
                    # assigned PROVIDERS, which can exceed the task
                    # count — the gauge stays a fraction
                    s.assigned_frac = frac
                    s.min_assigned_frac = min(s.min_assigned_frac, frac)
                if stats:
                    # the arena reports row counts over its PADDED
                    # (pow2) batch; mixing them with the real n_tasks
                    # would push the ratio out of [0, 1] on non-pow2
                    # batches
                    rows = int(stats.get("rows", n_tasks))
                    if rows > 0:
                        s.rows_total += rows
                        s.rows_changed += int(
                            stats.get("changed_rows", rows if cold else 0)
                        )
                    s.cand_cold_passes += int(
                        stats.get("cand_cold_passes", 1 if cold else 0)
                    )
                    s.cand_repaired_rows += int(
                        stats.get("eng_cand_repair_rows", 0)
                    )
                    s.cand_rescan_rows += int(
                        stats.get("eng_cand_repair_rescans", 0)
                    )
                    s.observe_quality(stats)
                    isa = stats.get("native_isa")
                    if isa is not None:
                        s.native_isa = str(isa)
                s.delta_rows += int(delta_rows)
            alerts: list = []
            if self._slo is not None:
                alerts = self._slo.observe(
                    session_id, tenant_of(session_id), tick,
                    {
                        "wall_ms": wall_ms,
                        "assigned_frac": frac,
                        "starve_max": stats.get("starve_max"),
                        "gap_per_task": stats.get("gap_per_task"),
                        "churn_ratio": stats.get("churn_ratio"),
                        # stateful (session) ticks always carry a
                        # streak value — 0 on fresh solves — so the
                        # stale SLO objective sees every tick, not just
                        # degraded ones; stateless kernels (no stats)
                        # pass None = not evaluated
                        "stale_streak": (
                            int(stats.get("stale_streak") or 0)
                            if stats else None
                        ),
                    },
                    cold=cold,
                )
                for a in alerts:
                    self._alerts.append(a)
        return alerts

    def observe_event(
        self,
        session_id: str,
        wall_ms: float,
        deduped: bool = False,
        reconciled: bool = False,
        divergence_rows: int = 0,
        repair_rows: int = 0,
    ) -> None:
        """One STREAM event for one session: per-event apply latency
        (µs-scale histogram), dedup/reconcile counters, divergence vs
        the last reconciled plan, and the repair scope. Recorded per
        session AND per tenant, like observe_tick."""
        with self._lock:
            for s in (
                self._entry(self._sessions, session_id),
                self._entry(self._tenants, tenant_of(session_id)),
            ):
                s.events.observe_ms(wall_ms)
                if deduped:
                    s.events_deduped += 1
                if reconciled:
                    s.events_reconciled += 1
                s.event_divergence_max = max(
                    s.event_divergence_max, int(divergence_rows)
                )
                s.event_repair_rows += int(repair_rows)

    def forget(self, session_id: str) -> None:
        """Drop one session's metrics (optional — the LRU cap already
        bounds the registry; use when a tenant's history must go now)."""
        with self._lock:
            self._sessions.pop(session_id, None)

    # ---------------- export ----------------

    def snapshot(self) -> dict:
        """Authoritative nested snapshot: per-session histograms +
        fleet-level gauges. Works with or without prometheus."""
        def _one(s: _SessionObs, key: str) -> dict:
            out = {
                "tenant": tenant_of(key),
                "tick": s.ticks.snapshot_ms(),
                "cold_tick": s.cold_ticks.snapshot_ms(),
                "assigned_frac": round(s.assigned_frac, 4),
                "min_assigned_frac": round(s.min_assigned_frac, 4),
                "arena_reuse_ratio": round(s.reuse_ratio(), 4),
                "delta_rows": s.delta_rows,
            }
            if s.native_isa is not None:
                out["native_isa"] = s.native_isa
            if s.stale_ticks:
                out["stale_ticks"] = s.stale_ticks
                out["stale_streak_max"] = s.stale_streak_max
            if s.cand_cold_passes or s.cand_repaired_rows:
                out["candidates"] = {
                    "cold_passes": s.cand_cold_passes,
                    "repaired_rows": s.cand_repaired_rows,
                    "rescan_rows": s.cand_rescan_rows,
                }
            if s.events.count:
                out["stream"] = {
                    "event": s.events.snapshot_us(),
                    "deduped": s.events_deduped,
                    "reconciled": s.events_reconciled,
                    "divergence_rows_max": s.event_divergence_max,
                    "repair_rows": s.event_repair_rows,
                }
            quality = s.quality_snapshot()
            if quality is not None:
                out["quality"] = quality
            return out

        with self._lock:
            sessions = {
                sid: _one(s, sid) for sid, s in self._sessions.items()
            }
            tenants = {
                t: _one(s, t) for t, s in self._tenants.items()
            }
            # SLO engine + alert ring are registry state mutated under
            # this lock by observe_tick — snapshot them here too, or a
            # scrape races "OrderedDict mutated during iteration"
            slo_snap: Optional[dict] = None
            if self._slo is not None:
                slo_snap = self._slo.snapshot()
                slo_snap["recent"] = list(self._alerts)[-32:]
        out: dict = {
            "role": self.role, "sessions": sessions, "tenants": tenants,
        }
        if self._proc_id is not None:
            out["proc_id"] = self._proc_id
        budget = self._budget
        if budget is not None:
            avail = budget.available
            out["budget"] = {
                "total": budget.total,
                "available": avail,
                "saturation": round(
                    1.0 - max(avail, 0) / max(budget.total, 1), 4
                ),
                "grants": getattr(budget, "grants", 0),
                "degraded_grants": getattr(budget, "degraded_grants", 0),
                "min_avail": getattr(budget, "min_avail", avail),
            }
        if budget is not None and hasattr(budget, "fairness_index"):
            # FairThreadBudget: the fairness gauge + per-tenant grants
            out["budget"]["fairness_index"] = budget.fairness_index()
            out["budget"]["tenants"] = budget.tenant_snapshot()
        store = self._store
        if store is not None:
            out["session_store"] = {
                "active": len(store),
                "max_sessions": store.max_sessions,
                "evictions": store.evictions,
                "expirations": store.expirations,
            }
        fleet = self._fleet
        if fleet is not None:
            out["fleet"] = fleet.snapshot()
        admission = self._admission
        if admission is not None:
            out["admission"] = admission.snapshot()
        if slo_snap is not None:
            out["slo"] = slo_snap
        return out

    def render(self) -> bytes:
        """Prometheus text exposition, rebuilt from the snapshot at
        scrape time. Raises ImportError when prometheus_client is
        absent (the endpoint's 503 path)."""
        if CollectorRegistry is None:
            raise ImportError("prometheus_client is not installed")
        reg = CollectorRegistry()
        role = self.role
        g_tick = Gauge(
            "scheduler_obs_tick_latency_ms",
            "Per-session tick latency quantiles (warm ticks)",
            ["role", "session", "tenant", "quantile"],
            registry=reg,
        )
        g_ticks = Gauge(
            "scheduler_obs_ticks_total",
            "Warm ticks observed per session",
            ["role", "session", "tenant"],
            registry=reg,
        )
        g_frac = Gauge(
            "scheduler_obs_assigned_frac",
            "Assigned fraction at the last tick",
            ["role", "session", "tenant"],
            registry=reg,
        )
        g_reuse = Gauge(
            "scheduler_obs_arena_reuse_ratio",
            "Fraction of candidate rows NOT recomputed (warm health)",
            ["role", "session", "tenant"],
            registry=reg,
        )
        snap = self.snapshot()
        for sid, s in snap["sessions"].items():
            labels = dict(role=role, session=sid, tenant=s["tenant"])
            tick = s["tick"]
            if tick.get("count"):
                for q in ("p50", "p90", "p99", "p999"):
                    g_tick.labels(**labels, quantile=q).set(
                        tick[f"{q}_ms"]
                    )
                g_ticks.labels(**labels).set(tick["count"])
            g_frac.labels(**labels).set(s["assigned_frac"])
            g_reuse.labels(**labels).set(s["arena_reuse_ratio"])
        if "budget" in snap:
            b = snap["budget"]
            g_sat = Gauge(
                "scheduler_obs_thread_budget_saturation",
                "EngineThreadBudget in-use fraction", ["role"],
                registry=reg,
            )
            g_sat.labels(role=role).set(b["saturation"])
            g_deg = Gauge(
                "scheduler_obs_thread_budget_degraded_grants",
                "Grants smaller than requested", ["role"], registry=reg,
            )
            g_deg.labels(role=role).set(b["degraded_grants"])
        if "session_store" in snap:
            st = snap["session_store"]
            g_occ = Gauge(
                "scheduler_obs_session_store_occupancy",
                "SessionStore state", ["role", "state"], registry=reg,
            )
            g_occ.labels(role=role, state="active").set(st["active"])
            g_occ.labels(role=role, state="evictions").set(st["evictions"])
            g_occ.labels(role=role, state="expirations").set(
                st["expirations"]
            )
        if snap.get("tenants"):
            g_ten = Gauge(
                "scheduler_obs_tenant_tick_latency_ms",
                "Per-tenant tick latency quantiles (warm ticks, merged "
                "over the tenant's sessions)",
                ["role", "tenant", "quantile"], registry=reg,
            )
            g_ten_frac = Gauge(
                "scheduler_obs_tenant_assigned_frac",
                "Per-tenant minimum assigned fraction",
                ["role", "tenant"], registry=reg,
            )
            g_gap = Gauge(
                "scheduler_obs_tenant_duality_gap_per_task",
                "Certified duality gap per assigned task (quality plane)",
                ["role", "tenant", "agg"], registry=reg,
            )
            g_churn = Gauge(
                "scheduler_obs_tenant_plan_churn_ratio",
                "Fraction of tasks whose provider changed tick-over-tick",
                ["role", "tenant", "agg"], registry=reg,
            )
            g_starve = Gauge(
                "scheduler_obs_tenant_starvation_age_max",
                "Longest consecutive-ticks-unassigned age observed",
                ["role", "tenant"], registry=reg,
            )
            g_cause = Gauge(
                "scheduler_obs_tenant_task_outcomes_total",
                "Cumulative per-task decision outcomes by cause",
                ["role", "tenant", "cause"], registry=reg,
            )
            for t, s in snap["tenants"].items():
                tick = s["tick"]
                if tick.get("count"):
                    for q in ("p50", "p90", "p99", "p999"):
                        g_ten.labels(
                            role=role, tenant=t, quantile=q
                        ).set(tick[f"{q}_ms"])
                g_ten_frac.labels(role=role, tenant=t).set(
                    s["min_assigned_frac"]
                )
                quality = s.get("quality")
                if not quality:
                    continue
                gap = quality.get("gap_per_task")
                if gap:
                    g_gap.labels(role=role, tenant=t, agg="last").set(
                        gap["last"]
                    )
                    g_gap.labels(role=role, tenant=t, agg="max").set(
                        gap["max"]
                    )
                churn = quality.get("churn_ratio")
                if churn:
                    g_churn.labels(role=role, tenant=t, agg="last").set(
                        churn["last"]
                    )
                    g_churn.labels(role=role, tenant=t, agg="max").set(
                        churn["max"]
                    )
                g_starve.labels(role=role, tenant=t).set(
                    quality["starvation"]["max_age"]
                )
                for cause, count in (quality.get("outcomes") or {}).items():
                    g_cause.labels(role=role, tenant=t, cause=cause).set(
                        count
                    )
        if "fleet" in snap:
            fl = snap["fleet"]
            g_shard = Gauge(
                "scheduler_obs_fleet_shard_sessions",
                "Sessions pinned per fabric shard",
                ["role", "shard"], registry=reg,
            )
            for i, n in enumerate(fl["shards"]):
                g_shard.labels(role=role, shard=str(i)).set(n)
            g_bytes = Gauge(
                "scheduler_obs_fleet_arena_bytes",
                "Estimated pinned arena bytes", ["role", "tenant"],
                registry=reg,
            )
            g_bytes.labels(role=role, tenant="_total").set(
                fl["total_bytes"]
            )
            for t, b in fl["tenant_bytes"].items():
                g_bytes.labels(role=role, tenant=t).set(b)
            g_prs = Gauge(
                "scheduler_obs_fleet_pressure_evictions",
                "Sessions evicted by cross-shard memory pressure",
                ["role"], registry=reg,
            )
            g_prs.labels(role=role).set(fl["pressure_evictions"])
        if "admission" in snap:
            g_adm = Gauge(
                "scheduler_obs_fleet_admission_total",
                "Per-tenant admission decisions",
                ["role", "tenant", "outcome"], registry=reg,
            )
            for t, c in snap["admission"]["tenants"].items():
                g_adm.labels(role=role, tenant=t, outcome="admitted").set(
                    c["admitted"]
                )
                g_adm.labels(role=role, tenant=t, outcome="refused").set(
                    c["refused"]
                )
        if snap.get("budget", {}).get("fairness_index") is not None:
            g_fair = Gauge(
                "scheduler_obs_thread_budget_fairness_index",
                "Jain fairness index over per-tenant granted threads",
                ["role"], registry=reg,
            )
            g_fair.labels(role=role).set(snap["budget"]["fairness_index"])
        if "slo" in snap:
            slo = snap["slo"]
            g_slo = Gauge(
                "scheduler_obs_slo_alerts_fired_total",
                "Multi-window burn-rate SLO alerts fired",
                ["role", "tenant"], registry=reg,
            )
            g_slo.labels(role=role, tenant="_total").set(
                slo["fired_total"]
            )
            for t, n in slo["fired_by_tenant"].items():
                g_slo.labels(role=role, tenant=t).set(n)
            g_slo_active = Gauge(
                "scheduler_obs_slo_alerts_active",
                "Currently-firing SLO alerts", ["role"], registry=reg,
            )
            g_slo_active.labels(role=role).set(len(slo["active"]))
        return generate_latest(reg)
