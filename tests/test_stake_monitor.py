"""Worker stake / chain-event monitor (VERDICT r2 item 8).

Reference: provider.rs:47-147 (continuous stake-sufficiency watch) and
compute_node.rs:32-115 (compute-node chain events). Done-bar: a mid-run
slash triggers the worker's alarm path.
"""

import pytest

# Environment guard: this module's import chain reaches
# protocol_tpu.security / protocol_tpu.utils.tls, which need the
# third-party `cryptography` package (wallet signing + TLS material).
# On hosts without it, report the whole module as SKIPPED instead of a
# collection error (tier-1 keeps an honest skip count; CI installs
# cryptography and runs everything).
pytest.importorskip(
    "cryptography", reason="cryptography not installed (signing/TLS dependency)"
)

from protocol_tpu.chain.ledger import Ledger
from protocol_tpu.models import ComputeSpecs, CpuSpecs, GpuSpecs
from protocol_tpu.security.wallet import Wallet
from protocol_tpu.services.worker import WorkerAgent


def specs():
    return ComputeSpecs(
        gpu=GpuSpecs(count=8, model="H100", memory_mb=80000),
        cpu=CpuSpecs(cores=32),
        ram_mb=65536,
        storage_gb=1000,
    )


def build_agent():
    ledger = Ledger()
    creator, manager = Wallet.from_seed(b"c"), Wallet.from_seed(b"m")
    did = ledger.create_domain("d", validation_logic="any")
    pid = ledger.create_pool(did, creator.address, manager.address, "")
    ledger.start_pool(pid, creator.address)
    provider, node = Wallet.from_seed(b"p"), Wallet.from_seed(b"n")
    ledger.mint(provider.address, 1000)
    agent = WorkerAgent(
        provider_wallet=provider,
        node_wallet=node,
        ledger=ledger,
        pool_id=pid,
        compute_specs=specs(),
    )
    agent.register_on_ledger()
    ledger.whitelist_provider(provider.address)
    return ledger, agent, creator, manager


class TestStakeMonitor:
    def test_steady_state_no_alarms(self):
        _, agent, _, _ = build_agent()
        assert agent.stake_monitor_once() == []
        assert agent.stake_monitor_once() == []

    def test_mid_run_slash_triggers_alarm(self):
        import time

        from protocol_tpu.chain.ledger import invite_digest

        ledger, agent, _, manager = build_agent()
        # join the pool so work can be submitted, then slash through the
        # real penalty path (invalidate_work with a penalty IS the
        # ledger's stake slash, prime_network semantics)
        provider = agent.provider_wallet.address
        node = agent.node_wallet.address
        ledger.validate_node(node)
        nonce, exp = "a" * 16, time.time() + 60
        sig = manager.sign_message(
            invite_digest(0, agent.pool_id, node, nonce, exp)
        )
        ledger.join_compute_pool(agent.pool_id, provider, node, nonce, exp, sig)
        agent.stake_monitor_once()  # establish baseline
        ledger.submit_work(agent.pool_id, node, "deadbeef" * 8, 10)
        ledger.invalidate_work(
            agent.pool_id, "deadbeef" * 8, penalty=ledger.get_stake(provider)
        )
        alarms = agent.stake_monitor_once()
        assert any("stake" in a and "below required" in a for a in alarms)
        assert agent.chain_alarms  # accumulated for the control surface
        # a transition alarms ONCE, not every tick
        assert agent.stake_monitor_once() == []

    def test_whitelist_revocation_alarm(self):
        ledger, agent, _, _ = build_agent()
        agent.stake_monitor_once()
        # the ledger has no un-whitelist op (parity with the wrappers);
        # simulate the chain-state drift directly
        ledger.get_provider(agent.provider_wallet.address).whitelisted = False
        alarms = agent.stake_monitor_once()
        assert any("whitelist" in a for a in alarms)

    def test_deregistration_stops_heartbeats(self):
        ledger, agent, _, _ = build_agent()
        agent.heartbeat_active = True
        agent.stake_monitor_once()
        ledger.remove_compute_node(
            agent.provider_wallet.address, agent.node_wallet.address
        )
        alarms = agent.stake_monitor_once()
        assert any("deregistered" in a for a in alarms)
        assert agent.heartbeat_active is False

    def test_ejection_from_pool_alarm(self):
        import time

        from protocol_tpu.chain.ledger import invite_digest

        ledger, agent, creator, manager = build_agent()
        # join the pool exactly as the invite flow does (invite.rs:86-115)
        ledger.validate_node(agent.node_wallet.address)
        nonce, exp = "a" * 16, time.time() + 60
        digest = invite_digest(
            0, agent.pool_id, agent.node_wallet.address, nonce, exp
        )
        sig = manager.sign_message(digest)
        ledger.join_compute_pool(
            agent.pool_id,
            agent.provider_wallet.address,
            agent.node_wallet.address,
            nonce,
            exp,
            sig,
        )
        agent.stake_monitor_once()  # baseline with in_pool=True
        ledger.eject_node(agent.pool_id, agent.node_wallet.address, manager.address)
        alarms = agent.stake_monitor_once()
        assert any("pool" in a for a in alarms)

    def test_chain_error_is_alarm_not_crash(self):
        _, agent, _, _ = build_agent()

        class Boom:
            def __getattr__(self, name):
                raise RuntimeError("rpc down")

        agent.ledger = Boom()
        alarms = agent.stake_monitor_once()
        assert any("chain monitor error" in a for a in alarms)
