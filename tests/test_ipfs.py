"""IPFS artifact mirroring (reference worker file_handler.rs:109-118,
342-352) against a fake kubo /api/v0/add endpoint."""

import asyncio
import hashlib

import aiohttp
from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from protocol_tpu.utils.ipfs import IpfsMirror


def run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def make_kubo(fail: bool = False):
    added: list[dict] = []

    async def add(request):
        if fail:
            return web.Response(status=500)
        assert request.query.get("raw-leaves") == "true"
        reader = await request.multipart()
        part = await reader.next()
        data = await part.read()
        cid = "bafk" + hashlib.sha256(data).hexdigest()[:20]
        added.append({"name": part.filename, "bytes": data, "cid": cid})
        return web.json_response({"Hash": cid, "Size": str(len(data))})

    app = web.Application()
    app.router.add_post("/api/v0/add", add)
    app["added"] = added
    return app


def test_add_returns_cid_and_pins_bytes():
    app = make_kubo()

    async def flow():
        async with TestClient(TestServer(app)) as client:
            m = IpfsMirror("", http=client)
            cid = await m.add(b"artifact-bytes", file_name="out.parquet")
            return cid, m

    cid, m = run(flow())
    assert cid and cid.startswith("bafk")
    assert m.mirrored == 1 and m.failed == 0
    assert app["added"][0]["bytes"] == b"artifact-bytes"
    assert app["added"][0]["name"] == "out.parquet"


def test_down_daemon_is_best_effort():
    app = make_kubo(fail=True)

    async def flow():
        async with TestClient(TestServer(app)) as client:
            m = IpfsMirror("", http=client)
            return await m.add(b"x"), m

    cid, m = run(flow())
    assert cid is None and m.failed == 1



import importlib.util

import pytest

# Environment guard for the marked tests below: their code paths reach
# protocol_tpu.chain / protocol_tpu.security (wallet signing), which
# need the third-party `cryptography` package. Without it they skip —
# the rest of this module runs everywhere.
_HAS_CRYPTO = importlib.util.find_spec("cryptography") is not None
requires_crypto = pytest.mark.skipif(
    not _HAS_CRYPTO,
    reason="cryptography not installed (signing/TLS dependency)",
)

@requires_crypto
def test_worker_upload_mirrors_to_ipfs():
    """submit_output mirrors the artifact after the primary signed-URL
    upload; a dead IPFS daemon never fails the work submission."""
    import time

    from aiohttp.test_utils import TestServer as TS

    from protocol_tpu.chain import Ledger
    from protocol_tpu.chain.ledger import invite_digest
    from protocol_tpu.security import Wallet
    from protocol_tpu.services.orchestrator import OrchestratorService
    from protocol_tpu.services.worker import MockRuntime, WorkerAgent
    from protocol_tpu.store import NodeStatus, OrchestratorNode

    ledger = Ledger()
    creator, manager = Wallet.from_seed(b"ic"), Wallet.from_seed(b"im")
    provider, node = Wallet.from_seed(b"ip"), Wallet.from_seed(b"iw")
    ledger.mint(provider.address, 1000)
    did = ledger.create_domain("d")
    pid = ledger.create_pool(did, creator.address, manager.address, "")
    ledger.start_pool(pid, creator.address)
    ledger.register_provider(provider.address, 100)
    ledger.add_compute_node(provider.address, node.address)
    ledger.validate_node(node.address)
    exp = time.time() + 60
    sig = manager.sign_message(invite_digest(0, pid, node.address, "n", exp))
    ledger.join_compute_pool(pid, provider.address, node.address, "n", exp, sig)

    import tempfile

    from protocol_tpu.utils.storage import LocalDirStorageProvider

    storage = LocalDirStorageProvider(tempfile.mkdtemp())
    kubo = make_kubo()

    async def flow():
        orch = OrchestratorService(ledger, pid, manager, storage=storage)
        orch.store.node_store.add_node(
            OrchestratorNode(address=node.address, status=NodeStatus.HEALTHY)
        )
        orch_server = TS(orch.make_app())
        await orch_server.start_server()
        # signed URLs must point at the live orchestrator upload endpoint
        storage.public_base_url = str(orch_server.make_url("/")).rstrip("/")
        kubo_server = TS(kubo)
        await kubo_server.start_server()
        async with aiohttp.ClientSession() as session:
            mirror = IpfsMirror(
                str(kubo_server.make_url("/")).rstrip("/"), http=session
            )
            agent = WorkerAgent(
                provider, node, ledger, pid, runtime=MockRuntime(),
                http=session, ipfs=mirror,
            )
            agent.orchestrator_url = str(orch_server.make_url("/")).rstrip("/")
            agent.heartbeat_active = True
            ok = await agent.submit_output(
                sha="ab" * 32, flops=7, file_name="a.bin", data=b"bytes"
            )
        await orch_server.close()
        await kubo_server.close()
        return ok, mirror

    ok, mirror = run(flow())
    assert ok
    assert mirror.mirrored == 1
    assert kubo["added"][0]["bytes"] == b"bytes"
    assert ledger.get_work_info(pid, "ab" * 32).work_units == 7
