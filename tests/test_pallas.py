"""Pallas fused candidate kernel: interpret-mode parity with the XLA path.

(Interpret mode runs the kernel logic on CPU; native Mosaic compilation is
exercised on real TPU hardware where available.)"""

import numpy as np
import pytest

from protocol_tpu.ops.cost import CostWeights
from protocol_tpu.ops.pallas_kernels import candidates_topk_pallas
from protocol_tpu.ops.sparse import candidates_topk

from tests.test_sparse import encode_random_marketplace


@pytest.mark.parametrize("seed", [0, 1])
def test_interpret_parity_with_xla_path(seed):
    ep, er = encode_random_marketplace(seed, 32, 16)
    xp, xc = candidates_topk(ep, er, CostWeights(), k=8, tile=16)
    pp, pc = candidates_topk_pallas(
        ep, er, CostWeights(), k=8, provider_block=16, interpret=True
    )
    np.testing.assert_array_equal(np.asarray(pp), np.asarray(xp))
    feas = np.asarray(xp) >= 0
    np.testing.assert_allclose(
        np.asarray(pc)[feas], np.asarray(xc)[feas], rtol=1e-5
    )


def test_block_divisibility_enforced():
    ep, er = encode_random_marketplace(2, 24, 8)
    with pytest.raises(ValueError):
        candidates_topk_pallas(ep, er, k=4, provider_block=16, interpret=True)
