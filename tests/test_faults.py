"""Chaos plane + crash-safe session recovery (ISSUE 9).

Covers the resilience contracts the chaos CI gate rests on, at unit
grain: the seeded fault schedule's byte-replayability, input hardening
at the wire (NaN/Inf costs, ragged columns, dtype-mangled TensorBlobs
refused at decode, BEFORE a session arena can be poisoned), deadline
propagation (the matcher sizes per-RPC deadlines to the tick budget;
the servicer refuses dead/burned contexts before dispatching a solve),
graceful drain (stop admitting, flush checkpoints, restart resumes
warm), and the client fallback ladder under DIRTY failures —
mid-stream connection reset during OpenSession, a truncated snapshot
chunk, and a delta answered then dropped before the response — with
the shadow-column state asserted equal to the server's after every
recovery. The end-to-end seeded drill (kill + drop + delay + blackout
over the committed golden trace) lives in ``perf_gate.py --chaos``.
"""

import numpy as np
import pytest

import grpc

from protocol_tpu import native
from protocol_tpu.faults.inject import FaultInjectedError, corrupt_request
from protocol_tpu.faults.plan import ChaosConfig, FaultSchedule, NO_FAULT
from protocol_tpu.fleet.fabric import FleetConfig
from protocol_tpu.proto import scheduler_pb2 as pb
from protocol_tpu.proto import wire
from protocol_tpu.services.scheduler_grpc import (
    RemoteBatchMatcher,
    SchedulerBackendClient,
    drain,
    serve,
)
from protocol_tpu.trace import format as tfmt

from tests.test_scheduler_grpc import _pool_world

NATIVE = native.available()


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---------------- the seeded fault schedule ----------------


class TestFaultSchedule:
    def test_same_seed_replays_the_identical_fault_train(self):
        cfg = ChaosConfig(
            seed=7, drop_rate=0.1, delay_rate=0.1, corrupt_rate=0.05,
            truncate_rate=0.05, duplicate_rate=0.1,
        )
        a = [
            FaultSchedule(cfg).decide("client", "AssignDelta", i)
            for i in range(300)
        ]
        b = [
            FaultSchedule(cfg).decide("client", "AssignDelta", i)
            for i in range(300)
        ]
        assert a == b
        assert any(not act.clean for act in a)
        assert any(act.clean for act in a)

    def test_seed_changes_the_train(self):
        mk = lambda seed: [
            FaultSchedule(
                ChaosConfig(seed=seed, drop_rate=0.2)
            ).decide("client", "AssignDelta", i)
            for i in range(200)
        ]
        assert mk(1) != mk(2)

    def test_inert_default_decides_no_fault(self):
        sched = FaultSchedule(ChaosConfig())
        assert not ChaosConfig().active()
        assert all(
            sched.decide("client", m, i) == NO_FAULT
            for m in ("AssignDelta", "OpenSession")
            for i in range(50)
        )

    def test_spec_roundtrip_and_rejections(self):
        cfg = ChaosConfig(
            seed=3, drop_rate=0.05, delay_rate=0.05, delay_ms=2.0,
            kill_at_tick=4, blackout_shard=1,
        )
        assert ChaosConfig.from_spec(cfg.spec()) == cfg
        assert ChaosConfig.from_env({"PROTOCOL_TPU_CHAOS": ""}) is None
        assert ChaosConfig.from_env(
            {"PROTOCOL_TPU_CHAOS": "seed=9,drop=0.5"}
        ) == ChaosConfig(seed=9, drop_rate=0.5)
        with pytest.raises(ValueError, match="unknown chaos knob"):
            ChaosConfig.from_spec("seed=1,warp=0.5")
        with pytest.raises(ValueError, match="not key=value"):
            ChaosConfig.from_spec("drop")

    def test_corrupt_byte_is_in_range_with_nonzero_mask(self):
        sched = FaultSchedule(ChaosConfig(seed=5, corrupt_rate=1.0))
        for i in range(64):
            off, mask = sched.corrupt_byte("client", "AssignDelta", i, 37)
            assert 0 <= off < 37
            assert mask != 0  # a no-op flip is not a fault


# ---------------- input hardening at the wire ----------------


def _market_cols(seed=0, P=16, T=12):
    import bench

    rng = np.random.default_rng(seed)
    ep = bench.synth_providers(rng, P)
    er = bench.synth_requirements(rng, T)
    p_cols = wire.canon_columns(ep, wire.P_WIRE_DTYPES)
    r_cols = wire.canon_columns(er, wire.R_WIRE_DTYPES)
    return p_cols, r_cols


class TestInputHardening:
    def test_nan_cost_refused_at_decode(self):
        p_cols, _ = _market_cols()
        p_cols["price"] = p_cols["price"].copy()
        p_cols["price"][3] = np.nan
        msg = wire.encode_providers_v2(tfmt._as_ns(p_cols))
        with pytest.raises(ValueError, match="non-finite"):
            wire.decode_providers_v2(msg)

    def test_inf_cost_refused_at_decode(self):
        _, r_cols = _market_cols()
        r_cols["priority"] = r_cols["priority"].copy()
        r_cols["priority"][0] = np.inf
        msg = wire.encode_requirements_v2(tfmt._as_ns(r_cols))
        with pytest.raises(ValueError, match="non-finite"):
            wire.decode_requirements_v2(msg)

    def test_ragged_columns_refused_at_decode(self):
        p_cols, _ = _market_cols()
        msg = wire.encode_providers_v2(tfmt._as_ns(p_cols))
        for col in msg.columns:
            if col.name == "price":
                short = np.asarray(p_cols["price"][:-2], np.float32)
                col.tensor.CopyFrom(wire.blob(short, np.float32))
        with pytest.raises(ValueError, match="row-count mismatch"):
            wire.decode_providers_v2(msg)

    def test_dtype_mangled_blob_refused_at_decode(self):
        p_cols, _ = _market_cols()
        msg = wire.encode_providers_v2(tfmt._as_ns(p_cols))
        for col in msg.columns:
            if col.name == "price":
                col.tensor.dtype = "float64"  # mangled in transit
        with pytest.raises(ValueError, match="dtype mismatch"):
            wire.decode_providers_v2(msg)

    def test_corrupt_request_mutates_a_copy_not_the_original(self):
        p_cols, r_cols = _market_cols()
        req = pb.AssignRequestV2(
            providers=wire.encode_providers_v2(tfmt._as_ns(p_cols)),
            requirements=wire.encode_requirements_v2(tfmt._as_ns(r_cols)),
            kernel="native-mt", top_k=8,
        )
        before = req.SerializeToString()
        sched = FaultSchedule(ChaosConfig(seed=11, corrupt_rate=1.0))
        mutated = corrupt_request(req, sched, "client", "AssignV2", 0)
        assert mutated is not None
        assert mutated.SerializeToString() != before
        assert req.SerializeToString() == before  # sender's buffer intact
        # the contract: a corrupted frame is REFUSABLE at decode — a
        # poison that decoded to valid finite values would silently
        # apply into carried state instead
        with pytest.raises(ValueError):
            wire.decode_providers_v2(mutated.providers)
        # an int-only message shears a blob instead: size mismatch
        rows_only = pb.AssignDeltaRequest(
            session_id="x",
            provider_rows=wire.blob(np.arange(4, dtype=np.int32)),
        )
        sheared = corrupt_request(
            rows_only, sched, "client", "AssignDelta", 1
        )
        assert sheared is not None
        with pytest.raises(ValueError, match="size mismatch"):
            wire.unblob(sheared.provider_rows, np.int32)
        # an empty message carries no blob bytes: nothing to corrupt
        assert corrupt_request(
            pb.AssignDeltaRequest(session_id="x"), sched, "client",
            "AssignDelta", 0,
        ) is None


@pytest.mark.skipif(not NATIVE, reason="no native toolchain")
class TestHardeningProtectsSessionState:
    """The refusal must land BEFORE the arena: a poisoned delta aborts
    INVALID_ARGUMENT and the session's tick cursor + columns move not
    one bit."""

    def test_poisoned_delta_cannot_reach_carried_state(self):
        port = _free_port()
        addr = f"127.0.0.1:{port}"
        server = serve(addr)
        store = _pool_world()
        m = RemoteBatchMatcher(
            store, addr, min_solve_interval=0.0, wire="v2",
            native_fallback=True, native_engine="native-mt",
            native_threads=2,
        )
        try:
            m.refresh()
            st = m._session
            assert st is not None and st["tick"] == 0
            session = _server_session(server, st["id"])
            clean_price = np.array(session.p_cols["price"], copy=True)

            # a NaN-poisoned one-row delta, sent out-of-band (as a
            # mangled-in-transit frame would arrive)
            poison = wire.take_rows(st["p_cols"], np.array([0]))
            poison.price = np.array([np.nan], np.float32)
            req = pb.AssignDeltaRequest(
                session_id=st["id"], epoch_fingerprint=st["fp"], tick=1,
                provider_rows=wire.blob(np.array([0]), np.int32),
                providers=wire.encode_providers_v2(poison),
            )
            raw = SchedulerBackendClient(addr)
            try:
                with pytest.raises(grpc.RpcError) as exc:
                    raw.assign_delta(req, timeout=30)
                assert exc.value.code() == grpc.StatusCode.INVALID_ARGUMENT
            finally:
                raw.close()

            # nothing moved: cursor still 0, columns bit-identical
            assert session.tick == 0
            np.testing.assert_array_equal(
                session.p_cols["price"], clean_price
            )
            # and the session still serves: the next clean tick lands
            m.refresh()
            assert m._session["tick"] == 1
            _assert_shadow_matches_server(m, server)
        finally:
            m.client.close()
            server.stop(grace=None)

    def test_matcher_resends_once_on_corrupted_in_transit_delta(self):
        """The ladder's INVALID_ARGUMENT rung: a frame mangled on the
        wire is refused at decode (no state moved), so the matcher
        resends the SAME delta once — counted, then back to normal."""
        port = _free_port()
        addr = f"127.0.0.1:{port}"
        server = serve(addr)
        store = _pool_world()
        m = RemoteBatchMatcher(
            store, addr, min_solve_interval=0.0, wire="v2",
            native_fallback=True, native_engine="native-mt",
            native_threads=2,
        )
        try:
            m.refresh()
            m.client = _CorruptDeltaOnce(m.client)
            m.refresh()
            assert m.seam.snapshot().get("session_corrupt_resend") == 1
            assert m._session["tick"] == 1
            assert _server_session(server, m._session["id"]).tick == 1
            _assert_shadow_matches_server(m, server)
        finally:
            m.client.close()
            server.stop(grace=None)


# ---------------- wrappers (dirty-failure injectors) ----------------


class _ClientShim:
    """Pass-through client wrapper with the ``rebind`` hook, so the
    matcher's reconnect path swaps the channel UNDER the shim instead
    of discarding it (exactly what faults.inject.ChaosClient does)."""

    def __init__(self, real):
        self._real = real
        self.address = real.address

    def rebind(self, fresh) -> None:
        old, self._real = self._real, fresh
        try:
            old.close()
        except Exception:
            pass

    def assign(self, *a, **k):
        return self._real.assign(*a, **k)

    def assign_v2(self, *a, **k):
        return self._real.assign_v2(*a, **k)

    def assign_delta(self, *a, **k):
        return self._real.assign_delta(*a, **k)

    def open_session(self, *a, **k):
        return self._real.open_session(*a, **k)

    def health(self, *a, **k):
        return self._real.health(*a, **k)

    def close(self):
        self._real.close()


class _ResetMidStreamOnce(_ClientShim):
    """Mid-stream connection reset during OpenSession: the server sees
    a half-open stream die; the client sees UNAVAILABLE after having
    already shipped part of the snapshot."""

    def __init__(self, real):
        super().__init__(real)
        self.resets = 0

    def open_session(self, chunks, **k):
        if self.resets == 0:
            self.resets += 1
            next(iter(chunks))  # part of the stream left the client
            raise FaultInjectedError(details="injected mid-stream reset")
        return self._real.open_session(chunks, **k)


class _TruncateSnapshotOnce(_ClientShim):
    """A torn stream: the final snapshot chunk never arrives. The
    server must refuse (short stream), and the refusal is TRANSIENT —
    the ladder degrades one tick, never demotes permanently."""

    def __init__(self, real):
        super().__init__(real)
        self.truncated = 0

    def open_session(self, chunks, **k):
        if self.truncated == 0:
            self.truncated += 1
            chunk_list = list(chunks)[:-1]
            assert chunk_list, "need a multi-chunk snapshot to truncate"
            return self._real.open_session(iter(chunk_list), **k)
        return self._real.open_session(chunks, **k)


class _DropDeltaResponseOnce(_ClientShim):
    """The crash-protocol window in miniature: the server APPLIES the
    delta, the response dies on the wire. The retransmit must be
    answered idempotently (replayed twin), never re-applied."""

    def __init__(self, real):
        super().__init__(real)
        self.dropped = 0

    def assign_delta(self, req, **k):
        resp = self._real.assign_delta(req, **k)
        if self.dropped == 0 and resp.session_ok:
            self.dropped += 1
            raise FaultInjectedError(details="injected response drop")
        return resp


class _CorruptDeltaOnce(_ClientShim):
    """Mangle the first delta in transit: splice a NaN-poisoned
    provider row into a COPY of the request (the sender's buffer stays
    intact, like a real bit flip)."""

    def __init__(self, real):
        super().__init__(real)
        self.corrupted = 0

    def assign_delta(self, req, **k):
        if self.corrupted == 0:
            self.corrupted += 1
            mangled = pb.AssignDeltaRequest()
            mangled.CopyFrom(req)
            bad = np.full(1, np.nan, np.float32)
            mangled.provider_rows.CopyFrom(wire.blob(
                np.array([0]), np.int32
            ))
            mangled.providers.columns.add(
                name="price"
            ).tensor.CopyFrom(wire.blob(bad, np.float32))
            return self._real.assign_delta(mangled, **k)
        return self._real.assign_delta(req, **k)


def _server_session(server, session_id: str):
    for session in server.servicer.sessions.snapshot_sessions():
        if session.session_id == session_id:
            return session
    raise AssertionError(f"session {session_id} not on the server")


def _assert_shadow_matches_server(m, server) -> None:
    """The satellite's acceptance bar: after any recovery, the client's
    shadow columns must be bit-identical to the server session's
    (valid prefix — the server pads; the client shadow is stripped)."""
    st = m._session
    session = _server_session(server, st["id"])
    assert session.tick == st["tick"]
    for name, client_col in st["p_cols"].items():
        n = client_col.shape[0]
        np.testing.assert_array_equal(
            np.asarray(session.p_cols[name])[:n], client_col,
            err_msg=f"provider column {name!r} diverged",
        )
    for name, client_col in st["r_cols"].items():
        n = client_col.shape[0]
        np.testing.assert_array_equal(
            np.asarray(session.r_cols[name])[:n], client_col,
            err_msg=f"task column {name!r} diverged",
        )


# ---------------- the fallback ladder under dirty failures ----------------


@pytest.mark.skipif(not NATIVE, reason="no native toolchain")
class TestDirtyFailureLadder:
    def _matcher(self, addr, n_nodes=12, n_tasks=5, **kw):
        store = _pool_world(n_nodes=n_nodes, n_tasks=n_tasks)
        return RemoteBatchMatcher(
            store, addr, min_solve_interval=0.0, wire="v2",
            native_fallback=True, native_engine="native-mt",
            native_threads=2, retry_base_s=0.01, **kw,
        )

    def test_mid_stream_reset_during_open_session(self):
        port = _free_port()
        addr = f"127.0.0.1:{port}"
        server = serve(addr)
        m = self._matcher(addr)
        shim = _ResetMidStreamOnce(m.client)
        m.client = shim
        try:
            m.refresh()
            assert shim.resets == 1
            assert m.seam.snapshot().get("session_retry", 0) >= 1
            assert m._session is not None and m._session["tick"] == 0
            assert m._assignment
            m.refresh()  # the session is healthy: deltas advance
            assert m._session["tick"] == 1
            _assert_shadow_matches_server(m, server)
        finally:
            m.client.close()
            server.stop(grace=None)

    def test_truncated_snapshot_chunk_is_a_transient_refusal(self):
        port = _free_port()
        addr = f"127.0.0.1:{port}"
        server = serve(addr)
        # small uncompressed chunks so the snapshot spans several and
        # losing the last one is a genuinely torn stream
        m = self._matcher(
            addr, n_nodes=64, n_tasks=8, chunk_bytes=1024,
            gzip_snapshots=False,
        )
        shim = _TruncateSnapshotOnce(m.client)
        m.client = shim
        try:
            m.refresh()
            assert shim.truncated == 1
            snap = m.seam.snapshot()
            assert snap.get("session_session_transient_refusal") == 1
            # degraded THIS tick to unary — but not demoted for good
            assert m._session is None
            assert not m._session_refused
            assert m._assignment
            m.refresh()
            assert m._session is not None and m._session["tick"] == 0
            _assert_shadow_matches_server(m, server)
        finally:
            m.client.close()
            server.stop(grace=None)

    def test_delta_applied_but_response_dropped_replays_idempotently(self):
        port = _free_port()
        addr = f"127.0.0.1:{port}"
        server = serve(addr)
        m = self._matcher(addr)
        try:
            m.refresh()
            shim = _DropDeltaResponseOnce(m.client)
            m.client = shim
            m.refresh()
            assert shim.dropped == 1
            # the retransmit was answered from the dedup cache: applied
            # exactly once on the server, advanced exactly once on the
            # client, counted on both sides
            assert m.seam.snapshot().get("session_delta_replayed") == 1
            assert m.last_solve_stats.get("replayed_ticks") == 1
            seam = server.servicer.seam.snapshot()
            assert seam.get("session_delta_replayed", 0) >= 1
            assert m._session["tick"] == 1
            assert _server_session(server, m._session["id"]).tick == 1
            _assert_shadow_matches_server(m, server)
            m.refresh()
            assert m._session["tick"] == 2
            _assert_shadow_matches_server(m, server)
        finally:
            m.client.close()
            server.stop(grace=None)


# ---------------- deadline propagation ----------------


class _RecordTimeouts(_ClientShim):
    def __init__(self, real):
        super().__init__(real)
        self.timeouts: dict = {}

    def open_session(self, chunks, timeout=300.0, **k):
        self.timeouts["OpenSession"] = timeout
        return self._real.open_session(chunks, timeout=timeout, **k)

    def assign_delta(self, req, timeout=60.0, **k):
        self.timeouts["AssignDelta"] = timeout
        return self._real.assign_delta(req, timeout=timeout, **k)


class _FakeAbort(Exception):
    pass


class _FakeContext:
    """A bare gRPC context: alive or not, deadline burned or not."""

    def __init__(self, active=True, remaining=None):
        self._active = active
        self._remaining = remaining
        self.abort_code = None

    def is_active(self):
        return self._active

    def time_remaining(self):
        return self._remaining

    def abort(self, code, details):
        self.abort_code = code
        raise _FakeAbort(details)


@pytest.mark.skipif(not NATIVE, reason="no native toolchain")
def test_matcher_sizes_delta_deadline_to_the_tick_budget():
    port = _free_port()
    addr = f"127.0.0.1:{port}"
    server = serve(addr)
    store = _pool_world()
    m = RemoteBatchMatcher(
        store, addr, min_solve_interval=0.0, wire="v2",
        native_fallback=True, native_engine="native-mt",
        native_threads=2, tick_timeout_s=7.5,
    )
    rec = _RecordTimeouts(m.client)
    m.client = rec
    try:
        m.refresh()  # cold: the snapshot stream keeps the long timeout
        assert rec.timeouts["OpenSession"] == m.request_timeout
        m.refresh()  # steady state: deltas carry the TICK budget
        assert rec.timeouts["AssignDelta"] == 7.5
    finally:
        m.client.close()
        server.stop(grace=None)


def test_servicer_refuses_dead_or_burned_contexts_before_solving():
    """A client that hung up (or whose deadline is already spent) must
    not consume engine threads — refused BEFORE the solve dispatch."""
    port = _free_port()
    addr = f"127.0.0.1:{port}"
    server = serve(addr)
    servicer = server.servicer
    try:
        import bench

        rng = np.random.default_rng(0)
        from protocol_tpu.services.scheduler_grpc import encoded_to_proto_v2

        req = encoded_to_proto_v2(
            bench.synth_providers(rng, 16),
            bench.synth_requirements(rng, 12),
            kernel="greedy", top_k=8,
        )
        dead = _FakeContext(active=False)
        with pytest.raises(_FakeAbort):
            servicer.AssignV2(req, dead)
        assert dead.abort_code == grpc.StatusCode.CANCELLED

        burned = _FakeContext(active=True, remaining=0.0)
        with pytest.raises(_FakeAbort):
            servicer.AssignV2(req, burned)
        assert burned.abort_code == grpc.StatusCode.DEADLINE_EXCEEDED

        seam = servicer.seam.snapshot()
        assert seam.get("session_deadline_refused") == 2

        # a live context with budget left solves normally
        alive = _FakeContext(active=True, remaining=30.0)
        resp = servicer.AssignV2(req, alive)
        assert resp.num_assigned > 0
    finally:
        server.stop(grace=None)


# ---------------- graceful drain + warm restart ----------------


@pytest.mark.skipif(not NATIVE, reason="no native toolchain")
class TestDrainAndWarmRestart:
    def test_draining_refusal_is_transient_on_the_ladder(self, tmp_path):
        port = _free_port()
        addr = f"127.0.0.1:{port}"
        server = serve(
            addr, fleet=FleetConfig(shards=2, ckpt_dir=str(tmp_path))
        )
        store = _pool_world()
        m = RemoteBatchMatcher(
            store, addr, min_solve_interval=0.0, wire="v2",
            native_fallback=True, native_engine="native-mt",
            native_threads=2,
        )
        try:
            server.servicer.draining = True
            m.refresh()  # refused -> unary rung for THIS tick only
            snap = m.seam.snapshot()
            assert snap.get("session_session_transient_refusal") == 1
            assert m._session is None and not m._session_refused
            assert m._assignment
            seam = server.servicer.seam.snapshot()
            assert seam.get("session_drain_refused") == 1

            server.servicer.draining = False  # the replacement admits
            m.refresh()
            assert m._session is not None and m._session["tick"] == 0
        finally:
            m.client.close()
            server.stop(grace=None)

    def test_drain_flushes_and_restart_resumes_warm(self, tmp_path):
        port = _free_port()
        addr = f"127.0.0.1:{port}"
        fleet = FleetConfig(shards=2, ckpt_dir=str(tmp_path))
        server = serve(addr, fleet=fleet)
        store = _pool_world()
        m = RemoteBatchMatcher(
            store, addr, min_solve_interval=0.0, wire="v2",
            native_fallback=True, native_engine="native-mt",
            native_threads=2, retry_base_s=0.01,
        )
        try:
            m.refresh()
            m.refresh()
            assert m._session["tick"] == 1

            flushed = drain(server)  # the SIGTERM path minus the signal
            assert flushed == 1
            assert list(tmp_path.glob("**/*.ckpt"))

            # rolling restart: a fresh servicer on the same port
            # rehydrates from the checkpoint directory
            server = serve(addr, fleet=fleet)
            seam = server.servicer.seam.snapshot()
            assert seam.get("session_session_restored") == 1

            # the channel transparently reconnects to the same port;
            # the delta RESUMES against the rehydrated session
            m.refresh()
            snap = m.seam.snapshot()
            assert m._session["tick"] == 2
            assert "session_session_reopen" not in snap  # warm: no herd
            assert m._assignment
            _assert_shadow_matches_server(m, server)

            # checkpoint GC: a client-dropped session's file goes with
            # it (its client is gone — the file would only resurrect a
            # dead session at every restart); ckpt_dir stays bounded
            server.servicer.sessions.drop(m._session["id"])
            assert not list(tmp_path.glob("**/*.ckpt"))
        finally:
            m.client.close()
            server.stop(grace=None)


# ---------------- checkpoint + codec resilience ----------------


def test_pack_arrays_roundtrip_and_torn_payload_refused():
    named = {
        "cand_p": np.arange(12, dtype=np.int32).reshape(3, 4),
        "price": np.linspace(0, 1, 5).astype(np.float32),
        "f": None,
        "scalar_shaped": np.zeros((), np.float64),
    }
    payload = tfmt.pack_arrays(named)
    out = tfmt.unpack_arrays(payload)
    assert out["f"] is None
    for name in ("cand_p", "price", "scalar_shaped"):
        assert out[name].dtype == named[name].dtype
        np.testing.assert_array_equal(out[name], named[name])
    # a torn tail must fail loudly at load, never decode at the wrong
    # widths (the checkpoint loader turns this into a skipped file)
    with pytest.raises(ValueError, match="truncated"):
        tfmt.unpack_arrays(payload[:-3])
    with pytest.raises(ValueError, match="too short"):
        tfmt.unpack_arrays(b"\x01")


@pytest.mark.skipif(not NATIVE, reason="no native toolchain")
@pytest.mark.parametrize("mode", ["crash", "drain"])
def test_loadgen_restart_driver_recovers_warm(mode):
    """The loadgen restart drill (the SIGTERM-drain satellite's test
    vehicle, plus the crash twin): servicer taken down mid-run, a fresh
    one rehydrates on the same port, every session resumes WARM — zero
    full-snapshot reopens, no failed session."""
    from protocol_tpu.fleet.loadgen import run_load

    rep = run_load(
        sessions=2, tenants=1, providers=96, tasks=64, ticks=5,
        shards=2, max_workers=8, check_endpoint=False,
        restart_at_tick=2, restart_mode=mode,
    )
    assert not rep["errors"]
    rs = rep["restart"]
    assert rs["restarted"]
    assert rs["sessions_restored"] == 2
    assert rs["reopens_total"] == 0  # recovery was warm, not a herd
    assert rs["transport_retries_total"] >= 1
    if mode == "drain":
        assert rs["flushed"] == 2  # the drain tail flushed every session
    for tenant in rep["tenants"].values():
        # every session completed its full life: tick 0 (snapshot) + 5
        # recorded deltas, across the outage
        assert tenant["ticks_done"] == 2 * 6
        assert tenant["min_assigned_frac"] >= 0.9


@pytest.mark.skipif(not NATIVE, reason="no native toolchain")
def test_chaos_harness_end_to_end_kill_and_deadline(tmp_path):
    """run_chaos in miniature (the CI gate runs the committed golden
    trace; this keeps the harness itself under test): a servicer kill
    mid-run must reconverge warm and bit-identical, and a starved tick
    deadline must degrade explicitly and boundedly."""
    from protocol_tpu.faults.harness import run_chaos
    from protocol_tpu.trace.synth import synth_trace

    trace = synth_trace(
        str(tmp_path / "tiny.trace"), n_providers=96, n_tasks=64,
        ticks=5, churn=0.05, seed=2, kernel="native-mt:1", top_k=16,
    )
    rep = run_chaos(trace, seed=1, kill_at_tick=2, duplicate_rate=0.2)
    assert rep["restarted"]
    assert rep["client"]["reopens"] == 0
    assert rep["client"]["replayed_served"] >= 1
    assert rep["fresh_ticks_identical"] and rep["final_tick_identical"]
    assert not rep["stale_ticks"]

    rep_d = run_chaos(trace, seed=1, tick_deadline_ms=0.01,
                      max_stale_ticks=2)
    assert rep_d["stale_ticks"], "starved deadline produced no staleness"
    assert rep_d["max_stale_streak"] <= 2  # the bounded-staleness contract
    # degraded answers are explicit end to end: flagged on the wire
    # (client count), counted in the obs plane (per tenant)
    assert rep_d["client"]["stale_served"] == len(rep_d["stale_ticks"])
    assert sum(rep_d["server_stale_obs"].values()) == len(
        rep_d["stale_ticks"]
    )
    # staleness trades identity for latency by CONTRACT (a fresh solve
    # after skipped ticks continues a different warm path than the
    # solve-every-tick baseline) — what it must never trade away is
    # the answer's quality floor
    assert rep_d["assigned_frac_min"] >= 0.97


def test_unloadable_checkpoints_are_skipped_not_fatal(tmp_path):
    from protocol_tpu.faults.checkpoint import SessionCheckpointer

    ckpt = SessionCheckpointer(str(tmp_path))
    # journals live in the checkpointer's own (proc id) namespace
    import pathlib

    ns = pathlib.Path(ckpt.directory)
    (ns / "torn.ckpt").write_bytes(b"PTTRACE1garbage")
    (ns / "empty.ckpt").write_bytes(b"")
    # recovery is an optimization, never a new failure mode
    assert ckpt.load_all() == []
    assert ckpt.due(0) and ckpt.due(1)
    every3 = SessionCheckpointer(str(tmp_path), every=3)
    assert [t for t in range(7) if every3.due(t)] == [0, 3, 6]


# ---------------- asymmetric partition (ISSUE 14) ----------------


class TestDirectionalDrops:
    def test_decisions_are_one_directional(self):
        """With only drop_response_rate set, the schedule must never
        lose a request (and vice versa): the partition is ASYMMETRIC
        by construction — A→B flows while B→A drops."""
        sched = FaultSchedule(ChaosConfig(seed=5, drop_response_rate=0.3))
        acts = [sched.decide("client", "AssignDelta", i) for i in range(64)]
        assert any(a.drop_response for a in acts)
        assert not any(a.drop_request or a.drop for a in acts)
        rev = FaultSchedule(ChaosConfig(seed=5, drop_request_rate=0.3))
        acts = [rev.decide("client", "AssignDelta", i) for i in range(64)]
        assert any(a.drop_request for a in acts)
        assert not any(a.drop_response or a.drop for a in acts)

    def test_new_knobs_parse_and_roundtrip(self):
        cfg = ChaosConfig.from_spec(
            "seed=9,dropreq=0.1,dropresp=0.2,slow_proc=1,slow_ms=40,"
            "slow_rate=0.5,pause_proc_at_tick=3,pause_proc=2"
        )
        assert cfg.drop_request_rate == 0.1
        assert cfg.drop_response_rate == 0.2
        assert cfg.slow_proc == 1 and cfg.slow_ms == 40.0
        assert cfg.pause_proc_at_tick == 3 and cfg.pause_proc == 2
        assert cfg.active()
        assert ChaosConfig.from_spec(cfg.spec()) == cfg
        # every new knob alone arms the plane
        assert ChaosConfig(drop_response_rate=0.1).active()
        assert ChaosConfig(slow_proc=0).active()
        assert ChaosConfig(pause_proc_at_tick=1).active()

    @pytest.mark.skipif(not NATIVE, reason="no native toolchain")
    @pytest.mark.parametrize("kernel", ["native-mt:1", "sinkhorn-mt:1"])
    def test_response_drop_rides_retransmit_dedup_bit_identical(
        self, tmp_path, kernel
    ):
        """The asymmetric-partition site end to end, on BOTH engines:
        requests flow, responses drop (seed 5 kills delta answers at
        call indices 1 and 5). The server APPLIES each dropped tick;
        the client's resend must be served the replayed twin — zero
        reopens, every plan bit-identical to the fault-free replay."""
        from protocol_tpu.faults.harness import _Driver
        from protocol_tpu.trace.replay import iter_input_ticks, replay
        from protocol_tpu.trace.synth import synth_trace

        trace_path = str(tmp_path / f"part_{kernel.split(':')[0]}.trace")
        synth_trace(
            trace_path, n_providers=64, n_tasks=64, ticks=6,
            churn=0.05, seed=3, kernel=kernel,
        )
        trace = tfmt.read_trace(trace_path)
        baseline = replay(
            trace_path, engine=kernel, verify=False, keep_p4t=True
        )["p4ts"]
        schedule = FaultSchedule(
            ChaosConfig(seed=5, drop_response_rate=0.3)
        )
        address = f"127.0.0.1:{_free_port()}"
        server = serve(address, fleet=FleetConfig(shards=2))
        driver = _Driver(
            address, schedule, "t0@partition", kernel, trace.snapshot
        )
        try:
            for tick, p_cols, r_cols, delta in iter_input_ticks(trace):
                if tick == 0:
                    p4t = driver.open(p_cols, r_cols)
                else:
                    p4t, stale = driver.tick(delta, p_cols, r_cols)
                    assert not stale
                assert np.array_equal(p4t, baseline[tick]), (
                    f"tick {tick} diverged under response drops"
                )
            assert driver.client.counters.get("drop_response", 0) >= 1
            assert "drop_request" not in driver.client.counters
            assert driver.counters["replayed_served"] >= 1
            assert driver.counters["reopens"] == 0
            seam = server.servicer.seam.snapshot()
            assert seam.get("session_delta_replayed", 0) >= 1
        finally:
            driver.close()
            server.stop(grace=None)


class TestSlowNodeInterceptor:
    def test_slow_proc_targets_one_process(self):
        """The gray slow-node site: the interceptor inflates responses
        ONLY in the targeted process — the same schedule in any other
        proc_id leaves the handler untouched."""
        cfg = ChaosConfig(seed=1, slow_proc=1, slow_ms=1.0)
        sched = FaultSchedule(cfg)
        from protocol_tpu.faults.inject import ChaosServerInterceptor

        calls = []

        class _Details:
            method = "/pkg.Svc/AssignDelta"

        def handler_fn(request, context):
            calls.append(request)
            return "ok"

        def continuation(details):
            return grpc.unary_unary_rpc_method_handler(handler_fn)

        slow = ChaosServerInterceptor(sched, proc_id="p1")
        fast = ChaosServerInterceptor(sched, proc_id="p0")
        wrapped = slow.intercept_service(continuation, _Details())
        assert wrapped.unary_unary is not handler_fn  # wrapped: delays
        assert wrapped.unary_unary("req", None) == "ok"
        assert slow.counters.get("slow") == 1
        untouched = fast.intercept_service(continuation, _Details())
        assert untouched.unary_unary is handler_fn  # pass-through
        assert "slow" not in fast.counters
