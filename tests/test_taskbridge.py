"""TaskBridge: the workload-facing unix-socket intake
(docker/taskbridge/bridge.rs). Focus: the output message's save_path ->
artifact-bytes path (reference file_handler.rs:21-118 semantics) with its
integrity gate — bytes that don't hash to the claimed sha must never be
uploaded, and the work submission still happens bodyless."""

import pytest

# Environment guard: this module's import chain reaches
# protocol_tpu.security / protocol_tpu.utils.tls, which need the
# third-party `cryptography` package (wallet signing + TLS material).
# On hosts without it, report the whole module as SKIPPED instead of a
# collection error (tier-1 keeps an honest skip count; CI installs
# cryptography and runs everything).
pytest.importorskip(
    "cryptography", reason="cryptography not installed (signing/TLS dependency)"
)

import asyncio
import hashlib
import json
import os

from protocol_tpu.services.worker import TaskBridge


class StubAgent:
    def __init__(self):
        self.calls = []

    async def submit_output(self, sha, flops, file_name, data=None, task_id=None):
        self.calls.append(
            {"sha": sha, "flops": flops, "file_name": file_name,
             "data": data, "task_id": task_id}
        )
        return True


def run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def dispatch(msg):
    agent = StubAgent()
    bridge = TaskBridge("/tmp/unused.sock", agent)
    run(bridge._dispatch(msg))
    return agent.calls


def output_msg(data: bytes, tmp_path, sha=None, **extra):
    p = tmp_path / "artifact.bin"
    p.write_bytes(data)
    return {
        "output": {
            "sha256": sha or hashlib.sha256(data).hexdigest(),
            "output_flops": 3,
            "file_name": "artifact.bin",
            "save_path": str(p),
            **extra,
        }
    }


def test_save_path_bytes_flow_to_submit(tmp_path):
    data = os.urandom(512)
    calls = dispatch(output_msg(data, tmp_path))
    assert len(calls) == 1
    assert calls[0]["data"] == data
    assert calls[0]["sha"] == hashlib.sha256(data).hexdigest()


def test_sha_mismatch_uploads_nothing_but_submits(tmp_path):
    calls = dispatch(output_msg(os.urandom(512), tmp_path, sha="ab" * 32))
    assert len(calls) == 1
    assert calls[0]["data"] is None  # integrity gate held
    assert calls[0]["sha"] == "ab" * 32  # bodyless legacy submission intact


def test_missing_file_is_bodyless(tmp_path):
    msg = output_msg(b"x", tmp_path)
    os.unlink(msg["output"]["save_path"])
    calls = dispatch(msg)
    assert len(calls) == 1 and calls[0]["data"] is None


def test_duplicate_sha_deduped(tmp_path):
    data = os.urandom(64)
    agent = StubAgent()
    bridge = TaskBridge("/tmp/unused.sock", agent)
    msg = output_msg(data, tmp_path)
    run(bridge._dispatch(msg))
    run(bridge._dispatch(json.loads(json.dumps(msg))))
    assert len(agent.calls) == 1  # bridge.rs:150-156 dedup


def test_output_task_id_attribution(tmp_path):
    """Colocated workloads share one bridge socket: the message's own
    task_id must reach submit_output so an extra task's artifact is not
    attributed to the primary."""
    data = os.urandom(64)
    msg = output_msg(data, tmp_path, task_id="task-b")
    calls = dispatch(msg)
    assert calls[0]["task_id"] == "task-b"
    # absent task_id -> None (submit_output falls back to current_task)
    msg2 = output_msg(os.urandom(64), tmp_path)
    assert dispatch(msg2)[0]["task_id"] is None
