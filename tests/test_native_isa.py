"""Per-ISA dispatch contract for the native engine (ISSUE 16).

The engine carries three float pipelines in ONE baseline .so — scalar
(the historical referee), AVX2, and AVX-512 — selected at runtime
through the ``kIsaOps`` dispatch table. The contract under test:

  * forced-ISA env round-trip: ``PROTOCOL_TPU_NATIVE_ISA`` /
    ``native.set_isa`` pin the pipeline, ``native.current_isa`` reports
    the EFFECTIVE one, and the tag rides EngineStats / arena
    ``last_stats`` / checkpoint state,
  * graceful scalar fallback: unsupported requests clamp (never fail)
    and the tag names what actually ran,
  * per-ISA golden plans: committed digests at 2k and 16k — bit-identity
    within an ISA across runs, builds, and thread counts is the whole
    determinism story, and avx2 == avx512 exactly (one fmaf-matched
    pipeline),
  * vector-vs-scalar referee equivalence on the repair-vs-cold oracle
    suite (the drift/mutate/join-leave/task-churn scripts from
    test_cand_repair.py) x threads {1,2,4} x both solve engines: exact
    plan-set equality within an ISA, documented float tolerance across
    the scalar/vector pipeline boundary.
"""

import hashlib
import os

import numpy as np
import pytest

from protocol_tpu import native
from protocol_tpu.ops.cost import CostWeights

import test_cand_repair as tcr

pytestmark = pytest.mark.skipif(
    not native.available(), reason="no native toolchain"
)

W = CostWeights()
THREADS = (1, 2, 4)

# committed per-ISA golden digests: sha256 over the bucketed cold plan
# (cand_p || cand_c) at threads=1, k=64, population
# bench.synth_providers(rng(2)) x bench.synth_requirements(rng(3)) —
# the same basis as perf_floor.json's simd_* family. avx2 and avx512
# share one fmaf-matched pipeline, hence one digest.
_VEC_2K = "2f03847bb30ea2ded3058171ada4197342cac0be9e4c04d504f00ebf518f17cd"
_VEC_16K = "97c3106eeaf425b78c2faafd10f62ace94a98baa2723869a89e3f68c2ba8218a"
GOLDEN = {
    2048: {
        "scalar": "96afb6c6ed4e32ed5e0744620879b1e3c0397e368300b482e71f5c1c3f613b28",
        "avx2": _VEC_2K,
        "avx512": _VEC_2K,
    },
    16384: {
        "scalar": "4f0d3f374d00f4ed98c33a1a700ef3fd3fc47ccf4649ac85a1f218ef9ead5e18",
        "avx2": _VEC_16K,
        "avx512": _VEC_16K,
    },
}

# documented scalar-vs-vector pipeline tolerance (perf_floor.json
# _basis_simd): same polynomial, different mul+add vs fmaf chains
REFEREE_COST_TOL = 5e-3
REFEREE_ROW_MISMATCH_FRAC = 0.01


def _isas():
    return ["scalar"] + [
        i for i in ("avx2", "avx512") if native.isa_supported(i)
    ]


def _vector_isas():
    return [i for i in ("avx2", "avx512") if native.isa_supported(i)]


@pytest.fixture(autouse=True)
def _restore_isa():
    prev_env = os.environ.get("PROTOCOL_TPU_NATIVE_ISA")
    prev = native.current_isa()
    yield
    if prev_env is None:
        os.environ.pop("PROTOCOL_TPU_NATIVE_ISA", None)
    else:
        os.environ["PROTOCOL_TPU_NATIVE_ISA"] = prev_env
    native._apply_isa(native.load(), prev)


def _bench_pop(n):
    import bench

    return (
        bench.synth_providers(np.random.default_rng(2), n),
        bench.synth_requirements(np.random.default_rng(3), n),
    )


def _digest(cp, cc) -> str:
    h = hashlib.sha256()
    h.update(np.ascontiguousarray(cp).tobytes())
    h.update(np.ascontiguousarray(cc).tobytes())
    return h.hexdigest()


class TestEnvRoundTrip:
    def test_set_isa_round_trips_through_env_and_load(self):
        for isa in _isas():
            eff = native.set_isa(isa)
            assert eff == isa
            assert os.environ["PROTOCOL_TPU_NATIVE_ISA"] == isa
            # a later load() (cached path) must re-apply the env request
            native.load()
            assert native.current_isa() == isa

    def test_auto_selects_the_widest_supported(self):
        assert native.set_isa("auto") == _isas()[-1]

    def test_bad_isa_names_are_rejected(self, monkeypatch):
        with pytest.raises(native.NativeBuildError):
            native.set_isa("neon")
        monkeypatch.setenv("PROTOCOL_TPU_NATIVE_ISA", "sse9")
        with pytest.raises(native.NativeBuildError):
            native.isa_request()

    def test_unset_env_keeps_the_running_isa(self):
        """Env unset means 'no forcing' — the engine keeps whatever it
        runs (the baked .so default at first load): committed scalar
        goldens stay valid with no env plumbing anywhere."""
        target = _isas()[-1]
        native.set_isa(target)
        os.environ.pop("PROTOCOL_TPU_NATIVE_ISA", None)
        native.load()
        assert native.current_isa() == target

    def test_stats_carry_the_effective_isa_tag(self):
        ep, er = tcr._pop(3, 128)
        for isa in _isas():
            native.set_isa(isa)
            st: dict = {}
            native.fused_topk_candidates(ep, er, W, k=16, stats=st)
            assert st["native_isa"] == isa


class TestGracefulFallback:
    def test_engine_clamps_out_of_range_requests(self):
        lib = native.load()
        assert lib.engine_isa_supported(99) == 0
        assert lib.engine_isa_supported(-1) == 0
        best = native._ISA_CODES[_isas()[-1]]
        prev = lib.engine_get_isa()
        try:
            # an absurd request clamps to the best the host supports —
            # never an error, and the getter names what actually runs
            assert lib.engine_set_isa(99) == best
            assert lib.engine_get_isa() == best
            assert lib.engine_set_isa(0) == 0
        finally:
            lib.engine_set_isa(prev)

    def test_isa_supported_name_surface(self):
        assert native.isa_supported("scalar")
        assert native.isa_supported("auto")
        assert not native.isa_supported("bogus")

    def test_scalar_request_always_lands_scalar(self):
        assert native.set_isa("scalar") == "scalar"
        ep, er = tcr._pop(5, 96)
        st: dict = {}
        native.fused_topk_candidates(ep, er, W, k=8, stats=st)
        assert st["native_isa"] == "scalar"


class TestPerIsaGoldenPlans:
    def _check(self, n):
        ep, er = _bench_pop(n)
        seen = {}
        for isa in _isas():
            assert native.set_isa(isa) == isa
            cp, cc = native.fused_topk_candidates(
                ep, er, W, k=64, threads=1, bucketed=True
            )
            d = _digest(cp, cc)
            seen[isa] = d
            assert d == GOLDEN[n][isa], (
                f"{isa} plan digest drifted at n={n} — the per-ISA "
                "bit-identity contract (across runs AND builds) is broken"
            )
        if "avx2" in seen and "avx512" in seen:
            assert seen["avx2"] == seen["avx512"]

    def test_golden_2k(self):
        self._check(2048)

    @pytest.mark.slow
    def test_golden_16k(self):
        self._check(16384)


class TestRefereeEquivalence:
    """The oracle suite from test_cand_repair.py, run per ISA: within an
    ISA everything is exact (repair == rebuild, thread-invariant); across
    the scalar/vector boundary the plans agree up to the documented
    float-pipeline tolerance."""

    def test_oracle_churn_scripts_per_isa(self):
        for isa in _vector_isas():
            rng = np.random.default_rng(0)
            P = T = 256
            k = 16
            ep, er = tcr._pop(0, P)
            # one persistent structure per (isa, threads), plus the
            # scalar referee structure
            native.set_isa(isa)
            structs = {}
            for thr in THREADS:
                rev = np.zeros((P, 8), np.uint64)
                cp, cc = native.fused_topk_candidates(
                    ep, er, W, k=k, threads=thr, rev_out=rev, bucketed=True
                )
                structs[thr] = (cp, cc, rev)
            native.set_isa("scalar")
            rev_s = np.zeros((P, 8), np.uint64)
            cp_s, cc_s = native.fused_topk_candidates(
                ep, er, W, k=k, threads=1, rev_out=rev_s, bucketed=True
            )
            for tick in range(4):
                ep, er, dp, dt = tcr._churn(rng, ep, er, P, T)
                native.set_isa(isa)
                for thr in THREADS:
                    cp, cc, rev = structs[thr]
                    native.repair_topk_candidates(
                        ep, er, W, cp, cc, rev, dp, dt, k=k, threads=thr
                    )
                # exact within the ISA: thread-invariant ...
                for thr in (2, 4):
                    for a, b in zip(structs[1], structs[thr]):
                        np.testing.assert_array_equal(
                            a, b,
                            err_msg=f"{isa} tick {tick} threads={thr}",
                        )
                # ... and repair == same-ISA cold rebuild (plan-set
                # equality where the oracle demands bit-identity)
                rev_r = np.zeros((P, 8), np.uint64)
                rp, rc = native.fused_topk_candidates(
                    ep, er, W, k=k, reverse_r=8, extra=16, threads=2,
                    rev_out=rev_r,
                )
                cp, cc, rev = structs[1]
                np.testing.assert_array_equal(cp, rp)
                np.testing.assert_array_equal(cc, rc)
                np.testing.assert_array_equal(rev, rev_r)
                # scalar referee: maintain its structure through the
                # same script, compare across the pipeline boundary
                native.set_isa("scalar")
                native.repair_topk_candidates(
                    ep, er, W, cp_s, cc_s, rev_s, dp, dt, k=k, threads=1
                )
                same = np.all(cp_s == cp, axis=1)
                assert 1.0 - float(same.mean()) <= REFEREE_ROW_MISMATCH_FRAC, (
                    f"{isa} tick {tick}: provider sets diverge from the "
                    "scalar referee beyond near-tie reorders"
                )
                if bool(same.any()):
                    dc = np.abs(cc_s[same] - cc[same])
                    assert float(dc.max()) <= REFEREE_COST_TOL, (
                        f"{isa} tick {tick}: cost delta vs scalar referee "
                        f"{float(dc.max()):.2e} beyond documented tolerance"
                    )

    @pytest.mark.parametrize("engine", ["auction", "sinkhorn"])
    def test_arena_chain_vector_vs_scalar_referee(self, engine):
        """Arena-level, both solve engines: a vector-pinned arena and a
        scalar-pinned arena tick through the same churn script; each
        stays exact against its own pipeline's rebuild (structure
        invariant), their assignments agree up to near-ties, and every
        last_stats carries the pipeline's tag."""
        vec = _vector_isas()
        if not vec:
            pytest.skip("host has no vector ISA")
        isa = vec[-1]
        from protocol_tpu.native.arena import NativeSolveArena

        rng = np.random.default_rng(21)
        P = T = 256
        ep, er = tcr._pop(21, P)
        arena_v = NativeSolveArena(
            k=16, threads=2, engine=engine, cold_every=1_000_000
        )
        arena_s = NativeSolveArena(
            k=16, threads=2, engine=engine, cold_every=1_000_000
        )
        native.set_isa(isa)
        arena_v.solve(ep, er, W)
        assert arena_v.last_stats["native_isa"] == isa
        native.set_isa("scalar")
        arena_s.solve(ep, er, W)
        assert arena_s.last_stats["native_isa"] == "scalar"
        for tick in range(3):
            ep, er, _dp, _dt = tcr._churn(rng, ep, er, P, T)
            native.set_isa(isa)
            p4t_v = arena_v.solve(ep, er, W)
            assert arena_v.last_stats["cand_cold_passes"] == 0
            assert arena_v.last_stats["native_isa"] == isa
            # structure invariant against the SAME pipeline's rebuild
            rp, rc, rrev = tcr._rebuild(ep, er, 16, P)
            np.testing.assert_array_equal(arena_v._cand_p, rp)
            np.testing.assert_array_equal(arena_v._cand_c, rc)
            np.testing.assert_array_equal(arena_v._rev, rrev)
            native.set_isa("scalar")
            p4t_s = arena_s.solve(ep, er, W)
            assert arena_s.last_stats["native_isa"] == "scalar"
            n_v = int((p4t_v >= 0).sum())
            n_s = int((p4t_s >= 0).sum())
            assert abs(n_v - n_s) <= max(2, T // 100), (
                f"tick {tick}: assigned counts diverge ({n_v} vs {n_s})"
            )
            agree = float((p4t_v == p4t_s).mean())
            assert agree >= 0.95, (
                f"tick {tick}: only {agree:.1%} of tasks agree between "
                "vector and scalar pipelines"
            )


class TestCheckpointIsaProvenance:
    def test_isa_skewed_restore_cold_regrounds(self):
        """A structure exported under one pipeline must NOT be repaired
        under another (repair assumes bit-exact carried floats): the
        restore degrades to an honest cold re-ground, same as a
        config-skewed carry."""
        from protocol_tpu.native.arena import NativeSolveArena

        rng = np.random.default_rng(31)
        P = T = 192
        ep, er = tcr._pop(31, P)
        native.set_isa("scalar")
        src = NativeSolveArena(k=16, threads=2)
        src.solve(ep, er, W)
        state = src.export_state()
        assert state["native_isa"] == "scalar"

        skew = dict(state)
        skew["native_isa"] = "avx2"
        dst = NativeSolveArena(k=16, threads=2)
        dst.restore_state(ep, er, skew)
        ep2, er2, _dp, _dt = tcr._churn(rng, ep, er, P, T)
        dst.solve(ep2, er2, W)
        assert dst.last_stats["cold"] is True  # honest re-ground

        # matching tag restores warm (the carry contract holds)
        ok = NativeSolveArena(k=16, threads=2)
        ok.restore_state(ep, er, state)
        ok.solve(ep2, er2, W)
        assert ok.last_stats["cold"] is False
        assert ok.last_stats["cand_cold_passes"] == 0


class TestIsaVariantSo:
    def test_baked_default_variant_dispatches_without_env(self):
        """make native-avx2 bakes ENGINE_DEFAULT_ISA=1: selecting the
        variant .so (PROTOCOL_TPU_NATIVE_ISA_VARIANT) must come up on
        the vector pipeline with NO runtime-ISA env at all."""
        if not native.isa_supported("avx2"):
            pytest.skip("host has no AVX2")
        if not os.path.exists(native.so_path("avx2")):
            pytest.skip("variant .so not built (make native-avx2)")
        os.environ.pop("PROTOCOL_TPU_NATIVE_ISA", None)
        os.environ["PROTOCOL_TPU_NATIVE_ISA_VARIANT"] = "avx2"
        try:
            assert native.current_isa() == "avx2"
        finally:
            os.environ.pop("PROTOCOL_TPU_NATIVE_ISA_VARIANT", None)
