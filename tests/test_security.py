"""Security layer tests: wallet signatures, request signing round-trips, and
the aiohttp signature middleware (sig, nonce replay, rate limit, api key) —
mirroring the reference's middleware test coverage."""

import pytest

# Environment guard: this module's import chain reaches
# protocol_tpu.security / protocol_tpu.utils.tls, which need the
# third-party `cryptography` package (wallet signing + TLS material).
# On hosts without it, report the whole module as SKIPPED instead of a
# collection error (tier-1 keeps an honest skip count; CI installs
# cryptography and runs everything).
pytest.importorskip(
    "cryptography", reason="cryptography not installed (signing/TLS dependency)"
)

import asyncio
import json

from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

import pytest

from protocol_tpu.security import (
    EvmRecoveryWallet,
    EvmWallet,
    Wallet,
    sign_request,
    verify_request,
    verify_signature,
)
from protocol_tpu.security.middleware import (
    RateLimiter,
    api_key_middleware,
    validate_signature_middleware,
)
from protocol_tpu.security.signer import canonical_json
from protocol_tpu.store.kv import KVStore


@pytest.fixture(
    params=[Wallet, EvmWallet, EvmRecoveryWallet],
    ids=["ed25519", "evm", "evm-recovery"],
)
def wallet_cls(request):
    """Every signature scheme must pass the identical signer/middleware
    suite — the adapter contract (VERDICT r4 item 7). evm-recovery is
    the reference's literal wire (r||s||v + EIP-191 + address recovery),
    so this parametrization proves an alloy/MetaMask-style client
    authenticates against this control plane verbatim."""
    return request.param


class TestWallet:
    def test_sign_verify_roundtrip(self, wallet_cls):
        w = wallet_cls()
        sig = w.sign_message("hello")
        assert verify_signature("hello", sig, w.address)

    def test_wrong_message_rejected(self, wallet_cls):
        w = wallet_cls()
        sig = w.sign_message("hello")
        assert not verify_signature("other", sig, w.address)

    def test_wrong_address_rejected(self, wallet_cls):
        w, w2 = wallet_cls(), wallet_cls()
        sig = w.sign_message("hello")
        assert not verify_signature("hello", sig, w2.address)

    def test_garbage_signature(self):
        assert not verify_signature("m", "nonsense", "0xabc")
        assert not verify_signature("m", "aa:bb", "0xabc")

    def test_deterministic_from_seed(self, wallet_cls):
        a = wallet_cls.from_seed(b"x" * 32)
        b = wallet_cls.from_seed(b"x" * 32)
        assert a.address == b.address

    def test_hex_roundtrip(self, wallet_cls):
        w = wallet_cls()
        w2 = wallet_cls.from_hex(w.private_key_hex())
        assert w.address == w2.address


class TestSigner:
    def test_signed_body_roundtrip(self, wallet_cls):
        w = wallet_cls()
        headers, body = sign_request("/heartbeat", w, {"address": w.address, "b": 1})
        assert "nonce" in body
        assert verify_request("/heartbeat", headers, body) == w.address

    def test_get_request_roundtrip(self, wallet_cls):
        w = wallet_cls()
        headers, body = sign_request("/api/pool/0", w)
        assert body is None
        assert verify_request("/api/pool/0", headers) == w.address

    def test_tampered_body_rejected(self, wallet_cls):
        w = wallet_cls()
        headers, body = sign_request("/x", w, {"v": 1})
        body["v"] = 2
        assert verify_request("/x", headers, body) is None

    def test_wrong_endpoint_rejected(self, wallet_cls):
        w = wallet_cls()
        headers, body = sign_request("/x", w, {"v": 1})
        assert verify_request("/y", headers, body) is None

    def test_canonical_json_sorts_keys(self):
        assert canonical_json({"b": 1, "a": {"d": 2, "c": 3}}) == '{"a":{"c":3,"d":2},"b":1}'


class TestRateLimiter:
    def test_limits_within_window(self):
        rl = RateLimiter(limit=3, window=60)
        assert all(rl.allow("a", now=0.0) for _ in range(3))
        assert not rl.allow("a", now=1.0)
        assert rl.allow("b", now=1.0)  # other address unaffected
        assert rl.allow("a", now=61.0)  # window rolls


def make_app(kv, **mw_kwargs):
    async def echo(request):
        return web.json_response(
            {"success": True, "address": request.get("auth_address")}
        )

    app = web.Application(
        middlewares=[
            validate_signature_middleware(kv, ["/signed"], **mw_kwargs),
            api_key_middleware("admin-key", ["/admin"]),
        ]
    )
    app.router.add_post("/signed/echo", echo)
    app.router.add_get("/open", echo)
    app.router.add_get("/admin/list", echo)
    return app


async def _request(app, method, path, headers=None, body=None):
    async with TestClient(TestServer(app)) as client:
        resp = await client.request(
            method, path, headers=headers or {},
            data=json.dumps(body) if body is not None else None,
        )
        return resp.status, await resp.json()


def run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


class TestSignatureMiddleware:
    def test_valid_signature_passes(self, wallet_cls):
        kv = KVStore()
        w = wallet_cls()
        headers, body = sign_request("/signed/echo", w, {"hello": 1})
        status, data = run(_request(make_app(kv), "POST", "/signed/echo", headers, body))
        assert status == 200 and data["address"] == w.address

    def test_missing_headers_rejected(self):
        status, _ = run(_request(make_app(KVStore()), "POST", "/signed/echo", {}, {"a": 1}))
        assert status == 401

    def test_nonce_replay_rejected(self, wallet_cls):
        kv = KVStore()
        w = wallet_cls()
        app = make_app(kv)

        async def replay():
            async with TestClient(TestServer(app)) as client:
                headers, body = sign_request("/signed/echo", w, {"hello": 1})
                r1 = await client.post("/signed/echo", headers=headers, data=json.dumps(body))
                r2 = await client.post("/signed/echo", headers=headers, data=json.dumps(body))
                return r1.status, r2.status

        s1, s2 = run(replay())
        assert s1 == 200 and s2 == 401

    def test_tampered_body_rejected(self, wallet_cls):
        kv = KVStore()
        w = wallet_cls()
        headers, body = sign_request("/signed/echo", w, {"hello": 1})
        body["hello"] = 2
        status, _ = run(_request(make_app(kv), "POST", "/signed/echo", headers, body))
        assert status == 401

    def test_unprotected_route_open(self):
        status, _ = run(_request(make_app(KVStore()), "GET", "/open"))
        assert status == 200

    def test_allow_list(self, wallet_cls):
        kv = KVStore()
        w = wallet_cls()
        headers, body = sign_request("/signed/echo", w, {"a": 1})
        status, _ = run(
            _request(make_app(kv, allowed_addresses=["0xother"]), "POST", "/signed/echo", headers, body)
        )
        assert status == 401

    def test_async_validator(self, wallet_cls):
        kv = KVStore()
        w = wallet_cls()

        async def reject_all(addr):
            return False

        headers, body = sign_request("/signed/echo", w, {"a": 1})
        status, _ = run(
            _request(make_app(kv, validator=reject_all), "POST", "/signed/echo", headers, body)
        )
        assert status == 401

    def test_rate_limit(self, wallet_cls):
        kv = KVStore()
        w = wallet_cls()
        app = make_app(kv, rate_limiter=RateLimiter(limit=2))

        async def burst():
            async with TestClient(TestServer(app)) as client:
                statuses = []
                for _ in range(3):
                    headers, body = sign_request("/signed/echo", w, {"a": 1})
                    r = await client.post("/signed/echo", headers=headers, data=json.dumps(body))
                    statuses.append(r.status)
                return statuses

        assert run(burst()) == [200, 200, 429]


class TestApiKeyMiddleware:
    def test_admin_requires_key(self):
        async def flow():
            app = make_app(KVStore())
            async with TestClient(TestServer(app)) as client:
                r1 = await client.get("/admin/list")
                r2 = await client.get(
                    "/admin/list", headers={"Authorization": "Bearer admin-key"}
                )
                return r1.status, r2.status

        s1, s2 = run(flow())
        assert s1 == 401 and s2 == 200


class TestEvmScheme:
    """Pins the EVM wallet to public Ethereum test vectors — the adapter
    claim is that these are REAL chain-compatible addresses/signatures
    (reference scheme: crates/shared/src/web3/wallet.rs:28-68)."""

    def test_keccak256_known_vectors(self):
        from protocol_tpu.security.wallet import keccak256

        assert keccak256(b"").hex() == (
            "c5d2460186f7233c927e7db2dcc703c0e500b653ca82273b7bfad8045d85a470"
        )
        assert keccak256(b"abc").hex() == (
            "4e03657aea45a94fc7d47ba826c8d667c0d1e6e33a64a036ec44f58fa12d6c45"
        )
        assert keccak256(b"hello").hex() == (
            "1c8aff950685c2ed4bc3174f3472287b56d9517b9c948127319a09a7a36deac8"
        )

    def test_known_ethereum_address(self):
        # private key 0x01 -> the canonical generator-point address
        w = EvmWallet.from_hex("0x01")
        assert w.address == "0x7e5f4552091a69125d5dfcb7b8c2659029395bdf"

    def test_schemes_not_interchangeable(self):
        """A signature from one scheme never verifies against the other
        scheme's address for the same seed."""
        ed = Wallet.from_seed(b"same-seed")
        evm = EvmWallet.from_seed(b"same-seed")
        assert ed.address != evm.address
        assert not verify_signature("m", ed.sign_message("m"), evm.address)
        assert not verify_signature("m", evm.sign_message("m"), ed.address)

    def test_truncated_secp_signature_rejected(self):
        w = EvmWallet()
        pub_hex, sig_hex = w.sign_message("m").split(":")
        assert not verify_signature("m", f"{pub_hex}:{sig_hex[:-2]}", w.address)


    def test_high_s_twin_rejected(self):
        """ECDSA malleability: flipping s to n-s yields a second valid
        raw signature — the verifier must reject it (it would defeat the
        middleware's signature-keyed replay cache for bodyless requests)."""
        from protocol_tpu.security.wallet import _SECP_N

        w = EvmWallet()
        pub_hex, sig_hex = w.sign_message("m").split(":")
        sig = bytes.fromhex(sig_hex)
        r = sig[:32]
        s_int = int.from_bytes(sig[32:], "big")
        assert s_int <= _SECP_N // 2  # signer normalizes to low-s
        twin = r + (_SECP_N - s_int).to_bytes(32, "big")
        assert not verify_signature("m", f"{pub_hex}:{twin.hex()}", w.address)

    def test_oversized_keccak_message_refused(self):
        from protocol_tpu.security.wallet import EVM_MAX_MESSAGE_BYTES

        w = EvmWallet()
        big = b"x" * (EVM_MAX_MESSAGE_BYTES + 1)
        with pytest.raises(ValueError, match="keccak signing cap"):
            w.sign_message(big)
        # a forged signature over an oversized message is refused before
        # the verifier spends seconds hashing it
        ok = w.sign_message(b"small")
        pub_hex, sig_hex = ok.split(":")
        assert not verify_signature(big, f"{pub_hex}:{sig_hex}", w.address)


    def test_recovery_wire_roundtrip_and_malleability(self):
        from protocol_tpu.security.wallet import _SECP_N

        w = EvmRecoveryWallet.from_hex("0x01")
        assert w.address == "0x7e5f4552091a69125d5dfcb7b8c2659029395bdf"
        sig = w.sign_message("payload")
        assert sig.startswith("0x") and len(sig) == 132  # the reference's
        # exact shape (request_signer.rs test: 0x + 130 hex chars)
        assert verify_signature("payload", sig, w.address)
        assert not verify_signature("payloaD", sig, w.address)
        raw = bytes.fromhex(sig[2:])
        s_int = int.from_bytes(raw[32:64], "big")
        assert s_int <= _SECP_N // 2  # low-s on the wire
        # the genuinely-valid malleated twin: s -> n-s with the OTHER
        # recovery id (27<->28); must be rejected by the low-s rule alone
        twin = (
            raw[:32]
            + (_SECP_N - s_int).to_bytes(32, "big")
            + bytes([55 - raw[64]])
        )
        assert not verify_signature("payload", "0x" + twin.hex(), w.address)
        # high-s with the ORIGINAL v: also rejected (isolates the low-s
        # check from recovery-id validation)
        high_s_orig_v = (
            raw[:32] + (_SECP_N - s_int).to_bytes(32, "big") + raw[64:]
        )
        assert not verify_signature(
            "payload", "0x" + high_s_orig_v.hex(), w.address
        )
        # non-canonical re-encodings of the VALID signature must not
        # verify (they would bypass the signature-string replay cache)
        assert not verify_signature("payload", sig[2:], w.address)  # no 0x
        assert not verify_signature("payload", sig.upper().replace("0X", "0x"), w.address)
        v0 = raw[:64] + bytes([raw[64] - 27])  # v rewritten 27/28 -> 0/1
        assert not verify_signature("payload", "0x" + v0.hex(), w.address)

    def test_recovery_rejects_garbage(self):
        w = EvmRecoveryWallet()
        assert not verify_signature("m", "0x" + "00" * 65, w.address)
        assert not verify_signature("m", "0x" + "ff" * 65, w.address)
        assert not verify_signature("m", "0xzz", w.address)


class TestChallengeSizedBodies:
    """ADVICE r5: the hardware-challenge body (~254 KB of matrices at
    challenge_size=64) exceeded the EVM schemes' 64 KB keccak signing cap,
    so sign_request raised mid-tick and no node ever got validated under
    PROTOCOL_TPU_WALLET_SCHEME=evm. Oversized bodies now sign a sha256
    digest of the canonical JSON (x-body-digest header); every scheme must
    round-trip a challenge-sized body through signer AND middleware."""

    @staticmethod
    def _challenge_payload():
        import numpy as np

        from protocol_tpu.utils import fixedf64

        rng = np.random.default_rng(0)
        n = 64
        a = fixedf64.roundtrip(
            rng.standard_normal((n, n), dtype=np.float32)
        ).astype(np.float32)
        b = fixedf64.roundtrip(
            rng.standard_normal((n, n), dtype=np.float32)
        ).astype(np.float32)
        return {
            "matrix_a_fixed": fixedf64.encode_array(a),
            "matrix_b_fixed": fixedf64.encode_array(b),
            "matrix_a": a.tolist(),
            "matrix_b": b.tolist(),
        }

    def test_signer_roundtrip(self, wallet_cls):
        from protocol_tpu.security.signer import (
            BODY_DIGEST_HEADER,
            BODY_DIGEST_THRESHOLD,
        )

        w = wallet_cls()
        payload = self._challenge_payload()
        assert len(canonical_json(payload)) > BODY_DIGEST_THRESHOLD
        headers, body = sign_request("/control/challenge", w, payload)
        assert headers.get(BODY_DIGEST_HEADER) == "sha256"
        assert verify_request("/control/challenge", headers, body) == w.address.lower()

    def test_tampered_digest_body_rejected(self, wallet_cls):
        w = wallet_cls()
        headers, body = sign_request(
            "/control/challenge", w, self._challenge_payload()
        )
        body["matrix_a_fixed"][0][0] += 1
        assert verify_request("/control/challenge", headers, body) is None

    def test_stripped_digest_header_rejected(self, wallet_cls):
        from protocol_tpu.security.signer import BODY_DIGEST_HEADER

        w = wallet_cls()
        headers, body = sign_request(
            "/control/challenge", w, self._challenge_payload()
        )
        stripped = {k: v for k, v in headers.items() if k != BODY_DIGEST_HEADER}
        assert verify_request("/control/challenge", stripped, body) is None

    def test_small_bodies_keep_the_raw_json_wire(self, wallet_cls):
        # wire compatibility: below the threshold nothing changes (an
        # unupgraded peer's verifier still reconstructs endpoint+ts+json)
        from protocol_tpu.security.signer import BODY_DIGEST_HEADER

        w = wallet_cls()
        headers, body = sign_request("/signed/echo", w, {"hello": 1})
        assert BODY_DIGEST_HEADER not in headers
        assert verify_request("/signed/echo", headers, body) == w.address.lower()

    def test_middleware_passes_challenge_sized_body(self, wallet_cls):
        # the worker-side verify path (middleware -> verify_request): a
        # challenge-sized signed body authenticates end to end
        kv = KVStore()
        w = wallet_cls()
        headers, body = sign_request("/signed/echo", w, self._challenge_payload())
        status, data = run(
            _request(make_app(kv), "POST", "/signed/echo", headers, body)
        )
        assert status == 200 and data["address"] == w.address

    def test_unsignable_body_fails_challenge_not_tick(self):
        # challenge_node catches a signing ValueError: one bad challenge
        # returns False instead of aborting validation_loop_once
        from protocol_tpu.chain import Ledger
        from protocol_tpu.services.validator import ValidatorService

        class RefusingWallet(Wallet):
            def sign_message(self, message):
                raise ValueError("over the signing cap")

        svc = ValidatorService(
            RefusingWallet(), Ledger(), pool_id=0, http=None
        )
        ok = run(svc.challenge_node("http://127.0.0.1:1"))
        assert ok is False
