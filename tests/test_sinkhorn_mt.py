"""Sparse multi-threaded Sinkhorn engine (engine=sinkhorn-mt): NumPy
reference parity, thread-count invariance, uniform-shift invariance of the
warm potential carry, the arena integration (only dirty rows recomputed),
and the auction-referee rounding contract (injective, auction-grade).

The engine is DETERMINISTIC by construction — every row/column logsumexp
is reduced serially by one thread in a fixed edge order — so the
potentials must be bit-identical for every thread count, which is what
makes a threads=4 production deployment debuggable against a threads=1
repro (the same contract as auction_sparse_mt).
"""

import dataclasses

import numpy as np
import pytest

from protocol_tpu import native
from protocol_tpu.ops.cost import CostWeights
from protocol_tpu.ops.sparse import sinkhorn_potentials_sparse_np

from tests.test_sparse import encode_random_marketplace

pytestmark = pytest.mark.skipif(
    not native.available(), reason="no native toolchain"
)


def _synthetic_candidates(seed, T, P, K=24, invalid_frac=0.1):
    """Random candidate lists (no generation cost): the sinkhorn engine
    consumes any [T, K] slot layout, so structure-free inputs are enough
    for numerics tests and let T exceed the helper-pool threshold."""
    rng = np.random.default_rng(seed)
    cand_p = rng.integers(0, P, size=(T, K), dtype=np.int32)
    cand_p[rng.random((T, K)) < invalid_frac] = -1
    cand_c = rng.uniform(0.5, 10.0, size=(T, K)).astype(np.float32)
    return cand_p, cand_c


class TestNumpyParity:
    def test_matches_reference_at_2k(self):
        """The acceptance bar: native potentials match the pure-NumPy
        reference to <= 1e-6 at 2k x 2k, on REAL marketplace candidates
        (the fused generator's output, infeasible padding included)."""
        ep, er = encode_random_marketplace(11, 2048, 2048)
        cand_p, cand_c = native.fused_topk_candidates(
            ep, er, CostWeights(), k=16, reverse_r=4, extra=8
        )
        for eps, f0, g0 in [(0.2, None, None)]:
            f, g, it, err = native.sinkhorn_sparse_mt(
                cand_p, cand_c, 2048, eps=eps, max_iters=30, tol=1e-4,
                threads=2, f=f0, g=g0,
            )
            fr, gr, itr, errr = sinkhorn_potentials_sparse_np(
                cand_p, cand_c, 2048, eps=eps, max_iters=30, tol=1e-4,
                f0=f0, g0=g0,
            )
            assert it == itr
            np.testing.assert_allclose(f, fr, rtol=1e-6, atol=1e-6)
            np.testing.assert_allclose(g, gr, rtol=1e-6, atol=1e-6)
        # second phase warm from the first's duals (the anneal step):
        # the carried-potential path must track the reference too
        f2, g2, it2, _ = native.sinkhorn_sparse_mt(
            cand_p, cand_c, 2048, eps=0.05, max_iters=20, tol=1e-4,
            threads=2, f=f, g=g,
        )
        fr2, gr2, itr2, _ = sinkhorn_potentials_sparse_np(
            cand_p, cand_c, 2048, eps=0.05, max_iters=20, tol=1e-4,
            f0=fr, g0=gr,
        )
        assert it2 == itr2
        np.testing.assert_allclose(f2, fr2, rtol=1e-6, atol=1e-6)
        np.testing.assert_allclose(g2, gr2, rtol=1e-6, atol=1e-6)


class TestThreadInvariance:
    @pytest.mark.parametrize("threads", [2, 4])
    def test_bit_identical_small(self, threads):
        cand_p, cand_c = _synthetic_candidates(0, 512, 512)
        ref = native.sinkhorn_sparse_mt(
            cand_p, cand_c, 512, eps=0.1, max_iters=25, tol=1e-4, threads=1
        )
        got = native.sinkhorn_sparse_mt(
            cand_p, cand_c, 512, eps=0.1, max_iters=25, tol=1e-4,
            threads=threads,
        )
        np.testing.assert_array_equal(got[0], ref[0])
        np.testing.assert_array_equal(got[1], ref[1])
        assert got[2] == ref[2] and got[3] == ref[3]

    @pytest.mark.parametrize("threads", [2, 4])
    def test_bit_identical_above_parallel_threshold(self, threads):
        """The engine engages its helper pool only when max(P, T) >=
        kParMinRows (4096): the small cases above run the inline path,
        which would let a chunk-boundary dependence in the parallel
        passes ship unnoticed. 16k rows push past the threshold so the
        pool genuinely runs."""
        cand_p, cand_c = _synthetic_candidates(1, 16384, 16384, K=16)
        ref = native.sinkhorn_sparse_mt(
            cand_p, cand_c, 16384, eps=0.1, max_iters=12, tol=0.0, threads=1
        )
        got = native.sinkhorn_sparse_mt(
            cand_p, cand_c, 16384, eps=0.1, max_iters=12, tol=0.0,
            threads=threads,
        )
        np.testing.assert_array_equal(got[0], ref[0])
        np.testing.assert_array_equal(got[1], ref[1])
        assert got[2] == ref[2] and got[3] == ref[3]


class TestShiftInvariance:
    def test_uniform_shift_preserves_the_plan(self):
        """The warm-carry soundness argument: the plan exp((f+g-c)/eps)
        is invariant under (f - s, g + s), so a carried potential pair is
        as good a warm start as any of its shifts — one update from
        shifted duals lands exactly one shift away from the unshifted
        run (the f update re-pins the gauge)."""
        cand_p, cand_c = _synthetic_candidates(2, 1024, 1024)
        f0, g0, _, _ = native.sinkhorn_sparse_mt(
            cand_p, cand_c, 1024, eps=0.1, max_iters=10, tol=0.0, threads=2
        )
        shift = np.float32(3.5)
        fa, ga, ita, _ = native.sinkhorn_sparse_mt(
            cand_p, cand_c, 1024, eps=0.1, max_iters=5, tol=0.0, threads=2,
            f=f0, g=g0,
        )
        fb, gb, itb, _ = native.sinkhorn_sparse_mt(
            cand_p, cand_c, 1024, eps=0.1, max_iters=5, tol=0.0, threads=2,
            f=f0 - shift, g=g0 + shift,
        )
        assert ita == itb
        # f depends on g only through (g - c)/eps: the shifted run's f is
        # the unshifted f minus the shift, g re-converges on top of it
        np.testing.assert_allclose(fb + shift, fa, rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(gb - shift, ga, rtol=2e-5, atol=2e-5)


class TestArenaSinkhorn:
    def _marketplace(self, seed=0, n=256):
        return encode_random_marketplace(seed, n, n)

    def test_cold_solve_injective_and_auction_grade(self):
        from protocol_tpu.native.arena import NativeSolveArena

        ep, er = self._marketplace(7, 512)
        w = CostWeights()
        a_sink = NativeSolveArena(threads=2, engine="sinkhorn")
        a_auc = NativeSolveArena(threads=2)
        p_s = a_sink.solve(ep, er, w)
        p_a = a_auc.solve(ep, er, w)
        assert a_sink.last_stats["engine"] == "sinkhorn"
        assert a_sink.last_stats["sinkhorn_iters"] > 0
        pos = p_s[p_s >= 0]
        assert np.unique(pos).size == pos.size
        n_s, n_a = int((p_s >= 0).sum()), int((p_a >= 0).sum())
        # referee rounding must not lose matchings the plain auction finds
        assert n_s >= n_a - max(2, 512 // 100)
        f, g = a_sink.potentials
        assert f is not None and f.shape == (512,)
        assert g is not None and g.shape == (512,)

    def test_warm_recomputes_only_dirty_rows(self, monkeypatch):
        """The warm contract on the sinkhorn path: churn flows through
        the SAME incremental repair kernel as the auction engine — zero
        fused candidate passes, zero full-matrix regenerations — and
        the potentials re-converge from the carried (f, g) instead of a
        cold anneal."""
        from protocol_tpu.native.arena import NativeSolveArena

        ep, er = self._marketplace(3, 256)
        w = CostWeights()
        arena = NativeSolveArena(threads=2, engine="sinkhorn")
        arena.solve(ep, er, w)
        f_before = arena.potentials[0].copy()

        mem = np.array(ep.gpu_mem_mb, copy=True)
        mem[[5, 60]] += 8000
        ep2 = dataclasses.replace(ep, gpu_mem_mb=mem)
        monkeypatch.setattr(
            native, "fused_topk_candidates",
            lambda *a, **kw: pytest.fail(
                "sinkhorn warm churn ran a fused candidate pass"
            ),
        )
        p4t = arena.solve(ep2, er, w)
        stats = arena.last_stats
        assert stats["cold"] is False
        assert stats["engine"] == "sinkhorn"
        assert stats["dirty_providers"] == 2
        assert stats["cand_cold_passes"] == 0
        assert stats["sinkhorn_phases"] == 1  # warm: single fine phase
        pos = p4t[p4t >= 0]
        assert np.unique(pos).size == pos.size
        # potentials were carried and re-converged, not reset to zero
        f_after = arena.potentials[0]
        assert not np.array_equal(f_after, np.zeros_like(f_after))
        assert np.abs(f_after - f_before).max() < 10.0
        # the repaired structure is the cold structure, bit for bit
        monkeypatch.undo()
        ref_p, ref_c = native.fused_topk_candidates(
            ep2, er, w, k=arena.k, reverse_r=arena.reverse_r,
            extra=arena.extra, threads=2,
        )
        np.testing.assert_array_equal(arena._cand_p, ref_p)
        np.testing.assert_array_equal(arena._cand_c, ref_c)

    def test_no_churn_short_circuits(self, monkeypatch):
        from protocol_tpu.native.arena import NativeSolveArena

        ep, er = self._marketplace(5, 256)
        w = CostWeights()
        arena = NativeSolveArena(threads=2, engine="sinkhorn")
        p1 = arena.solve(ep, er, w)
        monkeypatch.setattr(
            native, "fused_topk_candidates",
            lambda *a, **kw: pytest.fail("byte-identical solve regenerated"),
        )
        monkeypatch.setattr(
            native, "sinkhorn_sparse_mt",
            lambda *a, **kw: pytest.fail("byte-identical solve re-iterated"),
        )
        p2 = arena.solve(ep, er, w)
        np.testing.assert_array_equal(p1, p2)
        assert arena.last_stats["changed_rows"] == 0

    def test_matcher_engages_sinkhorn_arena(self):
        """TpuBatchMatcher(native_engine='sinkhorn-mt') routes phase 1
        through the sinkhorn arena and reports its stats."""
        import random

        from protocol_tpu.models.task import (
            SchedulingConfig,
            Task,
            TaskRequest,
        )
        from protocol_tpu.sched.tpu_backend import TpuBatchMatcher
        from protocol_tpu.store import (
            NodeStatus,
            OrchestratorNode,
            StoreContext,
        )
        from tests.test_encoding import random_specs

        rng = random.Random(9)
        store = StoreContext.new_test()
        for i in range(12):
            store.node_store.add_node(
                OrchestratorNode(
                    address=f"0xsk{i:02d}",
                    status=NodeStatus.HEALTHY,
                    compute_specs=random_specs(rng),
                )
            )
        store.task_store.add_task(
            Task.from_request(
                TaskRequest(
                    name="sk-b",
                    image="img",
                    scheduling_config=SchedulingConfig(
                        plugins={"tpu_scheduler": {"replicas": ["4"]}}
                    ),
                )
            )
        )
        m = TpuBatchMatcher(
            store, min_solve_interval=0.0, native_fallback=True,
            native_engine="sinkhorn-mt", native_threads=2,
        )
        m.refresh()
        assert m.last_solve_stats["kernel"] == "native_cpu_sinkhorn_mt"
        assert m.last_solve_stats["arena_cold"] is True
        assert m.last_solve_stats["arena_engine"] == "sinkhorn"
        first = dict(m._assignment)
        m.mark_dirty()
        m.refresh()
        assert m.last_solve_stats["arena_cold"] is False
        assert m._assignment == first  # steady state: no flapping

    def test_rejects_unknown_engine(self):
        from protocol_tpu.native.arena import NativeSolveArena
        from protocol_tpu.sched.tpu_backend import TpuBatchMatcher
        from protocol_tpu.store import StoreContext

        with pytest.raises(ValueError):
            NativeSolveArena(engine="simplex")
        with pytest.raises(ValueError):
            TpuBatchMatcher(
                StoreContext.new_test(), native_engine="sinkhorn"
            )


class TestGrpcKernel:
    def test_unary_assign_with_sinkhorn_kernel(self):
        """kernel='sinkhorn-mt:2' through the v1 Assign surface: the
        servicer's unary arena solves with the sinkhorn engine, and a
        repeat call rides the warm path (same matching, no flapping)."""
        from protocol_tpu.services.scheduler_grpc import (
            SchedulerBackendServicer,
            encoded_to_proto,
        )

        ep, er = encode_random_marketplace(13, 96, 64)
        servicer = SchedulerBackendServicer()
        req = encoded_to_proto(
            ep, er, CostWeights(), kernel="sinkhorn-mt:2", top_k=16
        )
        resp1 = servicer.Assign(req, context=None)
        assert servicer._native_arena is not None
        assert servicer._native_arena.engine == "sinkhorn"
        p4t = np.asarray(resp1.provider_for_task, np.int32)
        pos = p4t[p4t >= 0]
        assert np.unique(pos).size == pos.size
        assert resp1.num_assigned == int((p4t >= 0).sum())
        resp2 = servicer.Assign(req, context=None)
        np.testing.assert_array_equal(
            np.asarray(resp2.provider_for_task, np.int32), p4t
        )

    def test_parse_session_kernel(self):
        from protocol_tpu.services.session_store import (
            parse_native_threads,
            parse_session_kernel,
        )

        assert parse_session_kernel("native-mt") == ("auction", 0)
        assert parse_session_kernel("native-mt:4") == ("auction", 4)
        assert parse_session_kernel("sinkhorn-mt") == ("sinkhorn", 0)
        assert parse_session_kernel("sinkhorn-mt:2") == ("sinkhorn", 2)
        assert parse_session_kernel("topk") is None
        assert parse_session_kernel("sinkhorn-mt:x") is None
        assert parse_native_threads("sinkhorn-mt:3") == 3
        assert parse_native_threads("auction") is None
