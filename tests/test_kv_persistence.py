"""KV durability: journal + replay + compaction, orchestrator
kill-and-restart preserving nodes/tasks/groups (the reference's Redis
outliving the process, orchestrator/src/store/core/redis.rs:38-72), and a
SIGKILL'd writer process losing nothing that was journaled."""

import pytest

# Environment guard: this module's import chain reaches
# protocol_tpu.security / protocol_tpu.utils.tls, which need the
# third-party `cryptography` package (wallet signing + TLS material).
# On hosts without it, report the whole module as SKIPPED instead of a
# collection error (tier-1 keeps an honest skip count; CI installs
# cryptography and runs everything).
pytest.importorskip(
    "cryptography", reason="cryptography not installed (signing/TLS dependency)"
)

import os
import signal
import subprocess
import sys
import time

from protocol_tpu.chain import Ledger
from protocol_tpu.models.task import Task, TaskRequest
from protocol_tpu.security import Wallet
from protocol_tpu.sched.node_groups import NodeGroupConfiguration, NodeGroupsPlugin
from protocol_tpu.services.orchestrator import OrchestratorService
from protocol_tpu.store import NodeStatus, OrchestratorNode
from protocol_tpu.store.kv import KVStore


def test_journal_replay_all_types(tmp_path):
    p = str(tmp_path / "kv.aof")
    kv = KVStore(persist_path=p)
    kv.set("a", "1")
    kv.set("gone", "x", ex=0.01)
    kv.set("keep", "y", ex=3600)
    kv.hset("h", "f", "v")
    kv.hincrby("h", "n", 7)
    kv.sadd("s", "m1", "m2")
    kv.srem("s", "m2")
    kv.zadd("z", {"p": 1.5, "q": 2.5})
    kv.zrem("z", "q")
    kv.rpush("l", "x", "y")
    kv.lrem("l", 1, "x")
    kv.incr("ctr")
    kv.incr("ctr")
    kv.delete("a")
    time.sleep(0.02)

    kv2 = KVStore(persist_path=p)
    assert kv2.get("a") is None
    assert kv2.get("gone") is None  # TTL expired across the restart
    assert kv2.get("keep") == "y" and kv2.ttl("keep") > 3500
    assert kv2.hgetall("h") == {"f": "v", "n": "7"}
    assert kv2.smembers("s") == {"m1"}
    assert kv2.zrangebyscore("z") == [("p", 1.5)]
    assert kv2.lrange("l") == ["y"]
    assert kv2.get("ctr") == "2"


def test_failed_nx_write_not_journaled(tmp_path):
    """A failed SET NX (and EXPIRE on a missing key) mutates nothing and
    must not be journaled: replaying an expired NX SET would otherwise
    delete a durable value the original call never replaced."""
    p = str(tmp_path / "kv.aof")
    kv = KVStore(persist_path=p)
    kv.set("k", "durable")
    assert kv.set("k", "claim", nx=True, ex=0.01) is False
    assert kv.expire("missing", 5) is False
    time.sleep(0.02)

    kv2 = KVStore(persist_path=p)
    assert kv2.get("k") == "durable"
    assert kv2.ttl("k") is None


def test_compaction_bounds_journal(tmp_path):
    p = str(tmp_path / "kv.aof")
    kv = KVStore(persist_path=p, compact_threshold=50)
    for i in range(300):
        kv.set("k", str(i))  # same key rewritten: compacts to one line
    kv2 = KVStore(persist_path=p)
    assert kv2.get("k") == "299"
    assert len(open(p).read().splitlines()) <= 51


def test_orchestrator_restart_preserves_pool_state(tmp_path):
    p = str(tmp_path / "orch.aof")
    ledger = Ledger()
    creator, manager = Wallet.from_seed(b"kc"), Wallet.from_seed(b"km")
    did = ledger.create_domain("d")
    pid = ledger.create_pool(did, creator.address, manager.address, "")

    svc = OrchestratorService(ledger, pid, manager, persist_path=p)
    svc.store.node_store.add_node(
        OrchestratorNode(address="0xn1", status=NodeStatus.HEALTHY,
                         ip_address="1.2.3.4", port=80)
    )
    task = Task.from_request(TaskRequest(name="job", image="img"))
    svc.store.task_store.add_task(task)
    groups = NodeGroupsPlugin(
        svc.store,
        [NodeGroupConfiguration(name="solo", min_group_size=1, max_group_size=1)],
    )
    group = groups._create_group(groups.configurations[0], ["0xn1"])
    del svc  # "kill" the orchestrator

    svc2 = OrchestratorService(ledger, pid, manager, persist_path=p)
    node = svc2.store.node_store.get_node("0xn1")
    assert node is not None and node.status == NodeStatus.HEALTHY
    tasks = svc2.store.task_store.get_all_tasks()
    assert [t.name for t in tasks] == ["job"]
    groups2 = NodeGroupsPlugin(
        svc2.store,
        [NodeGroupConfiguration(name="solo", min_group_size=1, max_group_size=1)],
    )
    restored = groups2.group_for_node("0xn1")
    assert restored is not None and restored.id == group.id


def test_sigkilled_writer_loses_nothing_journaled(tmp_path):
    """SIGKILL the writing process mid-run; every write it completed must
    be visible after reload (line-buffered AOF semantics)."""
    p = str(tmp_path / "kv.aof")
    ready = str(tmp_path / "ready")
    code = f"""
import sys, time
sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
from protocol_tpu.store.kv import KVStore
kv = KVStore(persist_path={p!r})
for i in range(500):
    kv.set(f"k{{i}}", str(i))
open({ready!r}, "w").write("500")
time.sleep(30)  # hold the process open for the SIGKILL
"""
    proc = subprocess.Popen([sys.executable, "-S", "-c", code])
    deadline = time.time() + 30
    while not os.path.exists(ready) and time.time() < deadline:
        time.sleep(0.05)
    assert os.path.exists(ready), "writer never finished its writes"
    os.kill(proc.pid, signal.SIGKILL)
    proc.wait()

    kv = KVStore(persist_path=p)
    for i in range(500):
        assert kv.get(f"k{i}") == str(i)
