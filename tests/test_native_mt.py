"""Multi-threaded native engine (engine=native-mt): thread-count
invariance, quality parity with the Gauss-Seidel engine, and the
persistent warm-solve arena's only-dirty-rows-recomputed contract.

The -mt engine is DETERMINISTIC by construction (synchronous Jacobi
bidding rounds merged by a value-based reduction): the matching must be
bit-identical for every thread count, which is what makes a threads=4
production deployment debuggable against a threads=1 repro.
"""

import dataclasses

import numpy as np
import pytest

from protocol_tpu import native
from protocol_tpu.ops.cost import CostWeights

from tests.test_sparse import encode_random_marketplace

pytestmark = pytest.mark.skipif(
    not native.available(), reason="no native toolchain"
)

N = 512


def _total_cost(cand_p, cand_c, p4t):
    """Sum of each assigned task's cost on its provider (looked up in the
    candidate list — the only cost surface the auction ever sees)."""
    total = 0.0
    for t, p in enumerate(p4t):
        if p < 0:
            continue
        (j,) = np.where(cand_p[t] == p)[:1]
        total += float(cand_c[t, j[0]])
    return total


def _dense_candidates():
    rng = np.random.default_rng(0)
    cost = rng.uniform(0.0, 10.0, size=(N, N)).astype(np.float32)
    return native.topk_candidates(cost, k=64)


def _sparse_candidates():
    ep, er = encode_random_marketplace(7, N, N)
    return native.fused_topk_candidates(
        ep, er, CostWeights(), k=16, reverse_r=8, extra=16
    )


class TestThreadParity:
    @pytest.mark.parametrize("threads", [1, 2, 4])
    @pytest.mark.parametrize("case", ["dense", "sparse"])
    def test_identical_assignments_and_cost(self, case, threads):
        cand_p, cand_c = (
            _dense_candidates() if case == "dense" else _sparse_candidates()
        )
        ref, ref_price, ref_retired = native.auction_sparse_mt(
            cand_p, cand_c, num_providers=N, threads=1
        )
        got, price, retired = native.auction_sparse_mt(
            cand_p, cand_c, num_providers=N, threads=threads
        )
        np.testing.assert_array_equal(got, ref)
        np.testing.assert_array_equal(price, ref_price)
        np.testing.assert_array_equal(retired, ref_retired)
        assert _total_cost(cand_p, cand_c, got) == _total_cost(
            cand_p, cand_c, ref
        )

    @pytest.mark.parametrize("case", ["dense", "sparse"])
    def test_quality_parity_with_gauss_seidel_engine(self, case):
        """The Jacobi engine is a different (deterministic) bidding
        schedule, not a different problem: its matching must be as
        complete as the Gauss-Seidel engine's and economically close."""
        cand_p, cand_c = (
            _dense_candidates() if case == "dense" else _sparse_candidates()
        )
        p4t_gs = native.auction_sparse(cand_p, cand_c, num_providers=N)
        p4t_mt, _, _ = native.auction_sparse_mt(
            cand_p, cand_c, num_providers=N, threads=2
        )
        n_gs = int((p4t_gs >= 0).sum())
        n_mt = int((p4t_mt >= 0).sum())
        assert n_mt >= n_gs - max(2, N // 100)
        pos = p4t_mt[p4t_mt >= 0]
        assert np.unique(pos).size == pos.size  # a matching, always
        if n_gs == n_mt and n_gs > 0:
            c_gs = _total_cost(cand_p, cand_c, p4t_gs)
            c_mt = _total_cost(cand_p, cand_c, p4t_mt)
            assert c_mt <= c_gs * 1.05 + 1.0

    @pytest.mark.parametrize("threads", [2, 4])
    def test_identical_above_parallel_threshold(self, threads):
        """The engine only engages its helper pool when a round has
        >= kParMin (8192) open tasks — the 512-row cases above all run the
        inline path, which would let a race or chunk-boundary dependence
        in the PARALLEL bid pass ship unnoticed. Synthetic candidate
        lists (no generation cost) push T past the threshold so the pool
        genuinely runs."""
        rng = np.random.default_rng(1)
        T = P = 16384
        cand_p = rng.integers(0, P, size=(T, 16), dtype=np.int32)
        cand_c = rng.uniform(0.0, 10.0, size=(T, 16)).astype(np.float32)
        ref, ref_price, ref_retired = native.auction_sparse_mt(
            cand_p, cand_c, num_providers=P, threads=1
        )
        got, price, retired = native.auction_sparse_mt(
            cand_p, cand_c, num_providers=P, threads=threads
        )
        np.testing.assert_array_equal(got, ref)
        np.testing.assert_array_equal(price, ref_price)
        np.testing.assert_array_equal(retired, ref_retired)

    @pytest.mark.parametrize("threads", [2, 4])
    def test_fused_generation_identical(self, threads):
        ep, er = encode_random_marketplace(3, N, N)
        ref = native.fused_topk_candidates(
            ep, er, CostWeights(), k=16, threads=1
        )
        st = native.fused_topk_candidates(ep, er, CostWeights(), k=16)
        got = native.fused_topk_candidates(
            ep, er, CostWeights(), k=16, threads=threads
        )
        np.testing.assert_array_equal(got[0], ref[0])
        np.testing.assert_array_equal(got[1], ref[1])
        # and the mt engine reproduces the historical single-threaded pass
        np.testing.assert_array_equal(got[0], st[0])
        np.testing.assert_array_equal(got[1], st[1])


class TestWarmArena:
    def _marketplace(self, seed=0, n=256):
        ep, er = encode_random_marketplace(seed, n, n)
        return ep, er

    def test_no_churn_reuses_everything(self, monkeypatch):
        from protocol_tpu.native.arena import NativeSolveArena

        ep, er = self._marketplace()
        arena = NativeSolveArena(threads=2)
        p1 = arena.solve(ep, er, CostWeights())
        calls = []
        real = native.fused_topk_candidates
        monkeypatch.setattr(
            native, "fused_topk_candidates",
            lambda *a, **kw: calls.append(a) or real(*a, **kw),
        )
        p2 = arena.solve(ep, er, CostWeights())
        assert calls == []  # byte-identical marketplace: zero regeneration
        np.testing.assert_array_equal(p1, p2)
        assert arena.last_stats["changed_rows"] == 0
        assert arena.last_stats["cold"] is False

    def test_churn_repairs_in_place_without_any_fused_pass(
        self, monkeypatch
    ):
        """Mixed churn (specs + price + task priorities) must flow
        entirely through the native repair kernel — zero calls to the
        fused generator, zero full-matrix passes — and leave the
        persistent structure bit-identical to a from-scratch rebuild on
        the churned features."""
        from protocol_tpu.native.arena import NativeSolveArena

        ep, er = self._marketplace()
        arena = NativeSolveArena(threads=2)
        arena.solve(ep, er, CostWeights())

        mem = np.array(ep.gpu_mem_mb, copy=True)
        mem[[3, 50, 99, 120, 200]] += 8000
        price = np.array(ep.price, copy=True)
        price[[10, 11]] += 0.5
        ep2 = dataclasses.replace(ep, gpu_mem_mb=mem, price=price)
        prio = np.array(er.priority, copy=True)
        prio[[7, 8, 9]] += 0.25
        er2 = dataclasses.replace(er, priority=prio)

        monkeypatch.setattr(
            native, "fused_topk_candidates",
            lambda *a, **kw: pytest.fail(
                "warm churn ran a fused candidate pass"
            ),
        )
        p4t = arena.solve(ep2, er2, CostWeights())
        stats = arena.last_stats
        assert stats["cold"] is False
        assert stats["cand_cold_passes"] == 0
        assert stats["dirty_providers"] == 5
        assert stats["base_only_providers"] == 2
        assert stats["dirty_tasks"] == 3
        pos = p4t[p4t >= 0]
        assert np.unique(pos).size == pos.size
        # the repaired structure IS the cold structure, bit for bit
        monkeypatch.undo()
        rev_ref = np.zeros_like(arena._rev)
        ref_p, ref_c = native.fused_topk_candidates(
            ep2, er2, CostWeights(), k=arena.k,
            reverse_r=arena.reverse_r, extra=arena.extra,
            threads=2, rev_out=rev_ref,
        )
        np.testing.assert_array_equal(arena._cand_p, ref_p)
        np.testing.assert_array_equal(arena._cand_c, ref_c)
        np.testing.assert_array_equal(arena._rev, rev_ref)

    def test_base_only_churn_repairs_membership_exactly(self, monkeypatch):
        """Price drift is churn like any other under the exactness
        contract: no fused pass, but a repriced provider's candidate
        entries (and any membership it gained or lost) match a cold
        rebuild exactly — not the historical stale in-place shift."""
        from protocol_tpu.native.arena import NativeSolveArena

        ep, er = self._marketplace()
        arena = NativeSolveArena(threads=2)
        arena.solve(ep, er, CostWeights())

        price = np.array(ep.price, copy=True)
        price[7] += 0.25
        ep2 = dataclasses.replace(ep, price=price)
        monkeypatch.setattr(
            native, "fused_topk_candidates",
            lambda *a, **kw: pytest.fail("base-only churn ran a fused pass"),
        )
        arena.solve(ep2, er, CostWeights())
        assert arena.last_stats["base_only_providers"] == 1
        assert arena.last_stats["dirty_providers"] == 0
        assert arena.last_stats["cand_cold_passes"] == 0
        monkeypatch.undo()
        ref_p, ref_c = native.fused_topk_candidates(
            ep2, er, CostWeights(), k=arena.k,
            reverse_r=arena.reverse_r, extra=arena.extra, threads=2,
        )
        np.testing.assert_array_equal(arena._cand_p, ref_p)
        np.testing.assert_array_equal(arena._cand_c, ref_c)

    def test_heavy_churn_falls_back_to_cold(self):
        from protocol_tpu.native.arena import NativeSolveArena

        ep, er = self._marketplace()
        arena = NativeSolveArena(threads=2, max_dirty_frac=0.1)
        arena.solve(ep, er, CostWeights())
        cores = np.array(ep.cpu_cores, copy=True)
        cores += 1  # every provider STRUCT dirty
        p4t = arena.solve(
            dataclasses.replace(ep, cpu_cores=cores), er, CostWeights()
        )
        assert arena.last_stats["cold"] is True
        pos = p4t[p4t >= 0]
        assert np.unique(pos).size == pos.size

    def test_fleetwide_price_drift_regrounds_cold(self):
        """A fleet-wide reprice dirties every provider: under the
        exactness contract the repair would cost a cold pass anyway, so
        max_dirty_frac routes it to an HONEST cold rebuild instead of
        the historical stay-warm-on-stale-selections shift (whose
        membership drifted until the next cold_every beat)."""
        from protocol_tpu.native.arena import NativeSolveArena

        ep, er = self._marketplace()
        arena = NativeSolveArena(threads=2, max_dirty_frac=0.1)
        arena.solve(ep, er, CostWeights())
        price = np.array(ep.price, copy=True)
        price += 0.01
        p4t = arena.solve(
            dataclasses.replace(ep, price=price), er, CostWeights()
        )
        assert arena.last_stats["cold"] is True
        assert arena.last_stats["cand_cold_passes"] == 1
        pos = p4t[p4t >= 0]
        assert np.unique(pos).size == pos.size

    def test_matcher_engages_arena(self):
        """TpuBatchMatcher(native_engine='native-mt') routes phase 1
        through the arena and reports its reuse stats."""
        import random

        from protocol_tpu.models.task import (
            SchedulingConfig,
            Task,
            TaskRequest,
        )
        from protocol_tpu.sched.tpu_backend import TpuBatchMatcher
        from protocol_tpu.store import (
            NodeStatus,
            OrchestratorNode,
            StoreContext,
        )
        from tests.test_encoding import random_specs

        rng = random.Random(5)
        store = StoreContext.new_test()
        for i in range(12):
            store.node_store.add_node(
                OrchestratorNode(
                    address=f"0xmt{i:02d}",
                    status=NodeStatus.HEALTHY,
                    compute_specs=random_specs(rng),
                )
            )
        store.task_store.add_task(
            Task.from_request(
                TaskRequest(
                    name="mt-b",
                    image="img",
                    scheduling_config=SchedulingConfig(
                        plugins={"tpu_scheduler": {"replicas": ["4"]}}
                    ),
                )
            )
        )
        m = TpuBatchMatcher(
            store, min_solve_interval=0.0, native_fallback=True,
            native_engine="native-mt", native_threads=2,
        )
        m.refresh()
        assert m.last_solve_stats["kernel"] == "native_cpu_mt"
        assert m.last_solve_stats["arena_cold"] is True
        first = dict(m._assignment)
        m.mark_dirty()
        m.refresh()
        assert m.last_solve_stats["arena_cold"] is False
        assert m.last_solve_stats["arena_changed_rows"] == 0
        assert m._assignment == first  # steady state: no flapping


class TestStaleRetirementClearedOnChurn:
    """ADVICE r5 (stale-retirement starvation), native-arena twin of the
    tpu_backend fix: a carried retirement flag must be cleared for
    exactly the rows whose candidates churned — otherwise a task that
    retired for want of a feasible provider stays starved until the
    next cold solve (cold_every beats) even after a provider it can use
    appears. Regression: a churned warm chain where the row must become
    re-biddable AND actually seat."""

    def _scarce_marketplace(self):
        """256x256 marketplace where task 0 has NO feasible provider
        (cpu demand beyond every spec): the cold solve organically
        retires it (no-candidates retirement, not an injected flag)."""
        ep, er = encode_random_marketplace(11, 256, 256)
        req_cores = np.array(er.cpu_cores, copy=True)
        req_cpu = np.array(er.cpu_required, copy=True)
        req_ram = np.array(er.ram_mb, copy=True)
        req_storage = np.array(er.storage_gb, copy=True)
        gpu_opt = np.array(er.gpu_opt_valid, copy=True)
        req_cores[0] = 1_000_000
        req_cpu[0] = True
        # the cpu demand is the ONLY constraint on task 0: the upgraded
        # provider must fail/pass on exactly that axis
        req_ram[0] = -1
        req_storage[0] = -1
        gpu_opt[0, :] = False
        er = dataclasses.replace(
            er, cpu_cores=req_cores, cpu_required=req_cpu,
            ram_mb=req_ram, storage_gb=req_storage, gpu_opt_valid=gpu_opt,
        )
        return ep, er

    def test_churned_row_is_rebiddable(self):
        from protocol_tpu.native.arena import NativeSolveArena

        ep, er = self._scarce_marketplace()
        w = CostWeights()
        arena = NativeSolveArena(threads=2, cold_every=1_000_000)
        p1 = arena.solve(ep, er, w)
        assert p1[0] == -1
        assert bool(np.asarray(arena.retired)[0])

        # warm tick with UNRELATED churn (another task's priority): task
        # 0's candidates did not change, so the carried flag must
        # SURVIVE — the carry is the point; clearing everything would
        # re-fight the priced-out tail every tick
        prio = np.array(er.priority, copy=True)
        prio[200] += 0.25
        er2 = dataclasses.replace(er, priority=prio)
        p2 = arena.solve(ep, er2, w)
        assert p2[0] == -1
        assert bool(np.asarray(arena.retired)[0])
        assert arena.last_stats["cold"] is False

        # churned warm chain: ONE provider upgrades to satisfy task 0
        # (structural churn -> delta pass folds it into row 0) — the
        # flag must clear and the row must be re-biddable, seating task
        # 0 in the SAME warm solve instead of starving until cold
        cores = np.array(ep.cpu_cores, copy=True)
        has_cpu = np.array(ep.has_cpu, copy=True)
        cores[42] = 1_000_000
        has_cpu[42] = True
        ep3 = dataclasses.replace(ep, cpu_cores=cores, has_cpu=has_cpu)
        p3 = arena.solve(ep3, er2, w)
        assert arena.last_stats["cold"] is False
        assert arena.last_stats["dirty_providers"] == 1
        assert not bool(np.asarray(arena.retired)[0])
        assert p3[0] >= 0, (
            "task 0 stayed starved after a feasible provider churned in "
            "— stale carried retirement (ADVICE r5)"
        )
        pos = p3[p3 >= 0]
        assert np.unique(pos).size == pos.size  # matching stays injective

    def test_sinkhorn_arena_rebids_churned_row(self):
        """Same chain through the sinkhorn engine's referee (shares the
        candidate machinery; retirement carry rides the referee seed)."""
        from protocol_tpu.native.arena import NativeSolveArena

        ep, er = self._scarce_marketplace()
        w = CostWeights()
        arena = NativeSolveArena(
            threads=2, engine="sinkhorn", cold_every=1_000_000
        )
        p1 = arena.solve(ep, er, w)
        assert p1[0] == -1
        cores = np.array(ep.cpu_cores, copy=True)
        has_cpu = np.array(ep.has_cpu, copy=True)
        cores[42] = 1_000_000
        has_cpu[42] = True
        ep2 = dataclasses.replace(ep, cpu_cores=cores, has_cpu=has_cpu)
        p2 = arena.solve(ep2, er, w)
        assert arena.last_stats["cold"] is False
        assert p2[0] >= 0
