"""GCS/S3 storage providers against a fake bucket that verifies real V4
signatures (reference google_cloud.rs:16-233)."""

import pytest

# Environment guard: this module's import chain reaches
# protocol_tpu.security / protocol_tpu.utils.tls, which need the
# third-party `cryptography` package (wallet signing + TLS material).
# On hosts without it, report the whole module as SKIPPED instead of a
# collection error (tier-1 keeps an honest skip count; CI installs
# cryptography and runs everything).
pytest.importorskip(
    "cryptography", reason="cryptography not installed (signing/TLS dependency)"
)

import asyncio
import base64
import json

import pytest
from aiohttp.test_utils import TestServer
from cryptography.hazmat.primitives import serialization
from cryptography.hazmat.primitives.asymmetric import rsa

from protocol_tpu.utils.cloud_storage import (
    GcsStorageProvider,
    S3StorageProvider,
    _split_bucket,
)

from tests.fake_bucket import FakeBucket


def run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


@pytest.fixture(scope="module")
def sa_creds():
    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    pem = key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    ).decode()
    creds = base64.b64encode(
        json.dumps(
            {"client_email": "svc@test.iam.gserviceaccount.com",
             "private_key": pem}
        ).encode()
    ).decode()
    return creds, key.public_key()


def test_bucket_subpath_split():
    assert _split_bucket("mybucket") == ("mybucket", "")
    assert _split_bucket("mybucket/runs/a") == ("mybucket", "runs/a")


def test_gcs_full_cycle_with_signature_verification(sa_creds):
    creds, pub = sa_creds
    bucket = FakeBucket(rsa_public_key=pub)

    async def flow():
        import aiohttp

        server = TestServer(bucket.make_app())
        await server.start_server()
        base = str(server.make_url("")).rstrip("/")
        async with aiohttp.ClientSession() as client:
            gcs = GcsStorageProvider(
                "artifacts/pool-7", creds, client, endpoint=base
            )
            # mapping write + resolve (google_cloud.rs:84-141)
            await gcs.generate_mapping_file("ab" * 32, "run_1/file.parquet")
            assert (
                await gcs.resolve_mapping_for_sha("ab" * 32)
            ) == "run_1/file.parquet"
            assert await gcs.resolve_mapping_for_sha("cd" * 32) is None
            # subpath is part of the object key
            assert f"artifacts/pool-7/mapping/{'ab' * 32}" in bucket.objects

            # worker-style upload through a minted signed URL
            url = await gcs.generate_upload_signed_url(
                "out.parquet", max_bytes=11
            )
            async with client.put(
                url, data=b"hello world",
                headers={"Content-Length": "11"},
            ) as resp:
                assert resp.status == 200, await resp.text()
            assert await gcs.file_exists("out.parquet")
            assert not await gcs.file_exists("missing.bin")

            # the SIGNED content-length binds the size: lying fails
            url2 = await gcs.generate_upload_signed_url("big.bin", max_bytes=4)
            async with client.put(
                url2, data=b"toolarge", headers={"Content-Length": "8"}
            ) as resp:
                assert resp.status == 403

            # names needing percent-encoding survive sign + verify: the
            # URL path and the signed canonical path use ONE encoding
            url3 = await gcs.generate_upload_signed_url(
                "run 1/out file+pct%.parquet", max_bytes=3
            )
            async with client.put(
                url3, data=b"abc", headers={"Content-Length": "3"}
            ) as resp:
                assert resp.status == 200, await resp.text()
            assert await gcs.file_exists("run 1/out file+pct%.parquet")

            # tampered signature rejected
            bad = url.replace("Signature=", "Signature=00")
            async with client.put(
                bad, data=b"hello world", headers={"Content-Length": "11"}
            ) as resp:
                assert resp.status == 403
        return True

    assert run(flow())
    # both the oversize upload (its real Content-Length diverges from the
    # SIGNED one, changing the canonical request) and the tampered URL die
    # as signature failures
    assert bucket.rejections.count("bad signature") >= 2


def test_s3_sigv4_cycle(sa_creds):
    bucket = FakeBucket(hmac_secret="sekrit", region="us-east-1")

    async def flow():
        import aiohttp

        server = TestServer(bucket.make_app())
        await server.start_server()
        base = str(server.make_url("")).rstrip("/")
        async with aiohttp.ClientSession() as client:
            s3 = S3StorageProvider(
                "artifacts", "AKIDEXAMPLE", "sekrit", client,
                endpoint=base, region="us-east-1",
            )
            await s3.generate_mapping_file("ef" * 32, "w/file.bin")
            assert await s3.resolve_mapping_for_sha("ef" * 32) == "w/file.bin"
            url = await s3.generate_upload_signed_url("a.bin", max_bytes=3)
            async with client.put(
                url, data=b"abc", headers={"Content-Length": "3"}
            ) as resp:
                assert resp.status == 200, await resp.text()
            assert await s3.file_exists("a.bin")

            # wrong secret -> rejected
            s3bad = S3StorageProvider(
                "artifacts", "AKIDEXAMPLE", "wrong", client,
                endpoint=base, region="us-east-1",
            )
            url_bad = await s3bad.generate_upload_signed_url("b.bin")
            async with client.put(url_bad, data=b"x") as resp:
                assert resp.status == 403
        return True

    assert run(flow())


def test_gcs_behind_orchestrator_upload_route(sa_creds):
    """The adapter slots behind the orchestrator's /storage/request-upload
    exactly like LocalDir/Mock do (the StorageProvider seam)."""
    from aiohttp.test_utils import TestClient as TC

    from protocol_tpu.security import sign_request
    from protocol_tpu.services.orchestrator import OrchestratorService
    from protocol_tpu.store import NodeStatus, OrchestratorNode
    from tests.test_services import make_world

    creds, pub = sa_creds
    bucket = FakeBucket(rsa_public_key=pub)
    ledger, creator, manager, provider, node, pid = make_world()

    async def flow():
        import aiohttp

        server = TestServer(bucket.make_app())
        await server.start_server()
        base = str(server.make_url("")).rstrip("/")
        async with aiohttp.ClientSession() as bucket_client:
            gcs = GcsStorageProvider("pool-bucket", creds, bucket_client, endpoint=base)
            svc = OrchestratorService(ledger, pid, manager, storage=gcs)
            svc.store.node_store.add_node(
                OrchestratorNode(address=node.address, status=NodeStatus.HEALTHY)
            )
            async with TC(TestServer(svc.make_app())) as api:
                payload = {
                    "file_name": "artifact.bin",
                    "file_size": 5,
                    "file_type": "bin",
                    "sha256": "aa" * 32,
                }
                headers, body = sign_request(
                    "/storage/request-upload", node, payload
                )
                r = await api.post(
                    "/storage/request-upload", json=body, headers=headers
                )
                assert r.status == 200, await r.text()
                url = (await r.json())["data"]["signed_url"]
                # worker uploads through the signed URL
                async with bucket_client.put(
                    url, data=b"hello", headers={"Content-Length": "5"}
                ) as up:
                    assert up.status == 200, await up.text()
            # mapping landed; validator resolution works
            assert await gcs.resolve_mapping_for_sha("aa" * 32) == "artifact.bin"
            assert await gcs.file_exists("artifact.bin")
        return True

    assert run(flow())
