"""STUN public-IP detection against a local fake STUN server
(reference worker/src/checks/stun.rs)."""

import socket
import struct
import threading

from protocol_tpu.utils.stun import (
    _MAGIC_COOKIE,
    get_public_ip,
)


def fake_stun_server(mapped_ip: str, mapped_port: int, xor: bool = True):
    """One-shot UDP server answering a binding request."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]

    def run():
        data, addr = sock.recvfrom(2048)
        msg_type, _len, cookie = struct.unpack("!HHI", data[:8])
        assert msg_type == 0x0001 and cookie == _MAGIC_COOKIE
        txn = data[8:20]
        ip_raw = struct.unpack("!I", socket.inet_aton(mapped_ip))[0]
        if xor:
            attr_type = 0x0020
            p = mapped_port ^ (_MAGIC_COOKIE >> 16)
            raw = ip_raw ^ _MAGIC_COOKIE
        else:
            attr_type = 0x0001
            p, raw = mapped_port, ip_raw
        value = struct.pack("!BBH", 0, 0x01, p) + struct.pack("!I", raw)
        attrs = struct.pack("!HH", attr_type, len(value)) + value
        resp = struct.pack("!HHI", 0x0101, len(attrs), _MAGIC_COOKIE) + txn + attrs
        sock.sendto(resp, addr)
        sock.close()

    threading.Thread(target=run, daemon=True).start()
    return port


def test_xor_mapped_address_round_trip():
    port = fake_stun_server("203.0.113.7", 54321, xor=True)
    ip = get_public_ip(servers=[("127.0.0.1", port)], timeout=3.0)
    assert ip == "203.0.113.7"


def test_plain_mapped_address_fallback():
    port = fake_stun_server("198.51.100.9", 1234, xor=False)
    ip = get_public_ip(servers=[("127.0.0.1", port)], timeout=3.0)
    assert ip == "198.51.100.9"


def test_unreachable_server_returns_none():
    # closed port: fast OSError/timeout path, never raises
    with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
        s.bind(("127.0.0.1", 0))
        dead_port = s.getsockname()[1]
    ip = get_public_ip(servers=[("127.0.0.1", dead_port)], timeout=0.3)
    assert ip is None
