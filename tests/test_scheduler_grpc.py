"""gRPC scheduler-backend shim: round-trip over a real localhost channel,
kernel selection, and parity with the in-process kernels."""

import numpy as np
import pytest

from protocol_tpu.models.node import ComputeRequirements
from protocol_tpu.ops.encoding import FeatureEncoder
from protocol_tpu.proto import scheduler_pb2 as pb
from protocol_tpu.services.scheduler_grpc import (
    SchedulerBackendClient,
    encoded_to_proto,
    serve,
)

from tests.test_encoding import random_requirements, random_specs


@pytest.fixture(scope="module")
def backend():
    server = serve(address="127.0.0.1:50971")
    client = SchedulerBackendClient("127.0.0.1:50971")
    yield client
    client.close()
    server.stop(grace=None)


def build_batch(seed=0, P=24, T=16):
    import random

    rng = random.Random(seed)
    enc = FeatureEncoder()
    specs = [random_specs(rng) for _ in range(P)]
    reqs = [random_requirements(rng) for _ in range(T)]
    ep = enc.encode_providers(specs)
    er = enc.encode_requirements(reqs)
    return ep, er, specs, reqs


def test_health(backend):
    h = backend.health()
    assert h.status == "ok"
    assert h.device_count >= 1


@pytest.mark.parametrize("kernel", ["greedy", "auction", "sinkhorn", "topk"])
def test_assign_kernels_feasible(backend, kernel):
    ep, er, specs, reqs = build_batch()
    req = encoded_to_proto(ep, er, kernel=kernel, top_k=8)
    resp = backend.assign(req)
    p4t = list(resp.provider_for_task)
    assert len(p4t) == 16
    used = set()
    for t, p in enumerate(p4t):
        if p >= 0:
            assert specs[p].meets(reqs[t]), f"incompatible {kernel} match t={t} p={p}"
            assert p not in used
            used.add(p)
    assert resp.num_assigned == sum(1 for p in p4t if p >= 0)
    assert resp.solve_ms > 0


def test_greedy_parity_with_inprocess(backend):
    from protocol_tpu.ops.assign import assign_greedy
    from protocol_tpu.ops.cost import CostWeights, cost_matrix

    ep, er, _, _ = build_batch(seed=1)
    req = encoded_to_proto(ep, er, kernel="greedy")
    resp = backend.assign(req)
    cost, _ = cost_matrix(ep, er, CostWeights())
    local = assign_greedy(cost)
    np.testing.assert_array_equal(
        np.asarray(resp.provider_for_task),
        np.asarray(local.provider_for_task),
    )


def test_unknown_kernel_rejected(backend):
    import grpc

    ep, er, _, _ = build_batch(seed=2, P=4, T=4)
    req = encoded_to_proto(ep, er, kernel="magic")
    with pytest.raises(grpc.RpcError) as e:
        backend.assign(req)
    assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT
