"""gRPC scheduler-backend shim: round-trip over a real localhost channel,
kernel selection, and parity with the in-process kernels."""

import numpy as np
import pytest

from protocol_tpu.ops.encoding import FeatureEncoder
from protocol_tpu.services.scheduler_grpc import (
    SchedulerBackendClient,
    encoded_to_proto,
    serve,
)

from tests.test_encoding import random_requirements, random_specs


@pytest.fixture(scope="module")
def backend():
    server = serve(address="127.0.0.1:50971")
    client = SchedulerBackendClient("127.0.0.1:50971")
    yield client
    client.close()
    server.stop(grace=None)


def build_batch(seed=0, P=24, T=16):
    import random

    rng = random.Random(seed)
    enc = FeatureEncoder()
    specs = [random_specs(rng) for _ in range(P)]
    reqs = [random_requirements(rng) for _ in range(T)]
    ep = enc.encode_providers(specs)
    er = enc.encode_requirements(reqs)
    return ep, er, specs, reqs


def test_health(backend):
    h = backend.health()
    assert h.status == "ok"
    assert h.device_count >= 1


@pytest.mark.parametrize("kernel", ["greedy", "auction", "sinkhorn", "topk"])
def test_assign_kernels_feasible(backend, kernel):
    ep, er, specs, reqs = build_batch()
    req = encoded_to_proto(ep, er, kernel=kernel, top_k=8)
    resp = backend.assign(req)
    p4t = list(resp.provider_for_task)
    assert len(p4t) == 16
    used = set()
    for t, p in enumerate(p4t):
        if p >= 0:
            assert specs[p].meets(reqs[t]), f"incompatible {kernel} match t={t} p={p}"
            assert p not in used
            used.add(p)
    assert resp.num_assigned == sum(1 for p in p4t if p >= 0)
    assert resp.solve_ms > 0


def test_greedy_parity_with_inprocess(backend):
    from protocol_tpu.ops.assign import assign_greedy
    from protocol_tpu.ops.cost import CostWeights, cost_matrix

    ep, er, _, _ = build_batch(seed=1)
    req = encoded_to_proto(ep, er, kernel="greedy")
    resp = backend.assign(req)
    cost, _ = cost_matrix(ep, er, CostWeights())
    local = assign_greedy(cost)
    np.testing.assert_array_equal(
        np.asarray(resp.provider_for_task),
        np.asarray(local.provider_for_task),
    )


def test_unknown_kernel_rejected(backend):
    import grpc

    ep, er, _, _ = build_batch(seed=2, P=4, T=4)
    req = encoded_to_proto(ep, er, kernel="magic")
    with pytest.raises(grpc.RpcError) as e:
        backend.assign(req)
    assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT


def _pool_world(n_nodes=12, n_tasks=5):
    """Control-plane world: store + healthy nodes + tasks (bounded and
    unbounded) for an end-to-end matcher run."""
    import random

    from protocol_tpu.models.task import SchedulingConfig, Task, TaskRequest
    from protocol_tpu.store import NodeStatus, OrchestratorNode, StoreContext

    rng = random.Random(7)
    store = StoreContext.new_test()
    for i in range(n_nodes):
        store.node_store.add_node(
            OrchestratorNode(
                address=f"0xnode{i:02d}",
                status=NodeStatus.HEALTHY,
                ip_address=f"10.0.0.{i}",
                port=9000 + i,
                compute_specs=random_specs(rng),
            )
        )
    for i in range(n_tasks):
        cfg = None
        if i % 2 == 0:  # bounded: wants 2 replicas
            cfg = SchedulingConfig(plugins={"tpu_scheduler": {"replicas": ["2"]}})
        store.task_store.add_task(
            Task.from_request(
                TaskRequest(name=f"task-{i}", image="img", scheduling_config=cfg)
            )
        )
    return store


def test_remote_matcher_end_to_end_parity_and_rtt(backend):
    """Control plane -> RemoteBatchMatcher -> gRPC -> kernels -> assignment:
    the full scheduler path with the seam load-bearing, checked for parity
    against the in-process matcher and measuring the round-trip cost
    (BASELINE.json north star; SURVEY §7 hard part #6)."""
    from protocol_tpu.sched import Scheduler
    from protocol_tpu.sched.tpu_backend import TpuBatchMatcher
    from protocol_tpu.services.scheduler_grpc import RemoteBatchMatcher

    store = _pool_world()
    local = TpuBatchMatcher(store, min_solve_interval=0.0)
    remote = RemoteBatchMatcher(
        store, "127.0.0.1:50971", min_solve_interval=0.0
    )

    sched = Scheduler(store, batch_matcher=remote)
    assignments = {}
    for node in store.node_store.get_nodes():
        task = sched.get_task_for_node(node.address)
        if task is not None:
            assignments[node.address] = task.name

    local.refresh()
    local_assignments = {
        addr: store.task_store.get_task(tid).name
        for addr, tid in local._assignment.items()
    }
    assert assignments == local_assignments
    assert assignments, "remote matcher assigned nothing"

    stats = remote.last_solve_stats
    assert stats["remote_calls"] >= 1
    assert stats["remote_rtt_ms"] > 0
    assert stats["remote_backend_ms"] > 0
    # the columnar seam must stay cheap: serialization + transport overhead
    # (rtt - backend solve) bounded well under the 10 s heartbeat cadence
    overhead_ms = stats["remote_rtt_ms"] - stats["remote_backend_ms"]
    assert overhead_ms < 1000, stats
    print(f"remote seam: {stats}")


def test_remote_matcher_replica_bounds_respected(backend):
    """Bounded tasks keep their replica caps through the remote path."""
    from protocol_tpu.services.scheduler_grpc import RemoteBatchMatcher

    store = _pool_world(n_nodes=10, n_tasks=3)
    remote = RemoteBatchMatcher(
        store, "127.0.0.1:50971", min_solve_interval=0.0
    )
    remote.refresh()
    by_task: dict = {}
    for addr, tid in remote._assignment.items():
        by_task.setdefault(store.task_store.get_task(tid).name, []).append(addr)
    for name, nodes in by_task.items():
        idx = int(name.split("-")[1])
        if idx % 2 == 0:  # bounded at 2 replicas
            assert len(nodes) <= 2, (name, nodes)
